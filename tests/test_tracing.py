"""Distributed tracing tests (ISSUE 6, docs/tracing.md).

Coverage per the issue checklist:
- trace-ID propagation across a 4-proc eager ring: ONE deterministic ID
  per collective, spans from every rank, hop-level wire spans, directive
  echo agreement (no mismatch warnings);
- clock-offset estimator accuracy units (known offset + jitter);
- critical-path attribution on a synthetic span set with an injected
  straggler (rank + phase + >=80% share), including the negotiate-clipping
  rule that keeps a punctual rank's blocking exchange from diluting the
  skew verdict;
- Perfetto/Chrome-trace strict validity of the merged file;
- perf-gate pass/fail units against fixture bench JSON.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

from launch_util import REPO, launch_world

sys.path.insert(0, os.path.join(REPO, "tools"))

import perf_gate  # noqa: E402  (tools/perf_gate.py)
from horovod_tpu.tracing import (  # noqa: E402
    TraceRecorder,
    analyze,
    build_trace,
    estimate_offset_ns,
    export_gauges,
    load_spans,
    merge_trace,
    span_path,
    trace_id,
)


# ---------------------------------------------------------------- recorder

def test_recorder_writes_meta_then_spans(tmp_path):
    path = str(tmp_path / "spans-rank3.jsonl")
    rec = TraceRecorder(path, rank=3, clock_offset_ns=1234)
    rec.point("a#1", "a", "allreduce", "enqueue", bytes=64)
    rec.span("a#1", "a", "allreduce", "negotiate", 100, 200, cached=False)
    rec.close()
    lines = [json.loads(l) for l in open(path)]
    assert lines[0]["meta"] == 1
    assert lines[0]["rank"] == 3
    assert lines[0]["clock_offset_ns"] == 1234
    assert lines[1]["phase"] == "enqueue"
    assert lines[1]["t0"] == lines[1]["t1"]
    assert lines[2] == {"tid": "a#1", "rank": 3, "name": "a",
                        "op": "allreduce", "phase": "negotiate",
                        "t0": 100, "t1": 200, "cached": False}


def test_recorder_survives_unwritable_path():
    rec = TraceRecorder("/proc/definitely/not/writable/spans.jsonl", rank=0)
    before = rec.dropped
    for _ in range(3):
        rec.point("x#1", "x", "allreduce", "enqueue")
    assert rec.dropped >= before + 3   # counted, not raised
    rec.close()


def test_trace_id_deterministic():
    assert trace_id("grad.7", 3) == "grad.7#3"
    assert span_path("/tmp/t", 2).endswith("spans-rank2.jsonl")


# ------------------------------------------------------------------- clock

def test_clock_offset_estimator_accuracy():
    true_offset = 5_000_000   # 5 ms between the two clocks
    calls = {"n": 0}

    def probe():
        # Simulated server: local clock + true offset, plus asymmetric
        # jitter on some rounds — the min-RTT filter must reject those.
        calls["n"] += 1
        import time

        jitter = 2_000_000 if calls["n"] % 3 == 0 else 0
        if jitter:
            time.sleep(0.002)
        return time.monotonic_ns() + true_offset + jitter

    offset, err = estimate_offset_ns(probe, rounds=10)
    assert abs(offset - true_offset) < 1_000_000, (offset, err)
    assert err >= 0


def test_clock_offset_estimator_all_failures_raise():
    def probe():
        raise ConnectionError("down")

    with pytest.raises(ConnectionError):
        estimate_offset_ns(probe, rounds=3)


# ----------------------------------------------------------- critical path

def _synthetic_spans(world=4, straggler=2, delay_ns=500_000_000, n=3):
    """n collectives; `straggler` enqueues `delay_ns` late on each."""
    spans = []
    t = 1_000_000_000
    for i in range(n):
        tid = f"g.{i}#1"
        gate = t + delay_ns
        for r in range(world):
            enq = gate if r == straggler else t + r * 1000
            spans.append({"tid": tid, "rank": r, "name": f"g.{i}",
                          "op": "allreduce", "phase": "enqueue",
                          "t0": enq, "t1": enq})
            # Punctual ranks' negotiate spans BLOCK across the gate — the
            # analyzer must clip them, not book them as negotiation.
            spans.append({"tid": tid, "rank": r, "name": f"g.{i}",
                          "op": "allreduce", "phase": "negotiate",
                          "t0": enq + 100, "t1": gate + 2_000_000,
                          "cached": False})
            spans.append({"tid": tid, "rank": r, "name": f"g.{i}",
                          "op": "allreduce", "phase": "wire_send",
                          "t0": gate + 2_000_000, "t1": gate + 5_000_000,
                          "bytes": 4096})
            spans.append({"tid": tid, "rank": r, "name": f"g.{i}",
                          "op": "allreduce", "phase": "reduce",
                          "t0": gate + 5_000_000, "t1": gate + 5_500_000})
            spans.append({"tid": tid, "rank": r, "name": f"g.{i}",
                          "op": "allreduce", "phase": "done",
                          "t0": gate + 6_000_000, "t1": gate + 6_000_000})
        t = gate + 10_000_000
    return spans


def test_critical_path_attributes_injected_straggler():
    delay = 500_000_000
    n = 3
    report = analyze(_synthetic_spans(straggler=2, delay_ns=delay, n=n))
    assert report["collectives"] == n
    assert report["multi_rank_collectives"] == n
    strag = report["straggler"]
    assert strag is not None
    assert strag["rank"] == 2
    assert strag["phase"] == "compute_skew"
    injected = delay * n / 1e9
    attributed = report["skew_seconds_by_rank"][2]
    assert attributed >= 0.8 * injected
    # >=80% of ALL blocked time lands on the straggler: the negotiate
    # clipping rule is what makes this hold.
    assert strag["share_of_blocked"] >= 0.8
    # negotiation only counts post-gate time: 2ms per rank per collective
    assert report["phase_seconds"]["negotiation"] <= 0.010
    assert report["phase_seconds"]["wire"] > 0
    assert report["phase_seconds"]["reduce"] > 0


def test_critical_path_cache_vs_negotiation_split():
    spans = []
    for r in range(2):
        spans.append({"tid": "x#1", "rank": r, "name": "x",
                      "op": "allreduce", "phase": "enqueue",
                      "t0": 1000, "t1": 1000})
        spans.append({"tid": "x#1", "rank": r, "name": "x",
                      "op": "allreduce", "phase": "negotiate",
                      "t0": 1000, "t1": 2000, "cached": True})
    report = analyze(spans)
    assert report["phase_seconds"]["cache"] > 0
    assert report["phase_seconds"]["negotiation"] == 0


def test_critical_path_single_rank_no_skew():
    spans = [{"tid": "y#1", "rank": 0, "name": "y", "op": "allreduce",
              "phase": "enqueue", "t0": 0, "t1": 0},
             {"tid": "y#1", "rank": 0, "name": "y", "op": "allreduce",
              "phase": "done", "t0": 100, "t1": 100}]
    report = analyze(spans)
    assert report["multi_rank_collectives"] == 0
    assert report["straggler"] is None


def test_export_gauges_publishes_attribution():
    from horovod_tpu.metrics import registry

    report = analyze(_synthetic_spans())
    export_gauges(report)
    reg = registry()
    assert reg.gauge("horovod_straggler_rank").value == 2
    assert reg.gauge("horovod_critical_path_seconds",
                     phase="compute_skew").value > 0
    info = reg.get_info("straggler_attribution")
    assert info and info["straggler"]["rank"] == 2


def test_watchdog_report_enriched_with_attribution():
    from horovod_tpu.metrics import StallWatchdog, StallInfo, registry

    export_gauges(analyze(_synthetic_spans()))
    wd = StallWatchdog(check_time_s=0.01, rank=0, poll_interval_s=10.0)
    try:
        wd.add_source(lambda: [StallInfo(name="g.0", op="allreduce",
                                         age_s=5.0, missing_ranks=[2])])
        wd._scan()
        rep = registry().get_info("stall_report")
        assert rep is not None
        assert rep["straggler_attribution"]["straggler"]["rank"] == 2
    finally:
        wd.stop()


# ------------------------------------------------------- merge / perfetto

def _write_rank_file(tmp_path, rank, offset_ns, spans):
    path = span_path(str(tmp_path), rank)
    with open(path, "w") as f:
        f.write(json.dumps({"meta": 1, "rank": rank, "clock": "monotonic_ns",
                            "clock_offset_ns": offset_ns}) + "\n")
        for s in spans:
            f.write(json.dumps(s) + "\n")


def test_merge_applies_clock_offsets_and_is_strict_json(tmp_path):
    # Rank 1's clock reads 1s behind; its meta offset corrects it.
    _write_rank_file(tmp_path, 0, 0, [
        {"tid": "a#1", "rank": 0, "name": "a", "op": "allreduce",
         "phase": "enqueue", "t0": 5_000_000_000, "t1": 5_000_000_000}])
    _write_rank_file(tmp_path, 1, 1_000_000_000, [
        {"tid": "a#1", "rank": 1, "name": "a", "op": "allreduce",
         "phase": "enqueue", "t0": 4_000_000_000, "t1": 4_000_000_000}])
    spans, metas = load_spans(str(tmp_path))
    assert sorted(metas) == [0, 1]
    ts = {s["rank"]: s["t0"] for s in spans}
    assert ts[0] == ts[1] == 5_000_000_000   # aligned
    out = str(tmp_path / "trace.json")
    merge_trace(str(tmp_path), out)
    with open(out) as f:
        trace = json.load(f)   # STRICT parse from disk
    events = trace["traceEvents"]
    assert isinstance(events, list) and events
    span_events = [e for e in events if e["ph"] in ("X", "i")]
    assert {e["pid"] for e in span_events} == {0, 1}
    for e in span_events:
        assert isinstance(e["ts"], (int, float))
        assert e["args"]["tid"] == "a#1"
        if e["ph"] == "X":
            assert e["dur"] >= 0
    # metadata process names present for Perfetto track labeling
    assert any(e.get("name") == "process_name" for e in events)


def test_build_trace_lane_mapping():
    spans = [{"tid": "t#1", "rank": 0, "name": "t", "op": "allreduce",
              "phase": p, "t0": 10, "t1": 20}
             for p in ("negotiate", "wire_send", "wire_recv", "reduce")]
    trace = build_trace(spans)
    lanes = {e["cat"]: e["tid"] for e in trace["traceEvents"]
             if e["ph"] == "X"}
    assert lanes["wire_send"] != lanes["wire_recv"]
    assert lanes["negotiate"] != lanes["reduce"]


def test_load_spans_skips_torn_lines(tmp_path):
    path = span_path(str(tmp_path), 0)
    with open(path, "w") as f:
        f.write(json.dumps({"meta": 1, "rank": 0,
                            "clock_offset_ns": 0}) + "\n")
        f.write(json.dumps({"tid": "a#1", "rank": 0, "name": "a",
                            "op": "allreduce", "phase": "enqueue",
                            "t0": 1, "t1": 1}) + "\n")
        f.write('{"tid": "b#1", "rank": 0, "na')   # torn tail (crash)
    spans, _ = load_spans(str(tmp_path))
    assert len(spans) == 1


# --------------------------------------------------------------- perf gate

def _gate(args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "perf_gate.py")] + args,
        capture_output=True, text=True)


def _write(tmp_path, name, obj):
    p = str(tmp_path / name)
    with open(p, "w") as f:
        json.dump(obj, f)
    return p


REC = {"metric": "resnet50_images_per_sec", "value": 1000.0, "unit": "img/s"}


def test_perf_gate_passes_on_baseline(tmp_path):
    base = _write(tmp_path, "base.json", REC)
    cur = _write(tmp_path, "cur.json", REC)
    r = _gate(["--current", cur, "--baseline", base])
    assert r.returncode == 0, r.stdout + r.stderr


def test_perf_gate_fails_20pct_regression(tmp_path):
    base = _write(tmp_path, "base.json", REC)
    cur = _write(tmp_path, "cur.json", dict(REC, value=800.0))
    r = _gate(["--current", cur, "--baseline", base])
    assert r.returncode == 1, r.stdout + r.stderr
    assert "REGRESSION" in r.stdout


def test_perf_gate_per_metric_threshold(tmp_path):
    base = _write(tmp_path, "base.json", REC)
    cur = _write(tmp_path, "cur.json", dict(REC, value=800.0))
    r = _gate(["--current", cur, "--baseline", base,
               "--per-metric", "resnet50_images_per_sec=0.75"])
    assert r.returncode == 0, r.stdout + r.stderr


def test_perf_gate_smoke_and_full_never_compared(tmp_path):
    base = _write(tmp_path, "base.json", REC)   # full-mode baseline
    cur = _write(tmp_path, "cur.json",
                 dict(REC, value=1.0, smoke=True))  # tiny smoke number
    r = _gate(["--current", cur, "--baseline", base,
               "--allow-missing-baseline"])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "no comparable baseline" in r.stdout


def test_perf_gate_harness_shape_and_history(tmp_path):
    # BENCH_r0*.json shape: {"parsed": {...}} — best value wins as reference
    _write(tmp_path, "BENCH_r01.json", {"parsed": dict(REC, value=900.0)})
    _write(tmp_path, "BENCH_r02.json", {"parsed": dict(REC, value=1000.0)})
    cur = _write(tmp_path, "cur.json", dict(REC, value=860.0))
    r = _gate(["--current", cur,
               "--history", str(tmp_path / "BENCH_r0*.json")])
    assert r.returncode == 0, r.stdout + r.stderr   # 0.86 >= 0.85 vs best
    cur2 = _write(tmp_path, "cur2.json", dict(REC, value=840.0))
    r = _gate(["--current", cur2,
               "--history", str(tmp_path / "BENCH_r0*.json")])
    assert r.returncode == 1, r.stdout + r.stderr


def test_perf_gate_partial_skipped_and_empty_is_error(tmp_path):
    cur = _write(tmp_path, "cur.json",
                 dict(REC, value=0.0, partial=True, reason="budget"))
    base = _write(tmp_path, "base.json", REC)
    r = _gate(["--current", cur, "--baseline", base,
               "--allow-missing-baseline"])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "SKIP partial" in r.stdout
    empty = _write(tmp_path, "empty.json", {"no": "metrics"})
    r = _gate(["--current", empty, "--baseline", base])
    assert r.returncode == 2


def test_perf_gate_skips_nonzero_rc_bench_records(tmp_path):
    """ISSUE 9 satellite: a harness record from a bench that exited
    non-zero (the pre-watchdog BENCH_r05 rc=124 shape) is skipped
    outright — even when its tail happens to contain parseable JSON
    fragments, which must never become a comparison baseline."""
    import perf_gate

    bad = _write(tmp_path, "BENCH_bad.json", {
        "n": 5, "cmd": "python bench.py", "rc": 124, "parsed": None,
        # A metric line stranded in the killed process's stderr tail:
        # scraping it would fabricate a 9000 img/s baseline.
        "tail": json.dumps(dict(REC, value=9000.0))})
    assert perf_gate.load_records(bad) == []
    # rc=0 harness records still parse through their "parsed" payload.
    good = _write(tmp_path, "BENCH_good.json",
                  {"rc": 0, "parsed": dict(REC, value=900.0)})
    assert [r["value"] for r in perf_gate.load_records(good)] == [900.0]
    # End to end: the rc!=0 file contributes no baseline, so a current run
    # far below the stranded tail value still passes against the real one.
    cur = _write(tmp_path, "cur.json", dict(REC, value=860.0))
    r = _gate(["--current", cur, "--baseline", bad, "--baseline", good])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "skipping" in r.stdout and "rc=124" in r.stdout


def test_perf_gate_require_metric(tmp_path):
    cur = _write(tmp_path, "cur.json", REC)
    r = _gate(["--current", cur, "--allow-missing-baseline",
               "--require-metric", "something_else"])
    assert r.returncode == 2


def test_perf_gate_self_check(tmp_path):
    cur = _write(tmp_path, "cur.json", REC)
    r = _gate(["--current", cur, "--self-check"])
    assert r.returncode == 0, r.stdout + r.stderr


def test_perf_gate_load_records_from_log_lines(tmp_path):
    p = str(tmp_path / "bench.log")
    with open(p, "w") as f:
        f.write("WARNING: some jax noise\n")
        f.write("bench: skipping stage 'x'\n")
        f.write(json.dumps(REC) + "\n")
    recs = perf_gate.load_records(p)
    assert recs == [REC]


# ------------------------------------------- 4-proc eager ring propagation

RING_WORKER = r"""
import os, sys, time
sys.path.insert(0, os.environ["HVD_REPO"])
import json
import numpy as np
from horovod_tpu.common.engine import PyEngine
from horovod_tpu.common.config import Config
from horovod_tpu.common.topology import Topology

rank = int(os.environ["HOROVOD_RANK"]); world = int(os.environ["HOROVOD_SIZE"])
topo = Topology(rank=rank, size=world, local_rank=rank, local_size=world,
                cross_rank=0, cross_size=1)
eng = PyEngine(topo, Config(cycle_time_ms=2.0, stall_check_disable=True))
assert eng._ring is not None, "expected the ring data plane in a 4-world"
for i in range(3):
    out = eng.run("allreduce", np.full(512, float(rank + 1), np.float32),
                  f"g.{i}")
eng.shutdown()
print(json.dumps({"rank": rank, "ok": True}))
"""


@pytest.mark.fast
def test_trace_id_propagation_4proc_eager_ring(tmp_path):
    """One trace ID per collective across a 4-proc RING world: spans on all
    ranks, hop-level wire spans, coordinator echo accepted silently."""
    trace_dir = str(tmp_path / "trace")
    results = launch_world(4, RING_WORKER,
                           extra_env={"HOROVOD_TRACE_DIR": trace_dir,
                                      "JAX_PLATFORMS": "cpu"})
    for r in results:
        assert r["out"]["ok"]
        # propagation must be verified silently: any disagreement logs a
        # trace-id mismatch warning
        assert "trace id mismatch" not in r["stderr"]
        assert "trace-id disagreement" not in r["stderr"]
    spans, metas = load_spans(trace_dir)
    assert sorted(metas) == [0, 1, 2, 3]
    by_tid: dict = {}
    phases_by_tid: dict = {}
    for s in spans:
        by_tid.setdefault(s["tid"], set()).add(s["rank"])
        phases_by_tid.setdefault(s["tid"], set()).add(s["phase"])
    for i in range(3):
        tid = f"g.{i}#1"
        assert by_tid.get(tid) == {0, 1, 2, 3}, by_tid
        assert {"enqueue", "negotiate", "wire_send", "wire_recv", "reduce",
                "done"} <= phases_by_tid[tid], phases_by_tid[tid]
    # non-coordinator ranks estimated a clock offset (meta present even if
    # near-zero on one host)
    assert all("clock_offset_ns" in m for m in metas.values())
    report = analyze(spans)
    assert report["multi_rank_collectives"] == 3


# ------------------------------------------------------------ native engine

NATIVE_WORKER = r"""
import os, sys
sys.path.insert(0, os.environ["HVD_REPO"])
import json
import numpy as np
from horovod_tpu.cc.native_engine import NativeEngine
from horovod_tpu.common.config import Config
from horovod_tpu.common.topology import Topology

rank = int(os.environ["HOROVOD_RANK"]); world = int(os.environ["HOROVOD_SIZE"])
host, port = os.environ["HOROVOD_COORD_ADDR"].rsplit(":", 1)
topo = Topology(rank=rank, size=world, local_rank=rank, local_size=world,
                cross_rank=0, cross_size=1)
eng = NativeEngine(topo, Config(cycle_time_ms=2.0, stall_check_disable=True))
for i in range(3):
    out = eng.run("allreduce", np.full(256, float(rank + 1), np.float32),
                  f"ng.{i}")
    assert abs(float(out[0]) - (world + 1) / 2.0) < 1e-6, float(out[0])
eng.shutdown()
print(json.dumps({"rank": rank, "ok": True}))
"""


@pytest.mark.fast
def test_trace_native_engine_2proc(tmp_path):
    """Native plane: Request.trace_seq rides the wire, engine.cc spans are
    drained through hvd_trace_drain into the same span files, and both
    ranks' spans share each collective's ID."""
    pytest.importorskip("ctypes")
    from horovod_tpu.cc import lib_path, NativeBuildError

    try:
        lib_path()
    except NativeBuildError:
        pytest.skip("native core unavailable")
    trace_dir = str(tmp_path / "trace")
    results = launch_world(2, NATIVE_WORKER,
                           extra_env={"HOROVOD_TRACE_DIR": trace_dir,
                                      "JAX_PLATFORMS": "cpu"})
    for r in results:
        assert r["out"]["ok"]
    spans, metas = load_spans(trace_dir)
    assert sorted(metas) == [0, 1]
    native = [s for s in spans if s.get("engine") == "native"]
    assert native, "no native-tagged spans drained"
    by_tid: dict = {}
    phases: set = set()
    for s in native:
        by_tid.setdefault(s["tid"], set()).add(s["rank"])
        phases.add(s["phase"])
    for i in range(3):
        assert by_tid.get(f"ng.{i}#1") == {0, 1}, by_tid
    assert {"enqueue", "negotiate", "wire", "done"} <= phases, phases
    report = analyze(spans)
    assert report["multi_rank_collectives"] == 3
