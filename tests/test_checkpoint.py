"""Checkpoint/resume contract tests (SURVEY.md §5.4): rank-0-writes,
restore + broadcast consistency, latest-step discovery."""

import numpy as np
import pytest

import horovod_tpu as hvd_core
from horovod_tpu import checkpoint


@pytest.fixture()
def hvd():
    hvd_core.init()
    yield hvd_core
    hvd_core.shutdown()


def test_save_restore_roundtrip(hvd, tmp_path):
    state = {"params": {"w": np.arange(6.0).reshape(2, 3)}, "epoch": np.int64(7)}
    checkpoint.save(str(tmp_path / "ckpt"), state, step=7)
    assert checkpoint.latest_step(str(tmp_path / "ckpt")) == 7
    restored = checkpoint.restore(str(tmp_path / "ckpt"), step=7)
    np.testing.assert_array_equal(restored["params"]["w"], state["params"]["w"])
    assert int(restored["epoch"]) == 7


def test_latest_step_multiple(hvd, tmp_path):
    for s in (1, 5, 3):
        checkpoint.save(str(tmp_path / "c"), {"x": np.ones(2) * s}, step=s)
    assert checkpoint.latest_step(str(tmp_path / "c")) == 5
    restored = checkpoint.restore(str(tmp_path / "c"), step=5)
    np.testing.assert_array_equal(restored["x"], np.ones(2) * 5)


def test_latest_step_missing_dir(hvd, tmp_path):
    assert checkpoint.latest_step(str(tmp_path / "nope")) is None


def test_broadcast_resume_state_single(hvd):
    state = {"epoch": 3, "arr": np.ones((2, 2))}
    out = checkpoint.broadcast_resume_state(state)
    assert out["epoch"] == 3
    np.testing.assert_array_equal(out["arr"], state["arr"])


def test_digest_verify_single_is_noop(hvd):
    # size-1 world: nothing to compare
    checkpoint._verify_cross_rank_digest({"w": np.ones(3)}, "t")


DIGEST_SCRIPT = """
import json, os, sys
import numpy as np
sys.path.insert(0, os.environ["HVD_REPO"])
import horovod_tpu as hvd
from horovod_tpu import checkpoint
from horovod_tpu.common.engine import HorovodInternalError

hvd.init()
r = hvd.rank()
# identical state on every rank: must pass
checkpoint._verify_cross_rank_digest({"w": np.arange(8.0)}, "same")
# rank-dependent state: must raise on every rank
try:
    checkpoint._verify_cross_rank_digest({"w": np.full(8, float(r))}, "diff")
    diverged_caught = False
except HorovodInternalError as e:
    diverged_caught = "diverged across ranks" in str(e)
hvd.shutdown()
print(json.dumps({"ok": diverged_caught}))
"""


@pytest.mark.engine
@pytest.mark.slow  # re-tiered r5: multi-process spawn cost; core coverage stays fast
def test_digest_verify_two_ranks():
    """Cross-rank digest check: identical restored state passes, divergent
    state raises on every rank (the docstring-promised guarantee,
    VERDICT weak #5)."""
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from launch_util import launch_world

    for res in launch_world(2, DIGEST_SCRIPT):
        assert res["out"]["ok"] is True
