"""Checkpoint/resume contract tests (SURVEY.md §5.4): rank-0-writes,
restore + broadcast consistency, latest-step discovery."""

import numpy as np
import pytest

import horovod_tpu as hvd_core
from horovod_tpu import checkpoint


@pytest.fixture()
def hvd():
    hvd_core.init()
    yield hvd_core
    hvd_core.shutdown()


def test_save_restore_roundtrip(hvd, tmp_path):
    state = {"params": {"w": np.arange(6.0).reshape(2, 3)}, "epoch": np.int64(7)}
    checkpoint.save(str(tmp_path / "ckpt"), state, step=7)
    assert checkpoint.latest_step(str(tmp_path / "ckpt")) == 7
    restored = checkpoint.restore(str(tmp_path / "ckpt"), step=7)
    np.testing.assert_array_equal(restored["params"]["w"], state["params"]["w"])
    assert int(restored["epoch"]) == 7


def test_latest_step_multiple(hvd, tmp_path):
    for s in (1, 5, 3):
        checkpoint.save(str(tmp_path / "c"), {"x": np.ones(2) * s}, step=s)
    assert checkpoint.latest_step(str(tmp_path / "c")) == 5
    restored = checkpoint.restore(str(tmp_path / "c"), step=5)
    np.testing.assert_array_equal(restored["x"], np.ones(2) * 5)


def test_latest_step_missing_dir(hvd, tmp_path):
    assert checkpoint.latest_step(str(tmp_path / "nope")) is None


def test_broadcast_resume_state_single(hvd):
    state = {"epoch": 3, "arr": np.ones((2, 2))}
    out = checkpoint.broadcast_resume_state(state)
    assert out["epoch"] == 3
    np.testing.assert_array_equal(out["arr"], state["arr"])


def test_digest_verify_single_is_noop(hvd):
    # size-1 world: nothing to compare
    checkpoint._verify_cross_rank_digest({"w": np.ones(3)}, "t")


DIGEST_SCRIPT = """
import json, os, sys
import numpy as np
sys.path.insert(0, os.environ["HVD_REPO"])
import horovod_tpu as hvd
from horovod_tpu import checkpoint
from horovod_tpu.common.engine import HorovodInternalError

hvd.init()
r = hvd.rank()
# identical state on every rank: must pass
checkpoint._verify_cross_rank_digest({"w": np.arange(8.0)}, "same")
# rank-dependent state: must raise on every rank
try:
    checkpoint._verify_cross_rank_digest({"w": np.full(8, float(r))}, "diff")
    diverged_caught = False
except HorovodInternalError as e:
    diverged_caught = "diverged across ranks" in str(e)
hvd.shutdown()
print(json.dumps({"ok": diverged_caught}))
"""


@pytest.mark.engine
@pytest.mark.slow  # re-tiered r5: multi-process spawn cost; core coverage stays fast
def test_digest_verify_two_ranks():
    """Cross-rank digest check: identical restored state passes, divergent
    state raises on every rank (the docstring-promised guarantee,
    VERDICT weak #5)."""
    import os
    import sys

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from launch_util import launch_world

    for res in launch_world(2, DIGEST_SCRIPT):
        assert res["out"]["ok"] is True


# -- crash-consistent commits (ISSUE 8) --------------------------------------

def _fake_ckpt(path, tag):
    import os

    os.makedirs(path)
    with open(os.path.join(path, "data.bin"), "w") as f:
        f.write(tag)


def _read_tag(path):
    import os

    with open(os.path.join(path, "data.bin")) as f:
        return f.read()


def test_swap_into_place_replaces_atomically(tmp_path):
    import os

    target = str(tmp_path / "ckpt")
    _fake_ckpt(target, "old")
    tmp = f"{target}.tmp.123"
    _fake_ckpt(tmp, "new")
    checkpoint._swap_into_place(tmp, target)
    assert _read_tag(target) == "new"
    # no leftovers: the stage, its marker, and the displaced copy are gone
    leftovers = [n for n in os.listdir(tmp_path) if n != "ckpt"]
    assert leftovers == []


def test_heal_adopts_complete_stage_when_target_missing(tmp_path):
    # Crash window between the two swap renames: target gone, stage
    # complete (.ok marker written after fsync) — heal must adopt it.
    target = str(tmp_path / "ckpt")
    tmp = f"{target}.tmp.99"
    _fake_ckpt(tmp, "staged")
    with open(tmp + ".ok", "w") as f:
        f.write("complete\n")
    checkpoint._heal_interrupted(target)
    assert _read_tag(target) == "staged"


def test_heal_discards_incomplete_stage_and_trash(tmp_path):
    import os

    # Crash mid-write: stage has NO .ok marker — it may be torn; the old
    # checkpoint (still in place) must win and the junk must go.
    target = str(tmp_path / "ckpt")
    _fake_ckpt(target, "good")
    _fake_ckpt(f"{target}.tmp.7", "torn")
    _fake_ckpt(f"{target}.trash.8", "displaced")
    checkpoint._heal_interrupted(target)
    assert _read_tag(target) == "good"
    assert sorted(os.listdir(tmp_path)) == ["ckpt"]


def test_heal_prefers_existing_target_over_stage(tmp_path):
    import os

    # Both a target AND a complete stage exist (crash after the second
    # rename but before stage cleanup is impossible — but a duplicate save
    # race can leave this): the in-place target wins, the stage is junk.
    target = str(tmp_path / "ckpt")
    _fake_ckpt(target, "current")
    _fake_ckpt(f"{target}.tmp.5", "stale-stage")
    with open(f"{target}.tmp.5.ok", "w") as f:
        f.write("complete\n")
    checkpoint._heal_interrupted(target)
    assert _read_tag(target) == "current"
    assert sorted(os.listdir(tmp_path)) == ["ckpt"]


def test_save_commit_is_staged_and_healed(hvd, tmp_path):
    import os

    # End-to-end through orbax: a save overwriting an existing checkpoint
    # leaves no stage/trash debris, and a restore after a simulated
    # mid-commit crash (target renamed away, stage left complete) heals.
    target = str(tmp_path / "ckpt")
    checkpoint.save(target, {"w": np.arange(4.0)})
    checkpoint.save(target, {"w": np.arange(4.0) * 2})  # overwrite commit
    assert sorted(os.listdir(tmp_path)) == ["ckpt"]
    out = checkpoint.restore(target, template={"w": np.zeros(4)})
    np.testing.assert_array_equal(out["w"], np.arange(4.0) * 2)
    # simulate the crash window: target vanished, complete stage waiting
    os.rename(target, target + ".tmp.42")
    with open(target + ".tmp.42.ok", "w") as f:
        f.write("complete\n")
    out = checkpoint.restore(target, template={"w": np.zeros(4)})
    np.testing.assert_array_equal(out["w"], np.arange(4.0) * 2)
