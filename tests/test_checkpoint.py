"""Checkpoint/resume contract tests (SURVEY.md §5.4): rank-0-writes,
restore + broadcast consistency, latest-step discovery."""

import numpy as np
import pytest

import horovod_tpu as hvd_core
from horovod_tpu import checkpoint


@pytest.fixture()
def hvd():
    hvd_core.init()
    yield hvd_core
    hvd_core.shutdown()


def test_save_restore_roundtrip(hvd, tmp_path):
    state = {"params": {"w": np.arange(6.0).reshape(2, 3)}, "epoch": np.int64(7)}
    checkpoint.save(str(tmp_path / "ckpt"), state, step=7)
    assert checkpoint.latest_step(str(tmp_path / "ckpt")) == 7
    restored = checkpoint.restore(str(tmp_path / "ckpt"), step=7)
    np.testing.assert_array_equal(restored["params"]["w"], state["params"]["w"])
    assert int(restored["epoch"]) == 7


def test_latest_step_multiple(hvd, tmp_path):
    for s in (1, 5, 3):
        checkpoint.save(str(tmp_path / "c"), {"x": np.ones(2) * s}, step=s)
    assert checkpoint.latest_step(str(tmp_path / "c")) == 5
    restored = checkpoint.restore(str(tmp_path / "c"), step=5)
    np.testing.assert_array_equal(restored["x"], np.ones(2) * 5)


def test_latest_step_missing_dir(hvd, tmp_path):
    assert checkpoint.latest_step(str(tmp_path / "nope")) is None


def test_broadcast_resume_state_single(hvd):
    state = {"epoch": 3, "arr": np.ones((2, 2))}
    out = checkpoint.broadcast_resume_state(state)
    assert out["epoch"] == 3
    np.testing.assert_array_equal(out["arr"], state["arr"])
