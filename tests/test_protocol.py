"""The shared protocol core (ISSUE 13): spec conformance, the state
machine, golden protocol-trace replay against BOTH engines, and the
4-proc native==python bitwise matrix pinned to the canonical oracles.

Three layers of the same contract:

- ``common/protocol.py`` is the importable copy of the machine-extracted
  ``docs/protocol_spec.json`` — :func:`verify_spec` must return zero
  mismatches (the analyzer re-checks this in CI; here it runs in-process
  so a drift fails the unit tier too, naming the first divergent table).
- The :class:`protocol.Machine` validates negotiation/cache/demote
  transition traces; golden traces replay clean, corrupted ones fail
  naming the FIRST bad transition.
- Real engines: scripted op sequences drive the Python and the native
  engine through identical cache lifecycles (miss/bind, steady-state
  hits, shape-change rebind, flush + re-learn), and the observed
  transition streams must agree with the golden trace and with each
  other; the bitwise matrix runs {none, bf16, fp16, topk} through
  {python-star, python-ring, python-hier, native-flat, native-hier} on
  4-proc worlds and pins every result to the
  ``_ring_order_reduce``/``_grid_order_reduce`` oracles.
"""

from __future__ import annotations

import os
import textwrap

import numpy as np
import pytest

from launch_util import launch_world

from horovod_tpu.common import protocol
from horovod_tpu.common.engine import _ring_order_reduce
from horovod_tpu.compression import (
    topk_densify,
    topk_k,
    topk_select,
)

# --------------------------------------------------------- spec conformance


def test_protocol_core_matches_generated_spec():
    """common/protocol.py == docs/protocol_spec.json, entry by entry. A
    drift names the first mismatching table, not a downstream symptom."""
    mismatches = protocol.verify_spec()
    assert mismatches == [], "\n".join(mismatches)


def test_chunk_bounds_matches_engine():
    from horovod_tpu.common.engine import _chunk_bounds

    for n, w in [(0, 4), (7, 4), (8, 4), (30011, 4), (5, 8)]:
        assert protocol.chunk_bounds(n, w) == _chunk_bounds(n, w)
        counts = np.diff(protocol.chunk_bounds(n, w))
        split = [len(c) for c in np.array_split(np.zeros(n), w)]
        assert list(counts) == split


def test_fold_order_covers_every_rank_once():
    for world in (2, 3, 4, 8):
        for c in range(world):
            order = protocol.fold_order(c, world)
            assert sorted(order) == list(range(world))
            assert order[0] == protocol.fold_start(c, world)
            # the fold ENDS on the chunk's owner: rank c holds the result
            assert order[-1] == c


def test_reduce_plan_canonical_semantics():
    import ml_dtypes

    # uncompressed: native ring width — f32 adds for f32, f64 for f64
    assert protocol.reduce_plan(np.float32) == {
        "acc": np.dtype(np.float32), "hop": None, "storage_round": False}
    assert protocol.reduce_plan(np.float64)["acc"] == np.dtype(np.float64)
    # 16-bit payloads: implicit wire = self, per-hop rounding
    p = protocol.reduce_plan(np.float16)
    assert p["hop"] == np.dtype(np.float16) and p["storage_round"]
    p = protocol.reduce_plan(ml_dtypes.bfloat16)
    assert p["hop"] == np.dtype(ml_dtypes.bfloat16)
    # explicit wire: f32 accumulator, rounded hops + storage round
    p = protocol.reduce_plan(np.float32, np.dtype(ml_dtypes.bfloat16))
    assert p["acc"] == np.dtype(np.float32) and p["storage_round"]
    # sparse: exact f32 fold
    assert protocol.reduce_plan(np.float32, "topk")["hop"] == "topk"


# ------------------------------------------------------------ state machine

KEY_A = ("a", "allreduce", "float32", (8,), 0, True, None)
KEY_A2 = ("a", "allreduce", "float32", (16,), 0, True, None)


def _golden_cache_trace():
    """The canonical 2-rank cache lifecycle: full negotiation + bind,
    steady-state cached ticks, shape-change rebind, flush + re-learn."""
    return [
        ("tick_full", 0, KEY_A), ("tick_full", 1, KEY_A),
        ("assign", 0, KEY_A), ("execute", KEY_A),
        ("tick_cached", 0, KEY_A), ("tick_cached", 1, KEY_A),
        ("execute", KEY_A),
        # shape change: the stale bit evicts everywhere, the new signature
        # binds fresh
        ("tick_full", 0, KEY_A2), ("tick_full", 1, KEY_A2),
        ("evict", 0), ("assign", 1, KEY_A2), ("execute", KEY_A2),
        # rank 0 flushes its mirror: it must re-learn from a full request
        # + re-announcement before its next cached tick
        ("flush", 0),
        ("tick_full", 0, KEY_A2), ("tick_cached", 1, KEY_A2),
        ("assign", 1, KEY_A2),  # mirror re-heal: same (bit, key) re-announce
        ("execute", KEY_A2),
        ("tick_cached", 0, KEY_A2), ("tick_cached", 1, KEY_A2),
        ("execute", KEY_A2),
    ]


def test_golden_cache_trace_replays_clean():
    trace = _golden_cache_trace()
    assert protocol.replay(trace, world=2) == len(trace) == 20


def test_demote_redo_trace_replays_clean():
    trace = [
        ("tick_full", 0, KEY_A), ("tick_full", 1, KEY_A),
        ("assign", 0, KEY_A), ("execute", KEY_A),
        ("demote", 0), ("demote", 1),
        ("redo", KEY_A),
        ("repromote", 0), ("repromote", 1),
    ]
    assert protocol.replay(trace, world=2) == len(trace)


@pytest.mark.parametrize("mutate, bad_index, why", [
    # cached tick before any bind
    (lambda t: [("tick_cached", 0, KEY_A)] + t, 0, "no bound bit"),
    # bit re-bound to a different key without an evict
    (lambda t: t[:4] + [("assign", 0, KEY_A2)] + t[4:], 4, "already bound"),
    # execute with a missing rank's contribution
    (lambda t: t[:2] + [("execute", KEY_A), ("execute", KEY_A)] + t[3:],
     3, "contributions"),
    # cached tick after a flush, before the re-announcement
    (lambda t: t[:13] + [("tick_cached", 0, KEY_A2)] + t[13:],
     13, "mirror learned"),
    # redo replay with no demotion epoch open
    (lambda t: t + [("redo", KEY_A2)], 20, "outside a demotion"),
])
def test_corrupted_traces_name_first_bad_transition(mutate, bad_index, why):
    trace = mutate(_golden_cache_trace())
    with pytest.raises(protocol.ProtocolViolation) as e:
        protocol.replay(trace, world=2)
    assert e.value.index == bad_index, (e.value.index, str(e.value))
    assert why in str(e.value)


# ------------------------------------- golden trace replay, real engines

# Scripted cache lifecycle driven through a REAL 2-proc engine; rank 0
# reports the observed transition stream as (hit|miss, mirror size)
# symbols. Identical script for both engines — their streams must match
# the golden and each other.
CACHE_TRACE_WORKER = r"""
import json, os, sys
sys.path.insert(0, os.environ["HVD_REPO"])
import numpy as np
from horovod_tpu.common.config import Config
from horovod_tpu.common.engine import create
from horovod_tpu.common.topology import Topology

rank = int(os.environ["HOROVOD_RANK"]); world = int(os.environ["HOROVOD_SIZE"])
eng = create(Topology(rank, world, 0, 1, rank, world),
             Config(cycle_time_ms=1.0, stall_check_disable=True))
try:
    stream = []

    def observe(step):
        before = eng.cache_stats()["mirror"]
        step()
        after = eng.cache_stats()["mirror"]
        stream.append([
            "hit" if after["hits"] > before["hits"] else "miss",
            int(before["size"]), int(after["size"])])

    a8 = np.arange(8, dtype=np.float32) * (rank + 1)
    a16 = np.arange(16, dtype=np.float32) * (rank + 1)
    observe(lambda: eng.run("allreduce", a8, "a"))      # miss, bind
    observe(lambda: eng.run("allreduce", a8, "a"))      # steady-state hit
    observe(lambda: eng.run("allreduce", a8, "a"))      # hit
    observe(lambda: eng.run("allreduce", a16, "a"))     # shape change: rebind
    observe(lambda: eng.run("allreduce", a16, "a"))     # hit under new key
    eng.cache_flush()                                   # rank-local flush
    observe(lambda: eng.run("allreduce", a16, "a"))     # re-learn (full req)
    observe(lambda: eng.run("allreduce", a16, "a"))     # healed: hit again
    print(json.dumps({"rank": rank, "stream": stream}))
finally:
    eng.shutdown()
"""

# What both engines must observe, symbol by symbol (rank 0's view):
GOLDEN_STREAM = [
    ["miss", 0, 1],   # full negotiation, bit bound
    ["hit", 1, 1],    # steady state
    ["hit", 1, 1],
    ["miss", 1, 1],   # shape change: evict + fresh bind (net size 0)
    ["hit", 1, 1],
    ["miss", 0, 1],   # flushed mirror re-learns from the re-announcement
    ["hit", 1, 1],
]


@pytest.mark.parametrize("engine", ["python", "native!"])
def test_golden_trace_replays_through_engine(engine):
    outs = [r["out"] for r in launch_world(
        2, CACHE_TRACE_WORKER, extra_env={"HOROVOD_ENGINE": engine})]
    stream = next(o["stream"] for o in outs if o["rank"] == 0)
    for i, (got, want) in enumerate(zip(stream, GOLDEN_STREAM)):
        assert got == want, (
            f"{engine} engine diverged at transition {i}: observed {got}, "
            f"golden {want} (full stream: {stream})")
    assert len(stream) == len(GOLDEN_STREAM)


def test_both_engines_produce_identical_transition_streams():
    streams = {}
    for engine in ("python", "native!"):
        outs = [r["out"] for r in launch_world(
            2, CACHE_TRACE_WORKER, extra_env={"HOROVOD_ENGINE": engine})]
        streams[engine] = next(
            o["stream"] for o in outs if o["rank"] == 0)
    py, nat = streams["python"], streams["native!"]
    assert len(py) == len(nat)
    for i, (p, n) in enumerate(zip(py, nat)):
        assert p == n, (
            f"engines diverged at transition {i}: python {p} vs native {n}")


# ---------------------------------------- 4-proc bitwise matrix vs oracles

WORLD = 4
ELEMS = 30011  # odd: uneven ring chunks; ~120 KB f32 (topk-eligible)
STEPS = 3

MATRIX_WORKER = r"""
import hashlib, json, os, sys
sys.path.insert(0, os.environ["HVD_REPO"])
import numpy as np
from horovod_tpu.common.config import Config
from horovod_tpu.common.engine import create
from horovod_tpu.common.topology import Topology

rank = int(os.environ["HOROVOD_RANK"]); world = int(os.environ["HOROVOD_SIZE"])
lsz = int(os.environ.get("T_LOCAL", "1"))
topo = (Topology(rank, world, rank % lsz, lsz, rank // lsz, world // lsz)
        if lsz > 1 else Topology(rank, world, 0, 1, rank, world))
eng = create(topo, Config(
    cycle_time_ms=1.0, stall_check_disable=True,
    compression=os.environ.get("T_COMP", "none"),
    hierarchical_allreduce=os.environ.get("T_HIER", "0") == "1"))
try:
    elems = int(os.environ["T_ELEMS"]); steps = int(os.environ["T_STEPS"])
    rng = np.random.default_rng(23)
    digest = hashlib.sha256()
    for step in range(steps):
        pay = [(rng.standard_normal(elems) * (r + 1)).astype(np.float32)
               for r in range(world)]
        out = eng.run("allreduce", pay[rank], f"g.{step % 2}")
        digest.update(np.ascontiguousarray(out).tobytes())
    print(json.dumps({"rank": rank, "hash": digest.hexdigest(),
                      "plane": eng.cache_stats().get("plane", "?")}))
finally:
    eng.shutdown()
"""


def _matrix_world(engine: str, comp: str, hier: bool = False,
                  ring: bool = True):
    env = {"HOROVOD_ENGINE": engine, "T_COMP": comp,
           "T_ELEMS": str(ELEMS), "T_STEPS": str(STEPS),
           "HOROVOD_COMPRESSION": comp,
           "HOROVOD_RING_DATA_PLANE": "1" if ring else "0"}
    if hier:
        env.update({"T_LOCAL": "2", "T_HIER": "1",
                    "HOROVOD_HIERARCHICAL_ALLREDUCE": "1"})
    return [r["out"] for r in launch_world(WORLD, MATRIX_WORKER,
                                           extra_env=env)]


def _oracle_digest(comp: str, grid=None) -> str:
    """The canonical result stream every plane must reproduce bitwise:
    the pure-numpy oracles over the same seeded payloads, including the
    enqueue-time quantize/sparsify + EF residual semantics."""
    import hashlib

    import ml_dtypes

    rng = np.random.default_rng(23)
    digest = hashlib.sha256()
    residuals: dict = {}
    for step in range(STEPS):
        name = f"g.{step % 2}"
        pay = [(rng.standard_normal(ELEMS) * (r + 1)).astype(np.float32)
               for r in range(WORLD)]
        if comp == "topk":
            prepared = []
            for r in range(WORLD):
                res = residuals.pop((name, r), None)
                x = pay[r] if res is None else pay[r] + res
                i, v = topk_select(x.ravel(), topk_k(x.size, 0.01))
                d = topk_densify(i, v, x.size)
                residuals[(name, r)] = x - d
                prepared.append(d)
            out = _ring_order_reduce(prepared, True, wire_dtype="topk",
                                     grid=grid)
        elif comp in ("bf16", "fp16"):
            wd = np.dtype(ml_dtypes.bfloat16 if comp == "bf16"
                          else np.float16)
            quant = [p.astype(wd).astype(np.float32) for p in pay]
            out = _ring_order_reduce(quant, True, wire_dtype=wd, grid=grid)
        else:
            out = _ring_order_reduce(pay, True, grid=grid)
        digest.update(np.ascontiguousarray(out).tobytes())
    return digest.hexdigest()


@pytest.mark.parametrize("comp", ["none", "bf16", "fp16", "topk"])
def test_bitwise_matrix_flat_native_equals_python(comp):
    """The acceptance pin: native-flat == python-ring == the flat oracle,
    bitwise, for every wire format (incl. topk EF residual carry across
    re-submissions of the same names)."""
    want = _oracle_digest(comp)
    native = _matrix_world("native!", comp)
    py = _matrix_world("python", comp)
    assert {o["hash"] for o in native} == {want}, \
        f"native flat plane != oracle for {comp}"
    assert {o["hash"] for o in py} == {want}, \
        f"python ring plane != oracle for {comp}"
    assert all(o["plane"] == "ring" for o in py)


@pytest.mark.parametrize("comp", ["none", "topk"])
def test_bitwise_matrix_star_pinned_to_same_oracle(comp):
    """The python STAR relay reduces through the same canonical fold —
    star == ring == native for the formats the star executor decodes."""
    want = _oracle_digest(comp)
    star = _matrix_world("python", comp, ring=False)
    assert all(o["plane"] == "star" for o in star)
    assert {o["hash"] for o in star} == {want}, \
        f"python star plane != oracle for {comp}"


@pytest.mark.parametrize("comp", ["bf16", "topk"])
def test_bitwise_matrix_hier_native_equals_python(comp):
    """The two-level ladder: native-hier == python-hier == the grid
    oracle on a simulated 2-host x 2-rank grid."""
    want = _oracle_digest(comp, grid=(2, 2))
    native = _matrix_world("native!", comp, hier=True)
    py = _matrix_world("python", comp, hier=True)
    assert {o["hash"] for o in native} == {want}, \
        f"native hier ladder != grid oracle for {comp}"
    assert {o["hash"] for o in py} == {want}, \
        f"python hier plane != grid oracle for {comp}"
    assert all(o["plane"] == "hier" for o in py + native)


@pytest.mark.slow
@pytest.mark.parametrize("comp", ["bf16", "fp16"])
def test_bitwise_matrix_star_slow(comp):
    want = _oracle_digest(comp)
    star = _matrix_world("python", comp, ring=False)
    assert {o["hash"] for o in star} == {want}


@pytest.mark.slow
@pytest.mark.parametrize("comp", ["none", "fp16"])
def test_bitwise_matrix_hier_slow(comp):
    want = _oracle_digest(comp, grid=(2, 2))
    native = _matrix_world("native!", comp, hier=True)
    py = _matrix_world("python", comp, hier=True)
    assert {o["hash"] for o in native} == {want}
    assert {o["hash"] for o in py} == {want}


# ------------------------- EF residual carry across a plane demotion

DEMOTION_WORKER = r"""
import hashlib, json, os, sys
sys.path.insert(0, os.environ["HVD_REPO"])
import numpy as np
from horovod_tpu.common.config import Config
from horovod_tpu.common.engine import PyEngine
from horovod_tpu.common.topology import Topology
from horovod_tpu import metrics as hvd_metrics

rank = int(os.environ["HOROVOD_RANK"]); world = int(os.environ["HOROVOD_SIZE"])
eng = PyEngine(Topology(rank, world, 0, 1, rank, world),
               Config(cycle_time_ms=1.0, stall_check_disable=True,
                      compression="topk"))
try:
    elems = 30011
    rng = np.random.default_rng(31)
    digest = hashlib.sha256()
    for step in range(6):
        pay = [(rng.standard_normal(elems) * (r + 1)).astype(np.float32)
               for r in range(world)]
        out = eng.run("allreduce", pay[rank], "grad")
        digest.update(np.ascontiguousarray(out).tobytes())
    snap = hvd_metrics.registry().snapshot()["counters"]
    print(json.dumps({
        "rank": rank, "hash": digest.hexdigest(),
        "demotions": snap.get("horovod_plane_demotions_total", 0),
        "resets": snap.get("horovod_elastic_resets_total", 0)}))
finally:
    eng.shutdown()
"""


def test_topk_residual_carry_across_mid_collective_demotion():
    """EF residuals must survive a rung-2 plane demotion MID-COLLECTIVE:
    the same name reuses its residual every step, a ring frame is chaos-
    reset during step 3, and the faulted world's 6-step result stream must
    stay bitwise identical to the fault-free world's — the redo replays
    the already-sparsified contribution (residual claimed at enqueue,
    never folded twice) and later steps keep folding the carried
    residuals."""
    clean = [r["out"] for r in launch_world(
        WORLD, DEMOTION_WORKER, extra_env={"HOROVOD_ENGINE": "python"})]
    fault = [r["out"] for r in launch_world(
        WORLD, DEMOTION_WORKER,
        extra_env={"HOROVOD_ENGINE": "python",
                   "HOROVOD_FAULT_NET": "reset",
                   "HOROVOD_FAULT_NET_SCOPE": "ring",
                   "HOROVOD_FAULT_NET_RANK": "1",
                   "HOROVOD_FAULT_NET_AFTER": "18",
                   "HOROVOD_FAULT_NET_COUNT": "1"})]
    assert len({o["hash"] for o in clean}) == 1
    assert len({o["hash"] for o in fault}) == 1
    assert {o["hash"] for o in fault} == {clean[0]["hash"]}, (
        "faulted world diverged bitwise — a residual was dropped or "
        "folded twice across the demotion replay")
    assert max(o["demotions"] for o in fault) >= 1, \
        "the chaos reset never demoted the plane (test exercised nothing)"
    assert all(o["resets"] == 0 for o in fault), \
        "the demotion escalated to an elastic reset"
