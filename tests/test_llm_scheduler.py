"""Iteration-level scheduler (ISSUE 12): token-granularity join/leave,
fairness under KV pressure, preempt/resume exactness, and the
cross-contamination oracle (every scheduled output must equal the
sequential contiguous-cache generation, whatever the batch did)."""

from __future__ import annotations

import numpy as np
import pytest

from horovod_tpu.serving.llm.kv_cache import PagedKVCache, blocks_for
from horovod_tpu.serving.llm.scheduler import (
    FAILED,
    IterationScheduler,
    Sequence,
)
from horovod_tpu.serving.model import (
    lm_context_step,
    lm_generate,
    lm_prefill,
    tiny_lm_params,
)

PARAMS = tiny_lm_params()


def _run(sched, max_steps=2000, until=None):
    for _ in range(max_steps):
        sched.step()
        if sched.finished_total >= (until or 0) and until is not None:
            return
        if not sched.waiting and not sched.running:
            return
    raise AssertionError(f"scheduler did not drain: {sched.stats()}")


def _outputs(sched) -> dict:
    return {s.seq_id: list(s.out) for s in sched.finished}


# -- model sanity -------------------------------------------------------------


def test_lm_is_deterministic_across_processes_by_construction():
    p1, p2 = tiny_lm_params(seed=3), tiny_lm_params(seed=3)
    for k in ("embed", "pos", "wq", "wk", "wv", "wo"):
        np.testing.assert_array_equal(p1[k], p2[k])
    assert lm_generate(p1, [5, 6], 8) == lm_generate(p2, [5, 6], 8)


def test_lm_prefill_equals_stepwise():
    k, v, nxt = lm_prefill(PARAMS, [4, 9, 11])
    ks, vs = np.zeros((0, 16), np.float32), np.zeros((0, 16), np.float32)
    for i, t in enumerate([4, 9, 11]):
        n2, kv_k, kv_v = lm_context_step(PARAMS, t, i, ks, vs)
        ks = np.concatenate([ks, kv_k[None]])
        vs = np.concatenate([vs, kv_v[None]])
    np.testing.assert_array_equal(k, ks)
    np.testing.assert_array_equal(v, vs)
    assert nxt == n2


# -- token-granularity join/leave ---------------------------------------------


def test_single_sequence_matches_oracle():
    cache = PagedKVCache(32, 4, 16)
    s = IterationScheduler(cache, PARAMS, max_active=4)
    s.submit(Sequence(0, [3, 17, 5], 16))
    _run(s, until=1)
    assert _outputs(s)[0] == lm_generate(PARAMS, [3, 17, 5], 16)
    assert cache.alloc.used_count == 0      # retired blocks all freed


def test_eos_retires_immediately():
    """A sequence retires the iteration EOS appears — no trailing decode
    up to max_new_tokens."""
    oracle = lm_generate(PARAMS, [3, 17, 5], 32)
    eos = oracle[4]                          # force an early stop
    cache = PagedKVCache(32, 4, 16)
    s = IterationScheduler(cache, PARAMS, max_active=2)
    seq = Sequence(0, [3, 17, 5], 32, eos_id=eos)
    s.submit(seq)
    _run(s, until=1)
    out = _outputs(s)[0]
    assert out == oracle[:5]                 # cut AT the eos token
    assert len(out) < 32


def test_mid_stream_admission_and_retirement():
    """Short sequences join a long generation's batch mid-stream, finish
    first, and leave — the no-head-of-line-blocking core. Mean occupancy
    must exceed 1 and every output must match its oracle."""
    cache = PagedKVCache(128, 4, 16)
    s = IterationScheduler(cache, PARAMS, max_active=8)
    s.submit(Sequence("long", [1, 2, 3], 40))
    # run the long one alone for a few iterations, then add late joiners
    for _ in range(5):
        s.step()
    assert [q.seq_id for q in s.running] == ["long"]
    for i in range(3):
        s.submit(Sequence(i, [10 + i, 20 + i], 5))
    _run(s, until=4)
    outs = _outputs(s)
    assert outs["long"] == lm_generate(PARAMS, [1, 2, 3], 40)
    for i in range(3):
        assert outs[i] == lm_generate(PARAMS, [10 + i, 20 + i], 5)
    # the short ones joined AND left while the long one kept running
    finish_order = [q.seq_id for q in s.finished]
    assert finish_order.index("long") == 3
    st = s.stats()
    assert st["occupancy_sum"] / st["iterations_total"] > 1.0


def test_batch_outputs_equal_oracle_under_churn():
    """The contamination oracle at scale: 12 overlapping sequences with
    mixed lengths through a pool small enough to force block reuse —
    every token of every output must equal the isolated sequential run."""
    rng = np.random.RandomState(5)
    cache = PagedKVCache(48, 4, 16)
    s = IterationScheduler(cache, PARAMS, max_active=4,
                           admission_window=16)
    prompts = {}
    for i in range(12):
        pr = [int(t) for t in rng.randint(0, 64, rng.randint(1, 9))]
        prompts[i] = pr
        s.submit(Sequence(i, pr, int(rng.randint(2, 14))))
    _run(s, until=12)
    outs = _outputs(s)
    for i, pr in prompts.items():
        seq = next(q for q in s.finished if q.seq_id == i)
        assert outs[i] == lm_generate(PARAMS, pr, seq.max_new_tokens), \
            f"sequence {i} diverged from its oracle (contamination)"
    cache.alloc.check_invariants()
    assert cache.alloc.used_count == 0


# -- fairness under KV pressure -----------------------------------------------


def test_admission_window_bounds_prefill_starvation():
    """Generations hogging every block cannot starve a queued prefill
    past the admission window: once the window expires, force-admission
    preempts the newest running sequence and the starved prefill starts
    — while every output (including the victim's) stays oracle-exact."""
    window = 3
    cache = PagedKVCache(12, 2, 16, watermark=1 / 12)   # reserve = 1
    s = IterationScheduler(cache, PARAMS, max_active=4,
                           admission_window=window)
    hogs = {"hog1": [1] * 6, "hog2": [2] * 6}
    for sid, pr in hogs.items():
        s.submit(Sequence(sid, pr, 10))    # each grows toward 8 blocks
    # run until growth has exhausted admission headroom
    for _ in range(200):
        s.step()
        if not cache.alloc.can_alloc(1):
            break
    assert not cache.alloc.can_alloc(1), "pool never saturated"
    assert len(s.running) >= 1 and s.finished_total == 0
    s.submit(Sequence("late", [7, 8], 4))
    late = next(q for q in s.waiting if q.seq_id == "late")
    waited_iters = 0
    while late.state == "waiting":
        s.step()
        waited_iters += 1
        assert waited_iters <= 3 * (window + 2), \
            "late prefill starved past the admission window"
    assert cache.alloc.preemptions_total >= 1
    _run(s, until=3)
    for q in s.finished:
        pr = dict(hogs, late=[7, 8])[q.seq_id]
        assert q.out == lm_generate(PARAMS, pr, q.max_new_tokens), \
            f"{q.seq_id} diverged after preemption churn"


def test_preempted_sequence_resumes_bitwise_identically():
    """The satellite bar: preempt mid-generation, requeue, resume — the
    final tokens equal the never-preempted run exactly."""
    prompt, max_new = [3, 17, 5], 12
    oracle = lm_generate(PARAMS, prompt, max_new)

    cache = PagedKVCache(32, 4, 16)
    s = IterationScheduler(cache, PARAMS, max_active=2)
    seq = Sequence(0, prompt, max_new)
    s.submit(seq)
    for _ in range(4):                     # some decode progress
        s.step()
    assert seq.state == "running" and len(seq.out) >= 2
    mid = list(seq.out)
    s._preempt(seq)                        # forced preemption
    assert seq.state == "waiting" and seq.preemptions == 1
    assert cache.alloc.used_count == 0
    _run(s, until=1)
    assert seq.out == oracle
    assert seq.out[:len(mid)] == mid       # the prefix was preserved


def test_preemption_on_block_exhaustion_never_fails_a_sequence():
    """Tiny pool, many sequences: exhaustion degrades to preempt+requeue
    and everything completes exactly (never OOM, never wrong)."""
    cache = PagedKVCache(8, 2, 16, watermark=0.125)
    s = IterationScheduler(cache, PARAMS, max_active=3,
                           admission_window=8)
    prompts = {i: [int(i) + 1, int(i) + 2] for i in range(6)}
    for i, pr in prompts.items():
        s.submit(Sequence(i, pr, 5))
    _run(s, until=6, max_steps=4000)
    for i, pr in prompts.items():
        out = _outputs(s)[i]
        assert out == lm_generate(PARAMS, pr, 5)
    cache.alloc.check_invariants()


def test_oversized_request_fails_fast_not_deadlocks():
    cache = PagedKVCache(4, 2, 16, watermark=0.25)   # 3 usable blocks
    s = IterationScheduler(cache, PARAMS, max_active=2)
    seq = Sequence(0, [1] * 5, 4)                    # needs 9 > 6 tokens
    s.submit(seq)
    assert seq.state == FAILED
    assert "exceeds capacity" in seq.error
    assert s.finished and s.finished[0] is seq


def test_retired_slot_reuse_does_not_contaminate():
    """Serial reuse of the same tiny cache across many sequences: block
    tables from retired sequences are recycled; outputs stay exact."""
    cache = PagedKVCache(6, 2, 16, watermark=0.0)
    s = IterationScheduler(cache, PARAMS, max_active=1)
    for i in range(8):
        pr = [(3 * i) % 64, (5 * i + 1) % 64]
        s.submit(Sequence(i, pr, 4))
    _run(s, until=8)
    for i in range(8):
        pr = [(3 * i) % 64, (5 * i + 1) % 64]
        assert _outputs(s)[i] == lm_generate(PARAMS, pr, 4)


def test_handoff_admission_matches_local_prefill():
    """A sequence entering via KV handoff (prefill-pool path) decodes
    exactly like one prefilled in-engine (colocated path)."""
    prompt, max_new = [9, 30, 2], 10
    k, v, first = lm_prefill(PARAMS, prompt)

    via_handoff = IterationScheduler(PagedKVCache(16, 4, 16), PARAMS)
    via_handoff.submit(Sequence(0, prompt, max_new, first_token=first,
                                handoff=(k, v)))
    _run(via_handoff, until=1)

    local = IterationScheduler(PagedKVCache(16, 4, 16), PARAMS)
    local.submit(Sequence(0, prompt, max_new))
    _run(local, until=1)

    oracle = lm_generate(PARAMS, prompt, max_new)
    assert _outputs(via_handoff)[0] == oracle
    assert _outputs(local)[0] == oracle


def test_stats_shape_and_block_accounting():
    cache = PagedKVCache(16, 4, 16)
    s = IterationScheduler(cache, PARAMS, max_active=2)
    s.submit(Sequence(0, [1, 2], 3))
    _run(s, until=1)
    st = s.stats()
    for key in ("active", "waiting", "blocks_used", "blocks_free",
                "waiting_blocks_needed", "preemptions_total",
                "tokens_prefill_total", "tokens_decode_total",
                "iterations_total", "occupancy_sum", "finished_total",
                "blocks_freed_total"):
        assert key in st, key
    assert st["blocks_free"] == 16 and st["blocks_used"] == 0
    assert st["blocks_freed_total"] == blocks_for(2 + 3 - 1, 4)
    assert st["tokens_decode_total"] == 2    # 3 new tokens, 1 via prefill