"""Serving vertical (ISSUE 10): continuous batcher, SLO admission,
autoscaler decision, checkpoint refusal, scan decode, and the 2-replica
end-to-end kill/retry/drain path."""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

from horovod_tpu import checkpoint
from horovod_tpu.serving import (
    AdmissionController,
    ContinuousBatcher,
    InferenceServer,
    ReplicaManager,
    Request,
    ServeConfig,
    autoscale_decision,
    bucket_for,
    bucket_sizes,
    load_for_serving,
    make_decode_fn,
    mlp_builder,
    pad_batch,
    resolve_builder,
)


def _cfg(**kw):
    kw.setdefault("port", 0)
    return ServeConfig.from_env(**kw)


# -- config ------------------------------------------------------------------


def test_config_env_and_overrides(monkeypatch):
    monkeypatch.setenv("HOROVOD_SERVE_MAX_BATCH", "16")
    monkeypatch.setenv("HOROVOD_SERVE_SLO_MS", "250")
    cfg = ServeConfig.from_env()
    assert cfg.max_batch == 16 and cfg.slo_ms == 250.0
    # explicit overrides win over env
    assert ServeConfig.from_env(max_batch=4).max_batch == 4
    with pytest.raises(TypeError):
        ServeConfig.from_env(nonsense=1)
    with pytest.raises(ValueError):
        ServeConfig.from_env(min_replicas=3, max_replicas=2)


# -- padding buckets ---------------------------------------------------------


def test_bucket_sizes_and_selection():
    assert bucket_sizes(8) == (1, 2, 4, 8)
    assert bucket_sizes(6) == (1, 2, 4, 6)
    assert bucket_sizes(1) == (1,)
    sizes = bucket_sizes(8)
    assert [bucket_for(n, sizes) for n in (1, 2, 3, 5, 8)] == [1, 2, 4, 8, 8]
    with pytest.raises(ValueError):
        bucket_for(9, sizes)


def test_pad_batch_zero_fills_to_bucket():
    xs = [np.full(3, i, np.float32) for i in range(3)]
    arr = pad_batch(xs, 4)
    assert arr.shape == (4, 3)
    np.testing.assert_array_equal(arr[2], np.full(3, 2.0))
    np.testing.assert_array_equal(arr[3], np.zeros(3))
    with pytest.raises(ValueError):
        pad_batch(xs, 2)


# -- continuous batcher ------------------------------------------------------


def test_batcher_coalesces_queued_requests_in_one_take():
    b = ContinuousBatcher(_cfg(max_batch=8, max_wait_ms=30.0))
    reqs = [Request(np.zeros(2, np.float32)) for _ in range(5)]
    for r in reqs:
        assert b.submit(r)
    batch = b.take_batch(timeout=1.0)
    assert [r.rid for r in batch] == [r.rid for r in reqs]
    assert b.depth() == 0


def test_batcher_waits_max_wait_for_late_companions():
    b = ContinuousBatcher(_cfg(max_batch=8, max_wait_ms=200.0))
    first = Request(np.zeros(2, np.float32))
    late = Request(np.zeros(2, np.float32))
    b.submit(first)

    def arrive_late():
        time.sleep(0.05)
        b.submit(late)

    t = threading.Thread(target=arrive_late)
    t.start()
    batch = b.take_batch(timeout=1.0)
    t.join()
    # the late arrival landed inside the max-wait window and coalesced
    assert len(batch) == 2


def test_batcher_full_batch_dispatches_without_waiting():
    b = ContinuousBatcher(_cfg(max_batch=4, max_wait_ms=5000.0))
    for _ in range(4):
        b.submit(Request(np.zeros(2, np.float32)))
    t0 = time.monotonic()
    batch = b.take_batch(timeout=1.0)
    assert len(batch) == 4
    assert time.monotonic() - t0 < 1.0  # did NOT sit out the 5s max-wait


def test_batcher_fails_expired_requests_with_504():
    b = ContinuousBatcher(_cfg(max_batch=4, max_wait_ms=1.0))
    dead = Request(np.zeros(2, np.float32),
                   deadline_t=time.monotonic() - 0.01)
    live = Request(np.zeros(2, np.float32),
                   deadline_t=time.monotonic() + 30.0)
    b.submit(dead)
    b.submit(live)
    batch = b.take_batch(timeout=1.0)
    assert [r.rid for r in batch] == [live.rid]
    assert dead.code == 504 and dead.event.is_set()


def test_batcher_requeue_front_preserves_order_and_closes_with_503():
    b = ContinuousBatcher(_cfg(max_batch=8, max_wait_ms=1.0))
    r1, r2, r3 = (Request(np.zeros(1, np.float32)) for _ in range(3))
    b.submit(r3)
    b.requeue_front([r1, r2])
    batch = b.take_batch(timeout=1.0)
    assert [r.rid for r in batch] == [r1.rid, r2.rid, r3.rid]
    pending = Request(np.zeros(1, np.float32))
    b.submit(pending)
    b.close()
    assert pending.code == 503
    assert b.submit(Request(np.zeros(1, np.float32))) is False


def test_request_terminal_state_is_single_assignment():
    r = Request(np.zeros(1, np.float32))
    assert r.finish(np.ones(1)) is True
    assert r.fail(504, "late") is False
    assert r.code == 200 and r.output is not None
    r2 = Request(np.zeros(1, np.float32))
    assert r2.fail(429, "shed") is True
    assert r2.finish(np.ones(1)) is False
    assert r2.code == 429


# -- SLO admission -----------------------------------------------------------


def test_admission_cold_start_admits_then_sheds_on_projection():
    cfg = _cfg(slo_ms=500.0)
    adm = AdmissionController(cfg)
    # cold: no drain-rate estimate, nothing sheds however deep the queue
    ok, wait = adm.admit(queue_depth=10_000, replicas=1)
    assert ok and wait == 0.0
    # one replica retires 10 req/s -> 10 queued project to 1s > 500ms SLO
    adm.observe_batch(10, 1.0)
    assert adm.projected_wait_s(10, 1) == pytest.approx(1.0)
    ok, wait = adm.admit(10, 1)
    assert not ok and wait == pytest.approx(1.0)
    # more replicas drain faster: the same depth fits the SLO again
    ok, _ = adm.admit(10, 4)
    assert ok
    # a request with its own generous deadline is NOT shed
    ok, _ = adm.admit(10, 1, budget_s=20.0)
    assert ok
    # ... and a tighter-than-SLO deadline sheds earlier
    ok, _ = adm.admit(3, 1, budget_s=0.1)
    assert not ok


def test_admission_ewma_tracks_observed_rate():
    adm = AdmissionController(_cfg())
    adm.observe_batch(8, 1.0)      # 8 req/s
    r0 = adm.drain_rate()
    adm.observe_batch(16, 1.0)     # rate doubles; EWMA moves toward it
    assert r0 < adm.drain_rate() < 16.0


# -- autoscaler decision -----------------------------------------------------


def test_autoscale_decision_up_down_and_cooldown():
    cfg = _cfg(min_replicas=1, max_replicas=4, target_queue=4.0,
               cooldown_s=10.0)
    now = 1000.0
    # queue over the per-replica setpoint -> +1
    assert autoscale_decision(depth=9, desired=2, cfg=cfg, now=now,
                              last_scale_t=0.0, last_busy_t=now) == 1
    # inside the cooldown window -> hold, whatever the queue says
    assert autoscale_decision(9, 2, cfg, now, last_scale_t=now - 5.0,
                              last_busy_t=now) == 0
    # at max_replicas -> hold
    assert autoscale_decision(100, 4, cfg, now, 0.0, now) == 0
    # empty queue but only briefly idle -> hold
    assert autoscale_decision(0, 2, cfg, now, 0.0,
                              last_busy_t=now - 2.0) == 0
    # empty queue, idle a full cooldown -> -1
    assert autoscale_decision(0, 2, cfg, now, 0.0,
                              last_busy_t=now - 11.0) == -1
    # never below min_replicas
    assert autoscale_decision(0, 1, cfg, now, 0.0, now - 100.0) == 0


def test_manager_requeue_failed_retries_then_503():
    cfg = _cfg(max_retries=1, max_batch=4)
    b = ContinuousBatcher(cfg)
    mgr = ReplicaManager(cfg, b, AdmissionController(cfg))
    fresh = Request(np.zeros(1, np.float32))
    spent = Request(np.zeros(1, np.float32))
    spent.retries = 1   # already used its one retry
    mgr._requeue_failed([fresh, spent])
    assert spent.code == 503 and "retries exhausted" in spent.error
    assert fresh.retries == 1 and not fresh.event.is_set()
    assert b.depth() == 1   # only the retryable request went back


# -- model machinery ---------------------------------------------------------


def test_load_for_serving_refuses_raw_training_checkpoint(tmp_path):
    state = {"params": {"w": np.ones(3)},
             "opt_state": {"momentum": np.ones(3)}}
    checkpoint.save(str(tmp_path / "train"), state)
    with pytest.raises(ValueError, match="export_for_inference"):
        load_for_serving(str(tmp_path / "train"))
    checkpoint.export_for_inference(str(tmp_path / "serve"), state)
    restored = load_for_serving(str(tmp_path / "serve"))
    assert "opt_state" not in restored


def test_resolve_builder_spec_errors():
    assert resolve_builder("horovod_tpu.serving.model:mlp_builder") \
        is mlp_builder
    with pytest.raises(ValueError):
        resolve_builder("no-colon-here")
    with pytest.raises(ImportError):
        resolve_builder("not.a.module:fn")
    with pytest.raises(AttributeError):
        resolve_builder("horovod_tpu.serving.model:nope")


def test_make_decode_fn_scan_matches_sequential_applies():
    import jax.numpy as jnp

    def step(x):
        return jnp.tanh(x) * 1.5 + 0.25

    x = np.linspace(-1, 1, 12).astype(np.float32).reshape(3, 4)
    scanned = make_decode_fn(step, steps=4)
    expect = x
    for _ in range(4):
        expect = step(expect)
    np.testing.assert_allclose(np.asarray(scanned(x)), np.asarray(expect),
                               rtol=1e-6)
    with pytest.raises(ValueError):
        make_decode_fn(step, steps=0)


def test_mlp_builder_rederives_architecture_from_params():
    import jax

    from horovod_tpu.models import MLP

    model = MLP(features=(24, 7))
    x = np.random.RandomState(0).randn(5, 12).astype(np.float32)
    params = model.init(jax.random.PRNGKey(0), x)["params"]
    apply_fn = mlp_builder({"params": params})
    out = np.asarray(apply_fn(x))
    assert out.shape == (5, 7)
    np.testing.assert_allclose(
        out, np.asarray(model.apply({"params": params}, x)), rtol=1e-6)
    with pytest.raises(ValueError, match="no Dense"):
        mlp_builder({"params": {"Conv_0": {"kernel": np.ones((3, 3))}}})


# -- end to end --------------------------------------------------------------


def _post(port: int, payload: dict, timeout: float = 30.0):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/infer",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def test_two_replica_serve_kill_retry_and_drain(tmp_path):
    """The serving e2e: export -> 2 replicas -> HTTP + in-process infer ->
    SIGKILL one replica under load (zero failed requests, respawn,
    blacklist) -> drain to 1 on scale-down -> still serving."""
    import jax

    from horovod_tpu.models import MLP

    dim = 16
    model = MLP(features=(32, 8))
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((2, dim), np.float32))["params"]
    ckpt = str(tmp_path / "serve")
    checkpoint.export_for_inference(ckpt, {"params": params})

    cfg = _cfg(min_replicas=1, max_replicas=2, max_batch=4,
               max_wait_ms=5.0, slo_ms=8000.0, cooldown_s=3600.0)
    server = InferenceServer(ckpt, config=cfg,
                             replica_env={"JAX_PLATFORMS": "cpu"}).start()
    try:
        server.manager.scale_to(2)
        deadline = time.monotonic() + 180
        while server.manager.serving_count() < 2 and \
                time.monotonic() < deadline:
            time.sleep(0.1)
        assert server.manager.serving_count() == 2, \
            server.manager.degraded_reason or server.manager.describe()

        # in-process + HTTP round trips agree with the model
        x = np.linspace(0, 1, dim).astype(np.float32)
        expect = np.asarray(model.apply({"params": params}, x[None]))[0]
        np.testing.assert_allclose(server.infer(x, deadline_ms=8000),
                                   expect, rtol=1e-5)
        status, body = _post(server.port, {"inputs": x.tolist()})
        assert status == 200
        np.testing.assert_allclose(np.asarray(body["outputs"]), expect,
                                   rtol=1e-4)

        # kill one replica while requests are in flight
        failures: list[str] = []

        def load():
            for _ in range(60):
                try:
                    server.infer(x, deadline_ms=8000)
                except RuntimeError as e:
                    failures.append(str(e))

        threads = [threading.Thread(target=load) for _ in range(3)]
        for t in threads:
            t.start()
        victim = next(r["pid"] for r in
                      server.manager.describe()["replicas"].values()
                      if r["state"] == "serving")
        time.sleep(0.2)
        os.kill(victim, 9)
        for t in threads:
            t.join()
        assert not failures, failures[:3]
        deadline = time.monotonic() + 120
        while server.manager.serving_count() < 2 and \
                time.monotonic() < deadline:
            time.sleep(0.1)
        assert server.manager.serving_count() == 2, "no respawn"
        assert server.manager.blacklist.blacklisted(), \
            "killed replica not blacklisted"

        # drain-on-scale-down: back to 1 replica with no dropped requests
        server.manager.scale_to(1)
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            reps = server.manager.describe()["replicas"]
            if len(reps) == 1 and all(r["state"] == "serving"
                                      for r in reps.values()):
                break
            time.sleep(0.1)
        reps = server.manager.describe()["replicas"]
        assert len(reps) == 1, reps
        np.testing.assert_allclose(server.infer(x, deadline_ms=8000),
                                   expect, rtol=1e-5)

        # /stats carries the serving series + a schema-valid snapshot
        from horovod_tpu.metrics import validate_snapshot

        stats = server.stats()
        assert validate_snapshot(stats["metrics"]) == []
        counters = stats["metrics"]["counters"]
        assert counters.get('horovod_serve_requests_total{code="200"}',
                            0) > 0
        assert counters.get("horovod_serve_replica_deaths_total", 0) >= 1
        assert counters.get("horovod_serve_replica_respawns_total", 0) >= 1
    finally:
        server.stop()
