"""Native C++ engine tests — same semantics matrix as test_engine.py, run
against libhvd_core.so. Multi-rank worlds are real OS processes talking to
the rank-0 TCP coordinator (the reference tests the analogous path under
`mpirun -np 2`, SURVEY.md §4)."""

from __future__ import annotations

import os
import subprocess
import sys
import textwrap

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np
import pytest

from launch_util import launch_world as _launch_world

from horovod_tpu.common.config import Config
from horovod_tpu.common.topology import Topology


@pytest.fixture(scope="module")
def native():
    from horovod_tpu.cc import lib_path

    lib_path()  # build if needed
    from horovod_tpu.cc.native_engine import NativeEngine

    return NativeEngine


def make_engine(NativeEngine):
    topo = Topology(0, 1, 0, 1, 0, 1)
    return NativeEngine(topo, Config(cycle_time_ms=1.0))


def test_native_single_process_ops(native):
    eng = make_engine(native)
    try:
        a = np.arange(6.0).reshape(2, 3)
        np.testing.assert_array_equal(eng.run("allreduce", a, "t1"), a)
        np.testing.assert_array_equal(eng.run("allgather", a, "t2"), a)
        np.testing.assert_array_equal(eng.run("broadcast", a, "t3"), a)
        h = eng.enqueue("allreduce", np.ones(4, np.float32), "async")
        out = eng.synchronize(h, timeout=10)
        assert out.dtype == np.float32
        np.testing.assert_array_equal(out, np.ones(4))
    finally:
        eng.shutdown()


def test_native_dtypes(native):
    eng = make_engine(native)
    try:
        for dt in (np.uint8, np.int8, np.int32, np.int64, np.float16,
                   np.float32, np.float64):
            a = np.ones((3,), dtype=dt)
            out = eng.run("allreduce", a, f"dt.{np.dtype(dt).name}")
            assert out.dtype == dt
            np.testing.assert_array_equal(out, a)
        import ml_dtypes

        a = np.ones((3,), dtype=ml_dtypes.bfloat16)
        out = eng.run("allreduce", a, "dt.bf16")
        assert out.dtype == ml_dtypes.bfloat16
    finally:
        eng.shutdown()


# --------------------------------------------------------- multi-process world

WORLD = 4

RANK_SCRIPT = textwrap.dedent("""
    import json, os, sys
    import numpy as np
    sys.path.insert(0, os.environ["HVD_REPO"])
    from horovod_tpu.cc.native_engine import NativeEngine, TensorShapeMismatchError
    from horovod_tpu.common.config import Config
    from horovod_tpu.common.topology import Topology

    rank = int(os.environ["HOROVOD_RANK"])
    world = int(os.environ["HOROVOD_SIZE"])
    topo = Topology(rank, world, rank, world, 0, 1)
    eng = NativeEngine(topo, Config(cycle_time_ms=1.0))
    out = {}

    # allreduce average
    a = np.full((3,), float(rank))
    out["allreduce"] = eng.run("allreduce", a, "g").tolist()

    # variable-dim allgather
    ag = np.full((rank + 1, 2), float(rank))
    out["allgather_shape"] = list(eng.run("allgather", ag, "ag").shape)

    # broadcast from root 2
    bc = np.full((2,), float(rank))
    out["broadcast"] = eng.run("broadcast", bc, "bc", root_rank=2).tolist()

    # alltoall
    a2a = np.full((world, 2), float(rank))
    out["alltoall"] = eng.run("alltoall", a2a, "a2a").tolist()

    # reducescatter (sum)
    rs = np.arange(world * 2, dtype=np.float64)
    out["reducescatter"] = eng.run("reducescatter", rs, "rs", average=False).tolist()

    # rank-divergent shape -> error on every rank
    bad = np.ones((3,) if rank != 1 else (4,))
    try:
        eng.run("allreduce", bad, "bad")
        out["mismatch"] = "no-error"
    except TensorShapeMismatchError as e:
        out["mismatch"] = "Mismatched" in str(e)
    eng.shutdown()
    print(json.dumps(out))
""")


def launch_world(world, script, extra_env=None):
    return [r["out"] for r in
            _launch_world(world, script, extra_env=extra_env, timeout=120)]


@pytest.mark.slow
def test_native_multiprocess_world(native):
    outs = launch_world(WORLD, RANK_SCRIPT)
    mean = float(np.mean(np.arange(WORLD)))
    total_rows = sum(r + 1 for r in range(WORLD))
    a2a_expect = np.repeat(np.arange(WORLD, dtype=np.float64), 2).reshape(WORLD, 2)
    for rank, o in enumerate(outs):
        np.testing.assert_allclose(o["allreduce"], np.full((3,), mean))
        assert o["allgather_shape"] == [total_rows, 2]
        np.testing.assert_allclose(o["broadcast"], np.full((2,), 2.0))
        np.testing.assert_allclose(o["alltoall"], a2a_expect)
        np.testing.assert_allclose(
            o["reducescatter"],
            WORLD * np.arange(WORLD * 2, dtype=np.float64)[rank * 2:(rank + 1) * 2],
        )
        assert o["mismatch"] is True


def test_native_timeline(native, tmp_path):
    """Timeline file contains negotiation + op phases (reference
    test/test_timeline.py:41-58)."""
    tl = tmp_path / "timeline.json"
    script = textwrap.dedent(f"""
        import os, sys
        import numpy as np
        sys.path.insert(0, os.environ["HVD_REPO"])
        from horovod_tpu.cc.native_engine import NativeEngine
        from horovod_tpu.common.config import Config
        from horovod_tpu.common.topology import Topology

        eng = NativeEngine(Topology(0, 1, 0, 1, 0, 1),
                           Config(cycle_time_ms=1.0, timeline={str(tl)!r},
                                  timeline_mark_cycles=True))
        eng.run("allreduce", np.ones(4), "tl_tensor")
        eng.shutdown()
        print('{{}}')
    """)
    env = dict(os.environ)
    env["HVD_REPO"] = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run([sys.executable, "-c", script], env=env,
                          capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr[-2000:]
    content = tl.read_text()
    assert "NEGOTIATE_ALLREDUCE" in content
    assert "tl_tensor" in content
    assert "CYCLE_START" in content


def test_native_duplicate_name_rejected(native, monkeypatch):
    """Second enqueue of a live name must raise (reference duplicate-name
    test, test_torch.py:356)."""
    from horovod_tpu.cc.native_engine import HorovodInternalError

    # A long fixed cycle keeps the first enqueue live across the second
    # one. HOROVOD_WAKE_ON_ENQUEUE=0 opts out of the adaptive cycle's
    # instant wake, which would otherwise drain h1 before the duplicate.
    monkeypatch.setenv("HOROVOD_WAKE_ON_ENQUEUE", "0")
    eng = native(Topology(0, 1, 0, 1, 0, 1), Config(cycle_time_ms=500.0))
    try:
        eng._lib  # engine built
        h1 = eng.enqueue("allreduce", np.ones(4), "dup")
        with pytest.raises(HorovodInternalError, match="Duplicate tensor name"):
            eng.enqueue("allreduce", np.ones(4), "dup")
        eng.synchronize(h1, timeout=10)
        # after completion the name is reusable
        h2 = eng.enqueue("allreduce", np.ones(4), "dup")
        eng.synchronize(h2, timeout=10)
    finally:
        eng.shutdown()


def test_native_autoname_unique(native):
    """Unnamed tensors get unique auto-names (no silent collision)."""
    eng = make_engine(native)
    try:
        h1 = eng.enqueue("allreduce", np.full(3, 1.0), None)
        h2 = eng.enqueue("allreduce", np.full(3, 2.0), None)  # same shape!
        np.testing.assert_array_equal(eng.synchronize(h1, timeout=10), np.full(3, 1.0))
        np.testing.assert_array_equal(eng.synchronize(h2, timeout=10), np.full(3, 2.0))
    finally:
        eng.shutdown()


def test_native_timeout_keeps_handle(native, monkeypatch):
    """A timed-out wait must not consume the handle; the result stays
    claimable (review finding: stranded-result leak)."""
    import threading
    from horovod_tpu.common.config import Config
    from horovod_tpu.common.topology import Topology

    # Fixed-cycle mode: the adaptive cycle's wake-on-enqueue would finish
    # the op before the deliberately-too-short wait below.
    monkeypatch.setenv("HOROVOD_WAKE_ON_ENQUEUE", "0")
    eng = native(Topology(0, 1, 0, 1, 0, 1), Config(cycle_time_ms=200.0))
    try:
        h = eng.enqueue("allreduce", np.arange(4.0), "slowpoke")
        with pytest.raises(TimeoutError):
            eng.synchronize(h, timeout=0.01)  # cycle is 200ms: not done yet
        out = eng.synchronize(h, timeout=10)  # retry wins
        np.testing.assert_array_equal(out, np.arange(4.0))
    finally:
        eng.shutdown()


def test_native_scalar_allgather_errors(native):
    from horovod_tpu.cc.native_engine import HorovodInternalError

    eng = make_engine(native)
    try:
        with pytest.raises((HorovodInternalError, Exception), match="rank >= 1"):
            eng.run("allgather", np.float64(3.0), "scalar_ag")
    finally:
        eng.shutdown()
