"""Multi-process compiled plane: jitted collectives spanning process
boundaries — the pod execution shape (N host processes x M local chips).

The reference's core CI discipline is running the REAL multi-process shape
(`mpirun -np 2`, .travis.yml:100-113; world formation operations.cc:1728-1797).
Here the equivalent launch is ``hvdrun -np 2 --jax-distributed`` with 4
virtual CPU devices per process: each worker's ``hvd.init()`` joins the JAX
distributed runtime at the launcher-negotiated coordinator, the default mesh
spans all 8 devices, and the fused-DistributedOptimizer step runs jitted
collectives (gloo on CPU, ICI/DCN on TPU) across the two processes.

The single-process 8-device run (the rest of the suite's harness) is the
oracle: same program, the only change is the process boundary.
"""

import json
import os
import subprocess
import sys

import numpy as np
import optax
import pytest

import horovod_tpu as hvd
from horovod_tpu.runner import run_command

SCRIPT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "mp_train_script.py")
# Workers override the pytest harness's 8-virtual-device XLA_FLAGS: 2 procs
# x 4 devices each = the same 8-device world split across processes.
WORKER_ENV = {"XLA_FLAGS": "--xla_force_host_platform_device_count=4"}


def _launch(mode, out_base, np_=2):
    rc = run_command(
        [sys.executable, SCRIPT, mode, str(out_base)],
        num_proc=np_, env=dict(WORKER_ENV), timeout=300.0,
        jax_distributed=True)
    assert rc == 0, f"hvdrun-style launch failed with exit code {rc}"
    results = []
    for rank in range(np_):
        with open(f"{out_base}.{rank}") as f:
            results.append(json.load(f))
    return results


def test_two_process_trajectory_matches_single_process(tmp_path, mesh8):
    """hvdrun -np 2 --jax-distributed == one process with 8 devices, for the
    fused DistributedOptimizer step (trajectory equality across the process
    boundary — VERDICT r4 item 1's done-criterion)."""
    r0, r1 = _launch("trajectory", tmp_path / "traj")
    # World formed as 2 processes x 4 local = 8 global devices.
    for r in (r0, r1):
        assert r["nproc"] == 2 and r["local"] == 4 and r["ndev"] == 8
    # Replicated params: both processes hold bit-identical results.
    assert r0["w"] == r1["w"] and r0["b"] == r1["b"]

    # Oracle: the identical program on this process's 8-device mesh.
    sys.path.insert(0, os.path.dirname(SCRIPT))
    try:
        import mp_train_script as mp
    finally:
        sys.path.pop(0)
    from horovod_tpu.compat import shard_map
    import jax
    from jax.sharding import PartitionSpec as P

    x, y, params = mp.make_problem(8)
    opt = hvd.jax.DistributedOptimizer(optax.adam(1e-2))
    state = opt.init(params)

    def step(params, state, x, y):
        grads = jax.grad(mp.loss_fn)(params, x, y)
        updates, state = opt.update(grads, state, params)
        return optax.apply_updates(params, updates), state

    sstep = jax.jit(shard_map(step, mesh=mesh8,
                              in_specs=(P(), P(), P("hvd"), P("hvd")),
                              out_specs=(P(), P()), check_vma=False))
    for _ in range(mp.STEPS):
        params, state = sstep(params, state, x, y)
    np.testing.assert_allclose(np.array(r0["w"]), np.asarray(params["w"]),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.array(r0["b"]), np.asarray(params["b"]),
                               rtol=1e-5, atol=1e-6)


def test_cli_flag_reaches_worker_env(monkeypatch):
    """`hvdrun --jax-distributed` flows through argparse into run_command's
    jax_distributed knob, which injects HOROVOD_JAX_DISTRIBUTED=1 into worker
    env (checked against the real run_command's env-merge logic)."""
    from horovod_tpu.runner import __main__ as cli

    seen = {}

    def fake_run_command(command, num_proc=None, env=None, **kw):
        seen["jax_distributed"] = kw.get("jax_distributed")
        return 0

    import horovod_tpu.runner as runner_pkg

    monkeypatch.setattr(runner_pkg, "run_command", fake_run_command)
    rc = cli.main(["-np", "2", "--jax-distributed", "--", "true"])
    assert rc == 0
    assert seen["jax_distributed"] is True


def test_init_refuses_without_coordinator(monkeypatch):
    """HOROVOD_JAX_DISTRIBUTED=1 outside a launcher context fails loudly, not
    with a hang at a dead address."""
    hvd.shutdown()
    monkeypatch.setenv("HOROVOD_JAX_DISTRIBUTED", "1")
    monkeypatch.delenv("HOROVOD_JAX_COORDINATOR", raising=False)
    with pytest.raises(RuntimeError, match="HOROVOD_JAX_COORDINATOR"):
        hvd.init()
    monkeypatch.delenv("HOROVOD_JAX_DISTRIBUTED")
    hvd.init()  # state must be clean after the refused init
    hvd.shutdown()


@pytest.mark.slow
def test_hvdrun_cli_end_to_end(tmp_path):
    """The literal CLI: `python -m horovod_tpu.runner -np 2 --jax-distributed
    -- python mp_train_script.py` (argparse -> run_command -> task_exec ->
    register -> exec -> init -> federated mesh)."""
    out = tmp_path / "cli"
    env = dict(os.environ, **WORKER_ENV)
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", "2",
         "--jax-distributed",
         "--env", f"XLA_FLAGS={WORKER_ENV['XLA_FLAGS']}",
         "--", sys.executable, SCRIPT, "trajectory", str(out)],
        env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-3000:]
    with open(f"{out}.0") as f:
        r0 = json.load(f)
    assert r0["nproc"] == 2 and r0["ndev"] == 8
