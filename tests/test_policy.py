"""Adaptive compression-policy tests (ISSUE 9, common/policy.py).

Unit tier only: the policy's value-changing decisions must be
deterministic functions of (size, dtype, topology, config) — that
determinism IS the cross-rank agreement contract — and the live-metrics
refresh may steer only the value-neutral sparse/dense hop framing. The
end-to-end demonstration (different formats per fabric tier on a real
grid) lives in tools/sparse_smoke.py and the engine tests.
"""

import numpy as np

from horovod_tpu.common.config import Config
from horovod_tpu.common.policy import CompressionPolicy, resolve_format
from horovod_tpu.common.topology import Topology

BIG = 4 << 20      # 4 MiB f32 gradient: topk territory on DCN
MED = 16 << 10     # 16 KiB: bf16 territory on DCN
TINY = 256         # below HOROVOD_COMPRESSION_MIN_BYTES


def _grid_topo(rank=0, world=4, local=2):
    return Topology(rank, world, rank % local, local,
                    rank // local, world // local)


def _single_host_topo(rank=0, world=4):
    return Topology(rank, world, rank, world, 0, 1)


def test_decision_table_per_tier():
    pol = CompressionPolicy(Config(), _grid_topo())
    # ICI: full width for everything (the fast fabric is not the cliff).
    assert pol.decide(BIG, np.float32, "ici") == "none"
    assert pol.decide(BIG, np.float32, "local") == "none"
    # DCN: topk for large f32, bf16 for medium floats and f64.
    assert pol.decide(BIG, np.float32, "dcn") == "topk"
    assert pol.decide(BIG, np.float32, "cross") == "topk"
    assert pol.decide(MED, np.float32, "dcn") == "bf16"
    assert pol.decide(BIG, np.float64, "dcn") == "bf16"  # topk is f32-only
    # Opt-outs on every tier: non-floats, <=2-byte floats, sub-floor sizes.
    for tier in ("ici", "dcn"):
        assert pol.decide(BIG, np.int32, tier) == "none"
        assert pol.decide(BIG, np.float16, tier) == "none"
        assert pol.decide(TINY, np.float32, tier) == "none"


def test_resolve_depends_on_topology():
    # A grid world crosses hosts: the value-changing format is the DCN
    # decision. A single-host world never touches DCN: full width.
    grid = CompressionPolicy(Config(), _grid_topo())
    flat = CompressionPolicy(Config(), _single_host_topo())
    assert grid.resolve(BIG, np.float32) == "topk"
    assert grid.resolve(MED, np.float32) == "bf16"
    assert flat.resolve(BIG, np.float32) == "none"
    # Deterministic across ranks: every rank of the same grid resolves
    # identically (the cross-rank wire-agreement contract).
    for rank in range(4):
        pol = CompressionPolicy(Config(), _grid_topo(rank))
        assert pol.resolve(BIG, np.float32) == "topk"


def test_topk_ratio_and_floor_config(monkeypatch):
    monkeypatch.delenv("HOROVOD_TOPK_RATIO", raising=False)
    pol = CompressionPolicy(Config(topk_ratio=0.05), _grid_topo())
    assert pol.topk_ratio == 0.05
    monkeypatch.setenv("HOROVOD_TOPK_MIN_BYTES", str(32 << 20))
    high_floor = CompressionPolicy(Config(), _grid_topo())
    # Below the raised topk floor the DCN pick degrades to bf16.
    assert high_floor.decide(BIG, np.float32, "dcn") == "bf16"


def test_refresh_steers_sparse_framing_only():
    pol = CompressionPolicy(Config(), _grid_topo())
    assert pol.sparse_tiers() == frozenset({"cross"})
    # Cross-dominant wire time: sparse framing stays DCN-only.
    diag = pol.refresh({"counters": {
        'horovod_wire_bytes_total{tier="local"}': 1000,
        'horovod_wire_bytes_total{tier="cross"}': 9000,
    }, "gauges": {}})
    assert diag["bottleneck_tier"] == "dcn"
    assert pol.sparse_tiers() == frozenset({"cross"})
    # Local-dominant critical-path wire seconds (shared-core hosts):
    # the local tier gains sparse framing too — value-neutral escalation.
    diag = pol.refresh({"counters": {}, "gauges": {
        'horovod_critical_path_wire_seconds{tier="local"}': 3.0,
        'horovod_critical_path_wire_seconds{tier="cross"}': 0.5,
    }})
    assert diag["bottleneck_tier"] == "ici"
    assert pol.sparse_tiers() == frozenset({"cross", "local"})
    # The refresh NEVER changes the value-changing table.
    assert pol.decide(BIG, np.float32, "ici") == "none"
    assert pol.decide(BIG, np.float32, "dcn") == "topk"
    # Empty snapshot: falls back to the topology default.
    diag = pol.refresh({})
    assert diag["bottleneck_tier"] == "dcn"


def test_report_shape_for_smoke():
    pol = CompressionPolicy(Config(), _grid_topo())
    rep = pol.report()
    assert rep["ici"] == "none" and rep["dcn"] == "topk"
    assert rep["resolved"] == "topk"
    assert rep["topk_ratio"] == pol.topk_ratio
    assert rep["sparse_tiers"] == ["cross"]


def test_resolve_format_helper():
    pol = CompressionPolicy(Config(), _grid_topo())
    assert resolve_format("bf16", None, BIG, np.float32) == "bf16"
    assert resolve_format("topk@0.02", None, BIG, np.float32) == "topk"
    assert resolve_format("adaptive", pol, BIG, np.float32) == "topk"
    assert resolve_format("adaptive", pol, MED, np.float32) == "bf16"
    # No policy wired (non-engine callers): adaptive degrades to none.
    assert resolve_format("adaptive", None, BIG, np.float32) == "none"


def test_compiled_tier_format_substitutes_topk_by_design():
    """ISSUE 16 satellite — the ROADMAP open question is retired: the
    compiled plane's topk substitution is the DESIGNED table answer
    (policy.COMPILED_TOPK_SUBSTITUTE), not a warned-about gap. This test
    pins the substitution so a table change that silently re-opens it
    fails loudly."""
    from horovod_tpu.common.policy import (COMPILED_TOPK_SUBSTITUTE,
                                           compiled_tier_format)

    assert COMPILED_TOPK_SUBSTITUTE == "bf16"
    # The eager table answers topk for a big f32 DCN bucket; the compiled
    # resolve ships the designed substitute and reports the substitution.
    assert CompressionPolicy().decide(BIG, np.float32, "dcn") == "topk"
    assert compiled_tier_format(BIG, np.float32, "dcn") == \
        COMPILED_TOPK_SUBSTITUTE
    fmt, substituted = compiled_tier_format(BIG, np.float32, "dcn",
                                            with_fallback=True)
    assert fmt == COMPILED_TOPK_SUBSTITUTE and substituted is True
    # Already-servable answers pass through with no substitution flagged.
    assert compiled_tier_format(MED, np.float32, "dcn",
                                with_fallback=True) == ("bf16", False)
    assert compiled_tier_format(BIG, np.float32, "ici",
                                with_fallback=True) == ("none", False)
