"""Runtime-controller unit tests (ISSUE 16, horovod_tpu/control/).

Unit tier: the propose -> canary -> commit/rollback state machine, the
knob bounds, the training rule pass and the serving rule table — all
driven by hand-fed scores with zero threads and zero sleeps (the loop
takes explicit ``now`` values). The multi-process proof that a committed
knob change replays interrupted collectives bitwise lives in
tests/test_resilience.py (test_knob_flip_mid_run_stays_bitwise_consistent);
the end-to-end chaos recovery lives in tools/controller_smoke.py.
"""

import pytest

from horovod_tpu.control import (ControlLoop, Knob, ServingController,
                                 TrainingController)
from horovod_tpu.control.serving import RULES, maybe_start_serving_controller
from horovod_tpu.control.training import WIRE_LADDER
from horovod_tpu.metrics.registry import MetricsRegistry
from horovod_tpu.serving.config import ServeConfig


def _loop(applied, canary_steps=3, tolerance=0.05, **knobs):
    knobs = knobs or {"x": Knob("x", "int", lo=1, hi=64)}
    return ControlLoop(knobs, lambda n, v: applied.append((n, v)),
                       canary_steps=canary_steps, tolerance=tolerance,
                       cooldown_s=0.0, reg=MetricsRegistry())


def _settle(loop, score=10.0, n=8):
    for _ in range(n):
        loop.observe(score)


# ------------------------------------------------- knob bounds & stepping

def test_knob_clamp_and_bounds():
    k = Knob("t", "int", lo=4, hi=32)
    assert k.clamp(1) == 4 and k.clamp(100) == 32 and k.clamp(8) == 8
    assert k.in_bounds(4) and k.in_bounds(32) and not k.in_bounds(33)
    f = Knob("r", "float", lo=0.5, hi=2.0)
    assert f.clamp(10) == 2.0 and f.in_bounds(1.0)
    c = Knob("c", "choice", choices=("a", "b"))
    assert c.clamp("zzz") == "a" and c.in_bounds("b")
    assert not c.in_bounds("zzz")


def test_knob_step_ladders():
    c = Knob("c", "choice", choices=WIRE_LADDER)
    assert c.step("none", +1) == "bf16"
    assert c.step("bf16", -1) == "none"
    assert c.step("none", -1) is None           # edge: no step
    assert c.step(WIRE_LADDER[-1], +1) is None
    n = Knob("n", "int", lo=1, hi=8)
    assert n.step(2, +1) == 4 and n.step(2, -1) == 1
    assert n.step(8, +1) is None and n.step(1, -1) is None
    b = Knob("b", "bool")
    assert b.step(False, +1) is True and b.step(True, +1) is None


def test_propose_rejects_out_of_bounds_and_same_value():
    applied = []
    loop = _loop(applied)
    loop.set_current("x", 8)
    _settle(loop)
    assert not loop.propose("x", 8, "same value")
    assert not loop.propose("nope", 4, "unknown knob")
    # Out-of-range values clamp to the bound; a clamp onto the current
    # value is a refusal, and nothing out of [lo, hi] is ever applied.
    loop.set_current("x", 64)
    assert not loop.propose("x", 10_000, "clamps onto current")
    assert applied == []


def test_propose_one_in_flight_and_cooldown():
    applied = []
    loop = ControlLoop({"x": Knob("x", "int", lo=1, hi=64)},
                       lambda n, v: applied.append((n, v)),
                       canary_steps=2, cooldown_s=10.0,
                       reg=MetricsRegistry())
    loop.set_current("x", 8)
    _settle(loop)
    assert loop.propose("x", 16, "first", now=100.0)
    assert loop.in_canary
    assert not loop.propose("x", 32, "second while canarying", now=100.0)
    loop.observe(10.0, now=101.0)
    assert loop.observe(10.0, now=102.0) == "commit"
    # Decision at t=102; the cooldown refuses until t=112.
    assert not loop.propose("x", 32, "inside cooldown", now=105.0)
    assert loop.propose("x", 32, "after cooldown", now=113.0)


def test_apply_exception_vetoes_proposal():
    def veto(name, value):
        raise RuntimeError("actuator says no")

    loop = ControlLoop({"x": Knob("x", "int", lo=1, hi=64)}, veto,
                       canary_steps=2, cooldown_s=0.0,
                       reg=MetricsRegistry())
    loop.set_current("x", 8)
    _settle(loop)
    assert not loop.propose("x", 16, "vetoed")
    assert not loop.in_canary and loop.values["x"] == 8


# ------------------------------------------------- canary accept / reject

def test_canary_commit_on_steady_throughput():
    applied = []
    loop = _loop(applied)
    loop.set_current("x", 8)
    _settle(loop, 10.0)
    assert loop.propose("x", 16, "try wider")
    assert applied == [("x", 16)]
    verdicts = [loop.observe(s) for s in (10.1, 9.9, 10.0)]
    assert verdicts[-1] == "commit" and verdicts[:2] == [None, None]
    assert loop.values["x"] == 16
    p = loop.history[-1]
    assert p["verdict"] == "commit" and p["knob"] == "x"
    # The canary window became the new baseline evidence.
    assert loop.baseline == pytest.approx(10.0, rel=0.05)


def test_canary_rollback_on_regression():
    applied = []
    loop = _loop(applied)
    loop.set_current("x", 8)
    _settle(loop, 10.0)
    assert loop.propose("x", 16, "forced regression")
    # Forced regression: throughput halves under the canaried value.
    verdicts = [loop.observe(s) for s in (5.0, 5.0, 5.0)]
    assert verdicts[-1] == "rollback"
    assert loop.values["x"] == 8
    assert applied == [("x", 16), ("x", 8)]    # the rollback re-applied
    assert loop.history[-1]["verdict"] == "rollback"
    # Baseline unharmed by the rejected canary window.
    assert loop.baseline == pytest.approx(10.0, rel=0.05)


def test_canary_tolerance_band():
    applied = []
    loop = _loop(applied, tolerance=0.10)
    loop.set_current("x", 8)
    _settle(loop, 10.0)
    assert loop.propose("x", 16, "slightly slower is fine")
    # 4% down: inside the 10% tolerance -> commit.
    assert [loop.observe(9.6) for _ in range(3)][-1] == "commit"
    assert loop.values["x"] == 16


def test_decision_counters_and_history():
    reg = MetricsRegistry()
    loop = ControlLoop({"x": Knob("x", "int", lo=1, hi=64)},
                       lambda n, v: None, canary_steps=2, cooldown_s=0.0,
                       reg=reg)
    loop.set_current("x", 8)
    _settle(loop, 10.0)
    loop.propose("x", 16, "a")
    loop.observe(10.0), loop.observe(10.0)          # commit
    loop.propose("x", 32, "b")
    loop.observe(1.0), loop.observe(1.0)            # rollback
    c = reg.snapshot()["counters"]
    assert c['horovod_controller_decisions_total'
             '{action="propose",plane="training"}'] == 2
    assert c['horovod_controller_decisions_total'
             '{action="commit",plane="training"}'] == 1
    assert c['horovod_controller_decisions_total'
             '{action="rollback",plane="training"}'] == 1
    assert [p["verdict"] for p in loop.history] == ["commit", "rollback"]


# ------------------------------------------------- training controller

class _FakeEngine:
    def __init__(self):
        self.tables = []
        self._knobs = {"compression": "none", "topk_ratio": 0.01}

    def set_knobs(self, table):
        self.tables.append(dict(table))
        self._knobs.update(table)
        return len(self.tables)


def test_training_degradation_steps_down_wire_ladder():
    eng = _FakeEngine()
    tc = TrainingController(engine=eng, canary_steps=2, cooldown_s=0.0,
                            reg=MetricsRegistry())
    for _ in range(8):
        tc.on_step(10.0)                  # healthy baseline
    for _ in range(3):
        tc.on_step(2.0)                   # collapse: DCN-delay shape
    # The degradation rule proposed bf16 via the engine knob path...
    assert eng.tables and eng.tables[0] == {"compression": "bf16"}
    assert tc.loop.in_canary
    # ...and the canary commits when sparse restores throughput.
    verdicts = [tc.on_step(9.8) for _ in range(2)]
    assert verdicts[-1] == "commit"
    assert tc.report()["degraded"] is True
    assert tc.loop.values["compression"] == "bf16"


def test_training_recovery_probes_back_to_full_width():
    eng = _FakeEngine()
    tc = TrainingController(engine=eng, canary_steps=2, cooldown_s=0.0,
                            reg=MetricsRegistry())
    for _ in range(8):
        tc.on_step(10.0)
    for _ in range(3):
        tc.on_step(2.0)                   # degrade -> canary bf16
    for _ in range(2):
        tc.on_step(9.8)                   # commit the degraded format
    # Fault clears; after the probe interval the controller canaries a
    # step BACK toward full width and keeps it (throughput holds).
    for _ in range(20):
        tc.on_step(10.0)
    assert {"compression": "none"} in eng.tables
    assert tc.report()["degraded"] is False
    assert tc.loop.values["compression"] == "none"


def test_training_rollback_restores_prior_format():
    eng = _FakeEngine()
    tc = TrainingController(engine=eng, canary_steps=2, cooldown_s=0.0,
                            reg=MetricsRegistry())
    for _ in range(8):
        tc.on_step(10.0)
    for _ in range(3):
        tc.on_step(2.0)                   # propose bf16
    # Sparse does NOT help (the regression was never the wire): rollback.
    verdicts = [tc.on_step(2.0) for _ in range(2)]
    assert verdicts[-1] == "rollback"
    assert tc.loop.values["compression"] == "none"
    assert eng.tables[-1] == {"compression": "none"}
    assert tc.report()["degraded"] is False


def test_training_rejit_knob_requires_callback():
    tc = TrainingController(engine=_FakeEngine(), canary_steps=2,
                            cooldown_s=0.0, reg=MetricsRegistry())
    for _ in range(8):
        tc.on_step(10.0)
    # No rejit callback attached: the apply raises, the propose is vetoed.
    assert not tc.loop.propose("fusion_threshold", 128 << 20, "no rejit")
    rejits = []
    tc2 = TrainingController(engine=_FakeEngine(), rejit=rejits.append,
                             canary_steps=2, cooldown_s=0.0,
                             reg=MetricsRegistry())
    # With a rejit callback attached, the hill-climb rule itself starts
    # canarying tuner probes within a few steady steps — proof the
    # compiled-knob actuator path lands through the callback.
    for _ in range(8):
        tc2.on_step(10.0)
    assert rejits, "hill climb never exercised the rejit callback"
    assert all(set(r) <= {"fusion_threshold", "num_buckets"}
               for r in rejits)


def test_training_mesh_knob_controller_visible(monkeypatch):
    """ISSUE 19: the 3-D mesh cube registers as a rejit-class knob when
    the caller enumerates legal shapes — current value seeded from
    HOROVOD_MESH, propose/canary/commit landing through the rejit
    callback like every other trace-time constant."""
    monkeypatch.setenv("HOROVOD_MESH", "4x2x1")
    rejits = []
    tc = TrainingController(engine=_FakeEngine(), rejit=rejits.append,
                            canary_steps=2, cooldown_s=0.0,
                            reg=MetricsRegistry(),
                            mesh_choices=("4x2x1", "2x2x2"))
    assert tc.loop.values["mesh"] == "4x2x1"
    assert tc.loop.propose("mesh", "2x2x2", "operator reshape")
    assert {"mesh": "2x2x2"} in rejits
    verdicts = [tc.on_step(10.0) for _ in range(3)]
    assert "commit" in verdicts
    assert tc.loop.values["mesh"] == "2x2x2"


def test_training_mesh_knob_validates_choices():
    """Oversubscribed/malformed spec strings are rejected at construction,
    not at the first reshape."""
    with pytest.raises(ValueError):
        TrainingController(engine=_FakeEngine(), reg=MetricsRegistry(),
                           mesh_choices=("16x1x1",))
    # Without mesh_choices the knob never registers (back-compat).
    tc = TrainingController(engine=_FakeEngine(), reg=MetricsRegistry())
    assert "mesh" not in tc.loop.values
    assert not tc.loop.propose("mesh", "2x2x2", "unregistered knob")


# ------------------------------------------------- serving controller

def _serving(cfg=None, reg=None, **kw):
    cfg = cfg or ServeConfig()
    reg = reg or MetricsRegistry()
    return cfg, ServingController(cfg, reg=reg, canary_steps=2,
                                  cooldown_s=0.0, **kw)


def test_serving_rule_table_covers_anomaly_kinds():
    # Every rule row drives a real knob in a real direction, and the four
    # serving anomaly kinds the issue names are all covered.
    assert set(RULES) == {"ttft_slo", "drain_collapse", "shed_spike",
                          "preempt_storm"}
    cfg, sc = _serving()
    for kind, moves in RULES.items():
        assert moves, kind
        for name, direction in moves:
            assert name in sc.loop.knobs, (kind, name)
            assert direction in (-1, +1)


def test_serving_ttft_slo_firing_cuts_wait_then_batch():
    cfg, sc = _serving()
    for _ in range(8):
        sc.tick(now=float(_))             # goodput baseline (zeros)
    sc.on_anomaly("ttft_slo", {"ttft_p99_s": 1.0})
    sc.tick(now=100.0)
    # First in-bounds move of the ttft_slo row: max_wait_ms halves.
    assert sc.loop.in_canary
    assert cfg.max_wait_ms == ServeConfig().max_wait_ms / 2


def test_serving_rule_falls_through_at_knob_edge():
    cfg, sc = _serving()
    cfg.max_batch = 1                     # preempt_storm's only move...
    sc.loop.set_current("max_batch", 1)   # ...is already at the edge
    for _ in range(8):
        sc.tick(now=float(_))
    sc.on_anomaly("preempt_storm", {})
    sc.tick(now=100.0)
    assert not sc.loop.in_canary          # no in-bounds move -> no change
    sc.on_anomaly("shed_spike", {})
    sc.tick(now=101.0)
    assert sc.loop.in_canary              # first move: target_queue down
    assert cfg.target_queue == ServeConfig().target_queue / 2


def test_serving_canary_rollback_restores_config():
    cfg, sc = _serving()
    reg = sc.reg
    req = reg.counter("horovod_serve_requests_total",
                      help="terminal request outcomes", code="200")
    # Healthy goodput baseline: 10 requests per tick.
    total = 0
    for i in range(10):
        total += 10
        req.inc(10)
        sc.tick(now=float(i))
    sc.on_anomaly("drain_collapse", {})
    req.inc(10)
    sc.tick(now=50.0)                     # proposes target_queue down
    assert sc.loop.in_canary
    before = ServeConfig().target_queue
    assert cfg.target_queue == before / 2
    # Goodput collapses under the canaried value -> rollback restores it.
    sc.tick(now=51.0)
    sc.tick(now=52.0)
    assert not sc.loop.in_canary
    assert sc.loop.history[-1]["verdict"] == "rollback"
    assert cfg.target_queue == before


def test_serving_slo_knob_updates_admission():
    class _Adm:
        def __init__(self):
            self.slo = None

        def set_slo_ms(self, v):
            self.slo = v

    adm = _Adm()
    cfg, sc = _serving(admission=adm)
    for _ in range(8):
        sc.tick(now=float(_))
    assert sc.loop.propose("slo_ms", cfg.slo_ms * 2, "test", now=100.0)
    assert adm.slo == ServeConfig().slo_ms * 2
    assert cfg.slo_ms == ServeConfig().slo_ms * 2


def test_maybe_start_serving_controller_gated_on_env(monkeypatch):
    cfg = ServeConfig()
    monkeypatch.delenv("HOROVOD_CONTROLLER", raising=False)
    assert maybe_start_serving_controller(cfg, anomaly=object()) is None
    monkeypatch.setenv("HOROVOD_CONTROLLER", "1")
    # No anomaly stream to subscribe to: still None (nothing to sense).
    assert maybe_start_serving_controller(cfg, anomaly=None) is None

    class _Anom:
        def __init__(self):
            self.subs = []

        def subscribe(self, cb):
            self.subs.append(cb)

        def unsubscribe(self, cb):
            self.subs.remove(cb)

    anom = _Anom()
    sc = maybe_start_serving_controller(cfg, anomaly=anom)
    try:
        assert sc is not None and anom.subs == [sc.on_anomaly]
    finally:
        sc.stop()
    assert anom.subs == []


def test_anomaly_subscription_fans_out():
    from horovod_tpu.metrics.anomaly import AnomalyDetector

    reg = MetricsRegistry()
    det = AnomalyDetector(reg=reg, cooldown_s=0.0)
    seen = []
    det.subscribe(lambda kind, detail: seen.append(kind))
    det.subscribe(lambda kind, detail: 1 / 0)   # broken subscriber
    assert det._fire("shed_spike", 1.0, {"per_tick": 9})
    assert seen == ["shed_spike"]               # others unaffected
