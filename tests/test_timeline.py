"""Timeline content test (reference test/test_timeline.py:41-58: set
HOROVOD_TIMELINE, run one allreduce, assert NEGOTIATE_ALLREDUCE / ALLREDUCE /
CYCLE_START appear in the JSON)."""

import json
import time

import numpy as np

from horovod_tpu.common.config import Config
from horovod_tpu.common.engine import PyEngine
from horovod_tpu.common.topology import Topology
from horovod_tpu.utils.timeline import Timeline


def test_timeline_file_contents(tmp_path):
    path = str(tmp_path / "timeline.json")
    cfg = Config(cycle_time_ms=1.0, timeline=path, timeline_mark_cycles=True)
    eng = PyEngine(Topology(0, 1, 0, 1, 0, 1), cfg)
    try:
        eng.run("allreduce", np.ones(4), "grad/w")
        time.sleep(0.05)
    finally:
        eng.shutdown()
    text = open(path).read()
    assert "NEGOTIATE_ALLREDUCE" in text
    assert '"ALLREDUCE"' in text
    assert "CYCLE_START" in text
    events = json.loads(text)
    assert isinstance(events, list) and events


def test_timeline_valid_json_and_phases(tmp_path):
    path = str(tmp_path / "t.json")
    tl = Timeline(path, mark_cycles=True)
    tl.negotiate_start("tensor.a", "ALLREDUCE")
    tl.negotiate_rank_ready("tensor.a", 0)
    tl.start("tensor.a", "ALLREDUCE")
    tl.activity_start("tensor.a", "MEMCPY_IN_FUSION_BUFFER")
    tl.activity_end("tensor.a")
    tl.end("tensor.a")
    tl.mark_cycle()
    time.sleep(0.05)
    tl.close()
    events = json.loads(open(path).read())
    names = [e["name"] for e in events]
    assert "NEGOTIATE_ALLREDUCE" in names
    assert "MEMCPY_IN_FUSION_BUFFER" in names
    assert "process_name" in names  # tensor pid metadata
