"""Timeline content test (reference test/test_timeline.py:41-58: set
HOROVOD_TIMELINE, run one allreduce, assert NEGOTIATE_ALLREDUCE / ALLREDUCE /
CYCLE_START appear in the JSON)."""

import json
import time

import numpy as np

from horovod_tpu.common.config import Config
from horovod_tpu.common.engine import PyEngine
from horovod_tpu.common.topology import Topology
from horovod_tpu.utils.timeline import Timeline


def test_timeline_file_contents(tmp_path):
    path = str(tmp_path / "timeline.json")
    cfg = Config(cycle_time_ms=1.0, timeline=path, timeline_mark_cycles=True)
    eng = PyEngine(Topology(0, 1, 0, 1, 0, 1), cfg)
    try:
        eng.run("allreduce", np.ones(4), "grad/w")
        time.sleep(0.05)
    finally:
        eng.shutdown()
    text = open(path).read()
    assert "NEGOTIATE_ALLREDUCE" in text
    assert '"ALLREDUCE"' in text
    assert "CYCLE_START" in text
    events = json.loads(text)
    assert isinstance(events, list) and events


def test_timeline_valid_json_and_phases(tmp_path):
    path = str(tmp_path / "t.json")
    tl = Timeline(path, mark_cycles=True)
    tl.negotiate_start("tensor.a", "ALLREDUCE")
    tl.negotiate_rank_ready("tensor.a", 0)
    tl.start("tensor.a", "ALLREDUCE")
    tl.activity_start("tensor.a", "MEMCPY_IN_FUSION_BUFFER")
    tl.activity_end("tensor.a")
    tl.end("tensor.a")
    tl.mark_cycle()
    time.sleep(0.05)
    tl.close()
    events = json.loads(open(path).read())
    names = [e["name"] for e in events]
    assert "NEGOTIATE_ALLREDUCE" in names
    assert "MEMCPY_IN_FUSION_BUFFER" in names
    assert "process_name" in names  # tensor pid metadata


def test_trace_two_pane_profile(tmp_path):
    """hvd.timeline.trace must drop BOTH artifacts in one directory: the
    XLA device profile and the host engine timeline (VERDICT r2 missing #5
    — docs/timeline.md's two-pane story, executable)."""
    import glob

    import jax
    import jax.numpy as jnp
    import numpy as np

    import horovod_tpu as hvd

    hvd.init()
    try:
        log_dir = str(tmp_path / "profile")
        step = jax.jit(lambda x: (x @ x.T).sum())
        with hvd.timeline.trace(log_dir):
            out = step(jnp.ones((64, 64)))
            jax.block_until_ready(out)
            # host-side eager op inside the same window
            hvd.allreduce(np.ones(4), name="traced.op")
        # device pane: jax.profiler drops .trace/.pb artifacts under plugins/
        assert glob.glob(log_dir + "/**/*.pb", recursive=True) or \
            glob.glob(log_dir + "/**/*.trace*", recursive=True), \
            f"no device profile under {log_dir}"
        # host pane: the engine timeline recorded the eager collective
        host = tmp_path / "profile" / "host_timeline.json"
        assert host.exists()
        content = host.read_text()
        assert "traced.op" in content
        # closed catapult stream in STRICT json (ISSUE 2 satellite: the
        # native writer's historical trailing comma before `]` is gone —
        # ci.sh validates the shape the same way)
        import json as _json

        events = _json.loads(content)
        assert isinstance(events, list) and events
    finally:
        hvd.shutdown()


def test_timeline_unwritable_path_counts_drops(tmp_path):
    """A bad HOROVOD_TIMELINE path must not kill the writer thread or the
    engine: events degrade to counted drops in the metrics registry
    (ISSUE 2 satellite; docs/timeline.md 'Dropped events')."""
    from horovod_tpu import metrics

    before = metrics.registry().counter(
        "horovod_timeline_dropped_total").value
    tl = Timeline(str(tmp_path / "no" / "such" / "dir" / "t.json"))
    for i in range(5):
        tl.start(f"tensor.{i}", "ALLREDUCE")
        tl.end(f"tensor.{i}")
    time.sleep(0.3)  # writer thread drains the queue into the drop counter
    tl.close()
    dropped = metrics.registry().counter(
        "horovod_timeline_dropped_total").value - before
    assert dropped >= 10, dropped      # 5 starts + 5 ends + pid metadata
    assert tl.dropped >= 10


def test_native_timeline_dropped_metric_exported(tmp_path):
    """The C++ writer's drop counter crosses the c_api (hvd_metric
    'timeline_dropped') and lands in the registry as a native gauge."""
    import numpy as np

    from horovod_tpu.cc.native_engine import NativeEngine
    from horovod_tpu import metrics

    eng = NativeEngine(Topology(0, 1, 0, 1, 0, 1),
                       Config(cycle_time_ms=1.0,
                              timeline=str(tmp_path / "native_tl.json")))
    try:
        eng.run("allreduce", np.ones(4), "tl.op")
        m = eng.metrics()
        assert m["timeline_dropped"] == 0          # healthy queue: no shed
        snap = metrics.snapshot()
        assert snap["gauges"]["horovod_native_timeline_dropped"] == 0
    finally:
        eng.shutdown()
    # the finished file parses strictly (no trailing comma before `]`)
    events = json.loads(open(tmp_path / "native_tl.json").read())
    assert isinstance(events, list) and events


def test_trace_leaves_preconfigured_timeline_alone(tmp_path):
    """With HOROVOD_TIMELINE already configured, trace() must not hijack or
    close the engine's timeline."""
    import os

    import numpy as np

    import horovod_tpu as hvd

    env_tl = str(tmp_path / "env_timeline.json")
    os.environ["HOROVOD_TIMELINE"] = env_tl
    try:
        hvd.init()
        with hvd.timeline.trace(str(tmp_path / "prof")):
            hvd.allreduce(np.ones(2), name="op.a")
        hvd.allreduce(np.ones(2), name="op.b")  # after: still recording
        hvd.shutdown()
        content = open(env_tl).read()
        assert "op.a" in content and "op.b" in content
        assert not (tmp_path / "prof" / "host_timeline.json").exists()
    finally:
        os.environ.pop("HOROVOD_TIMELINE", None)
