"""Telemetry subsystem tests (ISSUE 2): registry semantics + thread safety,
histogram percentiles, Prometheus/JSON exposition (including the HTTP
server and the checked-in snapshot schema), pod aggregation, the stall
watchdog (unit + an injected two-process stall that must name the missing
rank within HOROVOD_STALL_CHECK_TIME), and the compiled-path bucket overlap
gauges' consistency with the fusion planner (test_overlap.py's plan).
"""

from __future__ import annotations

import json
import os
import sys
import textwrap
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np
import pytest

from launch_util import launch_world  # noqa: E402

from horovod_tpu.metrics import (  # noqa: E402
    MetricsRegistry,
    StallInfo,
    StallWatchdog,
    merge_snapshots,
    start_metrics_server,
    validate_snapshot,
)
from horovod_tpu.metrics.registry import DEFAULT_BYTE_BUCKETS  # noqa: E402


# ------------------------------------------------------------------ registry


def test_counter_gauge_basics():
    reg = MetricsRegistry()
    c = reg.counter("c_total", help="h", op="allreduce")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)
    # get-or-create: same (name, labels) -> same object; new labels -> new
    assert reg.counter("c_total", op="allreduce") is c
    assert reg.counter("c_total", op="allgather") is not c
    g = reg.gauge("g")
    g.set(7)
    g.inc(-2)
    assert g.value == 5.0


def test_registry_thread_safety():
    """1000 increments from each of 8 threads across shared counter,
    gauge, and histogram must all land (the lock-cheap claim)."""
    reg = MetricsRegistry()
    c = reg.counter("t_total")
    h = reg.histogram("t_seconds")

    def worker(i):
        for k in range(1000):
            c.inc()
            h.observe(0.001 * ((i + k) % 10 + 1))
            # concurrent get-or-create of the same series must never race
            reg.counter("t_total").inc(0)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 8000
    assert h.count == 8000


def test_histogram_percentiles_and_bounds():
    reg = MetricsRegistry()
    h = reg.histogram("lat", buckets=[0.001 * (2 ** i) for i in range(12)])
    vals = [0.001 * i for i in range(1, 101)]      # 1ms..100ms uniform
    for v in vals:
        h.observe(v)
    assert h.count == 100
    assert abs(h.sum - sum(vals)) < 1e-9
    # estimates stay inside the observed range and are ordered
    p50, p90, p99 = h.percentile(50), h.percentile(90), h.percentile(99)
    assert min(vals) <= p50 <= p90 <= p99 <= max(vals)
    # and roughly where a uniform distribution puts them (bucketed estimate)
    assert 0.02 <= p50 <= 0.08
    assert p90 >= 0.05
    d = h.to_dict()
    assert d["count"] == 100 and d["buckets"][-1][0] == "+Inf"


def test_prometheus_rendering():
    reg = MetricsRegistry()
    reg.counter("hvd_ops_total", help="ops done", op="allreduce").inc(4)
    reg.gauge("hvd_up").set(1)
    reg.histogram("hvd_lat_seconds", buckets=[0.1, 1.0]).observe(0.5)
    text = reg.render_prometheus()
    assert '# TYPE hvd_ops_total counter' in text
    assert '# HELP hvd_ops_total ops done' in text
    assert 'hvd_ops_total{op="allreduce"} 4.0' in text
    assert '# TYPE hvd_lat_seconds histogram' in text
    assert 'hvd_lat_seconds_bucket{le="+Inf"} 1' in text
    assert 'hvd_lat_seconds_count 1' in text
    assert text.endswith("\n")


def test_snapshot_schema_and_pod_merge():
    reg = MetricsRegistry()
    reg.counter("n_total", op="allreduce").inc(2)
    reg.gauge("rate").set(10.0)
    reg.histogram("lat").observe(0.25)
    reg.set_info("stall_report", {"text": "x"})
    snap = reg.snapshot()
    assert validate_snapshot(snap) == []
    other = MetricsRegistry()
    other.counter("n_total", op="allreduce").inc(3)
    other.gauge("rate").set(30.0)
    other.histogram("lat").observe(0.75)
    pod = merge_snapshots([snap, other.snapshot(), None])
    assert validate_snapshot(pod) == []
    assert pod["ranks"] == 3 and pod["ranks_reporting"] == 2
    assert pod["counters"]['n_total{op="allreduce"}'] == 5
    assert pod["gauges"]["rate"] == {"min": 10.0, "max": 30.0, "mean": 20.0}
    assert pod["histograms"]["lat"]["count"] == 2
    assert pod["info"]["0"]["stall_report"]["text"] == "x"


def test_schema_validator_catches_violations():
    from horovod_tpu.metrics.schema import validate

    schema = {"type": "object", "required": ["a"],
              "properties": {"a": {"type": "integer", "minimum": 0}}}
    assert validate({"a": 1}, schema) == []
    assert validate({"a": -1}, schema)
    assert validate({"a": "x"}, schema)
    assert validate({}, schema)
    assert validate({"a": True}, schema)  # bool must not satisfy integer


def test_collector_runs_before_snapshot():
    reg = MetricsRegistry()
    calls = []

    def collect(r):
        calls.append(1)
        r.gauge("from_collector").set(42)

    reg.register_collector(collect)
    snap = reg.snapshot()
    assert snap["gauges"]["from_collector"] == 42 and calls
    reg.unregister_collector(collect)
    reg.snapshot()
    assert len(calls) == 1


# ---------------------------------------------------------------- exposition


def test_http_exposition_endpoints():
    reg = MetricsRegistry()
    reg.counter("served_total").inc(3)
    srv = start_metrics_server(0, reg)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        text = urllib.request.urlopen(f"{base}/metrics", timeout=5).read().decode()
        assert "served_total 3.0" in text
        snap = json.loads(urllib.request.urlopen(
            f"{base}/metrics.json", timeout=5).read())
        assert validate_snapshot(snap) == []
        assert snap["counters"]["served_total"] == 3
        ok = urllib.request.urlopen(f"{base}/healthz", timeout=5).read()
        assert ok == b"ok\n"
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"{base}/nope", timeout=5)
    finally:
        srv.stop()


# ------------------------------------------------------------------ watchdog


def test_watchdog_warns_and_publishes_report():
    reg = MetricsRegistry()
    infos = [StallInfo(name="grad.7", op="allreduce", age_s=0.0,
                       missing_ranks=[1, 3])]
    wd = StallWatchdog(check_time_s=0.1, rank=0, reg=reg,
                       poll_interval_s=0.02)
    wd.add_source(lambda: infos)
    try:
        infos[0].age_s = 0.5  # past the threshold
        deadline = time.monotonic() + 2.0
        while wd.report() is None and time.monotonic() < deadline:
            time.sleep(0.02)
        rep = wd.report()
        assert rep is not None, "watchdog never reported"
        assert rep["stalled"][0]["name"] == "grad.7"
        assert rep["stalled"][0]["missing_ranks"] == [1, 3]
        assert "grad.7" in rep["text"] and "missing ranks: 1, 3" in rep["text"]
        assert reg.counter("horovod_stall_warnings_total").value >= 1
    finally:
        wd.stop()


def test_watchdog_escalates_past_shutdown_time():
    reg = MetricsRegistry()
    aborted = []
    wd = StallWatchdog(check_time_s=0.05, shutdown_time_s=0.2, rank=0,
                       on_abort=aborted.append, reg=reg,
                       poll_interval_s=0.02)
    wd.add_source(lambda: [StallInfo("t", "allreduce", age_s=1.0)])
    try:
        deadline = time.monotonic() + 2.0
        while not aborted and time.monotonic() < deadline:
            time.sleep(0.02)
        assert aborted and aborted[0].name == "t"
        assert len(aborted) == 1, "abort must fire once per tensor"
        time.sleep(0.1)
        assert len(aborted) == 1
        assert reg.counter("horovod_stall_aborts_total").value == 1
    finally:
        wd.stop()


# ---------------------------------------------- injected stall, two processes


@pytest.mark.engine
def test_injected_stall_watchdog_names_missing_rank():
    """Rank 1 delays submitting tensor `lonely` past HOROVOD_STALL_CHECK_TIME
    (0.5s): rank 0's watchdog must publish a structured report naming BOTH
    the tensor and missing rank 1 within a few check windows, the warning
    must hit stderr, and the collective must still complete once rank 1
    joins (acceptance criterion: report within HOROVOD_STALL_CHECK_TIME)."""
    script = textwrap.dedent("""
        import json, os, sys, time
        sys.path.insert(0, os.environ["HVD_REPO"])
        import numpy as np
        import horovod_tpu as hvd
        from horovod_tpu.common import basics
        from horovod_tpu import metrics

        hvd.init()
        eng = basics.engine()
        rank = hvd.rank()
        t0 = time.monotonic()
        h = None
        if rank == 0:
            h = eng.enqueue("allreduce", np.ones(4), "lonely")
            deadline = time.monotonic() + 4.0
            rep = None
            while time.monotonic() < deadline:
                rep = metrics.registry().get_info("stall_report")
                if rep:
                    break
                time.sleep(0.05)
            report_age = time.monotonic() - t0
        else:
            time.sleep(2.0)
            h = eng.enqueue("allreduce", np.ones(4), "lonely")
        out = eng.synchronize(h, timeout=30)
        ok = bool(np.allclose(out, 1.0))
        result = {"ok": ok, "rank": rank}
        if rank == 0:
            result["report"] = rep
            result["report_age_s"] = report_age
            snap = metrics.snapshot()
            result["warnings"] = snap["counters"].get(
                "horovod_stall_warnings_total", 0)
        eng.shutdown()
        print(json.dumps(result))
    """)
    results = launch_world(
        2, script, timeout=120,
        extra_env={"HOROVOD_ENGINE": "python",
                   "JAX_PLATFORMS": "cpu",
                   "HOROVOD_STALL_CHECK_TIME": "0.5"})
    r0 = next(r for r in results if r["out"]["rank"] == 0)
    assert r0["out"]["ok"] is True
    rep = r0["out"]["report"]
    assert rep, f"no stall report on rank 0; stderr:\n{r0['stderr'][-2000:]}"
    stalled = {s["name"]: s for s in rep["stalled"]}
    assert "lonely" in stalled
    assert stalled["lonely"]["missing_ranks"] == [1]
    assert stalled["lonely"]["op"] == "allreduce"
    # reported within ~3 check windows of the 0.5s HOROVOD_STALL_CHECK_TIME
    assert r0["out"]["report_age_s"] < 2.0, r0["out"]["report_age_s"]
    assert r0["out"]["warnings"] >= 1
    assert "lonely" in r0["stderr"] and "missing ranks: 1" in r0["stderr"]


@pytest.mark.engine
def test_stall_shutdown_time_fails_collective():
    """Past HOROVOD_STALL_SHUTDOWN_TIME the watchdog fails the stalled
    collective with an error naming the missing rank instead of hanging."""
    script = textwrap.dedent("""
        import json, os, sys, time
        sys.path.insert(0, os.environ["HVD_REPO"])
        import numpy as np
        import horovod_tpu as hvd
        from horovod_tpu.common import basics
        from horovod_tpu.common.engine import HorovodInternalError

        hvd.init()
        eng = basics.engine()
        rank = hvd.rank()
        err = ""
        if rank == 0:
            h = eng.enqueue("allreduce", np.ones(4), "doomed")
            try:
                eng.synchronize(h, timeout=20)
            except HorovodInternalError as e:
                err = str(e)
        else:
            time.sleep(5.0)   # never submits `doomed` within the threshold
        eng.shutdown()
        print(json.dumps({"rank": rank, "err": err}))
    """)
    results = launch_world(
        2, script, timeout=120,
        extra_env={"HOROVOD_ENGINE": "python",
                   "JAX_PLATFORMS": "cpu",
                   "HOROVOD_STALL_CHECK_TIME": "0.4",
                   "HOROVOD_STALL_SHUTDOWN_TIME": "1.2"})
    r0 = next(r for r in results if r["out"]["rank"] == 0)
    assert "stalled" in r0["out"]["err"], r0["out"]["err"]
    assert "doomed" in r0["out"]["err"]
    assert "missing ranks: 1" in r0["out"]["err"]


# ----------------------------------------------- engine feed points (local)


def test_engine_feeds_registry(hvd):
    """Whichever engine implementation is active (native preferred, Python
    fallback), the per-op count/bytes/latency series must populate."""
    from horovod_tpu import metrics
    from horovod_tpu.common import basics

    eng = basics.engine()
    before = metrics.snapshot()["counters"].get(
        'horovod_collectives_total{op="allreduce"}', 0)
    arr = np.arange(16, dtype=np.float32)
    for i in range(3):
        eng.run("allreduce", arr, f"m.{i}")
    snap = metrics.snapshot()
    assert snap["counters"][
        'horovod_collectives_total{op="allreduce"}'] == before + 3
    assert snap["counters"][
        'horovod_collective_bytes_total{op="allreduce"}'] >= 3 * arr.nbytes
    hist = snap["histograms"]['horovod_collective_seconds{op="allreduce"}']
    assert hist["count"] >= 3 and hist["p50"] > 0


# ------------------------------------------ compiled-path overlap (mesh8)


def test_bucket_overlap_metrics_consistent_with_plan(mesh8):
    """The recorded plan gauges must match fusion.build_plan exactly, and
    the planned overlap-efficiency bound must be monotone non-decreasing in
    K (more buckets -> smaller unhideable tail) — the metrics counterpart
    of test_overlap.py's planning invariants."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from horovod_tpu import metrics
    from horovod_tpu.compat import shard_map
    from horovod_tpu.parallel import fusion

    grads = {
        "w1": jnp.ones((8, 33, 7)),
        "w2": jnp.ones((8, 129)),
        "w3": jnp.ones((8, 5, 5)),
        "w4": jnp.ones((8, 257)),
    }
    reg = metrics.registry()
    hist_before = reg.histogram(
        "horovod_fusion_bucket_bytes",
        buckets=DEFAULT_BYTE_BUCKETS).count
    planned = []
    recorded_buckets = 0
    for k in (1, 2, 4, 8):
        out = jax.jit(shard_map(
            lambda g, nb=k: fusion.fused_allreduce(g, num_buckets=nb),
            mesh=mesh8, in_specs=P("hvd"), out_specs=P(),
            check_vma=False))(grads)
        jax.block_until_ready(out)
        plan = fusion.build_plan(
            jax.tree_util.tree_map(lambda t: t[0], grads), num_buckets=k)
        # ^ per-shard tree: inside shard_map leaves carry the per-rank shape
        rec = metrics.last_plan()
        assert rec is not None
        assert reg.gauge("horovod_fusion_buckets").value == plan.num_buckets
        assert len(rec) == plan.num_buckets
        plan_bytes = [sum(d.size * d.dtype.itemsize for d in b)
                      for b in plan.buckets]
        assert [n for _, n in rec] == plan_bytes
        assert reg.gauge("horovod_fusion_planned_bytes").value == sum(plan_bytes)
        recorded_buckets += plan.num_buckets
        planned.append(
            (k, reg.gauge("horovod_overlap_efficiency_planned").value))
    assert planned[0][1] == 0.0          # K=1: nothing can be hidden
    effs = [e for _, e in planned]
    assert effs == sorted(effs), effs    # monotone in K
    assert effs[-1] > 0.5                # 8 buckets hide most of the bytes
    snap = metrics.snapshot()
    assert snap["histograms"]["horovod_fusion_bucket_bytes"]["count"] \
        >= hist_before + recorded_buckets


def test_overlap_trace_parser_interval_math():
    """parse_overlap on a synthetic device trace: one collective fully
    hidden under compute, one fully exposed -> efficiency 0.5."""
    from horovod_tpu.metrics.overlap import parse_overlap

    def ev(pid, name, ts, dur, cat):
        return {"ph": "X", "pid": pid, "ts": ts, "dur": dur, "name": name,
                "args": {"device_duration_ps": int(dur * 1e6),
                         "hlo_category": cat}}

    events = [
        {"ph": "M", "pid": 1, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        ev(1, "fusion.1", 0, 100, "convolution"),
        ev(1, "all-reduce.1", 20, 50, "all reduce"),    # inside compute
        ev(1, "all-reduce.2", 200, 50, "all reduce"),   # after compute ends
    ]
    rep = parse_overlap(events)
    assert rep["ok"] and rep["collectives"] == 2
    assert rep["collective_ms"] == pytest.approx(0.1)
    assert rep["hidden_ms"] == pytest.approx(0.05)
    assert rep["overlap_efficiency"] == pytest.approx(0.5)
    # host-only traces (CPU backend) degrade explicitly, not silently
    assert parse_overlap([{"ph": "X", "pid": 9, "ts": 0, "dur": 5,
                           "name": "python_frame", "args": {}}])["ok"] is False


# ------------------------------------------------------- runner aggregation


def test_driver_service_pod_metrics():
    from horovod_tpu.runner.service import DriverService

    svc = DriverService.__new__(DriverService)  # no sockets needed
    svc.num_proc = 2
    svc._lock = threading.Lock()
    svc._cv = threading.Condition(svc._lock)
    svc._results = {}
    svc._metrics = {}
    assert svc.pod_metrics() is None
    reg = MetricsRegistry()
    reg.counter("steps_total").inc(5)
    svc.handle({"kind": "metrics", "rank": 0, "snapshot": reg.snapshot()},
               ("127.0.0.1", 1))
    reg2 = MetricsRegistry()
    reg2.counter("steps_total").inc(7)
    svc.handle({"kind": "result", "rank": 1,
                "value": {"ok": True, "value": 1,
                          "metrics": reg2.snapshot()}}, ("127.0.0.1", 2))
    pod = svc.pod_metrics()
    assert pod["ranks_reporting"] == 2
    assert pod["counters"]["steps_total"] == 12
    assert validate_snapshot(pod) == []


def test_metrics_callback_single_process(hvd, tmp_path):
    from horovod_tpu.callbacks import MetricsCallback
    from horovod_tpu import metrics

    path = tmp_path / "pod.json"
    cb = MetricsCallback(snapshot_path=str(path))
    cb.on_train_begin()
    cb.on_epoch_begin(0)
    time.sleep(0.01)
    cb.on_epoch_end(0, {"steps": 50})
    assert metrics.registry().gauge("horovod_steps_per_sec").value > 0
    cb.on_train_end()
    assert cb.pod_snapshot is not None
    assert cb.pod_snapshot["ranks_reporting"] == 1
    on_disk = json.loads(path.read_text())
    assert validate_snapshot(on_disk) == []
    assert on_disk["counters"].get("horovod_epochs_total", 0) >= 1


def test_http_exposition_bind_retry_on_busy_port():
    """EADDRINUSE slides the exporter up a small port window instead of
    crashing hvd.init (ISSUE 8 satellite: an elastic respawn lands where
    the previous generation's exporter still holds port + local_rank)."""
    import socket as _socket

    reg = MetricsRegistry()
    blocker = _socket.socket()
    blocker.bind(("127.0.0.1", 0))
    blocker.listen(1)
    busy = blocker.getsockname()[1]
    try:
        srv = start_metrics_server(busy, reg)
        try:
            assert srv.port != busy
            assert busy < srv.port < busy + 16
            ok = urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz", timeout=5).read()
            assert ok == b"ok\n"
        finally:
            srv.stop()
    finally:
        blocker.close()


def test_http_exposition_window_exhaustion_raises(monkeypatch):
    import socket as _socket

    monkeypatch.setenv("HOROVOD_METRICS_PORT_WINDOW", "1")
    reg = MetricsRegistry()
    blocker = _socket.socket()
    blocker.bind(("127.0.0.1", 0))
    blocker.listen(1)
    try:
        with pytest.raises(OSError):
            start_metrics_server(blocker.getsockname()[1], reg)
    finally:
        blocker.close()
