"""Torch binding tests — mirrors the reference test_torch.py matrix: op
correctness, DistributedOptimizer hooks, broadcast_parameters /
broadcast_optimizer_state, compression, backward_passes_per_step
(reference test/test_torch.py, SURVEY.md §4)."""

from __future__ import annotations

import os
import sys
import textwrap

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np
import pytest

from launch_util import launch_world

torch = pytest.importorskip("torch")


@pytest.fixture()
def hvd_torch():
    import horovod_tpu.torch as hvd

    hvd.init()
    yield hvd
    hvd.shutdown()


def test_single_process_ops(hvd_torch):
    hvd = hvd_torch
    t = torch.arange(6, dtype=torch.float32).reshape(2, 3)
    out = hvd.allreduce(t)
    assert torch.equal(out, t)
    out = hvd.allgather(t)
    assert torch.equal(out, t)
    out = hvd.broadcast(t, root_rank=0)
    assert torch.equal(out, t)
    # in-place
    t2 = t.clone()
    hvd.allreduce_(t2)
    assert torch.equal(t2, t)


def test_allreduce_grad(hvd_torch):
    hvd = hvd_torch
    t = torch.ones(4, requires_grad=True)
    out = hvd.allreduce(t, average=True)
    out.sum().backward()
    # grad of averaged allreduce in a size-1 world is 1
    assert torch.allclose(t.grad, torch.ones(4))


def test_fp16_compression_roundtrip(hvd_torch):
    hvd = hvd_torch
    t = torch.randn(16)
    out = hvd.allreduce(t, compression=hvd.Compression.fp16)
    assert out.dtype == torch.float32
    assert torch.allclose(out, t, atol=1e-2)


def test_distributed_optimizer_single(hvd_torch):
    hvd = hvd_torch
    model = torch.nn.Linear(4, 2)
    opt = torch.optim.SGD(model.parameters(), lr=0.1)
    opt = hvd.DistributedOptimizer(opt, named_parameters=model.named_parameters())
    assert isinstance(opt, torch.optim.SGD)
    x = torch.randn(8, 4)
    loss = model(x).pow(2).mean()
    loss.backward()
    before = model.weight.detach().clone()
    opt.step()
    assert not torch.equal(before, model.weight)


def test_duplicate_names_rejected(hvd_torch):
    hvd = hvd_torch
    model = torch.nn.Linear(2, 2)
    with pytest.raises(ValueError, match="unique"):
        hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=[("same", model.weight), ("same", model.bias)],
        )


def test_broadcast_optimizer_state_single(hvd_torch):
    hvd = hvd_torch
    model = torch.nn.Linear(4, 2)
    opt = torch.optim.Adam(model.parameters(), lr=3e-4)
    loss = model(torch.randn(2, 4)).sum()
    loss.backward()
    opt.step()
    hvd.broadcast_optimizer_state(opt, root_rank=0)
    assert opt.param_groups[0]["lr"] == pytest.approx(3e-4)


# --------------------------------------------------------- multi-process world

RANK_SCRIPT = textwrap.dedent("""
    import json, os, sys
    import numpy as np
    import torch
    sys.path.insert(0, os.environ["HVD_REPO"])
    import horovod_tpu.torch as hvd

    hvd.init()
    r, n = hvd.rank(), hvd.size()
    torch.manual_seed(1234 + r)  # deliberately different init per rank
    out = {}

    model = torch.nn.Linear(4, 2)
    # broadcast_parameters makes all ranks identical to root
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    out["weights_hash"] = float(model.weight.detach().double().sum() +
                                model.bias.detach().double().sum())

    opt = torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9)
    opt = hvd.DistributedOptimizer(opt, named_parameters=model.named_parameters())
    hvd.broadcast_optimizer_state(opt, root_rank=0)

    # Each rank trains on rank-dependent data; with hook-driven averaging the
    # models must stay in lockstep.
    for step in range(3):
        torch.manual_seed(100 + step * n + r)
        x = torch.randn(8, 4)
        loss = model(x).pow(2).mean()
        loss.backward()
        opt.step()
        opt.zero_grad()
    out["final"] = model.weight.detach().numpy().round(6).tolist()

    # plain op check
    t = torch.full((3,), float(r))
    out["allreduce"] = hvd.allreduce(t).tolist()
    # beyond-reference op set: alltoall sends row i to rank i; reducescatter
    # returns this rank's summed shard
    a2a = torch.arange(float(n * 2)).reshape(n, 2) + 10 * r
    out["alltoall"] = hvd.alltoall(a2a).tolist()
    rs = torch.arange(float(n * 2)).reshape(n, 2) * (r + 1)
    out["reducescatter"] = hvd.reducescatter(rs).tolist()
    hvd.shutdown()
    print(json.dumps(out))
""")


@pytest.mark.slow
def test_torch_two_rank_lockstep():
    world = 2
    outs = [r["out"] for r in launch_world(
        world, RANK_SCRIPT,
        per_rank_env={r: {"HOROVOD_LOCAL_RANK": str(r),
                          "HOROVOD_LOCAL_SIZE": str(world)}
                      for r in range(world)})]
    # identical after broadcast
    assert outs[0]["weights_hash"] == pytest.approx(outs[1]["weights_hash"])
    # identical after 3 hook-averaged steps on different data
    np.testing.assert_allclose(outs[0]["final"], outs[1]["final"], atol=1e-6)
    # allreduce of ranks {0,1} averages to 0.5
    np.testing.assert_allclose(outs[0]["allreduce"], [0.5, 0.5, 0.5])
    # alltoall: rank i receives row i of every rank's [[0,1],[2,3]]+10r
    np.testing.assert_allclose(outs[0]["alltoall"], [[0, 1], [10, 11]])
    np.testing.assert_allclose(outs[1]["alltoall"], [[2, 3], [12, 13]])
    # reducescatter: sum of arange(4).reshape(2,2)*(r+1) is arange*3; each
    # rank keeps its dim-0 shard
    np.testing.assert_allclose(outs[0]["reducescatter"], [[0, 3]])
    np.testing.assert_allclose(outs[1]["reducescatter"], [[6, 9]])


SPARSE_SCRIPT = textwrap.dedent("""
    import json, os, sys
    import numpy as np
    sys.path.insert(0, os.environ["HVD_REPO"])
    import torch
    import horovod_tpu.torch as hvd

    hvd.init()
    rank, world = hvd.rank(), hvd.size()
    torch.manual_seed(0)  # identical init on every rank

    # Two models: sparse-gradient embedding through the DistributedOptimizer
    # hook, and a dense oracle trained on the SAME global batch.
    emb = torch.nn.Embedding(10, 4, sparse=True)
    oracle = torch.nn.Embedding(10, 4, sparse=False)
    with torch.no_grad():
        oracle.weight.copy_(emb.weight)

    opt = hvd.DistributedOptimizer(torch.optim.SGD(emb.parameters(), lr=0.5),
                                   named_parameters=[("emb.weight", emb.weight)])
    oopt = torch.optim.SGD(oracle.parameters(), lr=0.5)

    # per-rank disjoint-and-overlapping rows: rank 0 sees [1,2], rank 1 [2,7]
    per_rank = {0: [1, 2], 1: [2, 7]}
    idx = torch.tensor(per_rank[rank])
    for step in range(2):
        opt.zero_grad()
        emb(idx).sum().backward()
        assert emb.weight.grad.is_sparse
        opt.step()

        oopt.zero_grad()
        glob = torch.tensor([i for r in range(world) for i in per_rank[r]])
        # oracle: mean over ranks of per-rank sums == hook's averaged grad
        (oracle(glob).sum() / world).backward()
        oopt.step()

    same = bool(torch.allclose(emb.weight, oracle.weight, atol=1e-6))

    # Asymmetric step: rank 1 never touches the embedding, so its
    # synchronize() zeros-fallback must contribute an EMPTY sparse pair
    # (a dense allreduce would mismatch rank 0's allgathers and stall).
    opt.zero_grad(set_to_none=True)
    if rank == 0:
        emb(torch.tensor([5])).sum().backward()
    opt.step()
    oopt.zero_grad()
    (oracle(torch.tensor([5])).sum() / world).backward()
    oopt.step()
    same_asym = bool(torch.allclose(emb.weight, oracle.weight, atol=1e-6))

    # also the raw op: values/indices survive the ring and scatter-add
    g = torch.sparse_coo_tensor([[rank]], [[1.0, 2.0, 3.0, 4.0]], (3, 4))
    red = hvd.sparse_allreduce(g, average=False).to_dense()
    hvd.shutdown()
    print(json.dumps({"same": same, "same_asym": same_asym,
                      "red": red.numpy().tolist()}))
""")


@pytest.mark.slow  # re-tiered r5: multi-process spawn cost; core coverage stays fast
def test_sparse_embedding_grad_matches_dense_oracle():
    """VERDICT r3 item 5: a torch.nn.Embedding(sparse=True) gradient must
    round-trip the eager ring as (values, indices) — no densification — and
    train identically to a dense oracle on the global batch."""
    outs = [r["out"] for r in launch_world(2, SPARSE_SCRIPT)]
    assert all(o["same"] for o in outs)
    assert all(o["same_asym"] for o in outs), (
        "zeros-fallback for an unused sparse param must stay collective")
    # raw sparse allreduce: rank r contributed row r -> both rows present
    expect = [[1, 2, 3, 4], [1, 2, 3, 4], [0, 0, 0, 0]]
    for o in outs:
        np.testing.assert_allclose(o["red"], expect)


def test_sparse_allreduce_single_process(hvd_torch):
    hvd = hvd_torch
    g = torch.sparse_coo_tensor([[0, 2, 0]], [[1.0], [2.0], [3.0]], (3, 1))
    out = hvd.sparse_allreduce(g, average=False)
    assert out.is_coalesced()  # local scatter-add merged the duplicate row 0
    np.testing.assert_allclose(out.to_dense().numpy(), [[4.0], [0.0], [2.0]])
