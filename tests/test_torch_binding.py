"""Torch binding tests — mirrors the reference test_torch.py matrix: op
correctness, DistributedOptimizer hooks, broadcast_parameters /
broadcast_optimizer_state, compression, backward_passes_per_step
(reference test/test_torch.py, SURVEY.md §4)."""

from __future__ import annotations

import os
import sys
import textwrap

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np
import pytest

from launch_util import launch_world

torch = pytest.importorskip("torch")


@pytest.fixture()
def hvd_torch():
    import horovod_tpu.torch as hvd

    hvd.init()
    yield hvd
    hvd.shutdown()


def test_single_process_ops(hvd_torch):
    hvd = hvd_torch
    t = torch.arange(6, dtype=torch.float32).reshape(2, 3)
    out = hvd.allreduce(t)
    assert torch.equal(out, t)
    out = hvd.allgather(t)
    assert torch.equal(out, t)
    out = hvd.broadcast(t, root_rank=0)
    assert torch.equal(out, t)
    # in-place
    t2 = t.clone()
    hvd.allreduce_(t2)
    assert torch.equal(t2, t)


def test_allreduce_grad(hvd_torch):
    hvd = hvd_torch
    t = torch.ones(4, requires_grad=True)
    out = hvd.allreduce(t, average=True)
    out.sum().backward()
    # grad of averaged allreduce in a size-1 world is 1
    assert torch.allclose(t.grad, torch.ones(4))


def test_fp16_compression_roundtrip(hvd_torch):
    hvd = hvd_torch
    t = torch.randn(16)
    out = hvd.allreduce(t, compression=hvd.Compression.fp16)
    assert out.dtype == torch.float32
    assert torch.allclose(out, t, atol=1e-2)


def test_distributed_optimizer_single(hvd_torch):
    hvd = hvd_torch
    model = torch.nn.Linear(4, 2)
    opt = torch.optim.SGD(model.parameters(), lr=0.1)
    opt = hvd.DistributedOptimizer(opt, named_parameters=model.named_parameters())
    assert isinstance(opt, torch.optim.SGD)
    x = torch.randn(8, 4)
    loss = model(x).pow(2).mean()
    loss.backward()
    before = model.weight.detach().clone()
    opt.step()
    assert not torch.equal(before, model.weight)


def test_duplicate_names_rejected(hvd_torch):
    hvd = hvd_torch
    model = torch.nn.Linear(2, 2)
    with pytest.raises(ValueError, match="unique"):
        hvd.DistributedOptimizer(
            torch.optim.SGD(model.parameters(), lr=0.1),
            named_parameters=[("same", model.weight), ("same", model.bias)],
        )


def test_broadcast_optimizer_state_single(hvd_torch):
    hvd = hvd_torch
    model = torch.nn.Linear(4, 2)
    opt = torch.optim.Adam(model.parameters(), lr=3e-4)
    loss = model(torch.randn(2, 4)).sum()
    loss.backward()
    opt.step()
    hvd.broadcast_optimizer_state(opt, root_rank=0)
    assert opt.param_groups[0]["lr"] == pytest.approx(3e-4)


# --------------------------------------------------------- multi-process world

RANK_SCRIPT = textwrap.dedent("""
    import json, os, sys
    import numpy as np
    import torch
    sys.path.insert(0, os.environ["HVD_REPO"])
    import horovod_tpu.torch as hvd

    hvd.init()
    r, n = hvd.rank(), hvd.size()
    torch.manual_seed(1234 + r)  # deliberately different init per rank
    out = {}

    model = torch.nn.Linear(4, 2)
    # broadcast_parameters makes all ranks identical to root
    hvd.broadcast_parameters(model.state_dict(), root_rank=0)
    out["weights_hash"] = float(model.weight.detach().double().sum() +
                                model.bias.detach().double().sum())

    opt = torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9)
    opt = hvd.DistributedOptimizer(opt, named_parameters=model.named_parameters())
    hvd.broadcast_optimizer_state(opt, root_rank=0)

    # Each rank trains on rank-dependent data; with hook-driven averaging the
    # models must stay in lockstep.
    for step in range(3):
        torch.manual_seed(100 + step * n + r)
        x = torch.randn(8, 4)
        loss = model(x).pow(2).mean()
        loss.backward()
        opt.step()
        opt.zero_grad()
    out["final"] = model.weight.detach().numpy().round(6).tolist()

    # plain op check
    t = torch.full((3,), float(r))
    out["allreduce"] = hvd.allreduce(t).tolist()
    # beyond-reference op set: alltoall sends row i to rank i; reducescatter
    # returns this rank's summed shard
    a2a = torch.arange(float(n * 2)).reshape(n, 2) + 10 * r
    out["alltoall"] = hvd.alltoall(a2a).tolist()
    rs = torch.arange(float(n * 2)).reshape(n, 2) * (r + 1)
    out["reducescatter"] = hvd.reducescatter(rs).tolist()
    hvd.shutdown()
    print(json.dumps(out))
""")


@pytest.mark.slow
def test_torch_two_rank_lockstep():
    world = 2
    outs = [r["out"] for r in launch_world(
        world, RANK_SCRIPT,
        per_rank_env={r: {"HOROVOD_LOCAL_RANK": str(r),
                          "HOROVOD_LOCAL_SIZE": str(world)}
                      for r in range(world)})]
    # identical after broadcast
    assert outs[0]["weights_hash"] == pytest.approx(outs[1]["weights_hash"])
    # identical after 3 hook-averaged steps on different data
    np.testing.assert_allclose(outs[0]["final"], outs[1]["final"], atol=1e-6)
    # allreduce of ranks {0,1} averages to 0.5
    np.testing.assert_allclose(outs[0]["allreduce"], [0.5, 0.5, 0.5])
    # alltoall: rank i receives row i of every rank's [[0,1],[2,3]]+10r
    np.testing.assert_allclose(outs[0]["alltoall"], [[0, 1], [10, 11]])
    np.testing.assert_allclose(outs[1]["alltoall"], [[2, 3], [12, 13]])
    # reducescatter: sum of arange(4).reshape(2,2)*(r+1) is arange*3; each
    # rank keeps its dim-0 shard
    np.testing.assert_allclose(outs[0]["reducescatter"], [[0, 3]])
    np.testing.assert_allclose(outs[1]["reducescatter"], [[6, 9]])
