"""Transport-resilience ladder tests (ISSUE 8).

Covers, bottom rung to top:

- the shared policy/backoff module (``common/resilience.py``): env parsing,
  decorrelated-jitter bounds, retry-within-budget and budget-exhaustion on
  ``recv_exact`` with the matching counters;
- frame-level defenses on the authenticated Channel: corrupt-HMAC and
  replayed-sequence frames are REJECTED (never unpickled), counted in
  ``horovod_frames_rejected_total``, and surface as a link fault the
  demotion rung absorbs — not a crash;
- the env-triggered network chaos hooks (``elastic/fault.py``): action /
  scope / rank / AFTER / COUNT selectors, and the injected faults' wire
  behaviour (drop consumes a sequence number, delay stalls the frame);
- coordinator escalation-ladder protocol units: plane_fault demotes the
  world and opens seq-tagged redo negotiations, stale retained answers are
  rejected, finishers are pre-claimed so redo results retire, dead ranks
  fail pending collectives with the reset-worthy ``[reset]`` error;
- (slow) a 4-process end-to-end: an injected link reset mid-run demotes
  ring -> star with BITWISE-identical results, zero HorovodInternalErrors,
  and re-promotes to the ring after the cooldown.
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import time

import numpy as np
import pytest

from horovod_tpu.common import resilience
from horovod_tpu.elastic import fault


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    for var in list(os.environ):
        if var.startswith(("HOROVOD_NETWORK_", "HOROVOD_FAULT_NET")):
            monkeypatch.delenv(var, raising=False)
    resilience._reset_for_tests()
    fault.reset_net_fault_state()
    yield
    resilience._reset_for_tests()
    fault.reset_net_fault_state()


# ------------------------------------------------------------ policy/backoff

def test_policy_env_parsing(monkeypatch):
    assert resilience.from_env() == resilience.Policy()
    monkeypatch.setenv("HOROVOD_NETWORK_TIMEOUT", "2.5")
    monkeypatch.setenv("HOROVOD_NETWORK_RETRIES", "5")
    monkeypatch.setenv("HOROVOD_NETWORK_BACKOFF_MAX_MS", "750")
    pol = resilience.from_env()
    assert (pol.timeout_s, pol.retries, pol.backoff_max_ms) == (2.5, 5, 750.0)
    assert pol.patience_s == pytest.approx(15.0)
    # Hostile values clamp instead of breaking every socket op.
    monkeypatch.setenv("HOROVOD_NETWORK_TIMEOUT", "-3")
    monkeypatch.setenv("HOROVOD_NETWORK_RETRIES", "-2")
    pol = resilience.from_env()
    assert pol.timeout_s == 0.05 and pol.retries == 0


def test_default_policy_cached_until_refresh(monkeypatch):
    p0 = resilience.default_policy()
    monkeypatch.setenv("HOROVOD_NETWORK_TIMEOUT", "9")
    assert resilience.default_policy() is p0  # cached
    assert resilience.default_policy(refresh=True).timeout_s == 9.0


def test_backoff_decorrelated_jitter_bounds():
    class Rng:
        def uniform(self, a, b):
            return b  # worst case: always the top of the window

    b = resilience.Backoff(base_s=0.05, cap_s=0.4, rng=Rng())
    delays = [b.next() for _ in range(8)]
    assert all(0.05 <= d <= 0.4 for d in delays)
    assert delays[-1] == 0.4  # growth saturates at the cap
    b.reset()
    assert b.next() == pytest.approx(0.15)  # 3 * base after reset


def test_backoff_default_cap_from_env(monkeypatch):
    monkeypatch.setenv("HOROVOD_NETWORK_BACKOFF_MAX_MS", "123")
    resilience._reset_for_tests()
    assert resilience.Backoff().cap_s == pytest.approx(0.123)


def _pair(timeout=0.1):
    a, b = socket.socketpair()
    b.settimeout(timeout)
    return a, b


def test_recv_exact_retries_within_budget():
    a, b = _pair(timeout=0.1)
    pol = resilience.Policy(timeout_s=0.1, retries=5)
    r0 = resilience.retries_counter().value
    t0 = resilience.timeouts_counter().value
    try:
        t = threading.Timer(0.25, lambda: a.sendall(b"x" * 64))
        t.start()
        got = resilience.recv_exact(b, 64, policy=pol)
        assert bytes(got) == b"x" * 64
        # The ~0.25 s stall cost >= 2 idle deadlines, absorbed in place.
        assert resilience.retries_counter().value - r0 >= 2
        assert resilience.timeouts_counter().value == t0
    finally:
        a.close()
        b.close()


def test_recv_exact_exhausts_budget_and_counts_timeout():
    a, b = _pair(timeout=0.05)
    pol = resilience.Policy(timeout_s=0.05, retries=2)
    r0 = resilience.retries_counter().value
    t0 = resilience.timeouts_counter().value
    try:
        with pytest.raises(TimeoutError, match="HOROVOD_NETWORK_RETRIES"):
            resilience.recv_exact(b, 8, policy=pol)
        assert resilience.timeouts_counter().value - t0 == 1
        assert resilience.retries_counter().value - r0 == 2
    finally:
        a.close()
        b.close()


def test_recv_exact_progress_resets_budget():
    # Two stalls of ~2 deadlines each: a FIXED budget of 3 would fail, but
    # progress resets it — the deadline bounds idle time, not frame size.
    a, b = _pair(timeout=0.05)
    pol = resilience.Policy(timeout_s=0.05, retries=3)

    def feed():
        time.sleep(0.12)
        a.sendall(b"x" * 32)
        time.sleep(0.12)
        a.sendall(b"y" * 32)

    r0 = resilience.retries_counter().value
    t0 = resilience.timeouts_counter().value
    try:
        th = threading.Thread(target=feed)
        th.start()
        got = resilience.recv_exact(b, 64, policy=pol)
        th.join()
        assert bytes(got) == b"x" * 32 + b"y" * 32
        assert resilience.retries_counter().value - r0 >= 4
        assert resilience.timeouts_counter().value == t0
    finally:
        a.close()
        b.close()


def test_recv_exact_peer_close_raises_connection_error():
    a, b = _pair()
    a.close()
    try:
        with pytest.raises(ConnectionError):
            resilience.recv_exact(b, 4, policy=resilience.Policy())
    finally:
        b.close()


# ----------------------------------------------------------- frame defenses

def _channel_pair(scope_client="ctl", scope_server="ctl"):
    from horovod_tpu.runner.network import Channel, make_secret

    key = make_secret()
    s_srv, s_cli = socket.socketpair()
    s_srv.settimeout(5)
    s_cli.settimeout(5)
    out = {}
    th = threading.Thread(
        target=lambda: out.update(
            srv=Channel(s_srv, key, server=True, scope=scope_server)))
    th.start()
    cli = Channel(s_cli, key, server=False, scope=scope_client)
    th.join()
    return cli, out["srv"]


def test_channel_replayed_sequence_rejected_and_counted():
    cli, srv = _channel_pair()
    cli.send({"n": 1})
    assert srv.recv() == {"n": 1}
    # Replay frame seq 0 verbatim: re-MAC the same payload under the OLD
    # sequence number and push the raw bytes (a captured-frame replay).
    import pickle

    payload = pickle.dumps({"n": 1}, protocol=pickle.HIGHEST_PROTOCOL)
    mac = cli._mac(cli._send_dir, 0, payload)
    cli.sock.sendall(mac + struct.pack("!Q", len(payload)) + payload)
    before = resilience.frames_rejected_counter().value
    with pytest.raises(PermissionError, match="replayed"):
        srv.recv()
    assert resilience.frames_rejected_counter().value == before + 1


def test_channel_corrupt_mac_rejected_and_counted(monkeypatch):
    monkeypatch.setenv("HOROVOD_FAULT_NET", "corrupt")
    monkeypatch.setenv("HOROVOD_FAULT_NET_SCOPE", "*")
    fault.reset_net_fault_state()
    cli, srv = _channel_pair()
    before = resilience.frames_rejected_counter().value
    cli.send({"secret": 42})
    with pytest.raises(PermissionError, match="HMAC"):
        srv.recv()
    assert resilience.frames_rejected_counter().value == before + 1


def test_channel_drop_consumes_sequence_number(monkeypatch):
    # A swallowed frame must surface as a DETECTED fault on the next frame,
    # never as a silent substitution of the following message.
    monkeypatch.setenv("HOROVOD_FAULT_NET", "drop")
    monkeypatch.setenv("HOROVOD_FAULT_NET_SCOPE", "*")
    fault.reset_net_fault_state()
    cli, srv = _channel_pair()
    cli.send({"dropped": True})   # injected: swallowed, seq consumed
    cli.send({"next": True})      # arrives bearing seq 1; receiver expects 0
    with pytest.raises(PermissionError, match="HMAC"):
        srv.recv()


def test_channel_delay_injection_stalls_frame(monkeypatch):
    monkeypatch.setenv("HOROVOD_FAULT_NET", "delay")
    monkeypatch.setenv("HOROVOD_FAULT_NET_SCOPE", "*")
    monkeypatch.setenv("HOROVOD_FAULT_NET_DELAY_MS", "200")
    fault.reset_net_fault_state()
    cli, srv = _channel_pair()
    t0 = time.monotonic()
    cli.send({"late": 1})
    assert srv.recv() == {"late": 1}
    assert time.monotonic() - t0 >= 0.2


def test_channel_reset_injection_raises(monkeypatch):
    monkeypatch.setenv("HOROVOD_FAULT_NET", "reset")
    monkeypatch.setenv("HOROVOD_FAULT_NET_SCOPE", "*")
    fault.reset_net_fault_state()
    cli, srv = _channel_pair()
    with pytest.raises(ConnectionResetError):
        cli.send({"x": 1})


# ------------------------------------------------------ chaos hook selectors

def test_net_fault_selectors(monkeypatch):
    monkeypatch.setenv("HOROVOD_FAULT_NET", "delay")
    monkeypatch.setenv("HOROVOD_RANK", "1")
    # scope filter: default targets only ring channels
    assert fault.net_fault("ctl") is None
    assert fault.net_fault("ring") == "delay"
    # rank filter
    fault.reset_net_fault_state()
    monkeypatch.setenv("HOROVOD_FAULT_NET_RANK", "0")
    assert not fault.net_fault_armed()
    assert fault.net_fault("ring") is None
    monkeypatch.setenv("HOROVOD_FAULT_NET_RANK", "1")
    assert fault.net_fault_armed()


def test_net_fault_after_and_count(monkeypatch):
    monkeypatch.setenv("HOROVOD_FAULT_NET", "corrupt")
    monkeypatch.setenv("HOROVOD_FAULT_NET_AFTER", "2")
    monkeypatch.setenv("HOROVOD_FAULT_NET_COUNT", "2")
    fault.reset_net_fault_state()
    hits = [fault.net_fault("ring") for _ in range(6)]
    # frames 1-2 skipped (AFTER), frames 3-4 fire (COUNT=2), rest pass
    assert hits == [None, None, "corrupt", "corrupt", None, None]


def test_net_fault_unknown_action_inert(monkeypatch):
    monkeypatch.setenv("HOROVOD_FAULT_NET", "explode")
    assert not fault.net_fault_armed()
    assert fault.net_fault("ring") is None


# ------------------------------------------- coordinator ladder (protocol)

@pytest.fixture()
def coord(monkeypatch):
    from horovod_tpu.common.engine import _Coordinator

    monkeypatch.setenv("HOROVOD_PLANE_REPROMOTE_S", "30")
    c = _Coordinator(4, "127.0.0.1", 0, key=b"k" * 16)
    yield c
    c.stop()


def _seed_directive(coord, name, claimed):
    """Issue a ring directive for ``name`` the way _execute would."""
    seq = coord._ring_seq
    coord._ring_seq += 1
    coord._directive_seq[name] = seq
    coord._results[name] = (None, {"__ring__": True, "seq": seq,
                                   "average": True})
    coord._claimed[name] = set(claimed)
    return seq


def test_plane_fault_demotes_world_and_opens_redo(coord):
    coord.ring_active = True
    seq = _seed_directive(coord, "t", claimed={0, 1})   # 2 and 3 not yet
    coord._pending["u"] = {0: ({"op": "allreduce"}, None),
                           2: ({"op": "allreduce"}, np.ones(2))}
    coord._handle_plane_fault(1, ["t"], "boom")
    assert coord.ring_active is False
    assert coord._demote_epoch == 1
    assert coord._repromote_at is not None
    # the undelivered directive was recalled into a seq-tagged redo
    assert "t" not in coord._results
    assert coord._redo_wanted == {"t": seq}
    # reporter 1 must replay; 0 finished; 2/3 never claimed -> will replay
    assert coord._redo_claim["t"] == {0}
    # metadata-only (ring) contributions dropped, bytes kept
    assert list(coord._pending["u"]) == [2]


def test_redo_stale_seq_rejected_fresh_accepted(coord):
    coord.ring_active = True
    seq = _seed_directive(coord, "t", claimed={0, 1, 2, 3})
    del coord._results["t"]     # fully delivered before the fault
    del coord._claimed["t"]
    coord._handle_plane_fault(2, ["t"], "boom")
    assert coord._redo_wanted == {"t": seq}
    # a STALE retained copy (previous step, seq-1) must not answer
    out = coord._handle_exchange(3, [], {},
                                 redo_results={"t": (seq - 1, np.ones(2))})
    assert "t" not in out["results"] and "t" not in coord._results
    assert [list(x) for x in out["redo"]] == [["t", seq]]
    # the matching copy answers and is pre-claimed for the finishers
    coord._handle_exchange(0, [], {},
                           redo_results={"t": (seq, np.full(2, 7.0))})
    assert "t" in coord._results
    # world minus reporter(2): {0,1,3} pre-claimed; 2 claims on its re-poll
    assert coord._claimed["t"] == {0, 1, 3}
    out = coord._handle_exchange(2, [{"name": "t", "op": "allreduce",
                                      "shape": (2,), "dtype": "float64",
                                      "root": 0, "average": True}],
                                 {"t": np.ones(2)})
    err, val = out["results"]["t"]
    assert err is None and np.array_equal(val, np.full(2, 7.0))
    # all four claimed -> the result retired (no lingering stale bits for
    # the NEXT same-name collective)
    assert "t" not in coord._results


def test_peer_lost_fails_pending_with_reset_error(coord):
    from horovod_tpu.common.engine import _FATAL

    coord._pending["g"] = {0: ({"op": "allreduce"}, np.ones(2))}
    coord._peer_lost(2)
    err, _ = coord._results["g"]
    assert err.startswith(_FATAL) and "rank 2" in err
    assert not coord._pending
    # idempotent
    coord._peer_lost(2)
    # new names keep failing while the rank is dead (rung 3 backstop)
    out = coord._handle_exchange(0, [{"name": "h", "op": "allreduce",
                                      "shape": (2,), "dtype": "float64",
                                      "root": 0, "average": True}],
                                 {"h": np.ones(2)})
    err, _ = out["results"]["h"]
    assert err.startswith(_FATAL)


def test_fatal_error_surfaces_as_internal_error():
    from horovod_tpu.common.engine import (_FATAL, HorovodInternalError,
                                           TensorShapeMismatchError)

    # the client maps [reset]-tagged errors to the reset-worthy class
    err = _FATAL + "lost control connection to rank 1"
    exc = HorovodInternalError(err) if err.startswith(_FATAL) \
        else TensorShapeMismatchError(err)
    assert type(exc) is HorovodInternalError


def test_exchange_response_carries_plane_epochs(coord):
    out = coord._handle_exchange(0, [], {})
    assert "plane" not in out     # steady state: no extra bytes
    coord.ring_active = True
    coord._handle_plane_fault(1, [], "boom")
    out = coord._handle_exchange(0, [], {})
    assert out["plane"] == {"demote": 1, "reprobe": 0}


def test_reprobe_fires_after_cooldown(coord):
    coord.ring_active = True
    coord._handle_plane_fault(1, [], "boom")
    coord._ring_endpoints[0] = {"enabled": True}
    coord._ring_votes[0] = False
    with coord._cv:
        coord._maybe_schedule_reprobe()
        assert coord._reprobe_epoch == 0    # cooldown not expired
        coord._repromote_at = time.monotonic() - 1
        coord._maybe_schedule_reprobe()
        assert coord._reprobe_epoch == 1
        # establishment barriers cleared for the re-entry
        assert not coord._ring_endpoints and not coord._ring_votes
        assert coord._repromote_at is None
    out = coord._handle_exchange(0, [], {})
    assert out["plane"] == {"demote": 1, "reprobe": 1}


def test_reprobe_held_while_a_rank_is_dead(coord):
    coord.ring_active = True
    coord._handle_plane_fault(1, [], "boom")
    coord._peer_lost(3)
    with coord._cv:
        coord._repromote_at = time.monotonic() - 1
        coord._maybe_schedule_reprobe()
        assert coord._reprobe_epoch == 0    # dead rank: stay on the star


# ------------------------------------- knob-change epochs (ISSUE 16 units)

def test_knob_change_bumps_epochs_and_recalls_pending(coord):
    coord.ring_active = True
    seq = _seed_directive(coord, "t", claimed={0, 1})
    coord._pending["u"] = {0: ({"op": "allreduce"}, np.ones(2)),
                           2: ({"op": "allreduce"}, np.ones(2))}
    out = coord._handle_knob_change(0, {"compression": "fp16"})
    assert out == {"ok": 1, "epoch": 1}
    # the safe switch rides the plane-demotion epoch
    assert coord.ring_active is False and coord._demote_epoch == 1
    assert coord._repromote_at is not None
    # undelivered ring directive: seq-tagged redo (bitwise replay); recalled
    # star pending: fresh-only redo (sentinel -1 — a stale retained copy of
    # a previous same-name execution must never answer it)
    assert coord._redo_wanted == {"t": seq, "u": -1}
    assert coord._redo_claim["u"] == set()
    assert "u" not in coord._pending
    # stale retained copies cannot close the sentinel redo
    coord._handle_exchange(3, [], {}, redo_results={"u": (seq, np.ones(2))})
    assert "u" not in coord._results


def test_knob_change_without_ring_still_bumps_demote_epoch(coord):
    assert coord._handle_knob_change(1, {"topk_ratio": 0.05})["epoch"] == 1
    # ranks must run _redo_inflight (re-ship bytes for sent entries) even
    # though there was no eager plane to demote
    assert coord._demote_epoch == 1 and coord._repromote_at is None
    # cumulative table: a second change merges, epoch advances
    coord._handle_knob_change(1, {"compression": "bf16"})
    assert coord._knob_epoch == 2
    assert coord._knob_table == {"topk_ratio": 0.05, "compression": "bf16"}


def test_exchange_response_carries_knob_table(coord):
    out = coord._handle_exchange(0, [], {})
    assert "knob" not in out and "reformat" not in out   # steady state
    coord._handle_knob_change(2, {"compression": "fp16"})
    out = coord._handle_exchange(0, [], {})
    assert out["knob"] == {"epoch": 1, "table": {"compression": "fp16"}}


def test_stale_knob_epoch_contribution_bounced_then_ingested(coord):
    coord._handle_knob_change(0, {"compression": "fp16"})
    req = {"name": "g", "op": "allreduce", "shape": (2,),
           "dtype": "float32", "root": 0, "average": True}
    # formatted under epoch 0 (no ke): bounced, never ingested
    out = coord._handle_exchange(1, [dict(req)], {"g": np.ones(2)})
    assert out["reformat"] == ["g"] and "g" not in out["results"]
    assert "g" not in coord._pending
    # re-formatted under the committed epoch: ingested normally
    wire = dict(req, ke=1, wire="float16")
    for r in range(4):
        out = coord._handle_exchange(
            r, [dict(wire)], {"g": np.ones(2, dtype=np.float16)})
    err, val = out["results"]["g"]
    assert err is None


def test_ring_redo_exempt_from_knob_epoch_bounce(coord):
    coord.ring_active = True
    seq = _seed_directive(coord, "t", claimed=set())
    coord._handle_knob_change(0, {"compression": "fp16"})
    assert coord._redo_wanted["t"] == seq
    # the recalled directive's replay re-ships OLD-format bytes (no ke):
    # exempt from the bounce — this is the bitwise replay path
    out = coord._handle_exchange(
        0, [{"name": "t", "op": "allreduce", "shape": (2,),
             "dtype": "float32", "root": 0, "average": True}],
        {"t": np.ones(2, dtype=np.float32)})
    assert "reformat" not in out
    assert 0 in coord._pending["t"]


def test_knob_change_flushes_response_cache(coord):
    req = {"name": "c", "op": "allreduce", "shape": (2,),
           "dtype": "float32", "root": 0, "average": True}
    for r in range(4):
        out = coord._handle_exchange(r, [dict(req)], {"c": np.ones(2)})
    assert out["assign"], "negotiation was not cached"
    bit = out["assign"][0][0]
    coord._handle_knob_change(0, {"compression": "fp16"})
    out = coord._handle_exchange(0, [], {})
    assert bit in out["evict"], "stale wire-signature bit must be evicted"


# ----------------------------------------------------------- e2e (4-proc)

WORKER = r"""
import hashlib, json, os, sys, time
sys.path.insert(0, os.environ["HVD_REPO"])
import numpy as np
from horovod_tpu.common.config import Config
from horovod_tpu.common.engine import PyEngine, HorovodInternalError
from horovod_tpu.common.topology import Topology
from horovod_tpu import metrics as hvd_metrics

rank = int(os.environ["HOROVOD_RANK"]); world = int(os.environ["HOROVOD_SIZE"])
steps = int(os.environ["T_STEPS"]); settle = int(os.environ["T_SETTLE"])
eng = PyEngine(Topology(rank, world, 0, 1, rank, world),
               Config(cycle_time_ms=1.0, stall_check_disable=True))
errors = 0
digest = hashlib.sha256()
try:
    for i in range(steps):
        for t in range(2):
            try:
                out = eng.run("allreduce",
                              np.arange(128, dtype=np.float32) * (rank + 1)
                              + i + t, f"g.{t}")
                digest.update(out.tobytes())
            except HorovodInternalError:
                errors += 1
        time.sleep(0.01)
    for j in range(settle):
        eng.run("allreduce", np.ones(4, dtype=np.float32), f"s.{j}")
        time.sleep(0.05)
    snap = hvd_metrics.registry().snapshot()
    print(json.dumps({
        "hash": digest.hexdigest(), "errors": errors,
        "demotions": snap["counters"].get("horovod_plane_demotions_total", 0),
        "repromotions": snap["counters"].get(
            "horovod_plane_repromotions_total", 0),
        "plane": snap["gauges"].get("horovod_plane_current", -1),
    }), flush=True)
finally:
    eng.shutdown()
"""


KNOB_WORKER = r"""
import hashlib, json, os, sys, time
sys.path.insert(0, os.environ["HVD_REPO"])
import numpy as np
from horovod_tpu.common.config import Config
from horovod_tpu.common.engine import PyEngine, HorovodInternalError
from horovod_tpu.common.topology import Topology
from horovod_tpu import metrics as hvd_metrics

rank = int(os.environ["HOROVOD_RANK"]); world = int(os.environ["HOROVOD_SIZE"])
steps = int(os.environ["T_STEPS"]); flip = int(os.environ["T_FLIP"])
settle = int(os.environ["T_SETTLE"])
eng = PyEngine(Topology(rank, world, 0, 1, rank, world),
               Config(cycle_time_ms=1.0, stall_check_disable=True))
errors = 0
digest = hashlib.sha256()
try:
    for i in range(steps):
        if i == flip and rank == 0:
            # Live wire-dtype retune mid-run, with collectives in flight on
            # the other ranks: the coordinator's knob epoch must land it
            # atomically on the whole world.
            eng.set_knobs({"compression": "fp16"})
        for t in range(2):
            try:
                out = eng.run("allreduce",
                              np.arange(128, dtype=np.float32) * (rank + 1)
                              + i + t, f"g.{t}")
                digest.update(out.tobytes())
            except HorovodInternalError:
                errors += 1
        time.sleep(0.01)
    for j in range(settle):
        eng.run("allreduce", np.ones(4, dtype=np.float32), f"s.{j}")
        time.sleep(0.05)
    snap = hvd_metrics.registry().snapshot()
    print(json.dumps({
        "hash": digest.hexdigest(), "errors": errors,
        "epoch": eng.knob_epoch(),
        "knob_changes": snap["counters"].get(
            "horovod_knob_changes_total", 0),
        "fp16_saved": snap["counters"].get(
            'horovod_wire_bytes_saved_total{method="fp16"}', 0),
        "demotions": snap["counters"].get("horovod_plane_demotions_total", 0),
        "repromotions": snap["counters"].get(
            "horovod_plane_repromotions_total", 0),
        "plane": snap["gauges"].get("horovod_plane_current", -1),
    }), flush=True)
finally:
    eng.shutdown()
"""


@pytest.mark.slow
def test_knob_flip_mid_run_stays_bitwise_consistent():
    """ISSUE 16: flipping the wire dtype mid-run through the coordinator
    knob epoch keeps all four ranks bitwise identical (interrupted
    collectives replay under their old format; later steps quantize under
    the new one), with zero internal errors, the demote/re-promote safe
    switch exercised, and fp16 savings flowing after the flip."""
    from launch_util import launch_world

    ranks = launch_world(4, KNOB_WORKER, extra_env={
        "HOROVOD_ENGINE": "python", "HOROVOD_RING_DATA_PLANE": "1",
        "HOROVOD_NETWORK_TIMEOUT": "0.4", "HOROVOD_NETWORK_RETRIES": "3",
        "T_STEPS": "14", "T_FLIP": "7", "T_SETTLE": "40",
        "HOROVOD_PLANE_REPROMOTE_S": "30",
        "HOROVOD_KNOB_REPROMOTE_S": "0.5"})
    for r in ranks:
        o = r["out"]
        assert o["errors"] == 0, "knob switch escalated to an internal error"
        assert o["epoch"] == 1, "knob epoch did not reach every rank"
        assert o["knob_changes"] >= 1
        assert o["fp16_saved"] > 0, "new wire format never used post-flip"
        assert o["demotions"] >= 1, "safe switch did not demote the plane"
        assert o["repromotions"] >= 1, "knob cooldown never re-promoted"
        assert o["plane"] == 1, "world did not return to the ring plane"
    assert len({r["out"]["hash"] for r in ranks}) == 1, \
        "ranks diverged bitwise across the live knob switch"


@pytest.mark.slow
def test_injected_reset_demotes_then_repromotes_bitwise():
    from launch_util import launch_world

    base = {"HOROVOD_ENGINE": "python", "HOROVOD_RING_DATA_PLANE": "1",
            "HOROVOD_NETWORK_TIMEOUT": "0.4", "HOROVOD_NETWORK_RETRIES": "3",
            "T_STEPS": "14", "T_SETTLE": "0",
            "HOROVOD_PLANE_REPROMOTE_S": "0"}
    clean = launch_world(4, WORKER, extra_env=base)
    faulty = launch_world(4, WORKER, extra_env={
        **base, "T_SETTLE": "50", "HOROVOD_PLANE_REPROMOTE_S": "1.0",
        "HOROVOD_FAULT_NET": "reset", "HOROVOD_FAULT_NET_RANK": "1",
        "HOROVOD_FAULT_NET_SCOPE": "ring",
        # land the reset mid-run: after ~7 steps x 2 tensors x 6 frames
        "HOROVOD_FAULT_NET_AFTER": "84", "HOROVOD_FAULT_NET_COUNT": "1"})
    clean_hash = {r["out"]["hash"] for r in clean}
    assert len(clean_hash) == 1
    for r in faulty:
        o = r["out"]
        assert o["errors"] == 0, "ladder escalated past demotion"
        assert o["demotions"] >= 1, "reset did not demote the plane"
        assert o["repromotions"] >= 1, "cooldown probe never re-promoted"
        assert o["plane"] == 1, "world did not return to the ring plane"
    assert {r["out"]["hash"] for r in faulty} == clean_hash, \
        "faulted world diverged bitwise from the clean world"


# -- bind_with_retry (ISSUE 20 satellite) -------------------------------------


def _bind(p):
    s = socket.create_server(("127.0.0.1", p))
    return s


def test_bind_with_retry_free_port_binds_at_offset_zero():
    from launch_util import free_port

    port = free_port()
    s, offset = resilience.bind_with_retry(_bind, port)
    try:
        assert offset == 0 and s.getsockname()[1] == port
    finally:
        s.close()


def test_bind_with_retry_slides_through_the_window():
    from launch_util import free_port

    port = free_port()
    holder = socket.create_server(("127.0.0.1", port))
    try:
        with pytest.raises(OSError):          # window=1: no slide allowed
            resilience.bind_with_retry(_bind, port, window=1)
        s, offset = resilience.bind_with_retry(_bind, port, window=8)
        try:
            assert offset >= 1
            assert s.getsockname()[1] == port + offset
        finally:
            s.close()
    finally:
        holder.close()


def test_bind_with_retry_deadline_outwaits_a_lingering_holder():
    from launch_util import free_port

    port = free_port()
    holder = socket.create_server(("127.0.0.1", port))
    threading.Timer(0.4, holder.close).start()
    t0 = time.monotonic()
    s, offset = resilience.bind_with_retry(_bind, port, deadline_s=10.0,
                                           sleep_s=0.05)
    try:
        assert offset == 0 and time.monotonic() - t0 >= 0.3
    finally:
        s.close()


def test_bind_with_retry_propagates_non_eaddrinuse_errors():
    def boom(p):
        raise OSError(13, "Permission denied")

    with pytest.raises(OSError, match="Permission denied"):
        resilience.bind_with_retry(boom, 1)
