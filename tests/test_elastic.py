"""Elastic training (ISSUE 3): state commit/restore semantics, rendezvous
generations, blacklist/discovery, fault injection, and kill-a-worker-
mid-train end-to-end through the elastic launcher.

Upstream Horovod tests its elastic mode by killing workers mid-run and
asserting the job completes from the last commit (test_elastic_torch.py);
same shape here, on the TPU-side control plane. Multi-process resets that
need the stall-watchdog escalation run in the slow tier; the protocol and
state tests plus the fast reset (dead coordinator fails survivors
immediately) are tier-1."""

from __future__ import annotations

import json
import os
import stat
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from horovod_tpu.elastic import (
    Blacklist,
    ElasticState,
    ScriptDiscovery,
    StaticDiscovery,
    parse_discovery_output,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --------------------------------------------------------------- ElasticState

def test_state_commit_restore_bitwise():
    """restore() returns bitwise-identical committed values; uncommitted
    mutations are rolled back (the reset-path contract)."""
    w = np.arange(12, dtype=np.float32).reshape(3, 4) / 7.0
    m = np.arange(12, dtype=np.float64).reshape(3, 4) * 1e-3
    state = ElasticState(params={"w": w.copy()},
                         opt_state={"mom": m.copy(), "count": 3},
                         epoch=1, step=10)
    state.params["w"] *= 1.5
    state.opt_state["mom"] += 0.25
    state.step = 11
    state.commit(check_host_updates=False)
    committed_w = state.params["w"].copy()
    committed_m = state.opt_state["mom"].copy()
    # uncommitted progress
    state.params["w"] += 99.0
    state.opt_state["mom"] *= 0.0
    state.opt_state["count"] = 77
    state.step = 12
    state.restore()
    assert state.params["w"].dtype == np.float32
    assert state.params["w"].tobytes() == committed_w.tobytes()
    assert state.opt_state["mom"].tobytes() == committed_m.tobytes()
    assert state.opt_state["count"] == 3
    assert state.step == 11 and state.epoch == 1


def test_state_construction_is_first_commit():
    state = ElasticState(x=np.ones(3), step=0)
    state.x = state.x + 5
    state.step = 4
    state.restore()
    assert np.array_equal(state.x, np.ones(3))
    assert state.step == 0


def test_state_commit_does_not_alias_live_values():
    """The committed snapshot must be a copy: mutating live arrays after
    commit() must not corrupt the rollback point."""
    w = np.zeros(4, dtype=np.float32)
    state = ElasticState(w=w)
    state.commit(check_host_updates=False)
    state.w[:] = 42.0   # in-place mutation of the live array
    state.restore()
    assert np.array_equal(state.w, np.zeros(4))


def test_state_unknown_attribute_raises():
    state = ElasticState(a=1)
    with pytest.raises(AttributeError, match="no value"):
        _ = state.missing


def test_state_checkpoint_backed_commit(tmp_path):
    """checkpoint_dir makes commit() write a rank-0 checkpoint; a fresh
    state object cold-starts from it (the full-job-restart story)."""
    ckpt = str(tmp_path / "elastic_ckpt")
    state = ElasticState(checkpoint_dir=ckpt,
                         params={"w": np.arange(6, dtype=np.float32)},
                         step=0)
    state.params["w"] = state.params["w"] * 2.0
    state.step = 5
    state.commit(check_host_updates=False)
    cold = ElasticState(checkpoint_dir=ckpt,
                        params={"w": np.zeros(6, dtype=np.float32)},
                        step=0)
    assert cold.load_checkpoint()
    assert np.array_equal(cold.params["w"],
                          np.arange(6, dtype=np.float32) * 2.0)
    assert int(cold.step) == 5
    # restore() after load rolls back to the loaded snapshot, not zeros
    cold.step = 9
    cold.restore()
    assert int(cold.step) == 5


def test_state_checkpoint_every(tmp_path):
    ckpt = str(tmp_path / "ck")
    state = ElasticState(checkpoint_dir=ckpt, checkpoint_every=100,
                         x=np.ones(2))
    state.commit(check_host_updates=False)   # commit #2 of 100: no write
    assert ElasticState(checkpoint_dir=ckpt,
                        x=np.zeros(2)).load_checkpoint() is False


def test_state_sync_single_process():
    state = ElasticState(x=np.ones(2), step=3)
    state.x = state.x + 1
    state.sync()   # size-1 world: adopt own commit
    assert np.array_equal(state.x, np.ones(2))


# ------------------------------------------------------ blacklist / discovery

def test_blacklist_threshold():
    b = Blacklist(threshold=2)
    assert not b.record_failure("hostA")
    assert not b.is_blacklisted("hostA")
    assert b.record_failure("hostA")          # second failure crosses
    assert b.is_blacklisted("hostA")
    assert not b.record_failure("hostA")      # already blacklisted: no edge
    assert b.blacklisted() == ["hostA"]
    assert b.filter([("hostA", 2), ("hostB", 1)]) == [("hostB", 1)]


def test_blacklist_ban_and_env(monkeypatch):
    monkeypatch.setenv("HOROVOD_ELASTIC_BLACKLIST_THRESHOLD", "5")
    b = Blacklist()
    assert b.threshold == 5
    assert b.ban("gone")
    assert b.is_blacklisted("gone")
    assert not b.ban("gone")   # already banned


def test_discovery_parse_and_static():
    assert parse_discovery_output("a:2\n\n# comment\nb\nbad:x\n") == [
        ("a", 2), ("b", 1)]
    d = StaticDiscovery([("h1", 4), ("h2", 4)])
    assert d.probe() == [("h1", 4), ("h2", 4)]


def test_discovery_script(tmp_path):
    """ScriptDiscovery runs the --host-discovery-script analog; failures
    return the last good answer instead of scaling the world to zero."""
    script = tmp_path / "discover.sh"
    script.write_text("#!/bin/sh\ncat " + str(tmp_path / "hosts.txt") + "\n")
    script.chmod(script.stat().st_mode | stat.S_IEXEC)
    (tmp_path / "hosts.txt").write_text("node1:2\nnode2:2\n")
    d = ScriptDiscovery(str(script))
    assert d.probe() == [("node1", 2), ("node2", 2)]
    (tmp_path / "hosts.txt").write_text("node1:2\nnode2:2\nnode3:1\n")
    assert d.probe() == [("node1", 2), ("node2", 2), ("node3", 1)]
    os.remove(tmp_path / "hosts.txt")   # script now fails (cat exits 1)
    assert d.probe() == [("node1", 2), ("node2", 2), ("node3", 1)]


# ------------------------------------------------------------ fault injection

def test_fault_injection_fires_at_step():
    script = (
        "import os\n"
        "from horovod_tpu.elastic import fault\n"
        "fault.maybe_die(4)\n"          # != 5: no-op
        "fault.maybe_die(5)\n"          # == 5: dies with exit:7
        "print('survived')\n"
    )
    env = dict(os.environ)
    env.update({"HOROVOD_FAULT_INJECT_STEP": "5",
                "HOROVOD_FAULT_INJECT_INDEX": "3",
                "HOROVOD_TASK_INDEX": "3",
                "HOROVOD_FAULT_INJECT_SIGNAL": "exit:7",
                "JAX_PLATFORMS": "cpu"})
    p = subprocess.run([sys.executable, "-c", script], env=env, cwd=REPO,
                       capture_output=True, text=True, timeout=60)
    assert p.returncode == 7, (p.returncode, p.stdout, p.stderr)
    assert "survived" not in p.stdout
    # wrong index: inert
    env["HOROVOD_TASK_INDEX"] = "0"
    p = subprocess.run([sys.executable, "-c", script], env=env, cwd=REPO,
                       capture_output=True, text=True, timeout=60)
    assert p.returncode == 0 and "survived" in p.stdout


def test_fault_injection_unarmed_is_free():
    from horovod_tpu.elastic import fault

    assert not fault.armed()
    fault.maybe_die(5)   # must be a no-op without the env vars


# ------------------------------------------------- rendezvous protocol (unit)

def _register(addr, key, index, kind="register", min_gen=1, coord_port=0):
    from horovod_tpu.runner.network import BasicClient

    c = BasicClient(addr, key)
    c.request({"kind": kind, "index": index, "host_hash": f"host{index}",
               "addresses": [("127.0.0.1", 0)],
               "coord_port": coord_port or 7100 + index})
    resp = c.request({"kind": "wait_assignment", "index": index,
                      "min_generation": min_gen, "timeout": 30.0})
    c.close()
    return resp


def test_elastic_driver_generations():
    """Membership protocol: formation, survivor-keeps-rank-0 reassignment,
    removed-slot notification, and the elastic_poll reset signal."""
    from horovod_tpu.runner.network import BasicClient, make_secret
    from horovod_tpu.runner.service import ElasticDriverService

    key = make_secret()
    d = ElasticDriverService(key)
    addr = [("127.0.0.1", d.port)]
    try:
        d.begin_reset({0, 1})
        out: dict = {}
        ts = [threading.Thread(
            target=lambda i=i: out.__setitem__(i, _register(addr, key, i)))
            for i in (0, 1)]
        [t.start() for t in ts]
        [t.join(30) for t in ts]
        assert out[0]["generation"] == 1 and out[1]["generation"] == 1
        assert {out[0]["rank"], out[1]["rank"]} == {0, 1}
        assert d.generation == 1

        # index 0 dies; survivor 1 re-rendezvouses, replacement joins as 2
        d.begin_reset({1, 2})
        out2: dict = {}
        ts = [threading.Thread(target=lambda: out2.__setitem__(
                  1, _register(addr, key, 1, kind="rendezvous", min_gen=2))),
              threading.Thread(target=lambda: out2.__setitem__(
                  2, _register(addr, key, 2)))]
        [t.start() for t in ts]
        [t.join(30) for t in ts]
        # the SURVIVOR is rank 0 of the new world (it roots the state sync)
        assert out2[1]["rank"] == 0 and out2[1]["generation"] == 2
        assert out2[2]["rank"] == 1
        assert out2[1]["topology"]["size"] == 2

        c = BasicClient(addr, key)
        resp = c.request({"kind": "wait_assignment", "index": 0,
                          "min_generation": 2})
        assert resp.get("removed"), resp
        assert c.request({"kind": "elastic_poll", "index": 1,
                          "generation": 1})["reset_required"]
        assert not c.request({"kind": "elastic_poll", "index": 1,
                              "generation": 2})["reset_required"]
        # stale-generation results are dropped, current ones accepted
        c.request({"kind": "result", "rank": 0, "index": 1, "generation": 1,
                   "value": {"ok": True, "value": "stale"}})
        c.request({"kind": "result", "rank": 0, "index": 1, "generation": 2,
                   "value": {"ok": True, "value": "fresh"}})
        c.close()
        m = d.membership()
        assert m["results"] == {0: {"ok": True, "value": "fresh"}}
    finally:
        d.stop()


def test_agent_spawn_extend():
    """HostAgent grows an existing job with spawn+extend (the elastic
    scale-up path) and refuses duplicate indices."""
    from horovod_tpu.runner.agent import HostAgent
    from horovod_tpu.runner.network import BasicClient, make_secret

    key = make_secret()
    agent = HostAgent(key)
    try:
        c = BasicClient([("127.0.0.1", agent.port)], key)
        sleeper = [sys.executable, "-c", "import time; time.sleep(60)"]
        r = c.request({"kind": "spawn", "job_id": "j1",
                       "workers": [{"index": 0, "argv": sleeper, "env": {}}]})
        assert r["ok"], r
        r = c.request({"kind": "spawn", "job_id": "j1", "extend": True,
                       "workers": [{"index": 1, "argv": sleeper, "env": {}}]})
        assert r["ok"], r
        r = c.request({"kind": "poll", "job_id": "j1"})
        assert [w["index"] for w in r["workers"]] == [0, 1]
        assert all(w["returncode"] is None for w in r["workers"])
        # duplicate index in extend is refused
        r = c.request({"kind": "spawn", "job_id": "j1", "extend": True,
                       "workers": [{"index": 1, "argv": sleeper, "env": {}}]})
        assert not r["ok"] and "already has worker" in r["error"]
        # plain (non-extend) respawn of an existing job is still refused
        r = c.request({"kind": "spawn", "job_id": "j1",
                       "workers": [{"index": 9, "argv": sleeper, "env": {}}]})
        assert not r["ok"] and "already exists" in r["error"]
        c.request({"kind": "kill", "job_id": "j1"})
        c.close()
    finally:
        agent.stop()


# --------------------------------------------------------------- end to end

def _make_entry(total_steps):
    """Build the e2e training entry as a CLOSURE (cloudpickle ships
    closures by value; a module-level function in a test module is not
    importable from worker processes). The loop does world-size-invariant
    accumulation (+1 per step via an averaged allreduce of ones), committed
    every step, so the final value proves exact resume — committed steps
    counted once, uncommitted ones rolled back and re-run."""

    def entry():
        import os as _os

        import numpy as _np

        import horovod_tpu as hvd

        state = hvd.elastic.ElasticState(step=0, acc=0.0)

        def train(state):
            while state.step < total_steps:
                gen = _os.environ.get("HOROVOD_ELASTIC_GENERATION", "0")
                out = hvd.allreduce(_np.ones(2), average=True,
                                    name=f"grad.{state.step}.g{gen}")
                state.acc = state.acc + float(out[0])
                state.step += 1
                state.commit()
            return (hvd.rank(), hvd.size(), int(state.step),
                    float(state.acc))

        return hvd.elastic.run(train)(state)

    return entry


def test_run_elastic_no_faults_matches_run():
    """Without faults, run_elastic behaves like run(): results ordered by
    rank, one generation, exact step count."""
    from horovod_tpu.runner import run_elastic

    results = run_elastic(_make_entry(4), num_proc=2, timeout=120,
                          env={"HOROVOD_ENGINE": "python"})
    assert [(r, s) for r, s, _, _ in results] == [(0, 2), (1, 2)]
    assert all(step == 4 and acc == 4.0 for _, _, step, acc in results)


def test_run_elastic_kill_coordinator_completes():
    """Kill rank 0 (the eager coordinator) mid-train: the survivor's
    collectives fail fast, it re-rendezvouses into a world of one, resumes
    from the last commit, and delivers the exact final state (committed
    progress kept, nothing double-counted)."""
    from horovod_tpu.runner import run_elastic

    results = run_elastic(
        _make_entry(8), num_proc=2, timeout=120,
        env={"HOROVOD_ENGINE": "python",
             "HOROVOD_ELASTIC_BLACKLIST_THRESHOLD": "1",
             "HOROVOD_FAULT_INJECT_STEP": "3",
             "HOROVOD_FAULT_INJECT_INDEX": "0",
             "HOROVOD_STALL_CHECK_TIME": "1",
             "HOROVOD_STALL_SHUTDOWN_TIME": "3"})
    assert results == [(0, 1, 8, 8.0)]


def test_run_elastic_respawn_rejoins():
    """Below the blacklist threshold a dead slot is RESPAWNED (fresh task
    index): the replacement re-joins, syncs the survivors' committed state,
    and the job finishes at full width with exact accumulation."""
    from horovod_tpu.runner import run_elastic

    results = run_elastic(
        _make_entry(8), num_proc=2, timeout=120,
        env={"HOROVOD_ENGINE": "python",
             "HOROVOD_ELASTIC_BLACKLIST_THRESHOLD": "2",
             "HOROVOD_FAULT_INJECT_STEP": "3",
             "HOROVOD_FAULT_INJECT_INDEX": "0",
             "HOROVOD_STALL_CHECK_TIME": "1",
             "HOROVOD_STALL_SHUTDOWN_TIME": "3"})
    # back to 2 ranks; the replacement adopted committed state, so both
    # report the exact accumulated value
    assert results == [(0, 2, 8, 8.0), (1, 2, 8, 8.0)]


def test_run_elastic_below_min_np_aborts():
    """Losing a worker with min_np too high must fail loudly, not hang."""
    from horovod_tpu.runner import run_elastic

    with pytest.raises((RuntimeError, TimeoutError), match="min_np|failed"):
        run_elastic(
            _make_entry(50), num_proc=2, min_np=2,
            timeout=60,
            env={"HOROVOD_ENGINE": "python",
                 "HOROVOD_ELASTIC_BLACKLIST_THRESHOLD": "1",
                 "HOROVOD_FAULT_INJECT_STEP": "2",
                 "HOROVOD_FAULT_INJECT_INDEX": "0",
                 "HOROVOD_STALL_CHECK_TIME": "1",
                 "HOROVOD_STALL_SHUTDOWN_TIME": "3"})


def test_run_elastic_user_exception_aborts():
    """A genuine bug in the training fn must abort the job with the remote
    traceback — elastic recovery is for infrastructure failures only."""
    from horovod_tpu.runner import run_elastic

    def entry():
        import horovod_tpu as hvd

        state = hvd.elastic.ElasticState(step=0)

        def train(state):
            raise ValueError("intentional elastic user bug")

        return hvd.elastic.run(train)(state)

    with pytest.raises(RuntimeError, match="intentional elastic user bug"):
        run_elastic(entry, num_proc=2, timeout=90,
                    env={"HOROVOD_ENGINE": "python"})


@pytest.mark.slow
def test_run_elastic_kill_nonroot_via_stall_escalation(tmp_path):
    """Kill a NON-coordinator rank: survivors' collectives hang at the
    coordinator, the PR 2 stall watchdog escalates past
    HOROVOD_STALL_SHUTDOWN_TIME, and the elastic wrapper turns that
    escalation into a reset. Also asserts the event log trail."""
    from horovod_tpu.runner import run_elastic

    event_log = str(tmp_path / "events.jsonl")
    results = run_elastic(
        _make_entry(8), num_proc=3, timeout=150,
        env={"HOROVOD_ENGINE": "python",
             "HOROVOD_ELASTIC_EVENT_LOG": event_log,
             "HOROVOD_ELASTIC_BLACKLIST_THRESHOLD": "1",
             "HOROVOD_FAULT_INJECT_STEP": "4",
             "HOROVOD_FAULT_INJECT_INDEX": "2",
             "HOROVOD_STALL_CHECK_TIME": "0.5",
             "HOROVOD_STALL_SHUTDOWN_TIME": "2"})
    assert [(r, s, st, a) for r, s, st, a in results] == [
        (0, 2, 8, 8.0), (1, 2, 8, 8.0)]
    events = [json.loads(line)["event"] for line in open(event_log)]
    assert "worker_failed" in events
    assert "host_blacklisted" in events
    assert events.count("rendezvous_complete") >= 2


@pytest.mark.slow
def test_run_elastic_discovery_adds_worker():
    """Scale-up: discovery grows the slot set mid-run; running workers get
    the HostsUpdatedInterrupt at commit, re-rendezvous, and the new worker
    joins with the survivors' committed state."""
    from horovod_tpu.elastic import HostDiscovery
    from horovod_tpu.runner import run_elastic

    class GrowAfter(HostDiscovery):
        def __init__(self):
            self.t0 = time.time()

        def probe(self):
            return [("local", 3 if time.time() - self.t0 > 2.0 else 2)]

    def entry():
        import time as _t

        import numpy as _np

        import horovod_tpu as hvd

        state = hvd.elastic.ElasticState(step=0, sizes=[])

        def train(state):
            while state.step < 30:
                gen = os.environ.get("HOROVOD_ELASTIC_GENERATION", "0")
                hvd.allreduce(_np.ones(1), name=f"g.{state.step}.{gen}")
                state.sizes = state.sizes + [hvd.size()]
                state.step += 1
                state.commit()
                _t.sleep(0.15)
            return (hvd.rank(), sorted(set(state.sizes)))

        return hvd.elastic.run(train)(state)

    results = run_elastic(entry, num_proc=2, timeout=150, max_np=4,
                          env={"HOROVOD_ENGINE": "python",
                               "HOROVOD_ELASTIC_POLL_S": "0.2",
                               "HOROVOD_STALL_CHECK_TIME": "1",
                               "HOROVOD_STALL_SHUTDOWN_TIME": "3"},
                          discovery=GrowAfter())
    assert len(results) == 3
    # every final member saw both world sizes or joined at 3
    assert results[0][1] == [2, 3]


@pytest.mark.slow
def test_run_elastic_through_agents_survives_worker_death():
    """The remote leg: two fake-host agents, one worker killed mid-train;
    its host is blacklisted and the survivor completes. Exercises the
    incremental agent spawn (extend) and agent-side liveness."""
    from horovod_tpu.runner import run_elastic
    from horovod_tpu.runner.network import make_secret

    def start_agent(fake_host, secret):
        env = dict(os.environ)
        env["HOROVOD_HOSTNAME"] = fake_host
        env["HOROVOD_AGENT_SECRET"] = secret.hex()
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        proc = subprocess.Popen(
            [sys.executable, "-m", "horovod_tpu.runner.agent", "--port", "0"],
            env=env, cwd=REPO, stdout=subprocess.PIPE, text=True)
        info = json.loads(proc.stdout.readline())
        assert info["agent"] == "ready"
        return proc, info["port"]

    secret = make_secret()
    a, port_a = start_agent("elastic-host-a", secret)
    b, port_b = start_agent("elastic-host-b", secret)
    try:
        results = run_elastic(
            _make_entry(8),
            hosts=f"127.0.0.1@{port_a}:1,127.0.0.1@{port_b}:1",
            agent_secret=secret, timeout=150,
            env={"HOROVOD_ENGINE": "python",
                 "HOROVOD_ELASTIC_BLACKLIST_THRESHOLD": "1",
                 "HOROVOD_FAULT_INJECT_STEP": "3",
                 "HOROVOD_FAULT_INJECT_INDEX": "1",
                 "HOROVOD_STALL_CHECK_TIME": "0.5",
                 "HOROVOD_STALL_SHUTDOWN_TIME": "2"})
        assert [(s, st, a_) for _, s, st, a_ in results] == [(1, 8, 8.0)]
    finally:
        for p in (a, b):
            if p.poll() is None:
                p.kill()
            p.communicate()
