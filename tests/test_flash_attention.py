"""Pallas flash-attention correctness (interpret mode on the CPU mesh):
forward and all three gradients against the dense causal oracle, non-causal
mode, block validation, and the TransformerLM attention="flash" path."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu.ops.flash_attention import flash_attention
from horovod_tpu.ops.ring_attention import causal_reference

B, T, H, D = 2, 128, 2, 32


def qkv(seed=0, t=T):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (B, t, H, D), jnp.float32) for k in ks)


def test_forward_matches_oracle():
    q, k, v = qkv()
    with jax.default_matmul_precision("highest"):
        out = flash_attention(q, k, v, True, 32, 32)
        ref = causal_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-6, rtol=2e-6)


def test_gradients_match_oracle():
    q, k, v = qkv(1)
    g = jax.random.normal(jax.random.PRNGKey(9), q.shape, jnp.float32)
    with jax.default_matmul_precision("highest"):
        gf = jax.grad(lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, True, 32, 32) * g), argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda q, k, v: jnp.sum(
            causal_reference(q, k, v) * g), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-6, rtol=5e-6)


def test_gqa_matches_replicated_oracle():
    """Grouped-query attention: 4 q heads over 2 kv heads must equal the
    oracle with kv heads explicitly replicated — forward and all grads
    (the oracle's autodiff sums each group's dk/dv for free)."""
    hkv, group = 2, 2
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(ks[0], (B, 64, hkv * group, D), jnp.float32)
    k = jax.random.normal(ks[1], (B, 64, hkv, D), jnp.float32)
    v = jax.random.normal(ks[2], (B, 64, hkv, D), jnp.float32)
    g = jax.random.normal(jax.random.PRNGKey(9), q.shape, jnp.float32)

    def rep(x):
        return jnp.repeat(x, group, axis=2)

    with jax.default_matmul_precision("highest"):
        out = flash_attention(q, k, v, True, 16, 8)
        ref = causal_reference(q, rep(k), rep(v))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-6, rtol=2e-6)
        gf = jax.grad(lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, True, 16, 8) * g), argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda q, k, v: jnp.sum(
            causal_reference(q, rep(k), rep(v)) * g), argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gr, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-6, rtol=5e-6, err_msg=name)


def test_gqa_rejects_bad_heads():
    q, k, v = qkv()
    k3 = jnp.repeat(k[:, :, :1], 3, axis=2)  # 3 kv heads, H=2 q heads
    v3 = jnp.repeat(v[:, :, :1], 3, axis=2)
    with pytest.raises(ValueError, match="not divisible"):
        flash_attention(q, k3, v3, True, 32, 32)


def test_non_causal_full_softmax():
    q, k, v = qkv(2)
    with jax.default_matmul_precision("highest"):
        out = flash_attention(q, k, v, False, 32, 32)
        # dense non-causal oracle
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (D ** -0.5)
        p = jax.nn.softmax(s, axis=-1)
        ref = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-6, rtol=2e-6)


def test_block_sizes_are_ceilings():
    """Requested block sizes auto-shrink to the largest conforming divisor
    of the sequence (96 with a 64 ceiling fits at 48) — and still match the
    oracle."""
    from horovod_tpu.ops.flash_attention import _check_blocks

    assert _check_blocks(96, 64, 64, True) == (48, 48)
    # TPU quantum: blocks shrink to the largest conforming divisor
    assert _check_blocks(1536, 1024, 512, False) == (768, 384)
    # ...or fall back to the always-legal whole axis when none exists
    assert _check_blocks(130, 1024, 512, False) == (130, 130)
    assert _check_blocks(1160, 1024, 512, False) == (1160, 232)
    # sub-quantum ceilings round up to the quantum
    assert _check_blocks(4096, 64, 64, False) == (128, 64)
    q, k, v = qkv(3, t=96)
    with jax.default_matmul_precision("highest"):
        out = flash_attention(q, k, v, True, 64, 64)
        ref = causal_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-6, rtol=2e-6)


@pytest.mark.slow
def test_transformer_flash_equals_dense():
    from horovod_tpu.models import TransformerLM

    tok = jax.random.randint(jax.random.PRNGKey(4), (2, 128), 0, 64)
    dense = TransformerLM(vocab=64, dim=32, heads=4, layers=2, dtype=jnp.float32)
    flash = TransformerLM(vocab=64, dim=32, heads=4, layers=2, dtype=jnp.float32,
                          attention="flash")
    params = dense.init(jax.random.PRNGKey(0), tok)["params"]
    with jax.default_matmul_precision("highest"):
        od = dense.apply({"params": params}, tok)
        of = flash.apply({"params": params}, tok)
    np.testing.assert_allclose(np.asarray(of), np.asarray(od),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.slow
def test_transformer_gqa_flash_equals_dense():
    """kv_heads < heads: the dense path replicates kv heads, the flash
    path aliases them in the kernel — same params, same output."""
    from horovod_tpu.models import TransformerLM

    tok = jax.random.randint(jax.random.PRNGKey(4), (2, 128), 0, 64)
    kw = dict(vocab=64, dim=32, heads=4, kv_heads=2, layers=2,
              dtype=jnp.float32)
    dense = TransformerLM(**kw)
    flash = TransformerLM(**kw, attention="flash")
    params = dense.init(jax.random.PRNGKey(0), tok)["params"]
    # GQA swaps the fused qkv kernel for split q/kv projections
    assert "q_proj" in params["block_0"] and "kv_proj" in params["block_0"]
    with jax.default_matmul_precision("highest"):
        od = dense.apply({"params": params}, tok)
        of = flash.apply({"params": params}, tok)
    np.testing.assert_allclose(np.asarray(of), np.asarray(od),
                               atol=2e-5, rtol=2e-5)


def test_non_causal_gradients_match_oracle():
    """Covers the causal=False loop bounds in BOTH backward kernels."""
    q, k, v = qkv(5)
    g = jax.random.normal(jax.random.PRNGKey(6), q.shape, jnp.float32)

    def dense_nc(q, k, v):
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * (D ** -0.5)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bkhd->bqhd", p, v)

    with jax.default_matmul_precision("highest"):
        gf = jax.grad(lambda q, k, v: jnp.sum(
            flash_attention(q, k, v, False, 32, 32) * g), argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(lambda q, k, v: jnp.sum(
            dense_nc(q, k, v) * g), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-6, rtol=5e-6)


def test_unknown_attention_value_rejected():
    from horovod_tpu.models import TransformerLM

    tok = jnp.ones((1, 32), jnp.int32)
    bad = TransformerLM(vocab=8, dim=16, heads=2, layers=1, attention="Flash")
    with pytest.raises(ValueError, match="unknown attention"):
        bad.init(jax.random.PRNGKey(0), tok)
    # attention="flash" + sp_axis is a VALID pair (ring_flash_attention);
    # its parity is covered in tests/test_ring_flash.py.
