"""Speculative decoding (ISSUE 20 tentpole): the draft/verify loop must
reproduce the sequential ``lm_generate`` oracle BITWISE — under batching,
preemption churn, EOS, handoff admission, and even a deliberately wrong
draft — while the acceptance counters prove the speculation actually
paid (net tokens per target iteration > 1 with the f16 draft)."""

from __future__ import annotations

import numpy as np
import pytest

from horovod_tpu.serving.llm.kv_cache import PagedKVCache
from horovod_tpu.serving.llm.scheduler import IterationScheduler, Sequence
from horovod_tpu.serving.model import (
    draft_lm_params,
    lm_context_step,
    lm_draft_chain,
    lm_generate,
    lm_prefill,
    lm_prefill_from,
    lm_verify_chain,
    tiny_lm_params,
)

PARAMS = tiny_lm_params()
DRAFT = draft_lm_params(PARAMS)


def _run(sched, max_steps=4000, until=None):
    for _ in range(max_steps):
        sched.step()
        if until is not None and sched.finished_total >= until:
            return
        if not sched.waiting and not sched.running:
            return
    raise AssertionError(f"scheduler did not drain: {sched.stats()}")


def _outputs(sched) -> dict:
    return {s.seq_id: list(s.out) for s in sched.finished}


def _sched(cache=None, draft=DRAFT, k=3, **kw):
    cache = cache or PagedKVCache(64, 4, 16)
    return IterationScheduler(cache, PARAMS, draft_params=draft,
                              draft_k=k, **kw)


# -- model-side pieces --------------------------------------------------------


def test_lm_prefill_from_empty_prefix_is_lm_prefill():
    k, v, nxt = lm_prefill(PARAMS, [4, 9, 11])
    empty = np.zeros((0, 16), np.float32)
    k2, v2, n2 = lm_prefill_from(PARAMS, [4, 9, 11], empty, empty)
    np.testing.assert_array_equal(k, k2)
    np.testing.assert_array_equal(v, v2)
    assert nxt == n2


def test_lm_prefill_from_any_split_is_bitwise_identical():
    tokens = [4, 9, 11, 30, 2, 8, 17]
    k_ref, v_ref, nxt_ref = lm_prefill(PARAMS, tokens)
    for cut in range(1, len(tokens)):
        k_new, v_new, nxt = lm_prefill_from(
            PARAMS, tokens, k_ref[:cut], v_ref[:cut])
        np.testing.assert_array_equal(k_new, k_ref[cut:])
        np.testing.assert_array_equal(v_new, v_ref[cut:])
        assert nxt == nxt_ref


def test_lm_prefill_from_rejects_full_or_overlong_prefix():
    k, v, _ = lm_prefill(PARAMS, [4, 9])
    with pytest.raises(ValueError):
        lm_prefill_from(PARAMS, [4, 9], k, v)


def test_verify_chain_bitwise_equals_repeated_context_steps():
    """The amortized verify chain (one gather, buffer views, no per-step
    concat) must be BITWISE the naive lm_context_step loop — that
    equivalence is what lets speculation inherit the oracle contract."""
    tokens = [4, 9, 11, 30, 2]
    k_ref, v_ref, feed = lm_prefill(PARAMS, tokens)
    n = len(tokens)
    # naive: one lm_context_step per fed token, re-concatenated context
    ks, vs = list(k_ref), list(v_ref)
    naive, tok = [], feed
    for j in range(4):
        nxt, kv, vv = lm_context_step(
            PARAMS, tok, n + j,
            np.asarray(ks, np.float32), np.asarray(vs, np.float32))
        ks.append(kv)
        vs.append(vv)
        naive.append(nxt)
        tok = nxt
    # chained: proposals == the target's own outputs, so all accepted
    buf_k = np.empty((n + 4, 16), np.float32)
    buf_v = np.empty_like(buf_k)
    buf_k[:n] = k_ref
    buf_v[:n] = v_ref
    chain = lm_verify_chain(PARAMS, feed, naive[:3], n, buf_k, buf_v)
    assert chain == naive
    np.testing.assert_array_equal(buf_k[n:], np.asarray(ks[n:], np.float32))
    np.testing.assert_array_equal(buf_v[n:], np.asarray(vs[n:], np.float32))
    # first-mismatch-wins: a wrong proposal stops the chain AFTER the
    # target's own (correct) token for that slot
    buf_k[:n] = k_ref
    buf_v[:n] = v_ref
    wrong = [naive[0], (naive[1] + 1) % PARAMS["vocab"], naive[2]]
    cut = lm_verify_chain(PARAMS, feed, wrong, n, buf_k, buf_v)
    assert cut == naive[:2]
    # guard parity with lm_context_step's max-context check
    with pytest.raises(ValueError, match="max_context"):
        lm_verify_chain(PARAMS, feed, [1] * len(PARAMS["pos"]), n,
                        buf_k, buf_v)


def test_draft_chain_stateless_and_bounded():
    props = lm_draft_chain(DRAFT, 5, 3, 4)
    assert props == lm_draft_chain(DRAFT, 5, 3, 4)   # deterministic
    assert len(props) == 4
    # position-dependent (it reads the pos table), eos stops early
    assert lm_draft_chain(DRAFT, 5, 3, 4, eos_id=props[0]) == props[:1]
    with pytest.raises(ValueError, match="max_context"):
        lm_draft_chain(DRAFT, 5, len(DRAFT["pos"]) - 2, 4)


def test_draft_params_deterministic_and_close():
    d2 = draft_lm_params(PARAMS)
    for key in ("embed", "pos", "wq", "wk", "wv", "wo"):
        np.testing.assert_array_equal(DRAFT[key], d2[key])
        assert DRAFT[key].dtype == np.float32
        # perturbed (it IS a different model) but only at f16 resolution
        assert not np.array_equal(DRAFT[key], PARAMS[key])
        np.testing.assert_allclose(DRAFT[key], PARAMS[key], rtol=2e-3,
                                   atol=2e-3)


# -- the oracle bar -----------------------------------------------------------


@pytest.mark.parametrize("k", [1, 2, 3, 4])
def test_single_sequence_oracle_exact_for_every_draft_k(k):
    s = _sched(k=k)
    s.submit(Sequence(0, [3, 17, 5], 16))
    _run(s, until=1)
    assert _outputs(s)[0] == lm_generate(PARAMS, [3, 17, 5], 16)
    assert s.cache.alloc.used_count == 0


def test_acceptance_rate_high_and_tokens_per_iteration_above_one():
    """The perf claim in miniature: the f16 draft's proposals almost all
    survive greedy verify, so the engine emits well over one token per
    target iteration for the same oracle-exact output."""
    s = _sched(k=3)
    for i in range(4):
        s.submit(Sequence(i, [3 * i + 1, 5 * i + 2], 24))
    _run(s, until=4)
    for i in range(4):
        assert _outputs(s)[i] == lm_generate(
            PARAMS, [3 * i + 1, 5 * i + 2], 24)
    st = s.stats()
    assert st["spec_proposed_total"] > 0
    rate = st["spec_accepted_total"] / st["spec_proposed_total"]
    assert rate >= 0.5, f"f16 draft acceptance collapsed: {rate:.2f}"
    per_iter = st["tokens_decode_total"] / st["iterations_total"]
    # 4 sequences per iteration at >= ~2 tokens each when accepting
    assert per_iter > len(_outputs(s)) * 1.3, \
        f"speculation bought nothing: {per_iter:.2f} tokens/iteration"
    # occupancy counts sequences, not tokens — unchanged by speculation
    assert st["occupancy_sum"] <= st["iterations_total"] * 4


def test_garbage_draft_still_oracle_exact_with_low_acceptance():
    """A draft from a DIFFERENT seed proposes mostly wrong tokens: the
    verify loop must discard them and still emit the target's exact
    output — speculation may only cost, never corrupt."""
    garbage = tiny_lm_params(seed=99)
    s = _sched(draft=garbage, k=4)
    for i in range(3):
        s.submit(Sequence(i, [i + 1, 2 * i + 3, 7], 20))
    _run(s, until=3)
    for i in range(3):
        assert _outputs(s)[i] == lm_generate(
            PARAMS, [i + 1, 2 * i + 3, 7], 20)
    st = s.stats()
    assert st["spec_proposed_total"] > 0
    rate = st["spec_accepted_total"] / st["spec_proposed_total"]
    assert rate < 0.9          # a garbage draft cannot look like a good one


def test_eos_mid_speculation_cuts_exactly_like_oracle():
    oracle = lm_generate(PARAMS, [3, 17, 5], 32)
    eos = oracle[4]                     # stops mid-verify-window
    s = _sched(k=3)
    s.submit(Sequence(0, [3, 17, 5], 32, eos_id=eos))
    _run(s, until=1)
    assert _outputs(s)[0] == lm_generate(PARAMS, [3, 17, 5], 32, eos_id=eos)
    assert _outputs(s)[0] == oracle[:5]


def test_max_new_tokens_never_overshoots():
    for max_new in (1, 2, 3, 4, 5):
        s = _sched(k=4)
        s.submit(Sequence(0, [9, 30, 2], max_new))
        _run(s, until=1)
        out = _outputs(s)[0]
        assert out == lm_generate(PARAMS, [9, 30, 2], max_new)
        assert len(out) == max_new


def test_churn_batch_with_speculation_oracle_exact():
    """The contamination oracle under speculation: overlapping mixed
    lengths through a pool small enough to force preemption — every
    output bitwise oracle-equal, allocator invariants clean."""
    rng = np.random.RandomState(11)
    cache = PagedKVCache(24, 4, 16, watermark=1 / 24)
    s = _sched(cache=cache, k=3, max_active=4, admission_window=8)
    prompts = {}
    for i in range(10):
        pr = [int(t) for t in rng.randint(0, 64, rng.randint(1, 7))]
        prompts[i] = pr
        s.submit(Sequence(i, pr, int(rng.randint(2, 12))))
    _run(s, until=10, max_steps=8000)
    outs = _outputs(s)
    for i, pr in prompts.items():
        seq = next(q for q in s.finished if q.seq_id == i)
        assert outs[i] == lm_generate(PARAMS, pr, seq.max_new_tokens), \
            f"sequence {i} diverged under speculative churn"
    cache.alloc.check_invariants()
    assert cache.alloc.used_count == 0


def test_preempt_mid_generation_resumes_exactly_with_draft():
    prompt, max_new = [3, 17, 5], 12
    s = _sched(k=3, max_active=2)
    seq = Sequence(0, prompt, max_new)
    s.submit(seq)
    for _ in range(2):
        s.step()
    assert seq.state == "running" and len(seq.out) >= 2
    s._preempt(seq)
    _run(s, until=1)
    assert seq.out == lm_generate(PARAMS, prompt, max_new)


def test_handoff_admission_speculates_exactly():
    """A sequence entering via the prefill-pool handoff path decodes
    speculatively to the same oracle output as the local path."""
    prompt, max_new = [9, 30, 2], 10
    k, v, first = lm_prefill(PARAMS, prompt)
    s = _sched(cache=PagedKVCache(16, 4, 16), k=3)
    s.submit(Sequence(0, prompt, max_new, first_token=first,
                      handoff=(k, v)))
    _run(s, until=1)
    assert _outputs(s)[0] == lm_generate(PARAMS, prompt, max_new)
    assert s.stats()["spec_accepted_total"] > 0


def test_draft_disabled_paths():
    # draft_k=0 with params: speculation off, counters stay zero
    s = IterationScheduler(PagedKVCache(16, 4, 16), PARAMS,
                           draft_params=DRAFT, draft_k=0)
    s.submit(Sequence(0, [1, 2], 6))
    _run(s, until=1)
    assert _outputs(s)[0] == lm_generate(PARAMS, [1, 2], 6)
    st = s.stats()
    assert st["spec_proposed_total"] == 0 and st["spec_accepted_total"] == 0
    # draft_k>0 without params: likewise off
    s2 = IterationScheduler(PagedKVCache(16, 4, 16), PARAMS, draft_k=3)
    assert s2.draft_k == 0
    with pytest.raises(ValueError, match="draft_k"):
        IterationScheduler(PagedKVCache(16, 4, 16), PARAMS, draft_k=-1)


def test_speculation_composes_with_prefix_cache():
    """Both tentpole optimizations on at once: shared-prefix admissions
    feeding speculative decode stay oracle-exact and actually share."""
    cache = PagedKVCache(64, 4, 16, prefix_cache=True)
    s = _sched(cache=cache, k=3, max_active=4)
    sys_prompt = [7, 7, 7, 7, 2, 9]          # > one full block shared
    for i in range(6):
        s.submit(Sequence(i, sys_prompt + [i + 1], 10))
    _run(s, until=6)
    for i in range(6):
        assert _outputs(s)[i] == lm_generate(PARAMS, sys_prompt + [i + 1],
                                             10)
    st = s.stats()
    assert st["prefix_hit_tokens_total"] > 0
    assert st["spec_accepted_total"] > 0
    cache.alloc.check_invariants()
