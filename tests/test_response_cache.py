"""Response-cache + ring-data-plane tests (the steady-state fast path).

Unit level: ResponseCache / CacheMirror semantics (hit, miss, LRU
eviction, shape-change invalidation, flush). Protocol level: the
bitvector agreement between _Client and _Coordinator. System level
(spawned worlds via launch_util): 4-proc ring-vs-star bitwise-identical
allreduce, zero coordinator-relayed tensor bytes on the ring plane,
steady-state hit rate, capacity-bounded eviction under churn, and the
elastic-reset flush (a stale cached response must never be servable
across a membership change).
"""

import threading

import numpy as np
import pytest

from horovod_tpu.common.engine import (
    HorovodInternalError,
    PyEngine,
    _Client,
    _Coordinator,
    _ring_order_reduce,
)
from horovod_tpu.common.config import Config
from horovod_tpu.common.response_cache import (
    CacheMirror,
    ResponseCache,
    request_key,
)
from horovod_tpu.common.topology import Topology

from launch_util import launch_world


def _req(name, shape=(4,), op="allreduce", dtype="float32", root=0,
         average=True):
    return {"name": name, "op": op, "shape": tuple(shape), "dtype": dtype,
            "root": root, "average": average}


# ------------------------------------------------------------------ unit tier

def test_authority_assign_and_hit():
    c = ResponseCache(capacity=4)
    key = request_key(_req("g0"))
    bit, evicted = c.assign(key, _req("g0"))
    assert bit is not None and evicted == []
    assert c.bit_for(key) == bit
    assert c.lookup_bit(bit)[0] == key
    # idempotent re-assign returns the same bit
    bit2, _ = c.assign(key, _req("g0"))
    assert bit2 == bit
    assert len(c) == 1


def test_authority_lru_eviction_order():
    c = ResponseCache(capacity=2)
    b0, _ = c.assign(request_key(_req("g0")), _req("g0"))
    b1, _ = c.assign(request_key(_req("g1")), _req("g1"))
    c.lookup_bit(b0)  # touch g0: g1 becomes LRU
    b2, evicted = c.assign(request_key(_req("g2")), _req("g2"))
    assert [e[0] for e in evicted] == [b1]
    assert c.lookup_bit(b0) is not None
    assert c.lookup_bit(b1) is None
    assert len(c) == 2 and c.evictions == 1


def test_authority_never_evicts_in_use_bits():
    c = ResponseCache(capacity=1)
    b0, _ = c.assign(request_key(_req("g0")), _req("g0"))
    bit, evicted = c.assign(request_key(_req("g1")), _req("g1"),
                            in_use={"g0"})
    assert bit is None and evicted == []  # table full of protected bits
    assert c.lookup_bit(b0) is not None


def test_authority_shape_change_evicts_stale_bit():
    c = ResponseCache(capacity=8)
    b0, _ = c.assign(request_key(_req("g0", shape=(4,))), _req("g0"))
    new = _req("g0", shape=(8,))
    b1, evicted = c.assign(request_key(new), new)
    assert [e[0] for e in evicted] == [b0]
    assert b1 != b0
    assert c.bit_for(request_key(_req("g0", shape=(4,)))) is None


def test_authority_flush_and_capacity_zero():
    c = ResponseCache(capacity=4)
    c.assign(request_key(_req("a")), _req("a"))
    c.assign(request_key(_req("b")), _req("b"))
    assert sorted(e[1][0] for e in c.flush()) == ["a", "b"]
    assert len(c) == 0
    off = ResponseCache(capacity=0)
    assert not off.enabled
    assert off.assign(request_key(_req("a")), _req("a")) == (None, [])


def test_mirror_follows_announcements_and_flushes():
    m = CacheMirror()
    key = request_key(_req("g0"))
    assert m.lookup(key) is None and m.misses == 1
    m.apply([(7, key)], [])
    assert m.lookup(key) == 7 and m.hits == 1
    assert m.peek(key) == 7 and m.hits == 1  # peek: no stats
    m.apply([], [7])
    assert m.lookup(key) is None
    m.apply([(9, key)], [])
    m.flush()
    assert len(m) == 0 and m.peek(key) is None


def test_ring_order_reduce_matches_manual():
    arrs = [np.arange(10, dtype=np.float64) * (r + 1) for r in range(4)]
    out = _ring_order_reduce(arrs, average=True)
    np.testing.assert_allclose(out, np.arange(10) * 2.5)
    ints = [np.full(5, r, dtype=np.int32) for r in range(3)]
    np.testing.assert_array_equal(
        _ring_order_reduce(ints, average=False), np.full(5, 3, np.int32))


# ------------------------------------------------- protocol tier (in-process)

KEY = b"test-secret"


def _run_ranks(world, fn):
    coord = _Coordinator(world, "127.0.0.1", 0, key=KEY, cache_capacity=64)
    port = coord.server.getsockname()[1]
    coord.start()
    results, errors = {}, []

    def worker(rank):
        try:
            client = _Client("127.0.0.1", port, rank, key=KEY)
            try:
                results[rank] = fn(rank, client)
            finally:
                client.close()
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(r,))
               for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    stats = coord.cache_stats()
    coord.stop()
    assert not errors, errors
    return results, stats


def test_bitvector_agreement_protocol():
    """Full request -> assignment announcement -> bit-only resubmission
    produces the same result, and the authority records the hits."""

    def fn(rank, client):
        req = _req("g", dtype="float64")
        arr = np.full(4, float(rank))
        out1 = client.exchange([req], {"g": arr})
        assign = list(client.last_cache[0])
        assert assign, "no assignment announced with the result"
        bit, key = assign[0]
        assert tuple(key) == request_key(req)
        # steady state: no request dicts at all, just the bitvector
        out2 = client.exchange([], {"g": arr + 1}, bits=1 << bit)
        return out1["g"], out2["g"]

    results, stats = _run_ranks(2, fn)
    for rank in range(2):
        (e1, v1), (e2, v2) = results[rank]
        assert e1 is None and e2 is None
        np.testing.assert_allclose(v1, [0.5] * 4)
        np.testing.assert_allclose(v2, [1.5] * 4)
    assert stats["hits"] == 2 and stats["size"] == 1


def test_protocol_shape_change_reassigns():
    """A full request under a NEW shape evicts the stale bit everywhere
    and the renamed signature gets a fresh bit."""

    def fn(rank, client):
        client.exchange([_req("g", shape=(4,), dtype="float64")],
                        {"g": np.ones(4)})
        bit0 = client.last_cache[0][0][0]
        client.exchange([_req("g", shape=(8,), dtype="float64")],
                        {"g": np.ones(8)})
        assign, evict = client.last_cache
        return bit0, assign, list(evict)

    results, stats = _run_ranks(2, fn)
    for rank in range(2):
        bit0, assign, evict = results[rank]
        assert bit0 in evict, "stale bit not evicted on shape change"
        assert assign and assign[0][0] != bit0
    assert stats["size"] == 1


def test_mirror_flush_self_heals():
    """A rank that flushed its mirror falls back to full requests; the
    coordinator re-announces the existing assignment instead of thrashing
    the bit table."""

    def fn(rank, client):
        req = _req("g", dtype="float64")
        client.exchange([req], {"g": np.ones(4)})
        bit0 = client.last_cache[0][0][0]
        # flushed-mirror behavior: full request again, same signature
        client.exchange([req], {"g": np.ones(4)})
        return bit0, list(client.last_cache[0]), list(client.last_cache[1])

    results, stats = _run_ranks(2, fn)
    for rank in range(2):
        bit0, assign, evict = results[rank]
        assert evict == []
        assert any(b == bit0 for b, _k in assign), "assignment not re-announced"
    assert stats["size"] == 1


# --------------------------------------------------- system tier (subprocess)

RING_VS_STAR_WORKER = r"""
import hashlib, json, os, sys
sys.path.insert(0, os.environ["HVD_REPO"])
import numpy as np
from horovod_tpu.common.config import Config
from horovod_tpu.common.engine import PyEngine
from horovod_tpu.common.topology import Topology
from horovod_tpu import metrics as hvd_metrics

rank = int(os.environ["HOROVOD_RANK"]); world = int(os.environ["HOROVOD_SIZE"])
eng = PyEngine(Topology(rank, world, 0, 1, rank, world),
               Config(cycle_time_ms=1.0, stall_check_disable=True))
try:
    digest = hashlib.sha256()
    for i in range(6):
        for t in range(4):
            out = eng.run(
                "allreduce",
                (np.arange(777, dtype=np.float32) * (rank + 1) + i * t) / 3.0,
                f"grad.{t}")
            digest.update(out.tobytes())
    snap = hvd_metrics.registry().snapshot()["counters"]
    stats = eng.cache_stats()
    print(json.dumps({
        "rank": rank, "hash": digest.hexdigest(),
        "ring_active": stats["ring_active"],
        "mirror": stats["mirror"],
        "star_bytes": snap.get(
            'horovod_engine_data_bytes_total{plane="star"}', 0),
        "ring_bytes": snap.get(
            'horovod_engine_data_bytes_total{plane="ring"}', 0),
    }))
finally:
    eng.shutdown()
"""


@pytest.mark.engine
def test_ring_vs_star_bitwise_identical_4proc():
    """The tentpole contract on 4 real processes: both data planes produce
    BITWISE-identical allreduce results (canonical chunk order), the ring
    plane moves the bytes peer-to-peer (coordinator relays exactly 0
    tensor bytes), and steady-state negotiations hit the cache."""
    ring = launch_world(4, RING_VS_STAR_WORKER,
                        extra_env={"HOROVOD_RING_DATA_PLANE": "1"})
    star = launch_world(4, RING_VS_STAR_WORKER,
                        extra_env={"HOROVOD_RING_DATA_PLANE": "0"})
    ring_hashes = {r["out"]["hash"] for r in ring}
    star_hashes = {r["out"]["hash"] for r in star}
    assert len(ring_hashes) == 1, "ring ranks disagree"
    assert ring_hashes == star_hashes, "ring and star disagree bitwise"
    for r in ring:
        o = r["out"]
        assert o["ring_active"]
        assert o["star_bytes"] == 0, (
            f"coordinator relayed {o['star_bytes']} tensor bytes on ring")
        assert o["ring_bytes"] > 0
        m = o["mirror"]
        assert m["hits"] >= 5 * 4 and m["misses"] <= 4  # 4 cold, rest hot
    for r in star:
        assert not r["out"]["ring_active"]
        assert r["out"]["star_bytes"] > 0  # the relay carried the bytes


EVICTION_WORKER = r"""
import json, os, sys
sys.path.insert(0, os.environ["HVD_REPO"])
import numpy as np
from horovod_tpu.common.config import Config
from horovod_tpu.common.engine import PyEngine
from horovod_tpu.common.topology import Topology

rank = int(os.environ["HOROVOD_RANK"]); world = int(os.environ["HOROVOD_SIZE"])
eng = PyEngine(Topology(rank, world, 0, 1, rank, world),
               Config(cycle_time_ms=1.0, stall_check_disable=True))
try:
    ok = True
    for i in range(3):
        for t in range(8):  # 8 distinct names > capacity of 4
            out = eng.run("allreduce", np.full(16, float(rank + t)),
                          f"churn.{t}", average=False)
            ok = ok and bool(np.allclose(
                out, sum(r + t for r in range(world))))
    stats = eng.cache_stats()
    print(json.dumps({"rank": rank, "ok": ok, "stats": stats}))
finally:
    eng.shutdown()
"""


@pytest.mark.engine
def test_eviction_under_capacity_churn_2proc():
    """HOROVOD_CACHE_CAPACITY bounds the table under name churn: results
    stay correct, the authority never exceeds capacity, and evictions
    are really happening (the mirror stays bounded too)."""
    res = launch_world(2, EVICTION_WORKER,
                       extra_env={"HOROVOD_CACHE_CAPACITY": "4"})
    for r in res:
        assert r["out"]["ok"]
        assert r["out"]["stats"]["mirror"]["size"] <= 4
    auth = next(r["out"]["stats"].get("authority") for r in res
                if r["out"]["stats"].get("authority"))
    assert auth["size"] <= 4 and auth["capacity"] == 4
    assert auth["evictions"] > 0


RESET_WORKER = r"""
import json, os, sys
sys.path.insert(0, os.environ["HVD_REPO"])
import numpy as np
from horovod_tpu.common.config import Config
from horovod_tpu.common.engine import PyEngine
from horovod_tpu.common.topology import Topology

rank = int(os.environ["HOROVOD_RANK"]); world = int(os.environ["HOROVOD_SIZE"])
topo = Topology(rank, world, 0, 1, rank, world)
eng = PyEngine(topo, Config(cycle_time_ms=1.0, stall_check_disable=True))
for i in range(3):
    eng.run("allreduce", np.full(8, float(rank)), "state.sync", average=False)
warm = eng.cache_stats()["mirror"]
# The elastic reset path: flush + teardown + re-init under a bumped
# generation (hvd.elastic.run does exactly this around re-rendezvous).
eng.cache_flush()
flushed = eng.cache_stats()["mirror"]
eng.shutdown()
# Generation bump: like a real elastic reset, the new world rendezvouses
# on a FRESH coordinator address (runner/service.py hands one out per
# generation) — the old port may still be draining.
os.environ["HOROVOD_ELASTIC_GENERATION"] = "1"
os.environ["HOROVOD_COORD_ADDR"] = os.environ["HVD_COORD2"]
eng2 = PyEngine(topo, Config(cycle_time_ms=1.0, stall_check_disable=True))
fresh = eng2.cache_stats()["mirror"]
out = eng2.run("allreduce", np.full(8, float(rank)), "state.sync",
               average=False)
post = eng2.cache_stats()["mirror"]
eng2.shutdown()
print(json.dumps({
    "rank": rank, "warm": warm, "flushed": flushed, "fresh": fresh,
    "post": post, "correct": bool(np.allclose(out, sum(range(world)))),
}))
"""


@pytest.mark.engine
def test_elastic_reset_flushes_cache_2proc():
    """Satellite contract: across a reset/generation bump no stale cached
    response is servable — the rebuilt engine starts cold (size 0), the
    first post-reset negotiation is a miss, and the result is computed
    fresh and correct."""
    from launch_util import free_port

    res = launch_world(
        2, RESET_WORKER,
        extra_env={"HVD_COORD2": f"127.0.0.1:{free_port()}"})
    for r in res:
        o = r["out"]
        assert o["warm"]["size"] >= 1 and o["warm"]["hits"] >= 2
        assert o["flushed"]["size"] == 0
        assert o["fresh"]["size"] == 0 and o["fresh"]["hits"] == 0
        assert o["post"]["misses"] >= 1  # renegotiated from scratch
        assert o["correct"]


def test_elastic_run_wrapper_flushes_cache(monkeypatch):
    """hvd.elastic.run flushes the response cache on EVERY reset, before
    engine teardown (stale bits must not survive into the next
    generation even if teardown is interrupted)."""
    import importlib

    from horovod_tpu.common import basics

    # horovod_tpu.elastic re-exports run() the decorator; we need the module
    elastic_run = importlib.import_module("horovod_tpu.elastic.run")

    events = []

    class FakeEngine:
        def cache_flush(self):
            events.append("flush")

        def shutdown(self):
            events.append("engine_shutdown")

    class FakeCtx:
        index = 0
        generation = 0

        def poll_reset_required(self):
            return False

        def rendezvous(self, timeout=300.0):
            events.append("rendezvous")
            return {}

    class FakeState:
        def restore(self):
            events.append("restore")

        def sync(self, root_rank=0):
            pass

    monkeypatch.setattr(elastic_run._WorkerContext, "from_env",
                        classmethod(lambda cls: FakeCtx()))
    monkeypatch.setattr(basics, "init", lambda: None)
    monkeypatch.setattr(basics, "is_initialized", lambda: True)
    monkeypatch.setattr(basics, "size", lambda: 1)
    monkeypatch.setattr(basics, "shutdown", lambda: events.append("shutdown"))
    monkeypatch.setattr(basics._state, "engine", FakeEngine(),
                        raising=False)
    attempts = [0]

    @elastic_run.run
    def train(state):
        attempts[0] += 1
        if attempts[0] == 1:
            raise HorovodInternalError("injected peer loss")
        return "done"

    assert train(FakeState()) == "done"
    assert "flush" in events
    assert events.index("flush") < events.index("shutdown")
    assert "restore" in events and "rendezvous" in events


def test_wake_on_enqueue_latency():
    """Adaptive-cycle satellite: a small eager op must complete far below
    the configured cycle time (the old fixed sleep taxed every op a
    half-cycle; wake-on-enqueue removes it)."""
    import time

    eng = PyEngine(Topology(0, 1, 0, 1, 0, 1),
                   Config(cycle_time_ms=300.0, stall_check_disable=True))
    try:
        eng.run("allreduce", np.ones(4), "warm")  # thread warm
        t0 = time.monotonic()
        eng.run("allreduce", np.ones(4), "fast")
        dt = time.monotonic() - t0
        assert dt < 0.15, (
            f"op took {dt * 1000:.0f}ms against a 300ms cycle: "
            "wake-on-enqueue not effective")
    finally:
        eng.shutdown()
