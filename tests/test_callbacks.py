"""Callback tests (reference _keras/callbacks.py behaviors: metric averaging,
LR warmup factor, momentum correction, broadcast-at-train-begin)."""

import math

import numpy as np
import pytest

import horovod_tpu as hvd_core
from horovod_tpu.callbacks import (
    LearningRateScheduleCallback,
    LearningRateWarmupCallback,
    MetricAverageCallback,
    average_metrics,
    warmup_schedule,
)

torch = pytest.importorskip("torch")


@pytest.fixture()
def hvd(hvd=None):
    hvd_core.init()
    yield hvd_core
    hvd_core.shutdown()


def test_average_metrics_single(hvd):
    out = average_metrics({"loss": 2.0, "acc": 0.5})
    assert out["loss"] == pytest.approx(2.0)
    assert out["acc"] == pytest.approx(0.5)


def test_metric_average_callback_updates_logs(hvd):
    cb = MetricAverageCallback()
    logs = {"loss": 1.25}
    cb.on_epoch_end(0, logs)
    assert logs["loss"] == pytest.approx(1.25)


def test_warmup_callback_ramps_lr(hvd):
    model = torch.nn.Linear(2, 2)
    opt = torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9)
    cb = LearningRateWarmupCallback(opt, warmup_epochs=4, size=8)
    lrs = []
    for epoch in range(6):
        cb.on_epoch_begin(epoch)
        lrs.append(opt.param_groups[0]["lr"])
    # reference factor: 1 + epoch*(size-1)/warmup, capped at size
    assert lrs[0] == pytest.approx(0.1)
    assert lrs[1] == pytest.approx(0.1 * (1 + 7 / 4))
    assert lrs[4] == pytest.approx(0.8)   # ramp complete: lr * size
    assert lrs[5] == pytest.approx(0.8)


def test_momentum_correction_scales_buffer(hvd):
    model = torch.nn.Linear(2, 2)
    opt = torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9)
    loss = model(torch.randn(4, 2)).sum()
    loss.backward()
    opt.step()  # creates momentum buffers
    buf_before = opt.state[model.weight]["momentum_buffer"].clone()
    cb = LearningRateScheduleCallback(opt, multiplier=lambda e: 2.0)
    cb.on_epoch_begin(0)
    buf_after = opt.state[model.weight]["momentum_buffer"]
    assert torch.allclose(buf_after, buf_before * 2.0)


def test_warmup_schedule_optax(hvd):
    sched = warmup_schedule(base_lr=0.1, warmup_epochs=2, steps_per_epoch=10, size=4)
    assert float(sched(0)) == pytest.approx(0.1)
    # end of warmup: base_lr * size
    assert float(sched(20)) == pytest.approx(0.4)
    mid = float(sched(10))
    assert 0.1 < mid < 0.4
