"""Sharded data parallelism through the Horovod API (ISSUE 14) — the
bucketed reduce-scatter/allgather planner on the ('batch','shard') mesh.

Coverage map (the ISSUE's test satellite):
- mesh spec parsing + HOROVOD_MESH resolution;
- shard-plan invariants: padding, chunk ownership, shard=1 plan identical
  to the DP plan;
- reduce-scatter-sum correctness vs the dense allreduce oracle on a 2x4
  mesh (exact integer payloads — any mismatch is a routing bug);
- sharded == DP BITWISE on a degenerate shard=1 mesh (full training loop
  through DistributedOptimizer), and within dtype tolerance on 2x2;
- zero-pad discipline: the tail receives zero gradients, the masked update
  keeps it bitwise 0.0 even under an optimizer chain that moves
  zero-gradient entries (gradient noise);
- sharded checkpoint save -> restore -> resume exactness, including
  restore onto a RESHAPED mesh;
- trace-time shard-plan gauges + the per-bucket wire-compression opt-outs
  riding along unchanged;
- the mesh shape as the FIFTH joint-autotune dimension.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu import metrics as hvd_metrics
from horovod_tpu.compat import shard_map
from horovod_tpu.parallel import sharded as sh
from horovod_tpu.parallel.mesh import parse_mesh_spec, sharded_mesh


def make_params(seed: int = 0):
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    # 33 and 9 are deliberately not divisible by the shard sizes under test.
    return {
        "w1": jax.random.normal(k1, (16, 33)) * 0.3,
        "b1": jnp.zeros((33,)),
        "w2": jax.random.normal(k2, (33, 9)) * 0.3,
    }


def loss_fn(p, x, y):
    h = jnp.tanh(x @ p["w1"] + p["b1"])
    return jnp.mean((h @ p["w2"] - y) ** 2)


def make_data(n: int):
    x = jax.random.normal(jax.random.PRNGKey(7), (8 * n, 16))
    y = jax.random.normal(jax.random.PRNGKey(8), (8 * n, 9))
    return x, y


def grid_mesh(batch: int, shard: int) -> Mesh:
    devs = jax.devices()[:batch * shard]
    return Mesh(np.asarray(devs).reshape(batch, shard), ("batch", "shard"))


# ---------------------------------------------------------------- mesh spec


def test_parse_mesh_spec():
    # 1-/2-axis back-compat: existing spellings resolve to model=1.
    assert parse_mesh_spec("", 8) == (8, 1, 1)
    assert parse_mesh_spec("8", 8) == (8, 1, 1)
    assert parse_mesh_spec("4x2", 8) == (4, 2, 1)
    assert parse_mesh_spec("2X4", 8) == (2, 4, 1)
    assert parse_mesh_spec("4×2", 8) == (4, 2, 1)  # unicode ×, the docs spelling
    assert parse_mesh_spec("-1x2", 8) == (4, 2, 1)
    assert parse_mesh_spec("2x-1", 8) == (2, 4, 1)
    # 3-axis specs (ISSUE 19), -1 legal in any one position.
    assert parse_mesh_spec("4x2x1", 8) == (4, 2, 1)
    assert parse_mesh_spec("2x2x2", 8) == (2, 2, 2)
    assert parse_mesh_spec("2X2×2", 8) == (2, 2, 2)
    assert parse_mesh_spec("-1x2x2", 8) == (2, 2, 2)
    assert parse_mesh_spec("2x-1x2", 8) == (2, 2, 2)
    assert parse_mesh_spec("4x1x-1", 8) == (4, 1, 2)
    for bad in ("3x2", "axb", "-1x-1", "0x8", "4x3",
                # malformed / oversubscribed 3-axis shapes
                "2x2x3", "4x2x2", "0x2x4", "2x2x0", "axbxc",
                "-1x-1x2", "2x-1x-1", "1x2x3x4", "16x1x1"):
        with pytest.raises(ValueError):
            parse_mesh_spec(bad, 8)


def test_sharded_mesh_from_env(monkeypatch):
    monkeypatch.setenv("HOROVOD_MESH", "2x4")
    mesh = sharded_mesh()
    assert mesh.shape == {"batch": 2, "shard": 4}
    monkeypatch.delenv("HOROVOD_MESH")
    mesh = sharded_mesh()
    assert mesh.shape == {"batch": 8, "shard": 1}
    assert sharded_mesh(shard=2).shape == {"batch": 4, "shard": 2}


def test_sharded_mesh_third_axis(monkeypatch):
    """The mesh goes 3-D exactly when the model axis is NAMED: a 3-axis
    env spec (even `...x1`) or an explicit model= argument — 2-axis
    spellings keep the bit-identical 2-D mesh."""
    monkeypatch.setenv("HOROVOD_MESH", "2x2x2")
    assert sharded_mesh().shape == {"batch": 2, "shard": 2, "model": 2}
    monkeypatch.setenv("HOROVOD_MESH", "4x2x1")
    assert sharded_mesh().shape == {"batch": 4, "shard": 2, "model": 1}
    monkeypatch.delenv("HOROVOD_MESH")
    assert sharded_mesh(model=2).shape == \
        {"batch": 4, "shard": 1, "model": 2}
    assert sharded_mesh(batch=2, shard=2, model=2).shape == \
        {"batch": 2, "shard": 2, "model": 2}
    m = sharded_mesh(batch=2, shard=2, model=2)
    assert m.axis_names == ("batch", "shard", "model")
    with pytest.raises(ValueError):
        sharded_mesh(batch=8, shard=1, model=2)   # oversubscribed
    with pytest.raises(ValueError):
        sharded_mesh(batch=4, shard=1)            # 4x1x1 != 8 devices


# ---------------------------------------------------------------- shard plan


def test_shard_plan_padding_and_chunks():
    params = make_params()
    plan = sh.build_shard_plan(params, 4, threshold=1 << 20, num_buckets=2)
    assert plan.shard_size == 4
    for raw, padded, chunk in zip(plan.raw_sizes, plan.padded_sizes,
                                  plan.chunk_sizes):
        assert padded % 4 == 0 and padded - raw < 4 and chunk * 4 == padded
    total = sum(int(l.size) for l in jax.tree_util.tree_leaves(params))
    assert sum(plan.raw_sizes) == total


def test_shard1_plan_identical_to_dp_plan():
    """The degenerate mesh's bucket layout IS the DP layout — same bucket
    boundaries, no padding."""
    from horovod_tpu.parallel import fusion

    params = make_params()
    plan = sh.build_shard_plan(params, 1, threshold=1 << 20, num_buckets=3)
    dp = fusion.build_plan(params, 1 << 20, pad_to=1, num_buckets=3)
    assert plan.base.buckets == dp.buckets
    assert plan.raw_sizes == plan.padded_sizes


def test_dcn_threshold_caps_shard_buckets():
    """HOROVOD_DCN_FUSION_THRESHOLD applies unchanged: a bucket's scatter
    ships 1/shard of its bytes, so the cap bounds bucket bytes at D*shard
    (single oversize leaves keep their own bucket, as everywhere else)."""
    params = {f"w{i}": jnp.zeros((1 << 10,), jnp.float32)   # 64 x 4 KiB
              for i in range(64)}
    plan = sh.build_shard_plan(params, 4, threshold=1 << 30,
                               dcn_threshold=16 << 10)
    assert plan.num_buckets > 1
    for padded, dt in zip(plan.padded_sizes, plan.bucket_dtypes):
        assert padded * jnp.dtype(dt).itemsize <= (16 << 10) * 4


def test_shard_unshard_roundtrip():
    params = make_params()
    for s in (1, 2, 4, 8):
        plan = sh.build_shard_plan(params, s, threshold=1 << 20)
        back = sh.unshard_params(sh.shard_params(params, plan), plan)
        for a, b in zip(jax.tree_util.tree_leaves(params),
                        jax.tree_util.tree_leaves(back)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_state_bytes_per_rank_shrinks_shard_fold():
    params = make_params()
    dp_bytes = sh.state_bytes(params)
    plan = sh.build_shard_plan(params, 4, threshold=1 << 20)
    per_rank = plan.state_bytes_per_rank()
    # 1/4 plus at most one pad row per bucket
    assert per_rank < dp_bytes / 4 + 4 * plan.num_buckets * 4
    sp = sh.shard_params(params, plan)
    assert sh.state_bytes(sp) // 4 == per_rank


# ------------------------------------------------- reduce-scatter vs oracle


def test_reduce_scatter_matches_dense_oracle_2x4(mesh8):
    """Gathering the sharded gradient exchange's result must reproduce the
    dense pmean oracle BITWISE on exactly-summable payloads — the
    reduce-scatter-sum correctness proof on a 2x4 mesh."""
    del mesh8  # only asserts the 8-device platform
    mesh = grid_mesh(2, 4)
    # Integer-valued floats: every reduction order is exact, so equality is
    # bitwise and any mismatch is a misrouted chunk, not rounding.
    grads = {
        "a": jnp.arange(131, dtype=jnp.float32).reshape(131) % 13,
        "b": (jnp.arange(64, dtype=jnp.float32).reshape(8, 8) % 7) - 3.0,
    }
    plan = sh.build_shard_plan(grads, 4, threshold=1 << 20, num_buckets=2)

    def body(g):
        g = jax.tree_util.tree_map(lambda t: jnp.squeeze(t, 0), g)
        # Per-rank distinct integer payloads (rank = batch*4 + shard).
        r = jax.lax.axis_index("batch") * 4 + jax.lax.axis_index("shard")
        g = jax.tree_util.tree_map(lambda t: t + r.astype(t.dtype), g)
        reduced = sh.reduce_scatter_gradients(g, plan)
        full = sh.gather_params(reduced, plan)
        oracle = jax.tree_util.tree_map(
            lambda t: jax.lax.pmean(t, ("batch", "shard")), g)
        return jax.tree_util.tree_map(lambda t: t[None], (full, oracle))

    stacked = jax.tree_util.tree_map(
        lambda t: jnp.broadcast_to(t[None], (8,) + t.shape), grads)
    got, want = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(P(("batch", "shard")),),
        out_specs=P(("batch", "shard")), check_vma=False))(stacked)
    for k in grads:
        np.testing.assert_array_equal(np.asarray(got[k]),
                                      np.asarray(want[k]))


# --------------------------------------------------- training-loop parity


def _train(mesh, batch, shard, params, x, y, steps=5, num_buckets=2,
           noise=False):
    """Run the full DistributedOptimizer loop and return the final FULL
    params. shard=1 exercises the degenerate (bitwise-DP) plan."""
    inner = optax.adam(1e-2)
    if noise:
        inner = optax.chain(inner, optax.add_noise(0.01, 0.0, 0))
    plan = sh.build_shard_plan(params, shard, threshold=1 << 20,
                               num_buckets=num_buckets)
    sp = sh.shard_params(params, plan)
    opt = hvd.jax.DistributedOptimizer(inner, sharded=True, shard_plan=plan)
    opt_state = opt.init(sp)
    specs = sh.shard_specs(opt_state)

    def step(sp, st, x, y):
        full = sh.gather_params(sp, plan)
        loss, g = jax.value_and_grad(lambda p: loss_fn(p, x, y))(full)
        upd, st = opt.update(g, st, sp)
        return optax.apply_updates(sp, upd), st, \
            jax.lax.pmean(loss, ("batch", "shard"))

    run = jax.jit(shard_map(
        step, mesh=mesh,
        in_specs=(P("shard"), specs, P(("batch", "shard")),
                  P(("batch", "shard"))),
        out_specs=(P("shard"), specs, P()), check_vma=False))
    for _ in range(steps):
        sp, opt_state, _ = run(sp, opt_state, x, y)
    return sh.unshard_params(sp, plan), sp, plan


def _train_dp(params, x, y, world=4, steps=5, num_buckets=2):
    mesh = Mesh(np.asarray(jax.devices()[:world]), ("hvd",))
    opt = hvd.jax.DistributedOptimizer(optax.adam(1e-2),
                                       fusion_threshold=1 << 20,
                                       num_buckets=num_buckets)
    st = opt.init(params)

    def step(p, st, x, y):
        loss, g = jax.value_and_grad(lambda p: loss_fn(p, x, y))(p)
        upd, st = opt.update(g, st, p)
        return optax.apply_updates(p, upd), st, jax.lax.pmean(loss, "hvd")

    run = jax.jit(shard_map(step, mesh=mesh,
                            in_specs=(P(), P(), P("hvd"), P("hvd")),
                            out_specs=(P(), P(), P()), check_vma=False))
    for _ in range(steps):
        params, st, _ = run(params, st, x, y)
    return params


def test_sharded_equals_dp_bitwise_on_shard1(mesh8):
    """The acceptance headline: a degenerate shard=1 mesh walks the
    IDENTICAL bit pattern as today's DP path — same plan, same collective,
    same casts, same update arithmetic."""
    del mesh8
    params = make_params()
    x, y = make_data(4)
    dp = _train_dp(params, x, y, world=4)
    got, _, _ = _train(grid_mesh(4, 1), 4, 1, params, x, y)
    for k in params:
        a, b = np.asarray(dp[k]), np.asarray(got[k])
        assert np.array_equal(a.view(np.uint8), b.view(np.uint8)), \
            f"{k}: shard=1 diverged from DP bitwise"


def test_sharded_trajectory_matches_dp_2x2(mesh8):
    del mesh8
    params = make_params()
    x, y = make_data(4)
    with jax.default_matmul_precision("highest"):
        dp = _train_dp(params, x, y, world=4)
        got, _, _ = _train(grid_mesh(2, 2), 2, 2, params, x, y)
    for k in params:
        np.testing.assert_allclose(np.asarray(dp[k]), np.asarray(got[k]),
                                   atol=2e-6, rtol=2e-6)


# ------------------------------------------------------- zero-pad discipline


def test_pad_tail_stays_zero_under_noise(mesh8):
    """An optimizer chain that moves zero-gradient entries (gradient noise)
    would drift the pad tail; the masked update pins it to bitwise 0.0 —
    the leak named by the ISSUE satellite."""
    del mesh8
    params = make_params()
    x, y = make_data(8)
    _, sp, plan = _train(grid_mesh(2, 4), 2, 4, params, x, y, steps=4,
                         noise=True)
    padded_any = False
    for b, buf in enumerate(sp):
        flat = np.asarray(buf).reshape(-1)
        tail = flat[plan.raw_sizes[b]:]
        padded_any = padded_any or tail.size > 0
        assert (tail == 0.0).all(), f"bucket {b} pad tail drifted: {tail}"
    assert padded_any, "test vacuous: no bucket had padding"


def test_mask_pad_updates_zeroes_only_the_tail():
    params = make_params()
    plan = sh.build_shard_plan(params, 4, threshold=1 << 20)
    ones = sh.ShardedBuckets(
        jnp.ones((plan.shard_size, c)) for c in plan.chunk_sizes)
    masked = sh.mask_pad_updates(ones, plan)
    for b, buf in enumerate(masked):
        flat = np.asarray(buf).reshape(-1)
        raw = plan.raw_sizes[b]
        assert (flat[:raw] == 1.0).all()
        assert (flat[raw:] == 0.0).all()


def test_unmasked_noise_would_drift_tail():
    """Control for the invariant above: WITHOUT the mask, the same noise
    chain provably moves the tail — the mask is load-bearing, not
    decorative."""
    params = make_params()
    plan = sh.build_shard_plan(params, 4, threshold=1 << 20)
    assert any(r != p for r, p in zip(plan.raw_sizes, plan.padded_sizes))
    sp = sh.shard_params(params, plan)
    noisy = optax.add_noise(0.01, 0.0, 0)
    st = noisy.init(sp)
    zero_grads = jax.tree_util.tree_map(jnp.zeros_like, sp)
    upd, _ = noisy.update(zero_grads, st)
    drifted = False
    for b, buf in enumerate(upd):
        tail = np.asarray(buf).reshape(-1)[plan.raw_sizes[b]:]
        drifted = drifted or (tail.size and (tail != 0.0).any())
    assert drifted


# ----------------------------------------------------------- checkpointing


def test_sharded_checkpoint_save_restore_resume(mesh8, tmp_path):
    """save -> restore -> resume walks the identical trajectory as never
    having checkpointed (bitwise), through the consolidated mesh-shape-
    independent checkpoint format."""
    del mesh8
    from horovod_tpu import checkpoint as hvd_ckpt

    params = make_params()
    x, y = make_data(8)
    mesh = grid_mesh(2, 4)
    inner = optax.adam(1e-2)
    plan = sh.build_shard_plan(params, 4, threshold=1 << 20, num_buckets=2)
    sp = sh.shard_params(params, plan)
    opt = hvd.jax.DistributedOptimizer(inner, sharded=True, shard_plan=plan)
    st = opt.init(sp)
    specs = sh.shard_specs(st)

    def step(sp, st, x, y):
        full = sh.gather_params(sp, plan)
        _, g = jax.value_and_grad(lambda p: loss_fn(p, x, y))(full)
        upd, st = opt.update(g, st, sp)
        return optax.apply_updates(sp, upd), st

    run = jax.jit(shard_map(
        step, mesh=mesh,
        in_specs=(P("shard"), specs, P(("batch", "shard")),
                  P(("batch", "shard"))),
        out_specs=(P("shard"), specs), check_vma=False))
    for _ in range(3):
        sp, st = run(sp, st, x, y)
    state = {"params": sp, "opt_state": st, "step": 3}
    hvd_ckpt.save_sharded(str(tmp_path / "ckpt"), state, plan)
    # Continue the original for 2 more steps -> the reference trajectory.
    sp_ref, st_ref = sp, st
    for _ in range(2):
        sp_ref, st_ref = run(sp_ref, st_ref, x, y)
    # Restore into the sharded layout and resume.
    restored = hvd_ckpt.restore_sharded(str(tmp_path / "ckpt"), state, plan)
    assert int(np.asarray(restored["step"])) == 3
    sp_r, st_r = restored["params"], restored["opt_state"]
    for _ in range(2):
        sp_r, st_r = run(sp_r, st_r, x, y)
    a = sh.unshard_params(sp_ref, plan)
    b = sh.unshard_params(sp_r, plan)
    for k in a:
        assert np.array_equal(np.asarray(a[k]), np.asarray(b[k])), \
            f"{k}: resume diverged from the uncheckpointed trajectory"


def test_sharded_checkpoint_restores_onto_reshaped_mesh(tmp_path):
    """The consolidated format is mesh-shape independent: a shard=2
    checkpoint restores onto a shard=4 plan (and back to full)."""
    from horovod_tpu import checkpoint as hvd_ckpt

    params = make_params()
    plan2 = sh.build_shard_plan(params, 2, threshold=1 << 20)
    sp2 = sh.shard_params(params, plan2)
    hvd_ckpt.save_sharded(str(tmp_path / "ck"), {"params": sp2}, plan2)

    plan4 = sh.build_shard_plan(params, 4, threshold=1 << 20)
    template = {"params": sh.shard_params(params, plan4)}
    restored = hvd_ckpt.restore_sharded(str(tmp_path / "ck"), template,
                                        plan4)
    got = sh.unshard_params(restored["params"], plan4)
    for k in params:
        np.testing.assert_array_equal(np.asarray(params[k]),
                                      np.asarray(got[k]))


# ------------------------------------------------------------- observability


def test_shard_plan_gauges_recorded(mesh8):
    del mesh8
    params = make_params()
    x, y = make_data(8)
    _train(grid_mesh(2, 4), 2, 4, params, x, y, steps=1)
    plan = hvd_metrics.last_shard_plan()
    assert plan is not None
    assert plan["batch"] == 2 and plan["shard"] == 4
    assert plan["buckets"] >= 1
    assert plan["bytes_per_step"]["scatter"] == sum(plan["scatter_bytes"])
    assert plan["bytes_per_step"]["gather"] == sum(plan["gather_bytes"])
    snap = hvd_metrics.snapshot()
    names = set(snap.get("gauges", {}))
    assert any(n.startswith("horovod_compiled_shard_plan") for n in names)
    assert any(n.startswith("horovod_compiled_shard_bytes_per_step")
               for n in names)


def test_wire_compression_rides_the_scatter(mesh8):
    """The per-bucket wire-dtype verdicts apply unchanged: with bf16 the
    recorded scatter bytes halve while the gather (storage dtype) stays —
    and a tiny bucket under HOROVOD_COMPRESSION_MIN_BYTES opts out."""
    del mesh8
    mesh = grid_mesh(2, 4)
    big = {"w": jnp.ones((1 << 14,), jnp.float32)}          # 64 KiB
    plan = sh.build_shard_plan(big, 4, threshold=1 << 20)

    def body(g):
        g = jax.tree_util.tree_map(lambda t: jnp.squeeze(t, 0), g)
        out = sh.reduce_scatter_gradients(
            g, plan, compression="bf16", compression_min_bytes=0)
        return jax.tree_util.tree_map(lambda t: t[None], out)

    stacked = jax.tree_util.tree_map(
        lambda t: jnp.broadcast_to(t[None], (8,) + t.shape), big)
    jax.jit(shard_map(body, mesh=mesh, in_specs=(P(("batch", "shard")),),
                      out_specs=P(("batch", "shard")),
                      check_vma=False))(stacked)
    plan_rec = hvd_metrics.last_shard_plan()
    assert plan_rec["bytes_per_step"]["scatter"] * 2 == \
        plan_rec["bytes_per_step"]["gather"]
    wire = hvd_metrics.last_wire_plan()
    assert wire[0] == "bf16" and all(c for _, c, _ in wire[1])

    # Opt-out: same payload under the min-bytes floor ships full width.
    def body2(g):
        g = jax.tree_util.tree_map(lambda t: jnp.squeeze(t, 0), g)
        out = sh.reduce_scatter_gradients(
            g, plan, compression="bf16", compression_min_bytes=1 << 20)
        return jax.tree_util.tree_map(lambda t: t[None], out)

    jax.jit(shard_map(body2, mesh=mesh, in_specs=(P(("batch", "shard")),),
                      out_specs=P(("batch", "shard")),
                      check_vma=False))(stacked)
    plan_rec = hvd_metrics.last_shard_plan()
    assert plan_rec["bytes_per_step"]["scatter"] == \
        plan_rec["bytes_per_step"]["gather"]


# ----------------------------------------------------- broadcast + autotune


def test_broadcast_sharded_state(mesh8):
    """Initial-state consistency on the 2-D mesh: the broadcast rides the
    BATCH axis only, so every replica row adopts root's shard without any
    rank's partition being clobbered."""
    del mesh8
    mesh = grid_mesh(2, 4)
    params = make_params()
    plan = sh.build_shard_plan(params, 4, threshold=1 << 20)
    sp = sh.shard_params(params, plan)

    def body(sp):
        # Perturb non-root batch rows, then broadcast back from batch row 0.
        b = jax.lax.axis_index("batch")
        skew = jax.tree_util.tree_map(
            lambda t: t + b.astype(t.dtype) * 100.0, sp)
        fixed = hvd.jax.broadcast_sharded_state(skew)
        return fixed

    out = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(P("shard"),),
        out_specs=P("shard"), check_vma=False))(sp)
    got = sh.unshard_params(out, plan)
    for k in params:
        np.testing.assert_array_equal(np.asarray(params[k]),
                                      np.asarray(got[k]))


def test_autotune_fifth_dimension():
    """jax.autotune.tune(mesh_shapes=...): the mesh shape is explored
    exhaustively beside (threshold, buckets, compression, ladder) and the
    winner's config records it."""
    from horovod_tpu.jax.autotune import tune

    seen = []

    def step_factory(fusion_threshold, num_buckets, mesh_shape):
        seen.append((fusion_threshold, num_buckets, mesh_shape))
        import time as _t

        delay = 0.0002 if mesh_shape == "4x2" else 0.003

        def run():
            _t.sleep(delay)

        return run

    report = tune(step_factory, thresholds=(1 << 20,), num_buckets=(1, 2),
                  mesh_shapes=("8x1", "4x2"),
                  warmup=0, iters=1, reps=1, gp_rounds=0)
    assert {m for (_, _, m) in seen} == {"8x1", "4x2"}
    assert report.best.mesh_shape == "4x2"
    assert report.best.config.get("mesh") == "4x2"
    assert "mesh" in report.knob_curve()
