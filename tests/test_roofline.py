"""Roofline profiler module (VERDICT r3 weak #1: measured HBM evidence).

On the CPU test platform the XLA trace carries no TPU device track, so the
contract under test is graceful degradation + the report shape; the real
numbers come from `bench.py --roofline` on the chip (docs/benchmarks.md).
"""

import jax
import jax.numpy as jnp

from horovod_tpu.utils.roofline import (V5E_BF16_TFLOPS, format_report,
                                        profile_device_ops)


def test_cpu_trace_degrades_gracefully(tmp_path):
    x = jnp.ones((256, 256))
    f = jax.jit(lambda a: a @ a)
    f(x).block_until_ready()

    def run():
        f(x).block_until_ready()

    rep = profile_device_ops(run, steps=2, logdir=str(tmp_path))
    # CPU: no TPU track with cost fields -> ok=False with a reason, and the
    # formatter must not crash on it (bench --roofline prints this path).
    assert rep["ok"] is False
    assert "trace" in rep["reason"] or "track" in rep["reason"]
    assert "unavailable" in format_report(rep)


def test_report_formatting_from_synthetic():
    rep = {
        "ok": True,
        "device_ms_per_step": 46.9,
        "model_bytes_gb_per_step": 43.9,
        "achieved_gbs": 937.0,
        "pct_hbm_roof": 114.4,
        "model_tflop_per_step": 3.06,
        "achieved_tflops": 65.2,
        "categories": [
            {"name": "convolution fusion", "ms_per_step": 36.95,
             "gbs": 758.4, "pct_hbm_roof": 92.6, "tflops": 82.6},
            {"name": "tiny", "ms_per_step": 0.001, "gbs": 1.0,
             "pct_hbm_roof": 0.1, "tflops": 0.0},
        ],
        "top_ops": [],
    }
    out = format_report(rep)
    assert "convolution fusion" in out
    assert "92.6" in out
    assert "tiny" not in out          # sub-0.01ms rows are dropped
    # the summary line carries both roofs: HBM % and % of bf16 peak
    assert "% of v5e HBM" in out
    assert f"{round(65.2 / V5E_BF16_TFLOPS * 100, 1)}" in out
