"""Overlap scheduler tests: reverse-order bucket planning, numerical
equivalence of K-bucket vs single-bucket allreduce on the 8-device CPU mesh,
and joint (fusion_threshold, num_buckets) autotuner convergence — the
bucketed compute/comm-overlap path of fusion.py / collectives.py /
DistributedOptimizer (Horovod's background-thread overlap expressed at the
XLA graph level; ISSUE 1 tentpole)."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.compat import shard_map
from horovod_tpu.parallel import fusion


def _tree(sizes=(100, 200, 300, 7), dtype=jnp.float32):
    return {f"w{i}": jnp.arange(s, dtype=dtype) for i, s in enumerate(sizes)}


# ------------------------------------------------------------------ planning


def test_reverse_plan_bucket_count_and_order():
    tree = _tree()
    n_leaves = len(jax.tree_util.tree_leaves(tree))
    for k in (2, 3, 4):
        plan = fusion.build_plan(tree, num_buckets=k)
        assert plan.reverse_order
        assert plan.num_buckets == k
        # Bucket 0 starts at the LAST flatten index (last-layer grads — what
        # the backward pass produces first) and indices never increase.
        flat_idx = [d.index for b in plan.buckets for d in b]
        assert flat_idx[0] == n_leaves - 1
        assert flat_idx == sorted(flat_idx, reverse=True)
        # Every leaf appears exactly once.
        assert sorted(flat_idx) == list(range(n_leaves))


def test_reverse_plan_respects_k_up_to_leaf_granularity():
    tree = [jnp.zeros((10,)) for _ in range(20)]
    for k, expect in ((1, 1), (4, 4), (7, 7), (20, 20), (30, 20)):
        assert fusion.build_plan(tree, num_buckets=k).num_buckets == expect


def test_reverse_plan_buckets_are_byte_balanced():
    tree = [jnp.zeros((64,)) for _ in range(16)]
    plan = fusion.build_plan(tree, num_buckets=4)
    sizes = [sum(d.size for d in b) for b in plan.buckets]
    assert max(sizes) <= 2 * min(sizes)


def test_reverse_plan_single_dtype_buckets_and_threshold_cap():
    tree = {"a": jnp.zeros((64,), jnp.float32),
            "b": jnp.zeros((64,), jnp.bfloat16),
            "c": jnp.zeros((64,), jnp.float32)}
    plan = fusion.build_plan(tree, num_buckets=2)
    for b in plan.buckets:
        assert len({d.dtype for d in b}) == 1
    # Threshold stays a hard cap in the K-bucket plan: 16 float32 leaves of
    # 64 B each with a 128 B cap can never fuse more than 2 leaves.
    big = [jnp.zeros((16,), jnp.float32) for _ in range(16)]
    plan = fusion.build_plan(big, threshold=128, num_buckets=2)
    for b in plan.buckets:
        assert sum(d.size * d.dtype.itemsize for d in b) <= 128


def test_reverse_plan_padding_invariant_roundtrip():
    tree = _tree((33, 65, 127))
    plan = fusion.build_plan(tree, num_buckets=3, pad_to=8)
    bufs = fusion.fuse(tree, plan)
    assert all(b.shape[0] % 8 == 0 for b in bufs)
    back = fusion.unfuse(bufs, plan)
    for a, b in zip(jax.tree_util.tree_leaves(tree),
                    jax.tree_util.tree_leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_single_bucket_plan_unchanged():
    """num_buckets=1 must stay the historical forward-order greedy merge."""
    tree = _tree()
    plan = fusion.build_plan(tree, num_buckets=1)
    assert not plan.reverse_order
    assert plan.num_buckets == 1
    assert [d.index for d in plan.buckets[0]] == [0, 1, 2, 3]


# ------------------------------------------------- numerical equivalence


def test_k_bucket_equals_single_bucket_allreduce(mesh8):
    """K-bucket and single-bucket fused allreduce must agree bitwise on the
    8-device CPU mesh: bucketing regroups the concatenation, not the
    per-element cross-rank sums."""
    key = jax.random.PRNGKey(0)
    grads = {
        "w1": jax.random.normal(key, (8, 33, 7)),
        "w2": jax.random.normal(jax.random.PRNGKey(1), (8, 129)),
        "w3": jax.random.normal(jax.random.PRNGKey(2), (8, 5, 5)),
        "w4": jax.random.normal(jax.random.PRNGKey(3), (8, 257)),
    }

    def reducer(nb):
        return jax.jit(shard_map(
            lambda g: fusion.fused_allreduce(g, num_buckets=nb),
            mesh=mesh8, in_specs=P("hvd"), out_specs=P(), check_vma=False))

    ref = reducer(1)(grads)
    for k in (2, 3, 8):
        out = reducer(k)(grads)
        for a, b in zip(jax.tree_util.tree_leaves(ref),
                        jax.tree_util.tree_leaves(out)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_distributed_optimizer_num_buckets_trajectory_matches(mesh8):
    """One SGD step through DistributedOptimizer(num_buckets=K) lands on the
    same parameters as the single-bucket optimizer."""
    x = jnp.ones((16, 12))
    y = jnp.zeros((16,), jnp.int32)
    w = {"a": jnp.full((12, 8), 0.1), "b": jnp.zeros((8,))}

    def one_step(nb):
        opt = hvd.jax.DistributedOptimizer(optax.sgd(0.1), num_buckets=nb)
        state = opt.init(w)

        def train(w, state, x, y):
            def loss_fn(w):
                logits = x @ w["a"] + w["b"]
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits, y).mean()

            g = jax.grad(loss_fn)(w)
            up, state = opt.update(g, state, w)
            return optax.apply_updates(w, up)

        step = jax.jit(shard_map(
            train, mesh=mesh8,
            in_specs=(P(), P(), P("hvd"), P("hvd")),
            out_specs=P(), check_vma=False))
        return step(w, state, x, y)

    ref = one_step(1)
    for k in (2, 4):
        out = one_step(k)
        for a, b in zip(jax.tree_util.tree_leaves(ref),
                        jax.tree_util.tree_leaves(out)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_num_buckets_env_knob(monkeypatch):
    from horovod_tpu.common.config import Config
    from horovod_tpu.jax import _resolved_num_buckets

    monkeypatch.setenv("HOROVOD_NUM_BUCKETS", "6")
    cfg = Config.from_env()
    assert cfg.num_buckets == 6
    assert "HOROVOD_NUM_BUCKETS" in cfg.pinned
    assert _resolved_num_buckets(None) == 6
    assert _resolved_num_buckets(3) == 3       # explicit argument wins
    monkeypatch.delenv("HOROVOD_NUM_BUCKETS")
    assert Config.from_env().num_buckets == 1


def test_latency_hiding_flags_idempotent():
    from horovod_tpu.common.config import (LATENCY_HIDING_XLA_FLAGS,
                                           enable_latency_hiding_scheduler)

    env = {"XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
    enable_latency_hiding_scheduler(env)
    for f in LATENCY_HIDING_XLA_FLAGS:
        assert f in env["XLA_FLAGS"]
    once = env["XLA_FLAGS"]
    enable_latency_hiding_scheduler(env)
    assert env["XLA_FLAGS"] == once           # no duplicate accumulation


# --------------------------------------------------- joint autotuning


def _sim(threshold: int, nb: int) -> float:
    """Synthetic objective over the 2-D space: best at a large threshold and
    ~8 buckets (overlap pays until launch overhead bites)."""
    t_mb = threshold / (1 << 20)
    return (math.log2(t_mb + 1) / 8.0) * math.exp(
        -((math.log2(nb) - 3.0) ** 2) / 4.0)


def test_native_manager_converges_over_threshold_and_buckets():
    """The 5-dim native BO (autotuner.h) must move BOTH knobs toward the
    simulated optimum when the bucket dimension is opened."""
    from horovod_tpu.autotune import ParameterManager

    pm = ParameterManager(fusion_threshold=2 << 20, cycle_time_ms=5.0,
                          cycle_pinned=True, num_buckets=1)
    start = _sim(2 << 20, 1)
    for _ in range(5000):
        if not pm.active:
            break
        score = _sim(pm.fusion_threshold, pm.num_buckets)
        pm.update(int(score * 1e6), 1.0)
    assert not pm.active
    final = _sim(pm.fusion_threshold, pm.num_buckets)
    assert final > start * 1.5
    assert pm.num_buckets > 1                  # found the overlap win
    pm.close()


def test_native_manager_bucket_pin_respected():
    from horovod_tpu.autotune import ParameterManager

    pm = ParameterManager(fusion_threshold=8 << 20, cycle_time_ms=5.0,
                          num_buckets=4, num_buckets_pinned=True)
    for _ in range(3000):
        if not pm.active:
            break
        pm.update(1000000, 0.01)
    assert pm.num_buckets == 4                 # pinned knob never moved
    pm.close()


def test_ei_suggest_joint_prefers_unexplored_interior():
    from horovod_tpu.jax.autotune import _ei_suggest_joint

    measured = {(1 << 20, 1): 1.0, (1 << 20, 8): 1.4,
                (1 << 28, 1): 1.1, (1 << 28, 8): 3.0,
                (1 << 24, 4): 2.0}
    nxt = _ei_suggest_joint(measured, (1 << 20, 1 << 28), (1, 8))
    assert nxt is not None
    th, nb = nxt
    assert (1 << 20) <= th <= (1 << 28)
    assert 1 <= nb <= 8
    assert nxt not in measured


def test_compiled_tuner_joint_grid_and_report(mesh8, tmp_path):
    """tune(num_buckets=...) must cover the (threshold × buckets) seed grid,
    call the factory with the num_buckets kwarg, and report a best config
    carrying both knobs."""
    from horovod_tpu.jax.autotune import tune

    built = []
    x = jnp.ones((16, 8))
    y = jnp.zeros((16,), jnp.int32)
    w = jnp.zeros((8, 4))

    def step_factory(fusion_threshold, num_buckets):
        built.append((fusion_threshold, num_buckets))
        opt = hvd.jax.DistributedOptimizer(
            optax.sgd(0.1), fusion_threshold=fusion_threshold,
            num_buckets=num_buckets)
        state = [w, opt.init(w)]

        def train(w, ostate, x, y):
            g = jax.grad(lambda w: ((x @ w) ** 2).mean())(w)
            up, ostate = opt.update(g, ostate, w)
            return optax.apply_updates(w, up), ostate

        step = jax.jit(shard_map(train, mesh=mesh8,
                                 in_specs=(P(), P(), P("hvd"), P("hvd")),
                                 out_specs=(P(), P()), check_vma=False))

        def run():
            state[0], state[1] = step(state[0], state[1], x, y)
            jax.block_until_ready(state[0])

        return run

    log = tmp_path / "joint.csv"
    report = tune(step_factory, thresholds=(1 << 18, 1 << 22),
                  num_buckets=(1, 2), warmup=1, iters=2, reps=2,
                  gp_rounds=0, log_path=str(log))
    assert {(t, b) for t, b in built} == {
        (1 << 18, 1), (1 << 18, 2), (1 << 22, 1), (1 << 22, 2)}
    assert report.best.config["num_buckets"] in (1, 2)
    assert report.best.config["fusion_threshold"] in (1 << 18, 1 << 22)
    text = log.read_text()
    assert text.startswith("branch,fusion_threshold,num_buckets,steps_per_s")
    assert len(text.strip().splitlines()) == len(report.table) + 1
    assert "num_buckets" in report.knob_curve()
