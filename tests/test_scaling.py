"""Scaling-efficiency harness tests (VERDICT r3 item 1).

A reduced version of examples/scaling_benchmark.py runs in the fast tier:
the eager sweep at worlds 2/4 with a small payload, and the analytic pod
projection's invariants. The compiled-plane sweep is exercised at worlds
1/2 in the slow tier (jit per world)."""

from __future__ import annotations

import os
import sys

import pytest

pytestmark = pytest.mark.engine  # spawns multi-process native-engine worlds

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "examples"))

import scaling_benchmark as sb  # noqa: E402


@pytest.fixture(scope="module", autouse=True)
def build_native():
    from horovod_tpu.cc import lib_path

    lib_path()


def test_eager_sweep_structure():
    """Fast tier: structural invariants of the sweep output only. The
    wall-clock throughput bound lives in the slow-tier test below (ISSUE 2
    satellite): it was the lone tier-1 flake since PR 1 — mid-suite, the
    shared single-core box carries every previous test's process churn and
    even a best-of-3 world-3 window can land under a bound it clears in
    isolation, so the bound is load-sensitive by construction and does not
    belong in the fast tier."""
    out = sb.eager_scaling(worlds=(2, 3), payload_mb=4.0, iters=1)
    rows = out["worlds"]
    assert [r["world"] for r in rows] == [2, 3]
    assert rows[0]["software_efficiency"] == 1.0
    # per-rank rate falls with world on a shared host — the documented
    # shape (not load-sensitive in the failing direction)
    assert rows[1]["MB_per_s_rank"] < rows[0]["MB_per_s_rank"] * 1.2


@pytest.mark.slow
def test_eager_sweep_throughput_bound():
    """Aggregate throughput must not collapse from a world-2 to a world-3
    coordinator: anything under half the baseline would mean superlinear
    software overhead. Best-of-3 because a single noisy window on a shared
    single-core host is load, not regression — a genuine regression fails
    all three attempts."""
    best = -1.0
    for _ in range(3):
        out = sb.eager_scaling(worlds=(2, 3), payload_mb=4.0, iters=1)
        rows = out["worlds"]
        best = max(best, rows[1]["software_efficiency"])
        if best > 0.4:
            break
    assert best > 0.4, rows


def test_eager_hierarchical_grid_cuts_cross_bytes():
    out = sb.eager_hierarchical(world=4, local=2, payload_mb=4.0, iters=1)
    assert out["cross_byte_ratio"] <= 1.0 / out["ranks_per_host"] * 1.15, out


def test_projection_invariants():
    """The analytic model must (a) show >=90% inside a pod at 256 chips —
    the BASELINE target — under the stated assumptions, (b) make the
    hierarchical ladder strictly better than flat across DCN, and (c)
    respond to assumptions honestly (zero overlap must not report 100%)."""
    out = sb.project_pod_efficiency()
    by = {(r["chips"], r["fabric"]): r for r in out["rows"]}
    assert by[(256, "ICI (one pod)")]["efficiency"] >= 0.90
    flat = next(r for r in out["rows"] if "flat" in r["fabric"])
    hier = next(r for r in out["rows"] if "ladder" in r["fabric"])
    assert hier["efficiency"] > flat["efficiency"]
    assert hier["t_comm_ms"] < flat["t_comm_ms"]
    # falsifiability: a model that always says ~1.0 is decoration
    hostile = sb.project_pod_efficiency(step_ms=1.0, overlap=0.0)
    assert any(r["efficiency"] < 0.5 for r in hostile["rows"])


@pytest.mark.slow
def test_compiled_sweep_trend():
    out = sb.compiled_scaling(worlds=(1, 2), global_batch=16, steps=3, reps=2)
    rows = out["worlds"]
    assert [r["world"] for r in rows] == [1, 2]
    # fixed total compute on shared silicon: the 2-device step must not be
    # drastically slower than the 1-device step (collective overhead bound)
    assert rows[1]["efficiency"] > 0.5, rows
