"""End-to-end checkpoint/resume drill (VERDICT #6): train under the launcher,
"crash" after epoch 2, relaunch, and assert the job resumes from the
checkpoint with loss continuity — the reference's
examples/pytorch_imagenet_resnet50.py resume-epoch flow, exercised as a test.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import pytest

pytest.importorskip("torch")

pytestmark = [pytest.mark.slow, pytest.mark.engine]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLE = os.path.join(REPO, "examples", "pytorch_imagenet_resnet50.py")

LAUNCH = """
import json, sys
sys.path.insert(0, {repo!r})
from horovod_tpu.runner import run_command
rc = run_command([sys.executable, {example!r}] + {args!r}, num_proc=2, timeout=150)
print("LAUNCH_RC", rc)
"""


def launch(args: list[str]) -> list[dict]:
    """Run the example world-2 under the launcher; return rank-0 JSON lines."""
    code = LAUNCH.format(repo=REPO, example=EXAMPLE, args=args)
    proc = subprocess.run([sys.executable, "-c", code], capture_output=True,
                          text=True, timeout=240, cwd=REPO)
    assert "LAUNCH_RC 0" in proc.stdout, (
        f"launcher failed:\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}")
    records = []
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            records.append(json.loads(line))
    return records


def test_crash_and_resume(tmp_path):
    ckpt = str(tmp_path / "ck")
    common = ["--epochs", "4", "--checkpoint-dir", ckpt,
              "--samples-per-rank", "64", "--image-size", "16",
              "--batch-size", "16"]

    # Phase 1: train epochs 1-2, then the job dies (simulated preemption).
    phase1 = launch(common + ["--stop-after-epoch", "2"])
    epochs1 = [r for r in phase1 if "epoch" in r]
    assert [r["epoch"] for r in epochs1] == [1, 2]
    assert all(r["resumed_from"] == 0 for r in epochs1)
    assert any("stopped_after_epoch" in r for r in phase1)
    assert os.path.exists(os.path.join(ckpt, "checkpoint-2.pt"))

    # Phase 2: relaunch with no special flags — it must discover epoch 2,
    # restore, broadcast, and train epochs 3-4 only.
    phase2 = launch(common)
    epochs2 = [r for r in phase2 if "epoch" in r]
    assert [r["epoch"] for r in epochs2] == [3, 4]
    assert all(r["resumed_from"] == 2 for r in epochs2)

    # Loss continuity: training resumed from learned state, not from scratch —
    # epoch-3 loss must be below epoch-1 loss (fresh-start level), and the
    # run keeps improving.
    assert epochs2[0]["train_loss"] < epochs1[0]["train_loss"]
    assert epochs2[-1]["train_loss"] < epochs2[0]["train_loss"]
