"""Worker program for the multi-process compiled-plane tests.

Launched as ``hvdrun -np 2 --jax-distributed -- python mp_train_script.py
<mode> <out>`` with 4 virtual CPU devices per process: ``hvd.init()`` joins
the JAX distributed runtime, so the default mesh spans both processes'
devices — the N-process x M-local-chips pod execution shape the reference
exercises with ``mpirun -np 2`` in CI (.travis.yml:100-113).

Modes:
- ``trajectory``: run fused-DistributedOptimizer steps over the combined
  8-device mesh; write the final params (must match the single-process
  8-device run bit-for-bit across ranks, and numerically across the
  process-count change).
- ``hier``: hierarchical fused allreduce on a ('dcn','ici') mesh whose dcn
  axis crosses the process boundary; write flat-vs-ladder agreement.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import optax  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402
from horovod_tpu.compat import shard_map  # noqa: E402

import horovod_tpu as hvd  # noqa: E402

STEPS = 3


def loss_fn(params, x, y):
    pred = x @ params["w"] + params["b"]
    return ((pred - y) ** 2).mean()


def make_problem(n_dev):
    rng = np.random.RandomState(0)
    x = rng.randn(n_dev * 4, 6).astype(np.float32)
    y = rng.randn(n_dev * 4, 2).astype(np.float32)
    params = {"w": (rng.randn(6, 2) * 0.1).astype(np.float32),
              "b": np.zeros((2,), np.float32)}
    return x, y, params


def trajectory(out_path):
    mesh = hvd.default_mesh()
    n_dev = jax.device_count()
    x, y, params = make_problem(n_dev)
    opt = hvd.jax.DistributedOptimizer(optax.adam(1e-2))
    state = jax.tree_util.tree_map(np.asarray, opt.init(params))

    # Each process holds only its slice of the global batch; global_array
    # reassembles the process-spanning input (P('hvd') row sharding).
    rows = x.shape[0] // jax.process_count()
    lo = jax.process_index() * rows
    xg = hvd.jax.global_array(x[lo:lo + rows], mesh=mesh)
    yg = hvd.jax.global_array(y[lo:lo + rows], mesh=mesh)
    params = hvd.jax.replicate(params, mesh=mesh)
    state = hvd.jax.replicate(state, mesh=mesh)

    def step(params, state, x, y):
        grads = jax.grad(loss_fn)(params, x, y)
        updates, state = opt.update(grads, state, params)
        return optax.apply_updates(params, updates), state

    sstep = jax.jit(shard_map(step, mesh=mesh,
                              in_specs=(P(), P(), P("hvd"), P("hvd")),
                              out_specs=(P(), P()), check_vma=False))
    for _ in range(STEPS):
        params, state = sstep(params, state, xg, yg)
    return {"w": np.asarray(params["w"]).tolist(),
            "b": np.asarray(params["b"]).tolist()}


def hier(out_path):
    from horovod_tpu.parallel import fusion
    from horovod_tpu.parallel.mesh import hierarchical_mesh

    n_dev = jax.device_count()
    local = jax.local_device_count()
    # dcn axis = process boundary, ici axis = this process's local devices:
    # the two-level ladder's cross-host stage really crosses processes here.
    mesh = hierarchical_mesh(ici_size=local)
    rng = np.random.RandomState(1)
    data = rng.randn(n_dev, 64).astype(np.float32)
    rows = n_dev // jax.process_count()
    xg = hvd.jax.global_array(
        data[jax.process_index() * rows:][:rows],
        spec=P(("dcn", "ici")), mesh=mesh)

    def flat(v):
        return jax.lax.psum(v, ("dcn", "ici"))

    def ladder(v):
        (out,) = fusion.fused_allreduce([v], hierarchical=True,
                                        op=hvd.ReduceOp.SUM)
        return out

    runs = {}
    for name, body in (("flat", flat), ("ladder", ladder)):
        f = jax.jit(shard_map(body, mesh=mesh,
                              in_specs=P(("dcn", "ici")),
                              out_specs=P(("dcn", "ici")),
                              check_vma=False))
        runs[name] = np.asarray(
            jax.device_get(f(xg).addressable_shards[0].data))
    expect = data.sum(axis=0)
    return {
        "agree": bool(np.allclose(runs["flat"], runs["ladder"], rtol=1e-5)),
        "correct": bool(np.allclose(runs["flat"][0], expect, rtol=1e-4)),
    }


def main():
    mode, out_path = sys.argv[1], sys.argv[2]
    hvd.init()
    from horovod_tpu.compat import distributed_is_initialized

    assert distributed_is_initialized(), "hvd.init() did not federate JAX"
    result = {"rank": hvd.rank(), "nproc": jax.process_count(),
              "ndev": jax.device_count(), "local": jax.local_device_count()}
    result.update({"trajectory": trajectory, "hier": hier}[mode](out_path))
    with open(f"{out_path}.{hvd.rank()}", "w") as f:
        json.dump(result, f)


if __name__ == "__main__":
    main()
