"""Paged KV-block allocator (ISSUE 12): unit + property tests.

The property test is the satellite's contract: random
alloc/extend/free/preempt interleavings never leak or double-own a
block, fragmentation never strands capacity (an admission that fits the
usable pool succeeds regardless of history), and the watermark reserve
is admission-proof but growth-permeable.
"""

from __future__ import annotations

import numpy as np
import pytest

from horovod_tpu.serving.llm.kv_cache import (
    BlockAllocator,
    PagedKVCache,
    blocks_for,
)


def test_blocks_for_math():
    assert blocks_for(0, 4) == 0
    assert blocks_for(1, 4) == 1
    assert blocks_for(4, 4) == 1
    assert blocks_for(5, 4) == 2
    assert blocks_for(16, 16) == 1


def test_alloc_free_roundtrip_and_views():
    a = BlockAllocator(num_blocks=10, block_size=4, watermark=0.0)
    t = a.alloc("s1", 9)           # 3 blocks
    assert len(t) == 3 and a.used_count == 3 and a.free_count == 7
    assert a.capacity("s1") == 12
    assert a.free("s1") == 3
    assert a.used_count == 0 and a.free_count == 10


def test_double_free_and_unknown_sequence_raise():
    a = BlockAllocator(8, 4)
    a.alloc("s", 4)
    a.free("s")
    with pytest.raises(ValueError, match="double free|unknown"):
        a.free("s")
    with pytest.raises(ValueError, match="unknown"):
        a.extend("ghost", 5)
    a.alloc("s", 4)
    with pytest.raises(ValueError, match="already holds"):
        a.alloc("s", 4)


def test_watermark_blocks_admission_but_not_growth():
    # 10 blocks, 10% watermark -> 1 reserved: admissions see 9 usable.
    a = BlockAllocator(10, 2, watermark=0.10)
    assert a.reserve == 1
    assert a.alloc("big", 18) is not None      # exactly the 9 usable
    assert a.free_count == 1                   # only the reserve left
    assert a.alloc("more", 1) is None          # admission can't touch it
    assert a.extend("big", 20)                 # growth can
    assert a.free_count == 0
    assert not a.extend("big", 22)             # truly exhausted -> preempt
    a.check_invariants()


def test_preempt_counts_and_frees():
    a = BlockAllocator(8, 2)
    a.alloc("s", 8)
    n = a.preempt("s")
    assert n == 4 and a.free_count == 8 and a.preemptions_total == 1


def test_property_random_ops_never_leak_or_strand(  # the satellite bar
        seed=0xC0FFEE, ops=3000):
    rng = np.random.RandomState(seed)
    a = BlockAllocator(num_blocks=32, block_size=4, watermark=0.1)
    live: dict = {}
    next_id = 0
    for _ in range(ops):
        op = rng.randint(4)
        if op == 0:                                   # alloc
            n_tok = int(rng.randint(1, 40))
            need = blocks_for(n_tok, a.block_size)
            fits = a.can_alloc(need)
            got = a.alloc(next_id, n_tok)
            # no stranding: success is EXACTLY "fits above the reserve",
            # independent of the alloc/free history that got us here
            assert (got is not None) == fits
            if got is not None:
                live[next_id] = n_tok
                next_id += 1
        elif op == 1 and live:                        # extend
            sid = int(rng.choice(list(live)))
            n_tok = live[sid] + int(rng.randint(1, 10))
            free_before = a.free_count
            need = max(blocks_for(n_tok, a.block_size) - a.owned(sid), 0)
            ok = a.extend(sid, n_tok)
            # growth may dip into the reserve; it fails only when the
            # free list itself cannot cover it
            assert ok == (free_before >= need)
            if ok:
                live[sid] = max(live[sid], n_tok)
        elif op == 2 and live:                        # free
            sid = int(rng.choice(list(live)))
            del live[sid]
            a.free(sid)
        elif op == 3 and live:                        # preempt
            sid = int(rng.choice(list(live)))
            del live[sid]
            a.preempt(sid)
        a.check_invariants()                          # never leaks
    # drain: everything returns, the pool is whole
    for sid in list(live):
        a.free(sid)
    a.check_invariants()
    assert a.free_count == a.num_blocks


def test_property_extend_oracle_exact(seed=7):
    """Tighter extend oracle than the inline one above: replay the same
    op stream against a pure counter model."""
    rng = np.random.RandomState(seed)
    a = BlockAllocator(16, 2, watermark=0.0)
    model_free = 16
    owned: dict = {}
    for _ in range(800):
        op = rng.randint(3)
        if op == 0:
            n_tok = int(rng.randint(1, 12))
            need = blocks_for(n_tok, 2)
            got = a.alloc(("s", _), n_tok)
            assert (got is not None) == (model_free >= need)
            if got is not None:
                owned[("s", _)] = need
                model_free -= need
        elif op == 1 and owned:
            sid = list(owned)[rng.randint(len(owned))]
            n_tok = (owned[sid] * 2) + int(rng.randint(0, 6))
            need = blocks_for(n_tok, 2) - owned[sid]
            ok = a.extend(sid, n_tok)
            assert ok == (need <= 0 or model_free >= need)
            if ok and need > 0:
                owned[sid] += need
                model_free -= need
        elif op == 2 and owned:
            sid = list(owned)[rng.randint(len(owned))]
            model_free += owned.pop(sid)
            a.free(sid)
        a.check_invariants()
        assert a.free_count == model_free


# -- the paged store ----------------------------------------------------------


def test_paged_gather_matches_contiguous_reference():
    rng = np.random.RandomState(1)
    cache = PagedKVCache(num_blocks=8, block_size=3, dim=5, watermark=0.0)
    n = 7
    k_ref = rng.randn(n, 5).astype(np.float32)
    v_ref = rng.randn(n, 5).astype(np.float32)
    cache.alloc.alloc("s", n)
    for pos in range(n):
        cache.write("s", pos, k_ref[pos], v_ref[pos])
    for ln in (1, 3, 4, 7):
        k, v = cache.gather("s", ln)
        np.testing.assert_array_equal(k, k_ref[:ln])
        np.testing.assert_array_equal(v, v_ref[:ln])


def test_paged_load_roundtrip_and_watermark_refusal():
    rng = np.random.RandomState(2)
    cache = PagedKVCache(num_blocks=4, block_size=2, dim=3, watermark=0.3)
    k = rng.randn(5, 3).astype(np.float32)
    v = rng.randn(5, 3).astype(np.float32)
    # 5 tokens -> 3 blocks; usable = 4 - ceil(4*0.3)=2 -> refuse
    assert not cache.load("s", k, v)
    assert cache.alloc.used_count == 0      # refusal allocates nothing
    ok = cache.load("t", k[:3], v[:3])      # 2 blocks fits
    assert ok
    gk, gv = cache.gather("t", 3)
    np.testing.assert_array_equal(gk, k[:3])
    np.testing.assert_array_equal(gv, v[:3])


def test_retired_blocks_reused_without_stale_reads():
    """Slot-reuse hygiene at the storage level: a new sequence's gather
    over reused blocks returns ITS data, bounded by ITS length — never a
    prior owner's leftovers."""
    rng = np.random.RandomState(3)
    cache = PagedKVCache(num_blocks=2, block_size=4, dim=2, watermark=0.0)
    a_k = rng.randn(8, 2).astype(np.float32)
    assert cache.load("a", a_k, a_k)
    cache.alloc.free("a")
    b_k = rng.randn(3, 2).astype(np.float32)
    assert cache.load("b", b_k, b_k)
    gk, _ = cache.gather("b", 3)
    np.testing.assert_array_equal(gk, b_k)   # nothing of "a" leaks in


# -- prefix sharing / copy-on-write (ISSUE 20) --------------------------------


def test_admit_with_shared_blocks_refcounts_and_free():
    a = BlockAllocator(8, 4, watermark=0.0)
    t1 = a.alloc("s1", 8)                    # 2 private blocks
    a.retain(t1[0])                          # trie pins the first
    t2 = a.admit("s2", 8, shared=(t1[0],))   # shares it + 1 fresh
    assert t2[0] == t1[0] and a.refs(t1[0]) == 3
    a.check_invariants()
    # owner retires: the shared block stays allocated (trie + s2 hold it)
    assert a.free("s1") == 1                 # only the private one freed
    assert a.refs(t1[0]) == 2
    assert a.free("s2") == 1
    assert a.refs(t1[0]) == 1                # trie retention remains
    assert a.release(t1[0])                  # now it frees
    a.check_invariants()
    assert a.free_count == a.num_blocks


def test_shared_admission_counts_only_fresh_against_watermark():
    a = BlockAllocator(4, 2, watermark=0.5)  # reserve = 2, usable = 2
    t = a.alloc("s1", 4)                     # both usable blocks
    a.retain(t[0])
    a.retain(t[1])
    assert a.alloc("s2", 2) is None          # no fresh block available
    # a FULLY shared admission needs zero fresh blocks -> admits
    t2 = a.admit("s2", 4, shared=tuple(t))
    assert t2 == t
    a.check_invariants()


def test_admit_refusal_references_nothing():
    a = BlockAllocator(4, 2, watermark=0.0)
    t = a.alloc("s1", 8)
    a.retain(t[0])
    before = a.refs(t[0])
    assert a.admit("s2", 12, shared=(t[0],)) is None   # 5 fresh > 0 free
    assert a.refs(t[0]) == before            # refusal left no refs behind
    a.check_invariants()


def test_cow_unshared_block_raises_and_shared_block_swaps():
    a = BlockAllocator(8, 2, watermark=0.0)
    t1 = a.alloc("s1", 4)
    with pytest.raises(ValueError, match="unshared"):
        a.cow("s1", 0)
    a.retain(t1[0])
    new = a.cow("s1", 0)
    assert new is not None and new != t1[0]
    assert a.table("s1")[0] == new
    assert a.refs(t1[0]) == 1 and a.refs(new) == 1
    a.check_invariants()


def test_retain_release_guardrails():
    a = BlockAllocator(4, 2)
    t = a.alloc("s", 2)
    with pytest.raises(ValueError, match="free block"):
        a.retain(3)                          # never allocated
    with pytest.raises(ValueError, match="unretained"):
        a.release(t[0])                      # table ref but no retention


def test_property_shared_ops_refcount_model_replay(seed=0xBEEF, ops=2500):
    """The COW/refcount property bar: random admit-with-shared / extend /
    free / preempt / retain / release / cow interleavings against a pure
    reference model of per-block refcounts — never a leak, never a
    double-free, invariants after every op."""
    rng = np.random.RandomState(seed)
    a = BlockAllocator(num_blocks=24, block_size=2, watermark=0.1)
    tables: dict = {}          # sid -> list of blocks (model mirror)
    retained: dict = {}        # block -> retention count (model mirror)
    next_id = 0

    def model_refs(b):
        return retained.get(b, 0) + sum(t.count(b) for t in tables.values())

    for _ in range(ops):
        op = rng.randint(6)
        if op == 0:                                       # admit w/ sharing
            n_tok = int(rng.randint(1, 16))
            shareable = [b for b in set().union(*tables.values(), set())
                         if model_refs(b)] if tables else []
            rng.shuffle(shareable)
            n_blocks = blocks_for(n_tok, 2)
            shared = shareable[:int(rng.randint(0, n_blocks + 1))]
            got = a.admit(next_id, n_tok, tuple(shared))
            if got is not None:
                assert got[:len(shared)] == list(shared)
                tables[next_id] = list(got)
                next_id += 1
        elif op == 1 and tables:                          # extend
            sid = int(rng.choice(list(tables)))
            n_tok = (len(tables[sid]) + int(rng.randint(0, 3))) * 2
            if a.extend(sid, n_tok):
                tables[sid] = a.table(sid)
        elif op == 2 and tables:                          # free / preempt
            sid = int(rng.choice(list(tables)))
            expect = sum(1 for b in set(tables[sid])
                         for _ in [0]
                         if model_refs(b) == tables[sid].count(b))
            freed = (a.preempt if rng.randint(2) else a.free)(sid)
            assert freed == expect
            del tables[sid]
        elif op == 3 and tables:                          # retain
            sid = int(rng.choice(list(tables)))
            b = int(rng.choice(tables[sid]))
            a.retain(b)
            retained[b] = retained.get(b, 0) + 1
        elif op == 4 and retained:                        # release
            b = int(rng.choice(list(retained)))
            a.release(b)
            retained[b] -= 1
            if not retained[b]:
                del retained[b]
        elif op == 5 and tables:                          # cow
            sid = int(rng.choice(list(tables)))
            idx = int(rng.randint(len(tables[sid])))
            b = tables[sid][idx]
            if model_refs(b) >= 2:
                new = a.cow(sid, idx)
                if new is not None:
                    tables[sid][idx] = new
        a.check_invariants()
        for sid, t in tables.items():
            assert a.table(sid) == t
    for sid in list(tables):
        a.free(sid)
        del tables[sid]
    for b in list(retained):
        for _ in range(retained.pop(b)):
            a.release(b)
    a.check_invariants()
    assert a.free_count == a.num_blocks


def test_radix_lookup_register_and_partial_match():
    from horovod_tpu.serving.llm.kv_cache import RadixPrefixCache

    a = BlockAllocator(8, 2, watermark=0.0)
    trie = RadixPrefixCache(a)
    t = a.alloc("s1", 6)                     # 3 blocks for [1,2,3,4,5,6]
    assert trie.register([1, 2, 3, 4, 5, 6], t) == 3
    assert len(trie) == 3
    # full-block hits, MRU-touched
    blocks, partial = trie.lookup([1, 2, 3, 4, 9, 9])
    assert blocks == t[:2] and partial is None
    # partial tail: [1,2] full + one row of the [3,4] block
    blocks, partial = trie.lookup([1, 2, 3, 7])
    assert blocks == t[:1] and partial == (t[1], 1)
    # re-registering the same tokens adds nothing (LRU refresh only)
    assert trie.register([1, 2, 3, 4], t) == 0
    assert trie.hit_tokens_total > 0 and trie.lookup_tokens_total > 0


def test_radix_evict_releases_lru_leaves_only():
    from horovod_tpu.serving.llm.kv_cache import RadixPrefixCache

    a = BlockAllocator(8, 2, watermark=0.0)
    trie = RadixPrefixCache(a)
    t1 = a.alloc("s1", 4)
    trie.register([1, 2, 3, 4], t1)
    a.free("s1")                             # trie-only retention now
    # the [1,2] interior node is NOT evictable while its child lives;
    # evict(1) must take the leaf [3,4] first
    assert trie.evict(1) == 1
    assert a.refs(t1[1]) == 0 and a.refs(t1[0]) == 1
    assert trie.evict(5) == 1                # then the (now leaf) root child
    assert a.free_count == a.num_blocks
    assert trie.recovered_blocks_total == 2
    assert len(trie) == 0
    a.check_invariants()


def test_reclaimer_hook_evicts_under_admission_pressure():
    cache = PagedKVCache(num_blocks=4, block_size=2, dim=3, watermark=0.0,
                         prefix_cache=True)
    rng = np.random.RandomState(5)
    k = rng.randn(8, 3).astype(np.float32)
    assert cache.load("a", k, k, tokens=[1, 2, 3, 4, 5, 6, 7, 8])
    cache.register_prefix("a", [1, 2, 3, 4, 5, 6, 7, 8])
    cache.alloc.free("a")                    # all 4 blocks trie-retained
    assert cache.alloc.free_count == 0
    # a cold admission must evict LRU prefixes instead of refusing
    assert cache.alloc.alloc("b", 6) is not None
    assert cache.prefix.recovered_blocks_total >= 3
    cache.alloc.check_invariants()


def test_paged_cow_isolates_sibling_reads_bitwise():
    """Two sequences share prefix blocks; one diverges and writes — the
    sibling's gather must stay bitwise the original (the COW safety
    net), and the write lands in a private copy."""
    rng = np.random.RandomState(7)
    cache = PagedKVCache(num_blocks=8, block_size=2, dim=3, watermark=0.0,
                         prefix_cache=True)
    tokens = [1, 2, 3, 4]
    k = rng.randn(4, 3).astype(np.float32)
    v = rng.randn(4, 3).astype(np.float32)
    assert cache.load("a", k, v, tokens=tokens)
    cache.register_prefix("a", tokens)
    shared = cache.admit_prefix("b", tokens)
    assert shared == 4                       # both blocks by reference
    assert cache.alloc.table("b") == cache.alloc.table("a")
    # "b" overwrites a SHARED position: must COW, not corrupt "a"
    cache.write("b", 3, np.ones(3, np.float32), np.ones(3, np.float32))
    assert cache.cow_copies_total == 1
    assert cache.alloc.table("b")[1] != cache.alloc.table("a")[1]
    ka, va = cache.gather("a", 4)
    np.testing.assert_array_equal(ka, k)
    np.testing.assert_array_equal(va, v)
    kb, _ = cache.gather("b", 4)
    np.testing.assert_array_equal(kb[:3], k[:3])   # copied rows preserved
    np.testing.assert_array_equal(kb[3], np.ones(3, np.float32))
    cache.alloc.check_invariants()


def test_admit_prefix_partial_tail_copies_rows_at_admission():
    rng = np.random.RandomState(9)
    cache = PagedKVCache(num_blocks=8, block_size=4, dim=3, watermark=0.0,
                         prefix_cache=True)
    tokens = [1, 2, 3, 4, 5, 6]
    k = rng.randn(6, 3).astype(np.float32)
    assert cache.load("a", k, k, tokens=tokens)
    cache.register_prefix("a", tokens)       # registers block [1,2,3,4]
    # [1,2,3,9]: 3 rows of the registered block match -> copied, not shared
    shared = cache.admit_prefix("b", [1, 2, 3, 9, 9])
    assert shared == 3
    assert cache.alloc.table("b")[0] != cache.alloc.table("a")[0]
    kb, _ = cache.gather("b", 3)
    np.testing.assert_array_equal(kb, k[:3])
    # writing the divergent tail needs no COW (the block is private)
    cache.write("b", 3, np.ones(3, np.float32), np.ones(3, np.float32))
    assert cache.cow_copies_total == 0
    cache.alloc.check_invariants()


def test_prefix_sharing_with_model_shards_bitwise():
    """Sharing lives in the block table, so a model-sharded cache shares
    and COWs identically — gathers reassemble bitwise."""
    rng = np.random.RandomState(13)
    for shards in (1, 2):
        cache = PagedKVCache(num_blocks=8, block_size=2, dim=4,
                             watermark=0.0, model_shards=shards,
                             prefix_cache=True)
        tokens = [5, 6, 7, 8]
        k = rng.randn(4, 4).astype(np.float32)
        v = rng.randn(4, 4).astype(np.float32)
        assert cache.load("a", k, v, tokens=tokens)
        cache.register_prefix("a", tokens)
        assert cache.admit_prefix("b", tokens) == 4
        # "b" diverges at position 2: rewrites its suffix (append-only,
        # like the scheduler) — the first rewrite COWs, the second lands
        # in the now-private block
        for pos in (2, 3):
            cache.write("b", pos, np.full(4, float(pos), np.float32),
                        np.full(4, float(pos), np.float32))
        assert cache.cow_copies_total == 1
        ka, _ = cache.gather("a", 4)
        np.testing.assert_array_equal(ka, k)
        kb, _ = cache.gather("b", 4)
        np.testing.assert_array_equal(kb[:2], k[:2])
        np.testing.assert_array_equal(kb[2], np.full(4, 2.0, np.float32))
        np.testing.assert_array_equal(kb[3], np.full(4, 3.0, np.float32))
        ks, _ = cache.gather_sharded("b", 4)
        np.testing.assert_array_equal(np.concatenate(ks, axis=-1), kb)
        cache.alloc.check_invariants()


def test_load_with_tokens_skips_shared_scatter_but_stays_exact():
    rng = np.random.RandomState(17)
    cache = PagedKVCache(num_blocks=8, block_size=2, dim=3, watermark=0.0,
                         prefix_cache=True)
    tokens = [1, 2, 3, 4]
    k = rng.randn(4, 3).astype(np.float32)
    v = rng.randn(4, 3).astype(np.float32)
    assert cache.load("a", k, v, tokens=tokens)
    cache.register_prefix("a", tokens)
    hits_before = cache.prefix.hit_tokens_total
    assert cache.load("b", k, v, tokens=tokens)   # full prefix hit
    assert cache.prefix.hit_tokens_total - hits_before == 4
    kb, vb = cache.gather("b", 4)
    np.testing.assert_array_equal(kb, k)
    np.testing.assert_array_equal(vb, v)
    assert cache.cow_copies_total == 0            # nothing re-scattered
    cache.alloc.check_invariants()


def test_prefix_stats_shape():
    on = PagedKVCache(4, 2, 3, prefix_cache=True).prefix_stats()
    off = PagedKVCache(4, 2, 3).prefix_stats()
    for d in (on, off):
        assert set(d) == {"prefix_hit_tokens_total",
                          "prefix_lookup_tokens_total",
                          "recovered_blocks_total", "cow_copies_total"}
        assert all(val == 0 for val in d.values())
