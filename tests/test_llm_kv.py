"""Paged KV-block allocator (ISSUE 12): unit + property tests.

The property test is the satellite's contract: random
alloc/extend/free/preempt interleavings never leak or double-own a
block, fragmentation never strands capacity (an admission that fits the
usable pool succeeds regardless of history), and the watermark reserve
is admission-proof but growth-permeable.
"""

from __future__ import annotations

import numpy as np
import pytest

from horovod_tpu.serving.llm.kv_cache import (
    BlockAllocator,
    PagedKVCache,
    blocks_for,
)


def test_blocks_for_math():
    assert blocks_for(0, 4) == 0
    assert blocks_for(1, 4) == 1
    assert blocks_for(4, 4) == 1
    assert blocks_for(5, 4) == 2
    assert blocks_for(16, 16) == 1


def test_alloc_free_roundtrip_and_views():
    a = BlockAllocator(num_blocks=10, block_size=4, watermark=0.0)
    t = a.alloc("s1", 9)           # 3 blocks
    assert len(t) == 3 and a.used_count == 3 and a.free_count == 7
    assert a.capacity("s1") == 12
    assert a.free("s1") == 3
    assert a.used_count == 0 and a.free_count == 10


def test_double_free_and_unknown_sequence_raise():
    a = BlockAllocator(8, 4)
    a.alloc("s", 4)
    a.free("s")
    with pytest.raises(ValueError, match="double free|unknown"):
        a.free("s")
    with pytest.raises(ValueError, match="unknown"):
        a.extend("ghost", 5)
    a.alloc("s", 4)
    with pytest.raises(ValueError, match="already holds"):
        a.alloc("s", 4)


def test_watermark_blocks_admission_but_not_growth():
    # 10 blocks, 10% watermark -> 1 reserved: admissions see 9 usable.
    a = BlockAllocator(10, 2, watermark=0.10)
    assert a.reserve == 1
    assert a.alloc("big", 18) is not None      # exactly the 9 usable
    assert a.free_count == 1                   # only the reserve left
    assert a.alloc("more", 1) is None          # admission can't touch it
    assert a.extend("big", 20)                 # growth can
    assert a.free_count == 0
    assert not a.extend("big", 22)             # truly exhausted -> preempt
    a.check_invariants()


def test_preempt_counts_and_frees():
    a = BlockAllocator(8, 2)
    a.alloc("s", 8)
    n = a.preempt("s")
    assert n == 4 and a.free_count == 8 and a.preemptions_total == 1


def test_property_random_ops_never_leak_or_strand(  # the satellite bar
        seed=0xC0FFEE, ops=3000):
    rng = np.random.RandomState(seed)
    a = BlockAllocator(num_blocks=32, block_size=4, watermark=0.1)
    live: dict = {}
    next_id = 0
    for _ in range(ops):
        op = rng.randint(4)
        if op == 0:                                   # alloc
            n_tok = int(rng.randint(1, 40))
            need = blocks_for(n_tok, a.block_size)
            fits = a.can_alloc(need)
            got = a.alloc(next_id, n_tok)
            # no stranding: success is EXACTLY "fits above the reserve",
            # independent of the alloc/free history that got us here
            assert (got is not None) == fits
            if got is not None:
                live[next_id] = n_tok
                next_id += 1
        elif op == 1 and live:                        # extend
            sid = int(rng.choice(list(live)))
            n_tok = live[sid] + int(rng.randint(1, 10))
            free_before = a.free_count
            need = max(blocks_for(n_tok, a.block_size) - a.owned(sid), 0)
            ok = a.extend(sid, n_tok)
            # growth may dip into the reserve; it fails only when the
            # free list itself cannot cover it
            assert ok == (free_before >= need)
            if ok:
                live[sid] = max(live[sid], n_tok)
        elif op == 2 and live:                        # free
            sid = int(rng.choice(list(live)))
            del live[sid]
            a.free(sid)
        elif op == 3 and live:                        # preempt
            sid = int(rng.choice(list(live)))
            del live[sid]
            a.preempt(sid)
        a.check_invariants()                          # never leaks
    # drain: everything returns, the pool is whole
    for sid in list(live):
        a.free(sid)
    a.check_invariants()
    assert a.free_count == a.num_blocks


def test_property_extend_oracle_exact(seed=7):
    """Tighter extend oracle than the inline one above: replay the same
    op stream against a pure counter model."""
    rng = np.random.RandomState(seed)
    a = BlockAllocator(16, 2, watermark=0.0)
    model_free = 16
    owned: dict = {}
    for _ in range(800):
        op = rng.randint(3)
        if op == 0:
            n_tok = int(rng.randint(1, 12))
            need = blocks_for(n_tok, 2)
            got = a.alloc(("s", _), n_tok)
            assert (got is not None) == (model_free >= need)
            if got is not None:
                owned[("s", _)] = need
                model_free -= need
        elif op == 1 and owned:
            sid = list(owned)[rng.randint(len(owned))]
            n_tok = (owned[sid] * 2) + int(rng.randint(0, 6))
            need = blocks_for(n_tok, 2) - owned[sid]
            ok = a.extend(sid, n_tok)
            assert ok == (need <= 0 or model_free >= need)
            if ok and need > 0:
                owned[sid] += need
                model_free -= need
        elif op == 2 and owned:
            sid = list(owned)[rng.randint(len(owned))]
            model_free += owned.pop(sid)
            a.free(sid)
        a.check_invariants()
        assert a.free_count == model_free


# -- the paged store ----------------------------------------------------------


def test_paged_gather_matches_contiguous_reference():
    rng = np.random.RandomState(1)
    cache = PagedKVCache(num_blocks=8, block_size=3, dim=5, watermark=0.0)
    n = 7
    k_ref = rng.randn(n, 5).astype(np.float32)
    v_ref = rng.randn(n, 5).astype(np.float32)
    cache.alloc.alloc("s", n)
    for pos in range(n):
        cache.write("s", pos, k_ref[pos], v_ref[pos])
    for ln in (1, 3, 4, 7):
        k, v = cache.gather("s", ln)
        np.testing.assert_array_equal(k, k_ref[:ln])
        np.testing.assert_array_equal(v, v_ref[:ln])


def test_paged_load_roundtrip_and_watermark_refusal():
    rng = np.random.RandomState(2)
    cache = PagedKVCache(num_blocks=4, block_size=2, dim=3, watermark=0.3)
    k = rng.randn(5, 3).astype(np.float32)
    v = rng.randn(5, 3).astype(np.float32)
    # 5 tokens -> 3 blocks; usable = 4 - ceil(4*0.3)=2 -> refuse
    assert not cache.load("s", k, v)
    assert cache.alloc.used_count == 0      # refusal allocates nothing
    ok = cache.load("t", k[:3], v[:3])      # 2 blocks fits
    assert ok
    gk, gv = cache.gather("t", 3)
    np.testing.assert_array_equal(gk, k[:3])
    np.testing.assert_array_equal(gv, v[:3])


def test_retired_blocks_reused_without_stale_reads():
    """Slot-reuse hygiene at the storage level: a new sequence's gather
    over reused blocks returns ITS data, bounded by ITS length — never a
    prior owner's leftovers."""
    rng = np.random.RandomState(3)
    cache = PagedKVCache(num_blocks=2, block_size=4, dim=2, watermark=0.0)
    a_k = rng.randn(8, 2).astype(np.float32)
    assert cache.load("a", a_k, a_k)
    cache.alloc.free("a")
    b_k = rng.randn(3, 2).astype(np.float32)
    assert cache.load("b", b_k, b_k)
    gk, _ = cache.gather("b", 3)
    np.testing.assert_array_equal(gk, b_k)   # nothing of "a" leaks in
