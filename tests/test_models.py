"""Model zoo shape/grad sanity — every benchmark family the reference
measures (ResNet, VGG, Inception; docs/benchmarks.md) plus the long-context
transformer builds, runs forward, and produces finite gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from horovod_tpu import models as zoo


@pytest.mark.parametrize("name,image", [
    ("ResNet18", 32),
    ("ResNet50", 64),
    ("VGG16", 32),
    ("InceptionV3", 96),
])
@pytest.mark.slow
def test_cnn_forward_and_grad(name, image):
    model = getattr(zoo, name)(num_classes=10)
    x = jnp.ones((2, image, image, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 10)
    assert out.dtype == jnp.float32
    assert np.isfinite(np.asarray(out)).all()

    def loss(params):
        logits, _ = model.apply(
            {"params": params, "batch_stats": variables["batch_stats"]},
            x, train=True, mutable=["batch_stats"])
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, jnp.zeros((2,), jnp.int32)).mean()

    grads = jax.jit(jax.grad(loss))(variables["params"])
    flat = jax.tree_util.tree_leaves(grads)
    assert all(np.isfinite(np.asarray(g)).all() for g in flat)
    assert any(float(jnp.abs(g).max()) > 0 for g in flat)


def test_transformer_forward():
    model = zoo.TransformerLM(vocab=64, dim=32, heads=4, layers=2)
    tokens = jnp.ones((2, 16), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)
    logits = model.apply(params, tokens)
    assert logits.shape == (2, 16, 64)
    assert np.isfinite(np.asarray(logits)).all()


def test_mlp_and_convnet():
    for model, shape in ((zoo.MLP(), (2, 28, 28)), (zoo.ConvNet(), (2, 28, 28, 1))):
        x = jnp.ones(shape, jnp.float32)
        params = model.init(jax.random.PRNGKey(0), x)
        out = model.apply(params, x)
        assert out.shape == (2, 10)


@pytest.mark.slow
def test_resnet_space_to_depth_stem():
    """s2d stem: same output shape and downsampling as the 7x7/s2 stem,
    trains (finite grads) — the MXU-friendly MLPerf stem variant."""
    model = zoo.ResNet18(num_classes=10, space_to_depth=True)
    x = jnp.ones((2, 32, 32, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(0), x, train=False)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 10)
    # conv_init sees 12 channels (2x2 s2d of RGB) with a 4x4 kernel
    k = variables["params"]["conv_init"]["kernel"]
    assert k.shape == (4, 4, 12, 64)
    assert np.isfinite(np.asarray(out)).all()


@pytest.mark.slow
def test_transformer_remat_matches_no_remat():
    """jax.checkpoint on the blocks must not change loss or gradients —
    only the activation-memory/FLOPs trade. Covers composition with the
    flash-attention custom_vjp (checkpoint replays its forward)."""
    import optax

    from horovod_tpu.models import TransformerLM

    tok = jax.random.randint(jax.random.PRNGKey(4), (2, 64), 0, 64)
    kw = dict(vocab=64, dim=32, heads=4, layers=2, dtype=jnp.float32,
              attention="flash")
    plain = TransformerLM(**kw)
    remat = TransformerLM(**kw, remat=True)
    params = plain.init(jax.random.PRNGKey(0), tok)["params"]

    def loss(model, params):
        logits = model.apply({"params": params}, tok)
        targets = jnp.roll(tok, -1, axis=1)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, targets).mean()

    with jax.default_matmul_precision("highest"):
        l0, g0 = jax.value_and_grad(lambda p: loss(plain, p))(params)
        l1, g1 = jax.value_and_grad(lambda p: loss(remat, p))(params)
    np.testing.assert_allclose(float(l1), float(l0), atol=1e-6, rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5), g1, g0)


@pytest.mark.slow
def test_chunked_lm_loss_matches_full():
    """Chunked loss head: identical loss AND gradients to the full-logits
    path (the chunk body is checkpointed; only shapes change)."""
    import optax

    from horovod_tpu.models import TransformerLM
    from horovod_tpu.models.transformer import chunked_lm_loss

    tok = jax.random.randint(jax.random.PRNGKey(4), (2, 64), 0, 64)
    model = TransformerLM(vocab=64, dim=32, heads=4, layers=2,
                          dtype=jnp.float32)
    params = model.init(jax.random.PRNGKey(0), tok)["params"]
    targets = jnp.roll(tok, -1, axis=1)

    def full(params):
        logits = model.apply({"params": params}, tok)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits, targets).mean()

    def chunked(params):
        hidden = model.apply({"params": params}, tok, return_hidden=True)
        return chunked_lm_loss(hidden, params["lm_head"]["kernel"],
                               targets, chunk=16)

    with jax.default_matmul_precision("highest"):
        l0, g0 = jax.value_and_grad(full)(params)
        l1, g1 = jax.value_and_grad(chunked)(params)
    np.testing.assert_allclose(float(l1), float(l0), atol=1e-6, rtol=1e-6)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5), g1, g0)
