"""Test harness: virtual 8-device CPU mesh.

Mirrors the reference CI strategy (SURVEY.md §4): one suite that self-adapts
to the topology it finds. Multi-*device* semantics run on an 8-device virtual
CPU platform (`--xla_force_host_platform_device_count=8`); multi-*process*
eager-engine semantics are tested in-process against the TCP coordinator.

Must run before any jax import in the test process: the environment pins
JAX_PLATFORMS=axon (single real TPU chip), which we override to CPU here —
benches use the real chip, tests use the virtual mesh.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# Persistent XLA compilation cache: the fast tier is dominated by CPU
# compiles of the same jitted steps every run; warm runs skip them. Set via
# env (not jax.config) so SPAWNED WORKER processes (launch_util, runner
# tests, mp_train_script) inherit it too. First run pays full compiles and
# populates the cache under .pytest_cache/.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                 ".pytest_cache", "jax_cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.3")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_collection_modifyitems(items):
    """Every test not marked slow is the fast tier: `-m fast` (or the
    equivalent `-m "not slow"`) is the sub-2-minute developer loop; `-m slow`
    holds the XLA-compile-heavy and multi-minute e2e tests."""
    for item in items:
        if "slow" not in item.keywords:
            item.add_marker(pytest.mark.fast)


@pytest.fixture()
def hvd():
    import horovod_tpu as hvd

    hvd.init()
    yield hvd
    hvd.shutdown()


@pytest.fixture()
def mesh8():
    from horovod_tpu.parallel.mesh import data_parallel_mesh

    assert jax.device_count() == 8, "virtual CPU mesh not active"
    return data_parallel_mesh()


@pytest.fixture()
def mesh_2x4():
    """('dcn','ici') hierarchical mesh: 2 virtual nodes × 4 chips."""
    from horovod_tpu.parallel.mesh import hierarchical_mesh

    return hierarchical_mesh(ici_size=4)
