"""ring_flash correctness: the pallas-fused ring schedule must match the
dense causal oracle — forward AND gradients, contiguous AND zigzag layouts.

The kernels run in interpret mode on the CPU test mesh. Interpret mode
skips Mosaic's block-tiling constraints, so the multi-block tests force
explicit small block sizes to exercise the grid accumulation and per-block
``pl.when`` skips; the TPU BlockSpec layouts themselves (the part
interpret mode cannot check) are guarded by the layout notes in
ring_flash.py and were validated on a real v5e chip at t_local=2560 —
the block_k=320 case that rejects a lane-major kpos layout.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from horovod_tpu.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.ops.ring_attention import (
    causal_reference,
    zigzag_shard,
    zigzag_unshard,
)
from horovod_tpu.ops.ring_flash import ring_flash_attention


def qkv(b=1, t=64, h=2, d=8, seed=0):
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (
        jax.random.normal(k1, (b, t, h, d), jnp.float32),
        jax.random.normal(k2, (b, t, h, d), jnp.float32),
        jax.random.normal(k3, (b, t, h, d), jnp.float32),
    )


@pytest.fixture()
def sp_mesh():
    return Mesh(np.asarray(jax.devices()[:4]), ("sp",))


def _sharded(mesh, fn):
    return shard_map(fn, mesh=mesh, in_specs=P(None, "sp"),
                     out_specs=P(None, "sp"), check_vma=False)


def test_ring_flash_matches_oracle(sp_mesh):
    q, k, v = qkv()
    with jax.default_matmul_precision("highest"):
        ref = causal_reference(q, k, v)
        out = _sharded(sp_mesh, lambda a, b, c: ring_flash_attention(
            a, b, c, "sp"))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.slow
def test_ring_flash_zigzag_matches_oracle(sp_mesh):
    n = sp_mesh.size
    q, k, v = qkv(t=64)
    qz, kz, vz = (zigzag_shard(x, n) for x in (q, k, v))
    with jax.default_matmul_precision("highest"):
        ref = causal_reference(q, k, v)
        out_z = _sharded(sp_mesh, lambda a, b, c: ring_flash_attention(
            a, b, c, "sp", zigzag=True))(qz, kz, vz)
        out = zigzag_unshard(out_z, n)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.slow  # re-tiered r5: multi-process spawn cost; core coverage stays fast
def test_ring_flash_multiblock_matches_oracle(sp_mesh):
    """Explicit small blocks: t_local=16 with block_q=8/block_k=4 gives a
    2x4 grid per ring step — exercises the scratch carry across k-blocks
    and the per-block pl.when skip (single-block runs never enter them)."""
    q, k, v = qkv(t=64)
    with jax.default_matmul_precision("highest"):
        ref = causal_reference(q, k, v)
        out = _sharded(sp_mesh, lambda a, b, c: ring_flash_attention(
            a, b, c, "sp", block_q=8, block_k=4))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


@pytest.mark.slow
def test_ring_flash_multiblock_grads_match_oracle(sp_mesh):
    q, k, v = qkv(t=32)  # t_local=8 with bq=4/bk=2: 2x4 grid per step
    w = jax.random.normal(jax.random.PRNGKey(9), q.shape, jnp.float32)
    ring = _sharded(sp_mesh, lambda a, b, c: ring_flash_attention(
        a, b, c, "sp", block_q=4, block_k=2))
    with jax.default_matmul_precision("highest"):
        g_ring = jax.grad(lambda a, b, c: jnp.sum(ring(a, b, c) * w),
                          argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(lambda a, b, c: jnp.sum(causal_reference(a, b, c) * w),
                         argnums=(0, 1, 2))(q, k, v)
    for got, want, name in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=3e-5, rtol=3e-5,
                                   err_msg=f"d{name} mismatch")


@pytest.mark.slow
def test_ring_flash_zigzag_grads_match_oracle(sp_mesh):
    """dQ accumulates locally, dK/dV ride the ring home — all three must
    equal autodiff through the dense oracle. Zigzag layout: the masking
    must use the true (non-contiguous) global positions in both passes.
    (Contiguous-layout gradients are covered by the multiblock test above
    and the full-model parity test below.)"""
    n = sp_mesh.size
    q, k, v = qkv(t=32)
    w = jax.random.normal(jax.random.PRNGKey(9), q.shape, jnp.float32)
    qz, kz, vz = (zigzag_shard(x, n) for x in (q, k, v))
    wz = zigzag_shard(w, n)

    ring = _sharded(sp_mesh, lambda a, b, c: ring_flash_attention(
        a, b, c, "sp", zigzag=True))
    with jax.default_matmul_precision("highest"):
        g_ring = jax.grad(lambda a, b, c: jnp.sum(ring(a, b, c) * wz),
                          argnums=(0, 1, 2))(qz, kz, vz)
        g_ref = jax.grad(lambda a, b, c: jnp.sum(causal_reference(a, b, c) * w),
                         argnums=(0, 1, 2))(q, k, v)
    for got_z, want, name in zip(g_ring, g_ref, "qkv"):
        got = zigzag_unshard(got_z, n)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=3e-5, rtol=3e-5,
                                   err_msg=f"d{name} mismatch")


@pytest.mark.slow
def test_ring_flash_gqa_matches_replicated_oracle(sp_mesh):
    """GQA through the ring: 4 q heads over 2 kv heads; the ring rotates
    only the small kv blocks and the dK/dV that ride home with them must
    equal the replicated-oracle group sums."""
    hkv, group = 2, 2
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q = jax.random.normal(ks[0], (1, 32, hkv * group, 8), jnp.float32)
    k = jax.random.normal(ks[1], (1, 32, hkv, 8), jnp.float32)
    v = jax.random.normal(ks[2], (1, 32, hkv, 8), jnp.float32)
    w = jax.random.normal(jax.random.PRNGKey(9), q.shape, jnp.float32)

    def rep(x):
        return jnp.repeat(x, group, axis=2)

    ring = _sharded(sp_mesh, lambda a, b, c: ring_flash_attention(a, b, c, "sp"))
    with jax.default_matmul_precision("highest"):
        out = ring(q, k, v)
        ref = causal_reference(q, rep(k), rep(v))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)
        g_ring = jax.grad(lambda a, b, c: jnp.sum(ring(a, b, c) * w),
                          argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(
            lambda a, b, c: jnp.sum(causal_reference(a, rep(b), rep(c)) * w),
            argnums=(0, 1, 2))(q, k, v)
    for got, want, name in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=3e-5, rtol=3e-5,
                                   err_msg=f"d{name} mismatch")


@pytest.mark.slow
def test_transformer_sp_flash_equals_dense(sp_mesh):
    """Full model: sp-sharded forward with ring-FLASH attention == the
    single-device dense forward, same params."""
    from horovod_tpu.models import TransformerLM

    dense = TransformerLM(vocab=64, dim=32, heads=4, layers=2,
                          dtype=jnp.float32)
    sp = TransformerLM(vocab=64, dim=32, heads=4, layers=2,
                       dtype=jnp.float32, sp_axis="sp", attention="flash")
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 64)
    params = dense.init(jax.random.PRNGKey(0), tokens)["params"]

    with jax.default_matmul_precision("highest"):
        ref = dense.apply({"params": params}, tokens)

        def fwd(tokens):
            t_local = tokens.shape[1]
            pos = (jax.lax.axis_index("sp") * t_local + jnp.arange(t_local))[None, :]
            return sp.apply({"params": params}, tokens, pos)

        out = shard_map(fwd, mesh=sp_mesh, in_specs=P(None, "sp"),
                        out_specs=P(None, "sp"), check_vma=False)(tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)
