"""Lifecycle + topology tests (reference: init/rank/size surface of every
binding's test file, e.g. test/test_torch.py TorchTests.test_horovod_rank)."""

import os

import pytest

import horovod_tpu as hvd
from horovod_tpu.common.config import Config
from horovod_tpu.common.topology import Topology, detect


def test_init_idempotent():
    hvd.init()
    hvd.init()  # InitializeHorovodOnce guard (operations.cc:2384-2401)
    assert hvd.is_initialized()
    assert hvd.rank() == 0
    assert hvd.size() == 1
    assert hvd.local_rank() == 0
    assert hvd.local_size() == 1
    hvd.shutdown()
    assert not hvd.is_initialized()


def test_reinit_after_shutdown():
    hvd.init()
    hvd.shutdown()
    hvd.init()  # re-init allowed (operations.cc:2424-2432)
    assert hvd.is_initialized()
    hvd.shutdown()


def test_not_initialized_raises():
    hvd.shutdown()
    with pytest.raises(hvd.NotInitializedError):
        hvd.rank()
    with pytest.raises(hvd.NotInitializedError):
        hvd.size()


def test_mpi_threads_supported_shim():
    hvd.init()
    assert hvd.mpi_threads_supported() is True
    hvd.shutdown()


def test_topology_from_env(monkeypatch):
    monkeypatch.setenv("HOROVOD_RANK", "3")
    monkeypatch.setenv("HOROVOD_SIZE", "8")
    monkeypatch.setenv("HOROVOD_LOCAL_RANK", "1")
    monkeypatch.setenv("HOROVOD_LOCAL_SIZE", "2")
    t = detect()
    assert (t.rank, t.size, t.local_rank, t.local_size) == (3, 8, 1, 2)
    assert t.cross_rank == 1 and t.cross_size == 4


def test_topology_validation():
    with pytest.raises(ValueError):
        Topology(rank=5, size=4, local_rank=0, local_size=1,
                 cross_rank=0, cross_size=1).validate()
    with pytest.raises(ValueError):
        Topology(rank=0, size=4, local_rank=3, local_size=2,
                 cross_rank=0, cross_size=1).validate()


def test_comm_subset():
    # init(comm=[ranks]) — reference horovod_init with ranks[] (operations.cc:2415)
    hvd.init(comm=[0])
    assert hvd.size() == 1 and hvd.rank() == 0
    hvd.shutdown()
    with pytest.raises(ValueError):
        hvd.init(comm="not-a-list")


def test_config_env_parsing(monkeypatch):
    monkeypatch.setenv("HOROVOD_FUSION_THRESHOLD", "1048576")
    monkeypatch.setenv("HOROVOD_CYCLE_TIME", "2.5")
    monkeypatch.setenv("HOROVOD_AUTOTUNE", "1")
    monkeypatch.setenv("HOROVOD_HIERARCHICAL_ALLREDUCE", "1")
    cfg = Config.from_env()
    assert cfg.fusion_threshold == 1048576
    assert cfg.cycle_time_ms == 2.5
    assert cfg.autotune is True
    assert cfg.hierarchical_allreduce is True
    # Pinned vars must not be autotuned (operations.cc:1840-1879 fixed=true)
    assert "HOROVOD_FUSION_THRESHOLD" in cfg.pinned
    assert "HOROVOD_CYCLE_TIME" in cfg.pinned


def test_config_defaults():
    cfg = Config()
    assert cfg.fusion_threshold == 64 * 1024 * 1024  # operations.cc:1838
    assert cfg.cycle_time_ms == 5.0                  # operations.cc:1844
    assert not cfg.autotune


def test_num_chips():
    assert hvd.num_chips() == 8  # virtual mesh
    assert hvd.num_local_devices() == 8


@pytest.mark.slow  # re-tiered r5: multi-process spawn cost; core coverage stays fast
def test_comm_subset_multiprocess():
    """VERDICT r3 item 6: a 4-process world where ranks 0 and 2 form
    comm=[0,2] must run a CORRECT 2-rank allreduce (ranks[0] binds the
    coordinator as the sub-world's rank 0), and non-members must get the
    actionable error instead of silently mis-remapped topology."""
    import sys as _sys
    import textwrap

    import numpy as np

    _sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from launch_util import launch_world

    script = textwrap.dedent("""
        import json, os, sys
        import numpy as np
        sys.path.insert(0, os.environ["HVD_REPO"])
        import horovod_tpu as hvd

        rank = int(os.environ["HOROVOD_RANK"])
        try:
            hvd.init(comm=[0, 2])
        except ValueError as e:
            assert "not a member" in str(e), e
            print(json.dumps({"member": False}))
            sys.exit(0)
        out = hvd.allreduce(np.full(3, float(rank)), name="sub",
                            average=False)
        res = {"member": True, "rank": hvd.rank(), "size": hvd.size(),
               "local_rank": hvd.local_rank(), "sum": out.tolist()}
        hvd.shutdown()
        print(json.dumps(res))
    """)
    outs = [r["out"] for r in launch_world(4, script)]
    # members: original ranks 0,2 -> sub-ranks 0,1; allreduce sums their
    # ORIGINAL rank values 0+2
    members = [o for o in outs if o["member"]]
    assert len(members) == 2
    assert sorted(m["rank"] for m in members) == [0, 1]
    assert all(m["size"] == 2 for m in members)
    assert all(m["local_rank"] == 0 for m in members)  # degenerate host view
    for m in members:
        np.testing.assert_allclose(m["sum"], [2.0, 2.0, 2.0])
    assert sum(not o["member"] for o in outs) == 2


def test_object_collectives_single_process():
    hvd.init()
    try:
        assert hvd.broadcast_object({"a": 1}) == {"a": 1}
        assert hvd.allgather_object("x") == ["x"]
    finally:
        hvd.shutdown()


@pytest.mark.slow  # re-tiered r5: multi-process spawn cost; core coverage stays fast
def test_object_collectives_multiprocess():
    """broadcast_object / allgather_object (post-reference upstream API,
    framework-free here): arbitrary picklable objects of DIFFERENT sizes
    per rank ride the ring."""
    import sys as _sys
    import textwrap

    _sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from launch_util import launch_world

    script = textwrap.dedent("""
        import json, os, sys
        sys.path.insert(0, os.environ["HVD_REPO"])
        import horovod_tpu as hvd

        import threading
        hvd.init()
        r = hvd.rank()
        # non-root objects are ignored BY CONTRACT: even unpicklable ones
        got = hvd.broadcast_object({"cfg": [1, 2, 3], "root": "r0"}
                                   if r == 0 else threading.Lock())
        objs = hvd.allgather_object({"rank": r, "pad": "x" * (10 * (r + 1))})
        hvd.shutdown()
        print(json.dumps({"bcast": got, "ranks": [o["rank"] for o in objs],
                          "lens": [len(o["pad"]) for o in objs]}))
    """)
    for res in launch_world(3, script):
        out = res["out"]
        assert out["bcast"] == {"cfg": [1, 2, 3], "root": "r0"}
        assert out["ranks"] == [0, 1, 2]
        assert out["lens"] == [10, 20, 30]
