"""On-the-wire gradient compression tests (ISSUE 5, docs/compression.md).

Unit tier: wire-dtype resolution, config parsing, the canonical oracle's
hop quantization, enqueue-time quantization and error-feedback residuals.
Protocol tier: cache-bit invalidation when a tensor's wire dtype changes.
System tier (spawned worlds via launch_util): ring==star bitwise identity
under a bf16 wire, wire-byte counters proving the >= 2x reduction,
compression=none staying bitwise identical to the uncompressed baseline,
and native-vs-eager agreement. Compiled tier (mesh8): per-bucket opt-outs,
trace-time wire gauges, and the autotuner's third search dimension.
"""

import threading

import numpy as np
import pytest

from horovod_tpu.common.config import Config
from horovod_tpu.common.engine import (
    PyEngine,
    _Client,
    _Coordinator,
    _ring_order_reduce,
)
from horovod_tpu.common.topology import Topology
from horovod_tpu.compression import (
    Compression,
    compiled_formats,
    compression_name,
    numpy_dtype_by_name,
    numpy_wire_dtype,
    parse_spec,
    topk_densify,
    topk_eligible,
    topk_encode,
    topk_k,
    topk_merge,
    topk_pack,
    topk_pack_dense,
    topk_ratio_from_env,
    topk_select,
    topk_sparsify,
    topk_state_add,
    topk_state_dense,
    topk_state_scale,
    topk_state_slice,
    topk_unpack,
)

from launch_util import launch_world


def _bf16():
    import ml_dtypes

    return np.dtype(ml_dtypes.bfloat16)


def _engine(compression="none", error_feedback=False):
    return PyEngine(
        Topology(0, 1, 0, 1, 0, 1),
        Config(cycle_time_ms=1.0, stall_check_disable=True,
               compression=compression,
               compression_error_feedback=error_feedback))


# ------------------------------------------------------------------ unit tier

def test_wire_dtype_resolution_matrix():
    bf16 = _bf16()
    assert numpy_wire_dtype("none", np.float32) is None
    assert numpy_wire_dtype("bf16", np.float32) == bf16
    assert numpy_wire_dtype("bf16", np.float64) == bf16
    assert numpy_wire_dtype("fp16", np.float32) == np.float16
    # Non-floats and types already at/below wire width opt out.
    assert numpy_wire_dtype("bf16", np.int32) is None
    assert numpy_wire_dtype("bf16", bf16) is None
    assert numpy_wire_dtype("fp16", np.float16) is None
    # Unknown names degrade to none, never raise.
    assert numpy_wire_dtype("gzip", np.float32) is None
    assert numpy_dtype_by_name("bfloat16") == bf16
    assert compression_name(Compression.bf16) == "bf16"
    assert compression_name(None) == "none"
    assert Compression.by_name("fp16") is Compression.fp16


def test_config_parses_compression_env(monkeypatch):
    monkeypatch.setenv("HOROVOD_COMPRESSION", "bf16")
    monkeypatch.setenv("HOROVOD_COMPRESSION_ERROR_FEEDBACK", "1")
    cfg = Config.from_env()
    assert cfg.compression == "bf16"
    assert cfg.compression_error_feedback
    # Directly-constructed Config (the test/bench idiom) honors the env too.
    assert Config(cycle_time_ms=2.0).compression == "bf16"
    monkeypatch.setenv("HOROVOD_COMPRESSION", "lz4")  # unknown -> none
    assert Config.from_env().compression == "none"
    monkeypatch.delenv("HOROVOD_COMPRESSION")
    assert Config.from_env().compression == "none"


def test_oracle_wire_quantization_properties():
    bf16 = _bf16()
    rng = np.random.default_rng(7)
    arrs = [rng.standard_normal(1003).astype(np.float32) for _ in range(4)]
    pre = [a.astype(bf16).astype(np.float32) for a in arrs]  # enqueue cast
    exact = _ring_order_reduce(arrs, True)
    comp = _ring_order_reduce(pre, True, wire_dtype=bf16)
    # Deterministic, wire-representable everywhere (the allgather hop's
    # final rounding), and within 16-bit tolerance of the exact average.
    np.testing.assert_array_equal(
        comp, _ring_order_reduce(pre, True, wire_dtype=bf16))
    np.testing.assert_array_equal(comp, comp.astype(bf16).astype(np.float32))
    assert np.abs(comp - exact).max() / np.abs(exact).max() < 0.05
    # wire_dtype=None is byte-for-byte the historical reduction.
    np.testing.assert_array_equal(exact, _ring_order_reduce(arrs, True))


def test_none_passthrough_bitwise():
    eng = _engine("none")
    try:
        x = np.arange(64, dtype=np.float32) / 7
        np.testing.assert_array_equal(eng.run("allreduce", x, "t"), x)
    finally:
        eng.shutdown()


def test_single_proc_bf16_quantizes_once():
    bf16 = _bf16()
    eng = _engine("bf16")
    try:
        x = np.arange(64, dtype=np.float32) / 7
        out = eng.run("allreduce", x, "t")
        np.testing.assert_array_equal(out, x.astype(bf16).astype(np.float32))
        # Integer tensors pass through untouched.
        i = np.arange(8, dtype=np.int64)
        np.testing.assert_array_equal(eng.run("allreduce", i, "i"), i)
    finally:
        eng.shutdown()


def test_error_feedback_residual_carries_across_steps():
    bf16 = _bf16()
    eng = _engine("bf16", error_feedback=True)
    try:
        x = np.arange(64, dtype=np.float32) / 7
        o1 = eng.run("allreduce", x, "g")
        r1 = eng._residuals["g"].copy()
        # The residual is exactly the quantization error of this step...
        np.testing.assert_allclose(o1 + r1, x, atol=0)
        assert np.abs(r1).max() > 0
        # ...and it folds into the NEXT submission of the same name.
        o2 = eng.run("allreduce", x, "g")
        np.testing.assert_array_equal(
            o2, (x + r1).astype(bf16).astype(np.float32))
        # Flush (the elastic-reset path) drops residuals.
        eng.cache_flush()
        assert not eng._residuals
    finally:
        eng.shutdown()


def test_error_feedback_mlp_converges_within_tolerance():
    """A small model trained with fp16-wire gradients + error feedback ends
    within tolerance of the uncompressed run (the Deep Gradient Compression
    claim, scaled down): same data, same init, same steps."""
    rng = np.random.default_rng(3)
    X = rng.standard_normal((64, 8)).astype(np.float32)
    w_true = rng.standard_normal(8).astype(np.float32)
    y = X @ w_true

    def train(compression, error_feedback):
        eng = _engine(compression, error_feedback)
        try:
            w = np.zeros(8, dtype=np.float32)
            for step in range(150):
                grad = (2.0 / len(X)) * X.T @ (X @ w - y)
                g = eng.run("allreduce", grad.astype(np.float32),
                            "grad.w")
                w = w - 0.05 * g
            return float(np.mean((X @ w - y) ** 2))
        finally:
            eng.shutdown()

    base = train("none", False)
    ef = train("fp16", True)
    assert ef <= max(base * 1.5, base + 1e-4), (base, ef)


# -------------------------------------------------------------- protocol tier

KEY = b"test-secret"


def _run_ranks(world, fn):
    coord = _Coordinator(world, "127.0.0.1", 0, key=KEY, cache_capacity=64)
    port = coord.server.getsockname()[1]
    coord.start()
    results, errors = {}, []

    def worker(rank):
        try:
            client = _Client("127.0.0.1", port, rank, key=KEY)
            try:
                results[rank] = fn(rank, client)
            finally:
                client.close()
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(r,))
               for r in range(world)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    coord.stop()
    assert not errors, errors
    return results


def _exchange_until(client, reqs, arrays, name, polls=300):
    """Exchange + metadata-only re-polls until ``name``'s result arrives
    (the coordinator never blocks an exchange on a straggling peer; real
    ranks re-poll exactly like this). Returns (result, assigns, evicts)
    with the announcements accumulated across the polls."""
    import time

    out = client.exchange(reqs, arrays)
    assign, evict = list(client.last_cache[0]), list(client.last_cache[1])
    for _ in range(polls):
        if name in out:
            return out[name], assign, evict
        time.sleep(0.01)
        out = client.exchange(reqs, {})
        assign += list(client.last_cache[0])
        evict += list(client.last_cache[1])
    raise AssertionError(f"result for {name} never delivered")


def test_wire_dtype_change_invalidates_cache_bit():
    """A full request for a name bound under a DIFFERENT wire dtype evicts
    the stale bit everywhere — the compression analog of shape-change
    invalidation (a bit must never resolve to the wrong wire format)."""
    bf16 = _bf16()

    def fn(rank, client):
        req = {"name": "g", "op": "allreduce", "shape": (4,),
               "dtype": "float32", "root": 0, "average": True}
        _, assign0, _ = _exchange_until(
            client, [req], {"g": np.ones(4, np.float32)}, "g")
        bit0 = assign0[0][0]
        # Barrier before the wire phase: a rank that raced ahead into the
        # wire request would evict the bit before the slow rank CLAIMED its
        # phase-1 result, and the pending announcement legitimately drops
        # (the mirror would just miss and self-heal) — the test needs both
        # ranks to hold bit0 first.
        _exchange_until(client, [dict(req, name="sync")],
                        {"sync": np.ones(4, np.float32)}, "sync")
        wire_req = dict(req, wire="bfloat16")
        res, assign, evict = _exchange_until(
            client, [wire_req], {"g": np.ones(4, bf16)}, "g")
        return bit0, assign, evict, res

    results = _run_ranks(2, fn)
    for rank in range(2):
        bit0, assign, evict, (err, value) = results[rank]
        assert bit0 in evict, "stale bit survived the wire-dtype change"
        assert assign and assign[0][0] != bit0
        assert err is None
        # Compressed star results travel at wire width, upcast by the rank.
        assert isinstance(value, dict) and "__wire__" in value
        assert value["__wire__"].dtype == bf16


def test_mismatched_wire_compression_is_an_error():
    """Half the world compressing and half not must produce a delivered
    error, not a deadlock or silent corruption."""

    def fn(rank, client):
        req = {"name": "g", "op": "allreduce", "shape": (4,),
               "dtype": "float32", "root": 0, "average": True}
        if rank == 1:
            req["wire"] = "bfloat16"
            arr = np.ones(4, _bf16())
        else:
            arr = np.ones(4, np.float32)
        res, _, _ = _exchange_until(client, [req], {"g": arr}, "g")
        return res

    results = _run_ranks(2, fn)
    for rank in range(2):
        err, _ = results[rank]
        assert err and "wire compression" in err


# -------------------------------------------------------------- system tier

COMPRESSION_WORKER = r"""
import hashlib, json, os, sys
sys.path.insert(0, os.environ["HVD_REPO"])
import numpy as np
from horovod_tpu.common.config import Config
from horovod_tpu.common.engine import PyEngine
from horovod_tpu.common.topology import Topology
from horovod_tpu import metrics as hvd_metrics

rank = int(os.environ["HOROVOD_RANK"]); world = int(os.environ["HOROVOD_SIZE"])
eng = PyEngine(Topology(rank, world, 0, 1, rank, world),
               Config(cycle_time_ms=1.0, stall_check_disable=True))
try:
    digest = hashlib.sha256()
    max_rel_err = 0.0
    for i in range(5):
        for t in range(4):
            out = eng.run("allreduce",
                          np.arange(613, dtype=np.float32) * (rank + 1) + i + t,
                          f"grad.{t}")
            digest.update(out.tobytes())
            exp = (np.arange(613, dtype=np.float64) * (world + 1) / 2.0
                   + i + t)
            max_rel_err = max(max_rel_err, float(
                np.abs(out.astype(np.float64) - exp).max()
                / np.abs(exp).max()))
    snap = hvd_metrics.registry().snapshot()["counters"]
    stats = eng.cache_stats()
    print(json.dumps({
        "rank": rank, "hash": digest.hexdigest(),
        "ring_active": stats["ring_active"],
        "compression": stats["compression"],
        "max_rel_err": max_rel_err,
        "wire_bytes": snap.get('horovod_wire_bytes_total{plane="eager"}', 0),
        "wire_saved": snap.get(
            'horovod_wire_bytes_saved_total{plane="eager"}', 0),
    }))
finally:
    eng.shutdown()
"""


@pytest.mark.engine
def test_ring_vs_star_bitwise_identical_bf16_4proc():
    """The tentpole contract under compression: both data planes produce
    BITWISE-identical results with a bf16 wire, the wire counters show the
    >= 2x byte reduction, results stay inside 16-bit tolerance, and the
    uncompressed world is untouched (different hash, zero wire bytes)."""
    ring = launch_world(4, COMPRESSION_WORKER,
                        extra_env={"HOROVOD_RING_DATA_PLANE": "1",
                                   "HOROVOD_COMPRESSION": "bf16"})
    star = launch_world(4, COMPRESSION_WORKER,
                        extra_env={"HOROVOD_RING_DATA_PLANE": "0",
                                   "HOROVOD_COMPRESSION": "bf16"})
    plain = launch_world(4, COMPRESSION_WORKER,
                         extra_env={"HOROVOD_RING_DATA_PLANE": "1",
                                    "HOROVOD_COMPRESSION": "none"})
    ring_hashes = {r["out"]["hash"] for r in ring}
    assert len(ring_hashes) == 1, "bf16 ring ranks disagree"
    assert ring_hashes == {r["out"]["hash"] for r in star}, (
        "bf16 ring and star disagree bitwise")
    assert ring_hashes != {r["out"]["hash"] for r in plain}, (
        "bf16 world produced the uncompressed hash (wire cast inert)")
    for r in ring:
        o = r["out"]
        assert o["ring_active"] and o["compression"] == "bf16"
        assert o["wire_bytes"] > 0
        assert (o["wire_bytes"] + o["wire_saved"]) / o["wire_bytes"] >= 2.0
        assert o["max_rel_err"] < 0.02
    for r in plain:
        o = r["out"]
        assert o["wire_bytes"] == 0 and o["wire_saved"] == 0
        assert o["max_rel_err"] < 1e-6  # none = the exact f64 reduction


# ---------------------------------------------------------------- native tier

@pytest.fixture(scope="module")
def native():
    from horovod_tpu.cc import lib_path

    lib_path()  # build if needed
    from horovod_tpu.cc.native_engine import NativeEngine

    return NativeEngine


def test_native_single_proc_bf16_matches_eager(native, monkeypatch):
    """Both engines quantize the contribution once at enqueue, so the
    single-process result is U(Q(x)) bitwise in both (the C++ float_to_bf16
    and ml_dtypes both round to nearest even)."""
    # monkeypatch (not Config alone): NativeEngine exports the compression
    # knob into os.environ for the C++ side; registering the key here makes
    # pytest restore it, so later tests' spawned worlds don't inherit bf16.
    monkeypatch.setenv("HOROVOD_COMPRESSION", "bf16")
    eng = native(Topology(0, 1, 0, 1, 0, 1),
                 Config(cycle_time_ms=1.0, stall_check_disable=True,
                        compression="bf16"))
    try:
        assert eng.wire_dtype() == "bfloat16"
        x = (np.arange(257, dtype=np.float32) - 128) / 7
        out = eng.run("allreduce", x, "t")
        assert out.dtype == np.float32
        np.testing.assert_array_equal(
            out, x.astype(_bf16()).astype(np.float32))
        m = eng.metrics()
        assert m["wire_bytes"] == 2 * 257
        assert m["wire_bytes_saved"] == 2 * 257
    finally:
        eng.shutdown()


NATIVE_WORKER = r"""
import json, os, sys
sys.path.insert(0, os.environ["HVD_REPO"])
import numpy as np
from horovod_tpu.common.config import Config
from horovod_tpu.cc.native_engine import NativeEngine
from horovod_tpu.common.topology import Topology

rank = int(os.environ["HOROVOD_RANK"]); world = int(os.environ["HOROVOD_SIZE"])
eng = NativeEngine(Topology(rank, world, 0, 1, rank, world),
                   Config(cycle_time_ms=1.0, stall_check_disable=True))
try:
    outs = []
    for t in range(3):
        out = eng.run("allreduce",
                      np.arange(613, dtype=np.float32) * (rank + 1) + t,
                      f"grad.{t}")
        outs.append(out)
    m = eng.metrics()
    print(json.dumps({
        "rank": rank,
        "out": [o.tolist() for o in outs],
        "wire_bytes": m["wire_bytes"],
    }))
finally:
    eng.shutdown()
"""


@pytest.mark.engine
def test_native_vs_eager_bf16_agreement_3proc(native):
    """Cross-engine agreement under a bf16 wire: the native ring (bf16
    buffers, f32 adds per hop) against the Python engines' canonical
    oracle (_ring_order_reduce with per-hop bf16 rounding) on the same
    inputs. The two pipelines round at the same points and differ only in
    the final divide's intermediate width, so they agree to ~1 bf16 ulp."""
    import json

    nat = launch_world(3, NATIVE_WORKER,
                       extra_env={"HOROVOD_COMPRESSION": "bf16"})
    assert len({json.dumps(r["out"]["out"]) for r in nat}) == 1
    for r in nat:
        assert r["out"]["wire_bytes"] > 0
    bf16 = _bf16()
    for t in range(3):
        arrs = [np.arange(613, dtype=np.float32) * (rank + 1) + t
                for rank in range(3)]
        pre = [a.astype(bf16).astype(np.float32) for a in arrs]
        oracle = _ring_order_reduce(pre, True, wire_dtype=bf16)
        nat_t = np.asarray(nat[0]["out"]["out"][t], dtype=np.float32)
        np.testing.assert_allclose(nat_t, oracle, rtol=0.01, atol=0.02)


# -------------------------------------------------------------- compiled tier

def test_compiled_bucket_optout_and_tolerance(mesh8):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from horovod_tpu import metrics as hvd_metrics
    from horovod_tpu.compat import shard_map
    from horovod_tpu.parallel import fusion

    tree = {"a": jnp.arange(4096, dtype=jnp.float32) / 100,
            "b": jnp.ones((64, 64), jnp.float32) * 0.3,
            "i": jnp.arange(2048, dtype=jnp.int32),
            "tiny": jnp.ones((4,), jnp.float32)}

    def run(compression):
        f = lambda t: fusion.fused_allreduce(  # noqa: E731
            t, "hvd", threshold=1 << 20, compression=compression)
        return jax.jit(shard_map(f, mesh=mesh8, in_specs=(P(),),
                                 out_specs=P(), check_vma=False))(tree)

    out = run("bf16")
    comp_name, buckets = hvd_metrics.last_wire_plan()
    assert comp_name == "bf16"
    # The big f32 bucket compresses; the int bucket and the tiny (<
    # HOROVOD_COMPRESSION_MIN_BYTES) bucket opt out.
    assert any(c for _, c, _ in buckets)
    assert not all(c for _, c, _ in buckets)
    gauges = hvd_metrics.registry().snapshot()["gauges"]
    assert gauges["horovod_compiled_wire_bytes_saved_per_step"] > 0
    assert gauges["horovod_compiled_wire_buckets"] >= 1
    exact = run(None)  # env unset -> none
    for k in tree:
        a, b = np.asarray(out[k]), np.asarray(exact[k])
        if k in ("i", "tiny"):
            np.testing.assert_array_equal(a, b)  # opted out: bitwise
        else:
            np.testing.assert_allclose(a, b, rtol=0.02, atol=1e-3)
    # compression="none" is bitwise the uncompressed path.
    out_none = run("none")
    for k in tree:
        np.testing.assert_array_equal(np.asarray(out_none[k]),
                                      np.asarray(exact[k]))


def test_autotune_compression_third_dimension():
    """tune(compressions=...) explores the wire dtype as a categorical
    third dimension: the factory receives compression=, every grid point is
    covered, and the winning config reports it."""
    from horovod_tpu.jax.autotune import tune

    calls = []

    def factory(fusion_threshold, num_buckets, compression):
        calls.append((fusion_threshold, num_buckets, compression))
        # bf16 "wins" at 4 buckets: the best config must carry all three.
        rate = {("none", 1): 1.0, ("none", 4): 1.2,
                ("bf16", 1): 1.1, ("bf16", 4): 2.0}[(compression,
                                                     num_buckets)]

        import time as _t

        def run():
            _t.sleep(0.001 / rate)
        return run

    report = tune(factory, thresholds=(1 << 20, 4 << 20),
                  num_buckets=(1, 4), compressions=("none", "bf16"),
                  warmup=0, iters=2, reps=1, gp_rounds=0)
    assert {c for _, _, c in calls} == {"none", "bf16"}
    assert len(calls) == 2 * 2 * 2  # thresholds x buckets x compressions
    assert report.best.compression == "bf16"
    assert report.best.num_buckets == 4
    assert report.best.config["compression"] == "bf16"
    assert "compression" in report.knob_curve()


# ---------------------------------------------------------- topk unit tier
# Sparse top-k wire format (ISSUE 9, docs/compression.md).

def test_topk_spec_and_eligibility():
    assert parse_spec("topk") == ("topk", None)
    assert parse_spec("topk@0.05") == ("topk", 0.05)
    assert parse_spec("topk@bogus") == ("none", None)
    assert parse_spec("adaptive") == ("adaptive", None)
    assert compression_name(Compression.topk) == "topk"
    assert Compression.by_name("topk@0.02") is Compression.topk
    assert Compression.by_name("adaptive") is Compression.adaptive
    # topk/adaptive are NOT dtype casts: no wire dtype resolves.
    assert numpy_wire_dtype("topk", np.float32) is None
    assert numpy_wire_dtype("adaptive", np.float32) is None
    # The compiled plane's substitution table.
    assert compiled_formats("adaptive") == ("none", "bf16")
    assert compiled_formats("topk") == ("none", "none")
    assert compiled_formats("bf16") == ("bf16", "bf16")
    # Eligibility: f32 only, floor HOROVOD_COMPRESSION_MIN_BYTES, and
    # sparse must beat dense (ratio bound).
    assert topk_eligible(np.float32, 1 << 20, 0.01, 4096)
    assert not topk_eligible(np.float64, 1 << 20, 0.01, 4096)
    assert not topk_eligible(np.float32, 1024, 0.01, 4096)
    assert not topk_eligible(np.float32, 1 << 20, 0.9, 4096)
    assert topk_k(1000, 0.01) == 10
    assert topk_k(10, 0.001) == 1  # floor: k >= 1


def test_topk_ratio_env(monkeypatch):
    monkeypatch.delenv("HOROVOD_TOPK_RATIO", raising=False)
    assert topk_ratio_from_env() == 0.01
    monkeypatch.setenv("HOROVOD_TOPK_RATIO", "0.05")
    assert topk_ratio_from_env() == 0.05
    monkeypatch.setenv("HOROVOD_TOPK_RATIO", "0.9")  # clamp: > 0.5 never pays
    assert topk_ratio_from_env() == 0.5
    monkeypatch.setenv("HOROVOD_TOPK_RATIO", "junk")
    assert topk_ratio_from_env() == 0.01
    monkeypatch.setenv("HOROVOD_TOPK_RATIO", "-1")
    assert topk_ratio_from_env() == 0.01


def test_topk_select_deterministic_and_zero_free():
    x = np.array([0.0, -3.0, 2.0, -2.0, 0.5, -0.0, 3.0], np.float32)
    idx, val = topk_select(x, 4)
    # Magnitude descending with lower-index tie-break: |−3|=|3| picks
    # index 1 first; |2|=|−2| picks index 2 first. Output index-ascending.
    np.testing.assert_array_equal(idx, [1, 2, 3, 6])
    np.testing.assert_array_equal(val, x[[1, 2, 3, 6]])
    # Exact zeros (and -0.0) are never selected, even when k exceeds the
    # nonzero count — the empty-k edge collapses to the nonzero support.
    idx, val = topk_select(np.zeros(8, np.float32), 4)
    assert idx.size == 0 and val.size == 0
    i2, v2 = topk_select(x, 100)
    assert 0 not in i2 and 5 not in i2 and len(i2) == 5
    # Deterministic: same input, same selection.
    rng = np.random.default_rng(1)
    big = rng.standard_normal(10000).astype(np.float32)
    a = topk_select(big, 100)
    b = topk_select(big.copy(), 100)
    np.testing.assert_array_equal(a[0], b[0])


def test_topk_pack_unpack_roundtrip_and_validation():
    rng = np.random.default_rng(2)
    x = rng.standard_normal(500).astype(np.float32)
    idx, val = topk_select(x, 20)
    kind, i2, v2 = topk_unpack(topk_pack(idx, val), 500)
    assert kind == "sparse"
    np.testing.assert_array_equal(i2, idx)
    np.testing.assert_array_equal(v2, val)
    kind, arr = topk_unpack(topk_pack_dense(x), 500)
    assert kind == "dense"
    np.testing.assert_array_equal(arr, x)
    # Empty sparse frame (the all-zero tensor) roundtrips.
    e = np.array([], np.int32)
    kind, i0, v0 = topk_unpack(topk_pack(e, e.astype(np.float32)), 500)
    assert kind == "sparse" and i0.size == 0 and v0.size == 0
    # Corrupt/inconsistent frames fail loudly, never scatter blindly.
    with pytest.raises(ValueError):
        topk_unpack(topk_pack(idx, val), 10)        # k > n
    with pytest.raises(ValueError):
        topk_unpack(topk_pack_dense(x), 400)        # wrong dense length
    with pytest.raises(ValueError):
        topk_unpack(np.array([7], np.uint8), 4)     # unknown kind
    bad = topk_pack(np.array([3, 2], np.int32), np.ones(2, np.float32))
    with pytest.raises(ValueError):
        topk_unpack(bad, 500)                        # non-ascending indices


def test_topk_merge_overflow_and_state_ops():
    n = 100
    i1 = np.array([1, 5, 9], np.int32)
    v1 = np.array([1.0, 2.0, 3.0], np.float32)
    i2 = np.array([5, 50], np.int32)
    v2 = np.array([10.0, 20.0], np.float32)
    st = topk_merge(i1, v1, i2, v2, n)
    assert st[0] == "sparse"
    np.testing.assert_array_equal(st[1], [1, 5, 9, 50])
    np.testing.assert_array_equal(st[2], [1.0, 12.0, 3.0, 20.0])
    # Densify-on-overflow: past max_nnz the merge returns dense — with the
    # identical values.
    dense_st = topk_merge(i1, v1, i2, v2, n, max_nnz=3)
    assert dense_st[0] == "dense"
    np.testing.assert_array_equal(
        dense_st[1], topk_state_dense(st, n))
    # state_add into a dense accumulator == dense elementwise add.
    st2 = topk_state_add(dense_st, i1, v1, n)
    assert st2[0] == "dense"
    np.testing.assert_array_equal(
        st2[1], dense_st[1] + topk_densify(i1, v1, n))
    # Empty merges are no-ops either way around.
    e = np.array([], np.int32)
    ev = np.array([], np.float32)
    assert topk_merge(e, ev, e, ev, n)[1].size == 0
    np.testing.assert_array_equal(
        topk_state_dense(topk_merge(e, ev, i1, v1, n), n),
        topk_densify(i1, v1, n))
    # Slice re-bases indices; scale divides values only (zeros stay +0.0).
    sl = topk_state_slice(st, 4, 60)
    np.testing.assert_array_equal(
        topk_state_dense(sl, 56), topk_state_dense(st, n)[4:60])
    sc = topk_state_scale(st, 4)
    np.testing.assert_array_equal(sc[2], st[2] / 4)
    # Encode: sparse when preferred and smaller; dense states re-sparsify
    # when the next tier prefers sparse (value-neutral either way).
    assert int(topk_encode(st, n, True)[0]) == 0
    assert int(topk_encode(st, n, False)[0]) == 1
    assert int(topk_encode(dense_st, n, True)[0]) == 0
    for frame, prefer in ((topk_encode(dense_st, n, True), True),
                          (topk_encode(st, n, False), False)):
        np.testing.assert_array_equal(
            topk_state_dense(topk_unpack(frame, n), n),
            topk_state_dense(st, n))


def test_oracle_topk_sentinel_is_pure_f32_fold():
    """_ring_order_reduce(..., wire_dtype='topk') = the f32 ring-order fold
    with no per-hop rounding — the canonical order the index-merging
    planes reproduce. Sparse merges (which skip the zero terms) must be
    bitwise identical to this dense fold."""
    rng = np.random.default_rng(5)
    n, world, k = 4001, 4, 40
    denses = []
    for r in range(world):
        idx, val = topk_select(rng.standard_normal(n).astype(np.float32), k)
        denses.append(topk_densify(idx, val, n))
    out = _ring_order_reduce(denses, True, wire_dtype="topk")
    ref = _ring_order_reduce(denses, True, wire_dtype=np.float32)
    np.testing.assert_array_equal(out, ref)
    # Replay the ring's sparse chunk merges and compare bitwise.
    from horovod_tpu.common.engine import _chunk_bounds

    bounds = _chunk_bounds(n, world)
    for c in range(world):
        lo, hi = bounds[c], bounds[c + 1]
        start = (c + 1) % world
        st = ("sparse", *topk_sparsify(denses[start][lo:hi]))
        for j in range(1, world):
            st = topk_state_add(
                st, *topk_sparsify(denses[(start + j) % world][lo:hi]),
                hi - lo)
        st = topk_state_scale(st, world)
        np.testing.assert_array_equal(
            topk_state_dense(st, hi - lo), out[lo:hi])
    # Grid sentinel: (1, world) degenerates to the flat order.
    np.testing.assert_array_equal(
        _ring_order_reduce(denses, True, wire_dtype="topk",
                           grid=(1, world)), out)


def test_single_proc_topk_selects_and_residual(monkeypatch):
    monkeypatch.delenv("HOROVOD_COMPRESSION_ERROR_FEEDBACK", raising=False)
    monkeypatch.delenv("HOROVOD_TOPK_RATIO", raising=False)
    eng = _engine("topk")
    try:
        x = ((np.arange(8192, dtype=np.float32) - 4096) / 7)
        out = eng.run("allreduce", x, "g")
        # topk@1% keeps exactly 82 entries; the rest is the residual
        # (error feedback defaults ON for topk — dropping 99% of the mass
        # without it is a bias, not a compression).
        assert int((out != 0).sum()) == topk_k(8192, 0.01)
        res = eng._residuals["g"]
        np.testing.assert_array_equal(out + res, x)
        # The residual folds into the NEXT submission of the same name.
        out2 = eng.run("allreduce", x, "g")
        assert int((out2 != 0).sum()) == topk_k(8192, 0.01)
        assert not np.array_equal(out, out2)
        # Flush (elastic reset) drops residuals.
        eng.cache_flush()
        assert not eng._residuals
        # Sub-floor and non-f32 tensors ship dense, untouched.
        tiny = np.ones(16, np.float32)
        np.testing.assert_array_equal(eng.run("allreduce", tiny, "t"), tiny)
        wide = np.arange(8192, dtype=np.float64)
        np.testing.assert_array_equal(eng.run("allreduce", wide, "w"), wide)
    finally:
        eng.shutdown()


def test_single_proc_topk_error_feedback_opt_out(monkeypatch):
    monkeypatch.setenv("HOROVOD_COMPRESSION_ERROR_FEEDBACK", "0")
    eng = _engine("topk")
    try:
        x = (np.arange(8192, dtype=np.float32) - 4096) / 7
        eng.run("allreduce", x, "g")
        assert "g" not in eng._residuals  # explicit opt-out honored
    finally:
        eng.shutdown()


def test_topk_error_feedback_linear_model_converges():
    """The DGC claim, scaled down: a linear model trained with topk@5%
    gradients + error feedback lands within tolerance of the uncompressed
    run — the un-sent 95% of the mass arrives over subsequent steps via
    the residual, so convergence is delayed, not lost."""
    rng = np.random.default_rng(9)
    X = rng.standard_normal((128, 64)).astype(np.float32)
    w_true = rng.standard_normal(64).astype(np.float32)
    y = X @ w_true

    def train(compression, ratio=None, steps=400):
        if ratio is not None:
            import os

            os.environ["HOROVOD_TOPK_RATIO"] = str(ratio)
        try:
            eng = _engine(compression)
            try:
                w = np.zeros(64, dtype=np.float32)
                for _ in range(steps):
                    grad = (2.0 / len(X)) * X.T @ (X @ w - y)
                    g = eng.run("allreduce", grad.astype(np.float32),
                                "grad.w")
                    w = w - 0.05 * g
                return float(np.mean((X @ w - y) ** 2))
            finally:
                eng.shutdown()
        finally:
            if ratio is not None:
                os.environ.pop("HOROVOD_TOPK_RATIO", None)

    base = train("none")
    sparse = train("topk", ratio=0.05)
    assert sparse <= max(base * 1.5, base + 1e-2), (base, sparse)


# ----------------------------------------------------- topk protocol tier

def test_topk_policy_flip_invalidates_cache_bit():
    """A full request for a name bound under a different wire format
    ('topk' vs dense) evicts the stale bit everywhere — a policy flip
    invalidates like a shape change (the ISSUE 9 cache-protocol clause)."""
    def fn(rank, client):
        req = {"name": "g", "op": "allreduce", "shape": (512,),
               "dtype": "float32", "root": 0, "average": True}
        _, assign0, _ = _exchange_until(
            client, [req], {"g": np.ones(512, np.float32)}, "g")
        bit0 = assign0[0][0]
        _exchange_until(client, [dict(req, name="sync")],
                        {"sync": np.ones(512, np.float32)}, "sync")
        idx, val = topk_select(np.arange(512, dtype=np.float32), 5)
        wire_req = dict(req, wire="topk")
        res, assign, evict = _exchange_until(
            client, [wire_req], {"g": topk_pack(idx, val)}, "g")
        return bit0, assign, evict, res

    results = _run_ranks(2, fn)
    # Both ranks shipped the identical selection, so the average equals it
    # ((v + v) / 2 is exact in f32).
    idx, val = topk_select(np.arange(512, dtype=np.float32), 5)
    expected = topk_densify(idx, val, 512)
    for rank in range(2):
        bit0, assign, evict, (err, value) = results[rank]
        assert bit0 in evict, "stale bit survived the topk policy flip"
        assert assign and assign[0][0] != bit0
        assert err is None
        # Sparse star results travel as packed frames with the shape tag.
        assert isinstance(value, dict) and value.get("fmt") == "topk"
        st = topk_unpack(value["__wire__"], 512)
        np.testing.assert_array_equal(topk_state_dense(st, 512), expected)


def test_mismatched_topk_vs_dense_is_an_error():
    """Half the world sparsifying and half not must produce a delivered
    error, not a deadlock (the existing wire-mismatch validation covers
    the topk tag too)."""
    def fn(rank, client):
        req = {"name": "g", "op": "allreduce", "shape": (512,),
               "dtype": "float32", "root": 0, "average": True}
        if rank == 1:
            req["wire"] = "topk"
            idx, val = topk_select(np.ones(512, np.float32), 5)
            arr = topk_pack(idx, val)
        else:
            arr = np.ones(512, np.float32)
        res, _, _ = _exchange_until(client, [req], {"g": arr}, "g")
        return res

    results = _run_ranks(2, fn)
    for rank in range(2):
        err, _ = results[rank]
        assert err and "wire compression" in err


# ------------------------------------------------------- topk system tier

TOPK_WORKER = r"""
import hashlib, json, os, sys
sys.path.insert(0, os.environ["HVD_REPO"])
import numpy as np
from horovod_tpu.common.config import Config
from horovod_tpu.common.engine import PyEngine
from horovod_tpu.common.topology import Topology
from horovod_tpu import metrics as hvd_metrics

rank = int(os.environ["HOROVOD_RANK"]); world = int(os.environ["HOROVOD_SIZE"])
L = int(os.environ.get("TOPK_LOCAL_SIZE", "1"))
hier = os.environ.get("TOPK_HIER", "0") == "1"
topo = (Topology(rank, world, rank % L, L, rank // L, world // L) if L > 1
        else Topology(rank, world, 0, 1, rank, world))
eng = PyEngine(topo, Config(cycle_time_ms=1.0, stall_check_disable=True,
                            hierarchical_allreduce=hier))
try:
    digest = hashlib.sha256()
    rng = np.random.default_rng(100 + rank)
    for i in range(3):
        for t in range(2):
            x = rng.standard_normal(20000).astype(np.float32)
            out = eng.run("allreduce", x, f"grad.{t}")
            digest.update(out.tobytes())
    snap = hvd_metrics.registry().snapshot()["counters"]
    print(json.dumps({
        "rank": rank, "hash": digest.hexdigest(),
        "plane": eng.cache_stats()["plane"],
        "wire": snap.get('horovod_wire_bytes_total{plane="eager"}', 0),
        "saved": snap.get('horovod_wire_bytes_saved_total{plane="eager"}', 0),
        "saved_topk": snap.get(
            'horovod_wire_bytes_saved_total{method="topk"}', 0),
    }))
finally:
    eng.shutdown()
"""


def _topk_oracle_hashes(world, grid=None, steps=3, tensors=2, n=20000):
    """Replay TOPK_WORKER's enqueue (top-1% select with the default-on
    error feedback) per rank and fold with the canonical oracle."""
    import hashlib

    k = topk_k(n, 0.01)
    res = {(r, t): np.zeros(n, np.float32)
           for r in range(world) for t in range(tensors)}
    rngs = [np.random.default_rng(100 + r) for r in range(world)]
    digest = hashlib.sha256()
    for i in range(steps):
        for t in range(tensors):
            denses = []
            for r in range(world):
                arr = rngs[r].standard_normal(n).astype(np.float32) \
                    + res[(r, t)]
                idx, val = topk_select(arr, k)
                dense = topk_densify(idx, val, n)
                res[(r, t)] = arr - dense
                denses.append(dense)
            out = _ring_order_reduce(denses, True, wire_dtype="topk",
                                     grid=grid)
            digest.update(out.tobytes())
    return digest.hexdigest()


@pytest.mark.engine
def test_topk_ring_star_hier_pinned_to_oracles_4proc():
    """The ISSUE 9 tentpole contract on free-form payloads: the sparse
    ring and the star relay produce the canonical flat fold BITWISE, the
    hierarchical plane produces the canonical grid fold BITWISE (the
    cross-plane hash identity on exact-arithmetic payloads is CI's
    tools/sparse_smoke.py), and the wire counters prove the >= 10x byte
    reduction at topk@1%."""
    env = {"HOROVOD_COMPRESSION": "topk"}
    ring = launch_world(4, TOPK_WORKER,
                        extra_env=dict(env, HOROVOD_RING_DATA_PLANE="1"))
    star = launch_world(4, TOPK_WORKER,
                        extra_env=dict(env, HOROVOD_RING_DATA_PLANE="0"))
    hier = launch_world(4, TOPK_WORKER,
                        extra_env=dict(env, HOROVOD_RING_DATA_PLANE="1",
                                       TOPK_LOCAL_SIZE="2", TOPK_HIER="1",
                                       HOROVOD_HIERARCHICAL_ALLREDUCE="1"))
    assert {r["out"]["plane"] for r in ring} == {"ring"}
    assert {r["out"]["plane"] for r in star} == {"star"}
    assert {r["out"]["plane"] for r in hier} == {"hier"}
    flat_oracle = _topk_oracle_hashes(4)
    grid_oracle = _topk_oracle_hashes(4, grid=(2, 2))
    assert {r["out"]["hash"] for r in ring} == {flat_oracle}, (
        "sparse ring diverged from the canonical flat fold")
    assert {r["out"]["hash"] for r in star} == {flat_oracle}, (
        "sparse star diverged from the canonical flat fold")
    assert {r["out"]["hash"] for r in hier} == {grid_oracle}, (
        "sparse hier plane diverged from the canonical grid fold")
    for r in ring + hier:
        o = r["out"]
        assert o["wire"] > 0 and o["saved_topk"] > 0
        assert (o["wire"] + o["saved"]) / o["wire"] >= 10.0, (
            "topk@1% did not deliver the 10x wire-byte reduction")


CHAOS_EF_WORKER = r"""
import hashlib, json, os, sys
sys.path.insert(0, os.environ["HVD_REPO"])
import numpy as np
from horovod_tpu.common.config import Config
from horovod_tpu.common.engine import PyEngine
from horovod_tpu.common.topology import Topology
from horovod_tpu import metrics as hvd_metrics

rank = int(os.environ["HOROVOD_RANK"]); world = int(os.environ["HOROVOD_SIZE"])
eng = PyEngine(Topology(rank, world, 0, 1, rank, world),
               Config(cycle_time_ms=1.0, stall_check_disable=True))
try:
    digest = hashlib.sha256()
    rng = np.random.default_rng(40 + rank)
    for i in range(10):
        x = rng.standard_normal(8192).astype(np.float32)
        out = eng.run("allreduce", x, "grad.ef")
        digest.update(out.tobytes())
    snap = hvd_metrics.registry().snapshot()["counters"]
    print(json.dumps({
        "rank": rank, "hash": digest.hexdigest(),
        "demotions": snap.get("horovod_plane_demotions_total", 0),
        "resets": snap.get("horovod_elastic_resets_total", 0),
    }))
finally:
    eng.shutdown()
"""


@pytest.mark.engine
@pytest.mark.parametrize("compression", ["bf16", "topk"])
def test_residual_not_double_folded_across_demotion(compression):
    """ISSUE 9 satellite: a plane-demotion redo (HOROVOD_FAULT_NET=reset
    mid-run) replays the already-quantized/sparsified contribution — the
    error-feedback residual was claimed at enqueue, so the replay must not
    fold it twice. Proof: the faulted world's 10-step result stream is
    BITWISE identical to the fault-free world's (any double fold would
    change every post-fault step), with the demotion actually exercised."""
    base = {"HOROVOD_RING_DATA_PLANE": "1",
            "HOROVOD_COMPRESSION": compression,
            "HOROVOD_COMPRESSION_ERROR_FEEDBACK": "1",
            "HOROVOD_PLANE_REPROMOTE_S": "0"}
    clean = launch_world(4, CHAOS_EF_WORKER, extra_env=base)
    # Land the reset on a mid-run ring data frame of rank 1 (each 4-world
    # flat-ring allreduce sends 6 frames per rank; skip establishment).
    fault = launch_world(4, CHAOS_EF_WORKER, extra_env=dict(
        base, HOROVOD_FAULT_NET="reset", HOROVOD_FAULT_NET_RANK="1",
        HOROVOD_FAULT_NET_SCOPE="ring", HOROVOD_FAULT_NET_AFTER="20",
        HOROVOD_FAULT_NET_COUNT="1"))
    clean_hashes = {r["out"]["hash"] for r in clean}
    fault_hashes = {r["out"]["hash"] for r in fault}
    assert len(clean_hashes) == 1 and len(fault_hashes) == 1
    assert clean_hashes == fault_hashes, (
        f"{compression}+EF results diverged across the demotion replay "
        "(residual folded twice or replay re-quantized)")
    assert max(r["out"]["demotions"] for r in fault) >= 1, (
        "fault injection never demoted the plane — the test exercised "
        "nothing")
    assert all(r["out"]["resets"] == 0 for r in fault), (
        "demotion escalated to an elastic reset")


def test_compiled_adaptive_reads_policy_tier_table(mesh_2x4):
    """ISSUE 13 satellite (ROADMAP known-satellite): compiled-plane
    'adaptive' reads the FIRST-CLASS per-tier table from common/policy.py
    — a DCN bucket large enough for the table to answer 'topk' (the
    genuinely unservable format) substitutes bf16 AND counts a fallback;
    a bucket whose table answer is already servable (bf16) compresses the
    DCN hop with NO fallback counted."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from horovod_tpu import metrics as hvd_metrics
    from horovod_tpu.compat import shard_map
    from horovod_tpu.parallel import fusion

    counter = hvd_metrics.registry().counter(
        "horovod_compiled_adaptive_fallback_total",
        help="compiled-plane traces where an 'adaptive' DCN tier resolved "
             "to the unservable topk format and substituted the bf16 cast "
             "(XLA collectives cannot ship runtime-sparse frames)")
    before = counter.value

    def run(n, hierarchical, compression="adaptive"):
        x = np.arange(8 * n, dtype=np.float32).reshape(8, n) / 3.0

        def body(t):
            (out,) = fusion.fused_allreduce(
                [jnp.squeeze(t, 0)], ("dcn", "ici"), threshold=1 << 26,
                hierarchical=hierarchical, compression=compression)
            return out[None]

        f = shard_map(body, mesh=mesh_2x4, in_specs=P(("dcn", "ici")),
                      out_specs=P(("dcn", "ici")))
        np.asarray(jax.jit(f)(x))
        return hvd_metrics.last_tier_plan()

    # Large f32 bucket (>= HOROVOD_TOPK_MIN_BYTES): the table says topk on
    # DCN -> unservable -> bf16 substituted, fallback counted per trace.
    plan = run(1 << 16, hierarchical=True)
    assert counter.value == before + 1, \
        "unservable topk tier did not count a fallback"
    assert plan["dcn_wire"] == "adaptive"
    ici = plan["bytes_per_step"]["ici"]
    assert plan["bytes_per_step"]["dcn"] == ici // 4 // 2, plan

    # Mid-size bucket (>= min_bytes, < topk_min_bytes): the table answers
    # bf16 — servable as-is, DCN hop compressed, NO fallback counted.
    plan = run(2048, hierarchical=True)
    assert counter.value == before + 1, \
        "a servable bf16 tier must not count a fallback"
    ici = plan["bytes_per_step"]["ici"]
    assert plan["bytes_per_step"]["dcn"] == ici // 4 // 2, plan

    # Flat (non-hierarchical) adaptive: no DCN psum exists, nothing is
    # unservable — ICI resolves full width through the same table.
    run(1 << 16, hierarchical=False)
    assert counter.value == before + 1, \
        "flat adaptive has no unservable tier to count"

    # Non-adaptive traces never touch the counter.
    run(1 << 16, hierarchical=True, compression="bf16")
    assert counter.value == before + 1


def test_autotune_topk_ratio_joins_compression_dimension():
    """tune(compressions=...) accepts 'topk@<ratio>' specs on the
    categorical compression dimension (ISSUE 9): the factory receives the
    spec, every grid point is covered, and the winner carries it."""
    from horovod_tpu.jax.autotune import tune

    calls = []

    def factory(fusion_threshold, num_buckets, compression):
        calls.append(compression)
        rate = {"none": 1.0, "topk@0.01": 2.0, "topk@0.05": 1.5}[compression]

        import time as _t

        def run():
            _t.sleep(0.001 / rate)
        return run

    report = tune(factory, thresholds=(1 << 20,), num_buckets=(1,),
                  compressions=("none", "topk@0.01", "topk@0.05"),
                  warmup=0, iters=2, reps=1, gp_rounds=0)
    assert set(calls) == {"none", "topk@0.01", "topk@0.05"}
    assert report.best.compression == "topk@0.01"
    assert report.best.config["compression"] == "topk@0.01"
    assert "topk@0.01" in report.knob_curve()
