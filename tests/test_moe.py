"""Expert-parallel MoE correctness: the all_to_all dispatch must match a
dense per-token oracle (every token × its argmax expert's MLP × gate prob)
when capacity is generous, drop tokens deterministically when it is not,
and differentiate cleanly through both exchanges."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from horovod_tpu.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.ops.moe import (
    init_moe_params,
    load_balancing_loss,
    moe_apply,
    top1_route,
)

DIM, HIDDEN, EXPERTS, EP = 8, 16, 8, 4
TOKENS = 16  # per rank


@pytest.fixture()
def ep_mesh():
    return Mesh(np.asarray(jax.devices()[:EP]), ("ep",))


def dense_oracle(params, x):
    """Every token through its argmax expert's MLP, scaled by gate prob —
    what EP must reproduce when nothing is dropped."""
    logits = x @ params.gate
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(probs, axis=-1)
    prob = jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0]
    h = jax.nn.relu(jnp.einsum("td,edh->teh", x, params.w_in))
    y = jnp.einsum("teh,ehd->ted", h, params.w_out)
    chosen = jnp.take_along_axis(
        y, expert[:, None, None].repeat(DIM, axis=2), axis=1)[:, 0]
    return chosen * prob[:, None]


def run_ep(ep_mesh, params, x, capacity):
    def fn(gate, w_in, w_out, x):
        from horovod_tpu.ops.moe import MoEParams

        return moe_apply(MoEParams(gate, w_in, w_out), x, capacity, "ep")

    return jax.jit(shard_map(
        fn, mesh=ep_mesh,
        in_specs=(P(), P("ep"), P("ep"), P("ep")),
        out_specs=P("ep"),
        check_vma=False,
    ), static_argnums=())(params.gate, params.w_in, params.w_out, x)


@pytest.mark.slow
def test_moe_matches_dense_oracle(ep_mesh):
    params = init_moe_params(jax.random.PRNGKey(0), DIM, HIDDEN, EXPERTS, EP)
    x = jax.random.normal(jax.random.PRNGKey(1), (TOKENS * EP, DIM))
    with jax.default_matmul_precision("highest"):
        out = run_ep(ep_mesh, params, x, capacity=TOKENS)  # generous: no drops
        ref = dense_oracle(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-4)


def test_moe_capacity_drops_overflow(ep_mesh):
    """With capacity 1 and inputs that all route to one expert, exactly one
    token per rank survives; the rest emit zeros."""
    params = init_moe_params(jax.random.PRNGKey(2), DIM, HIDDEN, EXPERTS, EP)
    # identical tokens → identical routing → one expert gets everything
    x = jnp.tile(jax.random.normal(jax.random.PRNGKey(3), (1, DIM)),
                 (TOKENS * EP, 1))
    out = np.asarray(run_ep(ep_mesh, params, x, capacity=1))
    per_rank = out.reshape(EP, TOKENS, DIM)
    for r in range(EP):
        nonzero = [t for t in range(TOKENS) if np.abs(per_rank[r, t]).max() > 0]
        assert nonzero == [0], f"rank {r}: expected only token 0 kept, got {nonzero}"


def test_top1_route_positions():
    logits = jnp.asarray([[9.0, 0.0], [9.0, 0.0], [0.0, 9.0], [9.0, 0.0]])
    expert, prob, pos, keep = top1_route(logits, capacity=2)
    np.testing.assert_array_equal(np.asarray(expert), [0, 0, 1, 0])
    np.testing.assert_array_equal(np.asarray(pos), [0, 1, 0, 2])
    np.testing.assert_array_equal(np.asarray(keep), [True, True, True, False])
    assert float(prob[0]) > 0.99


def test_moe_differentiable(ep_mesh):
    params = init_moe_params(jax.random.PRNGKey(4), DIM, HIDDEN, EXPERTS, EP)
    x = jax.random.normal(jax.random.PRNGKey(5), (TOKENS * EP, DIM))

    def loss(w_in, w_out, gate, x):
        from horovod_tpu.ops.moe import MoEParams

        out = moe_apply(MoEParams(gate, w_in, w_out), x, TOKENS, "ep")
        # mean over local tokens; psum/EP-average handled by caller IRL
        return jnp.mean(out ** 2)

    grads = jax.jit(shard_map(
        jax.grad(loss, argnums=(0, 1)), mesh=ep_mesh,
        in_specs=(P("ep"), P("ep"), P(), P("ep")),
        out_specs=P("ep"),
        check_vma=False,
    ))(params.w_in, params.w_out, params.gate, x)
    for g in jax.tree_util.tree_leaves(grads):
        arr = np.asarray(g)
        assert np.isfinite(arr).all()
    # experts that received tokens must have nonzero gradient
    assert any(np.abs(np.asarray(g)).max() > 0
               for g in jax.tree_util.tree_leaves(grads))


def test_load_balancing_loss_uniform_is_one():
    # perfectly uniform routing → loss == 1 (its minimum for top-1)
    t, e = 64, 8
    expert = jnp.arange(t) % e
    logits = jax.nn.one_hot(expert, e) * 20.0
    lb = float(load_balancing_loss(logits, expert, e))
    assert lb == pytest.approx(1.0, abs=0.05)


@pytest.mark.slow
def test_moe_transformer_and_ep_specs(ep_mesh):
    """TransformerLM with MoE blocks: forward + finite grads + sowed
    load-balance loss; and GSPMD expert sharding (ep_param_specs) produces
    the same logits as the unsharded run."""
    import optax
    from jax.sharding import NamedSharding

    from horovod_tpu.models import TransformerLM
    from horovod_tpu.models.moe import ep_param_specs

    model = TransformerLM(vocab=32, dim=16, heads=2, layers=2,
                          moe_experts=EP, dtype=jnp.float32)
    tok = jnp.ones((2, 8), jnp.int32)
    variables = model.init(jax.random.PRNGKey(0), tok)
    params = variables["params"]

    def loss_fn(params):
        logits, inter = model.apply({"params": params}, tok,
                                    mutable=["intermediates"])
        task = optax.softmax_cross_entropy_with_integer_labels(
            logits, jnp.roll(tok, -1, axis=1)).mean()
        lb = sum(jnp.asarray(v).sum() for v in
                 jax.tree_util.tree_leaves(inter["intermediates"]))
        return task + 0.01 * lb

    loss, grads = jax.value_and_grad(loss_fn)(params)
    assert np.isfinite(float(loss))
    moe_grads = [g for p, g in jax.tree_util.tree_leaves_with_path(grads)
                 if "moe" in "/".join(str(x) for x in p)]
    assert moe_grads and all(np.isfinite(np.asarray(g)).all() for g in moe_grads)

    # GSPMD EP: shard expert tensors over the ep axis; same logits
    specs = ep_param_specs(params, "ep")
    ep_leaves = [s for s in jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, P)) if s == P("ep", None, None)]
    assert len(ep_leaves) == 2  # one MoE block: w_in + w_out
    sharded = jax.device_put(params, jax.tree_util.tree_map(
        lambda s: NamedSharding(ep_mesh, s), specs,
        is_leaf=lambda x: isinstance(x, P)))
    with jax.default_matmul_precision("highest"):
        ref = model.apply({"params": params}, tok)
        with ep_mesh:
            got = jax.jit(lambda p: model.apply({"params": p}, tok))(sharded)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


def test_moe_matches_dense_oracle_fast(ep_mesh):
    """Fast-tier dense-oracle equivalence (ISSUE 19 promotion satellite):
    the only oracle pin that runs outside -m slow. Tiny token count keeps
    the double all_to_all compile cheap; generous capacity means nothing
    drops, so EP must reproduce the dense per-token arithmetic exactly
    (float tolerance)."""
    params = init_moe_params(jax.random.PRNGKey(6), DIM, HIDDEN, EXPERTS, EP)
    x = jax.random.normal(jax.random.PRNGKey(7), (4 * EP, DIM))
    with jax.default_matmul_precision("highest"):
        out = run_ep(ep_mesh, params, x, capacity=4 * EP)
        ref = dense_oracle(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-4)
