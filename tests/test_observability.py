"""Serving observability (ISSUE 15): flight recorder, anomaly detector,
serving-plane tracer, mixed-plane collector merge, debug bundles, the
replica stall-watchdog wiring, and perf_gate --trend.

Everything here is deterministic: the anomaly rules are driven by hand
(synthetic registry series, explicit tick() calls), the flight ring's
SIGKILL survival is proven with a real killed subprocess, and the trend
satellite is asserted against the checked-in BENCH_r01–r05 records.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from launch_util import REPO

from horovod_tpu.metrics.anomaly import (
    AnomalyDetector,
    DEMOTION_STORM,
    PREEMPT_STORM,
    WARMUP_TICKS,
)
from horovod_tpu.metrics.registry import MetricsRegistry
from horovod_tpu.tracing import flight as flight_mod
from horovod_tpu.tracing.bundle import make_bundle
from horovod_tpu.tracing.collector import build_trace, load_spans
from horovod_tpu.tracing.flight import (
    FlightRecorder,
    config_fingerprint,
    read_ring,
)
from horovod_tpu.tracing.serve import ServeTracer, serve_trace_id


# ------------------------------------------------------------------ flight

def test_flight_ring_mmap_roundtrip_and_wrap(tmp_path):
    fr = FlightRecorder("llm-decode-9", flight_dir=str(tmp_path),
                        capacity=16)
    for i in range(40):   # wraps the 16-slot ring
        fr.retain({"tid": f"req:gen:{i}", "phase": "decode", "i": i})
    recs = fr.records()
    assert len(recs) == 16
    assert [r["i"] for r in recs] == list(range(24, 40))   # newest 16
    ring = read_ring(FlightRecorder.ring_path(str(tmp_path),
                                              "llm-decode-9"))
    assert ring["proc"] == "llm-decode-9"
    assert [r["i"] for r in ring["records"]] == list(range(24, 40))
    assert ring["meta"]["fingerprint"]["hash"]
    fr.close()


def test_flight_oversize_record_truncates_not_drops(tmp_path):
    fr = FlightRecorder("p", flight_dir=str(tmp_path), capacity=16)
    fr.retain({"tid": "req:gen:1", "phase": "decode", "blob": "x" * 4096})
    (rec,) = fr.records()
    assert rec == {"flight_truncated": 1, "tid": "req:gen:1",
                   "phase": "decode", "flight_event": None}
    fr.close()


def test_flight_event_attrs_may_carry_kind_key(tmp_path):
    """Regression: anomaly events carry their own ``kind`` attr — it must
    not collide with the event-name parameter."""
    fr = FlightRecorder("p2", flight_dir=str(tmp_path), capacity=16)
    fr.event("anomaly", kind="ttft_slo", slo_s=2.0)
    (rec,) = fr.records()
    assert rec["flight_event"] == "anomaly" and rec["kind"] == "ttft_slo"
    fr.close()


def test_flight_dump_carries_ring_metrics_and_fingerprint(tmp_path):
    fr = FlightRecorder("router", flight_dir=str(tmp_path), capacity=32)
    fr.event("replica_death", replica=3, reason="kill")
    path = fr.dump("replica-death-3")
    assert os.path.basename(path).startswith("flight-router-001-")
    with open(path) as f:
        doc = json.load(f)
    assert doc["reason"] == "replica-death-3"
    assert doc["fingerprint"]["hash"]
    assert any(r.get("flight_event") == "replica_death"
               for r in doc["records"])
    assert "counters" in doc["metrics"]
    fr.close()


def test_flight_in_memory_mode_and_noop_dump():
    fr = FlightRecorder("memproc", flight_dir="", capacity=16)
    for i in range(20):
        fr.retain({"i": i})
    assert [r["i"] for r in fr.records()] == list(range(4, 20))
    assert fr.dump("whatever") == ""   # nowhere to write, never raises


def test_config_fingerprint_redacts_secrets(monkeypatch):
    monkeypatch.setenv("HOROVOD_SECRET", "deadbeef")
    monkeypatch.setenv("HVD_SERVE_SECRET", "deadbeef")
    monkeypatch.setenv("HOROVOD_SERVE_TOKEN", "tok")
    monkeypatch.setenv("HOROVOD_CYCLE_TIME", "5")
    fp = config_fingerprint()
    joined = json.dumps(fp)
    assert "deadbeef" not in joined and '"tok"' not in joined
    assert fp["env"].get("HOROVOD_CYCLE_TIME") == "5"


def test_flight_ring_survives_sigkill(tmp_path):
    """The black-box property: a SIGKILL'd process's ring decodes from
    disk with its final records intact."""
    child = (
        "import os, sys, time\n"
        f"sys.path.insert(0, {str(REPO)!r})\n"
        "from horovod_tpu.tracing.flight import FlightRecorder\n"
        f"fr = FlightRecorder('victim', flight_dir={str(tmp_path)!r},"
        " capacity=64)\n"
        "for i in range(50):\n"
        "    fr.retain({'tid': f'req:gen:{i}', 'phase': 'decode',"
        " 'i': i})\n"
        "print('ready', flush=True)\n"
        "time.sleep(60)\n")
    p = subprocess.Popen([sys.executable, "-c", child],
                         stdout=subprocess.PIPE, text=True)
    assert p.stdout.readline().strip() == "ready"
    os.kill(p.pid, signal.SIGKILL)
    p.wait(timeout=30)
    ring = read_ring(FlightRecorder.ring_path(str(tmp_path), "victim"))
    assert ring["proc"] == "victim"
    assert [r["i"] for r in ring["records"]] == list(range(50))


# ----------------------------------------------------------------- anomaly

def _det(reg, **kw):
    kw.setdefault("slo_s", 2.0)
    kw.setdefault("cooldown_s", 0.0)
    det = AnomalyDetector(reg=reg, **kw)
    det._flight = FlightRecorder("t", flight_dir="", capacity=64)
    return det


def test_anomaly_quiet_on_empty_and_nominal_registry():
    reg = MetricsRegistry()
    det = _det(reg)
    tok = reg.counter("horovod_serve_llm_tokens_total", phase="decode")
    for i in range(20):
        tok.inc(50)    # steady throughput, no demand queued, no sheds
        assert det.tick(now=float(i)) == []
    assert det.history == []


def test_anomaly_ttft_slo_via_projected_wait_and_p99():
    reg = MetricsRegistry()
    det = _det(reg)
    g = reg.gauge("horovod_serve_projected_wait_seconds")
    g.set(1.9)
    assert det.tick(now=0.0) == []
    g.set(5.0)
    assert det.tick(now=1.0) == ["ttft_slo"]
    assert det.history[-1]["projected_wait_s"] == 5.0
    # p99 path: a TTFT histogram past the SLO fires too
    reg2 = MetricsRegistry()
    det2 = _det(reg2)
    h = reg2.histogram("horovod_serve_llm_ttft_seconds")
    for _ in range(100):
        h.observe(6.0)
    assert det2.tick(now=0.0) == ["ttft_slo"]


def test_anomaly_preempt_and_demotion_storms():
    reg = MetricsRegistry()
    det = _det(reg)
    pre = reg.counter("horovod_serve_llm_preemptions_total")
    det.tick(now=0.0)
    pre.inc(PREEMPT_STORM - 1)
    assert det.tick(now=1.0) == []
    pre.inc(PREEMPT_STORM)
    assert det.tick(now=2.0) == ["preempt_storm"]
    dm = reg.counter("horovod_plane_demotions_total")
    dm.inc(DEMOTION_STORM - 1)
    assert det.tick(now=3.0) == []
    dm.inc(1)   # trailing-window sum reaches the storm threshold
    assert det.tick(now=4.0) == ["demotion_storm"]


def test_anomaly_drain_collapse_needs_demand_and_warm_baseline():
    reg = MetricsRegistry()
    det = _det(reg)
    tok = reg.counter("horovod_serve_llm_tokens_total", phase="decode")
    waiting = reg.gauge("horovod_serve_llm_waiting_sequences")
    now = 0.0
    for _ in range(WARMUP_TICKS + 2):
        tok.inc(100)
        waiting.set(4)
        assert det.tick(now=now) == []
        now += 1
    # collapse WITHOUT demand: never fires (idle is not an anomaly)
    waiting.set(0)
    for _ in range(6):
        assert det.tick(now=now) == []
        now += 1
    # collapse WITH demand: fires after the consecutive-tick rule (and
    # refires each window with the zero test cooldown)
    waiting.set(4)
    fired = []
    for _ in range(6):
        fired += det.tick(now=now)
        now += 1
    assert fired and set(fired) == {"drain_collapse"}


def test_anomaly_shed_spike_and_cooldown():
    reg = MetricsRegistry()
    det = _det(reg, cooldown_s=100.0)
    shed = reg.counter("horovod_serve_shed_total")
    det.tick(now=0.0)
    shed.inc(50)
    assert det.tick(now=1.0) == ["shed_spike"]
    shed.inc(500)
    assert det.tick(now=2.0) == []     # cooldown suppresses the refire
    assert reg.snapshot()["counters"][
        'horovod_anomaly_total{kind="shed_spike"}'] == 1.0


def test_anomaly_firing_lands_in_flight_ring():
    reg = MetricsRegistry()
    det = _det(reg)
    reg.gauge("horovod_serve_projected_wait_seconds").set(9.0)
    assert det.tick(now=0.0) == ["ttft_slo"]
    recs = det._flight.records()
    assert any(r.get("flight_event") == "anomaly"
               and r.get("kind") == "ttft_slo" for r in recs)


# ----------------------------------------------- serve tracer / collector

def test_serve_trace_ids_never_collide_with_training_scheme():
    assert serve_trace_id("gen", 12) == "req:gen:12"
    assert "#" not in serve_trace_id("infer", 99)


def test_serve_tracer_writes_proc_file_flight_always_on(tmp_path,
                                                        monkeypatch):
    monkeypatch.setenv("HOROVOD_TRACE_DIR", str(tmp_path))
    monkeypatch.setenv("HOROVOD_FLIGHT_DIR", "")
    flight_mod._flight = None   # fresh process singleton
    t = ServeTracer("serve-router")
    assert t.enabled
    t.span("req:gen:1", "admit", 100, 200, rid=1, decision="ok")
    t.point("req:gen:1", "retire", tokens=3)
    t.flush()
    path = os.path.join(str(tmp_path), "spans-serve-router.jsonl")
    lines = [json.loads(ln) for ln in open(path)]
    assert lines[0]["meta"] == 1 and lines[0]["proc"] == "serve-router"
    assert lines[1]["phase"] == "admit" and lines[1]["proc"] == \
        "serve-router"
    # flight retention happened even with no flight dir (memory ring)
    assert any(r.get("phase") == "retire" for r in t.flight.records())
    t.close()
    # with tracing OFF the tracer still retains into the ring
    monkeypatch.delenv("HOROVOD_TRACE_DIR")
    t2 = ServeTracer("llm-decode-0")
    assert not t2.enabled
    t2.span("it:llm-decode-0:1", "decode", 1, 2, seqs=[4])
    assert t2.flight.records()[-1]["phase"] == "decode"


def test_collector_merges_mixed_planes_with_proc_rows(tmp_path,
                                                      monkeypatch):
    from horovod_tpu.tracing import TraceRecorder, span_path

    for r in range(2):
        rec = TraceRecorder(span_path(str(tmp_path), r), rank=r)
        rec.point("grad.0#1", "grad.0", "allreduce", "enqueue")
        rec.close()
    monkeypatch.setenv("HOROVOD_TRACE_DIR", str(tmp_path))
    monkeypatch.setenv("HOROVOD_FLIGHT_DIR", "")
    flight_mod._flight = None
    t = ServeTracer("llm-decode-0")
    t.span("it:llm-decode-0:1", "decode", 10, 20, seqs=[7], n=1)
    t.point("req:gen:7", "retire", tokens=2)
    t.flush()
    # torn tail from a killed replica must not break the merge
    with open(os.path.join(str(tmp_path),
                           "spans-llm-decode-0.jsonl"), "a") as f:
        f.write('{"tid": "req:g')
    t.close()
    spans, metas = load_spans(str(tmp_path))
    assert sorted(k for k in metas if isinstance(k, int)) == [0, 1]
    assert [k for k in metas if not isinstance(k, int)] == \
        ["llm-decode-0"]
    trace = build_trace(spans, metas)
    names = {e["args"]["name"] for e in trace["traceEvents"]
             if e.get("name") == "process_name"}
    assert names == {"rank 0", "rank 1", "llm-decode-0"}
    decode_lanes = {e["tid"] for e in trace["traceEvents"]
                    if e.get("cat") == "decode"}
    retire_lanes = {e["tid"] for e in trace["traceEvents"]
                    if e.get("cat") == "retire"}
    assert decode_lanes and retire_lanes and \
        decode_lanes.isdisjoint(retire_lanes)
    json.loads(json.dumps(trace))   # strict round trip


# ---------------------------------------------------------------- bundle

def test_bundle_names_dead_replica_and_decodes_ring(tmp_path):
    flight_dir = str(tmp_path / "flight")
    router = FlightRecorder("serve-router", flight_dir=flight_dir,
                            capacity=32)
    router.event("replica_death", replica=2, pid=999, state_was="serving",
                 reason="decode dispatch failed")
    router.event("anomaly", kind="ttft_slo", slo_s=2.0)
    router.dump("replica-death-2")
    victim = FlightRecorder("llm-decode-2", flight_dir=flight_dir,
                            capacity=32)
    victim.retain({"tid": "it:llm-decode-2:9", "phase": "decode",
                   "seqs": [5]})
    victim.close()
    router.close()
    out = str(tmp_path / "bundle")
    summary = make_bundle(out, flight_dir=flight_dir)
    assert summary["dead_replicas"] == [2]
    manifest = open(os.path.join(out, "MANIFEST.md")).read()
    assert "replica 2 died" in manifest
    assert "anomaly `ttft_slo` fired" in manifest
    decoded = json.load(open(os.path.join(
        out, "flight", "flight-llm-decode-2.ring.json")))
    assert decoded["records"][0]["phase"] == "decode"


def test_bundle_cli_exits_1_on_nothing(tmp_path):
    r = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.tracing.bundle",
         "--trace-dir", str(tmp_path / "no"), "--flight-dir",
         str(tmp_path / "nope"), "-o", str(tmp_path / "out")],
        capture_output=True, text=True, cwd=REPO,
        env=dict(os.environ, HOROVOD_TRACE_DIR="", HOROVOD_FLIGHT_DIR=""))
    assert r.returncode == 1, r.stdout + r.stderr


# --------------------------------------- scheduler / engine instrumentation

class _FakeTracer:
    proc = "llm-decode-0"

    def __init__(self):
        self.recs = []

    def span(self, tid, phase, t0, t1=None, **attrs):
        self.recs.append(dict(tid=tid, phase=phase, **attrs))

    def point(self, tid, phase, **attrs):
        self.span(tid, phase, 0, 0, **attrs)


def _scheduler(tracer, num_blocks=16, block_size=4, max_active=2):
    from horovod_tpu.serving.llm.kv_cache import PagedKVCache
    from horovod_tpu.serving.llm.scheduler import (
        IterationScheduler,
        Sequence,
    )
    from horovod_tpu.serving.model import tiny_lm_params

    cache = PagedKVCache(num_blocks, block_size, 16)
    sched = IterationScheduler(cache, tiny_lm_params(),
                               max_active=max_active, tracer=tracer)
    return sched, Sequence


def test_scheduler_emits_iteration_spans_with_member_seqs():
    tr = _FakeTracer()
    sched, Sequence = _scheduler(tr)
    for rid in (1, 2):
        sched.submit(Sequence(rid, [3, 17], 4))
    while sched.running or sched.waiting:
        sched.step()
    decode = [r for r in tr.recs if r["phase"] == "decode"]
    assert decode, tr.recs
    # ONE span per iteration, member rids in args — both sequences ride
    # the same span while both are running.
    assert any(set(r["seqs"]) == {1, 2} for r in decode)
    assert all(r["tid"].startswith("it:llm-decode-0:") for r in decode)
    admits = [r for r in tr.recs if r["phase"] == "admit"]
    retires = [r for r in tr.recs if r["phase"] == "retire"]
    assert {r["tid"] for r in admits} == {"req:gen:1", "req:gen:2"}
    assert {r["tid"] for r in retires} == {"req:gen:1", "req:gen:2"}
    assert all(r["tokens"] == 4 for r in retires)


def test_scheduler_preempt_and_kv_pressure_events():
    tr = _FakeTracer()
    # 6 blocks x 2 tokens: two sequences growing toward 4 blocks each
    # must fight over the 6-block pool
    sched, Sequence = _scheduler(tr, num_blocks=6, block_size=2,
                                 max_active=2)
    sched.submit(Sequence(1, [3], 8))
    sched.submit(Sequence(2, [5], 8))
    for _ in range(40):
        sched.step()
        if not sched.running and not sched.waiting:
            break
    preempts = [r for r in tr.recs if r["phase"] == "preempt"]
    pressure = [r for r in tr.recs if r["phase"] == "kv_pressure"]
    assert preempts and pressure
    assert pressure[0]["free"] <= sched.cache.alloc.num_blocks


def test_scheduler_sequences_debug_view():
    tr = _FakeTracer()
    sched, Sequence = _scheduler(tr, max_active=1)
    sched.submit(Sequence(1, [3, 17], 4))
    sched.submit(Sequence(2, [5], 4))
    sched.step()
    rows = sched.sequences()
    by_rid = {r["rid"]: r for r in rows}
    assert by_rid[1]["state"] == "running" and by_rid[1]["slot"] == 0
    assert by_rid[1]["blocks"] >= 1 and by_rid[1]["tokens_out"] >= 1
    assert by_rid[2]["state"] == "waiting" and by_rid[2]["slot"] == -1


def test_decode_engine_stall_infos_names_stuck_sequences():
    from horovod_tpu.serving.llm.generator import DecodeEngine

    tr = _FakeTracer()
    sched, Sequence = _scheduler(tr)
    engine = DecodeEngine(sched)   # NOT started: the loop never runs
    assert engine.stall_infos() == []
    sched.submit(Sequence(7, [3], 4))
    sched.step()
    sched.last_progress_t = time.monotonic() - 9.0
    infos = engine.stall_infos()
    assert [i.name for i in infos] == ["seq:7"]
    assert infos[0].op == "decode" and infos[0].age_s >= 9.0


def test_watchdog_on_warn_hook_fires_once_per_fresh_batch():
    from horovod_tpu.metrics import StallInfo, StallWatchdog

    calls = []
    wd = StallWatchdog(check_time_s=0.01, rank=0, poll_interval_s=10.0,
                       on_warn=lambda stalled: calls.append(
                           [s.name for s in stalled]))
    try:
        wd.add_source(lambda: [StallInfo(name="seq:3", op="decode",
                                         age_s=5.0)])
        wd._scan()
        wd._scan()   # same tensor inside the rate-limit window: no refire
        assert calls == [["seq:3"]]
    finally:
        wd.stop()


def test_refresh_projection_keeps_gauge_live():
    from horovod_tpu.serving.admission import KVAdmission
    from horovod_tpu.serving.config import LLMConfig

    reg = MetricsRegistry()
    adm = KVAdmission(LLMConfig(num_blocks=24, block_size=4), reg=reg)
    adm.observe_release(20, 1.0)
    for _ in range(40):               # decay the release EWMA hard
        adm.observe_release(0, 0.05)
    wait = adm.refresh_projection(free_blocks=2, queued_blocks=20)
    assert wait > 2.0
    assert reg.gauge("horovod_serve_projected_wait_seconds").value == wait
    # an idle pool projects zero
    assert adm.refresh_projection(free_blocks=24, queued_blocks=0) == 0.0


# ---------------------------------------------------------- perf_gate trend

def _trend(args):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "perf_gate.py"),
         "--trend"] + args, capture_output=True, text=True, cwd=REPO)


def test_perf_gate_trend_on_checked_in_bench_records():
    r = _trend(["--history", os.path.join(REPO, "BENCH_r0*.json")])
    assert r.returncode == 0, r.stdout + r.stderr
    (line,) = [ln for ln in r.stdout.splitlines()
               if "resnet50_images_per_sec" in ln]
    # r05 exited rc=124 -> excluded; four usable records remain and the
    # trajectory is monotone up, so latest == best.
    assert "n=4" in line and "latest/best=1.000" in line
    assert "skipping" in r.stdout and "rc=124" in r.stdout


def test_perf_gate_trend_tracks_best_vs_latest(tmp_path):
    rec = {"metric": "m", "value": 100.0, "unit": "u"}
    for i, v in enumerate((100.0, 200.0, 150.0)):
        with open(tmp_path / f"BENCH_r{i:02d}.json", "w") as f:
            json.dump({"rc": 0, "parsed": dict(rec, value=v)}, f)
    r = _trend(["--history", str(tmp_path / "BENCH_r*.json")])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "n=3 best=200 latest=150 latest/best=0.750" in r.stdout


def test_perf_gate_trend_empty_history_errors(tmp_path):
    r = _trend(["--history", str(tmp_path / "nope*.json")])
    assert r.returncode == 2
