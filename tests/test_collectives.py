"""Collective correctness over the 8-device mesh — the core op matrix of the
reference suite (test/test_tensorflow.py:MPITests — allreduce/allgather/
broadcast across dtypes/dims, fusion, grads)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from horovod_tpu.compat import shard_map
from jax.sharding import PartitionSpec as P

from horovod_tpu.parallel import collectives, fusion
from horovod_tpu.parallel.collectives import ReduceOp

N = 8


def smap(fn, mesh, in_specs, out_specs):
    return jax.jit(shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                             check_vma=False))


def per_rank(mesh, shape, dtype=jnp.float32, seed=0):
    """A (N, *shape) array where slice i is rank i's local tensor."""
    k = jax.random.PRNGKey(seed)
    x = jax.random.normal(k, (N,) + shape).astype(dtype)
    return x


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16, jnp.int32])
@pytest.mark.parametrize("dims", [(4,), (3, 5), (2, 3, 4)])
def test_allreduce_average_dtypes(mesh8, dtype, dims):
    # reference test_horovod_allreduce (test/test_tensorflow.py:46)
    x = per_rank(mesh8, dims, jnp.float32).astype(dtype)
    op = ReduceOp.AVERAGE if jnp.issubdtype(dtype, jnp.floating) else ReduceOp.SUM
    f = smap(lambda t: collectives.allreduce(t, "hvd", op),
             mesh8, (P("hvd"),), P("hvd"))
    out = f(x)
    expect = np.mean(np.asarray(x, np.float64), axis=0) if op == ReduceOp.AVERAGE \
        else np.sum(np.asarray(x, np.float64), axis=0)
    tol = 1e-2 if dtype == jnp.bfloat16 else 1e-5
    for r in range(N):
        np.testing.assert_allclose(np.asarray(out[r], np.float64), expect, rtol=tol, atol=tol)


@pytest.mark.parametrize("op,npfn", [(ReduceOp.MIN, np.min), (ReduceOp.MAX, np.max)])
def test_allreduce_minmax(mesh8, op, npfn):
    x = per_rank(mesh8, (6,))
    f = smap(lambda t: collectives.allreduce(t, "hvd", op), mesh8, (P("hvd"),), P("hvd"))
    out = np.asarray(f(x))
    expect = npfn(np.asarray(x), axis=0)
    for r in range(N):
        np.testing.assert_allclose(out[r], expect, rtol=1e-6)


def test_allgather(mesh8):
    # reference test_horovod_allgather (test/test_tensorflow.py:392)
    x = per_rank(mesh8, (2, 3))
    f = smap(lambda t: collectives.allgather(t, "hvd"), mesh8, (P("hvd"),), P("hvd"))
    out = f(x)  # each rank gets (N*2, 3); stacked output (N, N*2, 3) after gather
    full = np.concatenate([np.asarray(x[r]) for r in range(N)], axis=0)
    got = np.asarray(out).reshape(N, N * 2, 3)
    for r in range(N):
        np.testing.assert_allclose(got[r], full, rtol=1e-6)


@pytest.mark.parametrize("root", [0, 3, 7])
def test_broadcast_roots(mesh8, root):
    # reference test_horovod_broadcast (test/test_tensorflow.py:524)
    x = per_rank(mesh8, (5,))
    f = smap(lambda t: collectives.broadcast(t, root, "hvd"), mesh8, (P("hvd"),), P("hvd"))
    out = np.asarray(f(x))
    for r in range(N):
        np.testing.assert_allclose(out[r], np.asarray(x[root]), rtol=1e-6)


def test_broadcast_int(mesh8):
    x = jnp.arange(N * 4, dtype=jnp.int32).reshape(N, 4)
    f = smap(lambda t: collectives.broadcast(t, 2, "hvd"), mesh8, (P("hvd"),), P("hvd"))
    out = np.asarray(f(x))
    for r in range(N):
        np.testing.assert_array_equal(out[r], np.asarray(x[2]))


def test_reducescatter(mesh8):
    x = per_rank(mesh8, (N * 2, 3))
    f = smap(lambda t: collectives.reducescatter(jnp.squeeze(t, 0), "hvd"),
             mesh8, (P("hvd"),), P("hvd"))
    out = np.asarray(f(x)).reshape(N, 2, 3)  # per-rank shard r
    total = np.sum(np.asarray(x, np.float64), axis=0)
    for r in range(N):
        np.testing.assert_allclose(out[r], total[r * 2:(r + 1) * 2], rtol=1e-4, atol=1e-5)


def test_alltoall(mesh8):
    x = per_rank(mesh8, (N, 4))
    f = smap(lambda t: collectives.alltoall(jnp.squeeze(t, 0), "hvd"),
             mesh8, (P("hvd"),), P("hvd"))
    out = np.asarray(f(x)).reshape(N, N, 4)
    xs = np.asarray(x)
    for r in range(N):
        expect = np.stack([xs[s, r] for s in range(N)], axis=0)
        np.testing.assert_allclose(out[r], expect, rtol=1e-6)


def test_ring_shift(mesh8):
    x = per_rank(mesh8, (3,))
    f = smap(lambda t: collectives.ring_shift(t, "hvd", 1), mesh8, (P("hvd"),), P("hvd"))
    out = np.asarray(f(x))
    xs = np.asarray(x)
    for r in range(N):
        np.testing.assert_allclose(out[r], xs[(r - 1) % N], rtol=1e-6)


def test_hierarchical_allreduce(mesh_2x4):
    # reference hierarchical ladder (operations.cc:1284-1436): result must
    # equal the flat allreduce over all 8 devices.
    x = per_rank(mesh_2x4, (8, 3))
    f = jax.jit(shard_map(
        lambda t: collectives.hierarchical_allreduce(jnp.squeeze(t, 0), "ici", "dcn"),
        mesh=mesh_2x4, in_specs=(P(("dcn", "ici")),), out_specs=P(("dcn", "ici")),
        check_vma=False))
    out = np.asarray(f(x)).reshape(N, 8, 3)
    expect = np.mean(np.asarray(x, np.float64), axis=0)
    for r in range(N):
        np.testing.assert_allclose(out[r], expect, rtol=1e-4, atol=1e-5)


def test_allreduce_grad(mesh8):
    # reference test_horovod_allreduce_grad (test/test_tensorflow.py:334):
    # backward of allreduce is allreduce (mpi_ops.py:94-183). In JAX the
    # transpose of pmean with a ones cotangent on every rank is
    # psum(1)/N == 1 — identical to the reference's averaged backward.
    x = per_rank(mesh8, (4,))

    def loss(t):
        return jnp.sum(collectives.allreduce(t, "hvd", ReduceOp.AVERAGE))

    f = smap(jax.grad(loss), mesh8, (P("hvd"),), P("hvd"))
    out = np.asarray(f(x))
    np.testing.assert_allclose(out, np.ones((N, 4)), rtol=1e-6)


def test_allgather_grad(mesh8):
    # reference test_horovod_allgather_grad (test/test_tensorflow.py:482).
    # JAX transpose of all_gather is slice-of-psum: with the replicated loss
    # computed on every rank, each rank's grad is N · 2·t_r (sum over the N
    # identical replicated losses, vs. the reference's averaged backward).
    x = per_rank(mesh8, (2,))

    def loss(t):
        g = collectives.allgather(t, "hvd")
        return jnp.sum(g * g)

    f = smap(jax.grad(loss), mesh8, (P("hvd"),), P("hvd"))
    out = np.asarray(f(x))
    np.testing.assert_allclose(out, N * 2 * np.asarray(x), rtol=1e-4)


# ---------------------------------------------------------------- fusion

def test_fusion_plan_respects_threshold():
    tree = {f"g{i}": jnp.ones((100,), jnp.float32) for i in range(10)}  # 400 B each
    plan = fusion.build_plan(tree, threshold=1000)  # 2 leaves per bucket
    assert plan.num_buckets == 5
    assert all(sum(d.size for d in b) * 4 <= 1000 for b in plan.buckets)


def test_fusion_groups_by_dtype():
    tree = {"a": jnp.ones((4,), jnp.float32), "b": jnp.ones((4,), jnp.bfloat16),
            "c": jnp.ones((4,), jnp.float32)}
    plan = fusion.build_plan(tree, threshold=1 << 20)
    dtypes = [b[0].dtype for b in plan.buckets]
    for bucket in plan.buckets:
        assert len({d.dtype for d in bucket}) == 1
    assert len(dtypes) == 2  # one f32 bucket (a+c), one bf16


def test_fuse_unfuse_roundtrip():
    tree = {"w": jnp.arange(12.0).reshape(3, 4), "b": jnp.arange(5.0),
            "s": jnp.array(7.0)}
    plan = fusion.build_plan(tree)
    bufs = fusion.fuse(tree, plan)
    out = fusion.unfuse(bufs, plan)
    for k in tree:
        np.testing.assert_array_equal(np.asarray(out[k]), np.asarray(tree[k]))


def test_fused_allreduce_matches_unfused(mesh8):
    # fusion must not change numerics (reference fused tests,
    # test_horovod_allreduce_cpu_fused, test/test_tensorflow.py:107)
    k = jax.random.PRNGKey(1)
    tree = {
        "a": jax.random.normal(k, (N, 16)),
        "b": jax.random.normal(jax.random.fold_in(k, 1), (N, 3, 3)),
        "c": jax.random.normal(jax.random.fold_in(k, 2), (N, 1)),
    }

    def fused(t):
        return fusion.fused_allreduce(t, "hvd", threshold=128)

    f = smap(fused, mesh8, ({"a": P("hvd"), "b": P("hvd"), "c": P("hvd")},),
             {"a": P("hvd"), "b": P("hvd"), "c": P("hvd")})
    out = f(tree)
    for key in tree:
        expect = np.mean(np.asarray(tree[key], np.float64), axis=0)
        got = np.asarray(out[key])
        for r in range(N):
            np.testing.assert_allclose(got[r], expect, rtol=1e-5, atol=1e-6)


def test_fused_allreduce_hierarchical(mesh_2x4):
    tree = {"a": jnp.ones((N, 7)), "b": jnp.ones((N, 13))}

    def fused(t):
        return fusion.fused_allreduce(t, threshold=1 << 20, hierarchical=True)

    f = jax.jit(shard_map(fused, mesh=mesh_2x4,
                          in_specs=({"a": P(("dcn", "ici")), "b": P(("dcn", "ici"))},),
                          out_specs={"a": P(("dcn", "ici")), "b": P(("dcn", "ici"))},
                          check_vma=False))
    out = f(tree)
    np.testing.assert_allclose(np.asarray(out["a"]), np.ones((N, 7)), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(out["b"]), np.ones((N, 13)), rtol=1e-6)


def test_hierarchical_allgather(mesh_2x4):
    """Two-stage allgather over ('dcn','ici') must match rank-order concat."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from horovod_tpu.compat import shard_map
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.parallel import collectives

    x = jnp.arange(16.0).reshape(8, 2)  # one row per device

    def body(x):
        return collectives.hierarchical_allgather(x)

    out = shard_map(body, mesh=mesh_2x4, in_specs=P(("dcn", "ici")),
                    out_specs=P(("dcn", "ici")), check_vma=False)(x)
    # every device holds the full concat; with out_specs sharding the global
    # result back, we get x stacked per device -> compare one shard
    full = shard_map(body, mesh=mesh_2x4, in_specs=P(("dcn", "ici")),
                     out_specs=P(None), check_vma=False)(x)[:8]
    np.testing.assert_allclose(np.asarray(full), np.asarray(x))


def test_sparse_allreduce(mesh8):
    """values/indices allgather parity with the reference's IndexedSlices
    path (tensorflow/__init__.py:72-83): scatter-adding the gathered pairs
    equals the dense average."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from horovod_tpu.compat import shard_map
    from jax.sharding import PartitionSpec as P

    from horovod_tpu.parallel import collectives

    vocab, dim, n = 16, 4, 8
    rng = np.random.default_rng(0)
    # per-rank sparse grads: 2 rows each
    values = jnp.asarray(rng.normal(size=(n * 2, dim)).astype(np.float32))
    indices = jnp.asarray(rng.integers(0, vocab, size=(n * 2,)).astype(np.int32))

    def body(v, i):
        av, ai = collectives.sparse_allreduce(v, i)
        dense = jnp.zeros((vocab, dim), jnp.float32).at[ai].add(av)
        return dense

    out = shard_map(body, mesh=mesh8, in_specs=(P("hvd"), P("hvd")),
                    out_specs=P(None), check_vma=False)(values, indices)
    expect = np.zeros((vocab, dim), np.float32)
    np.add.at(expect, np.asarray(indices), np.asarray(values) / n)
    np.testing.assert_allclose(np.asarray(out), expect, rtol=1e-5, atol=1e-5)


def test_allreduce_product_negatives_and_zeros(mesh8):
    """PRODUCT must be exact for non-positive values (a log-space psum NaNs
    on negatives and mishandles zeros) — VERDICT r2 weak #1."""
    vals = np.array([1.5, -2.0, 3.0, -1.0, 0.5, 2.0, -0.25, 4.0])
    x = jnp.asarray(np.repeat(vals[:, None], 3, axis=1))  # (8, 3) per-rank rows
    f = smap(lambda t: collectives.allreduce(t, "hvd", ReduceOp.PRODUCT),
             mesh8, (P("hvd"),), P("hvd"))
    out = np.asarray(f(x))
    expect = np.prod(vals)  # negative (three sign flips)
    assert expect < 0
    for r in range(N):
        np.testing.assert_allclose(out[r], np.full(3, expect), rtol=1e-6)
    # a single zero anywhere zeroes the product exactly
    vals0 = vals.copy()
    vals0[3] = 0.0
    out0 = np.asarray(f(jnp.asarray(np.repeat(vals0[:, None], 3, axis=1))))
    np.testing.assert_array_equal(out0, np.zeros((N, 3)))


def test_fused_allreduce_hierarchical_concrete_leaves(mesh_2x4):
    """The pad gate must fire even when the tree's leaves are concrete
    (closed-over constants in a shard_map body): previously pad_to stayed 1
    and psum_scatter crashed on non-divisible dim 0 — VERDICT r2 weak #2."""
    const = np.ones(7, np.float32)  # 7 not divisible by ici=4

    def fused(t):
        # leaves[0] is the closed-over concrete array, not a tracer
        return fusion.fused_allreduce({"const": const, "x": t},
                                      threshold=1 << 20, hierarchical=True)

    f = jax.jit(shard_map(fused, mesh=mesh_2x4,
                          in_specs=(P(("dcn", "ici")),),
                          out_specs={"const": P(None), "x": P(("dcn", "ici"))},
                          check_vma=False))
    out = f(jnp.ones((N, 13)))
    np.testing.assert_allclose(np.asarray(out["const"]), const, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(out["x"]), np.ones((N, 13)), rtol=1e-6)


def test_fused_allreduce_hierarchical_outside_mesh_is_actionable():
    """Without a trace or ambient mesh the axis size is unknowable: the error
    must say how to fix it, not crash in psum_scatter."""
    with pytest.raises(ValueError, match="hierarchical fusion needs"):
        fusion.fused_allreduce({"x": np.ones(7, np.float32)}, hierarchical=True)


def test_fused_allreduce_hierarchical_rejects_nonsum_ops(mesh_2x4):
    """The RS->psum->AG ladder can only sum; MAX/PRODUCT must error, not
    silently sum."""
    with pytest.raises(ValueError, match="SUM/AVERAGE only"):
        with mesh_2x4:
            fusion.fused_allreduce({"x": jnp.ones(8)}, op=ReduceOp.MAX,
                                   hierarchical=True)
