"""Sequence-parallel attention correctness: ring and Ulysses schedules must
match the dense causal oracle on a sequence-sharded virtual mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from horovod_tpu.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.ops.ring_attention import (
    causal_reference,
    ring_attention,
    ulysses_attention,
)


def qkv(b=2, t=64, h=8, d=16, seed=0):
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (
        jax.random.normal(k1, (b, t, h, d), jnp.float32),
        jax.random.normal(k2, (b, t, h, d), jnp.float32),
        jax.random.normal(k3, (b, t, h, d), jnp.float32),
    )


@pytest.fixture()
def sp_mesh():
    # 4 of the 8 virtual devices: the ring schedule unrolls one scan step per
    # device, so compile time scales with mesh size — 4 exercises the same
    # index math (>2 avoids trivial neighbour symmetry) at half the compile.
    return Mesh(np.asarray(jax.devices()[:4]), ("sp",))


def _run_sharded(fn, mesh, *args):
    return shard_map(
        fn, mesh=mesh, in_specs=P(None, "sp"), out_specs=P(None, "sp"),
        check_vma=False,
    )(*args)


@pytest.mark.slow
def test_ring_attention_matches_oracle(sp_mesh):
    q, k, v = qkv()
    with jax.default_matmul_precision("highest"):
        ref = causal_reference(q, k, v)
        out = _run_sharded(lambda a, b, c: ring_attention(a, b, c, "sp"), sp_mesh, q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.slow
def test_ring_attention_is_causal(sp_mesh):
    """Changing future tokens must not change past outputs."""
    q, k, v = qkv(t=32)
    k2, v2 = k.at[:, 16:].set(0.0), v.at[:, 16:].set(0.0)
    with jax.default_matmul_precision("highest"):
        a = _run_sharded(lambda x, y, z: ring_attention(x, y, z, "sp"), sp_mesh, q, k, v)
        b = _run_sharded(lambda x, y, z: ring_attention(x, y, z, "sp"), sp_mesh, q, k2, v2)
    np.testing.assert_allclose(np.asarray(a[:, :16]), np.asarray(b[:, :16]), atol=1e-6)
    assert not np.allclose(np.asarray(a[:, 16:]), np.asarray(b[:, 16:]))


def test_ulysses_matches_oracle(sp_mesh):
    q, k, v = qkv()
    with jax.default_matmul_precision("highest"):
        ref = causal_reference(q, k, v)
        out = _run_sharded(
            lambda a, b, c: ulysses_attention(a, b, c, "sp"), sp_mesh, q, k, v
        )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.slow
def test_ulysses_flash_matches_oracle(sp_mesh):
    """impl='flash': the post-all-to-all local attention runs through the
    pallas kernel; grads flow through its custom VJP and the all_to_all
    transposes."""
    q, k, v = qkv()
    w = jax.random.normal(jax.random.PRNGKey(9), q.shape, jnp.float32)
    uly = shard_map(
        lambda a, b, c: ulysses_attention(a, b, c, "sp", impl="flash"),
        mesh=sp_mesh, in_specs=P(None, "sp"), out_specs=P(None, "sp"),
        check_vma=False)
    with jax.default_matmul_precision("highest"):
        ref = causal_reference(q, k, v)
        out = uly(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)
        g_u = jax.grad(lambda a, b, c: jnp.sum(uly(a, b, c) * w),
                       argnums=(0, 1, 2))(q, k, v)
        g_r = jax.grad(lambda a, b, c: jnp.sum(causal_reference(a, b, c) * w),
                       argnums=(0, 1, 2))(q, k, v)
    for got, want, name in zip(g_u, g_r, "qkv"):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=3e-5, rtol=3e-5,
                                   err_msg=f"d{name} mismatch")


def test_ulysses_rejects_bad_heads(sp_mesh):
    q, k, v = qkv(h=6)  # 6 % 8 != 0
    with pytest.raises(ValueError, match="not divisible"):
        _run_sharded(lambda a, b, c: ulysses_attention(a, b, c, "sp"), sp_mesh, q, k, v)


@pytest.mark.slow
def test_transformer_sp_equals_dense(sp_mesh):
    """Full model: sp-sharded forward with ring attention == single-device
    forward with dense attention, same params."""
    from horovod_tpu.models import TransformerLM

    dense = TransformerLM(vocab=64, dim=32, heads=4, layers=2, dtype=jnp.float32)
    sp = TransformerLM(vocab=64, dim=32, heads=4, layers=2, dtype=jnp.float32,
                       sp_axis="sp")
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, 64)
    params = dense.init(jax.random.PRNGKey(0), tokens)["params"]

    with jax.default_matmul_precision("highest"):
        ref = dense.apply({"params": params}, tokens)

        def fwd(tokens):
            t_local = tokens.shape[1]
            pos = (jax.lax.axis_index("sp") * t_local + jnp.arange(t_local))[None, :]
            return sp.apply({"params": params}, tokens, pos)

        out = shard_map(fwd, mesh=sp_mesh, in_specs=P(None, "sp"),
                        out_specs=P(None, "sp"), check_vma=False)(tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4, rtol=1e-4)


@pytest.mark.slow
def test_zigzag_ring_matches_oracle(sp_mesh):
    """Zigzag layout (load-balanced causal sharding): shard the zigzag-
    reordered sequence, run ring attention with zigzag masking, undo the
    permutation — must equal the dense oracle on the ORIGINAL order."""
    from horovod_tpu.ops.ring_attention import zigzag_shard, zigzag_unshard

    n = sp_mesh.size
    q, k, v = qkv(t=64)
    qz, kz, vz = (zigzag_shard(x, n) for x in (q, k, v))
    with jax.default_matmul_precision("highest"):
        ref = causal_reference(q, k, v)
        out_z = _run_sharded(
            lambda a, b, c: ring_attention(a, b, c, "sp", zigzag=True),
            sp_mesh, qz, kz, vz)
        out = zigzag_unshard(out_z, n)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_zigzag_shard_roundtrip():
    from horovod_tpu.ops.ring_attention import zigzag_shard, zigzag_unshard

    x = jnp.arange(2 * 32 * 3).reshape(2, 32, 3).astype(jnp.float32)
    y = zigzag_unshard(zigzag_shard(x, 4), 4)
    np.testing.assert_array_equal(np.asarray(y), np.asarray(x))


@pytest.mark.slow
def test_ring_attention_gqa_matches_oracle(sp_mesh):
    """GQA kv (fewer heads) through the dense ring: the ring rotates the
    small kv blocks and replicates heads inside the local block product —
    must equal the oracle on pre-replicated kv (ADVICE r2 #3)."""
    q, _, _ = qkv(h=8)
    _, k, v = qkv(h=2, seed=1)
    rep = q.shape[2] // k.shape[2]
    with jax.default_matmul_precision("highest"):
        ref = causal_reference(q, jnp.repeat(k, rep, axis=2),
                               jnp.repeat(v, rep, axis=2))
        out = _run_sharded(lambda a, b, c: ring_attention(a, b, c, "sp"),
                           sp_mesh, q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ring_attention_rejects_nondivisible_gqa(sp_mesh):
    q, _, _ = qkv(h=8)
    _, k, v = qkv(h=3, seed=1)
    with pytest.raises(ValueError, match="multiple of kv heads"):
        _run_sharded(lambda a, b, c: ring_attention(a, b, c, "sp"),
                     sp_mesh, q, k, v)


def test_ulysses_rejects_unsplittable_gqa_kv(sp_mesh):
    """GQA kv that can't split over the axis must fail loudly and point at
    the ring path, not mis-shard through the all-to-all (ADVICE r2 #1)."""
    q, _, _ = qkv(h=8)
    _, k, v = qkv(h=2, seed=1)  # 2 kv heads % 4 devices != 0
    with pytest.raises(ValueError, match="GQA kv heads"):
        _run_sharded(lambda a, b, c: ulysses_attention(a, b, c, "sp"),
                     sp_mesh, q, k, v)


@pytest.mark.parametrize("impl", ["dense", "flash"])
def test_ulysses_gqa_matches_oracle(sp_mesh, impl):
    """GQA kv that DOES divide the axis (4 kv heads / 4 devices) must shard
    through the all-to-all and match the oracle — the split keeps the
    q→kv grouping contiguous per device."""
    q, _, _ = qkv(h=8, t=128 if impl == "flash" else 64)
    _, k, v = qkv(h=4, t=128 if impl == "flash" else 64, seed=1)
    rep = q.shape[2] // k.shape[2]
    with jax.default_matmul_precision("highest"):
        ref = causal_reference(q, jnp.repeat(k, rep, axis=2),
                               jnp.repeat(v, rep, axis=2))
        out = _run_sharded(
            lambda a, b, c: ulysses_attention(a, b, c, "sp", impl=impl),
            sp_mesh, q, k, v)
    tol = 2e-2 if impl == "flash" else 2e-5
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=tol, rtol=tol)


def test_ring_attention_matches_oracle_fast():
    """Fast-tier dense-oracle pin (ISSUE 19 promotion satellite): the ring
    schedule vs the causal reference at the smallest ring (2 devices,
    short sequence) — the online-softmax rescale is pinned at float32
    tolerance outside -m slow too."""
    mesh = Mesh(np.asarray(jax.devices()[:2]), ("sp",))
    q, k, v = qkv(b=1, t=16, h=2, d=8, seed=4)
    with jax.default_matmul_precision("highest"):
        ref = causal_reference(q, k, v)
        out = shard_map(
            lambda a, b, c: ring_attention(a, b, c, "sp"), mesh=mesh,
            in_specs=P(None, "sp"), out_specs=P(None, "sp"),
            check_vma=False)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
