"""Autotuner tests: native GP sanity and end-to-end parameter-manager
convergence toward a configuration with higher simulated throughput
(reference parameter_manager.cc / optim/, SURVEY.md §2.1)."""

import math

import numpy as np
import pytest

from horovod_tpu.autotune import ParameterManager, gp_fit_predict


def test_gp_interpolates_and_is_uncertain_far_away():
    X = [[0.0], [0.5], [1.0]]
    y = [0.0, 1.0, 0.0]
    mu_mid, sigma_mid = gp_fit_predict(X, y, [0.5])
    assert abs(mu_mid - 1.0) < 0.1          # near-interpolation at a sample
    assert sigma_mid < 0.3
    _, sigma_far = gp_fit_predict(X, y, [3.0])
    assert sigma_far > sigma_mid            # uncertainty grows off-sample


def test_gp_predict_matches_numpy_reference():
    """Cross-check the native Cholesky path against a numpy GP on random data."""
    rng = np.random.default_rng(0)
    X = rng.uniform(size=(12, 2))
    y = np.sin(3 * X[:, 0]) + X[:, 1] ** 2
    xstar = np.array([0.3, 0.7])

    mu, sigma = gp_fit_predict(X.tolist(), y.tolist(), xstar.tolist())

    # numpy reference with identical kernel/normalization (l=0.3, sf2=1, sn2=1e-4)
    l2, sf2, sn2 = 0.09, 1.0, 1e-4
    ym, ys = y.mean(), y.std(ddof=1)
    yn = (y - ym) / ys
    d2 = ((X[:, None, :] - X[None, :, :]) ** 2).sum(-1)
    K = sf2 * np.exp(-0.5 * d2 / l2) + sn2 * np.eye(len(X))
    ks = sf2 * np.exp(-0.5 * ((X - xstar) ** 2).sum(-1) / l2)
    alpha = np.linalg.solve(K, yn)
    mu_ref = ks @ alpha * ys + ym
    v = np.linalg.solve(np.linalg.cholesky(K), ks)
    sigma_ref = math.sqrt(max(sf2 - v @ v, 1e-12)) * ys
    assert abs(mu - mu_ref) < 1e-6
    assert abs(sigma - sigma_ref) < 1e-6


def simulated_throughput(threshold: int, cycle_ms: float) -> float:
    """Synthetic objective: best at large threshold, ~8 ms cycle."""
    t_mb = threshold / (1 << 20)
    return (math.log2(t_mb + 1) / 8.0) * math.exp(-((cycle_ms - 8.0) ** 2) / 50.0)


def test_parameter_manager_converges_to_better_config():
    pm = ParameterManager(fusion_threshold=2 << 20, cycle_time_ms=40.0)
    start_score = simulated_throughput(2 << 20, 40.0)
    # Feed samples: bytes/seconds chosen so bytes/us == simulated throughput.
    for _ in range(3000):
        if not pm.active:
            break
        score = simulated_throughput(pm.fusion_threshold, pm.cycle_time_ms)
        pm.update(int(score * 1e6), 1.0)  # bytes per 1 s -> score bytes/us
    final_score = simulated_throughput(pm.fusion_threshold, pm.cycle_time_ms)
    assert not pm.active                    # tuner froze at its best config
    assert final_score > start_score * 1.5  # materially better than the start
    pm.close()


def test_parameter_manager_respects_pins():
    pm = ParameterManager(fusion_threshold=8 << 20, cycle_time_ms=5.0,
                          threshold_pinned=True, cycle_pinned=False)
    for _ in range(3000):
        if not pm.active:
            break
        pm.update(1000000, 0.01)
    assert pm.fusion_threshold == 8 << 20   # pinned knob never moved
    pm.close()


def test_fully_pinned_manager_is_inactive():
    pm = ParameterManager(threshold_pinned=True, cycle_pinned=True)
    assert not pm.active
    assert pm.update(100, 0.1) is False
    pm.close()


def test_compiled_path_tuner_measures_and_picks():
    """The compiled-path tuner re-jits a real DistributedOptimizer step per
    candidate config, measures it, refines with GP/EI, and returns a best
    config from the measured table (VERDICT r2 missing #2)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax
    from horovod_tpu.compat import shard_map
    from jax.sharding import PartitionSpec as P

    import horovod_tpu as hvd
    from horovod_tpu.jax.autotune import tune

    mesh = hvd.data_parallel_mesh()
    n = mesh.size
    x = jnp.ones((n * 4, 16))
    y = jnp.zeros((n * 4,), jnp.int32)
    w = jnp.zeros((16, 8))
    built = []

    def step_factory(fusion_threshold, compression):
        built.append((fusion_threshold, compression))
        opt = hvd.jax.DistributedOptimizer(
            optax.sgd(0.1), fusion_threshold=fusion_threshold,
            compression=hvd.Compression.bf16 if compression == "bf16"
            else hvd.Compression.none)
        state = [w, opt.init(w)]

        def train(w, ostate, x, y):
            def loss_fn(w):
                logits = x @ w
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits, y).mean()

            g = jax.grad(loss_fn)(w)
            up, ostate = opt.update(g, ostate, w)
            return optax.apply_updates(w, up), ostate

        step = jax.jit(shard_map(train, mesh=mesh,
                                 in_specs=(P(), P(), P("hvd"), P("hvd")),
                                 out_specs=(P(), P()), check_vma=False))

        def run():
            state[0], state[1] = step(state[0], state[1], x, y)
            jax.block_until_ready(state[0])

        return run

    import tempfile

    with tempfile.NamedTemporaryFile(suffix=".csv", mode="r") as f:
        report = tune(step_factory,
                      thresholds=(1 << 18, 1 << 22),
                      branches=[{"compression": "none"},
                                {"compression": "bf16"}],
                      warmup=1, iters=3, reps=2, gp_rounds=1,
                      log_path=f.name)
        log = open(f.name).read()

    # every (branch x seed threshold) measured, plus up to 1 GP suggestion
    # per branch
    assert len(report.table) >= 4
    assert {c for _, c in built} == {"none", "bf16"}
    assert report.best.steps_per_s == max(m.steps_per_s for m in report.table)
    assert report.best.config["fusion_threshold"] in {t for t, _ in built}
    assert log.startswith("branch,fusion_threshold,steps_per_s")
    assert len(log.strip().splitlines()) == len(report.table) + 1
    assert "MiB" in report.knob_curve()


def test_ei_suggest_prefers_unexplored_peak():
    """EI over the native GP must suggest a threshold between measured
    points when the curve indicates an interior peak."""
    from horovod_tpu.jax.autotune import _ei_suggest

    measured = {1 << 20: 1.0, 1 << 24: 3.0, 1 << 28: 1.2}
    nxt = _ei_suggest(measured, 1 << 20, 1 << 28)
    assert nxt is not None
    assert (1 << 20) < nxt < (1 << 28)
    assert all(abs(np.log2(nxt) - np.log2(t)) > 0.1 for t in measured)


import numpy as np  # noqa: E402  (used by the EI test)
