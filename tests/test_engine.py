"""Eager engine tests: handle lifecycle, single-process completion, and
multi-process coordinator semantics exercised with in-process rank threads
(reference runs the same file under mpirun; here the TCP coordinator is the
wire, SURVEY.md §4)."""

import threading

import numpy as np
import pytest

import horovod_tpu as hvd
from horovod_tpu.common.config import Config
from horovod_tpu.common.engine import (
    HandleManager,
    PyEngine,
    TensorShapeMismatchError,
    _Client,
    _Coordinator,
)
from horovod_tpu.common.topology import Topology


def test_handle_manager():
    hm = HandleManager()
    h1, h2 = hm.allocate(), hm.allocate()
    assert h1 != h2
    assert not hm.poll(h1)
    hm.mark_done(h1, None, 42)
    assert hm.poll(h1)
    assert hm.wait_and_clear(h1) == 42
    assert not hm.poll(h1)  # cleared
    err = RuntimeError("boom")
    hm.mark_done(h2, err, None)
    with pytest.raises(RuntimeError):
        hm.wait_and_clear(h2)


def engine_single():
    topo = Topology(0, 1, 0, 1, 0, 1)
    cfg = Config(cycle_time_ms=1.0)
    return PyEngine(topo, cfg)


def test_single_process_ops():
    eng = engine_single()
    try:
        a = np.arange(6.0).reshape(2, 3)
        np.testing.assert_array_equal(eng.run("allreduce", a, "t1"), a)
        np.testing.assert_array_equal(eng.run("allgather", a, "t2"), a)
        np.testing.assert_array_equal(eng.run("broadcast", a, "t3"), a)
    finally:
        eng.shutdown()


def test_async_poll_synchronize():
    eng = engine_single()
    try:
        h = eng.enqueue("allreduce", np.ones(4), "async1")
        out = eng.synchronize(h, timeout=10)
        np.testing.assert_array_equal(out, np.ones(4))
    finally:
        eng.shutdown()


def test_shutdown_fails_pending():
    eng = engine_single()
    eng._shutdown.set()  # freeze the loop
    eng._thread.join(timeout=5)
    eng._queue.append({"op": "allreduce", "array": np.ones(2), "name": "x",
                       "root": 0, "average": True, "handle": eng.handles.allocate(),
                       "t": 0.0})
    h = eng._queue[-1]["handle"]
    eng.shutdown()
    with pytest.raises(RuntimeError):
        eng.synchronize(h, timeout=1)


# ------------------------------------------------- multi-rank via coordinator

WORLD = 4


KEY = b"test-secret"


def run_ranks(fn):
    """Run fn(rank, client) on WORLD threads against one coordinator."""
    coord = _Coordinator(WORLD, "127.0.0.1", 0, key=KEY)
    port = coord.server.getsockname()[1]
    coord.start()
    results: dict[int, object] = {}
    errors: list[Exception] = []

    def worker(rank):
        try:
            client = _Client("127.0.0.1", port, rank, key=KEY)
            try:
                results[rank] = fn(rank, client)
            finally:
                client.close()
        except Exception as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(r,)) for r in range(WORLD)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    coord.stop()
    assert not errors, errors
    return results


def test_coordinator_allreduce():
    def fn(rank, client):
        arr = np.full((3,), float(rank))
        req = [{"name": "g", "op": "allreduce", "shape": (3,), "dtype": "float64",
                "root": 0, "average": True}]
        out = client.exchange(req, {"g": arr})
        return out["g"]

    results = run_ranks(fn)
    expect = np.full((3,), np.mean(np.arange(WORLD)))
    for r in range(WORLD):
        err, val = results[r]
        assert err is None
        np.testing.assert_allclose(val, expect)


def test_coordinator_allgather_broadcast():
    def fn(rank, client):
        arr = np.full((rank + 1, 2), float(rank))  # variable dim 0!
        req = [
            {"name": "ag", "op": "allgather", "shape": arr.shape, "dtype": "float64",
             "root": 0, "average": True},
            {"name": "bc", "op": "broadcast", "shape": (2,), "dtype": "float64",
             "root": 2, "average": True},
        ]
        out = client.exchange(req, {"ag": arr, "bc": np.full((2,), float(rank))})
        return out

    results = run_ranks(fn)
    total_rows = sum(r + 1 for r in range(WORLD))
    for r in range(WORLD):
        err, val = results[r]["ag"]
        assert err is None
        assert val.shape == (total_rows, 2)  # variable-dim allgather (Allgatherv)
        err, val = results[r]["bc"]
        assert err is None
        np.testing.assert_allclose(val, np.full((2,), 2.0))


def test_coordinator_shape_mismatch_error():
    """Rank-divergent shapes must produce an error on every rank, not a hang
    (reference ConstructResponse error paths, test/test_tensorflow.py:265-333)."""

    def fn(rank, client):
        shape = (3,) if rank != 1 else (4,)
        arr = np.ones(shape)
        req = [{"name": "bad", "op": "allreduce", "shape": shape, "dtype": "float64",
                "root": 0, "average": True}]
        return client.exchange(req, {"bad": arr})["bad"]

    results = run_ranks(fn)
    for r in range(WORLD):
        err, val = results[r]
        assert err is not None and "Mismatched" in err


def test_coordinator_dtype_mismatch_error():
    def fn(rank, client):
        dtype = "float64" if rank != 2 else "int32"
        arr = np.ones((2,), dtype=np.float64 if rank != 2 else np.int32)
        req = [{"name": "badt", "op": "allreduce", "shape": (2,), "dtype": dtype,
                "root": 0, "average": True}]
        return client.exchange(req, {"badt": arr})["badt"]

    results = run_ranks(fn)
    for r in range(WORLD):
        err, val = results[r]
        assert err is not None and "Mismatched data types" in err


def test_coordinator_alltoall_reducescatter():
    def fn(rank, client):
        a2a = np.full((WORLD, 2), float(rank))
        rs = np.arange(WORLD * 2, dtype=np.float64)
        req = [
            {"name": "a2a", "op": "alltoall", "shape": a2a.shape, "dtype": "float64",
             "root": 0, "average": False},
            {"name": "rs", "op": "reducescatter", "shape": rs.shape, "dtype": "float64",
             "root": 0, "average": False},
        ]
        return client.exchange(req, {"a2a": a2a, "rs": rs})

    results = run_ranks(fn)
    for r in range(WORLD):
        err, val = results[r]["a2a"]
        assert err is None
        expect = np.repeat(np.arange(WORLD, dtype=np.float64), 2).reshape(WORLD, 2)
        np.testing.assert_allclose(val, expect)
        err, val = results[r]["rs"]
        assert err is None
        np.testing.assert_allclose(
            val, WORLD * np.arange(WORLD * 2, dtype=np.float64)[r * 2:(r + 1) * 2])


def test_coordinator_rejects_unauthenticated_frames():
    """A frame with a bad HMAC must be dropped without unpickling (ADVICE
    high: the round-1 channel unpickled unauthenticated bytes — remote code
    execution via pickle). The authenticated client still works after."""
    import socket as socket_mod
    import struct as struct_mod

    coord = _Coordinator(1, "127.0.0.1", 0, key=KEY)
    port = coord.server.getsockname()[1]
    coord.start()
    try:
        # attacker: valid pickle, wrong key
        raw = socket_mod.create_connection(("127.0.0.1", port), timeout=5)
        import pickle as pickle_mod

        payload = pickle_mod.dumps({"kind": "exchange", "rank": 0,
                                    "requests": [], "arrays": {}})
        import hmac as hmac_mod
        from hashlib import sha256 as sha256_mod

        bad_digest = hmac_mod.new(b"wrong-key", payload, sha256_mod).digest()
        raw.sendall(bad_digest + struct_mod.pack("!Q", len(payload)) + payload)
        # server must close the connection without answering
        raw.settimeout(5)
        assert raw.recv(1) == b"", "coordinator answered an unauthenticated frame"
        raw.close()
        # a properly keyed client is unaffected
        client = _Client("127.0.0.1", port, 0, key=KEY)
        out = client.exchange(
            [{"name": "t", "op": "allreduce", "shape": (2,),
              "dtype": "float64", "root": 0, "average": False}],
            {"t": np.ones(2)})
        err, val = out["t"]
        assert err is None
        np.testing.assert_allclose(val, np.ones(2))
        client.close()
    finally:
        coord.stop()


def test_fast_tensor_not_coupled_to_slow_batchmate():
    """VERDICT r3 weak #5: a tensor whose peers are all present must NOT
    wait on a batch-mate whose peer contribution is late. Rank 0 submits
    {fast, slow} in one exchange; rank 1 contributes fast immediately but
    slow only ~2s later. Rank 0's first exchange must return fast well
    before slow exists (partial response), and a metadata-only re-poll
    must complete slow without re-shipping bytes."""
    import time as _t

    def fn(rank, client):
        if rank >= 2:
            return None
        fast = np.full((4,), float(rank))
        slow = np.full((4,), 10.0 + rank)
        if rank == 0:
            req = [
                {"name": "fast", "op": "allreduce", "shape": (4,),
                 "dtype": "float64", "root": 0, "average": False},
                {"name": "slow", "op": "allreduce", "shape": (4,),
                 "dtype": "float64", "root": 0, "average": False},
            ]
            # All-unready exchange must hand control back after a short
            # tick, not block for 30 s — otherwise tensors enqueued in
            # LATER cycles queue behind the straggler too (the engine loop
            # is single-threaded).
            t0 = _t.monotonic()
            out = client.exchange(
                [req[1]], {"slow": slow})
            assert _t.monotonic() - t0 < 1.0, "all-unready exchange blocked"
            assert "slow" not in out
            t0 = _t.monotonic()
            out = client.exchange(req, {"fast": fast})
            first_rt = _t.monotonic() - t0
            got = dict(out)
            # re-poll (metadata only — bytes for both already shipped)
            deadline = _t.monotonic() + 20
            while "slow" not in got and _t.monotonic() < deadline:
                got.update(client.exchange([req[1]], {}))
                _t.sleep(0.05)
            return first_rt, got
        _t.sleep(0.1)
        client.exchange([{"name": "fast", "op": "allreduce", "shape": (4,),
                          "dtype": "float64", "root": 0, "average": False}],
                        {"fast": fast})
        _t.sleep(2.0)
        out = client.exchange([{"name": "slow", "op": "allreduce",
                                "shape": (4,), "dtype": "float64", "root": 0,
                                "average": False}], {"slow": slow})
        return out

    global WORLD
    saved = WORLD
    WORLD = 2
    try:
        results = run_ranks(fn)
    finally:
        WORLD = saved
    first_rt, got = results[0]
    assert first_rt < 1.5, (
        f"fast tensor waited {first_rt:.1f}s on its slow batch-mate")
    assert "fast" in got and "slow" in got
    np.testing.assert_allclose(got["fast"][1], [1.0] * 4)
    np.testing.assert_allclose(got["slow"][1], [21.0] * 4)
