"""Pod-scale telemetry tree tests (ISSUE 17): the associative merge monoid
(host-then-root bitwise == flat, fuzz + adversarial float fixtures), the
delta wire format and its seq/need_full resync on both hops, composed
clock offsets under injected per-hop jitter, the TelemetryAgent /
RankTelemetryClient / RootAggregator protocol end to end over real TCP,
the ``telemetry_lag`` anomaly (fires, NAMES the host, stops after
forget_host), the leader ``/metrics.json?host=1`` view, bundle leader
sweeps with named coverage gaps, and the watchdog/anomaly event plumbing.
"""

from __future__ import annotations

import json
import os
import random
import secrets
import sys
import threading
import time
import urllib.error
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import pytest

from horovod_tpu.metrics.aggregate import (  # noqa: E402
    apply_snapshot_delta,
    combine_partials,
    empty_partial,
    finalize_partial,
    lift_snapshot,
    merge_partials,
    merge_snapshots,
    snapshot_delta,
)
from horovod_tpu.metrics.anomaly import (  # noqa: E402
    TELEMETRY_LAG_TICKS,
    AnomalyDetector,
)
from horovod_tpu.metrics.registry import MetricsRegistry  # noqa: E402
from horovod_tpu.telemetry import (  # noqa: E402
    RankTelemetryClient,
    TelemetryAgent,
    interval_s_from_env,
    plan_tree,
)
from horovod_tpu.telemetry.root import RootAggregator  # noqa: E402
from horovod_tpu.tracing.clock import compose_offsets  # noqa: E402

KEY = secrets.token_bytes(32)
LOOP = "127.0.0.1"


def _snap(rank: int, tick: int = 1, rng: random.Random = None) -> dict:
    """A synthetic rank snapshot; with ``rng``, values are adversarial
    floats (non-dyadic decimals, tiny/huge magnitudes) whose sums are
    grouping-sensitive in plain fp arithmetic."""
    rv = rng.random if rng else (lambda: 0.1)
    counters = {"horovod_allreduce_ops_total": 3.0 * tick + rank,
                "horovod_x_total": 0.1 + rank * 0.3 + rv() * 1e-9,
                'horovod_labeled_total{op="ar"}': rv() * 1e12}
    gauges = {"horovod_q_depth": rank * 0.7 + rv(),
              "horovod_step_time_s": 0.1 * (1 + (rank + tick) % 3)}
    hist = {"count": 10 * tick + rank, "sum": 0.3 * tick + rv(),
            "p50": 0.1, "p90": 0.2, "p99": 0.3,
            "buckets": [[0.1, 4 * tick], [1.0, 8 * tick],
                        ["+Inf", 10 * tick + rank]]}
    return {"schema": "horovod_tpu.metrics.v1",
            "time_unix_s": 1.7e9 + tick + rank * 0.01,
            "counters": counters, "gauges": gauges,
            "histograms": {"horovod_lat_seconds": hist},
            "info": {"device": f"tpu:{rank}"}}


# --------------------------------------------------- the merge monoid


def test_host_then_root_merge_bitwise_equals_flat_fuzz():
    """The tentpole invariant: for random worlds and random host
    groupings, lifting per host, combining host partials, then finalizing
    is BITWISE identical to the flat merge — serialized JSON equality, so
    every float bit pattern counts."""
    rng = random.Random(1234)
    for trial in range(25):
        world = rng.randrange(2, 33)
        snaps = [_snap(r, tick=rng.randrange(1, 5), rng=rng)
                 for r in range(world)]
        flat = merge_snapshots(snaps)
        # random contiguous host grouping (the barrel-shift layout)
        cuts = sorted(rng.sample(range(1, world), min(rng.randrange(0, 5),
                                                      world - 1)))
        groups, lo = [], 0
        for c in cuts + [world]:
            groups.append(list(range(lo, c)))
            lo = c
        host_parts = [merge_partials([lift_snapshot(r, snaps[r])
                                      for r in g]) for g in groups]
        tree = finalize_partial(merge_partials(host_parts))
        assert json.dumps(tree, sort_keys=True) == \
            json.dumps(flat, sort_keys=True), f"trial {trial} diverged"


def test_merge_fixtures_nonassociative_floats():
    """0.1 + 0.2 + 0.3 groups differently in fp ((a+b)+c != a+(b+c));
    the exact-rational partial makes both groupings identical. None
    snapshots (a rank that never reported) and non-finite values are
    absorbed without poisoning the sums."""
    snaps = []
    for r, v in enumerate([0.1, 0.2, 0.3, float("nan"), float("inf")]):
        s = _snap(r)
        s["counters"] = {"horovod_t_total": v}
        s["gauges"] = {}
        s["histograms"] = {}
        snaps.append(s)
    snaps.append(None)
    flat = merge_snapshots(snaps)
    left = combine_partials(
        combine_partials(lift_snapshot(0, snaps[0]),
                         lift_snapshot(1, snaps[1])),
        merge_partials([lift_snapshot(r, snaps[r]) for r in range(2, 6)]))
    right = combine_partials(
        lift_snapshot(0, snaps[0]),
        merge_partials([lift_snapshot(r, snaps[r]) for r in range(1, 6)]))
    assert json.dumps(finalize_partial(left), sort_keys=True) == \
        json.dumps(finalize_partial(right), sort_keys=True) == \
        json.dumps(flat, sort_keys=True)
    # non-finite inputs counted as 0, not NaN-poisoning
    assert flat["counters"]["horovod_t_total"] == pytest.approx(0.6)
    assert flat["ranks_reporting"] == 5


def test_partial_survives_json_wire():
    """Host partials cross two TCP hops as JSON — a partial must combine
    identically after a dumps/loads round trip (Fraction pairs are int
    pairs, never floats)."""
    part = merge_partials([lift_snapshot(r, _snap(r)) for r in range(4)])
    wired = json.loads(json.dumps(part))
    more = lift_snapshot(7, _snap(7))
    assert json.dumps(finalize_partial(combine_partials(wired, more)),
                      sort_keys=True) == \
        json.dumps(finalize_partial(combine_partials(part, more)),
                   sort_keys=True)
    assert combine_partials(empty_partial(), wired)["ranks"] == \
        part["ranks"]


def test_snapshot_delta_roundtrip_and_size():
    prev, cur = _snap(3, tick=1), _snap(3, tick=2)
    cur["counters"]["horovod_new_total"] = 1.0
    del cur["gauges"]["horovod_q_depth"]
    d = snapshot_delta(prev, cur)
    assert apply_snapshot_delta(prev, d) == cur
    # unchanged series do not travel
    tiny = dict(prev, time_unix_s=prev["time_unix_s"] + 1)
    d2 = snapshot_delta(prev, tiny)
    assert len(json.dumps(d2)) < len(json.dumps(prev)) / 4
    # deltas work on PARTIALS too (the leader->root hop)
    pa = merge_partials([lift_snapshot(r, _snap(r, 1)) for r in range(3)])
    pb = merge_partials([lift_snapshot(r, _snap(r, 2)) for r in range(3)])
    assert apply_snapshot_delta(pa, snapshot_delta(pa, pb)) == pb


# --------------------------------------------------- clocks


def test_compose_offsets_accuracy_under_jitter():
    """Two simulated hops with asymmetric per-hop jitter: the composed
    (offset, error) must bracket the true end-to-end offset within the
    summed error bounds — the guarantee that makes tree-composed spans
    still order correctly in the merged trace."""
    from horovod_tpu.tracing.clock import estimate_offset_ns

    rng = random.Random(7)
    true_ab, true_bc = 5_000_000, -2_000_000   # a->b, b->c true offsets

    def probe(true_off):
        def one():
            # min-RTT estimator: jittered both ways, bounded by max RTT
            there = rng.randrange(10_000, 300_000)
            back = rng.randrange(10_000, 300_000)
            t = time.monotonic_ns() + true_off + there
            time.sleep((there + back) / 1e9)
            return t
        return one

    hop_ab = estimate_offset_ns(probe(true_ab), rounds=8)
    hop_bc = estimate_offset_ns(probe(true_bc), rounds=8)
    off, err = compose_offsets(hop_ab, hop_bc)
    assert err >= hop_ab[1] and err >= hop_bc[1]
    assert abs(off - (true_ab + true_bc)) <= err + 2_000_000
    assert compose_offsets((3, 1), (-5, 2)) == (-2, 3)


# --------------------------------------------------- agent protocol


def test_agent_push_delta_and_need_full_resync(tmp_path):
    reg = MetricsRegistry()
    ag = TelemetryAgent(KEY, host_name="hA", flight_dir="", trace_dir="",
                        interval_s=0.5, reg=reg)
    try:
        rc = RankTelemetryClient([(LOOP, ag.port)], KEY, rank=4)
        assert rc.interval_s == 0.5
        req1 = rc.push(_snap(4, 1))
        assert req1["full"] is True
        req2 = rc.push(_snap(4, 2))
        assert req2["full"] is False   # delta-compressed steady state
        assert len(json.dumps(req2["body"])) < \
            len(json.dumps(req1["body"]))
        view = ag.host_view()
        assert json.dumps(view, sort_keys=True) == \
            json.dumps(merge_snapshots([None] * 4 + [_snap(4, 2)]),
                       sort_keys=True).replace('"ranks": 5', '"ranks": 1')
        # seq gap (agent lost state): rank transparently resends full
        with ag._state_lock:
            ag._ranks.clear()
        rc.push(_snap(4, 3))
        assert ag.coverage()["ranks"]["4"]["seq"] == 2
        # counted per ACCEPTED push: 2 + the resent full (the rejected
        # delta that triggered need_full does not count)
        assert reg.counter("horovod_telemetry_pushes_total",
                           hop="rank").value == 3
        rc.close()
    finally:
        ag.stop()


def test_agent_events_batched_and_counted():
    reg = MetricsRegistry()
    ag = TelemetryAgent(KEY, host_name="hB", flight_dir="", trace_dir="",
                        interval_s=1.0, reg=reg)
    try:
        rc = RankTelemetryClient([(LOOP, ag.port)], KEY, rank=0)
        rc.push_events([{"kind": "stall", "rank": 0},
                        {"kind": "anomaly", "anomaly": "ttft_slo"},
                        {"kind": "custom"}])
        rc.event_sink({"kind": "stall", "rank": 0})   # never raises
        evs = ag.drain_events()
        assert len(evs) == 4 and all(e["_rank"] == 0 for e in evs)
        assert ag.drain_events() == []
        assert reg.counter("horovod_telemetry_events_total",
                           source="watchdog").value == 2
        assert reg.counter("horovod_telemetry_events_total",
                           source="anomaly").value == 1
        assert reg.counter("horovod_telemetry_events_total",
                           source="other").value == 1
        rc.close()
    finally:
        ag.stop()


def test_root_aggregator_delta_resync_and_coverage():
    reg = MetricsRegistry()
    clock = [100.0]
    root = RootAggregator(interval_s=1.0, reg=reg, now=lambda: clock[0])
    pa1 = merge_partials([lift_snapshot(r, _snap(r, 1)) for r in (0, 1)])
    pa2 = merge_partials([lift_snapshot(r, _snap(r, 2)) for r in (0, 1)])
    assert root.ingest({"host": "hA", "seq": 0, "full": True, "body": pa1,
                        "interval_s": 1.0}) == \
        {"ok": True, "need_full": False}
    r = root.ingest({"host": "hA", "seq": 1, "full": False,
                     "body": snapshot_delta(pa1, pa2), "interval_s": 1.0})
    assert r == {"ok": True, "need_full": False}
    assert root.partials() == [pa2]
    # seq gap (root restarted relative to the leader) -> need_full
    assert root.ingest({"host": "hA", "seq": 5, "full": False,
                        "body": {}})["need_full"] is True
    assert root.covered_ranks() == {0, 1}
    clock[0] += 2.5
    assert root.ages_ticks()["hA"] == pytest.approx(2.5)
    assert reg.counter("horovod_telemetry_pushes_total",
                       hop="host").value == 2
    root.forget_host("hA")
    assert root.hosts() == [] and root.covered_ranks() == set()


# --------------------------------------------------- driver e2e


def test_driver_tree_pod_metrics_bitwise_and_mixed(tmp_path):
    """End to end over real TCP: ranks -> two TelemetryAgents -> driver
    ``host_metrics``; plus one straggler rank pushing DIRECT via the flat
    ``metrics`` path. pod_metrics must bitwise-equal the flat merge of
    all snapshots — covered ranks are not double-counted even when the
    same rank ALSO pushed directly."""
    from horovod_tpu.runner.network import BasicClient
    from horovod_tpu.runner.service import DriverService

    world = 5
    snaps = {r: _snap(r, tick=2) for r in range(world)}
    driver = DriverService(world, KEY)
    agents, rcs = [], []
    try:
        for h, ranks in enumerate([(0, 1), (2, 3)]):
            ag = TelemetryAgent(KEY, host_name=f"h{h}", flight_dir="",
                                trace_dir="", interval_s=1.0,
                                expected_ranks=ranks,
                                reg=MetricsRegistry())
            ag.attach_root([(LOOP, driver.port)], probe_rounds=2,
                           start_loop=False)
            agents.append(ag)
            for r in ranks:
                rc = RankTelemetryClient([(LOOP, ag.port)], KEY, r)
                rc.push(snaps[r])
                rcs.append(rc)
            ag.push_to_root_once()
        # rank 4 is tree-less (no leader on its host): direct flat push
        c = BasicClient([(LOOP, driver.port)], KEY, timeout=10.0)
        c.request({"kind": "metrics", "rank": 4, "snapshot": snaps[4]})
        # rank 0 ALSO pushes directly (e.g. final result payload):
        # covered by host h0's partial, must not be double-counted
        c.request({"kind": "metrics", "rank": 0, "snapshot": snaps[0]})
        c.close()
        pod = driver.pod_metrics()
        flat = merge_snapshots([snaps[r] for r in range(world)])
        assert json.dumps(pod, sort_keys=True) == \
            json.dumps(flat, sort_keys=True)
        assert pod["ranks"] == world and pod["ranks_reporting"] == world
        # second tick: the leader->root hop is delta-compressed
        snaps2 = {r: _snap(r, tick=3) for r in range(world)}
        for rc in rcs:
            rc.push(snaps2[rc.rank])
        for ag in agents:
            ag.push_to_root_once()
            assert ag._root_seq == 2
        st = driver.telemetry_root().staleness()
        assert sorted(st) == ["h0", "h1"]
        assert st["h0"]["expected"] == [0, 1]
    finally:
        for rc in rcs:
            rc.close()
        for ag in agents:
            ag.stop()
        driver.stop()


def test_elastic_membership_prunes_telemetry_hosts():
    """A generation formed without a host must forget that host's partial
    and its staleness gauge (no spurious telemetry_lag on a host that
    legitimately left)."""
    from horovod_tpu.runner.service import ElasticDriverService

    drv = ElasticDriverService(KEY)
    try:
        root = drv.telemetry_root()
        for host in ("hGone", "hStays"):
            root.ingest({"host": host, "seq": 0, "full": True,
                         "body": lift_snapshot(0, _snap(0)),
                         "interval_s": 1.0})
        root.publish()
        assert root.reg.remove("x_not_there") is False
        drv.begin_reset({0, 1})
        for i in (0, 1):
            drv.handle({"kind": "register", "index": i,
                        "host_hash": "hStays",
                        "addresses": [(LOOP, 1)], "coord_port": 1,
                        "jax_coord_port": 2}, None)
        assert drv.generation == 1
        assert root.hosts() == ["hStays"]
        gauges = root.reg.snapshot()["gauges"]
        assert 'horovod_telemetry_snapshot_age_ticks{host="hGone"}' \
            not in gauges
        assert 'horovod_telemetry_snapshot_age_ticks{host="hStays"}' \
            in gauges
    finally:
        drv.stop()


# --------------------------------------------------- telemetry_lag


def test_telemetry_lag_fires_names_host_and_clears():
    reg = MetricsRegistry()
    clock = [50.0]
    root = RootAggregator(interval_s=0.5, reg=reg, now=lambda: clock[0])

    class _NullFlight:
        def event(self, *a, **k):
            pass

        def dump(self, *a, **k):
            return ""

    det = AnomalyDetector(reg=reg, cooldown_s=1e9, flight=_NullFlight())
    root.ingest({"host": "hFresh", "seq": 0, "full": True,
                 "body": lift_snapshot(0, _snap(0)), "interval_s": 0.5})
    root.ingest({"host": "hDead", "seq": 0, "full": True,
                 "body": lift_snapshot(1, _snap(1)), "interval_s": 0.5})
    root.publish()
    assert det.tick() == []   # both fresh
    clock[0] += (TELEMETRY_LAG_TICKS + 1) * 0.5
    root.ingest({"host": "hFresh", "seq": 1, "full": False,
                 "body": snapshot_delta(lift_snapshot(0, _snap(0)),
                                        lift_snapshot(0, _snap(0, 2))),
                 "interval_s": 0.5})
    assert "telemetry_lag" in det.tick()
    ev = det.history[-1]
    assert ev["hosts"] == ["hDead"] and ev["threshold_ticks"] == \
        TELEMETRY_LAG_TICKS
    assert ev["max_age_ticks"] > TELEMETRY_LAG_TICKS
    assert reg.counter("horovod_anomaly_total",
                       kind="telemetry_lag").value == 1
    # the host leaves membership: its gauge goes with it, no refire
    root.forget_host("hDead")
    det2 = AnomalyDetector(reg=reg, cooldown_s=1e9, flight=_NullFlight())
    root.publish()
    assert "telemetry_lag" not in det2.tick()
    gauges = reg.snapshot()["gauges"]
    assert 'horovod_telemetry_snapshot_age_ticks{host="hDead"}' \
        not in gauges


# --------------------------------------------------- exposition


def test_metrics_http_host_view():
    from horovod_tpu.metrics.exposition import MetricsServer

    reg = MetricsRegistry()
    reg.counter("horovod_local_total").inc(2)
    view = {"box": None}
    srv = MetricsServer(0, reg=reg, host_view=lambda: view["box"])
    plain = MetricsServer(0, reg=reg)
    try:
        url = f"http://{LOOP}:{srv.port}/metrics.json"
        # leader with no pushes yet: 503, a scraper should retry
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(url + "?host=1")
        assert ei.value.code == 503
        view["box"] = finalize_partial(
            merge_partials([lift_snapshot(r, _snap(r))
                            for r in range(3)]))
        doc = json.loads(urllib.request.urlopen(
            url + "?host=1").read())
        assert doc["schema"] == "horovod_tpu.metrics.pod.v1"
        assert doc["ranks_reporting"] == 3
        # the un-suffixed path still serves the PROCESS view
        doc2 = json.loads(urllib.request.urlopen(url).read())
        assert doc2["schema"] == "horovod_tpu.metrics.v1"
        # a non-leader exposes no host view: 404 names the reason
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://{LOOP}:{plain.port}/metrics.json?host=1")
        assert ei.value.code == 404
    finally:
        srv.stop()
        plain.stop()


# --------------------------------------------------- bundle sweeps


def test_bundle_leader_sweep_names_gaps(tmp_path):
    from horovod_tpu.tracing.bundle import make_bundle
    from horovod_tpu.tracing.flight import FlightRecorder

    fdir = tmp_path / "flight"
    tdir = tmp_path / "trace"
    fdir.mkdir()
    tdir.mkdir()
    fr = FlightRecorder("rank0", flight_dir=str(fdir))
    fr.event("replica_death", replica=9, pid=1, state_was="up",
             reason="test")
    fr.close()
    # a torn ring: decode must FAIL NAMED, not vanish
    (fdir / "flight-rank1.ring").write_bytes(b"HVDFLT1\ngarbage")
    (tdir / "spans-rank0.jsonl").write_text(
        json.dumps({"meta": 1, "rank": 0, "clock_offset_ns": 0}) + "\n" +
        json.dumps({"tid": "t#1", "rank": 0, "name": "g", "op": "ar",
                    "phase": "enqueue", "t0": 10, "t1": 20}) + "\n")
    ag = TelemetryAgent(KEY, host_name="hSwept", flight_dir=str(fdir),
                        trace_dir=str(tdir), interval_s=100.0,
                        expected_ranks=(0, 1), reg=MetricsRegistry())
    rc = RankTelemetryClient([(LOOP, ag.port)], KEY, 0)
    rc.push(_snap(0))
    try:
        out = tmp_path / "bundle"
        summary = make_bundle(
            str(out),
            leaders=[f"{LOOP}:{ag.port}", f"{LOOP}:1"],   # :1 unreachable
            leader_key=KEY)
        manifest = (out / "MANIFEST.md").read_text()
        assert "## Pod coverage" in manifest
        # expected rank 1 never pushed -> partial, NAMED
        assert "| hSwept | partial |" in manifest
        assert "ranks [1] never pushed" in manifest
        # the dead leader is named unreachable
        assert f"| {LOOP}:1 | unreachable |" in manifest
        assert summary["coverage_gaps"] == ["hSwept", f"{LOOP}:1"]
        # the torn ring decode failure is NAMED with its host
        assert summary["flight_decode_failures"] == 1
        assert "flight-rank1.ring" in manifest and "hSwept" in manifest
        # the good ring's replica_death surfaced in the Verdict
        assert "replica 9 died" in manifest
        # swept spans built a merged trace
        trace = json.loads((out / "trace.json").read_text())
        assert any(e.get("ph") == "X" for e in trace["traceEvents"])
    finally:
        rc.close()
        ag.stop()


# --------------------------------------------------- event plumbing


def test_watchdog_event_sink_receives_stall():
    from horovod_tpu.metrics.watchdog import StallWatchdog

    from horovod_tpu.metrics.watchdog import StallInfo

    reg = MetricsRegistry()
    got = []
    wd = StallWatchdog(check_time_s=0.05, rank=3, reg=reg,
                       event_sink=got.append)
    try:
        wd.add_source(lambda: [StallInfo(name="grad0", op="allreduce",
                                         age_s=1.0)])
        deadline = time.monotonic() + 5.0
        while not got and time.monotonic() < deadline:
            time.sleep(0.02)
        assert got, "stall never reached the event sink"
        ev = got[0]
        assert ev["kind"] == "stall" and ev["rank"] == 3
        assert ev["stalled"][0]["name"] == "grad0"
    finally:
        wd.stop()


def test_service_stats_count_wire_bytes():
    from horovod_tpu.runner.network import BasicClient, BasicService

    class Echo(BasicService):
        def handle(self, req, client_addr):
            return {"ok": True, "echo": req.get("x")}

    svc = Echo(KEY)
    try:
        c = BasicClient([(LOOP, svc.port)], KEY, timeout=10.0)
        for i in range(3):
            assert c.request({"kind": "e", "x": i})["echo"] == i
        c.close()
        deadline = time.monotonic() + 2.0
        while svc.stats()["requests_total"] < 3 \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        st = svc.stats()
        assert st["connections_total"] == 1
        assert st["requests_total"] == 3
        # every frame costs 32B MAC + 8B length + payload (+ handshake)
        assert st["bytes_in"] > 3 * 40 and st["bytes_out"] > 3 * 40
    finally:
        svc.stop()


def test_tree_plan_and_interval_knob(monkeypatch):
    plan = plan_tree(["hB", "hB", "hA", "hA", "hA"])
    assert plan.hosts == ("hA", "hB")     # sorted, like rank assignment
    assert plan.leader_of == {"hA": 2, "hB": 0}
    assert plan.leader_for(4) == 2 and plan.leader_for(1) == 0
    assert plan.is_leader(2) and not plan.is_leader(3)
    assert plan.num_hosts == 2
    with pytest.raises(KeyError):
        plan.host_of(99)
    monkeypatch.setenv("HOROVOD_TELEMETRY_INTERVAL_S", "2.5")
    assert interval_s_from_env() == 2.5
    monkeypatch.setenv("HOROVOD_TELEMETRY_INTERVAL_S", "0.0001")
    assert interval_s_from_env() == 0.05   # floored, cannot busy-spin
    monkeypatch.setenv("HOROVOD_TELEMETRY_INTERVAL_S", "bogus")
    assert interval_s_from_env() == 1.0
