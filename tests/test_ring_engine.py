"""Ring data-plane tests for the native engine.

Round-2 evidence for the VERDICT items: tensor fusion actually executes
(fewer ring passes for many small tensors), the data plane is peer-to-peer
(per-rank wire traffic is O(bytes), not O(N*bytes) through rank 0 — the
property of the reference's NCCL ring, operations.cc:1221-1446), the
coordinator tick scales to world 16, stall warnings name the missing ranks
(reference CheckForStalledTensors, operations.cc:1643-1665), and the
autotuner knobs are identical on every rank after tuning rounds (reference
ParameterManager::SyncParams, parameter_manager.cc:213-233).
"""

from __future__ import annotations

import json
import os
import secrets
import subprocess
import sys
import textwrap

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np
import pytest

pytestmark = pytest.mark.engine

from launch_util import REPO, free_port, launch_world  # noqa: E402


@pytest.fixture(scope="module", autouse=True)
def build_native():
    from horovod_tpu.cc import lib_path

    lib_path()


PRELUDE = textwrap.dedent("""
    import json, os, sys
    import numpy as np
    sys.path.insert(0, os.environ["HVD_REPO"])
    from horovod_tpu.cc.native_engine import NativeEngine
    from horovod_tpu.common.config import Config
    from horovod_tpu.common.topology import Topology

    rank = int(os.environ["HOROVOD_RANK"])
    world = int(os.environ["HOROVOD_SIZE"])
    topo = Topology(rank, world, rank, world, 0, 1)
""")


def test_fusion_executes_fewer_ring_passes():
    """50 small same-dtype allreduces submitted in one cycle must fuse into
    a handful of ring passes (reference fused MPI path,
    operations.cc:798-814, 1491-1586). Round 1's plan_fusion was dead code;
    this is the proof it now drives execution."""
    script = PRELUDE + textwrap.dedent("""
        # long cycle so all 50 enqueues land in the same tick on every rank
        eng = NativeEngine(topo, Config(cycle_time_ms=300.0))
        handles = [eng.enqueue("allreduce", np.full(64, float(rank + i)), f"g{i}")
                   for i in range(50)]
        outs = [eng.synchronize(h, timeout=60) for h in handles]
        st = eng.stats()
        ok = all(np.allclose(o, np.mean([r + i for r in range(world)]))
                 for i, o in enumerate(outs))
        eng.shutdown()
        print(json.dumps({"ok": ok, "passes": st["ring_passes"]}))
    """)
    for res in launch_world(2, script):
        assert res["out"]["ok"] is True
        # unfused would be 50 passes; one bucket (50*64*8B << 64MB) is ideal,
        # a couple is acceptable if ticks split the batch
        assert res["out"]["passes"] <= 5, res["out"]


@pytest.mark.slow
def test_ring_moves_100mb_world4():
    """World-4 allreduce of ~100 MB per rank: correct results, and every
    rank's wire traffic is ~1.5x payload (ring property) — far below the
    O(N*bytes) a rank-0 star relay would show."""
    script = PRELUDE + textwrap.dedent("""
        eng = NativeEngine(topo, Config(cycle_time_ms=5.0))
        n = 1_000_000
        payload = 25 * n * 4
        handles = [eng.enqueue("allreduce",
                               np.full(n, float(rank + i), dtype=np.float32),
                               f"big{i}", average=False)
                   for i in range(25)]
        ok = True
        for i, h in enumerate(handles):
            out = eng.synchronize(h, timeout=120)
            expect = float(sum(r + i for r in range(world)))
            ok = ok and bool(np.allclose(out, expect))
        st = eng.stats()
        eng.shutdown()
        print(json.dumps({"ok": ok, "bytes": st["ring_bytes_sent"],
                          "payload": payload}))
    """)
    for res in launch_world(4, script, timeout=300):
        out = res["out"]
        assert out["ok"] is True
        # ring allreduce sends 2*(N-1)/N = 1.5x payload per rank (N=4);
        # allow slack for tick splits, require well under star-relay cost
        assert out["bytes"] >= 1.0 * out["payload"]
        assert out["bytes"] <= 3.0 * out["payload"], (
            f"per-rank traffic {out['bytes']} vs payload {out['payload']}: "
            "not a bandwidth-optimal ring")


@pytest.mark.slow
def test_world16_coordinator_tick():
    """World-16: the coordinator's gather/bcast tick and the 16-link ring
    both hold up (VERDICT: thread-per-connection untested past 8)."""
    script = PRELUDE + textwrap.dedent("""
        eng = NativeEngine(topo, Config(cycle_time_ms=2.0))
        ok = True
        for i in range(5):
            out = eng.run("allreduce", np.full(32, float(rank)), f"t{i}",
                          average=False)
            ok = ok and bool(np.allclose(out, sum(range(world))))
        bcast = eng.run("broadcast", np.full(8, float(rank)), "b", root_rank=7)
        ok = ok and bool(np.allclose(bcast, 7.0))
        eng.shutdown()
        print(json.dumps({"ok": ok}))
    """)
    for res in launch_world(16, script, timeout=300):
        assert res["out"]["ok"] is True


@pytest.mark.slow
def test_stall_warning_names_missing_ranks():
    """Rank 1 never submits tensor `lonely`; the coordinator must broadcast
    a stall warning naming rank 1 to every rank (reference prints missing
    ranks, operations.cc:1643-1665 — round 1 printed tensor names only)."""
    script = PRELUDE + textwrap.dedent("""
        import threading
        eng = NativeEngine(topo, Config(cycle_time_ms=5.0, stall_warning_s=1.0))
        h = None
        if rank == 0:
            h = eng.enqueue("allreduce", np.ones(4), "lonely")
        # both ranks keep ticking so the coordinator keeps broadcasting
        import time
        time.sleep(3.0)
        # rank 1 finally joins so the job can end cleanly
        if rank == 1:
            h = eng.enqueue("allreduce", np.ones(4), "lonely")
        out = eng.synchronize(h, timeout=30)
        eng.shutdown()
        print(json.dumps({"ok": bool(np.allclose(out, 1.0))}))
    """)
    for rank, res in enumerate(launch_world(2, script, timeout=120)):
        assert res["out"]["ok"] is True
        assert "missing ranks: 1" in res["stderr"], (
            f"rank {rank} stderr lacks missing-rank stall warning:\n"
            + res["stderr"][-2000:])
        assert "lonely" in res["stderr"]


@pytest.mark.slow
def test_autotuner_knobs_identical_across_ranks():
    """After tuning rounds, every rank holds the same (threshold, cycle)
    knobs at the same version — the coordinator tunes and the knobs ride the
    response broadcast (reference SyncParams, parameter_manager.cc:213-233).
    Round 1 tuned per-rank on local timings and could diverge."""
    script = PRELUDE + textwrap.dedent("""
        eng = NativeEngine(topo, Config(cycle_time_ms=1.0, autotune=True))
        for i in range(300):
            eng.run("allreduce", np.ones(256, dtype=np.float32), f"t{i}")
        st = eng.stats()
        eng.shutdown()
        print(json.dumps({"version": st["knob_version"],
                          "threshold": st["fusion_threshold"],
                          "cycle": st["cycle_time_ms"]}))
    """)
    outs = [r["out"] for r in launch_world(4, script, timeout=300)]
    assert outs[0]["version"] > 0, f"autotuner never moved knobs: {outs[0]}"
    for o in outs[1:]:
        assert o == outs[0], f"ranks diverged: {outs}"


def test_bf16_nan_preserved_through_reduction():
    """bf16 NaN must survive the widen/reduce/narrow path (ADVICE: round-1
    float_to_bf16 rounded NaN to -0.0)."""
    script = PRELUDE + textwrap.dedent("""
        import ml_dtypes
        eng = NativeEngine(topo, Config(cycle_time_ms=2.0))
        val = np.array([np.nan if rank == 0 else 1.0, 2.0],
                       dtype=ml_dtypes.bfloat16)
        out = eng.run("allreduce", val, "nan_t", average=False)
        eng.shutdown()
        f32 = out.astype(np.float32)
        print(json.dumps({"nan": bool(np.isnan(f32[0])),
                          "rest": float(f32[1])}))
    """)
    for res in launch_world(2, script):
        assert res["out"]["nan"] is True
        assert res["out"]["rest"] == 4.0


def test_wrong_secret_rejected():
    """A rank with the wrong HOROVOD_SECRET must fail authentication instead
    of joining the job (ADVICE: round-1 coordinator accepted any peer)."""
    script = PRELUDE + textwrap.dedent("""
        try:
            eng = NativeEngine(topo, Config(cycle_time_ms=5.0))
            if rank == 0:
                # coordinator side: rank 1 never registers; init hangs at
                # hello which is the correct behaviour — bail out via timeout
                pass
            print(json.dumps({"joined": True}))
        except Exception as e:
            print(json.dumps({"joined": False, "error": str(e)[:200]}))
    """)
    port = free_port()
    env_common = {
        "HVD_REPO": REPO,
        "HOROVOD_SIZE": "2",
        "HOROVOD_COORD_ADDR": f"127.0.0.1:{port}",
    }
    good, bad = secrets.token_hex(16), secrets.token_hex(16)
    p1_env = dict(os.environ, **env_common, HOROVOD_RANK="1", HOROVOD_SECRET=bad)
    p1 = subprocess.Popen([sys.executable, "-c", script], env=p1_env,
                          stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                          text=True)
    # rank 0 (coordinator) with the good secret; it will block in hello —
    # that's fine, we only need rank 1's rejection, then kill rank 0.
    p0_env = dict(os.environ, **env_common, HOROVOD_RANK="0", HOROVOD_SECRET=good)
    p0 = subprocess.Popen([sys.executable, "-c", script], env=p0_env,
                          stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                          text=True)
    try:
        stdout, stderr = p1.communicate(timeout=90)
        out = json.loads(stdout.strip().splitlines()[-1])
        assert out["joined"] is False, "wrong-secret rank joined the job"
        assert "authentication" in out["error"] or "auth" in out["error"].lower() \
            or "recv" in out["error"].lower(), out
    finally:
        p0.kill()
        p1.kill()
        p0.communicate(timeout=10)


@pytest.mark.slow
def test_peer_death_mid_collective_fails_cleanly():
    """Kill one rank mid-stream: the survivors' collectives must FAIL (ring
    transport error or abort) — never hang past the transfer deadline and
    never deliver silently corrupt data (the ring-error latch: a desynced
    peer stream has no resync point, so the engine fails everything and
    departs)."""
    script = PRELUDE + textwrap.dedent("""
        import os, signal, time
        eng = NativeEngine(topo, Config(cycle_time_ms=2.0))
        # one good collective so the ring is fully established
        out = eng.run("allreduce", np.full(1024, float(rank)), "warm")
        ok_warm = bool(np.allclose(out, np.mean(range(world))))

        if rank == 2:
            os.kill(os.getpid(), signal.SIGKILL)  # die without cleanup

        # Large payload: the transfer is mid-stream when rank 2 dies.
        results = []
        for i in range(3):
            try:
                eng.run("allreduce", np.full(2_000_000, float(rank)),
                        f"big{i}", average=False)
                results.append("ok")
            except Exception as e:
                results.append(type(e).__name__ + ":" + str(e)[:80])
        try:
            eng.shutdown()
        except Exception:
            pass
        print(json.dumps({"warm": ok_warm, "results": results}))
    """)
    # generous deadline: under a fully loaded suite the XLA-compiling
    # neighbours starve these small processes of CPU
    res = launch_world(3, script, timeout=300, check=False)
    assert res[2]["rc"] != 0  # the killed rank
    for r in (res[0], res[1]):
        assert r["rc"] == 0, f"survivor crashed instead of erroring:\n{r['stderr'][-2000:]}"
        out = r["out"]
        assert out is not None, f"survivor printed no result:\n{r['stderr'][-2000:]}"
        assert out["warm"] is True
        # every post-death collective errored; none "succeeded" against a
        # dead peer
        assert all(x != "ok" for x in out["results"]), out["results"]


@pytest.mark.slow
def test_bf16_native_wire_width():
    """bf16 allreduce must move ~half the wire bytes of the same-element f32
    allreduce (VERDICT r2 weak #3: round 2 widened 16-bit buffers to f32 for
    the whole ring, doubling traffic), with f32-per-add precision and NaN
    propagation intact."""
    script = PRELUDE + textwrap.dedent("""
        # Workers must NOT initialize the tunneled TPU backend: N
        # concurrent axon inits wedge/time out (environment property —
        # the same reason conftest forces CPU in-process).
        import jax
        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp
        eng = NativeEngine(topo, Config(cycle_time_ms=5.0))
        n = 2_000_000
        x32 = np.full(n, float(rank + 1), dtype=np.float32)
        out = eng.synchronize(eng.enqueue("allreduce", x32, "f32", average=False),
                              timeout=120)
        base = eng.stats()["ring_bytes_sent"]
        ok = bool(np.allclose(out, sum(r + 1 for r in range(world))))

        xbf = np.asarray(jnp.full(n, float(rank + 1), dtype=jnp.bfloat16))
        out = eng.synchronize(eng.enqueue("allreduce", xbf, "bf16", average=False),
                              timeout=120)
        bf_bytes = eng.stats()["ring_bytes_sent"] - base
        ok = ok and bool(np.allclose(np.asarray(out, np.float32),
                                     sum(r + 1 for r in range(world))))

        # NaN anywhere must survive the native-width reduction
        xn = np.asarray(jnp.full(4, 1.0, dtype=jnp.bfloat16))
        if rank == 1:
            xn = np.asarray(jnp.asarray([1.0, float("nan"), 1.0, 1.0],
                                        dtype=jnp.bfloat16))
        out = eng.synchronize(eng.enqueue("allreduce", xn, "nan", average=True),
                              timeout=120)
        ok = ok and bool(np.isnan(np.asarray(out, np.float32)[1]))
        ok = ok and bool(np.isfinite(np.asarray(out, np.float32)[0]))
        st = eng.stats()
        eng.shutdown()
        print(json.dumps({"ok": ok, "f32_bytes": base, "bf16_bytes": bf_bytes}))
    """)
    for res in launch_world(4, script, timeout=300):
        out = res["out"]
        assert out["ok"] is True
        ratio = out["bf16_bytes"] / out["f32_bytes"]
        assert 0.4 <= ratio <= 0.6, (
            f"bf16 moved {out['bf16_bytes']} vs f32 {out['f32_bytes']} "
            f"(ratio {ratio:.2f}): 16-bit payloads are not at native width")


def test_shm_plane_upgrades_same_host_links():
    """Same-host ring links ride the shared-memory plane (cc/src/shm_ring.h
    — the reference's NCCL-shm / MPI shared-window intra-host role,
    operations.cc:929-1034): world 2 on one host upgrades both links, and
    the payload is correct through the SPSC rings across sizes that
    exercise wrap-around (segment is 1 MiB here, payloads 4 B..4 MB)."""
    script = PRELUDE + textwrap.dedent("""
        eng = NativeEngine(topo, Config(cycle_time_ms=1.0))
        outs = []
        for i, n in enumerate((1, 1000, 1_000_001)):
            out = eng.run("allreduce", np.full(n, float(rank + 1), np.float32),
                          f"t{i}", average=False)
            outs.append([float(out[0]), float(out[-1]), int(out.size)])
        ag = eng.run("allgather", np.array([rank], np.int32), "ag")
        st = eng.stats()
        eng.shutdown()
        print(json.dumps({"outs": outs, "ag": ag.tolist(),
                          "shm": st["shm_links"]}))
    """)
    res = launch_world(2, script, extra_env={"HOROVOD_SHM_BYTES": str(1 << 20)})
    for r in res:
        out = r["out"]
        assert out["shm"] == 2, "same-host links did not upgrade to shm"
        assert out["outs"] == [[3.0, 3.0, 1], [3.0, 3.0, 1000],
                               [3.0, 3.0, 1_000_001]]
        assert out["ag"] == [0, 1]


def test_shm_disabled_falls_back_to_tcp():
    """HOROVOD_SHM=0 keeps every link on TCP (the knob, config.py), with
    identical results — the fallback path stays exercised."""
    script = PRELUDE + textwrap.dedent("""
        eng = NativeEngine(topo, Config(cycle_time_ms=1.0))
        out = eng.run("allreduce", np.full(5, float(rank + 1), np.float32),
                      "t0", average=False)
        st = eng.stats()
        eng.shutdown()
        print(json.dumps({"out": out.tolist(), "shm": st["shm_links"]}))
    """)
    res = launch_world(2, script, extra_env={"HOROVOD_SHM": "0"})
    for r in res:
        assert r["out"]["shm"] == 0
        assert r["out"]["out"] == [3.0] * 5
