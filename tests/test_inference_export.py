"""Inference export round-trips — reference docs/inference.md's contract
(serving must not need the distributed machinery), restated for state:
train distributed -> export_for_inference -> restore in a FRESH process
(no hvd.init) -> identical logits to the consolidated in-training forward."""

from __future__ import annotations

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from horovod_tpu import checkpoint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class _BNModel:
    """Tiny deterministic linear+BN forward shared by trainer and server
    (module-level so a fresh process can import it by path)."""

    @staticmethod
    def apply(state, x):
        h = x @ np.asarray(state["params"]["w"], np.float64)
        mean = np.asarray(state["batch_stats"]["mean"], np.float64)
        var = np.asarray(state["batch_stats"]["var"], np.float64)
        return (h - mean) / np.sqrt(var + 1e-5)


def test_export_merges_stacked_stats_and_drops_opt_state(tmp_path):
    """Single-process sharded layout: stats carry a leading device dim; the
    export averages it, drops opt_state, and load_for_inference restores
    without any init."""
    stacked = {
        "mean": jnp.asarray([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0], [7.0, 8.0]]),
        "var": jnp.ones((4, 2)),
    }
    state = {
        "params": {"w": jnp.arange(6.0).reshape(3, 2)},
        "batch_stats": stacked,
        "opt_state": {"momentum": jnp.ones(3)},
    }
    serving = checkpoint.export_for_inference(
        str(tmp_path / "serve"), state, stacked_stats_axis=0)
    assert "opt_state" not in serving
    np.testing.assert_allclose(np.asarray(serving["batch_stats"]["mean"]),
                               [4.0, 5.0])

    restored = checkpoint.load_for_inference(str(tmp_path / "serve"))
    assert set(restored) == {"params", "batch_stats"}
    np.testing.assert_allclose(np.asarray(restored["batch_stats"]["mean"]),
                               [4.0, 5.0])
    x = np.ones((2, 3))
    np.testing.assert_allclose(_BNModel.apply(restored, x),
                               _BNModel.apply(serving, x))


@pytest.mark.slow
def test_multiprocess_roundtrip_fresh_process_same_logits(tmp_path):
    """The VERDICT r3 done-criterion: train 2 ranks (divergent per-rank BN
    stats) -> export -> restore on 1 fresh process -> same logits."""
    from horovod_tpu.runner import run

    ckpt = str(tmp_path / "serve")

    def train_fn(ckpt):
        import jax

        jax.config.update("jax_platforms", "cpu")  # no tunneled-TPU init in workers
        import numpy as np

        import horovod_tpu as hvd
        from horovod_tpu import checkpoint

        hvd.init()
        r = hvd.rank()
        # "Training": params kept in sync (as DistributedOptimizer would),
        # BN stats divergent per rank (each saw its own shard).
        state = {
            "params": {"w": np.arange(6.0).reshape(3, 2)},
            "batch_stats": {"mean": np.full(2, float(r)),
                            "var": np.full(2, 1.0 + r)},
            "opt_state": {"momentum": np.ones(3)},
        }
        serving = checkpoint.export_for_inference(ckpt, state)
        # consolidated in-training logits, the oracle for the fresh process
        x = np.ones((2, 3))
        h = x @ serving["params"]["w"]
        logits = (h - serving["batch_stats"]["mean"]) / np.sqrt(
            serving["batch_stats"]["var"] + 1e-5)
        hvd.shutdown()
        return logits.tolist()

    results = run(train_fn, args=(ckpt,), num_proc=2, timeout=120)
    oracle = np.asarray(results[0])
    np.testing.assert_allclose(np.asarray(results[1]), oracle)  # ranks agree

    # Fresh process: restores and serves with NO horovod init; its stats
    # must be the cross-rank average (mean 0.5, var 1.5), not rank 0's.
    server = (
        "import sys, json; sys.path.insert(0, %r)\n"
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "import numpy as np\n"
        "from horovod_tpu.checkpoint import load_for_inference\n"
        "state = load_for_inference(%r)\n"
        "assert 'opt_state' not in state\n"
        "assert np.allclose(state['batch_stats']['mean'], 0.5)\n"
        "x = np.ones((2, 3))\n"
        "h = x @ state['params']['w']\n"
        "logits = (h - state['batch_stats']['mean']) / np.sqrt(state['batch_stats']['var'] + 1e-5)\n"
        "print(json.dumps(logits.tolist()))\n" % (REPO, ckpt)
    )
    out = subprocess.run([sys.executable, "-c", server], capture_output=True,
                         text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    served = np.asarray(json.loads(out.stdout.strip().splitlines()[-1]))
    np.testing.assert_allclose(served, oracle, rtol=1e-12)


@pytest.mark.slow
def test_flax_model_roundtrip_logits(tmp_path):
    """Full flax path: BN model trained (stats mutated) on the stacked
    layout, exported, reloaded, and served single-replica — logits equal
    the inline consolidated forward."""
    import flax.linen as nn

    class Net(nn.Module):
        @nn.compact
        def __call__(self, x, train: bool = False):
            x = nn.Dense(8)(x)
            x = nn.BatchNorm(use_running_average=not train, momentum=0.9)(x)
            return nn.Dense(4)(x)

    net = Net()
    x = jax.random.normal(jax.random.PRNGKey(0), (16, 5))
    variables = net.init(jax.random.PRNGKey(1), x)
    # stacked per-device stats, rows made divergent as if each device saw
    # different shards
    stacked = jax.tree_util.tree_map(
        lambda t: jnp.stack([t + i for i in range(4)]),
        variables["batch_stats"])
    state = {"params": variables["params"], "batch_stats": stacked,
             "opt_state": {"junk": jnp.zeros(3)}}
    checkpoint.export_for_inference(str(tmp_path / "flax"), state,
                                    stacked_stats_axis=0)
    restored = checkpoint.load_for_inference(str(tmp_path / "flax"))
    merged = jax.tree_util.tree_map(lambda t: jnp.mean(t, axis=0), stacked)
    ref = net.apply({"params": variables["params"], "batch_stats": merged}, x)
    got = net.apply({"params": restored["params"],
                     "batch_stats": restored["batch_stats"]}, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-6)


@pytest.mark.slow
def test_torch_consolidate_bn_stats(tmp_path):
    """Torch path: divergent running stats across 2 ranks are averaged in
    place; rank 0's state_dict then serves in a fresh torch-only process."""
    from horovod_tpu.runner import run

    pt = str(tmp_path / "model.pt")

    def train_fn(pt):
        import torch

        import horovod_tpu.torch as hvd

        hvd.init()
        torch.manual_seed(0)
        model = torch.nn.Sequential(torch.nn.Linear(4, 3),
                                    torch.nn.BatchNorm1d(3))
        with torch.no_grad():
            model[1].running_mean.fill_(float(hvd.rank()))
            model[1].running_var.fill_(1.0 + hvd.rank())
        hvd.broadcast_parameters(model.state_dict(), root_rank=0)
        with torch.no_grad():  # re-diverge the stats after the broadcast
            model[1].running_mean.fill_(float(hvd.rank()))
            model[1].running_var.fill_(1.0 + hvd.rank())
        hvd.consolidate_bn_stats(model)
        mean = model[1].running_mean.tolist()
        var = model[1].running_var.tolist()
        if hvd.rank() == 0:
            torch.save(model.state_dict(), pt)
        hvd.shutdown()
        return mean, var

    results = run(train_fn, args=(pt,), num_proc=2, timeout=120)
    for mean, var in results:
        np.testing.assert_allclose(mean, [0.5] * 3)
        np.testing.assert_allclose(var, [1.5] * 3)

    server = (
        "import torch\n"
        "model = torch.nn.Sequential(torch.nn.Linear(4, 3), torch.nn.BatchNorm1d(3))\n"
        "model.load_state_dict(torch.load(%r, weights_only=True))\n"
        "assert torch.allclose(model[1].running_mean, torch.full((3,), 0.5))\n"
        "print('served')\n" % pt
    )
    out = subprocess.run([sys.executable, "-c", server], capture_output=True,
                         text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    assert "served" in out.stdout
