"""DistributedOptimizer / broadcast-state tests (reference:
test/test_torch.py broadcast_state matrix 802-934, test_force_allreduce 1040;
test/test_tensorflow.py DistributedOptimizer grad paths)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from horovod_tpu.compat import shard_map
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.compression import Compression

N = 8


def make_data(seed=0):
    k = jax.random.PRNGKey(seed)
    x = jax.random.normal(k, (N * 4, 6))
    y = jax.random.normal(jax.random.fold_in(k, 1), (N * 4, 2))
    return x, y


def make_params(seed=2):
    k = jax.random.PRNGKey(seed)
    return {"w": jax.random.normal(k, (6, 2)) * 0.1, "b": jnp.zeros((2,))}


def loss_fn(params, x, y):
    pred = x @ params["w"] + params["b"]
    return jnp.mean((pred - y) ** 2)


def run_distributed(mesh, opt, params, x, y, steps=3):
    state = opt.init(params)

    def step(params, state, x, y):
        grads = jax.grad(loss_fn)(params, x, y)
        updates, state = opt.update(grads, state, params)
        return optax.apply_updates(params, updates), state

    sstep = jax.jit(shard_map(step, mesh=mesh,
                              in_specs=(P(), P(), P("hvd"), P("hvd")),
                              out_specs=(P(), P()), check_vma=False))
    for _ in range(steps):
        params, state = sstep(params, state, x, y)
    return params


def run_single(opt, params, x, y, steps=3):
    state = opt.init(params)

    @jax.jit
    def step(params, state, x, y):
        grads = jax.grad(loss_fn)(params, x, y)
        updates, state = opt.update(grads, state, params)
        return optax.apply_updates(params, updates), state

    for _ in range(steps):
        params, state = step(params, state, x, y)
    return params


@pytest.mark.parametrize("inner", ["sgd", "adam"])
def test_distributed_matches_global_batch(mesh8, inner):
    """N-way data parallel with averaged grads == single process on the full
    batch — the core Horovod correctness property."""
    x, y = make_data()
    params = make_params()
    make = {"sgd": lambda: optax.sgd(0.05), "adam": lambda: optax.adam(1e-2)}[inner]
    p_dist = run_distributed(mesh8, hvd.jax.DistributedOptimizer(make()), dict(params), x, y)
    p_single = run_single(make(), dict(params), x, y)
    for k in params:
        np.testing.assert_allclose(np.asarray(p_dist[k]), np.asarray(p_single[k]),
                                   rtol=2e-4, atol=2e-5)


def test_compression_bf16_close(mesh8):
    x, y = make_data()
    params = make_params()
    opt = hvd.jax.DistributedOptimizer(optax.sgd(0.05), compression=Compression.bf16)
    p_c = run_distributed(mesh8, opt, dict(params), x, y)
    p_ref = run_single(optax.sgd(0.05), dict(params), x, y)
    for k in params:
        np.testing.assert_allclose(np.asarray(p_c[k]), np.asarray(p_ref[k]),
                                   rtol=3e-2, atol=3e-3)


def test_compression_fp16_roundtrip():
    # reference test_compress_fp16 (test/test_tensorflow.py:766)
    t = jnp.arange(8.0, dtype=jnp.float32)
    c, ctx = Compression.fp16.compress(t)
    assert c.dtype == jnp.float16
    out = Compression.fp16.decompress(c, ctx)
    assert out.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(out), np.asarray(t))
    # non-float passes through
    i = jnp.arange(4)
    c2, ctx2 = Compression.fp16.compress(i)
    assert c2.dtype == i.dtype and ctx2 is None


def test_backward_passes_per_step(mesh8):
    """k-step accumulation applies the inner update every k-th call with the
    accumulated-mean gradient (reference torch/__init__.py:71-93)."""
    x, y = make_data()
    params = make_params()
    opt = hvd.jax.DistributedOptimizer(optax.sgd(0.1), backward_passes_per_step=2)
    state = opt.init(params)

    def step(params, state, x, y):
        grads = jax.grad(loss_fn)(params, x, y)
        updates, state = opt.update(grads, state, params)
        return optax.apply_updates(params, updates), state

    sstep = jax.jit(shard_map(step, mesh=mesh8,
                              in_specs=(P(), P(), P("hvd"), P("hvd")),
                              out_specs=(P(), P()), check_vma=False))
    p1, state = sstep(params, state, x, y)
    # first microbatch: no update yet
    for k in params:
        np.testing.assert_allclose(np.asarray(p1[k]), np.asarray(params[k]), rtol=1e-6)
    p2, state = sstep(p1, state, x, y)
    changed = any(not np.allclose(np.asarray(p2[k]), np.asarray(params[k])) for k in params)
    assert changed


def test_broadcast_parameters(mesh8):
    """Initial-state consistency (reference broadcast_parameters,
    torch/__init__.py:200-230)."""
    def body(seed):
        # each rank fabricates different params; broadcast makes them rank 0's
        s = seed[0, 0]
        k = jax.random.fold_in(jax.random.PRNGKey(0), s)
        p = {"w": jax.random.normal(k, (1, 3, 3)),
             "step": jnp.reshape(s, (1,)).astype(jnp.int32)}
        return hvd.jax.broadcast_parameters(p, root_rank=0)

    seeds = jnp.arange(N, dtype=jnp.int32).reshape(N, 1)
    f = jax.jit(shard_map(body, mesh=mesh8, in_specs=(P("hvd"),),
                          out_specs={"w": P("hvd"), "step": P("hvd")}, check_vma=False))
    out = f(seeds)
    w = np.asarray(out["w"]).reshape(N, 3, 3)
    for r in range(1, N):
        np.testing.assert_allclose(w[r], w[0], rtol=1e-6)
    assert np.all(np.asarray(out["step"]) == 0)  # root's seed


def test_broadcast_optimizer_state(mesh8):
    """reference broadcast_optimizer_state over torch.optim matrix
    (torch/__init__.py:232-348) — optax states are pytrees with int steps and
    float moments; all leaves must end up as rank 0's."""
    opt = optax.adam(1e-3)
    params = make_params()
    n_leaves = len(jax.tree_util.tree_leaves(opt.init(params)))

    def body(seed):
        p = jax.tree_util.tree_map(lambda t: t + seed[0, 0].astype(t.dtype), params)
        state = opt.init(p)
        # perturb so ranks disagree before the broadcast
        state = jax.tree_util.tree_map(lambda t: t + seed[0, 0].astype(t.dtype), state)
        state = hvd.jax.broadcast_optimizer_state(state, root_rank=0)
        # flatten to rank-1 leaves so out_specs can stack them across ranks
        return [jnp.reshape(leaf, (1, -1)).astype(jnp.float32)
                for leaf in jax.tree_util.tree_leaves(state)]

    seeds = jnp.arange(N, dtype=jnp.float32).reshape(N, 1)
    f = jax.jit(shard_map(body, mesh=mesh8, in_specs=(P("hvd"),),
                          out_specs=[P("hvd")] * n_leaves, check_vma=False))
    out = f(seeds)
    for leaf in out:
        arr = np.asarray(leaf)  # (N, k)
        for r in range(1, N):
            np.testing.assert_allclose(arr[r], arr[0], rtol=1e-6)


def test_distributed_gradients_wrapper(mesh8):
    x, y = make_data()
    params = make_params()

    def step(params, x, y):
        g = hvd.jax.grad(lambda p: loss_fn(p, x, y))(params)
        return g

    f = jax.jit(shard_map(step, mesh=mesh8, in_specs=(P(), P("hvd"), P("hvd")),
                          out_specs=P(), check_vma=False))
    g = f(params, x, y)
    g_ref = jax.grad(loss_fn)(params, x, y)
    for k in params:
        np.testing.assert_allclose(np.asarray(g[k]), np.asarray(g_ref[k]),
                                   rtol=1e-4, atol=1e-6)


def test_metric_average(mesh8):
    def body(v):
        return hvd.jax.metric_average(jnp.squeeze(v, 0))

    vals = jnp.arange(N, dtype=jnp.float32).reshape(N, 1)
    f = jax.jit(shard_map(body, mesh=mesh8, in_specs=(P("hvd"),), out_specs=P(),
                          check_vma=False))
    out = float(np.asarray(f(vals)).ravel()[0])
    assert abs(out - np.mean(np.arange(N))) < 1e-6
