"""Example scripts run end-to-end under the launcher — the reference CI runs
its MNIST examples as integration tests (.travis.yml:116-140, shrunk via sed;
here the examples take small shapes natively)."""

from __future__ import annotations

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_example(cmd, timeout=300, env_extra=None):
    env = dict(os.environ)
    # Append (never replace) PYTHONPATH: the image's sitecustomize path on it
    # registers the TPU plugin; clobbering it breaks jax in subprocesses.
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(env_extra or {})
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=timeout,
                          cwd=REPO, env=env)
    assert proc.returncode == 0, f"stderr:\n{proc.stderr[-3000:]}"
    return proc.stdout


def test_pytorch_mnist_example_2proc():
    out = run_example([
        sys.executable, "-m", "horovod_tpu.runner", "-np", "2", "--",
        sys.executable, "examples/pytorch_mnist.py",
    ])
    assert "epoch 3" in out
    assert "averaged over 2 ranks" in out


def test_jax_mnist_example_single():
    out = run_example([sys.executable, "examples/jax_mnist.py"])
    assert "epoch 2" in out
