"""Example scripts run end-to-end under the launcher — the reference CI runs
its MNIST examples as integration tests (.travis.yml:116-140, shrunk via sed;
here the examples take small shapes natively)."""

from __future__ import annotations

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_example(cmd, timeout=300, env_extra=None, with_stderr=False):
    env = dict(os.environ)
    # Append (never replace) PYTHONPATH: the image's sitecustomize path on it
    # registers the TPU plugin; clobbering it breaks jax in subprocesses.
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(env_extra or {})
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=timeout,
                          cwd=REPO, env=env)
    assert proc.returncode == 0, f"stderr:\n{proc.stderr[-3000:]}"
    return (proc.stdout, proc.stderr) if with_stderr else proc.stdout


@pytest.mark.slow
def test_pytorch_mnist_example_2proc():
    out = run_example([
        sys.executable, "-m", "horovod_tpu.runner", "-np", "2", "--",
        sys.executable, "examples/pytorch_mnist.py",
    ])
    assert "epoch 3" in out
    assert "averaged over 2 ranks" in out


@pytest.mark.slow
def test_jax_mnist_example_single():
    out = run_example([sys.executable, "examples/jax_mnist.py"],
                      env_extra={"MNIST_STEPS": "3", "HVD_FORCE_CPU": "1"})
    assert "epoch 2" in out


@pytest.mark.slow
def test_pytorch_synthetic_benchmark_2proc():
    out = run_example([
        sys.executable, "-m", "horovod_tpu.runner", "-np", "2", "--",
        sys.executable, "examples/pytorch_synthetic_benchmark.py",
        "--num-iters", "2", "--num-batches-per-iter", "2",
        "--num-warmup-batches", "1",
    ])
    assert "Img/sec per device" in out
    assert "Total img/sec on 2 device(s)" in out


@pytest.mark.slow
def test_pytorch_mnist_callbacks_2proc():
    out = run_example([
        sys.executable, "-m", "horovod_tpu.runner", "-np", "2", "--",
        sys.executable, "examples/pytorch_mnist_callbacks.py",
    ], env_extra={"MNIST_EPOCHS": "3", "MNIST_STEPS": "4"})
    assert "epoch 3" in out
    assert "averaged over 2 ranks" in out
    # warmup ramped lr toward lr*size=0.02 over 2 epochs
    assert "lr 0.0200" in out


@pytest.mark.slow
def test_jax_mnist_advanced_2proc():
    """keras_mnist_advanced twin: warmup ramps lr toward base*size and the
    epoch-end metrics are engine-averaged across ranks."""
    out = run_example([
        sys.executable, "-m", "horovod_tpu.runner", "-np", "2", "--",
        sys.executable, "examples/jax_mnist_advanced.py",
    ], env_extra={"MNIST_EPOCHS": "3", "MNIST_STEPS": "4",
                  "HVD_FORCE_CPU": "1"})
    assert "epoch 2" in out
    assert "averaged over 2 ranks" in out
    assert "lr 0.0100" in out  # base 0.005 ramped to base*size at warmup end


@pytest.mark.slow
def test_jax_mnist_eager_2proc():
    """tensorflow_mnist_eager twin: gradients allreduced per step through
    the background engine, not in-jit collectives."""
    out, err = run_example([
        sys.executable, "-m", "horovod_tpu.runner", "-np", "2", "--",
        sys.executable, "examples/jax_mnist_eager.py",
    ], env_extra={"MNIST_EPOCHS": "2", "MNIST_STEPS": "4",
                  "HVD_FORCE_CPU": "1"}, with_stderr=True)
    assert "epoch 1" in out
    assert "eager engine, averaged over 2 ranks" in out
    # Clean coordinated shutdown: a worker that learns of shutdown from the
    # response broadcast must ANNOUNCE its departure (engine.cc one-extra-
    # tick protocol) — a silent exit makes the coordinator log every normal
    # multi-process teardown as a lost rank.
    assert "lost (connection dropped without shutdown)" not in err, err[-2000:]


@pytest.mark.slow
@pytest.mark.parametrize("extra", [[], ["--remat", "--loss-chunk", "16"],
                                   ["--scan-steps", "3", "--bf16-logits"]],
                         ids=["full-logits", "remat-chunked",
                              "scan-bf16-logits"])
def test_transformer_benchmark_flash_gqa(extra):
    """The tokens/s harness runs end-to-end with flash attention + GQA on
    tiny shapes (interpret-mode kernels on CPU) — both the default
    full-logits branch and the remat + chunked-loss long-context branch."""
    out = run_example([
        sys.executable, "examples/transformer_benchmark.py",
        "--dim", "32", "--heads", "4", "--kv-heads", "2", "--layers", "2",
        "--vocab", "64", "--seq-len", "64", "--num-warmup", "1",
        "--num-iters", "2", "--attention", "flash", *extra,
    ], env_extra={"HVD_FORCE_CPU": "1"})
    assert "Tokens/sec" in out
    assert "kv 2" in out


@pytest.mark.slow
def test_jax_word2vec_sparse_path():
    out = run_example(
        [sys.executable, "examples/jax_word2vec.py"],
        env_extra={"HVD_FORCE_CPU": "1", "W2V_EPOCHS": "1", "W2V_STEPS": "3",
                   "W2V_VOCAB": "200", "W2V_DIM": "16", "W2V_BATCH": "32"})
    assert "sparse rows/step" in out


@pytest.mark.slow
def test_jax_imagenet_resume(tmp_path):
    ck = str(tmp_path / "ckjax")
    args = [sys.executable, "examples/jax_imagenet_resnet50.py",
            "--epochs", "3", "--steps-per-epoch", "2", "--batch-size", "4",
            "--image-size", "16", "--checkpoint-dir", ck]
    env = {"HVD_FORCE_CPU": "1"}
    out1 = run_example(args + ["--stop-after-epoch", "1"], env_extra=env)
    assert '"epoch": 1' in out1 and "stopped_after_epoch" in out1
    out2 = run_example(args, env_extra=env)
    assert '"resumed_from": 1' in out2
    assert '"epoch": 2' in out2 and '"epoch": 3' in out2
