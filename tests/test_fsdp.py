"""FSDP (ZeRO-3) correctness: training with 1/N-sharded params, grads, and
optimizer state must walk the identical trajectory as replicated global-batch
training — the same invariant tests/test_optimizer.py proves for plain DP."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from horovod_tpu.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.parallel.fsdp import (
    fsdp_gather_params,
    fsdp_mask_updates,
    fsdp_shard_params,
    fsdp_unshard_params,
)

N = 4
DIM_IN, DIM_H, DIM_OUT = 6, 11, 3  # 11 is deliberately not divisible by 4
BATCH = 8  # per rank


@pytest.fixture()
def fsdp_mesh():
    return Mesh(np.asarray(jax.devices()[:N]), ("fsdp",))


def make_params():
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    return {
        "w1": jax.random.normal(k1, (DIM_IN, DIM_H)) * 0.4,
        "b1": jnp.zeros((DIM_H,)),
        "w2": jax.random.normal(k2, (DIM_H, DIM_OUT)) * 0.4,
    }


def loss_fn(params, x, y):
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    pred = h @ params["w2"]
    return jnp.mean((pred - y) ** 2)


def test_roundtrip_shard_unshard():
    params = make_params()
    sharded, shapes = fsdp_shard_params(params, N)
    back = fsdp_unshard_params(sharded, shapes)
    for a, b in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_fsdp_matches_replicated_training(fsdp_mesh):
    params = make_params()
    x = jax.random.normal(jax.random.PRNGKey(1), (BATCH * N, DIM_IN))
    y = jax.random.normal(jax.random.PRNGKey(2), (BATCH * N, DIM_OUT))
    opt = optax.adam(1e-2)

    # --- replicated oracle: global-batch training on one device
    ref_params = jax.tree_util.tree_map(jnp.copy, params)
    ref_state = opt.init(ref_params)
    for _ in range(5):
        g = jax.grad(loss_fn)(ref_params, x, y)
        upd, ref_state = opt.update(g, ref_state, ref_params)
        ref_params = optax.apply_updates(ref_params, upd)

    # --- FSDP: params/grads/opt-state all sharded 1/N; data sharded too.
    # The optimizer state is built straight from the (N, chunk) sharded
    # arrays, so its moment leaves shard with the params; scalars (adam's
    # step count) stay replicated via a per-leaf spec tree.
    sharded, shapes = fsdp_shard_params(params, N)
    opt_state = opt.init(sharded)
    state_specs = jax.tree_util.tree_map(
        lambda l: P("fsdp") if getattr(l, "ndim", 0) > 0 else P(), opt_state)

    def step(shards, opt_state, x, y):
        def sharded_loss(shards):
            full = fsdp_gather_params(shards, shapes, "fsdp")
            return loss_fn(full, x, y)

        grads = jax.grad(sharded_loss)(shards)
        # all_gather transpose delivered the cross-rank SUM scattered to the
        # owning shard; average for the global-batch gradient (each rank saw
        # 1/N of the batch, and mean-of-means == global mean here).
        grads = jax.tree_util.tree_map(lambda g: g / N, grads)
        upd, opt_state = opt.update(grads, opt_state, shards)
        shards = optax.apply_updates(shards, upd)
        return shards, opt_state

    run = jax.jit(shard_map(
        step, mesh=fsdp_mesh,
        in_specs=(P("fsdp"), state_specs, P("fsdp"), P("fsdp")),
        out_specs=(P("fsdp"), state_specs),
        check_vma=False))
    with jax.default_matmul_precision("highest"):
        for _ in range(5):
            sharded, opt_state = run(sharded, opt_state, x, y)

    got = fsdp_unshard_params(sharded, shapes)
    for a, b in zip(jax.tree_util.tree_leaves(got),
                    jax.tree_util.tree_leaves(ref_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)


def test_dp_x_fsdp_matches_replicated_training():
    """Composition over a 2x2 ('dp','fsdp') training_mesh: ZeRO sharding
    within the fsdp axis, plain gradient allreduce across dp — together
    they must still walk the replicated global-batch trajectory."""
    from horovod_tpu.parallel.mesh import training_mesh

    dp, fs = 2, 2
    # the other four axes stay at size 1 — they cost nothing in the specs
    mesh = training_mesh(dp=dp, fsdp=fs, devices=jax.devices()[:dp * fs])
    params = make_params()
    x = jax.random.normal(jax.random.PRNGKey(1), (BATCH * dp * fs, DIM_IN))
    y = jax.random.normal(jax.random.PRNGKey(2), (BATCH * dp * fs, DIM_OUT))
    opt = optax.adam(1e-2)

    ref_params = jax.tree_util.tree_map(jnp.copy, params)
    ref_state = opt.init(ref_params)
    for _ in range(5):
        g = jax.grad(loss_fn)(ref_params, x, y)
        upd, ref_state = opt.update(g, ref_state, ref_params)
        ref_params = optax.apply_updates(ref_params, upd)

    sharded, shapes = fsdp_shard_params(params, fs)
    opt_state = opt.init(sharded)
    state_specs = jax.tree_util.tree_map(
        lambda l: P("fsdp") if getattr(l, "ndim", 0) > 0 else P(), opt_state)

    def step(shards, opt_state, x, y):
        def sharded_loss(shards):
            full = fsdp_gather_params(shards, shapes, "fsdp")
            return loss_fn(full, x, y)

        grads = jax.grad(sharded_loss)(shards)
        # fsdp sum arrived via the all_gather transpose; dp needs the
        # explicit allreduce; average over the total data parallelism.
        grads = jax.tree_util.tree_map(
            lambda g: jax.lax.psum(g, "dp") / (dp * fs), grads)
        upd, opt_state = opt.update(grads, opt_state, shards)
        return optax.apply_updates(shards, upd), opt_state

    run = jax.jit(shard_map(
        step, mesh=mesh,
        in_specs=(P("fsdp"), state_specs, P(("dp", "fsdp")), P(("dp", "fsdp"))),
        out_specs=(P("fsdp"), state_specs),
        check_vma=False))
    with jax.default_matmul_precision("highest"):
        for _ in range(5):
            sharded, opt_state = run(sharded, opt_state, x, y)

    got = fsdp_unshard_params(sharded, shapes)
    for a, b in zip(jax.tree_util.tree_leaves(got),
                    jax.tree_util.tree_leaves(ref_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)


def test_fsdp_pad_tail_stays_zero_with_masked_updates(fsdp_mesh):
    """The ISSUE 14 pad-leak fix: an optimizer chain that moves
    zero-gradient entries (gradient noise here) drifts the zero-pad tail,
    which is then silently carried in checkpoints; fsdp_mask_updates pins
    the tail to bitwise 0.0 without touching real elements."""
    params = make_params()
    sharded, shapes = fsdp_shard_params(params, N)
    opt = optax.chain(optax.adam(1e-2), optax.add_noise(0.01, 0.0, 0))
    x = jax.random.normal(jax.random.PRNGKey(1), (BATCH * N, DIM_IN))
    y = jax.random.normal(jax.random.PRNGKey(2), (BATCH * N, DIM_OUT))

    def make_step(mask):
        opt_state = opt.init(sharded)
        # Shard only the (N, chunk) moment leaves; scalars AND the noise
        # chain's (2,)-shaped rng key stay replicated.
        state_specs = jax.tree_util.tree_map(
            lambda l: P("fsdp") if getattr(l, "ndim", 0) > 0
            and l.shape[0] % N == 0 else P(),
            opt_state)

        def step(shards, opt_state, x, y):
            def sharded_loss(shards):
                full = fsdp_gather_params(shards, shapes, "fsdp")
                return loss_fn(full, x, y)

            grads = jax.tree_util.tree_map(
                lambda g: g / N, jax.grad(sharded_loss)(shards))
            upd, opt_state = opt.update(grads, opt_state, shards)
            if mask:
                upd = fsdp_mask_updates(upd, shapes, "fsdp")
            return optax.apply_updates(shards, upd), opt_state

        return jax.jit(shard_map(
            step, mesh=fsdp_mesh,
            in_specs=(P("fsdp"), state_specs, P("fsdp"), P("fsdp")),
            out_specs=(P("fsdp"), state_specs), check_vma=False)), opt_state

    def tails(tree):
        out = []

        def collect(s, shape):
            size = int(np.prod(shape)) if shape else 1
            out.append(np.asarray(s).reshape(-1)[size:])
            return s

        jax.tree_util.tree_map(collect, tree, shapes)
        return np.concatenate([t for t in out if t.size]) \
            if any(t.size for t in out) else np.zeros(0)

    assert tails(sharded).size > 0, "test vacuous: no leaf had padding"

    # Unmasked control: the tail provably drifts (the leak).
    step_u, st_u = make_step(mask=False)
    drifted = jax.tree_util.tree_map(jnp.copy, sharded)
    for _ in range(3):
        drifted, st_u = step_u(drifted, st_u, x, y)
    assert (tails(drifted) != 0.0).any(), \
        "control broken: unmasked noise did not move the tail"

    # Masked: tail bitwise zero, real elements identical to the unmasked
    # run (the mask only ever touches pad positions).
    step_m, st_m = make_step(mask=True)
    clean = jax.tree_util.tree_map(jnp.copy, sharded)
    for _ in range(3):
        clean, st_m = step_m(clean, st_m, x, y)
    assert (tails(clean) == 0.0).all(), "masked update leaked into the tail"
    for a, b in zip(jax.tree_util.tree_leaves(
                        fsdp_unshard_params(drifted, shapes)),
                    jax.tree_util.tree_leaves(
                        fsdp_unshard_params(clean, shapes))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_fsdp_memory_is_sharded(fsdp_mesh):
    """Each rank's shard holds 1/N of the (padded) elements — the point of
    ZeRO-3."""
    params = make_params()
    sharded, _ = fsdp_shard_params(params, N)
    total = sum(x.size for x in jax.tree_util.tree_leaves(params))
    shard_rows = sum(s.shape[1] for s in jax.tree_util.tree_leaves(sharded))
    # per-rank elements ≈ total/N (+ padding < one chunk per leaf)
    assert shard_rows < total / N + sum(N for _ in jax.tree_util.tree_leaves(params))
