"""Launcher process-tree cleanup (VERDICT #8): when a worker fails or the
job aborts, the worker's own children must not survive as orphans
(reference safe_shell_exec.py:29-52 fork-middleman + psutil tree kill)."""

from __future__ import annotations

import os
import subprocess
import sys
import textwrap
import time

import pytest

pytestmark = pytest.mark.engine

# Worker: spawn a long-lived grandchild, report its pid, then fail.
FAILING_WORKER = textwrap.dedent("""
    import os, subprocess, sys, time
    child = subprocess.Popen([sys.executable, "-c", "import time; time.sleep(300)"])
    print(f"GRANDCHILD {child.pid}", flush=True)
    time.sleep(1)
    sys.exit(3)  # worker dies; launcher must reap the grandchild
""")


def alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True


@pytest.mark.slow
def test_failed_worker_leaves_no_orphans(tmp_path):
    """run() aborts when a worker exits non-zero; the worker's grandchild
    must be gone afterwards."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = textwrap.dedent("""
        import subprocess, sys
        sys.path.insert(0, @REPO@)
        from horovod_tpu.runner import run_command
        rc = run_command([sys.executable, "-c", @WORKER@],
                         num_proc=2, timeout=60)
        print("RC", rc)
    """).replace("@REPO@", repr(repo)).replace("@WORKER@", repr(FAILING_WORKER))
    proc = subprocess.run([sys.executable, "-c", code], capture_output=True,
                          text=True, timeout=120)
    pids = [int(line.split()[1]) for line in proc.stdout.splitlines()
            if line.startswith("GRANDCHILD")]
    assert len(pids) == 2, f"workers did not report grandchildren:\n{proc.stdout}\n{proc.stderr}"
    assert "RC 3" in proc.stdout
    # launcher returned: every grandchild must be dead (allow a beat for
    # signal delivery)
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and any(alive(p) for p in pids):
        time.sleep(0.2)
    leaked = [p for p in pids if alive(p)]
    for p in leaked:  # don't actually leak them on test failure
        os.kill(p, 9)
    assert not leaked, f"grandchildren survived the abort: {leaked}"


@pytest.mark.slow
def test_programmatic_run_timeout_reaps_tree(tmp_path):
    """run(fn) that times out must also kill workers' descendants."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = textwrap.dedent("""
        import subprocess, sys
        sys.path.insert(0, @REPO@)
        from horovod_tpu.runner import run

        def fn():
            import subprocess, sys, time
            child = subprocess.Popen(
                [sys.executable, "-c", "import time; time.sleep(300)"])
            print(f"GRANDCHILD {child.pid}", flush=True)
            time.sleep(300)  # never returns a result -> launcher times out

        try:
            run(fn, num_proc=1, timeout=8)
            print("NO_TIMEOUT")
        except Exception as e:
            print("TIMED_OUT")
    """).replace("@REPO@", repr(repo))
    proc = subprocess.run([sys.executable, "-c", code], capture_output=True,
                          text=True, timeout=120)
    pids = [int(line.split()[1]) for line in proc.stdout.splitlines()
            if line.startswith("GRANDCHILD")]
    assert pids, f"worker did not report a grandchild:\n{proc.stdout}\n{proc.stderr}"
    assert "TIMED_OUT" in proc.stdout
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and any(alive(p) for p in pids):
        time.sleep(0.2)
    leaked = [p for p in pids if alive(p)]
    for p in leaked:
        os.kill(p, 9)
    assert not leaked, f"grandchildren survived the timeout: {leaked}"
