"""Multi-host launch through resident hvd-agents — the reference launches
remote workers through Spark executors / mpirun's rsh agent
(spark/__init__.py:61-77, spark/driver/mpirun_rsh.py:24-43); here two
separately-started agents with distinct host identities stand in for two
machines, and the driver must bring up the world, run a collective, and
survive an agent dying with an actionable error and zero orphans."""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from horovod_tpu.runner import run, run_command
from horovod_tpu.runner.network import make_secret
from horovod_tpu.runner.remote import HostSpec, RemoteSpawner, parse_hosts

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _start_agent(fake_host: str, secret: bytes) -> tuple:
    """Start an agent subprocess with a faked host identity; returns
    (proc, port). HOROVOD_HOSTNAME feeds service.host_hash, so two local
    agents register as two distinct 'machines'."""
    env = dict(os.environ)
    env["HOROVOD_HOSTNAME"] = fake_host
    env["HOROVOD_AGENT_SECRET"] = secret.hex()
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-m", "horovod_tpu.runner.agent", "--port", "0"],
        env=env, cwd=REPO, stdout=subprocess.PIPE, text=True)
    line = proc.stdout.readline()
    info = json.loads(line)
    assert info["agent"] == "ready"
    return proc, info["port"]


@pytest.fixture()
def two_agents():
    secret = make_secret()
    a, port_a = _start_agent("fake-host-a", secret)
    b, port_b = _start_agent("fake-host-b", secret)
    try:
        yield secret, port_a, port_b, a, b
    finally:
        for p in (a, b):
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        for p in (a, b):
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def test_parse_hosts():
    specs = parse_hosts("host1:4,host2:4")
    assert specs == [HostSpec("host1", 4), HostSpec("host2", 4)]
    specs = parse_hosts("127.0.0.1@9001:2, 127.0.0.1@9002:1")
    assert specs[0] == HostSpec("127.0.0.1", 2, 9001)
    assert specs[1] == HostSpec("127.0.0.1", 1, 9002)
    assert parse_hosts("solo") == [HostSpec("solo", 1)]
    assert parse_hosts([("h", 2), ("g", 3, 7000)]) == [
        HostSpec("h", 2), HostSpec("g", 3, 7000)]
    with pytest.raises(ValueError, match="slots"):
        parse_hosts("host:0")
    with pytest.raises(ValueError, match="host spec"):
        parse_hosts("host:abc")
    with pytest.raises(ValueError, match="no hosts"):
        parse_hosts("")


def test_parse_hosts_ipv6():
    """ADVICE r3: bare IPv6 would be mangled by the first-colon split; the
    bracketed form parses and the bare form errors with the fix."""
    assert parse_hosts("[::1]:4") == [HostSpec("::1", 4)]
    specs = parse_hosts("[fe80::1]@9009:2,[::1]")
    assert specs[0] == HostSpec("fe80::1", 2, 9009)
    assert specs[1] == HostSpec("::1", 1)
    with pytest.raises(ValueError, match="bracket IPv6"):
        parse_hosts("::1:4")
    with pytest.raises(ValueError, match="unterminated"):
        parse_hosts("[::1:4")


def test_agent_rejects_wrong_secret(two_agents):
    _, port_a, _, _, _ = two_agents
    with pytest.raises(ConnectionError, match="cannot reach hvd-agent"):
        RemoteSpawner(parse_hosts(f"127.0.0.1@{port_a}:1"), make_secret(),
                      connect_timeout=10)


def test_unreachable_agent_is_actionable():
    # Nothing listens on this port: the error must say which host:port and
    # how to start an agent there.
    with pytest.raises(ConnectionError, match="start one there"):
        RemoteSpawner(parse_hosts("127.0.0.1@1:1"), make_secret(),
                      connect_timeout=5)


@pytest.mark.slow
def test_remote_run_two_hosts_collective(two_agents):
    """4-rank world through 2 agents: rank/topology correct (2 'hosts' ×
    2 slots), collective correct, results ordered by rank — the reference's
    test_happy_run shape (test/test_spark.py:51) across fake machines."""
    secret, port_a, port_b, _, _ = two_agents

    def train_fn(scale):
        import numpy as np

        import horovod_tpu as hvd

        hvd.init()
        out = hvd.allreduce(np.full((2,), float(hvd.rank()) * scale), average=True)
        result = (hvd.rank(), hvd.size(), hvd.cross_rank(), hvd.cross_size(),
                  hvd.local_size(), out.tolist())
        hvd.shutdown()
        return result

    results = run(train_fn, args=(2.0,),
                  hosts=f"127.0.0.1@{port_a}:2,127.0.0.1@{port_b}:2",
                  agent_secret=secret, timeout=180)
    assert len(results) == 4
    mean = sum(r * 2.0 for r in range(4)) / 4
    cross_ranks = set()
    for rank, (r, size, cross_rank, cross_size, local_size, reduced) in enumerate(results):
        assert r == rank
        assert size == 4
        assert cross_size == 2
        assert local_size == 2
        cross_ranks.add(cross_rank)
        assert reduced == [mean, mean]
    assert cross_ranks == {0, 1}


@pytest.mark.slow
def test_remote_run_command(two_agents):
    """CLI path across agents: HOROVOD_* env exported, supervised workers
    propagate the exit code."""
    secret, port_a, port_b, _, _ = two_agents
    script = (
        "import os, sys; sys.path.insert(0, os.environ['HVD_REPO'])\n"
        "assert os.environ['HOROVOD_SIZE'] == '3'\n"
        "assert os.environ['HOROVOD_CROSS_SIZE'] == '2'\n"
    )
    rc = run_command([sys.executable, "-c", script],
                     hosts=f"127.0.0.1@{port_a}:2,127.0.0.1@{port_b}:1",
                     agent_secret=secret, env={"HVD_REPO": REPO}, timeout=120)
    assert rc == 0
    rc = run_command([sys.executable, "-c", "raise SystemExit(3)"],
                     hosts=f"127.0.0.1@{port_a}:1,127.0.0.1@{port_b}:1",
                     agent_secret=secret, timeout=120)
    assert rc == 3
    # A signal-killed worker must NOT read as success: SIGKILL maps to
    # 128+9 by shell convention (a raw -9 would lose to 0 in max()).
    rc = run_command(
        [sys.executable, "-c",
         "import os, signal; os.kill(os.getpid(), signal.SIGKILL)"],
        hosts=f"127.0.0.1@{port_a}:1,127.0.0.1@{port_b}:1",
        agent_secret=secret, timeout=120)
    assert rc == 137


@pytest.mark.slow
def test_remote_fn_failure_surfaces_traceback(two_agents):
    """A raising fn must surface its remote traceback, not a bare
    'exited with code 1' (the worker reports the error result before it
    exits; the driver must prefer it over the liveness poll)."""
    secret, port_a, port_b, _, _ = two_agents

    def failing_fn():
        import os

        if os.environ["HOROVOD_RANK"] == "1":
            raise ValueError("intentional remote rank-1 explosion")
        import time

        time.sleep(30)  # others busy: the failure must cut them short

    with pytest.raises(RuntimeError, match="intentional remote rank-1 explosion"):
        run(failing_fn, hosts=f"127.0.0.1@{port_a}:1,127.0.0.1@{port_b}:1",
            agent_secret=secret, timeout=120)


@pytest.mark.slow
def test_agent_death_is_actionable_and_leaves_no_orphans(two_agents, tmp_path):
    """SIGKILL one agent mid-job: the driver must fail with an error naming
    the unreachable agent, and every worker (both the dead agent's and the
    survivor's) must be gone afterwards — the zero-orphan contract the
    reference gets from Spark task teardown."""
    secret, port_a, port_b, agent_a, _ = two_agents
    piddir = str(tmp_path)

    def stall_fn(piddir):
        import os
        import time

        with open(os.path.join(piddir, f"{os.getpid()}.pid"), "w") as f:
            f.write(str(os.getpid()))
        time.sleep(120)

    box: dict = {}

    def launch():
        try:
            run(stall_fn, args=(piddir,),
                hosts=f"127.0.0.1@{port_a}:2,127.0.0.1@{port_b}:2",
                agent_secret=secret, timeout=180)
            box["error"] = None
        except BaseException as e:
            box["error"] = e

    t = threading.Thread(target=launch)
    t.start()
    # Wait until every worker has checked in, then kill agent A hard.
    deadline = time.monotonic() + 60
    while len(os.listdir(piddir)) < 4:
        assert time.monotonic() < deadline, "workers never started"
        time.sleep(0.2)
    pids = [int(name.split(".")[0]) for name in os.listdir(piddir)]
    agent_a.kill()
    t.join(timeout=90)
    assert not t.is_alive(), "driver hung after agent death"
    assert box["error"] is not None, "driver did not notice the dead agent"
    assert "unreachable" in str(box["error"])
    # Zero orphans: dead agent's workers exit via the parent-death watchdog,
    # survivor's workers are killed by the driver's cleanup.
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        alive = [p for p in pids if _pid_alive(p)]
        if not alive:
            break
        time.sleep(0.5)
    assert not alive, f"orphaned workers survived: {alive}"


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    # Zombies count as dead once reaped by their (dead) parent's reaper;
    # check process state to avoid counting zombies as alive.
    try:
        with open(f"/proc/{pid}/stat") as f:
            return f.read().split()[2] != "Z"
    except OSError:
        return False


@pytest.mark.slow
def test_two_concurrent_jobs_share_agents(two_agents):
    """Agents key jobs by job_id and by owning connection: two drivers
    running jobs through the SAME agent fleet must not cross wires (the
    resident-daemon model's whole point — one agent serves many jobs)."""
    secret, port_a, port_b, _, _ = two_agents

    def job_fn(tag):
        import horovod_tpu as hvd

        hvd.init()
        out = hvd.allreduce(__import__("numpy").ones(2) * hvd.rank(),
                            average=False)
        hvd.shutdown()
        return (tag, out.tolist())

    hosts = f"127.0.0.1@{port_a}:1,127.0.0.1@{port_b}:1"
    results: dict = {}

    def launch(tag):
        try:
            results[tag] = run(job_fn, args=(tag,), hosts=hosts,
                               agent_secret=secret, timeout=180)
        except BaseException as e:  # surface in the main thread
            results[tag] = e

    threads = [threading.Thread(target=launch, args=(t,), daemon=True)
               for t in ("j1", "j2")]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=200)
    assert not any(t.is_alive() for t in threads), \
        f"jobs still running after 200s: {sorted(set(('j1','j2')) - set(results))}"
    for tag in ("j1", "j2"):
        assert not isinstance(results[tag], BaseException), results[tag]
        assert [r[0] for r in results[tag]] == [tag, tag]
        assert results[tag][0][1] == [1.0, 1.0]  # rank0+rank1 sum
