"""Tensor parallelism on the 3-D ('batch','shard','model') mesh (ISSUE 19).

Coverage map:
- TP forward == dense single-chip oracle: BITWISE on exact-arithmetic
  (integer-valued float) payloads, pinned dtype tolerance on generic
  floats;
- TP backward (the in-body ``jax.value_and_grad`` pattern the repo trains
  with) == dense oracle's slice gradients BITWISE, replicated parameters
  receiving identical gradients on every model rank — through single
  pairs AND chained pairs (the inter-pair cotangent rides
  ``copy_to_model``'s psum transpose);
- the conjugate f/g pair is load-bearing: a control shows JAX's default
  psum-transposes-to-psum rule scales slice gradients by model_size;
- model=1 on the 3-D mesh walks the IDENTICAL bit pattern as the 2-D
  plan (full DistributedOptimizer trajectory, uint8 compare);
- composed TP x FSDP x DP training (model=2, shard=2, batch=2) tracks the
  dense DP oracle within pinned tolerance, with the model-stacked
  ``(model*shard, chunk)`` host layout and ``P(('model','shard'))``
  specs;
- trace-time gauges record the model axis;
- EP promotion: ``moe_apply`` rides the 3-D mesh's 'model' axis and still
  matches its dense per-token oracle.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu import metrics as hvd_metrics
from horovod_tpu.compat import shard_map
from horovod_tpu.parallel import sharded as sh
from horovod_tpu.parallel import tensor as tp
from horovod_tpu.parallel.mesh import sharded_mesh


# ------------------------------------------------------------ tiny helpers


def int_pair(rng, d_in, h, d_out, lo=-3, hi=4):
    """A column/row pair with integer-valued float32 weights: every
    product and sum stays exactly representable, so TP-vs-dense equality
    is bitwise and any mismatch is a routing/transpose bug, not
    rounding."""
    return {
        "w_col": jnp.asarray(rng.randint(lo, hi, (d_in, h)).astype(np.float32)),
        "b_col": jnp.asarray(rng.randint(lo, hi, (h,)).astype(np.float32)),
        "w_row": jnp.asarray(rng.randint(lo, hi, (h, d_out)).astype(np.float32)),
        "b_row": jnp.asarray(rng.randint(lo, hi, (d_out,)).astype(np.float32)),
    }


def stack_local(local_pairs):
    """[rank][...] local trees -> one tree with a leading model dim, ready
    for in_specs=P('model')."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *local_pairs)


def bitwise_equal(a, b) -> bool:
    a, b = np.asarray(a), np.asarray(b)
    return a.shape == b.shape and bool(
        np.array_equal(a.view(np.uint8), b.view(np.uint8)))


# ------------------------------------------------------------- forward


def test_tp_forward_bitwise_vs_dense(mesh8):
    """One psum per pair: the TP forward reassociates only the hidden
    contraction, so integer payloads reproduce the dense oracle
    bitwise at every model size."""
    del mesh8
    rng = np.random.RandomState(0)
    pair = int_pair(rng, 4, 8, 3)
    x = jnp.asarray(rng.randint(-2, 3, (5, 4)).astype(np.float32))
    want = tp.dense_pair_apply(pair, x, activation=None)
    for S in (2, 4, 8):
        mesh = sharded_mesh(batch=8 // S, shard=1, model=S)
        stacked = stack_local(tp.tp_pair_slices(pair, S))

        def body(sp, x):
            local = jax.tree_util.tree_map(lambda t: t[0], sp)
            return tp.tp_pair_apply(local, x, activation=None)[None]

        got = jax.jit(shard_map(
            body, mesh=mesh, in_specs=(P("model"), P()),
            out_specs=P(("batch", "shard", "model")),
            check_vma=False))(stacked, x)
        for r in range(8):
            assert bitwise_equal(got[r], want), \
                f"model={S}: device {r} diverged from the dense oracle"


def test_tp_forward_pinned_tolerance_generic_floats(mesh8):
    """Generic float payloads + tanh: the reassociated hidden sum is the
    only rounding difference, pinned at float32 dtype tolerance."""
    del mesh8
    k = jax.random.PRNGKey(3)
    k1, k2 = jax.random.split(k)
    pairs = [
        {"w_col": jax.random.normal(k1, (6, 8)) * 0.3,
         "b_col": jnp.zeros((8,)),
         "w_row": jax.random.normal(k2, (8, 6)) * 0.3,
         "b_row": jnp.full((6,), 0.1)},
    ]
    x = jax.random.normal(jax.random.PRNGKey(5), (4, 6))
    mesh = sharded_mesh(batch=4, shard=1, model=2)
    stacked = [stack_local(tp.tp_pair_slices(p, 2)) for p in pairs]

    with jax.default_matmul_precision("highest"):
        want = tp.dense_apply(pairs, x)

        def body(sp, x):
            local = jax.tree_util.tree_map(lambda t: t[0], sp)
            return tp.tp_apply(local, x)[None]

        got = jax.jit(shard_map(
            body, mesh=mesh, in_specs=(P("model"), P()),
            out_specs=P(("batch", "shard", "model")),
            check_vma=False))(stacked, x)
    np.testing.assert_allclose(np.asarray(got[0]), np.asarray(want),
                               atol=1e-6, rtol=1e-6)


# ------------------------------------------------------------- backward


def _tp_grads(mesh, S, stacked, x, activation=None):
    """In-body value_and_grad — the composition DistributedOptimizer uses:
    grads of the REPLICATED loss wrt this rank's local slices."""

    def body(sp, x):
        local = jax.tree_util.tree_map(lambda t: t[0], sp)

        def loss_fn(lp):
            return jnp.sum(tp.tp_apply(lp, x, activation=activation))

        _, g = jax.value_and_grad(loss_fn)(local)
        return jax.tree_util.tree_map(lambda t: t[None], g)

    return jax.jit(shard_map(
        body, mesh=mesh, in_specs=(P("model"), P()),
        out_specs=P("model"), check_vma=False))(stacked, x)


def _assert_grads_match_dense(g, dgrad, S):
    for i, dg in enumerate(dgrad):
        want_slices = tp.tp_pair_slices(dg, S)
        for k in ("w_col", "b_col", "w_row"):
            want = np.stack([np.asarray(w[k]) for w in want_slices])
            assert bitwise_equal(np.asarray(g[i][k]), want), \
                f"pair{i}.{k}: slice gradient diverged from dense oracle"
        for r in range(S):
            assert bitwise_equal(np.asarray(g[i]["b_row"])[r],
                                 np.asarray(dg["b_row"])), \
                f"pair{i}.b_row rank{r}: replicated gradient diverged"


def test_tp_backward_bitwise_vs_dense(mesh8):
    """The in-body gradient contract: slice params get the dense
    gradient's slices bitwise; the replicated post-psum bias gets the
    IDENTICAL dense gradient on every model rank."""
    del mesh8
    rng = np.random.RandomState(0)
    pairs = [int_pair(rng, 4, 8, 3)]
    x = jnp.asarray(rng.randint(-2, 3, (2, 4)).astype(np.float32))
    dgrad = jax.grad(
        lambda ps, x: jnp.sum(tp.dense_apply(ps, x, activation=None)))(
            pairs, x)
    for S in (2, 4):
        mesh = sharded_mesh(batch=8 // S, shard=1, model=S)
        stacked = [stack_local(tp.tp_pair_slices(p, S)) for p in pairs]
        g = _tp_grads(mesh, S, stacked, x)
        _assert_grads_match_dense(g, dgrad, S)


def test_tp_chain_backward_bitwise(mesh8):
    """Chained pairs: the cotangent leaving pair i+1 must arrive at pair i
    COMPLETED across model ranks (copy_to_model's psum transpose) — a
    partial cotangent would silently corrupt every upstream slice
    gradient."""
    del mesh8
    rng = np.random.RandomState(1)
    pairs = [int_pair(rng, 4, 6, 4, lo=-2, hi=3),
             int_pair(rng, 4, 8, 3, lo=-2, hi=3)]
    x = jnp.asarray(rng.randint(-2, 3, (3, 4)).astype(np.float32))
    dgrad = jax.grad(
        lambda ps, x: jnp.sum(tp.dense_apply(ps, x, activation=None)))(
            pairs, x)
    S = 2
    mesh = sharded_mesh(batch=4, shard=1, model=S)
    stacked = [stack_local(tp.tp_pair_slices(p, S)) for p in pairs]
    g = _tp_grads(mesh, S, stacked, x)
    _assert_grads_match_dense(g, dgrad, S)


def test_naive_psum_transpose_would_scale_grads(mesh8):
    """Control for the conjugate f/g pair: JAX transposes a plain
    ``lax.psum`` as another psum, which under the in-body pattern scales
    every slice gradient by exactly model_size. The pair is load-bearing,
    not decorative."""
    del mesh8
    rng = np.random.RandomState(0)
    pair = int_pair(rng, 4, 8, 3)
    x = jnp.asarray(rng.randint(-2, 3, (2, 4)).astype(np.float32))
    dgrad = jax.grad(
        lambda p, x: jnp.sum(tp.dense_pair_apply(p, x, activation=None)))(
            pair, x)
    S = 4
    mesh = sharded_mesh(batch=2, shard=1, model=S)
    stacked = stack_local(tp.tp_pair_slices(pair, S))

    def naive_pair(lp, x):
        h = x @ lp["w_col"] + lp["b_col"]
        return jax.lax.psum(h @ lp["w_row"], "model") + lp["b_row"]

    def body(sp, x):
        local = jax.tree_util.tree_map(lambda t: t[0], sp)
        _, g = jax.value_and_grad(
            lambda lp: jnp.sum(naive_pair(lp, x)))(local)
        return jax.tree_util.tree_map(lambda t: t[None], g)

    g = jax.jit(shard_map(
        body, mesh=mesh, in_specs=(P("model"), P()),
        out_specs=P("model"), check_vma=False))(stacked, x)
    want = np.stack([np.asarray(w["w_col"])
                     for w in tp.tp_pair_slices(dgrad, S)])
    got = np.asarray(g["w_col"])
    assert np.array_equal(got, want * S), \
        "expected the naive psum to scale slice grads by model_size"
    assert not np.array_equal(got, want)


# --------------------------------------------------- trajectory identities


def _loss_pairs(pairs, x, y, apply):
    return jnp.mean((apply(pairs, x) - y) ** 2)


def _make_pairs(seed=0):
    k1, k2, k3, k4 = jax.random.split(jax.random.PRNGKey(seed), 4)
    return [
        {"w_col": jax.random.normal(k1, (12, 16)) * 0.3,
         "b_col": jnp.zeros((16,)),
         "w_row": jax.random.normal(k2, (16, 12)) * 0.3,
         "b_row": jnp.zeros((12,))},
        {"w_col": jax.random.normal(k3, (12, 8)) * 0.3,
         "b_col": jnp.zeros((8,)),
         "w_row": jax.random.normal(k4, (8, 5)) * 0.3,
         "b_row": jnp.zeros((5,))},
    ]


def _pairs_data(n=4, seed=11):
    x = jax.random.normal(jax.random.PRNGKey(seed), (8 * n, 12))
    y = jax.random.normal(jax.random.PRNGKey(seed + 1), (8 * n, 5))
    return x, y


def _train_tp(mesh, model_size, pairs, x, y, steps=5, num_buckets=2):
    """DistributedOptimizer(sharded=True) over the 3-D mesh: TP slices in
    the model-stacked host layout, the ('batch','shard') exchange
    unchanged per model group. Returns each model rank's final local
    pairs."""
    inner = optax.adam(1e-2)
    local = tp.tp_local_pairs(pairs, model_size)
    plan = sh.build_shard_plan(local[0], mesh.shape["shard"],
                               threshold=1 << 20, num_buckets=num_buckets,
                               model_size=model_size)
    sp = sh.shard_params_model(local, plan)
    opt = hvd.jax.DistributedOptimizer(inner, sharded=True, shard_plan=plan)
    st = opt.init(sp)
    specs = sh.shard_specs(st, model_axis="model")
    sp_spec = sh.shard_specs(sp, model_axis="model")

    def step(sp, st, x, y):
        local = sh.gather_params(sp, plan)
        loss, g = jax.value_and_grad(
            lambda p: _loss_pairs(p, x, y, tp.tp_apply))(local)
        upd, st = opt.update(g, st, sp)
        return optax.apply_updates(sp, upd), st, \
            jax.lax.pmean(loss, ("batch", "shard"))

    run = jax.jit(shard_map(
        step, mesh=mesh,
        in_specs=(sp_spec, specs, P(("batch", "shard")),
                  P(("batch", "shard"))),
        out_specs=(sp_spec, specs, P()), check_vma=False))
    for _ in range(steps):
        sp, st, _ = run(sp, st, x, y)
    return sh.unshard_params_model(sp, plan), plan


def _train_dp_pairs(pairs, x, y, world=4, steps=5, num_buckets=2):
    mesh = Mesh(np.asarray(jax.devices()[:world]), ("hvd",))
    opt = hvd.jax.DistributedOptimizer(optax.adam(1e-2),
                                       fusion_threshold=1 << 20,
                                       num_buckets=num_buckets)
    st = opt.init(pairs)

    def step(p, st, x, y):
        loss, g = jax.value_and_grad(
            lambda p: _loss_pairs(p, x, y, tp.dense_apply))(p)
        upd, st = opt.update(g, st, p)
        return optax.apply_updates(p, upd), st, jax.lax.pmean(loss, "hvd")

    run = jax.jit(shard_map(step, mesh=mesh,
                            in_specs=(P(), P(), P("hvd"), P("hvd")),
                            out_specs=(P(), P(), P()), check_vma=False))
    for _ in range(steps):
        pairs, st, _ = run(pairs, st, x, y)
    return pairs


def test_model1_3d_bitwise_identical_to_2d(mesh8):
    """The ISSUE 19 headline discipline: model=1 on the 3-D mesh compiles
    to the IDENTICAL bit pattern as the 2-D plan — same plan, same
    collectives (no model-axis op is emitted), same update arithmetic —
    through a full DistributedOptimizer trajectory."""
    del mesh8
    pairs = _make_pairs()
    x, y = _pairs_data(4)
    # 2-D reference: the PR 14 path on a (4,2) mesh.
    mesh2d = Mesh(np.asarray(jax.devices()[:8]).reshape(4, 2),
                  ("batch", "shard"))
    plan2 = sh.build_shard_plan(pairs, 2, threshold=1 << 20, num_buckets=2)
    sp2 = sh.shard_params(pairs, plan2)
    opt2 = hvd.jax.DistributedOptimizer(optax.adam(1e-2), sharded=True,
                                        shard_plan=plan2)
    st2 = opt2.init(sp2)
    specs2 = sh.shard_specs(st2)

    def step2(sp, st, x, y):
        full = sh.gather_params(sp, plan2)
        _, g = jax.value_and_grad(
            lambda p: _loss_pairs(p, x, y, tp.dense_apply))(full)
        upd, st = opt2.update(g, st, sp)
        return optax.apply_updates(sp, upd), st

    run2 = jax.jit(shard_map(
        step2, mesh=mesh2d,
        in_specs=(P("shard"), specs2, P(("batch", "shard")),
                  P(("batch", "shard"))),
        out_specs=(P("shard"), specs2), check_vma=False))
    for _ in range(5):
        sp2, st2 = run2(sp2, st2, x, y)
    want = sh.unshard_params(sp2, plan2)

    # 3-D degenerate: model=1 named on the mesh, model-stacked layout.
    mesh3d = sharded_mesh(batch=4, shard=2, model=1)
    got_ranks, _ = _train_tp(mesh3d, 1, pairs, x, y, steps=5)
    assert len(got_ranks) == 1
    got = got_ranks[0]
    for i in range(len(pairs)):
        for k in pairs[i]:
            assert bitwise_equal(got[i][k], want[i][k]), \
                f"pair{i}.{k}: model=1 3-D diverged from the 2-D plan bitwise"


def test_tp_sharded_training_matches_dense_dp(mesh8):
    """Composed TP x FSDP x DP on the full (2,2,2) cube: five optimizer
    steps track the dense DP oracle within pinned float32 tolerance, and
    the replicated b_row stays bitwise-identical across model ranks (the
    per-model-group exchanges see identical operands)."""
    del mesh8
    pairs = _make_pairs()
    x, y = _pairs_data(4)
    with jax.default_matmul_precision("highest"):
        want = _train_dp_pairs(pairs, x, y, world=4, steps=5)
        got_ranks, _ = _train_tp(sharded_mesh(batch=2, shard=2, model=2),
                                 2, pairs, x, y, steps=5)
    # Model ranks agree bitwise on replicated leaves.
    for i in range(len(pairs)):
        assert bitwise_equal(got_ranks[0][i]["b_row"],
                             got_ranks[1][i]["b_row"]), \
            f"pair{i}.b_row diverged across model ranks"
    # Reassemble the full pairs from rank slices and compare to dense DP.
    for i in range(len(pairs)):
        full_w_col = np.concatenate(
            [np.asarray(r[i]["w_col"]) for r in got_ranks], axis=-1)
        full_b_col = np.concatenate(
            [np.asarray(r[i]["b_col"]) for r in got_ranks])
        full_w_row = np.concatenate(
            [np.asarray(r[i]["w_row"]) for r in got_ranks], axis=0)
        np.testing.assert_allclose(full_w_col, np.asarray(want[i]["w_col"]),
                                   atol=2e-5, rtol=2e-5)
        np.testing.assert_allclose(full_b_col, np.asarray(want[i]["b_col"]),
                                   atol=2e-5, rtol=2e-5)
        np.testing.assert_allclose(full_w_row, np.asarray(want[i]["w_row"]),
                                   atol=2e-5, rtol=2e-5)
        np.testing.assert_allclose(np.asarray(got_ranks[0][i]["b_row"]),
                                   np.asarray(want[i]["b_row"]),
                                   atol=2e-5, rtol=2e-5)


def test_tp_gauges_record_model_axis(mesh8):
    """Trace-time shard-plan gauges carry the third axis: after a TP step
    the recorded plan shows (batch, shard, model) = the compiled cube."""
    del mesh8
    pairs = _make_pairs()
    x, y = _pairs_data(4)
    _train_tp(sharded_mesh(batch=2, shard=2, model=2), 2, pairs, x, y,
              steps=1)
    plan = hvd_metrics.last_shard_plan()
    assert plan is not None
    assert plan["batch"] == 2 and plan["shard"] == 2 and plan["model"] == 2


# ------------------------------------------------------- sixth dimension


def test_autotune_sixth_dimension():
    """The 3-D mesh shape joins the joint autotune: 3-axis spec strings
    flow through tune(mesh_shapes=...) exactly like the 2-axis ones, and
    the winner's config records the full cube."""
    from horovod_tpu.jax.autotune import tune

    seen = []

    def step_factory(fusion_threshold, num_buckets, mesh_shape):
        seen.append(mesh_shape)
        import time as _t

        delay = 0.0002 if mesh_shape == "2x2x2" else 0.003

        def run():
            _t.sleep(delay)

        return run

    report = tune(step_factory, thresholds=(1 << 20,), num_buckets=(1,),
                  mesh_shapes=("8x1x1", "4x2x1", "2x2x2"),
                  warmup=0, iters=1, reps=1, gp_rounds=0)
    assert set(seen) == {"8x1x1", "4x2x1", "2x2x2"}
    assert report.best.mesh_shape == "2x2x2"
    assert report.best.config.get("mesh") == "2x2x2"


# ---------------------------------------------------------- EP promotion


def test_moe_rides_model_axis(mesh8):
    """Expert parallelism promoted onto the 3-D mesh: ``moe_apply`` with
    axis_name='model' dispatches over the mesh's third axis (experts
    sharded over 'model', tokens over ('batch','model')) and still matches
    the dense per-token oracle when capacity is generous."""
    del mesh8
    from horovod_tpu.ops.moe import MoEParams, init_moe_params, moe_apply

    DIM, HIDDEN, EXPERTS, S = 8, 16, 8, 4
    params = init_moe_params(jax.random.PRNGKey(0), DIM, HIDDEN, EXPERTS, S)
    tokens_per_rank = 8
    mesh = sharded_mesh(batch=2, shard=1, model=S)
    x = jax.random.normal(jax.random.PRNGKey(1),
                          (2 * S * tokens_per_rank, DIM))

    def dense_oracle(params, x):
        logits = x @ params.gate
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        expert = jnp.argmax(probs, axis=-1)
        prob = jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0]
        h = jax.nn.relu(jnp.einsum("td,edh->teh", x, params.w_in))
        yv = jnp.einsum("teh,ehd->ted", h, params.w_out)
        chosen = jnp.take_along_axis(
            yv, expert[:, None, None].repeat(DIM, axis=2), axis=1)[:, 0]
        return chosen * prob[:, None]

    def fn(gate, w_in, w_out, x):
        return moe_apply(MoEParams(gate, w_in, w_out), x,
                         capacity=2 * S * tokens_per_rank,
                         axis_name="model")

    got = jax.jit(shard_map(
        fn, mesh=mesh,
        in_specs=(P(), P("model"), P("model"), P(("batch", "model"))),
        out_specs=P(("batch", "model")), check_vma=False))(
            params.gate, params.w_in, params.w_out, x)
    with jax.default_matmul_precision("highest"):
        want = dense_oracle(params, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)
