"""Pipeline parallelism correctness: the scan+ppermute GPipe schedule must
match a dense sequential forward, and its gradients must match too (the
backward pipeline is the autodiff of the forward schedule)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from horovod_tpu.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.parallel.pipeline import (
    last_stage_value,
    masked_last_stage_loss,
    pipeline_apply,
    stack_stage_params,
)

DIM = 16
N_LAYERS = 8
N_STAGES = 4
N_MICRO = 4
MB = 2  # microbatch size


def layer_fn(p, x):
    """One residual MLP layer: shape-preserving, as the pipeline requires."""
    return x + jnp.tanh(x @ p["w"] + p["b"])


def make_params(seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), N_LAYERS)
    return [
        {"w": jax.random.normal(k, (DIM, DIM)) * 0.3, "b": jnp.zeros((DIM,))}
        for k in ks
    ]


def sequential(params_list, x):
    for p in params_list:
        x = layer_fn(p, x)
    return x


@pytest.fixture()
def pp_mesh():
    return Mesh(np.asarray(jax.devices()[:N_STAGES]), ("pp",))


def sharded_pipeline(pp_mesh, stacked, micro):
    def fn(stage_params, micro):
        out = pipeline_apply(layer_fn, stage_params, micro, "pp")
        return last_stage_value(out, "pp")

    return jax.jit(shard_map(
        fn, mesh=pp_mesh,
        in_specs=(P("pp"), P()),      # layers sharded into stages; data repl
        out_specs=P(),
        check_vma=False,
    ))(stacked, micro)


def test_pipeline_matches_sequential(pp_mesh):
    params = make_params()
    stacked = stack_stage_params(params)   # (N_LAYERS, ...) -> shard over pp
    micro = jax.random.normal(jax.random.PRNGKey(1), (N_MICRO, MB, DIM))

    with jax.default_matmul_precision("highest"):
        out = sharded_pipeline(pp_mesh, stacked, micro)
        ref = jnp.stack([sequential(params, m) for m in micro])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.slow
def test_pipeline_grads_match_sequential(pp_mesh):
    """jax.grad through the pipeline == grads of the dense model: the
    backward pipeline needs no hand-written schedule."""
    params = make_params(seed=2)
    stacked = stack_stage_params(params)
    micro = jax.random.normal(jax.random.PRNGKey(3), (N_MICRO, MB, DIM))
    target = jnp.ones((N_MICRO, MB, DIM)) * 0.1

    def pipe_loss(stage_params, micro):
        out = pipeline_apply(layer_fn, stage_params, micro, "pp")
        # differentiate the last-stage-masked loss, NOT the broadcast one
        # (the broadcast's transpose would scale grads by n_stages)
        return masked_last_stage_loss(jnp.mean((out - target) ** 2), "pp")

    def seq_loss(stacked_params, micro):
        def body(h, p):
            return layer_fn(p, h), None

        outs = []
        for m in micro:
            h, _ = jax.lax.scan(body, m, stacked_params)
            outs.append(h)
        return jnp.mean((jnp.stack(outs) - target) ** 2)

    with jax.default_matmul_precision("highest"):
        g_pipe = jax.jit(shard_map(
            jax.grad(pipe_loss), mesh=pp_mesh,
            in_specs=(P("pp"), P()), out_specs=P("pp"),
            check_vma=False,
        ))(stacked, micro)
        g_ref = jax.grad(seq_loss)(stacked, micro)
    for a, b in zip(jax.tree_util.tree_leaves(g_pipe),
                    jax.tree_util.tree_leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)


def test_pipeline_bubble_isolation(pp_mesh):
    """Changing one microbatch must not change any other's output (no
    cross-talk through the in-flight buffer during bubble ticks)."""
    params = make_params(seed=4)
    stacked = stack_stage_params(params)
    micro = jax.random.normal(jax.random.PRNGKey(5), (N_MICRO, MB, DIM))
    out1 = sharded_pipeline(pp_mesh, stacked, micro)
    micro2 = micro.at[1].set(0.0)
    out2 = sharded_pipeline(pp_mesh, stacked, micro2)
    np.testing.assert_allclose(np.asarray(out1[0]), np.asarray(out2[0]), atol=1e-6)
    np.testing.assert_allclose(np.asarray(out1[2:]), np.asarray(out2[2:]), atol=1e-6)
    assert not np.allclose(np.asarray(out1[1]), np.asarray(out2[1]))


@pytest.mark.slow
def test_pipeline_transformer_lm_matches_sequential(pp_mesh):
    """The REAL model family through the pipeline: a 4-layer TransformerLM
    with one block per stage must reproduce the sequential model's loss and
    gradients (blocks sharded per stage; embed/head grads psummed home)."""
    from horovod_tpu.models import TransformerLM
    from horovod_tpu.models.pipeline_lm import (
        pipeline_lm_loss_and_grads,
        split_lm_params,
    )

    layers, n_micro, mb, t = 8, 4, 2, 16  # 2 blocks PER STAGE: covers the
    # stacked-layer shard boundaries and the intra-stage scan, not just the
    # 1-block/stage degenerate case
    model = TransformerLM(vocab=64, dim=32, heads=4, layers=layers,
                          dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (n_micro, mb, t), 0, 64)
    params = model.init(jax.random.PRNGKey(0), tokens[0])["params"]
    outer, blocks = split_lm_params(params, layers)

    run = jax.jit(shard_map(
        lambda o, b, tok: pipeline_lm_loss_and_grads(model, o, b, tok, "pp"),
        mesh=pp_mesh,
        in_specs=(P(), P("pp"), P()),
        out_specs=(P(), (P(), P("pp"))),
        check_vma=False))

    with jax.default_matmul_precision("highest"):
        loss, (outer_g, block_g) = run(outer, blocks, tokens)

        import optax

        def ref_loss(params):
            logits = model.apply({"params": params},
                                 tokens.reshape(n_micro * mb, t))
            targets = jnp.roll(tokens.reshape(n_micro * mb, t), -1, axis=-1)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, targets).mean()

        ref, ref_g = jax.value_and_grad(ref_loss)(params)

    np.testing.assert_allclose(float(loss), float(ref), atol=1e-5, rtol=1e-5)
    ref_outer, ref_blocks = split_lm_params(ref_g, layers)
    for got, want, where in (
        (outer_g, ref_outer, "outer"),
        (block_g, ref_blocks, "blocks"),
    ):
        # tree_map checks structure equality, so a dropped leaf fails loudly
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-5, rtol=2e-5,
                err_msg=where),
            got, want)


@pytest.mark.slow
def test_pipeline_x_sp_transformer_matches_sequential():
    """pp x sp composition: blocks pipelined over 'pp' while each block's
    attention rings over 'sp' (sequence sharded) — loss must equal the
    sequential dense model. The cross-entropy targets roll WITHIN each
    local shard, so the oracle loss is computed with the same local-roll
    convention (shard-boundary targets differ from a global roll)."""
    from horovod_tpu.models import TransformerLM
    from horovod_tpu.models.pipeline_lm import (
        pipeline_lm_loss_and_grads,
        split_lm_params,
    )

    pp, sp = 2, 2
    mesh = Mesh(np.asarray(jax.devices()[:pp * sp]).reshape(pp, sp),
                ("pp", "sp"))
    layers, n_micro, mb, t = 2, 2, 2, 16
    model = TransformerLM(vocab=64, dim=32, heads=4, layers=layers,
                          dtype=jnp.float32, sp_axis="sp")
    seq_model = TransformerLM(vocab=64, dim=32, heads=4, layers=layers,
                              dtype=jnp.float32)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (n_micro, mb, t), 0, 64)
    params = seq_model.init(jax.random.PRNGKey(0), tokens[0])["params"]
    outer, blocks = split_lm_params(params, layers)

    run = jax.jit(shard_map(
        # each sp rank's loss is the mean over ITS shard; pmean over sp
        # gives the global mean (equal shard sizes)
        lambda o, b, tok: jax.lax.pmean(
            pipeline_lm_loss_and_grads(model, o, b, tok, "pp")[0], "sp"),
        mesh=mesh,
        in_specs=(P(), P("pp"), P(None, None, "sp")),
        out_specs=P(),
        check_vma=False))

    import optax

    with jax.default_matmul_precision("highest"):
        loss = run(outer, blocks, tokens)
        flat = tokens.reshape(n_micro * mb, t)
        logits = seq_model.apply({"params": params}, flat)
        # local-roll targets: roll each sp shard independently, like the
        # sharded loss sees them
        tl = flat.reshape(n_micro * mb, sp, t // sp)
        targets = jnp.roll(tl, -1, axis=-1).reshape(n_micro * mb, t)
        ref = optax.softmax_cross_entropy_with_integer_labels(
            logits, targets).mean()
    # loss was psummed over pp AND sp sees per-shard means averaged by pmean?
    # pipeline_lm psums over pp only; each sp rank computes the mean over its
    # shard and the shard means average to the global mean, so compare the
    # psum/pp value against the oracle directly.
    np.testing.assert_allclose(float(loss), float(ref), atol=2e-5, rtol=2e-5)


def test_split_merge_lm_params_roundtrip():
    from horovod_tpu.models import TransformerLM
    from horovod_tpu.models.pipeline_lm import merge_lm_params, split_lm_params

    layers = 3
    model = TransformerLM(vocab=32, dim=16, heads=2, layers=layers,
                          dtype=jnp.float32)
    tokens = jnp.ones((1, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    back = merge_lm_params(*split_lm_params(params, layers), layers)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        params, back)


def test_pipeline_grads_match_sequential_fast():
    """Fast-tier gradient-oracle pin (ISSUE 19 promotion satellite): the
    backward pipeline == dense grads at the smallest non-trivial scale
    (2 stages, 1 layer each) so the equivalence fails loudly outside
    -m slow too."""
    mesh = Mesh(np.asarray(jax.devices()[:2]), ("pp",))
    ks = jax.random.split(jax.random.PRNGKey(9), 2)
    params = [{"w": jax.random.normal(k, (4, 4)) * 0.3,
               "b": jnp.zeros((4,))} for k in ks]
    stacked = stack_stage_params(params)
    micro = jax.random.normal(jax.random.PRNGKey(10), (2, 1, 4))
    target = jnp.full((2, 1, 4), 0.1)

    def pipe_loss(stage_params, micro):
        out = pipeline_apply(layer_fn, stage_params, micro, "pp")
        return masked_last_stage_loss(jnp.mean((out - target) ** 2), "pp")

    def seq_loss(stacked_params, micro):
        def body(h, p):
            return layer_fn(p, h), None

        outs = [jax.lax.scan(body, m, stacked_params)[0] for m in micro]
        return jnp.mean((jnp.stack(outs) - target) ** 2)

    with jax.default_matmul_precision("highest"):
        g_pipe = jax.jit(shard_map(
            jax.grad(pipe_loss), mesh=mesh,
            in_specs=(P("pp"), P()), out_specs=P("pp"),
            check_vma=False))(stacked, micro)
        g_ref = jax.grad(seq_loss)(stacked, micro)
    for a, b in zip(jax.tree_util.tree_leaves(g_pipe),
                    jax.tree_util.tree_leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)
