"""Pipeline parallelism correctness: the scan+ppermute GPipe schedule must
match a dense sequential forward, and its gradients must match too (the
backward pipeline is the autodiff of the forward schedule)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.parallel.pipeline import (
    last_stage_value,
    masked_last_stage_loss,
    pipeline_apply,
    stack_stage_params,
)

DIM = 16
N_LAYERS = 8
N_STAGES = 4
N_MICRO = 4
MB = 2  # microbatch size


def layer_fn(p, x):
    """One residual MLP layer: shape-preserving, as the pipeline requires."""
    return x + jnp.tanh(x @ p["w"] + p["b"])


def make_params(seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), N_LAYERS)
    return [
        {"w": jax.random.normal(k, (DIM, DIM)) * 0.3, "b": jnp.zeros((DIM,))}
        for k in ks
    ]


def sequential(params_list, x):
    for p in params_list:
        x = layer_fn(p, x)
    return x


@pytest.fixture()
def pp_mesh():
    return Mesh(np.asarray(jax.devices()[:N_STAGES]), ("pp",))


def sharded_pipeline(pp_mesh, stacked, micro):
    def fn(stage_params, micro):
        out = pipeline_apply(layer_fn, stage_params, micro, "pp")
        return last_stage_value(out, "pp")

    return jax.jit(shard_map(
        fn, mesh=pp_mesh,
        in_specs=(P("pp"), P()),      # layers sharded into stages; data repl
        out_specs=P(),
        check_vma=False,
    ))(stacked, micro)


def test_pipeline_matches_sequential(pp_mesh):
    params = make_params()
    stacked = stack_stage_params(params)   # (N_LAYERS, ...) -> shard over pp
    micro = jax.random.normal(jax.random.PRNGKey(1), (N_MICRO, MB, DIM))

    with jax.default_matmul_precision("highest"):
        out = sharded_pipeline(pp_mesh, stacked, micro)
        ref = jnp.stack([sequential(params, m) for m in micro])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-5, rtol=1e-5)


@pytest.mark.slow
def test_pipeline_grads_match_sequential(pp_mesh):
    """jax.grad through the pipeline == grads of the dense model: the
    backward pipeline needs no hand-written schedule."""
    params = make_params(seed=2)
    stacked = stack_stage_params(params)
    micro = jax.random.normal(jax.random.PRNGKey(3), (N_MICRO, MB, DIM))
    target = jnp.ones((N_MICRO, MB, DIM)) * 0.1

    def pipe_loss(stage_params, micro):
        out = pipeline_apply(layer_fn, stage_params, micro, "pp")
        # differentiate the last-stage-masked loss, NOT the broadcast one
        # (the broadcast's transpose would scale grads by n_stages)
        return masked_last_stage_loss(jnp.mean((out - target) ** 2), "pp")

    def seq_loss(stacked_params, micro):
        def body(h, p):
            return layer_fn(p, h), None

        outs = []
        for m in micro:
            h, _ = jax.lax.scan(body, m, stacked_params)
            outs.append(h)
        return jnp.mean((jnp.stack(outs) - target) ** 2)

    with jax.default_matmul_precision("highest"):
        g_pipe = jax.jit(shard_map(
            jax.grad(pipe_loss), mesh=pp_mesh,
            in_specs=(P("pp"), P()), out_specs=P("pp"),
            check_vma=False,
        ))(stacked, micro)
        g_ref = jax.grad(seq_loss)(stacked, micro)
    for a, b in zip(jax.tree_util.tree_leaves(g_pipe),
                    jax.tree_util.tree_leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-5)


def test_pipeline_bubble_isolation(pp_mesh):
    """Changing one microbatch must not change any other's output (no
    cross-talk through the in-flight buffer during bubble ticks)."""
    params = make_params(seed=4)
    stacked = stack_stage_params(params)
    micro = jax.random.normal(jax.random.PRNGKey(5), (N_MICRO, MB, DIM))
    out1 = sharded_pipeline(pp_mesh, stacked, micro)
    micro2 = micro.at[1].set(0.0)
    out2 = sharded_pipeline(pp_mesh, stacked, micro2)
    np.testing.assert_allclose(np.asarray(out1[0]), np.asarray(out2[0]), atol=1e-6)
    np.testing.assert_allclose(np.asarray(out1[2:]), np.asarray(out2[2:]), atol=1e-6)
    assert not np.allclose(np.asarray(out1[1]), np.asarray(out2[1]))
