"""Control-tree tests (ISSUE 18): per-host ControlAgent aggregation on the
runner plane, CoordRelay batching/barriers on the engine plane, and the
liveness semantics (peer_lost, host-drop) the tree must preserve."""

import json
import os
import socket
import threading
import time

import pytest

from horovod_tpu.ctrl.agent import ControlAgent
from horovod_tpu.ctrl.relay import CoordRelay
from horovod_tpu.ctrl.tree import use_tree
from horovod_tpu.runner.network import BasicClient, BasicService
from horovod_tpu.runner.service import (
    DriverService,
    ElasticDriverService,
    TaskAgent,
)

KEY = b"ctrl-test-secret"


# -- tree gate ----------------------------------------------------------------


def test_use_tree_gates(monkeypatch):
    monkeypatch.delenv("HOROVOD_CTRL_TREE", raising=False)
    assert use_tree(2, 8) is True
    assert use_tree(1, 8) is False      # single host: nothing to fan through
    assert use_tree(2, 2) is False      # degenerate grouping
    monkeypatch.setenv("HOROVOD_CTRL_TREE", "0")
    assert use_tree(2, 8) is False      # knobbed off


# -- runner plane: ControlAgent ----------------------------------------------


def test_control_agent_batches_registrations():
    """A host's ranks registering through the leader get the same ranks the
    flat path assigns, with far fewer upstream requests than ranks."""
    # TaskAgent.register() exports the assignment (HOROVOD_RANK/SIZE/
    # COORD_ADDR...) into os.environ — correct in a worker process, a leak
    # when run in-process: restore the environment afterwards or later
    # tests see a phantom 4-rank world.
    env_before = dict(os.environ)
    driver = DriverService(4, KEY, fn=None)
    ca = ControlAgent(KEY, batch_s=0.05)
    ca.attach_root(driver.addresses())
    results: dict[int, dict] = {}
    errors: list = []

    def worker(index):
        try:
            agent = TaskAgent(index, [("127.0.0.1", ca.port)], KEY)
            try:
                results[index] = agent.register()
            finally:
                agent.client.close()
        except Exception as e:  # noqa: BLE001 - surfaced via assert below
            errors.append(e)

    try:
        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
        assert sorted(r["rank"] for r in results.values()) == [0, 1, 2, 3]
        for r in results.values():
            assert r["topology"]["size"] == 4
        # 4 registrations + 4 assignment waits flat = 8 root requests;
        # batched they ride ~2 (one host_register + one
        # host_wait_assignment, modulo a latecomer follow-up).
        assert ca.upstream_requests() < 8
    finally:
        ca.stop()
        driver.stop()
        os.environ.clear()
        os.environ.update(env_before)


def test_control_agent_straggler_register_not_starved():
    """Head-of-line regression: the leader's grouped assignment wait must
    not hold the one upstream connection while a straggler's register
    batch queues behind it — the driver needs that registration before
    the wait can resolve. Short upstream polls bound the stall; an
    unbounded long-poll deadlocks here until the 120 s window expires."""
    driver = ElasticDriverService(KEY, fn=None)
    driver.begin_reset({0, 1})
    ca = ControlAgent(KEY, batch_s=0.01)
    ca.attach_root(driver.addresses())
    results: dict[int, dict] = {}
    errors: list = []

    def worker(index, delay):
        try:
            time.sleep(delay)
            client = BasicClient([("127.0.0.1", ca.port)], KEY, timeout=60.0)
            try:
                client.request({
                    "kind": "register", "index": index,
                    "host_hash": "straggler-host",
                    "addresses": [("127.0.0.1", 40000 + index)],
                    "coord_port": 40000 + index,
                    "jax_coord_port": 41000 + index})
                results[index] = client.request(
                    {"kind": "wait_assignment", "index": index,
                     "min_generation": 1, "timeout": 30.0})
            finally:
                client.close()
        except Exception as e:  # noqa: BLE001 - surfaced via assert below
            errors.append(e)

    try:
        t0 = time.monotonic()
        # rank 0 registers and waits immediately; rank 1 straggles in long
        # after rank 0's batch closed and its wait poll went upstream
        threads = [threading.Thread(target=worker, args=(0, 0.0)),
                   threading.Thread(target=worker, args=(1, 0.5))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        took = time.monotonic() - t0
        assert not errors, errors
        assert sorted(results) == [0, 1]
        for r in results.values():
            assert r["ok"], r
        # bounded by one short poll round, nowhere near the 120 s window
        assert took < ca.WAIT_POLL_S + 10.0, took
    finally:
        ca.stop()
        driver.stop()


def test_control_agent_elastic_poll_cached():
    """Commit-time polls within HOROVOD_CTRL_POLL_S are answered from the
    leader's cache: many rank polls, one upstream host_elastic_poll."""
    driver = ElasticDriverService(KEY, fn=None)
    ca = ControlAgent(KEY, poll_s=5.0, batch_s=0.01)
    ca.attach_root(driver.addresses())
    client = BasicClient([("127.0.0.1", ca.port)], KEY, timeout=30.0)
    try:
        before = ca.upstream_requests()
        for index in range(6):
            resp = client.request({"kind": "elastic_poll", "index": index,
                                   "generation": 0})
            assert resp["ok"] and resp["reset_required"] is False
        assert ca.upstream_requests() == before + 1
    finally:
        client.close()
        ca.stop()
        driver.stop()


def test_control_agent_poll_reports_removed_rank():
    """The cached verdict must not blur per-rank removal: a removed index
    polls reset_required=True while its host-mates poll False."""
    driver = ElasticDriverService(KEY, fn=None)
    ca = ControlAgent(KEY, poll_s=5.0, batch_s=0.01)
    ca.attach_root(driver.addresses())
    client = BasicClient([("127.0.0.1", ca.port)], KEY, timeout=30.0)
    try:
        # Teach the leader its index set first (cache keys on it).
        for index in (0, 1):
            client.request({"kind": "ctrl_hello", "index": index})
        with driver._cv:
            driver._removed.add(1)
        assert client.request({"kind": "elastic_poll", "index": 0,
                               "generation": 0})["reset_required"] is False
        assert client.request({"kind": "elastic_poll", "index": 1,
                               "generation": 0})["reset_required"] is True
    finally:
        client.close()
        ca.stop()
        driver.stop()


def test_control_agent_passthrough_verbatim():
    """Kinds the leader does not aggregate reach the root untouched — a
    worker pointed at the tree never needs a second address."""
    class Echo(BasicService):
        def handle(self, req, client_addr):
            return {"ok": True, "echo": req}

    root = Echo(KEY)
    ca = ControlAgent(KEY)
    ca.attach_root([("127.0.0.1", root.port)])
    client = BasicClient([("127.0.0.1", ca.port)], KEY, timeout=30.0)
    try:
        resp = client.request({"kind": "result", "rank": 3, "value": 42})
        assert resp["echo"] == {"kind": "result", "rank": 3, "value": 42}
    finally:
        client.close()
        ca.stop()
        root.stop()


def test_control_agent_without_root_errors_loudly():
    ca = ControlAgent(KEY)
    client = BasicClient([("127.0.0.1", ca.port)], KEY, timeout=30.0)
    try:
        resp = client.request({"kind": "result", "rank": 0, "value": 1})
        assert resp["ok"] is False and "no root" in resp["error"]
        assert ca.has_root() is False
    finally:
        client.close()
        ca.stop()


def test_host_agent_ctrl_cmd_idempotent():
    """HostAgent `ctrl` hosting: start is idempotent (same leader/port),
    relay starts on request, and job kill stops both."""
    from horovod_tpu.runner.agent import HostAgent
    from horovod_tpu.runner.network import derive_key

    agent_secret = b"agent-secret-ctrl"
    agent = HostAgent(agent_secret, host="127.0.0.1", port=0)
    client = BasicClient([("127.0.0.1", agent.port)], agent_secret,
                         timeout=30.0)
    try:
        a = client.request({"kind": "ctrl", "cmd": "start", "job_id": "j1",
                            "relay": True})
        assert a["ok"] and a["port"] > 0 and a["relay_port"] > 0
        b = client.request({"kind": "ctrl", "cmd": "start", "job_id": "j1",
                            "relay": True})
        assert (b["port"], b["relay_port"]) == (a["port"], a["relay_port"])
        # the leader is keyed with the derived job secret
        job_secret = derive_key(agent_secret, b"hvd-job:j1")
        cc = BasicClient([("127.0.0.1", a["port"])], job_secret, timeout=30.0)
        hello = cc.request({"kind": "ctrl_hello", "index": 0})
        cc.close()
        assert hello["ok"]
        client.request({"kind": "kill", "job_id": "j1"})
        assert agent._ctrl == {}
    finally:
        client.close()
        agent.stop()


# -- engine plane: CoordRelay -------------------------------------------------


@pytest.fixture()
def engine_coord():
    from horovod_tpu.common.engine import _Coordinator

    coord = _Coordinator(4, "127.0.0.1", 0, key=KEY)
    port = coord.server.getsockname()[1]
    coord.start()
    yield coord, port
    coord.stop()


def test_relay_exchange_barrier_probe(engine_coord, monkeypatch):
    """4 ranks through one relay: coalesced exchanges produce the same
    allreduce result, ring_hello resolves the shared world verdict, and
    clock probes pass through."""
    import numpy as np

    from horovod_tpu.common.engine import _Client

    coord, port = engine_coord
    relay = CoordRelay(KEY, window_s=0.02)
    monkeypatch.setenv("HOROVOD_CTRL_RELAY", f"127.0.0.1:{relay.port}")
    results: dict = {}
    errors: list = []

    def worker(rank):
        try:
            client = _Client("127.0.0.1", port, rank, key=KEY, local=4)
            try:
                req = [{"name": "g", "op": "allreduce", "shape": (3,),
                        "dtype": "float64", "root": 0, "average": True}]
                arr = np.full((3,), float(rank))
                out: dict = {}
                for _ in range(40):
                    out.update(client.exchange(
                        req, {"g": arr} if not out else {}))
                    if "g" in out:
                        break
                hello = client.ring_hello({"enabled": False})
                probe = client.clock_probe()
                results[rank] = (out["g"], hello, probe)
            finally:
                client.close()
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    try:
        threads = [threading.Thread(target=worker, args=(r,))
                   for r in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
        expect = np.full((3,), 1.5)
        for r in range(4):
            err, val = results[r][0]
            assert err is None, err
            np.testing.assert_allclose(val, expect)
            assert results[r][1] == {"peers": None}
            assert isinstance(results[r][2], int)
    finally:
        relay.stop()


class _FakeCoord:
    """Raw engine-wire coordinator stub: records every message, answers
    {'ok': 1} — for testing what the relay SENDS upstream."""

    def __init__(self, key):
        from horovod_tpu.common.engine import _recv_msg, _send_msg

        self.key = key
        self.messages: list = []
        self._recv, self._send = _recv_msg, _send_msg
        self._conns: list = []
        self._listener = socket.create_server(("127.0.0.1", 0))
        self.port = self._listener.getsockname()[1]
        self._stop = threading.Event()
        threading.Thread(target=self._accept, daemon=True).start()

    def _accept(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            self._conns.append(conn)
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn):
        try:
            while True:
                msg = self._recv(conn, self.key)
                self.messages.append(msg)
                if msg.get("kind") == "bye":
                    return
                self._send(conn, {"ok": 1}, self.key)
        except (ConnectionError, EOFError, OSError):
            return
        finally:
            conn.close()

    def stop(self):
        self._stop.set()
        self._listener.close()
        for conn in self._conns:   # die like a killed process: conns too
            try:
                conn.close()
            except OSError:
                pass


def test_relay_reports_peer_lost_on_unclean_drop():
    """An unclean LOCAL drop becomes a targeted upstream peer_lost; a clean
    bye does not — the flat path's rung-3 liveness, one rank wide."""
    from horovod_tpu.common.engine import _recv_msg, _send_msg

    fake = _FakeCoord(KEY)
    relay = CoordRelay(KEY)
    try:
        def dial(rank):
            s = socket.create_connection(("127.0.0.1", relay.port), timeout=10)
            _send_msg(s, {"kind": "relay_hello", "rank": rank, "local": 2,
                          "coord": ["127.0.0.1", fake.port]}, KEY)
            _recv_msg(s, KEY)
            return s

        s5, s6 = dial(5), dial(6)
        s5.close()                       # unclean: no bye
        _send_msg(s6, {"kind": "bye"}, KEY)   # clean shutdown
        s6.close()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if any(m.get("kind") == "peer_lost" for m in fake.messages):
                break
            time.sleep(0.05)
        lost = [m for m in fake.messages if m.get("kind") == "peer_lost"]
        assert [m["lost"] for m in lost] == [5]
        hellos = [m for m in fake.messages if m.get("kind") == "relay_hello"]
        assert hellos and set(hellos[-1]["ranks"]) <= {5, 6}
    finally:
        relay.stop()
        fake.stop()


def test_coordinator_fails_relayed_ranks_on_relay_drop():
    """Coordinator side of the failure domain: when a connection that
    declared relay_for ranks drops uncleanly, every declared rank is
    failed — a dead host leader reads as that whole host dying."""
    from horovod_tpu.common.engine import _Coordinator, _recv_msg, _send_msg

    coord = _Coordinator(4, "127.0.0.1", 0, key=KEY)
    port = coord.server.getsockname()[1]
    coord.start()
    try:
        s = socket.create_connection(("127.0.0.1", port), timeout=10)
        _send_msg(s, {"kind": "relay_hello", "ranks": [2, 3]}, KEY)
        assert _recv_msg(s, KEY)["ok"] == 1
        s.close()                        # unclean relay death
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            with coord._cv:
                if coord._dead >= {2, 3}:
                    break
            time.sleep(0.05)
        with coord._cv:
            assert coord._dead >= {2, 3}
    finally:
        coord.stop()


def test_relay_closes_locals_when_upstream_dies():
    """Relay-side escalation: coordinator death closes every local
    connection so ranks fall into the elastic reset instead of hanging."""
    from horovod_tpu.common.engine import _recv_msg, _send_msg

    fake = _FakeCoord(KEY)
    relay = CoordRelay(KEY)
    try:
        s = socket.create_connection(("127.0.0.1", relay.port), timeout=10)
        _send_msg(s, {"kind": "relay_hello", "rank": 0, "local": 1,
                      "coord": ["127.0.0.1", fake.port]}, KEY)
        _recv_msg(s, KEY)
        fake.stop()                      # coordinator gone
        # next pass-through forces the relay to notice the dead upstream
        _send_msg(s, {"kind": "clock_probe"}, KEY)
        s.settimeout(10)
        with pytest.raises((ConnectionError, EOFError, OSError)):
            while True:
                _recv_msg(s, KEY)
    finally:
        relay.stop()


def test_worker_addresses_prefers_ctrl(monkeypatch):
    from horovod_tpu.runner.service import worker_addresses

    monkeypatch.setenv("HOROVOD_DRIVER_ADDRS",
                       json.dumps([["10.0.0.1", 9000]]))
    monkeypatch.delenv("HOROVOD_CTRL_ADDRS", raising=False)
    assert worker_addresses() == [("10.0.0.1", 9000)]
    monkeypatch.setenv("HOROVOD_CTRL_ADDRS",
                       json.dumps([["127.0.0.1", 7777]]))
    assert worker_addresses() == [("127.0.0.1", 7777)]
