"""Hierarchical (two-level) eager collectives on the native engine.

Round-4 evidence for VERDICT item 2: the reference's hierarchical allreduce
(NCCL ReduceScatter → cross-node MPI allreduce → NCCL Allgather,
reference operations.cc:1284-1446) and hierarchical allgather (shared-memory
window + cross-node Allgatherv among node roots, operations.cc:929-1034)
now exist on the EAGER path, selected by the previously-dead
HOROVOD_HIERARCHICAL_* knobs, and measurably shrink per-rank inter-host
traffic. Hosts are simulated by giving each localhost process 2-hosts-x-2-
ranks coordinates; the engine derives the intra-/cross-host rings purely
from those coordinates, so the byte accounting is identical to a real
multi-host run.
"""

from __future__ import annotations

import os
import sys
import textwrap

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import pytest

pytestmark = pytest.mark.engine

from launch_util import launch_world  # noqa: E402


@pytest.fixture(scope="module", autouse=True)
def build_native():
    from horovod_tpu.cc import lib_path

    lib_path()


# 4 localhost processes laid out as 2 hosts x 2 ranks per host (blocked:
# rank == cross_rank*local_size + local_rank, like the launcher assigns).
GRID_PRELUDE = textwrap.dedent("""
    import json, os, sys
    import numpy as np
    sys.path.insert(0, os.environ["HVD_REPO"])
    from horovod_tpu.cc.native_engine import NativeEngine
    from horovod_tpu.common.config import Config
    from horovod_tpu.common.topology import Topology

    rank = int(os.environ["HOROVOD_RANK"])
    world = int(os.environ["HOROVOD_SIZE"])
    L = int(os.environ.get("TEST_LOCAL_SIZE", "2"))
    topo = Topology(rank, world, rank % L, L, rank // L, world // L)
    hier_ar = os.environ.get("TEST_HIER_ALLREDUCE", "0") == "1"
    hier_ag = os.environ.get("TEST_HIER_ALLGATHER", "0") == "1"
    cfg = Config(cycle_time_ms=5.0, hierarchical_allreduce=hier_ar,
                 hierarchical_allgather=hier_ag,
                 pinned={"HOROVOD_HIERARCHICAL_ALLREDUCE",
                         "HOROVOD_HIERARCHICAL_ALLGATHER"})
""")


ALLREDUCE_SCRIPT = GRID_PRELUDE + textwrap.dedent("""
    eng = NativeEngine(topo, cfg)
    n = 1_000_000
    payload = n * 4
    out = eng.run("allreduce", np.full(n, float(rank + 1), dtype=np.float32),
                  "grad", average=False)
    expect = float(sum(r + 1 for r in range(world)))
    ok = bool(np.allclose(out, expect))
    st = eng.stats()
    eng.shutdown()
    print(json.dumps({"ok": ok, "payload": payload,
                      "bytes": st["ring_bytes_sent"],
                      "cross": st["ring_cross_bytes_sent"],
                      "hier_on": st["hier_allreduce"],
                      "capable": st["hier_capable"]}))
""")


def _run_allreduce():
    return [r["out"] for r in launch_world(
        4, ALLREDUCE_SCRIPT, extra_env={"TEST_HIER_ALLREDUCE": "1"})]


def test_hierarchical_allreduce_cuts_cross_host_bytes():
    """The two-level ladder must (a) reduce correctly, (b) report the knob
    as live, and (c) hit the ladder's EXACT per-rank inter-host byte
    budget, 2*(B/L)*(C-1)/C = 0.5B on a 2x2 grid. The flat comparison run
    this test used to launch is analytic instead (the flat boundary rank
    carries 2*B*(N-1)/N = 1.5B, so the exact budget IS the 1/local_size
    cut VERDICT r3 asked for — 0.5B == 1.5B / local_size / 1.5); the
    byte counters are deterministic, so asserting the budget directly
    keeps the evidence and halves the spawn cost. A measured flat-vs-hier
    comparison still lives in the scaling harness
    (examples/scaling_benchmark.py eager_hierarchical, SCALING json) and
    the knob-off engine path in test_hierarchical_falls_back_loudly /
    the autotune-broadcast test below."""
    hier = _run_allreduce()
    payload = hier[0]["payload"]

    assert all(o["ok"] for o in hier)
    assert all(o["capable"] == 1 for o in hier)
    assert all(o["hier_on"] == 1 for o in hier), (
        "HOROVOD_HIERARCHICAL_ALLREDUCE must reach the eager engine")

    # 2x2 exact ladder budget: every rank crosses 2*(B/2)*(1/2) = 0.5B
    # (small slack for fusion-plan padding).
    for o in hier:
        assert 0.40 * payload <= o["cross"] <= 0.55 * payload, hier


ALLGATHER_SCRIPT = GRID_PRELUDE + textwrap.dedent("""
    eng = NativeEngine(topo, cfg)
    rows = rank + 1           # ragged first dimension
    t = 200_000
    x = np.full((rows, t), float(rank), dtype=np.float32)
    out = eng.run("allgather", x, "gath")
    total = sum(r + 1 for r in range(world))
    ok = out.shape == (total, t)
    row = 0
    for r in range(world):
        ok = ok and bool(np.all(out[row:row + r + 1] == float(r)))
        row += r + 1
    st = eng.stats()
    eng.shutdown()
    print(json.dumps({"ok": bool(ok), "local_rank": topo.local_rank,
                      "cross": st["ring_cross_bytes_sent"],
                      "hier_on": st["hier_allgather"]}))
""")


def test_hierarchical_allgather_two_stage():
    """Two-stage allgather: ragged shapes stay correct, ONLY the host
    representatives (local_rank 0) touch the inter-host links, and each
    representative crosses at most its host block once (cross-ring
    allgather sends own-block (C-1)/C = half at C=2) — strictly below the
    flat ring's boundary traffic (every rotation crosses: ~total bytes),
    which is asserted analytically instead of via a second comparison
    launch (deterministic counters; spawn cost halved)."""
    hier = [r["out"] for r in launch_world(
        4, ALLGATHER_SCRIPT, extra_env={"TEST_HIER_ALLGATHER": "1"})]

    assert all(o["ok"] for o in hier)
    assert all(o["hier_on"] == 1 for o in hier)
    for o in hier:
        if o["local_rank"] != 0:
            assert o["cross"] == 0, (
                "non-representative ranks must not touch inter-host links "
                f"in the two-stage allgather: {o}")
    # Each representative crosses EXACTLY its own host block once (cross
    # ring C=2 sends own block (C-1)/C = 1 time). Ragged rows rank+1:
    # host0 = ranks 0+1 = 3 rows, host1 = ranks 2+3 = 7 rows.
    row_bytes = 200_000 * 4
    rep_cross = sorted(o["cross"] for o in hier if o["local_rank"] == 0)
    assert rep_cross == [3 * row_bytes, 7 * row_bytes], rep_cross


@pytest.mark.slow  # re-tiered r5: multi-process spawn cost; core coverage stays fast
def test_hierarchical_falls_back_loudly_on_flat_topology():
    """A world whose topology is NOT a multi-host grid (here: 2 ranks on one
    host) must run the flat ring, stay correct, and report the knob as
    inactive — the round-3 silent no-op, made visible."""
    script = textwrap.dedent("""
        import json, os, sys
        import numpy as np
        sys.path.insert(0, os.environ["HVD_REPO"])
        from horovod_tpu.cc.native_engine import NativeEngine
        from horovod_tpu.common.config import Config
        from horovod_tpu.common.topology import Topology

        rank = int(os.environ["HOROVOD_RANK"])
        world = int(os.environ["HOROVOD_SIZE"])
        topo = Topology(rank, world, rank, world, 0, 1)
        cfg = Config(hierarchical_allreduce=True,
                     pinned={"HOROVOD_HIERARCHICAL_ALLREDUCE"})
        eng = NativeEngine(topo, cfg)
        out = eng.run("allreduce", np.full(64, float(rank)), "g",
                      average=False)
        st = eng.stats()
        eng.shutdown()
        ok = bool(np.allclose(out, sum(range(world))))
        print(json.dumps({"ok": ok, "hier_on": st["hier_allreduce"],
                          "capable": st["hier_capable"]}))
    """)
    for res in launch_world(2, script):
        assert res["out"]["ok"] is True
        assert res["out"]["capable"] == 0
        assert res["out"]["hier_on"] == 0
        assert "using the flat ring" in res["stderr"], (
            "fallback must warn, not silently ignore the knob")


def test_autotuner_explores_hierarchy_dimension():
    """The native ParameterManager, with the categorical dimension opened
    (reference parameter_manager.h:172), must visit both branches and settle
    on the hierarchical one when the synthetic objective rewards it."""
    from horovod_tpu.autotune import ParameterManager

    pm = ParameterManager(fusion_threshold=64 << 20, cycle_time_ms=5.0,
                          threshold_pinned=True, cycle_pinned=True)
    pm.enable_hierarchy(allreduce_capable=True, allgather_capable=True)
    assert pm.active, "opening the categorical dims must activate the tuner"
    seen = set()
    for _ in range(5000):
        if not pm.active:
            break
        seen.add(pm.hier_allreduce)
        score = 3.0 if pm.hier_allreduce else 1.0
        pm.update(int(score * 1e6), 1.0)
    assert seen == {True, False}, "both branches must be explored"
    assert not pm.active
    assert pm.hier_allreduce is True, "tuner must settle on the better branch"
    pm.close()


@pytest.mark.slow  # re-tiered r5: multi-process spawn cost; core coverage stays fast
def test_hierarchical_knob_rides_autotune_broadcast():
    """With HOROVOD_AUTOTUNE=1 and the hierarchy knobs unpinned, every rank
    must hold the SAME hierarchical state after tuning ticks (the knob rides
    the coordinator's ResponseList broadcast; a mismatch would deadlock the
    data plane)."""
    script = GRID_PRELUDE + textwrap.dedent("""
        cfg = Config(cycle_time_ms=2.0, autotune=True)
        eng = NativeEngine(topo, cfg)
        ok = True
        for i in range(40):
            out = eng.run("allreduce", np.full(4096, float(rank)), f"t{i}",
                          average=False)
            ok = ok and bool(np.allclose(out, sum(range(world))))
        st = eng.stats()
        eng.shutdown()
        print(json.dumps({"ok": ok, "hier": st["hier_allreduce"],
                          "version": st["knob_version"]}))
    """)
    results = [r["out"] for r in launch_world(4, script, timeout=240)]
    assert all(o["ok"] for o in results)
    states = {o["hier"] for o in results}
    assert len(states) == 1, f"ranks disagree on the hierarchical knob: {results}"


@pytest.mark.slow
def test_hierarchical_2x4_grid_correct():
    """Bigger geometry: 8 ranks as 2 hosts x 4. The ladder must stay exact
    (sum oracle) and keep the worst-rank inter-host cut at this shape:
    flat boundary rank carries 2B(N-1)/N = 1.75B; the ladder spreads
    2(B/4)(1/2) = B/4 per rank."""
    script = GRID_PRELUDE + textwrap.dedent("""
        eng = NativeEngine(topo, cfg)
        n = 400_000
        out = eng.run("allreduce", np.full(n, float(rank + 1),
                                           dtype=np.float32),
                      "g", average=False)
        ok = bool(np.allclose(out, float(sum(r + 1 for r in range(world)))))
        st = eng.stats()
        eng.shutdown()
        print(json.dumps({"ok": ok, "cross": st["ring_cross_bytes_sent"],
                          "hier_on": st["hier_allreduce"],
                          "payload": n * 4}))
    """)
    flat = [r["out"] for r in launch_world(
        8, script, extra_env={"TEST_HIER_ALLREDUCE": "0",
                              "TEST_LOCAL_SIZE": "4"}, timeout=300)]
    hier = [r["out"] for r in launch_world(
        8, script, extra_env={"TEST_HIER_ALLREDUCE": "1",
                              "TEST_LOCAL_SIZE": "4"}, timeout=300)]
    assert all(o["ok"] for o in flat + hier)
    assert all(o["hier_on"] == 1 for o in hier)
    L = 4
    max_flat = max(o["cross"] for o in flat)
    max_hier = max(o["cross"] for o in hier)
    assert max_flat >= 1.4 * flat[0]["payload"]
    assert max_hier <= max_flat / L * 1.10, (max_hier, max_flat)


@pytest.mark.slow
def test_peer_death_mid_hierarchical_fails_cleanly():
    """Kill a rank mid-stream while the two-level ladder is active: the
    survivors must error (ring latch + dead-rank coordination), never hang
    or deliver silently corrupt sums — same contract the flat ring proves
    in test_ring_engine, now over the local/cross rings."""
    script = GRID_PRELUDE + textwrap.dedent("""
        import signal
        cfg = Config(cycle_time_ms=2.0, hierarchical_allreduce=True,
                     pinned={"HOROVOD_HIERARCHICAL_ALLREDUCE"})
        eng = NativeEngine(topo, cfg)
        out = eng.run("allreduce", np.full(1024, float(rank)), "warm")
        ok_warm = bool(np.allclose(out, np.mean(range(world))))
        if rank == 3:
            os.kill(os.getpid(), signal.SIGKILL)  # die without cleanup
        results = []
        for i in range(3):
            try:
                eng.run("allreduce", np.full(2_000_000, float(rank)),
                        f"big{i}", average=False)
                results.append("ok")
            except Exception as e:
                results.append(type(e).__name__ + ":" + str(e)[:80])
        try:
            eng.shutdown()
        except Exception:
            pass
        print(json.dumps({"warm": ok_warm, "results": results}))
    """)
    res = launch_world(4, script, timeout=300, check=False)
    assert res[3]["rc"] != 0  # the killed rank
    for r in res[:3]:
        assert r["rc"] == 0, f"survivor crashed:\n{r['stderr'][-2000:]}"
        out = r["out"]
        assert out is not None, f"survivor printed nothing:\n{r['stderr'][-2000:]}"
        assert out["warm"] is True
        assert all(x != "ok" for x in out["results"]), out["results"]


def test_compiled_ladder_across_process_boundary(tmp_path):
    """The compiled plane's ('dcn','ici') ladder with the dcn axis crossing a
    REAL process boundary: 2 processes x 4 virtual CPU devices, jitted
    hierarchical fused_allreduce == flat psum == numpy oracle (VERDICT r4
    item 8 — the ladder exercised beyond the single-process mesh)."""
    import json
    from horovod_tpu.runner import run_command

    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "mp_train_script.py")
    out = tmp_path / "hier"
    rc = run_command(
        [sys.executable, script, "hier", str(out)],
        num_proc=2,
        env={"XLA_FLAGS": "--xla_force_host_platform_device_count=4"},
        timeout=300.0, jax_distributed=True)
    assert rc == 0
    for rank in range(2):
        with open(f"{out}.{rank}") as f:
            r = json.load(f)
        assert r["nproc"] == 2 and r["ndev"] == 8
        assert r["agree"] is True, "ladder != flat psum across processes"
        assert r["correct"] is True, "ladder != numpy oracle"


def test_flat_ring_mixed_shm_tcp_links():
    """On a simulated 2x2 grid with hierarchical OFF, the FLAT ring gives
    boundary ranks one shm link (same-host neighbour) and one TCP link
    (cross-host neighbour) in the same transfer — the mixed_duplex path of
    ring.h. Correctness plus the expected per-rank link census: one flat
    same-host link plus the grid's two intra-host sub-ring links = 3
    everywhere (the sub-rings are established for the ladder even while
    the knob is off)."""
    script = GRID_PRELUDE + textwrap.dedent("""
        eng = NativeEngine(topo, cfg)
        out = eng.run("allreduce", np.full(300_000, float(rank + 1),
                      dtype=np.float32), "g", average=False)
        expect = float(sum(r + 1 for r in range(world)))
        st = eng.stats()
        eng.shutdown()
        print(json.dumps({"ok": bool(np.allclose(out, expect)),
                          "shm": st["shm_links"],
                          "cross": st["ring_cross_bytes_sent"]}))
    """)
    res = [r["out"] for r in launch_world(4, script)]
    assert all(o["ok"] for o in res)
    # ranks 0,1 share host A; 2,3 share host B. Census per rank: the flat
    # ring contributes exactly ONE shm link (one of next/prev is same-host
    # on 0->1->2->3->0) and the grid's intra-host sub-ring contributes two
    # more (established for the ladder even while the knob is off) = 3.
    assert [o["shm"] for o in res] == [3, 3, 3, 3], res
    # and the cross-host hops (1->2, 3->0) still bill inter-host bytes
    assert sum(o["cross"] > 0 for o in res) == 2, res
