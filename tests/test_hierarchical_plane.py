"""Hierarchical fabric-aware allreduce (ISSUE 7) — the eager two-level
plane, the grid canonical order, and the compiled per-tier path.

Coverage map (the ISSUE's test satellite):
- topology detection units: host grouping / leader-ring membership
  determinism (plan_grid — the Python analyze_hier);
- the extended oracle: ``_ring_order_reduce(grid=...)`` degenerates to the
  flat order bitwise at L=1 / C=1, matches the exact mean on
  exactly-summable payloads, and mirrors the per-hop compression rounding;
- 4-proc 2-host worlds: flat == hier == star bitwise (with and without
  bf16 compression + error feedback; free-form payloads additionally pin
  the hier plane to the grid oracle bit for bit);
- elastic-style reset: tear the engine down mid-job and re-rendezvous — the
  rebuilt world re-establishes the two-level plane;
- single-host degeneracy: the hier knob on a non-grid topology keeps the
  PR 4 flat ring (and says so), with zero extra listeners;
- compiled plane: per-tier bucket sizing + wire dtype recorded in
  trace-time gauges; the joint autotune's fourth dimension.
"""

from __future__ import annotations

import os
import sys
import textwrap

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np
import pytest

from horovod_tpu.common.engine import (
    _grid_order_reduce,
    _ring_order_reduce,
    plan_grid,
)
from launch_util import launch_world  # noqa: E402

pytestmark = pytest.mark.engine


# ------------------------------------------------------- topology detection

def test_plan_grid_accepts_blocked_grid():
    plan = plan_grid({r: (r % 2, 2, r // 2, 2) for r in range(4)})
    assert plan is not None and plan["L"] == 2 and plan["C"] == 2
    # Host grouping and leader-ring membership are pure functions of the
    # blocked map — every rank derives the identical rings.
    assert plan["local_group"](0) == [0, 1]
    assert plan["local_group"](3) == [2, 3]
    assert plan["cross_group"](0) == [0, 2]
    assert plan["cross_group"](3) == [1, 3]


def test_plan_grid_bigger_geometry():
    plan = plan_grid({r: (r % 4, 4, r // 4, 3) for r in range(12)})
    assert plan is not None and (plan["L"], plan["C"]) == (4, 3)
    assert plan["local_group"](6) == [4, 5, 6, 7]
    assert plan["cross_group"](6) == [2, 6, 10]


@pytest.mark.parametrize("coords", [
    {r: (r, 4, 0, 1) for r in range(4)},          # single host (C=1)
    {r: (0, 1, r, 4) for r in range(4)},          # one rank per host (L=1)
    {0: (0, 2, 0, 2), 1: (1, 2, 0, 2), 2: (0, 2, 1, 2)},   # missing cell
    # non-blocked rank map: rank != cross*L + local
    {0: (0, 2, 0, 2), 1: (0, 2, 1, 2), 2: (1, 2, 0, 2), 3: (1, 2, 1, 2)},
    # heterogeneous local_size
    {0: (0, 2, 0, 2), 1: (1, 2, 0, 2), 2: (0, 3, 1, 2), 3: (1, 2, 1, 2)},
])
def test_plan_grid_rejects_non_grids(coords):
    assert plan_grid(coords) is None


# ------------------------------------------------------------- grid oracle

def test_grid_oracle_degenerates_to_flat_bitwise():
    """grid=(1, N) and grid=(N, 1) are the flat ring order bit for bit —
    the single-host degeneracy, on free-form payloads."""
    rng = np.random.default_rng(3)
    arrs = [rng.standard_normal(997).astype(np.float32)
            * np.float32(10.0) ** rng.integers(-3, 3) for _ in range(4)]
    flat = _ring_order_reduce(arrs, True)
    np.testing.assert_array_equal(flat, _ring_order_reduce(arrs, True, grid=(1, 4)))
    np.testing.assert_array_equal(flat, _ring_order_reduce(arrs, True, grid=(4, 1)))


def test_grid_oracle_degenerates_to_flat_compressed():
    import ml_dtypes

    bf16 = np.dtype(ml_dtypes.bfloat16)
    rng = np.random.default_rng(4)
    pre = [rng.standard_normal(513).astype(bf16).astype(np.float32)
           for _ in range(4)]
    flat = _ring_order_reduce(pre, True, wire_dtype=bf16)
    np.testing.assert_array_equal(
        flat, _ring_order_reduce(pre, True, wire_dtype=bf16, grid=(1, 4)))
    np.testing.assert_array_equal(
        flat, _ring_order_reduce(pre, True, wire_dtype=bf16, grid=(4, 1)))


def test_grid_oracle_matches_exact_mean():
    """On payloads whose sums are exact in the accumulator, every fold
    order agrees with the true mean — and the 2x2 grid order is such an
    order."""
    rng = np.random.default_rng(5)
    arrs = [rng.integers(-50, 50, 1013).astype(np.float32) for _ in range(4)]
    exact = np.mean([a.astype(np.float64) for a in arrs], axis=0)
    out = _ring_order_reduce(arrs, True, grid=(2, 2))
    np.testing.assert_array_equal(out, exact.astype(np.float32))
    np.testing.assert_array_equal(
        _ring_order_reduce(arrs, False, grid=(2, 2)),
        (exact * 4).astype(np.float32))


def test_grid_oracle_is_the_nested_fold():
    """Pin the documented order on a size-1 payload: host subtotals first
    (local fold), then hosts in cross order — distinguishable from the
    flat left fold with rounding-sensitive values."""
    import ml_dtypes

    bf16 = np.dtype(ml_dtypes.bfloat16)
    vals = [1.0, 1.0 + 2 ** -8, 3.0, 5.0]        # bf16 rounding bites
    arrs = [np.array([v], dtype=np.float32) for v in vals]

    def r(x):
        return np.array([x], np.float32).astype(bf16).astype(np.float32)[0]

    # chunk l=0, subchunk k=0: local folds start at member (0+1)%2=1,
    # cross fold starts at host (0+1)%2=1.
    p_h0 = r(vals[1]) + vals[0]
    p_h1 = r(vals[3]) + vals[2]
    expect = r(r(r(p_h1) + p_h0) / 4.0)
    out = _ring_order_reduce(arrs, True, wire_dtype=bf16, grid=(2, 2))
    assert out[0] == np.float32(expect)


def test_grid_oracle_integer_exact():
    arrs = [np.full(7, r + 1, dtype=np.int64) for r in range(4)]
    np.testing.assert_array_equal(
        _ring_order_reduce(arrs, False, grid=(2, 2)),
        np.full(7, 10, np.int64))


# ------------------------------------------------- 4-proc two-host worlds

GRID_WORKER = textwrap.dedent("""
    import hashlib, json, os, sys
    sys.path.insert(0, os.environ["HVD_REPO"])
    import numpy as np
    from horovod_tpu.common.config import Config
    from horovod_tpu.common.engine import PyEngine, _ring_order_reduce
    from horovod_tpu.common.topology import Topology
    from horovod_tpu import metrics as hvd_metrics

    rank = int(os.environ["HOROVOD_RANK"]); world = int(os.environ["HOROVOD_SIZE"])
    L = int(os.environ.get("T_LOCAL", "2"))
    hier = os.environ.get("T_HIER", "0") == "1"
    ring = os.environ.get("T_RING", "1") == "1"
    comp = os.environ.get("HOROVOD_COMPRESSION", "none")
    ef = os.environ.get("HOROVOD_COMPRESSION_ERROR_FEEDBACK", "0") == "1"
    topo = Topology(rank, world, rank % L, L, rank // L, world // L)
    eng = PyEngine(topo, Config(cycle_time_ms=1.0, stall_check_disable=True,
                                ring_data_plane=ring,
                                hierarchical_allreduce=hier))
    try:
        rng = np.random.default_rng(11)
        # Every rank derives ALL payloads from the shared seed, so it can
        # run the canonical grid oracle locally for the bitwise pin.
        payloads = [[(rng.standard_normal(611) * (r + 1)).astype(np.float32)
                     for r in range(world)] for _ in range(3)]
        digest = hashlib.sha256()
        oracle_ok = True
        for i, tick in enumerate(payloads):
            out = eng.run("allreduce", tick[rank], f"g.{i % 2}")
            digest.update(out.tobytes())
            if hier and comp == "none" and not ef:
                oracle = _ring_order_reduce(tick, True, grid=(L, world // L))
                oracle_ok = oracle_ok and bool(np.array_equal(out, oracle))
        snap = hvd_metrics.registry().snapshot()["counters"]
        stats = eng.cache_stats()
        print(json.dumps({
            "rank": rank, "plane": stats["plane"],
            "hash": digest.hexdigest(), "oracle_ok": oracle_ok,
            "tier_local": snap.get('horovod_wire_bytes_total{tier="local"}', 0),
            "tier_cross": snap.get('horovod_wire_bytes_total{tier="cross"}', 0),
            "star_bytes": snap.get(
                'horovod_engine_data_bytes_total{plane="star"}', 0),
        }))
    finally:
        eng.shutdown()
""")


def _grid_world(hier: bool, ring: bool = True, extra=None, world: int = 4):
    env = {"HOROVOD_ENGINE": "python", "T_HIER": "1" if hier else "0",
           "T_RING": "1" if ring else "0"}
    env.update(extra or {})
    return [r["out"] for r in launch_world(world, GRID_WORKER,
                                           extra_env=env)]


def test_hier_plane_matches_grid_oracle_and_cuts_cross_bytes():
    """Free-form payloads: the two-level plane must reproduce the grid
    oracle bit for bit on every rank, agree across ranks, keep the
    coordinator at zero tensor bytes, and spend <= 0.35x the flat ring's
    worst-rank cross-host bytes."""
    hier = _grid_world(hier=True)
    flat = _grid_world(hier=False)
    assert all(o["plane"] == "hier" for o in hier), hier
    assert all(o["plane"] == "ring" for o in flat), flat
    assert all(o["oracle_ok"] for o in hier), "hier plane != grid oracle"
    assert len({o["hash"] for o in hier}) == 1
    assert all(o["star_bytes"] == 0 for o in hier + flat)
    flat_cross = max(o["tier_cross"] for o in flat)
    hier_cross = max(o["tier_cross"] for o in hier)
    assert flat_cross > 0
    assert hier_cross <= 0.35 * flat_cross, (hier_cross, flat_cross)
    # Free-form f32 payloads: every plane is pinned to ITS canonical
    # oracle (flat fold vs grid fold — native-width f32 accumulation,
    # ISSUE 13), so the flat ranks must agree among themselves; cross-
    # plane identity is the exact-arithmetic test below.
    assert len({o["hash"] for o in flat}) == 1


def test_flat_hier_star_bitwise_with_bf16_and_error_feedback():
    """Exactly-summable payloads (integer-valued, partial sums < 256 =
    bf16's exact range): flat == hier == star bitwise, uncompressed AND
    compressed, with error feedback enabled on the compressed worlds
    (exact quantization leaves zero residuals — the wiring must not
    disturb the stream)."""
    script = GRID_WORKER.replace(
        "(rng.standard_normal(611) * (r + 1)).astype(np.float32)",
        "((rng.integers(0, 16, 611) + r).astype(np.float32))")
    def worlds(extra):
        outs = {}
        for name, env in {
            "flat": {"T_HIER": "0"}, "hier": {"T_HIER": "1"},
            "star": {"T_HIER": "0", "T_RING": "0"},
        }.items():
            e = {"HOROVOD_ENGINE": "python", "T_RING": "1"}
            e.update(env)
            e.update(extra)
            outs[name] = [r["out"] for r in launch_world(4, script,
                                                         extra_env=e)]
        return outs

    plain = worlds({})
    assert all(o["plane"] == "hier" for o in plain["hier"])
    hashes = {name: {o["hash"] for o in outs}
              for name, outs in plain.items()}
    assert all(len(h) == 1 for h in hashes.values()), hashes
    assert hashes["flat"] == hashes["hier"] == hashes["star"], hashes

    comp = worlds({"HOROVOD_COMPRESSION": "bf16",
                   "HOROVOD_COMPRESSION_ERROR_FEEDBACK": "1"})
    chashes = {name: {o["hash"] for o in outs}
               for name, outs in comp.items()}
    assert all(len(h) == 1 for h in chashes.values()), chashes
    assert chashes["flat"] == chashes["hier"] == chashes["star"], chashes


def test_elastic_reset_re_rendezvous():
    """The hvd.elastic reset path tears the engine down and rebuilds it
    against a fresh coordinator: the rebuilt world must re-establish the
    two-level plane and stay correct — generation 2 is not a degraded
    flat world. (Production resets are fenced by the elastic driver's
    rendezvous barrier before any engine rebuild; this in-process rebuild
    has no driver, so each generation gets its own pre-agreed coordinator
    port — a fast rank must not connect into the dying generation's
    listener.)"""
    from launch_util import free_port

    script = textwrap.dedent("""
        import json, os, sys
        sys.path.insert(0, os.environ["HVD_REPO"])
        import numpy as np
        from horovod_tpu.common.config import Config
        from horovod_tpu.common.engine import PyEngine
        from horovod_tpu.common.topology import Topology

        rank = int(os.environ["HOROVOD_RANK"]); world = int(os.environ["HOROVOD_SIZE"])
        topo = Topology(rank, world, rank % 2, 2, rank // 2, world // 2)
        ports = os.environ["T_GEN_PORTS"].split(",")
        planes, oks = [], []
        for gen in range(2):
            os.environ["HOROVOD_COORD_ADDR"] = f"127.0.0.1:{ports[gen]}"
            eng = PyEngine(topo, Config(cycle_time_ms=1.0,
                                        stall_check_disable=True,
                                        hierarchical_allreduce=True))
            try:
                out = eng.run("allreduce", np.full(257, float(rank + 1),
                                                   np.float32), f"gen{gen}",
                              average=False)
                oks.append(bool(np.allclose(out, 10.0)))
                planes.append(eng.cache_stats()["plane"])
            finally:
                eng.shutdown()
        print(json.dumps({"planes": planes, "oks": oks}))
    """)
    ports = f"{free_port()},{free_port()}"
    for r in launch_world(4, script,
                          extra_env={"HOROVOD_ENGINE": "python",
                                     "T_GEN_PORTS": ports},
                          timeout=240):
        assert r["out"]["planes"] == ["hier", "hier"], r["out"]
        assert all(r["out"]["oks"]), r["out"]


def test_single_host_degeneracy_keeps_flat_ring():
    """The knob on a non-grid topology (4 ranks, one host) must keep the
    PR 4 flat ring — same plane, loud warning, no hier listeners."""
    script = textwrap.dedent("""
        import json, os, sys
        sys.path.insert(0, os.environ["HVD_REPO"])
        import numpy as np
        from horovod_tpu.common.config import Config
        from horovod_tpu.common.engine import PyEngine, _HierPlane
        from horovod_tpu.common.topology import Topology

        rank = int(os.environ["HOROVOD_RANK"]); world = int(os.environ["HOROVOD_SIZE"])
        topo = Topology(rank, world, rank, world, 0, 1)   # one host
        eng = PyEngine(topo, Config(cycle_time_ms=1.0,
                                    stall_check_disable=True,
                                    hierarchical_allreduce=True))
        try:
            out = eng.run("allreduce", np.full(64, float(rank)), "g",
                          average=False)
            print(json.dumps({
                "ok": bool(np.allclose(out, sum(range(world)))),
                "plane": eng.cache_stats()["plane"],
                "is_hier": isinstance(eng._ring, _HierPlane),
            }))
        finally:
            eng.shutdown()
    """)
    for r in launch_world(4, script, extra_env={"HOROVOD_ENGINE": "python"},
                          check=False):
        assert r["rc"] == 0, r["stderr"][-2000:]
        assert r["out"]["ok"] is True
        assert r["out"]["plane"] == "ring"
        assert r["out"]["is_hier"] is False
        assert "using the flat eager plane" in r["stderr"], (
            "non-grid fallback must warn, not silently ignore the knob")


def test_hier_wire_spans_carry_tier(tmp_path):
    """Tracing satellite: the hier plane's wire_send/wire_recv spans are
    tier-tagged, and the critical-path analyzer splits wire time by fabric."""
    script = GRID_WORKER.replace("stall_check_disable=True,",
                                 "stall_check_disable=True, "
                                 "trace_dir=os.environ['T_TRACE'],")
    out_dir = tmp_path / "trace"
    outs = [r["out"] for r in launch_world(
        4, script, extra_env={"HOROVOD_ENGINE": "python", "T_HIER": "1",
                              "T_TRACE": str(out_dir)})]
    assert all(o["plane"] == "hier" for o in outs)
    from horovod_tpu.tracing.collector import load_spans
    from horovod_tpu.tracing.critical_path import analyze

    spans, _ = load_spans(str(out_dir))
    tiers = {s.get("tier") for s in spans
             if s.get("phase") in ("wire_send", "wire_recv")}
    assert tiers == {"local", "cross"}, tiers
    report = analyze(spans)
    by_tier = report["wire_seconds_by_tier"]
    assert set(by_tier) == {"local", "cross"}
    assert all(v >= 0 for v in by_tier.values())


# ------------------------------------------------------------ compiled plane

def test_compiled_per_tier_plan_gauges(mesh_2x4):
    """hierarchical=True with a DCN wire dtype must record the per-tier
    plan in trace-time gauges: dcn bytes = ici bytes / ici_size / 2 (the
    1/L scatter times the 16-bit wire), hierarchical gauge = 1."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    import horovod_tpu.metrics as hvd_metrics
    from horovod_tpu.compat import shard_map
    from horovod_tpu.parallel import fusion

    x = np.arange(8 * 4096, dtype=np.float32).reshape(8, 4096) / 3.0

    def body(t):
        (out,) = fusion.fused_allreduce(
            [jnp.squeeze(t, 0)], threshold=1 << 20, hierarchical=True,
            dcn_compression="bf16", compression_min_bytes=0)
        return out[None]

    f = shard_map(body, mesh=mesh_2x4, in_specs=P(("dcn", "ici")),
                  out_specs=P(("dcn", "ici")))
    out = np.asarray(jax.jit(f)(x))
    plan = hvd_metrics.last_tier_plan()
    assert plan["hierarchical"] is True
    assert plan["dcn_wire"] == "bf16" and plan["ici_size"] == 4
    ici = plan["bytes_per_step"]["ici"]
    assert plan["bytes_per_step"]["dcn"] == ici // 4 // 2, plan
    reg = hvd_metrics.registry().snapshot()["gauges"]
    assert reg.get("horovod_compiled_hierarchical") == 1.0
    assert reg.get(
        'horovod_compiled_tier_bytes_per_step{tier="dcn"}') == ici // 8
    # bf16 on the DCN hop only: within 16-bit tolerance of the true mean
    exp = x.mean(axis=0)
    scale = np.abs(exp).max()
    assert np.abs(out[0] - exp).max() / scale < 2 ** -7


def test_compiled_dcn_threshold_caps_buckets(mesh_2x4):
    """dcn_threshold bounds the bytes any bucket ships cross-host: with a
    cap of D the effective bucket cap is D*ici_size, so the plan splits
    into more buckets than the uncapped one."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    import horovod_tpu.metrics as hvd_metrics
    from horovod_tpu.compat import shard_map
    from horovod_tpu.parallel import fusion

    x = np.ones((8, 8192), dtype=np.float32)

    def run(dcn_threshold):
        def body(t):
            outs = fusion.fused_allreduce(
                [jnp.squeeze(t, 0)[:4096], jnp.squeeze(t, 0)[4096:]],
                threshold=1 << 20, hierarchical=True,
                dcn_threshold=dcn_threshold)
            return jnp.concatenate(outs)[None]

        f = shard_map(body, mesh=mesh_2x4, in_specs=P(("dcn", "ici")),
                      out_specs=P(("dcn", "ici")))
        jax.jit(f)(x).block_until_ready()
        return hvd_metrics.last_tier_plan()

    wide = run(None)
    # 4096 f32 elements = 16 KiB per leaf; DCN shard = 4 KiB. A 2 KiB DCN
    # cap forces each leaf's bucket (16 KiB > 2 KiB * ici_size=8 KiB) to
    # stay unmerged.
    capped = run(2 << 10)
    assert capped["buckets"] >= wide["buckets"], (wide, capped)
    assert capped["bytes_per_step"]["dcn"] <= wide["bytes_per_step"]["dcn"]
    assert max(b for b in [capped["bytes_per_step"]["dcn"]]) >= 0


def test_env_knob_reaches_compiled_plane(mesh_2x4, monkeypatch):
    """Satellite 1: HOROVOD_HIERARCHICAL_ALLREDUCE=1 flows through
    allreduce_gradients (no explicit argument) onto the ladder when the
    mesh has the axes — and degrades loudly to flat on a 1-D mesh."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    import horovod_tpu.jax as hvd_jax
    import horovod_tpu.metrics as hvd_metrics
    from horovod_tpu.compat import shard_map
    from horovod_tpu.parallel.mesh import data_parallel_mesh

    monkeypatch.setenv("HOROVOD_HIERARCHICAL_ALLREDUCE", "1")
    x = np.arange(8 * 512, dtype=np.float32).reshape(8, 512)

    def body(t):
        return hvd_jax.allreduce_gradients(jnp.squeeze(t, 0))[None]

    f = shard_map(body, mesh=mesh_2x4, in_specs=P(("dcn", "ici")),
                  out_specs=P(("dcn", "ici")))
    out = np.asarray(jax.jit(f)(x))
    assert hvd_metrics.last_tier_plan()["hierarchical"] is True
    np.testing.assert_allclose(out[0], x.mean(axis=0), rtol=1e-5)

    def body_flat(t):
        return hvd_jax.allreduce_gradients(jnp.squeeze(t, 0),
                                           axis_name="hvd")[None]

    f2 = shard_map(body_flat, mesh=data_parallel_mesh(), in_specs=P("hvd"),
                   out_specs=P("hvd"))
    out2 = np.asarray(jax.jit(f2)(x))
    assert hvd_metrics.last_tier_plan()["hierarchical"] is False
    np.testing.assert_allclose(out2[0], x.mean(axis=0), rtol=1e-5)


def test_autotune_fourth_dimension():
    """jax.autotune.tune(hierarchicals=...): the ladder choice is explored
    exhaustively beside (threshold, buckets, compression) and the winner's
    config records it."""
    from horovod_tpu.jax.autotune import tune

    seen = []

    def step_factory(fusion_threshold, num_buckets, compression,
                     hierarchical):
        seen.append((fusion_threshold, num_buckets, compression,
                     hierarchical))
        import time as _t

        # The synthetic objective rewards the hierarchical branch.
        delay = 0.0002 if hierarchical else 0.003

        def run():
            _t.sleep(delay)

        return run

    report = tune(step_factory, thresholds=(1 << 20,), num_buckets=(1, 2),
                  compressions=("none",), hierarchicals=(False, True),
                  warmup=0, iters=1, reps=1, gp_rounds=0)
    assert {h for (_, _, _, h) in seen} == {False, True}
    assert report.best.hierarchical is True
    assert report.best.config.get("hierarchical") is True
    assert "ladder" in report.knob_curve() or "hier" in report.knob_curve()
