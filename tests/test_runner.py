"""Launcher tests — the reference tests `horovod.spark.run` end-to-end on a
local cluster (test/test_spark.py:51 test_happy_run asserts allgather
results); same shape here without Spark."""

from __future__ import annotations

import os
import subprocess
import sys

import pytest

from horovod_tpu.runner.network import (
    BasicClient,
    BasicService,
    Channel,
    derive_key,
    make_secret,
)


def test_run_happy_path():
    """4-process programmatic launch: ranks assigned, collective correct,
    results ordered by rank (reference test_happy_run)."""
    from horovod_tpu.runner import run

    # Defined inside the test so cloudpickle ships it by value (module-level
    # functions in test modules aren't importable from worker processes).
    def train_fn(scale):
        import numpy as np

        import horovod_tpu as hvd

        hvd.init()
        out = hvd.allreduce(np.full((2,), float(hvd.rank()) * scale), average=True)
        result = (hvd.rank(), hvd.size(), out.tolist())
        hvd.shutdown()
        return result

    results = run(train_fn, args=(2.0,), num_proc=4, timeout=120)
    assert len(results) == 4
    mean = sum(r * 2.0 for r in range(4)) / 4
    for rank, (r, size, reduced) in enumerate(results):
        assert r == rank
        assert size == 4
        assert reduced == [mean, mean]


@pytest.mark.slow
def test_run_command_cli():
    """CLI path: each worker gets rank env and runs the command."""
    from horovod_tpu.runner import run_command

    script = (
        "import os, sys; sys.path.insert(0, os.environ['HVD_REPO']);\n"
        "import numpy as np, horovod_tpu as hvd\n"
        "hvd.init()\n"
        "out = hvd.allreduce(np.ones(2) * hvd.rank())\n"
        "assert out.tolist() == [0.5, 0.5], out\n"
        "hvd.shutdown()\n"
    )
    rc = run_command(
        [sys.executable, "-c", script], num_proc=2,
        env={"HVD_REPO": os.path.dirname(os.path.dirname(os.path.abspath(__file__)))},
    )
    assert rc == 0


def test_hmac_rejects_wrong_secret():
    """Unauthenticated peers are rejected before unpickling (reference
    spark/util/network.py digest check)."""

    class Echo(BasicService):
        def handle(self, request, client_addr):
            return request

    svc = Echo(make_secret())
    try:
        import socket as s

        conn = s.create_connection(("127.0.0.1", svc.port), timeout=10)
        conn.settimeout(10)
        ch = Channel(conn, make_secret(), server=False)  # wrong key
        ch.send({"evil": True})
        with pytest.raises((ConnectionError, OSError, PermissionError)):
            ch.recv()  # server dropped us without a response
    finally:
        svc.stop()


def test_replayed_message_rejected():
    """ADVICE r3 (medium): a captured request must not authenticate when
    replayed — neither within its own connection (sequence numbers) nor on
    a fresh connection (per-connection session nonce)."""
    import hashlib
    import hmac as h
    import socket as s
    import struct
    import pickle

    calls = []

    class Spy(BasicService):
        def handle(self, request, client_addr):
            calls.append(request)
            return {"ok": True}

    key = make_secret()
    svc = Spy(key)
    try:
        conn = s.create_connection(("127.0.0.1", svc.port), timeout=10)
        conn.settimeout(10)
        # perform the client handshake by hand so we hold the raw frame
        head = conn.recv(20)
        assert head[:4] == b"HVD2"
        session = h.new(key, b"hvd-session:" + head[4:], hashlib.sha256).digest()
        payload = pickle.dumps({"kind": "spawn", "argv": ["evil"]})
        mac = h.new(session, b"C" + struct.pack("!Q", 0) + payload,
                    hashlib.sha256).digest()
        frame = mac + struct.pack("!Q", len(payload)) + payload
        conn.sendall(frame)
        # legitimate first delivery is handled
        resp_head = conn.recv(1)
        assert resp_head  # server answered
        conn.recv(1 << 16)
        assert len(calls) == 1
        # in-connection replay: identical bytes, but the server now expects
        # seq 1 — must be dropped without reaching handle()
        conn.sendall(frame)
        conn.settimeout(5)
        got = b""
        try:
            got = conn.recv(1)
        except (ConnectionError, OSError, TimeoutError):
            pass
        assert got == b"", "server answered a replayed frame"
        # cross-connection replay: fresh connection = fresh nonce, the old
        # session MAC cannot validate
        conn2 = s.create_connection(("127.0.0.1", svc.port), timeout=10)
        conn2.settimeout(5)
        conn2.recv(20)  # new handshake (different nonce)
        conn2.sendall(frame)
        got = b""
        try:
            got = conn2.recv(1)
        except (ConnectionError, OSError, TimeoutError):
            pass
        assert got == b"", "server answered a cross-connection replay"
        assert len(calls) == 1, f"replay reached handle(): {calls}"
    finally:
        svc.stop()


def test_derive_key_is_purpose_bound():
    key = make_secret()
    a = derive_key(key, b"hvd-job:aaaa")
    b = derive_key(key, b"hvd-job:bbbb")
    assert a != b and len(a) == 32
    assert derive_key(key, b"hvd-job:aaaa") == a  # deterministic both ends


def test_hmac_happy_roundtrip():
    class Echo(BasicService):
        def handle(self, request, client_addr):
            return {"echo": request}

    key = make_secret()
    svc = Echo(key)
    try:
        client = BasicClient([("127.0.0.1", svc.port)], key)
        assert client.request({"x": 1}) == {"echo": {"x": 1}}
        client.close()
    finally:
        svc.stop()


def test_cli_requires_command():
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", "2"],
        capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert proc.returncode != 0
    assert "no command given" in proc.stderr


@pytest.mark.slow  # re-tiered r5: multi-process spawn cost; core coverage stays fast
def test_run_surfaces_worker_exception():
    """A failing rank must surface its traceback quickly, not a bare
    10-minute TimeoutError (reference spark timeout test, test_spark.py:71)."""
    from horovod_tpu.runner import run

    def failing_fn():
        import horovod_tpu as hvd

        hvd.init()
        if hvd.rank() == 1:
            raise ValueError("intentional rank-1 explosion")
        hvd.shutdown()
        return "ok"

    with pytest.raises(RuntimeError, match="intentional rank-1 explosion"):
        run(failing_fn, num_proc=2, timeout=120)


def test_run_clean_exit_without_result_fails_fast():
    """A worker that exits with code 0 WITHOUT reporting a result used to be
    invisible to the liveness poll (it only flagged non-zero codes), so the
    driver blocked for the full timeout. It must now fail promptly with an
    actionable message."""
    import time

    from horovod_tpu.runner import run

    def silent_quitter():
        import os

        if os.environ["HOROVOD_TASK_INDEX"] == "1":
            os._exit(0)   # clean exit, no registration, no result
        import numpy as np

        import horovod_tpu as hvd

        hvd.init()
        hvd.allreduce(np.ones(1))   # blocks forever waiting for rank 1

    t0 = time.monotonic()
    with pytest.raises(RuntimeError,
                       match="exited with code 0 before reporting"):
        run(silent_quitter, num_proc=2, timeout=120)
    assert time.monotonic() - t0 < 60, "clean exit took the full timeout path"


def test_basic_client_connect_retries():
    """Jittered connect retries (cold-start hardening): a client created
    BEFORE its service listens must connect once the service appears,
    instead of dying on the first refused connection."""
    import socket
    import threading
    import time

    class Echo(BasicService):
        def handle(self, request, client_addr):
            return {"echo": request}

    key = make_secret()
    # reserve a port, then start the service on it only after a delay
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    box: dict = {}

    def late_start():
        time.sleep(1.0)
        box["svc"] = Echo(key, host="127.0.0.1", port=port)

    t = threading.Thread(target=late_start)
    t.start()
    try:
        client = BasicClient([("127.0.0.1", port)], key, connect_retry_s=15.0)
        assert client.request({"x": 1}) == {"echo": {"x": 1}}
        client.close()
    finally:
        t.join()
        box["svc"].stop()
    # without a retry window the refused connection is immediate and fatal
    # (port 1 is never listening)
    t0 = time.monotonic()
    with pytest.raises(ConnectionError, match="cannot reach service"):
        BasicClient([("127.0.0.1", 1)], key)
    assert time.monotonic() - t0 < 5, "no-retry default should fail fast"


def test_run_rejects_bad_num_proc():
    from horovod_tpu.runner import run_command

    with pytest.raises(ValueError, match="num_proc"):
        run_command(["echo", "hi"], num_proc=0)


def test_payload_cap():
    """Oversized claimed lengths are rejected before allocation."""
    import socket as s
    import struct

    from horovod_tpu.runner.network import BasicService, make_secret

    class Echo(BasicService):
        def handle(self, request, client_addr):
            return request

    svc = Echo(make_secret())
    try:
        conn = s.create_connection(("127.0.0.1", svc.port), timeout=10)
        conn.settimeout(10)
        conn.recv(20)  # consume the server's session-nonce handshake
        conn.sendall(b"\0" * 32 + struct.pack("!Q", 1 << 40))  # 1 TiB claim
        conn.settimeout(5)
        with pytest.raises((ConnectionError, ConnectionResetError, OSError, TimeoutError)):
            data = conn.recv(1)
            if not data:
                raise ConnectionError("server closed on oversized claim")
    finally:
        svc.stop()


def test_check_build_reports_capabilities():
    """`hvdrun --check-build` (the later-reference horovodrun flag) must
    report the native engine and framework availability and exit 0."""
    from horovod_tpu.cc import lib_path

    lib_path()  # prebuild: the probe must not compile inside the timeout
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "--check-build"],
        capture_output=True, text=True, timeout=120,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "native eager engine (C++): yes" in proc.stdout
    assert "jax (compiled data plane): yes" in proc.stdout
    assert "collectives:" in proc.stdout
