"""Multi-chip sharded serving replicas (ISSUE 19): a prefill/decode
replica as a model-sharded mesh process group. Weights are dim-0-sliced
per chip and reassembled bitwise on access (ShardedLMParams); KV block
tables hold per-model-shard page slices (PagedKVCache(model_shards=));
the handoff channel carries the sharded pages. The bar everywhere is
token-for-token exactness against the unsharded ``lm_generate`` oracle —
including under preemption churn — plus the chip-budget gate that makes
the oversized-model smoke meaningful."""

from __future__ import annotations

import numpy as np
import pytest

from horovod_tpu.serving.config import LLMConfig
from horovod_tpu.serving.llm.handoff import (
    handoff_nbytes,
    is_sharded_payload,
    pack_kv,
    pack_kv_sharded,
    unpack_kv_sharded,
)
from horovod_tpu.serving.llm.kv_cache import PagedKVCache
from horovod_tpu.serving.llm.replica import (
    check_chip_budget,
    per_chip_persistent_nbytes,
)
from horovod_tpu.serving.llm.scheduler import IterationScheduler, Sequence
from horovod_tpu.serving.model import (
    ShardedLMParams,
    lm_generate,
    lm_params_nbytes,
    lm_prefill,
    shard_lm_params,
    tiny_lm_params,
)

PARAMS = tiny_lm_params()
ARRAY_KEYS = ("embed", "pos", "wq", "wk", "wv", "wo")


def _run(sched, max_steps=2000, until=None):
    for _ in range(max_steps):
        sched.step()
        if until is not None and sched.finished_total >= until:
            return
        if not sched.waiting and not sched.running:
            return
    raise AssertionError(f"scheduler did not drain: {sched.stats()}")


def _outputs(sched) -> dict:
    return {s.seq_id: list(s.out) for s in sched.finished}


# -- sharded params: bitwise gather + per-chip accounting ---------------------


@pytest.mark.parametrize("s", [1, 2, 4])
def test_shard_lm_params_gather_bitwise(s):
    sp = shard_lm_params(PARAMS, s)
    assert sp.model_shards == s
    for key in ARRAY_KEYS:
        got = sp[key]
        np.testing.assert_array_equal(got, PARAMS[key])
        assert got.dtype == PARAMS[key].dtype
    for key in ("vocab", "dim", "max_context"):
        assert sp[key] == PARAMS[key]
    assert "embed" in sp and "nope" not in sp
    assert sp.get("nope") is None
    assert set(sp.keys()) == set(PARAMS.keys())


def test_shard_lm_params_per_chip_bytes():
    total = lm_params_nbytes(PARAMS)
    for s in (2, 4):
        sp = shard_lm_params(PARAMS, s)
        assert sp.per_chip_nbytes() == total // s
        # The shards really are slices, not copies of the whole model.
        assert lm_params_nbytes(sp.shard(0)) == total // s


def test_shard_lm_params_validates_divisibility():
    with pytest.raises(ValueError, match="not divisible"):
        shard_lm_params(PARAMS, 3)   # 64/16/512 all reject s=3
    with pytest.raises(ValueError, match="model_shards"):
        shard_lm_params(PARAMS, 0)


# -- sharded KV pages ---------------------------------------------------------


def test_sharded_cache_gather_bitwise_vs_unsharded():
    rng = np.random.default_rng(7)
    dense = PagedKVCache(8, 4, 16)
    sharded = PagedKVCache(8, 4, 16, model_shards=4)
    assert sharded.per_chip_nbytes() * 4 == dense.per_chip_nbytes()
    for cache in (dense, sharded):
        assert cache.alloc.alloc("a", 10) is not None
    for pos in range(10):
        k, v = rng.normal(size=16).astype(np.float32), \
            rng.normal(size=16).astype(np.float32)
        dense.write("a", pos, k, v)
        sharded.write("a", pos, k, v)
    kd, vd = dense.gather("a", 10)
    ks, vs = sharded.gather("a", 10)
    np.testing.assert_array_equal(kd, ks)
    np.testing.assert_array_equal(vd, vs)
    # The per-shard page slices concatenate back to exactly the full view.
    k_sl, v_sl = sharded.gather_sharded("a", 10)
    assert len(k_sl) == 4 and k_sl[0].shape == (10, 4)
    np.testing.assert_array_equal(np.concatenate(k_sl, axis=-1), kd)
    np.testing.assert_array_equal(np.concatenate(v_sl, axis=-1), vd)


def test_cache_load_accepts_slice_lists_and_full_arrays():
    rng = np.random.default_rng(3)
    k = rng.normal(size=(6, 16)).astype(np.float32)
    v = rng.normal(size=(6, 16)).astype(np.float32)
    k_sl = np.split(k, 2, axis=1)
    v_sl = np.split(v, 2, axis=1)
    assert PagedKVCache.handoff_tokens(k) == 6
    assert PagedKVCache.handoff_tokens(k_sl) == 6
    # Every (cache sharding) x (payload form) combination lands the same
    # bytes — a sharded handoff can feed an unsharded cache and back.
    for shards in (1, 2, 4):
        for payload in ((k, v), (k_sl, v_sl)):
            cache = PagedKVCache(8, 4, 16, model_shards=shards)
            assert cache.load("s", payload[0], payload[1])
            gk, gv = cache.gather("s", 6)
            np.testing.assert_array_equal(gk, k)
            np.testing.assert_array_equal(gv, v)


def test_cache_validates_model_shards():
    with pytest.raises(ValueError, match="model_shards"):
        PagedKVCache(8, 4, 16, model_shards=3)   # 3 does not divide 16
    with pytest.raises(ValueError, match="model_shards"):
        PagedKVCache(8, 4, 16, model_shards=0)


# -- sharded handoff wire format ----------------------------------------------


def test_sharded_handoff_roundtrip_and_bytes():
    prompt = [9, 30, 2]
    k, v, first = lm_prefill(PARAMS, prompt)
    dense = pack_kv(prompt, k, v, first)
    sharded = pack_kv_sharded(prompt, np.split(k, 4, axis=1),
                              np.split(v, 4, axis=1), first)
    assert is_sharded_payload(sharded) and not is_sharded_payload(dense)
    # Same total wire bytes: sharding re-slices, it does not duplicate.
    assert handoff_nbytes(sharded) == handoff_nbytes(dense)
    tokens, ks, vs, f = unpack_kv_sharded(sharded)
    assert tokens == prompt and f == first
    np.testing.assert_array_equal(np.concatenate(ks, axis=1), k)
    np.testing.assert_array_equal(np.concatenate(vs, axis=1), v)


def test_sharded_handoff_validates_shapes():
    k, v, first = lm_prefill(PARAMS, [1, 2, 3])
    ks, vs = np.split(k, 2, axis=1), np.split(v, 2, axis=1)
    with pytest.raises(ValueError, match="malformed"):
        pack_kv_sharded([1, 2, 3], ks, vs[:1], first)       # count mismatch
    with pytest.raises(ValueError, match="malformed"):
        pack_kv_sharded([1, 2], ks, vs, first)              # token mismatch
    bad = pack_kv_sharded([1, 2, 3], ks, vs, first)
    bad["v_shards"] = [p[:-1] for p in bad["v_shards"]]     # truncated wire
    with pytest.raises(ValueError, match="malformed"):
        unpack_kv_sharded(bad)


# -- end-to-end: sharded replica group is token-for-token exact ---------------


def test_sharded_scheduler_token_for_token():
    """The full sharded stack (ShardedLMParams + sharded KV pages) under
    the iteration scheduler reproduces lm_generate exactly, per request,
    under continuous batching."""
    sp = shard_lm_params(PARAMS, 4)
    cache = PagedKVCache(32, 4, 16, model_shards=4)
    s = IterationScheduler(cache, sp, max_active=4)
    prompts = {0: [3, 17, 5], 1: [9, 30, 2, 8], 2: [60], 3: [1, 2, 3]}
    for sid, pr in prompts.items():
        s.submit(Sequence(sid, pr, 12))
    _run(s, until=len(prompts))
    outs = _outputs(s)
    for sid, pr in prompts.items():
        assert outs[sid] == lm_generate(PARAMS, pr, 12), sid


def test_sharded_preemption_churn_exact():
    """KV pressure forces preempt/resume on the SHARDED cache; every
    output still matches the unsharded oracle bitwise (resume re-prefills
    through the sharded params and re-pages the sharded slices)."""
    sp = shard_lm_params(PARAMS, 2)
    cache = PagedKVCache(12, 2, 16, watermark=1 / 12, model_shards=2)
    s = IterationScheduler(cache, sp, max_active=4, admission_window=8)
    prompts = {i: [10 + i, 20 + i, 30 + i] for i in range(6)}
    for sid, pr in prompts.items():
        s.submit(Sequence(sid, pr, 8))
    _run(s, until=len(prompts))
    assert cache.alloc.preemptions_total > 0, \
        "churn test did not actually churn"
    outs = _outputs(s)
    for sid, pr in prompts.items():
        assert outs[sid] == lm_generate(PARAMS, pr, 8), sid


def test_sharded_handoff_admission_matches_oracle():
    """Disaggregated path: a sharded prefill payload admitted into a
    sharded decode group decodes exactly like the colocated path and the
    oracle."""
    prompt, max_new = [9, 30, 2], 10
    sp = shard_lm_params(PARAMS, 4)
    k, v, first = lm_prefill(sp, prompt)   # prefill through sharded params
    payload = pack_kv_sharded(prompt, np.split(np.asarray(k), 4, axis=1),
                              np.split(np.asarray(v), 4, axis=1), first)
    tokens, ks, vs, f = unpack_kv_sharded(payload)

    via_handoff = IterationScheduler(
        PagedKVCache(16, 4, 16, model_shards=4), sp)
    via_handoff.submit(Sequence(0, tokens, max_new, first_token=f,
                                handoff=(ks, vs)))
    _run(via_handoff, until=1)
    assert _outputs(via_handoff)[0] == lm_generate(PARAMS, prompt, max_new)


# -- chip-budget gate ---------------------------------------------------------


def test_chip_budget_gate_frames_oversized_model():
    """A budget framed BETWEEN the sharded and unsharded per-chip
    footprints: the 2-D (unsharded) replica provably cannot start, the
    model_shards=2 group fits with the ISSUE 19 >= 1.8x headroom."""
    full = LLMConfig.from_env(num_blocks=64, model_shards=1)
    need_full = per_chip_persistent_nbytes(full, PARAMS)
    sharded_cfg = LLMConfig.from_env(num_blocks=64, model_shards=2)
    sp = shard_lm_params(PARAMS, 2)
    need_sharded = per_chip_persistent_nbytes(sharded_cfg, sp)
    assert need_full >= 1.8 * need_sharded   # uniform slices: exactly 2x
    budget = (need_sharded + need_full) // 2
    with pytest.raises(MemoryError, match="exceeds chip budget"):
        check_chip_budget(
            LLMConfig.from_env(num_blocks=64, chip_budget=budget), PARAMS)
    got = check_chip_budget(
        LLMConfig.from_env(num_blocks=64, model_shards=2,
                           chip_budget=budget), sp)
    assert got == need_sharded
    # chip_budget=0 never gates (the default).
    check_chip_budget(full, PARAMS)


def test_per_chip_bytes_excludes_cache_for_prefill_role():
    cfg = LLMConfig.from_env(model_shards=2)
    sp = shard_lm_params(PARAMS, 2)
    with_cache = per_chip_persistent_nbytes(cfg, sp, with_cache=True)
    without = per_chip_persistent_nbytes(cfg, sp, with_cache=False)
    assert without == sp.per_chip_nbytes()
    assert with_cache - without == \
        cfg.num_blocks * cfg.block_size * (16 // 2) * 4 * 2


# -- config plumbing ----------------------------------------------------------


def test_llmconfig_sharding_fields_roundtrip(monkeypatch):
    cfg = LLMConfig.from_env(model_shards=2, chip_budget=123456)
    env = cfg.to_env()
    assert env["HOROVOD_SERVE_LLM_MODEL_SHARDS"] == "2"
    assert env["HOROVOD_SERVE_LLM_CHIP_BUDGET_BYTES"] == "123456"
    for key, val in env.items():
        monkeypatch.setenv(key, val)
    again = LLMConfig.from_env()
    assert again.model_shards == 2 and again.chip_budget == 123456


def test_llmconfig_validates_sharding():
    with pytest.raises(ValueError, match="model_shards"):
        LLMConfig.from_env(model_shards=0)
    with pytest.raises(ValueError, match="divide dim"):
        LLMConfig.from_env(model_shards=3)   # dim=16
    with pytest.raises(ValueError, match="chip_budget"):
        LLMConfig.from_env(chip_budget=-1)


def test_sharded_params_type_is_dict_like_for_scheduler():
    sp = shard_lm_params(PARAMS, 2)
    assert isinstance(sp, ShardedLMParams)
    # The two accesses the scheduler/decode code actually performs:
    assert len(sp["pos"]) == PARAMS["max_context"]
    assert int(sp["dim"]) == 16
