"""LLM serving plane (ISSUE 12): config/admission/handoff units, request
plumbing, and the disaggregated + colocated end-to-end paths including
the decode-replica kill recovery bar."""

from __future__ import annotations

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from horovod_tpu.serving.admission import KVAdmission
from horovod_tpu.serving.config import LLMConfig, ServeConfig
from horovod_tpu.serving.llm import LLMServer
from horovod_tpu.serving.llm.generator import GenQueue, GenRequest
from horovod_tpu.serving.llm.handoff import (
    handoff_nbytes,
    pack_kv,
    unpack_kv,
)
from horovod_tpu.serving.model import (
    lm_builder,
    lm_generate,
    lm_prefill,
    tiny_lm_params,
)

PARAMS = tiny_lm_params()


# -- config -------------------------------------------------------------------


def test_llm_config_env_overrides_and_roundtrip(monkeypatch):
    monkeypatch.setenv("HOROVOD_SERVE_LLM_BLOCK_SIZE", "8")
    monkeypatch.setenv("HOROVOD_SERVE_LLM_NUM_BLOCKS", "99")
    monkeypatch.setenv("HOROVOD_SERVE_LLM_WATERMARK", "0.2")
    cfg = LLMConfig.from_env(max_active=3)
    assert (cfg.block_size, cfg.num_blocks, cfg.max_active) == (8, 99, 3)
    assert cfg.watermark == 0.2
    # env round trip: a replica re-reading to_env() gets the same config
    env = cfg.to_env()
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    assert LLMConfig.from_env() == cfg
    with pytest.raises(TypeError, match="unknown LLMConfig overrides"):
        LLMConfig.from_env(nope=1)


def test_llm_config_validation():
    with pytest.raises(ValueError, match="watermark"):
        LLMConfig.from_env(watermark=1.5)
    with pytest.raises(ValueError, match="decode_replicas"):
        LLMConfig.from_env(colocated=0, prefill_replicas=0)
    assert LLMConfig.from_env(colocated=1, prefill_replicas=0)  # ok
    with pytest.raises(ValueError, match="SLO"):
        LLMConfig.from_env(ttft_slo_ms=0)


def test_usable_blocks_excludes_watermark_reserve():
    cfg = LLMConfig.from_env(num_blocks=100, watermark=0.05)
    assert cfg.usable_blocks() == 95
    assert LLMConfig.from_env(num_blocks=10,
                              watermark=0.0).usable_blocks() == 10


def test_lm_builder_reads_env_contract(monkeypatch):
    monkeypatch.setenv("HOROVOD_SERVE_LLM_SEED", "7")
    monkeypatch.setenv("HOROVOD_SERVE_LLM_DIM", "8")
    p = lm_builder(None)
    assert p["dim"] == 8
    np.testing.assert_array_equal(
        p["embed"], tiny_lm_params(dim=8, seed=7)["embed"])
    # checkpointed params win verbatim
    assert lm_builder({"lm_params": PARAMS}) is PARAMS


# -- KV admission -------------------------------------------------------------


def _adm(**kw):
    kw.setdefault("num_blocks", 100)
    kw.setdefault("watermark", 0.0)
    return KVAdmission(LLMConfig.from_env(**kw))


def test_kv_admission_cold_start_admits_everything():
    adm = _adm()
    ok, wait = adm.admit(blocks_needed=1000, free_blocks=0,
                         queued_blocks=1000)
    assert ok and wait == 0.0


def test_kv_admission_fit_now_admits_without_estimate_pressure():
    adm = _adm()
    adm.observe_release(1, 10.0)           # slow: 0.1 blocks/s
    ok, wait = adm.admit(blocks_needed=5, free_blocks=50, queued_blocks=10)
    assert ok and wait == 0.0


def test_kv_admission_sheds_on_projected_block_wait():
    adm = _adm(ttft_slo_ms=1000.0)
    adm.observe_release(10, 1.0)           # 10 blocks/s
    # deficit = 30 needed + 0 queued - 10 free = 20 -> 2s > 1s budget
    ok, wait = adm.admit(blocks_needed=30, free_blocks=10, queued_blocks=0)
    assert not ok and wait == pytest.approx(2.0)
    # same deficit with a 3s request budget passes
    ok, _ = adm.admit(30, 10, 0, budget_s=3.0)
    assert ok


def test_kv_admission_respects_watermark_and_queue_demand():
    adm = _adm(num_blocks=100, watermark=0.1, ttft_slo_ms=100.0)
    adm.observe_release(1, 1.0)
    # 20 free but 10 reserved; 8 queued ahead: 5 + 8 > 10 usable -> wait
    ok, wait = adm.admit(blocks_needed=5, free_blocks=20, queued_blocks=8)
    assert not ok and wait == pytest.approx(3.0)


def test_kv_admission_ewma_tracks_release_rate():
    adm = _adm()
    for _ in range(60):
        adm.observe_release(20, 1.0)
    assert adm.release_rate() == pytest.approx(20.0, rel=0.05)


# -- handoff ------------------------------------------------------------------


def test_handoff_pack_unpack_roundtrip_and_bytes():
    k, v, first = lm_prefill(PARAMS, [3, 17, 5])
    payload = pack_kv([3, 17, 5], k, v, first)
    assert handoff_nbytes(payload) == k.nbytes + v.nbytes
    tokens, k2, v2, first2 = unpack_kv(payload)
    assert tokens == [3, 17, 5] and first2 == first
    np.testing.assert_array_equal(k, k2)
    np.testing.assert_array_equal(v, v2)


def test_handoff_rejects_malformed_payloads():
    k, v, first = lm_prefill(PARAMS, [3, 17])
    with pytest.raises(ValueError, match="malformed"):
        pack_kv([3], k, v, first)            # token/page count mismatch
    bad = pack_kv([3, 17], k, v, first)
    bad["k"] = bad["k"][:1]
    with pytest.raises(ValueError, match="malformed"):
        unpack_kv(bad)


# -- request/queue plumbing ---------------------------------------------------


def test_gen_request_terminal_state_single_assignment():
    req = GenRequest([1, 2], 8)
    assert req.finish([5, 6, 7])
    assert not req.fail(504, "late timeout")
    assert req.code == 200 and req.tokens == [5, 6, 7]
    req2 = GenRequest([1], 4)
    assert req2.fail(504, "deadline")
    assert not req2.finish([9])
    assert req2.code == 504


def test_gen_request_ttft_and_tpot_math():
    req = GenRequest([1], 8)
    req.mark_first_token(req.enqueue_t + 0.5)
    req.mark_first_token(req.enqueue_t + 9.0)   # second mark is a no-op
    assert req.ttft_s == pytest.approx(0.5, abs=0.01)
    assert req.finish([1, 2, 3])
    tpot = req.tpot_s()
    assert tpot is not None and tpot >= 0.0
    assert GenRequest([1], 4).tpot_s() is None   # unfinished -> None


def test_gen_queue_fifo_front_cap_and_close():
    q = GenQueue(cap=2)
    assert q.put("a") and q.put("b") and not q.put("c")
    q.put_front(["x", "y"])                 # order preserved: x, y, a, b
    assert [q.take(0.01) for _ in range(4)] == ["x", "y", "a", "b"]
    assert q.take(0.01) is None
    q2 = GenQueue()
    q2.put("z")
    assert q2.close() == ["z"]
    assert not q2.put("w")                  # closed


# -- e2e ----------------------------------------------------------------------


def _post(port, payload, timeout=60.0, path="/v1/generate"):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def test_disaggregated_e2e_oracle_kill_and_stats():
    """1 prefill + 1 decode replica: HTTP generations match the
    sequential oracle token-for-token, /stats carries a schema-valid
    snapshot with the llm series, and a SIGKILL'd decode replica
    recovers by re-prefill + requeue with ZERO failed requests."""
    cfg = ServeConfig.from_env(port=0, slo_ms=60000.0, max_retries=3)
    llm_cfg = LLMConfig.from_env(colocated=0, prefill_replicas=1,
                                 decode_replicas=1)
    server = LLMServer(config=cfg, llm_config=llm_cfg).start()
    try:
        assert server.wait_ready(60), \
            {r: p.describe() for r, p in server.pools.items()}
        st, body = _post(server.port, {"prompt": [3, 17, 5],
                                       "max_tokens": 16})
        assert st == 200
        assert body["tokens"] == lm_generate(PARAMS, [3, 17, 5], 16)
        assert body["ttft_ms"] > 0 and body["n_tokens"] == 16

        # GET /debug/sequences (ISSUE 15 satellite): the live
        # per-sequence mirror answers on the LLM plane with the decode
        # pool's replicas keyed in.
        with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/debug/sequences",
                timeout=10) as r:
            seqs = json.loads(r.read())
        assert "replicas" in seqs and "prefill_queue_depth" in seqs
        for rows in seqs["replicas"].values():
            for row in rows:
                assert {"rid", "state", "slot", "blocks", "tokens_out",
                        "waited_iters", "preemptions"} <= set(row)

        # malformed requests answer 400, not 500
        for bad in ({"prompt": []}, {"prompt": [999]},
                    {"prompt": [1], "max_tokens": 10 ** 6}, {}):
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(server.port, bad)
            assert ei.value.code == 400

        # kill the decode replica mid-load: every request still answers
        # 200 with oracle-exact tokens (re-prefill regenerates KV). The
        # load is time-based so requests are in flight across the kill.
        failures: list = []
        oks = []
        stop_t = time.monotonic() + 3.0

        def load(i):
            j = 0
            while time.monotonic() < stop_t:
                j += 1
                pr = [(i * 7 + j) % 64, (i * 3 + 1) % 64]
                try:
                    stc, b = _post(server.port,
                                   {"prompt": pr, "max_tokens": 10})
                    if stc != 200 or b["tokens"] != lm_generate(
                            PARAMS, pr, 10):
                        failures.append((stc, pr, b))
                    else:
                        oks.append(time.monotonic())
                except Exception as e:  # noqa: BLE001
                    failures.append(repr(e))

        threads = [threading.Thread(target=load, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.5)
        dec = server.pools["decode"]
        victim = next(r for r in dec.describe()["replicas"].values()
                      if r["state"] == "serving")
        kill_t = time.monotonic()
        os.kill(victim["pid"], 9)
        for t in threads:
            t.join()
        assert not failures, failures[:5]
        assert any(t0 > kill_t for t0 in oks), \
            "no request completed after the kill — chaos leg proved nothing"

        deadline = time.monotonic() + 60
        while dec.serving_count() < 1 and time.monotonic() < deadline:
            time.sleep(0.1)
        assert dec.serving_count() >= 1, "decode replica never respawned"
        assert dec.blacklist.blacklisted(), "victim not blacklisted"

        from horovod_tpu.metrics import validate_snapshot

        stats = server.stats()
        assert validate_snapshot(stats["metrics"]) == []
        cs = stats["metrics"]["counters"]
        assert cs.get("horovod_serve_replica_deaths_total", 0) >= 1
        assert cs.get("horovod_serve_replica_respawns_total", 0) >= 1
        assert cs.get('horovod_serve_llm_handoffs_total{path="wire"}',
                      0) >= 10
        assert cs.get("horovod_serve_llm_handoff_bytes_total", 0) > 0
        assert cs.get('horovod_serve_llm_tokens_total{phase="decode"}',
                      0) > 0
        assert stats["serving"]["llm"]["ttft_p99_ms"] > 0
    finally:
        server.stop()


def test_sharded_replica_e2e_oracle():
    """model_shards=2 (ISSUE 19): the disaggregated path serves through
    multi-chip mesh replica groups under a chip budget the UNSHARDED
    model provably exceeds — sharded pages cross the authenticated
    handoff channel and generations stay token-for-token oracle-exact."""
    from horovod_tpu.serving.llm.replica import per_chip_persistent_nbytes
    from horovod_tpu.serving.model import shard_lm_params

    need_full = per_chip_persistent_nbytes(
        LLMConfig.from_env(colocated=0), PARAMS)
    need_sharded = per_chip_persistent_nbytes(
        LLMConfig.from_env(colocated=0, model_shards=2),
        shard_lm_params(PARAMS, 2))
    budget = (need_full + need_sharded) // 2
    assert need_sharded <= budget < need_full, \
        "budget framing broken — the oversized claim would be vacuous"

    cfg = ServeConfig.from_env(port=0, slo_ms=60000.0)
    llm_cfg = LLMConfig.from_env(colocated=0, prefill_replicas=1,
                                 decode_replicas=1, model_shards=2,
                                 chip_budget=budget)
    server = LLMServer(config=cfg, llm_config=llm_cfg).start()
    before = dict(server.reg.snapshot()["counters"])
    try:
        assert server.wait_ready(60), \
            {r: p.describe() for r, p in server.pools.items()}
        for pr, n in ([3, 17, 5], 16), ([60], 8), ([9, 30, 2, 8], 12):
            st, body = _post(server.port, {"prompt": pr, "max_tokens": n})
            assert st == 200
            assert body["tokens"] == lm_generate(PARAMS, pr, n), pr
        after = server.reg.snapshot()["counters"]

        def delta(key):
            return after.get(key, 0) - before.get(key, 0)

        assert delta('horovod_serve_llm_handoffs_total{path="wire"}') >= 3
        assert delta("horovod_serve_llm_handoff_bytes_total") > 0
    finally:
        server.stop()


def test_colocated_e2e_local_fast_path():
    """HOROVOD_SERVE_LLM_COLOCATED=1: one both-role replica, prefill
    inside the decode engine, handoffs counted as path=local with zero
    wire bytes."""
    cfg = ServeConfig.from_env(port=0, slo_ms=60000.0)
    llm_cfg = LLMConfig.from_env(colocated=1, decode_replicas=1)
    server = LLMServer(config=cfg, llm_config=llm_cfg).start()
    # the registry is process-global: assert DELTAS, not absolutes
    before = dict(server.reg.snapshot()["counters"])
    try:
        assert server.wait_ready(60)
        st, body = _post(server.port, {"prompt": [9, 2], "max_tokens": 12})
        assert st == 200
        assert body["tokens"] == lm_generate(PARAMS, [9, 2], 12)
        cs = server.stats()["metrics"]["counters"]

        def delta(series):
            return cs.get(series, 0) - before.get(series, 0)

        assert delta('horovod_serve_llm_handoffs_total{path="local"}') >= 1
        assert delta('horovod_serve_llm_handoffs_total{path="wire"}') == 0
        assert delta("horovod_serve_llm_handoff_bytes_total") == 0
    finally:
        server.stop()


def test_generate_route_absent_on_stateless_server(tmp_path):
    """POST /v1/generate against the PR 10 stateless plane answers 404
    naming the LLM server (route delegation, not a crash)."""
    from horovod_tpu.serving.frontend import ServeFrontend

    class _Stub:
        cfg = ServeConfig.from_env(port=0)

        def ready_count(self):
            return 0

    stub = _Stub()
    fe = ServeFrontend(stub)
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(fe.port, {"prompt": [1]})
        assert ei.value.code == 404
        assert b"LLM" in ei.value.read()
    finally:
        fe.stop()

# -- streaming (ISSUE 20) -----------------------------------------------------


def _stream_post(port, payload, timeout=60.0):
    """Raw chunked read of a streaming /v1/generate: returns
    ``(status, transfer_encoding, [(arrival_t, parsed_line), ...])``."""
    import http.client

    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", "/v1/generate",
                     body=json.dumps(payload).encode(),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        lines = []
        while True:
            raw = resp.readline()
            if not raw:
                break
            lines.append((time.monotonic(), json.loads(raw)))
        return resp.status, resp.getheader("Transfer-Encoding"), lines
    finally:
        conn.close()


def test_streaming_reassembly_equals_non_streaming_body():
    """The streaming bar: per-token JSONL chunks reassemble to EXACTLY
    the non-streaming response — same tokens, same final object shape —
    and the final line is oracle-exact."""
    cfg = ServeConfig.from_env(port=0, slo_ms=60000.0)
    llm_cfg = LLMConfig.from_env(colocated=1, decode_replicas=1)
    server = LLMServer(config=cfg, llm_config=llm_cfg).start()
    try:
        assert server.wait_ready(60)
        prompt, max_new = [3, 17, 5], 24
        st, plain = _post(server.port, {"prompt": prompt,
                                        "max_tokens": max_new})
        assert st == 200
        status, te, lines = _stream_post(
            server.port, {"prompt": prompt, "max_tokens": max_new,
                          "stream": True})
        assert status == 200 and te == "chunked"
        token_lines, final = [obj for _, obj in lines[:-1]], lines[-1][1]
        # the terminal object IS the non-streaming body (timings differ)
        assert set(final) == set(plain)
        assert final["tokens"] == plain["tokens"] == lm_generate(
            PARAMS, prompt, max_new)
        assert final["n_tokens"] == max_new
        # per-token chunks: contiguous indices, reassembling to the body
        assert [ln["i"] for ln in token_lines] == list(range(max_new))
        assert [ln["token"] for ln in token_lines] == final["tokens"]
        cs = server.stats()["metrics"]["counters"]
        assert cs.get("horovod_serve_llm_streams_total", 0) >= 1
    finally:
        server.stop()


def test_streaming_default_env_and_per_request_override():
    """HOROVOD_SERVE_LLM_STREAM=1 makes streaming the default; a body
    ``"stream": false`` still gets a plain Content-Length reply."""
    cfg = ServeConfig.from_env(port=0, slo_ms=60000.0)
    llm_cfg = LLMConfig.from_env(colocated=1, decode_replicas=1, stream=1)
    server = LLMServer(config=cfg, llm_config=llm_cfg).start()
    try:
        assert server.wait_ready(60)
        status, te, lines = _stream_post(
            server.port, {"prompt": [9, 2], "max_tokens": 8})
        assert status == 200 and te == "chunked"
        assert lines[-1][1]["tokens"] == lm_generate(PARAMS, [9, 2], 8)
        st, body = _post(server.port, {"prompt": [9, 2], "max_tokens": 8,
                                       "stream": False})
        assert st == 200
        assert body["tokens"] == lm_generate(PARAMS, [9, 2], 8)
    finally:
        server.stop()


def test_streaming_errors_stay_reachable():
    """Admission rejections answer plain 400 (nothing to stream); a
    deadline that expires mid-stream surfaces in-band as the terminal
    object's ``"error"`` — the client never hangs on a dead stream."""
    cfg = ServeConfig.from_env(port=0, slo_ms=60000.0)
    llm_cfg = LLMConfig.from_env(colocated=1, decode_replicas=1)
    server = LLMServer(config=cfg, llm_config=llm_cfg).start()
    try:
        assert server.wait_ready(60)
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(server.port, {"prompt": [], "stream": True})
        assert ei.value.code == 400
        status, _, lines = _stream_post(
            server.port, {"prompt": [3, 1], "max_tokens": 16,
                          "stream": True, "deadline_ms": 1})
        assert status == 200                 # already committed to chunked
        assert "error" in lines[-1][1]
    finally:
        server.stop()
