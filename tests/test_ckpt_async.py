"""Async checkpoint commits + streaming cold-start (ISSUE 18).

The contract under test: moving the commit off the step path changes
NOTHING about crash consistency — a SIGKILL mid-stage leaves the old
checkpoint (the torn stage is discarded), a SIGKILL mid-rename leaves an
adoptable complete stage (healed on the next restore), and a checkpoint
streamed from a peer is bitwise identical to one restored from the
filesystem."""

import hashlib
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from horovod_tpu import checkpoint
from horovod_tpu.ckpt_async import (
    AsyncCheckpointer,
    fetch_from_peer,
    serve_chunk,
    serve_manifest,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tree_hash(root):
    """Order-stable digest over every file's relative path + bytes."""
    h = hashlib.sha256()
    for dirpath, dirnames, files in os.walk(root):
        dirnames.sort()
        for name in sorted(files):
            p = os.path.join(dirpath, name)
            h.update(os.path.relpath(p, root).encode())
            with open(p, "rb") as f:
                h.update(f.read())
    return h.hexdigest()


# -- background writer --------------------------------------------------------


def test_async_writer_commits_latest(tmp_path):
    path = str(tmp_path / "ck")
    w = AsyncCheckpointer(path)
    try:
        w.submit({"w": np.arange(4.0)})
        assert w.wait(60)
        w.submit({"w": np.arange(4.0) * 3})
    finally:
        w.close()
    assert w.commits == 2
    out = checkpoint.restore(path, template={"w": np.zeros(4)})
    np.testing.assert_array_equal(out["w"], np.arange(4.0) * 3)
    # commit discipline left no stage/trash debris
    assert sorted(os.listdir(tmp_path)) == ["ck"]


def test_async_writer_error_reraised_on_step_thread(tmp_path):
    def boom(path, state, step=None):
        raise RuntimeError("disk on fire")

    w = AsyncCheckpointer(str(tmp_path / "ck"), save_fn=boom)
    w.submit({"w": np.ones(2)})
    with pytest.raises(RuntimeError, match="background checkpoint commit"):
        # surfaces on the NEXT training-thread interaction
        for _ in range(200):
            time.sleep(0.01)
            w.submit({"w": np.ones(2)})
    # the raise consumed the error; a fresh failed commit re-arms it and
    # close() refuses to swallow it
    w.submit({"w": np.ones(2)})
    with pytest.raises(RuntimeError, match="background checkpoint commit"):
        w.close()


def test_elastic_commit_drains_to_same_process_reader(tmp_path, monkeypatch):
    """ElasticState.commit goes through the async writer (default ON) and a
    cold load_checkpoint in the same process flushes it first."""
    from horovod_tpu.elastic.state import ElasticState

    monkeypatch.delenv("HOROVOD_CKPT_ASYNC", raising=False)
    ckdir = str(tmp_path / "ck")
    state = ElasticState(checkpoint_dir=ckdir, step=0,
                         params=np.arange(6.0))
    state.step = 7
    state.params = np.arange(6.0) * 2
    state.commit(check_host_updates=False)
    assert state._async_writer is not None
    assert state.checkpoint_wait(60)
    cold = ElasticState(checkpoint_dir=ckdir, step=0, params=np.zeros(6))
    assert cold.load_checkpoint() is True
    assert int(cold.step) == 7
    np.testing.assert_array_equal(np.asarray(cold.params), np.arange(6.0) * 2)
    state._async_writer.close()


def test_elastic_commit_sync_when_knobbed_off(tmp_path, monkeypatch):
    from horovod_tpu.elastic.state import ElasticState

    monkeypatch.setenv("HOROVOD_CKPT_ASYNC", "0")
    ckdir = str(tmp_path / "ck")
    state = ElasticState(checkpoint_dir=ckdir, step=3, params=np.ones(2))
    state.commit(check_host_updates=False)
    assert state._async_writer is None          # sync path took it
    assert os.path.isdir(ckdir)


# -- SIGKILL crash windows ----------------------------------------------------

_KILL_SCRIPT = """
import os, sys
sys.path.insert(0, os.environ["HVD_REPO"])
import numpy as np
from horovod_tpu.ckpt_async import AsyncCheckpointer

w = AsyncCheckpointer(os.environ["CK_PATH"])
w.submit({"w": np.arange(4.0) * 5, "step": np.int64(2)})
w.wait(120)
print("COMMITTED", flush=True)
"""


def _spawn_killed_commit(path, stall_point, marker_fn, timeout=60.0):
    """Run the async-writer script with the commit stalled at
    ``stall_point``, SIGKILL it the moment ``marker_fn()`` sees the stall
    window's filesystem state, and assert the kill landed mid-commit."""
    env = dict(os.environ,
               HVD_REPO=REPO, CK_PATH=path, JAX_PLATFORMS="cpu",
               HOROVOD_CKPT_TEST_STALL=stall_point,
               HOROVOD_CKPT_TEST_STALL_S="45")
    proc = subprocess.Popen([sys.executable, "-c", _KILL_SCRIPT], env=env,
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    try:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if marker_fn():
                break
            if proc.poll() is not None:
                out, err = proc.communicate()
                raise AssertionError(
                    f"writer exited before the {stall_point} window:\n"
                    f"{err.decode()[-2000:]}")
            time.sleep(0.02)
        else:
            raise AssertionError(f"{stall_point} window never appeared")
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=30)
    assert proc.returncode == -signal.SIGKILL


def _siblings(path, infix):
    parent, base = os.path.split(path)
    try:
        return [n for n in os.listdir(parent)
                if n.startswith(base + infix)]
    except OSError:
        return []


def test_sigkill_mid_stage_discards_and_keeps_old(tmp_path):
    """Kill while the stage exists but carries no .ok: heal discards the
    torn stage; the previous commit restores bitwise intact."""
    path = str(tmp_path / "ck")
    checkpoint.save_local(path, {"w": np.arange(4.0), "step": np.int64(1)})
    before = _tree_hash(path)

    def in_stage_window():
        stages = [n for n in _siblings(path, ".tmp.")
                  if not n.endswith(".ok")]
        return bool(stages) and not any(
            n.endswith(".ok") for n in _siblings(path, ".tmp."))

    _spawn_killed_commit(path, "stage", in_stage_window)
    checkpoint._heal_interrupted(path)
    assert _siblings(path, ".tmp.") == [] and _siblings(path, ".trash.") == []
    assert _tree_hash(path) == before       # old checkpoint bitwise intact
    out = checkpoint.restore(path, template={"w": np.zeros(4),
                                             "step": np.array(0, np.int64)})
    np.testing.assert_array_equal(out["w"], np.arange(4.0))


def test_sigkill_mid_rename_adopts_complete_stage(tmp_path):
    """Kill between the swap's two renames (old moved aside, new not yet
    in): the complete .ok stage is adopted and the NEW commit restores."""
    path = str(tmp_path / "ck")
    checkpoint.save_local(path, {"w": np.arange(4.0), "step": np.int64(1)})

    def in_rename_window():
        return bool(_siblings(path, ".trash.")) and not os.path.exists(path)

    _spawn_killed_commit(path, "rename", in_rename_window)
    assert not os.path.exists(path)          # died inside the window
    # restore() heals: adopts the complete stage, discards the trash
    out = checkpoint.restore(path, template={"w": np.zeros(4),
                                             "step": np.array(0, np.int64)})
    np.testing.assert_array_equal(out["w"], np.arange(4.0) * 5)
    assert int(out["step"]) == 2
    checkpoint._heal_interrupted(path)
    assert _siblings(path, ".tmp.") == [] and _siblings(path, ".trash.") == []


# -- checkpoint streaming -----------------------------------------------------


def test_stream_fetch_bitwise_matches_filesystem(tmp_path):
    """A joiner's streamed checkpoint is bitwise identical to the peer's,
    and restores to the same values as a filesystem restore."""
    from horovod_tpu.ctrl.agent import ControlAgent

    src = str(tmp_path / "ck")
    checkpoint.save_local(src, {"w": np.arange(8.0), "step": np.int64(4)})
    agent = ControlAgent(b"stream-secret", ckpt_dir=src)
    dest = str(tmp_path / "fetched")
    try:
        man = fetch_from_peer([("127.0.0.1", agent.port)], b"stream-secret",
                              dest)
    finally:
        agent.stop()
    assert man["ok"] and man["total_bytes"] > 0
    assert _tree_hash(src) == _tree_hash(dest)
    got = checkpoint.load_for_inference(dest)
    np.testing.assert_array_equal(np.asarray(got["w"]), np.arange(8.0))
    # publish used the commit discipline: no stage/marker debris
    assert sorted(os.listdir(tmp_path)) == ["ck", "fetched"]


def test_stream_manifest_skips_uncommitted(tmp_path):
    src = str(tmp_path / "ck")
    checkpoint.save_local(src, {"w": np.ones(2)})
    os.makedirs(os.path.join(src, "x.tmp.99"))
    with open(os.path.join(src, "x.tmp.99", "torn"), "w") as f:
        f.write("torn")
    with open(src + ".ok", "w") as f:
        f.write("marker")
    man = serve_manifest(src)
    assert man["ok"]
    assert all(".tmp." not in e["path"] and not e["path"].endswith(".ok")
               for e in man["files"])


def test_stream_chunk_rejects_traversal(tmp_path):
    src = str(tmp_path / "ck")
    checkpoint.save_local(src, {"w": np.ones(2)})
    bad = serve_chunk(src, {"path": "../../etc/passwd"})
    assert bad["ok"] is False and "escapes" in bad["error"]


def test_stream_corrupt_peer_never_published(tmp_path, monkeypatch):
    """A digest mismatch aborts BEFORE publish: no destination directory,
    no adoptable .ok stage."""
    from horovod_tpu.ckpt_async import stream as stream_mod
    from horovod_tpu.ctrl.agent import ControlAgent

    src = str(tmp_path / "ck")
    checkpoint.save_local(src, {"w": np.arange(4.0)})
    agent = ControlAgent(b"stream-secret", ckpt_dir=src)
    dest = str(tmp_path / "fetched")
    monkeypatch.setattr(stream_mod, "_sha256_file", lambda p: "0" * 64)
    try:
        with pytest.raises(RuntimeError, match="refusing to publish"):
            fetch_from_peer([("127.0.0.1", agent.port)], b"stream-secret",
                            dest)
    finally:
        agent.stop()
    assert not os.path.exists(dest)
    assert not os.path.exists(dest + ".ok")


def test_stream_sources_env_parse(monkeypatch):
    from horovod_tpu.ckpt_async.stream import stream_sources_from_env

    monkeypatch.setenv("HOROVOD_CKPT_STREAM_FROM",
                       "10.0.0.1:9100, host-b:9101")
    assert stream_sources_from_env() == [("10.0.0.1", 9100),
                                        ("host-b", 9101)]
