"""Shared multi-process launch harness for engine tests.

One implementation of the free-port / shared-secret / HOROVOD_* env / Popen
world spawner (previously copied per test file — protocol env changes now
land in exactly one place).

``free_port()`` is inherently TOCTOU: the probe socket closes before the
coordinator binds, so a parallel test (or anything else on the host) can
steal the port in between. The coordinator itself now rides
``resilience.bind_with_retry`` (same-port re-sweep for ~15 s), which
absorbs the common case of a *lingering* socket from a previous world; when
the port is genuinely taken by another live server, ``launch_world``
detects the EADDRINUSE rank failure and relaunches the whole world on a
fresh port (the known test_protocol flake — passed in isolation, collided
under a full parallel run).
"""

from __future__ import annotations

import json
import os
import secrets
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Signatures of a rank that died because the coordinator (or any listener it
# opens) lost the free_port() race. Matched against stderr of failed ranks.
_EADDRINUSE_MARKS = ("Address already in use", "EADDRINUSE", "Errno 98")


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _launch_once(world: int, script: str, extra_env, per_rank_env,
                 timeout: float) -> list[dict]:
    port = free_port()
    secret = secrets.token_hex(16)
    procs = []
    for rank in range(world):
        env = dict(os.environ)
        env.update({
            "HVD_REPO": REPO,
            "HOROVOD_RANK": str(rank),
            "HOROVOD_SIZE": str(world),
            "HOROVOD_COORD_ADDR": f"127.0.0.1:{port}",
            "HOROVOD_SECRET": secret,
        })
        env.update(extra_env or {})
        env.update((per_rank_env or {}).get(rank, {}))
        procs.append(subprocess.Popen(
            [sys.executable, "-c", script], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        ))
    results = []
    try:
        for p in procs:
            stdout, stderr = p.communicate(timeout=timeout)
            out = stdout.strip().splitlines()
            parsed, parse_err = None, None
            if out:
                try:
                    parsed = json.loads(out[-1])
                except ValueError as e:
                    parse_err = e
            results.append({
                "rc": p.returncode,
                "out": parsed,
                "stderr": stderr,
                "_parse_err": parse_err,
            })
    finally:
        # One hung or failed rank must not leak the others into the rest of
        # the pytest session (they would keep the coordinator port busy).
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    return results


def launch_world(world: int, script: str, extra_env=None, per_rank_env=None,
                 timeout: float = 180, check: bool = True,
                 bind_attempts: int = 3) -> list[dict]:
    """Spawn ``world`` ranks running ``script`` with a shared secret and
    coordinator address. Returns per-rank dicts:
    ``{"rc": int, "out": <last stdout line parsed as JSON> | None,
    "stderr": str}``. With ``check`` (default) a non-zero rank fails the
    test immediately — unless the failure is a port-bind collision
    (EADDRINUSE in stderr), in which case the whole world is relaunched on
    a fresh port, up to ``bind_attempts`` times total."""
    attempts = max(bind_attempts, 1)
    results: list[dict] = []
    for attempt in range(attempts):
        results = _launch_once(world, script, extra_env, per_rank_env,
                               timeout)
        collided = any(
            r["rc"] != 0 and any(m in r["stderr"]
                                 for m in _EADDRINUSE_MARKS)
            for r in results)
        if not collided or attempt == attempts - 1:
            break
        time.sleep(0.2)
    if check:
        for r in results:
            assert r["rc"] == 0, f"rank failed:\n{r['stderr'][-3000:]}"
            if r["_parse_err"] is not None:
                raise r["_parse_err"]
    for r in results:
        r.pop("_parse_err", None)
    return results
