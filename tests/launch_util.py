"""Shared multi-process launch harness for engine tests.

One implementation of the free-port / shared-secret / HOROVOD_* env / Popen
world spawner (previously copied per test file — protocol env changes now
land in exactly one place).
"""

from __future__ import annotations

import json
import os
import secrets
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def launch_world(world: int, script: str, extra_env=None, per_rank_env=None,
                 timeout: float = 180, check: bool = True) -> list[dict]:
    """Spawn ``world`` ranks running ``script`` with a shared secret and
    coordinator address. Returns per-rank dicts:
    ``{"rc": int, "out": <last stdout line parsed as JSON> | None,
    "stderr": str}``. With ``check`` (default) a non-zero rank fails the
    test immediately."""
    port = free_port()
    secret = secrets.token_hex(16)
    procs = []
    for rank in range(world):
        env = dict(os.environ)
        env.update({
            "HVD_REPO": REPO,
            "HOROVOD_RANK": str(rank),
            "HOROVOD_SIZE": str(world),
            "HOROVOD_COORD_ADDR": f"127.0.0.1:{port}",
            "HOROVOD_SECRET": secret,
        })
        env.update(extra_env or {})
        env.update((per_rank_env or {}).get(rank, {}))
        procs.append(subprocess.Popen(
            [sys.executable, "-c", script], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        ))
    results = []
    try:
        for p in procs:
            stdout, stderr = p.communicate(timeout=timeout)
            if check:
                assert p.returncode == 0, f"rank failed:\n{stderr[-3000:]}"
            out = stdout.strip().splitlines()
            parsed = None
            if out:
                try:
                    parsed = json.loads(out[-1])
                except ValueError:
                    if check:
                        raise
            results.append({
                "rc": p.returncode,
                "out": parsed,
                "stderr": stderr,
            })
    finally:
        # One hung or failed rank must not leak the others into the rest of
        # the pytest session (they would keep the coordinator port busy).
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()
    return results
