"""Rank-sharded input pipeline — the DistributedSampler contract
(disjoint per-rank coverage, per-epoch reshuffle, equal step counts) and
real file IO through np.memmap (reference real-data recipe,
docs/benchmarks.md:40-63)."""

from __future__ import annotations

import os
import subprocess
import sys

import numpy as np
import pytest

from horovod_tpu.data import (
    DistributedSampler,
    MemmapArrayDataset,
    write_synthetic_shards,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_sampler_partitions_disjoint_and_complete():
    n, size = 103, 4  # non-divisible: tail is padded by wrapping
    per_rank = [list(DistributedSampler(n, rank=r, size=size, shuffle=False))
                for r in range(size)]
    lengths = {len(ix) for ix in per_rank}
    assert lengths == {26}, "all ranks must take the same number of steps"
    flat = [i for ix in per_rank for i in ix]
    assert set(flat) == set(range(n)), "every sample must be covered"
    # only the wrap-pad duplicates: total - n
    assert len(flat) - len(set(flat)) == 26 * size - n


def test_sampler_reshuffles_per_epoch_identically_across_ranks():
    samplers = [DistributedSampler(64, rank=r, size=2, seed=7) for r in (0, 1)]
    first = [s.indices().tolist() for s in samplers]
    assert not set(first[0]) & set(first[1]), "ranks must be disjoint"
    for s in samplers:
        s.set_epoch(1)
    second = [s.indices().tolist() for s in samplers]
    assert first[0] != second[0], "epoch must reshuffle"
    assert not set(second[0]) & set(second[1]), \
        "ranks must stay disjoint after reshuffle (same permutation)"


def test_sampler_batches_drop_ragged_tail():
    s = DistributedSampler(100, rank=0, size=2, shuffle=False)  # 50 idx
    batches = list(s.batches(16))
    assert [len(b) for b in batches] == [16, 16, 16]
    assert [len(b) for b in s.batches(16, drop_last=False)][-1] == 2


def test_memmap_dataset_roundtrip(tmp_path):
    d = write_synthetic_shards(str(tmp_path), 20, (3, 4, 4), 10, seed=1)
    ds = MemmapArrayDataset(d)
    assert len(ds) == 20
    x, y = ds[[3, 7, 7]]
    assert x.shape == (3, 3, 4, 4) and y.shape == (3,)
    assert x.dtype == np.float32 and y.dtype == np.int64
    np.testing.assert_array_equal(ds[[7]][0][0], x[1])
    # memmap: the file is the storage, not RAM
    assert isinstance(ds.images, np.memmap)


def test_sampler_rejects_bad_world():
    with pytest.raises(ValueError, match="outside world"):
        DistributedSampler(10, rank=3, size=2)
    with pytest.raises(ValueError, match="empty dataset"):
        DistributedSampler(0, rank=0, size=1)


@pytest.mark.slow
def test_imagenet_example_trains_from_files(tmp_path):
    """E2e: 2 ranks write + read npy shards from disk through the launcher;
    each rank reads a disjoint half per epoch and training completes."""
    data_dir = str(tmp_path / "shards")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, "-m", "horovod_tpu.runner", "-np", "2", "--",
         sys.executable, "examples/pytorch_imagenet_resnet50.py",
         "--epochs", "2", "--data-dir", data_dir, "--make-data", "128",
         "--batch-size", "16", "--image-size", "8",
         "--checkpoint-dir", str(tmp_path / "ck")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert os.path.exists(os.path.join(data_dir, "images.npy"))
    assert '"epoch": 2' in proc.stdout


def test_device_cache_epoch_contract():
    """DeviceCache.sample visits every shard row exactly once per epoch in a
    seeded order that changes across epochs — the in-jit realization of
    DistributedSampler.set_epoch's reshuffle contract (the device-resident
    pipeline of docs/benchmarks.md 'Real-data input pipeline')."""
    import jax
    import jax.numpy as jnp

    from horovod_tpu.data import DeviceCache

    n, batch = 32, 8
    images = np.arange(n, dtype=np.uint8).reshape(n, 1, 1, 1)
    labels = np.arange(n, dtype=np.int64)
    cache = DeviceCache(images, labels, batch_size=batch, seed=3)

    @jax.jit
    def draw(ctr):
        x, y, ctr = cache.sample(ctr)
        return x, y, ctr

    ctr = cache.counter()
    epochs = []
    for _ in range(2):
        seen = []
        for _ in range(n // batch):
            x, y, ctr = draw(ctr)
            rows = np.asarray(y)
            # x (uint8, normalize) is the same row id scaled: check pairing
            np.testing.assert_allclose(
                np.asarray(x).reshape(batch),
                rows.astype(np.float32) / 127.5 - 1.0, rtol=1e-6)
            seen.extend(rows.tolist())
        assert sorted(seen) == list(range(n))  # exactly once per epoch
        epochs.append(seen)
    assert epochs[0] != epochs[1]  # reshuffled across epochs
    assert int(ctr) == 2 * (n // batch)


def test_device_cache_validation():
    from horovod_tpu.data import DeviceCache

    with pytest.raises(ValueError, match="mismatch"):
        DeviceCache(np.zeros((4, 1)), np.zeros(3), batch_size=2)
    with pytest.raises(ValueError, match="cannot fill"):
        DeviceCache(np.zeros((2, 1)), np.zeros(2), batch_size=4)


def test_scan_train_loop_matches_stepwise():
    """hvd.jax.make_scan_train_loop: K scanned steps per dispatch over a
    DeviceCache must produce the EXACT trajectory of calling the same
    train_step K times with the same cache draws — the scan is a dispatch
    optimization, not a semantic change."""
    import jax
    import jax.numpy as jnp
    import optax

    import horovod_tpu as hvd
    from horovod_tpu.data import DeviceCache

    n, batch, K = 32, 4, 4
    rng = np.random.default_rng(0)
    images = rng.integers(0, 256, (n, 3), dtype=np.uint8)
    labels = (images.sum(axis=1) % 5).astype(np.int64)
    cache = DeviceCache(images, labels, batch_size=batch, seed=7)

    opt = optax.sgd(0.1)
    params = {"w": jnp.zeros((3, 5)), "b": jnp.zeros((5,))}
    state0 = opt.init(params)

    def train_step(p, o, x, y):
        def loss_fn(p):
            logits = x @ p["w"] + p["b"]
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()

        loss, g = jax.value_and_grad(loss_fn)(p)
        up, o = opt.update(g, o, p)
        return optax.apply_updates(p, up), o, loss

    # stepwise oracle (no scan, no donation)
    p_ref, o_ref, ctr = dict(params), state0, cache.counter()
    losses_ref = []
    for _ in range(K):
        x, y, ctr = cache.sample(ctr, cache.data, cache.labels)
        p_ref, o_ref, loss = train_step(p_ref, o_ref, x, y)
        losses_ref.append(float(loss))

    loop = hvd.jax.make_scan_train_loop(train_step, cache,
                                        steps_per_dispatch=K, donate=False)
    p_s, o_s, ctr_s, mean_loss = loop(dict(params), state0, cache.counter(),
                                      cache.data, cache.labels)
    assert int(ctr_s) == K
    np.testing.assert_allclose(float(mean_loss), np.mean(losses_ref),
                               rtol=1e-6)
    for k in params:
        np.testing.assert_allclose(np.asarray(p_s[k]), np.asarray(p_ref[k]),
                                   rtol=1e-6, atol=1e-7)

    # Default donated path: params/opt_state/ctr update in place, and the
    # cache shard must NOT be donated (a second call reuses it).
    loop_d = hvd.jax.make_scan_train_loop(train_step, cache,
                                          steps_per_dispatch=K)
    p_d, o_d, ctr_d, _ = loop_d(
        jax.tree_util.tree_map(lambda t: jnp.array(t, copy=True), dict(params)),
        jax.tree_util.tree_map(lambda t: jnp.array(t, copy=True), state0),
        cache.counter(), cache.data, cache.labels)
    for k in params:
        np.testing.assert_allclose(np.asarray(p_d[k]), np.asarray(p_ref[k]),
                                   rtol=1e-6, atol=1e-7)
    # shard survives donation and a second dispatch continues the epoch
    p_d, o_d, ctr_d, _ = loop_d(p_d, o_d, ctr_d, cache.data, cache.labels)
    assert int(ctr_d) == 2 * K

    with pytest.raises(ValueError, match="steps_per_dispatch"):
        hvd.jax.make_scan_train_loop(train_step, cache, steps_per_dispatch=0)
