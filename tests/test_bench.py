"""bench.py is the driver's measurement surface — its step must build and
run on the virtual mesh in BOTH data-plane shapes (flat hvd axis and the
hierarchical ('dcn','ici') ladder the --autotune branch uses on pods)."""

from __future__ import annotations

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402


@pytest.mark.slow
@pytest.mark.parametrize("hierarchical", [False, True])
def test_bench_build_runs_one_step(hvd, hierarchical, monkeypatch):
    monkeypatch.setenv("HVD_BENCH_BATCH", "1")
    import jax

    step, state, (x, y), batch, n_dev = bench._build(hierarchical=hierarchical)
    # snapshot BEFORE the call: the step donates its inputs
    leaves0 = [np.array(a) for a in jax.tree_util.tree_leaves(state[0])]
    params, batch_stats, opt_state, loss = step(*state, x, y)
    assert np.isfinite(float(loss))
    assert batch == n_dev  # 1 per device
    # the step must actually move parameters (optimizer ran)
    leaves1 = [np.asarray(a) for a in jax.tree_util.tree_leaves(params)]
    assert any(not np.array_equal(a, b) for a, b in zip(leaves0, leaves1))
