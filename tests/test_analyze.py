"""Conformance-analyzer tests (ISSUE 11, tools/analyze/, docs/analysis.md).

Three layers:

- parser units: the wire.h struct/enum extraction and the Python
  dict-shape/env/metric extraction against synthetic sources — the
  analyzer is only as good as these parsers, so they are pinned;
- synthetic drift fixtures: each of the four passes must CATCH its
  divergence class (an extra wire field, a default mismatch, a metric
  missing from the schema, an unlocked shared write) — proving the gate
  can actually fail;
- the live tree: every pass runs green on this repo, and the checked-in
  docs/protocol_spec.json + docs/config_registry.json regenerate
  byte-identically (the CI invariant).
"""

import ast
import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.analyze import common, cpp, knobs, locks, metrics_lint, protocol  # noqa: E402
from tools.analyze import pysrc  # noqa: E402


# ------------------------------------------------------------ parser units

WIRE_FIXTURE = """
// comment with struct Fake { inside } and "struct InString {"
struct Request {
  int32_t rank = 0;
  OpType op = OpType::ALLREDUCE;
  DataType dtype = DataType::F32;  // trailing comment
  std::string name;
  uint8_t average = 1;
  std::vector<int64_t> shape;
  int64_t scratch_only = 0;  // never serialized

  size_t elements() const {
    size_t n = 1;
    for (auto d : shape) n *= (size_t)d;
    return n;
  }

  void write(Writer& w) const {
    w.i32(rank);
    w.u8((uint8_t)op);
    w.u8((uint8_t)dtype);
    w.str(name);
    w.u8(average);
    w.u8((uint8_t)shape.size());
    for (auto d : shape) w.i64(d);
  }
};

struct Plain {
  uint8_t kind = 0;
  std::vector<uint8_t> data;
};
"""


def test_wire_struct_extraction():
    structs = cpp.parse_structs(WIRE_FIXTURE)
    req = structs["Request"]
    assert req.member_names() == [
        "rank", "op", "dtype", "name", "average", "shape", "scratch_only"]
    # wire order comes from write(), not declaration order
    assert req.wire_order == ["rank", "op", "dtype", "name", "average",
                              "shape"]
    assert req.scratch_members() == ["scratch_only"]
    assert req.has_write
    # a struct without write() is local-only: no wire order
    assert structs["Plain"].wire_order == []
    assert not structs["Plain"].has_write
    # comments never leak struct names
    assert "Fake" not in structs and "InString" not in structs


def test_enum_extraction_explicit_and_implicit():
    enums = cpp.parse_enums("""
        enum class DataType : uint8_t { U8 = 0, I8, F32 = 6, F64 };
        enum class OpType { ALLREDUCE, ALLGATHER };
    """)
    assert enums["DataType"] == {"U8": 0, "I8": 1, "F32": 6, "F64": 7}
    assert enums["OpType"] == {"ALLREDUCE": 0, "ALLGATHER": 1}


def test_cpp_getenv_default_idioms():
    src = """
    inline size_t cap_from_env() {
      const char* v = std::getenv("HOROVOD_FIXTURE_CAP");
      if (!v || !*v) return 1024;
      long n = std::strtol(v, nullptr, 10);
      return n > 0 ? (size_t)n : 0;   // clamp, NOT the default
    }
    inline uint64_t bytes_from_env() {
      const char* env = std::getenv("HOROVOD_FIXTURE_BYTES");
      uint64_t v = env ? std::strtoull(env, nullptr, 10) : (16u << 20);
      return v;
    }
    void opaque() { const char* t = std::getenv("HOROVOD_FIXTURE_OPAQUE"); use(t); }
    """
    reads = {r.knob: r for r in cpp.find_getenv(src, "fixture.h")}
    assert reads["HOROVOD_FIXTURE_CAP"].default == 1024      # guard-return
    assert reads["HOROVOD_FIXTURE_BYTES"].default == 16 << 20  # env-ternary
    assert not reads["HOROVOD_FIXTURE_OPAQUE"].default_known


def test_cache_key_field_extraction():
    fields = cpp.cache_key_fields("""
        inline std::string cache_key(const Request& q) {
          std::string k = q.name;
          k.push_back((char)q.op);
          k.append(std::to_string(q.root_rank));
          for (int64_t d : q.shape) k.append(std::to_string(d));
          return k;
        }
    """)
    assert fields == ["name", "op", "root_rank", "shape"]


def test_py_dict_shape_extraction():
    mod = ast.parse(textwrap.dedent("""
        def build(self, e):
            req = {"name": e["name"], "op": e["op"], "shape": (1,),
                   "dtype": "f4", "root": 0, "average": True}
            if e.get("wire"):
                req["wire"] = str(e["wire"])
            return req
    """))
    shape = pysrc.find_dict_shape(
        mod, {"name", "op", "shape", "dtype", "root", "average"})
    assert shape.base_keys == ["name", "op", "shape", "dtype", "root",
                               "average"]
    assert shape.optional_keys == ["wire"]


def test_py_env_read_extraction():
    mod = ast.parse(textwrap.dedent('''
        import os
        DEFAULT_CAP = 16 << 20

        def f():
            """Docstring naming HOROVOD_FIXTURE_DOCONLY is not a read."""
            a = os.environ.get("HOROVOD_FIXTURE_A", "8")
            b = _env_int("HOROVOD_FIXTURE_B", DEFAULT_CAP)
            c = _env_bool("HOROVOD_FIXTURE_C")
            os.environ["HOROVOD_FIXTURE_W"] = "1"
            table = {"x": "HOROVOD_FIXTURE_INDIRECT"}
            return a, b, c, table
    '''))
    reads, writes = pysrc.find_env_reads(mod, "fixture.py")
    by = {r.knob: r for r in reads}
    assert common.normalize_default(by["HOROVOD_FIXTURE_A"].default) == 8
    assert by["HOROVOD_FIXTURE_B"].default == 16 << 20  # const-folded Name
    assert by["HOROVOD_FIXTURE_C"].default is False     # _env_bool implicit
    assert by["HOROVOD_FIXTURE_INDIRECT"].indirect
    assert "HOROVOD_FIXTURE_DOCONLY" not in by
    assert [w[0] for w in writes] == ["HOROVOD_FIXTURE_W"]


def test_py_metric_emission_extraction():
    mod = ast.parse(textwrap.dedent('''
        NATIVE_METRICS = ("alpha", "beta")

        def f(reg, name):
            reg.counter("horovod_fixture_total", help="h", op=op).inc()
            _counter("horovod_fixture_wrapped_total", "help text")
            reg.gauge(f"horovod_native_{name}").set(1)
    '''))
    ems, dynamic = pysrc.find_metric_emissions(mod, "fixture.py")
    assert ("horovod_fixture_total", "counter", frozenset({"op"})) in [
        (e.name, e.kind, e.labels) for e in ems]
    # helper wrappers whose NAME contains counter/gauge/histogram count too
    assert any(e.name == "horovod_fixture_wrapped_total" for e in ems)
    assert [(d[0], d[1]) for d in dynamic] == [("horovod_native_", "gauge")]
    expanded = pysrc.expand_dynamic(mod, "fixture.py", "horovod_native_",
                                    "gauge", dynamic[0][2], "NATIVE_METRICS")
    assert [e.name for e in expanded] == ["horovod_native_alpha",
                                          "horovod_native_beta"]


def test_suppressions_parse_and_reject():
    entries = common.parse_suppressions(textwrap.dedent('''
        # comment
        [[suppress]]
        key = "locks:unlocked-write:a.py:C.m:_x"
        reason = "single-writer flag, readers tolerate staleness"
    '''))
    assert entries[0].key == "locks:unlocked-write:a.py:C.m:_x"
    with pytest.raises(common.SuppressionError):
        common.parse_suppressions('[[suppress]]\nkey = "k"\n')  # no reason
    with pytest.raises(common.SuppressionError):
        common.parse_suppressions('key = "orphan"\n')  # outside a table


# --------------------------------------------------- drift fixtures (fail!)

def _live_spec():
    return protocol.extract(REPO)


def test_protocol_drift_native_field_is_caught():
    spec = _live_spec()
    spec["native"]["messages"]["Request"]["wire_order"].append("priority")
    found = protocol.check(REPO, spec)
    assert any(f.code == "unmapped-native-field"
               and "priority" in f.key for f in found)


def test_protocol_drift_python_field_is_caught():
    spec = _live_spec()
    spec["python"]["request_optional_fields"].append("priority")
    found = protocol.check(REPO, spec)
    assert any(f.code == "unmapped-python-field"
               and "priority" in f.key for f in found)


def test_protocol_drift_op_id_is_caught():
    spec = _live_spec()
    spec["python"]["ops"]["allreduce"] = 3  # ctypes table flip
    found = protocol.check(REPO, spec)
    assert any(f.code == "op-id-mismatch" for f in found)


def test_protocol_drift_dtype_order_is_caught():
    spec = _live_spec()
    d = spec["python"]["dtypes"]
    d[0], d[1] = d[1], d[0]
    found = protocol.check(REPO, spec)
    assert any(f.code == "dtype-id-mismatch" for f in found)


def test_knob_drift_is_caught():
    ex = knobs.extract(REPO)
    # undocumented knob
    ex["knobs"]["HOROVOD_FIXTURE_NEW"] = {
        "python": {"files": ["x.py"], "default": 1}, "documented": False}
    # cross-engine default mismatch
    ex["knobs"]["HOROVOD_FIXTURE_SPLIT"] = {
        "python": {"files": ["x.py"], "default": 5},
        "native": {"files": ["y.h"], "default": 7}, "documented": True}
    # conflicting python defaults
    ex["knobs"]["HOROVOD_FIXTURE_TWICE"] = {
        "python": {"files": ["x.py", "z.py"], "defaults": [1, 2]},
        "documented": True}
    # documented-but-dead
    ex["doc_mentions"] = set(ex["doc_mentions"]) | {"HOROVOD_FIXTURE_GONE"}
    codes = {f.code for f in knobs.check(REPO, ex)
             if "FIXTURE" in f.key}
    assert codes == {"undocumented", "cross-default-mismatch",
                     "py-default-conflict", "documented-dead"}


def test_metric_drift_is_caught():
    ex = metrics_lint.extract(REPO)
    ex["emissions"].append(pysrc.MetricEmission(
        "horovod_fixture_rogue_total", "counter", frozenset(), "x.py", 1))
    ex["schema"][("horovod_fixture_orphan_total", frozenset())] = (
        "counter", "fixture_counters", "horovod_fixture_orphan_total")
    found = metrics_lint.check(REPO, ex)
    assert any(f.code == "code-not-in-schema" and "rogue" in f.key
               for f in found)
    assert any(f.code == "schema-orphan" and "orphan" in f.key
               for f in found)


def test_metric_kind_mismatch_is_caught():
    ex = metrics_lint.extract(REPO)
    key = ("horovod_elastic_resets_total", frozenset())
    assert key in ex["schema"]
    ex["emissions"] = [pysrc.MetricEmission(key[0], "gauge", key[1],
                                            "x.py", 1)]
    ex["schema"] = {key: ex["schema"][key]}
    found = metrics_lint.check(REPO, ex)
    assert any(f.code == "kind-mismatch" for f in found)


LOCK_RACE_FIXTURE = textwrap.dedent("""
    import threading

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self._count = 0
            self._items = []
            self._thread = threading.Thread(target=self._loop, daemon=True)

        def _loop(self):
            while True:
                with self._lock:
                    self._count += 1
                    self._items.append(self._count)

        def reset(self):
            self._count = 0          # RACE: unlocked write to guarded attr

        def push_unlocked(self, x):
            self._items.append(x)    # RACE: unlocked container mutation

        def drain(self):
            with self._lock:
                out = list(self._items)
                self._items.clear()
            return out

        def _helper(self):
            self._count += 1         # held: only ever called under lock

        def tick(self):
            with self._lock:
                self._helper()
""")


def test_lock_lint_catches_known_race_and_exempts_held_helpers():
    found = locks.check_module(ast.parse(LOCK_RACE_FIXTURE), "fixture.py")
    idents = {f.key for f in found}
    assert "locks:unlocked-write:fixture.py:Worker.reset:_count" in idents
    assert ("locks:unlocked-write:fixture.py:Worker.push_unlocked:_items"
            in idents)
    # __init__ writes and the callers-hold-lock helper are NOT findings
    assert len(found) == 2


def test_lock_lint_ignores_unthreaded_classes():
    src = LOCK_RACE_FIXTURE.replace(
        "self._thread = threading.Thread(target=self._loop, daemon=True)",
        "self._thread = None")
    assert locks.check_module(ast.parse(src), "fixture.py") == []


# ------------------------------------------------- e2e drift fixture tree

def test_protocol_extraction_failure_is_loud(tmp_path):
    """A fixture tree whose anchors do not match must produce
    extraction-failed findings, never a silent pass."""
    root = tmp_path
    (root / "horovod_tpu" / "cc" / "src").mkdir(parents=True)
    (root / "horovod_tpu" / "common").mkdir(parents=True)
    (root / "docs").mkdir()
    for rel in (protocol.WIRE_H, protocol.COMMON_H, protocol.CACHE_H,
                protocol.ENGINE_PY, protocol.RESPONSE_CACHE_PY,
                protocol.NATIVE_ENGINE_PY):
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text("# nothing the anchors can match\n")
    found = protocol.check(str(root))
    assert found and all(f.code == "extraction-failed" for f in found)


# ----------------------------------------------------- live-tree invariants

def test_live_tree_protocol_green():
    assert protocol.check(REPO) == []


def test_live_tree_knobs_green():
    assert knobs.check(REPO) == []


def test_live_tree_metrics_green():
    assert metrics_lint.check(REPO) == []


def test_live_tree_locks_green_or_suppressed():
    live, _, _ = common.apply_suppressions(
        locks.check(REPO), common.load_suppressions(REPO))
    assert live == []


def test_spec_files_regenerate_byte_identical():
    assert protocol.check_spec_file(REPO) == []
    assert knobs.check_registry_file(REPO) == []
    # and the renders themselves are deterministic
    assert protocol.render(protocol.extract(REPO)) == \
        protocol.render(protocol.extract(REPO))


def test_spec_file_staleness_is_caught():
    spec = protocol.extract(REPO)
    spec["version"] = 2  # any content change
    found = protocol.check_spec_file(REPO, spec)
    assert found and found[0].code == "stale"


def test_unused_suppression_detection():
    live, supp, unused = common.apply_suppressions(
        [common.make_finding("locks", "unlocked-write", "a.py:C.m:_x", "m")],
        [common.Suppression("locks:unlocked-write:a.py:C.m:_x", "ok"),
         common.Suppression("locks:unlocked-write:gone", "stale")])
    assert live == [] and len(supp) == 1
    assert [s.key for s in unused] == ["locks:unlocked-write:gone"]


def test_cli_check_exits_zero_on_tree():
    r = subprocess.run(
        [sys.executable, "-m", "tools.analyze", "--check"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "OK" in r.stderr
