# horovod_tpu on a TPU host (reference Dockerfile + build-docker-images.sh,
# re-targeted: no CUDA/NCCL/OpenMPI layers — the TPU runtime is the libtpu
# wheel, the host runtime is the in-repo C++ core built at image build).
#
#   docker build -t horovod-tpu .
#   docker run --privileged --network host horovod-tpu \
#       python examples/jax_mnist.py
#
# --privileged + host networking are the standard TPU-VM container settings
# (device access via /dev/vfio, ICI/DCN via the host stack). One container
# per host; start `hvd-agent` in it for multi-host `hvdrun -H` jobs
# (docs/running.md).

FROM python:3.12-slim-bookworm

# Native toolchain for the C++ host runtime (cc/Makefile).
RUN apt-get update && apt-get install -y --no-install-recommends \
        g++ make \
    && rm -rf /var/lib/apt/lists/*

# TPU-enabled jax; pin versions in production images.
RUN pip install --no-cache-dir "jax[tpu]" \
        -f https://storage.googleapis.com/jax-releases/libtpu_releases.html \
    && pip install --no-cache-dir flax optax numpy pytest

WORKDIR /opt/horovod_tpu
COPY . .

# Build the native core at image build (setup.py BuildWithNative), then
# install the package; the smoke test proves the ctypes bridge loads.
RUN pip install --no-cache-dir . \
    && python -c "import horovod_tpu as hvd; hvd.init(); \
                  assert hvd.size() >= 1; print('horovod_tpu ok')"

# Agent port for multi-host launches (hvdrun -H host1:8,host2:8).
EXPOSE 9009

CMD ["python", "-c", "import horovod_tpu as hvd; hvd.init(); print(hvd.rank(), hvd.size())"]
