"""JAX version compatibility shims.

One import site for APIs that moved between JAX releases, so the other ~20
modules (library, tests, examples, bench) never spell a version check
themselves.

``shard_map``: promoted from ``jax.experimental.shard_map`` to ``jax.shard_map``
around jax 0.6, and the replication-checking kwarg was renamed
``check_rep`` -> ``check_vma`` in the same move. Callers here write the
NEW spelling (``jax.shard_map`` signature with ``check_vma=``); on older
JAX the wrapper translates the kwarg and dispatches to the experimental
entry point.
"""

from __future__ import annotations

import jax as _jax

if hasattr(_jax, "shard_map"):
    shard_map = _jax.shard_map
    HAS_NATIVE_SHARD_MAP = True
else:  # jax < 0.6: experimental module, check_rep spelling
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    HAS_NATIVE_SHARD_MAP = False

    def shard_map(f, mesh=None, in_specs=None, out_specs=None, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _experimental_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs)


if hasattr(_jax.lax, "axis_size"):
    axis_size = _jax.lax.axis_size
else:  # jax < 0.6: psum of the literal 1 over the axis — constant-folded to
    # the axis size inside a trace, and raises the same NameError outside
    # one, so callers' error handling is identical on both spellings.

    def axis_size(axis_name):
        return _jax.lax.psum(1, axis_name)


def set_num_cpu_devices(n: int) -> None:
    """Request ``n`` virtual CPU devices BEFORE backend init.

    Newer jax has the ``jax_num_cpu_devices`` config; older releases only
    honor the ``--xla_force_host_platform_device_count`` XLA flag. Raises
    RuntimeError (like the config path) if a backend is already up, so
    callers' error handling stays one code path."""
    try:
        _jax.config.update("jax_num_cpu_devices", n)
        return
    except AttributeError:  # jax < 0.5: no such config option
        pass
    try:
        from jax._src import xla_bridge

        if xla_bridge.backends_are_initialized():
            raise RuntimeError(
                "jax backend already initialized; set device count earlier")
    except (ImportError, AttributeError):  # pragma: no cover - private API
        pass
    import os
    import re

    flags = os.environ.get("XLA_FLAGS", "")
    if re.search(r"xla_force_host_platform_device_count=\d+", flags):
        flags = re.sub(r"xla_force_host_platform_device_count=\d+",
                       f"xla_force_host_platform_device_count={n}", flags)
    else:
        flags = (flags + f" --xla_force_host_platform_device_count={n}").strip()
    os.environ["XLA_FLAGS"] = flags


def distributed_is_initialized() -> bool:
    """``jax.distributed.is_initialized()`` appeared after 0.4.x; older
    releases expose the same fact as a non-None client on the distributed
    global state."""
    if hasattr(_jax.distributed, "is_initialized"):
        return bool(_jax.distributed.is_initialized())
    try:
        from jax._src.distributed import global_state

        return global_state.client is not None
    except (ImportError, AttributeError):  # pragma: no cover - future move
        return False


__all__ = ["shard_map", "axis_size", "distributed_is_initialized",
           "set_num_cpu_devices", "HAS_NATIVE_SHARD_MAP"]
