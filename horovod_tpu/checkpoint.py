"""Checkpoint / resume helpers — the rank-0-writes + broadcast-on-restore
contract (SURVEY.md §5.4).

The reference delegates serialization to the framework and supplies the
consistency pieces: save only on rank 0 (reference README.md:117-119),
restore everywhere and re-broadcast (BroadcastGlobalVariablesHook,
hvd.broadcast_parameters / broadcast_optimizer_state, resume-epoch broadcast
in examples/pytorch_imagenet_resnet50.py). Here serialization is orbax (the
JAX checkpoint library), and the same contract is packaged as two calls:

    hvd.checkpoint.save(path, {"params": params, "opt_state": opt_state,
                               "epoch": epoch})          # writes on rank 0
    state = hvd.checkpoint.restore(path)                 # every rank reads;
    # restore() allgathers a digest of the restored leaves and fails loudly
    # if any rank read divergent state. Alternative on non-shared
    # filesystems: restore(path, verify=False) on rank 0 only, then
    # hvd.jax.broadcast_parameters / broadcast_resume_state.
"""

from __future__ import annotations

import os
from typing import Any, Optional

from .common import basics


def _ocp():
    import orbax.checkpoint as ocp

    return ocp


# -- crash-consistent commits (ISSUE 8) --------------------------------------
#
# The elastic ladder's restore point is only as good as its worst write: a
# worker killed mid-commit (exactly the fault the escalation ladder and the
# chaos harness exercise) must never leave a half-written directory where the
# last good checkpoint stood. So every save stages into a sibling temp
# directory, fsyncs it, marks it complete (a sibling ``.ok`` file, written
# after the data is durable), and swaps it into place with renames — the only
# atomic primitive POSIX gives us for directories. Every crash window leaves
# either the old checkpoint, or the new one, or a complete staged copy that
# the next save()/restore() adopts (_heal_interrupted).


def _fsync_tree(path: str) -> None:
    """Best-effort fsync of every file and directory under ``path`` — the
    rename below publishes the commit, so the data must be durable first.
    Filesystems that reject directory fsync (some network mounts) are
    tolerated: the rename ordering still bounds the damage to 'old or new'."""
    for root, dirs, files in os.walk(path, topdown=False):
        for name in files + [os.curdir]:
            try:
                fd = os.open(os.path.join(root, name) if name != os.curdir
                             else root, os.O_RDONLY)
            except OSError:
                continue
            try:
                os.fsync(fd)
            except OSError:
                pass
            finally:
                os.close(fd)


def _test_pause(point: str) -> None:
    """Deterministic kill window for the crash-consistency tests: when
    ``HOROVOD_CKPT_TEST_STALL`` names this pipeline point (``stage`` —
    staged copy exists but carries no ``.ok`` yet; ``rename`` — between
    the swap's two renames, the brief no-target window), the commit holds
    for ``HOROVOD_CKPT_TEST_STALL_S`` so the test can SIGKILL the writer
    exactly there. No-op unless explicitly armed."""
    if os.environ.get("HOROVOD_CKPT_TEST_STALL", "") == point:
        import time

        time.sleep(float(os.environ.get("HOROVOD_CKPT_TEST_STALL_S", "30")))


def _heal_interrupted(target: str) -> None:
    """Adopt or discard leftovers of an interrupted commit next to
    ``target``: a complete staged copy (``.tmp.* + .ok``) replaces a missing
    target (the crash hit between the two swap renames); incomplete stages
    and displaced old checkpoints (``.trash.*``) are deleted. Races between
    ranks healing a shared filesystem are benign — every rename is wrapped,
    and whoever wins leaves a valid target."""
    import shutil

    parent, base = os.path.split(target)
    try:
        names = os.listdir(parent or os.curdir)
    except OSError:
        return
    stale: list[str] = []
    for n in sorted(names):
        p = os.path.join(parent, n)
        if n.startswith(base + ".tmp.") and not n.endswith(".ok"):
            if os.path.exists(p + ".ok") and not os.path.exists(target):
                try:
                    os.rename(p, target)
                    os.unlink(p + ".ok")
                    continue
                except OSError:  # another rank adopted first
                    pass
            stale.append(p)
        elif n.startswith(base + ".trash."):
            stale.append(p)
    for p in stale:
        shutil.rmtree(p, ignore_errors=True)
        try:
            os.unlink(p + ".ok")
        except OSError:
            pass


def _swap_into_place(tmp: str, target: str) -> None:
    """Atomic publish: mark the staged copy complete, move any existing
    checkpoint aside, rename the stage in, then clean up. A kill at ANY
    point leaves a restorable state (the ``.ok`` marker makes the stage
    adoptable during the brief no-target window)."""
    import shutil

    ok = tmp + ".ok"
    with open(ok, "w") as f:
        f.write("complete\n")
        f.flush()
        os.fsync(f.fileno())
    trash = f"{target}.trash.{os.path.basename(tmp).rsplit('.', 1)[-1]}"
    if os.path.exists(target):
        os.rename(target, trash)
    _test_pause("rename")
    os.rename(tmp, target)
    try:  # publish the renames before declaring the commit durable
        fd = os.open(os.path.dirname(target) or os.curdir, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:
        pass
    try:
        os.unlink(ok)
    except OSError:
        pass
    shutil.rmtree(trash, ignore_errors=True)


def save_local(path: str, state: Any, step: Optional[int] = None) -> None:
    """The single-writer commit pipeline — stage, fsync, ``.ok``, atomic
    rename — with NO rank gate and NO completion barrier. This is the core
    :func:`save` wraps, and what the background writer
    (:class:`horovod_tpu.ckpt_async.AsyncCheckpointer`) runs off the step
    path: collectives may only run on the training thread, so the async
    writer must use the barrier-free form."""
    import numpy as np

    import jax

    ocp = _ocp()
    ckptr = ocp.StandardCheckpointer()
    target = os.path.join(os.path.abspath(path), f"step_{step}") \
        if step is not None else os.path.abspath(path)
    # numpy SCALARS (np.int64(7) epoch counters and friends) are not
    # ndarrays, and orbax's StandardCheckpointHandler rejects them on
    # some versions ("Unsupported type: <class 'numpy.int64'>") — lift
    # them to 0-d arrays, which restore round-trips (int() on a 0-d
    # array works) and every orbax accepts.
    state = jax.tree_util.tree_map(
        lambda x: np.asarray(x) if isinstance(x, np.generic) else x,
        state)
    # Crash-consistent commit (ISSUE 8): stage next to the target, make
    # it durable, then swap with atomic renames — a worker killed
    # mid-commit can never corrupt the restore point the elastic ladder
    # depends on. Also adopts/cleans leftovers of a previous kill.
    _heal_interrupted(target)
    os.makedirs(os.path.dirname(target) or os.curdir, exist_ok=True)
    tmp = f"{target}.tmp.{os.getpid()}"
    ckptr.save(tmp, state, force=True)
    ckptr.wait_until_finished()
    _test_pause("stage")
    _fsync_tree(tmp)
    _swap_into_place(tmp, target)


def save(path: str, state: Any, step: Optional[int] = None, force: bool = True) -> None:
    """Write a checkpoint from rank 0 only; other ranks return immediately
    (reference contract: 'save checkpoints only on worker 0 to prevent other
    workers from corrupting them', README.md:117-119). A marker barrier via
    the eager engine keeps ranks from racing ahead of an unfinished save."""
    import numpy as np

    # Uninitialized == single-process (a plain post-training export script);
    # rank 0 writes, and only a multi-rank world needs the barrier.
    if not basics.is_initialized() or basics.rank() == 0:
        save_local(path, state, step)
    if basics.is_initialized() and basics.size() > 1:
        # barrier: everyone waits until rank 0's save completed
        basics.engine().run("allreduce", np.zeros(1), f"ckpt.barrier.{path}.{step}")


def restore(path: str, template: Any = None, step: Optional[int] = None,
            verify: bool = True) -> Any:
    """Read a checkpoint on every rank (all ranks share the filesystem on a
    pod slice). ``template`` gives dtypes/shapes for orbax.

    With ``verify=True`` (default) every rank hashes the restored leaves and
    the digests are allgathered and compared, so ranks that read divergent
    files (stale NFS caches, non-shared filesystems) fail loudly instead of
    training from inconsistent state. The check is collective: it requires
    every rank to call restore(). If you instead restore on rank 0 only and
    broadcast (hvd.jax.broadcast_parameters / broadcast_resume_state), pass
    ``verify=False`` — the broadcast itself is the consistency guarantee."""
    ocp = _ocp()
    ckptr = ocp.StandardCheckpointer()
    target = os.path.join(os.path.abspath(path), f"step_{step}") \
        if step is not None else os.path.abspath(path)
    if not os.path.exists(target):
        # The writer may have been killed between the commit's two renames:
        # adopt a complete staged copy if one is waiting (crash-consistent
        # commits, ISSUE 8).
        _heal_interrupted(target)
    state = ckptr.restore(target, template) if template is not None \
        else ckptr.restore(target)
    if verify:
        _verify_cross_rank_digest(state, f"{path}.{step}")
    return state


def _verify_cross_rank_digest(state: Any, tag: str) -> None:
    """SHA-256 over every restored leaf (dtype + shape + bytes), allgathered
    through the eager engine; raises if any rank restored different state.
    Uninitialized == single-process (the same plain-export convention as
    save()): there is no peer to diverge from, so nothing to verify."""
    if not basics.is_initialized() or basics.size() == 1:
        return
    import hashlib

    import jax
    import numpy as np

    h = hashlib.sha256()
    for leaf in jax.tree_util.tree_leaves(state):
        arr = np.asarray(leaf)
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    mine = np.frombuffer(h.digest(), dtype=np.uint8).astype(np.float64)
    # Bounded: the check is collective, and a caller that restores on rank 0
    # only (the verify=False flow) must get an actionable error, not a hang.
    eng = basics.engine()
    handle = eng.enqueue("allgather", mine, f"ckpt.digest.{tag}")
    timeout = float(os.environ.get("HOROVOD_CKPT_VERIFY_TIMEOUT", "120"))
    try:
        gathered = np.asarray(eng.synchronize(handle, timeout=timeout))
    except Exception as exc:
        from .common.engine import HorovodInternalError

        raise HorovodInternalError(
            f"checkpoint digest verification did not complete within "
            f"{timeout:.0f}s — restore(verify=True) is collective and every "
            f"rank must call it; if you restore on rank 0 only and "
            f"broadcast, pass verify=False"
        ) from exc
    gathered = gathered.reshape(basics.size(), mine.size)
    bad = [r for r in range(basics.size())
           if not np.array_equal(gathered[r], gathered[0])]
    if bad:
        from .common.engine import HorovodInternalError

        raise HorovodInternalError(
            f"checkpoint restore diverged across ranks: ranks {bad} read "
            f"different state than rank 0 (non-shared or stale filesystem?); "
            f"restore on rank 0 only and broadcast, or fix the filesystem"
        )


def save_sharded(path: str, state: Any, plan, step: Optional[int] = None) -> None:
    """Checkpoint a SHARDED training state (ISSUE 14, docs/sharded.md).

    ``state`` is any pytree whose sharded sub-states are
    :class:`horovod_tpu.parallel.sharded.ShardedBuckets` (params, optimizer
    moments — whatever ``optimizer.init`` produced); ``plan`` is the
    :class:`ShardPlan` they were partitioned with. The checkpoint stores
    the CONSOLIDATED full leaves, so it is mesh-shape independent: restore
    onto any ('batch','shard') shape, including plain DP. Consolidation
    also drops the zero-pad tail — pad garbage can never be carried in a
    checkpoint (the fsdp pad-leak fix's checkpoint half). Rank-0-writes +
    completion barrier, exactly like :func:`save`."""
    from .parallel import sharded as _sharded

    save(path, _sharded.unshard_tree(state, plan), step)


def restore_sharded(path: str, template: Any, plan,
                    step: Optional[int] = None, verify: bool = True) -> Any:
    """Restore a checkpoint written by :func:`save_sharded` (or a plain DP
    :func:`save` of the same pytree) INTO a sharded layout: the full leaves
    are read with the consolidated template, then re-partitioned to
    ``plan`` with fresh zero padding. ``template`` is the live sharded
    state (it locates every :class:`ShardedBuckets` position); ``plan``
    may differ from the one the checkpoint was written under — that is
    what makes resume-after-reshape work. Same cross-rank digest
    verification contract as :func:`restore`."""
    from .parallel import sharded as _sharded

    full = restore(path, _sharded.unshard_tree(template, plan), step,
                   verify=verify)
    out = _sharded.reshard_tree(full, template, plan)
    # Re-place every restored leaf on the template leaf's sharding: a
    # restored host array left on the default device would make the next
    # jitted step compile a second executable (different input placement),
    # and two executables are allowed to differ by an ULP — which would
    # break the save->restore->resume bitwise-exactness contract the tests
    # pin. With matching shardings the resumed step reuses the SAME
    # compiled program as the uncheckpointed run.
    import jax

    def _place(t, r):
        if isinstance(t, jax.Array) and not isinstance(t, jax.core.Tracer):
            try:
                return jax.device_put(r, t.sharding)
            except (ValueError, AttributeError):
                return r
        return r

    return jax.tree_util.tree_map(_place, template, out)


def merge_stacked_stats(stats: Any, axis: int = 0) -> Any:
    """Consolidate per-device batch statistics that carry a leading device
    dimension (the single-process sharded layout: bench.py keeps one BN-stat
    row per mesh position) into single-replica values by averaging over
    ``axis``. Pure function — usable inside or outside jit."""
    import jax
    import jax.numpy as jnp

    return jax.tree_util.tree_map(lambda t: jnp.mean(t, axis=axis), stats)


def average_stats_across_ranks(stats: Any) -> Any:
    """Consolidate per-PROCESS batch statistics (the multi-process eager
    layout: each rank tracked its own BN running stats, reference-style) by
    averaging through the eager engine. Collective: every rank must call."""
    import numpy as np

    if _world_size() == 1:
        return stats
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(stats)
    # Enqueue everything, then synchronize: the leaves pipeline through the
    # engine's fusion machinery in one pass instead of paying one collective
    # round trip per BN layer (same pattern as _verify_cross_rank_digest).
    eng = basics.engine()
    arrs = [np.asarray(leaf) for leaf in leaves]
    handles = [eng.enqueue("allreduce", a.astype(np.float64),
                           f"export.stats.{i}", average=True)
               for i, a in enumerate(arrs)]
    out = [np.asarray(eng.synchronize(h)).reshape(a.shape).astype(a.dtype)
           for h, a in zip(handles, arrs)]
    return jax.tree_util.tree_unflatten(treedef, out)


def export_for_inference(path: str, state: Any, *,
                         drop: tuple = ("opt_state",),
                         stats_key: str = "batch_stats",
                         stacked_stats_axis: Optional[int] = None,
                         cross_rank: Optional[bool] = None) -> Any:
    """Strip the distributed machinery from a training state and write a
    single-replica serving checkpoint (the reference's optimize-for-inference
    step, /root/reference/docs/inference.md:1-16 — there a TF graph pass that
    removes HorovodAllreduce ops; here the training-only state).

    - ``drop``: top-level keys removed (optimizer state, step counters you
      don't serve with).
    - ``stats_key``: per-rank/per-device batch statistics to consolidate.
      With ``stacked_stats_axis`` the leaves carry a leading device dim and
      are averaged over it (single-process sharded layout); with
      ``cross_rank`` (default: whenever the world is larger than one) each
      process's stats are averaged through the eager engine (collective —
      every rank must call export_for_inference).
    - Writes on rank 0 only, with the same completion barrier as
      :func:`save`; returns the serving state on every rank.

    The result restores with :func:`load_for_inference` in a process that
    never imports the distributed pieces, let alone calls ``hvd.init()``.
    """
    if not isinstance(state, dict):
        raise TypeError(f"state must be a dict of top-level keys, got {type(state)}")
    serving = {k: v for k, v in state.items() if k not in set(drop)}
    if stats_key in serving:
        stats = serving[stats_key]
        if stacked_stats_axis is not None:
            stats = merge_stacked_stats(stats, axis=stacked_stats_axis)
        if cross_rank if cross_rank is not None else _world_size() > 1:
            stats = average_stats_across_ranks(stats)
        serving[stats_key] = stats
    save(path, serving)
    return serving


def _world_size() -> int:
    return basics.size() if basics.is_initialized() else 1


def load_for_inference(path: str, template: Any = None) -> Any:
    """Restore a serving checkpoint written by :func:`export_for_inference`.
    Standalone by design: no ``hvd.init()``, no collectives, no engine — a
    fresh serving process restores and runs a plain single-replica forward
    (the property the reference's inference doc is about: the serving side
    must not need the Horovod library's ops)."""
    ocp = _ocp()
    ckptr = ocp.StandardCheckpointer()
    target = os.path.abspath(path)
    return ckptr.restore(target, template) if template is not None \
        else ckptr.restore(target)


def latest_step(path: str) -> Optional[int]:
    """Highest step_N subdirectory under ``path`` (resume-epoch discovery,
    reference examples/pytorch_imagenet_resnet50.py scans for existing
    checkpoint files the same way)."""
    try:
        steps = [int(d.split("_", 1)[1]) for d in os.listdir(path)
                 if d.startswith("step_") and d.split("_", 1)[1].isdigit()]
    except OSError:
        return None
    return max(steps) if steps else None


def broadcast_resume_state(state: Any, root_rank: int = 0) -> Any:
    """Host-side broadcast of restored state (epoch counters, small pytrees)
    through the eager engine — for values needed OUTSIDE jit (the in-jit
    path is hvd.jax.broadcast_parameters)."""
    import numpy as np

    if basics.size() == 1:
        return state
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(state)
    out = []
    for i, leaf in enumerate(leaves):
        arr = np.asarray(leaf)
        res = basics.engine().run("broadcast", arr, f"ckpt.resume.{i}",
                                  root_rank=root_rank)
        out.append(np.asarray(res).reshape(arr.shape).astype(arr.dtype)
                   if arr.shape else type(leaf)(res))
    return jax.tree_util.tree_unflatten(treedef, out)
