"""Checkpoint / resume helpers — the rank-0-writes + broadcast-on-restore
contract (SURVEY.md §5.4).

The reference delegates serialization to the framework and supplies the
consistency pieces: save only on rank 0 (reference README.md:117-119),
restore everywhere and re-broadcast (BroadcastGlobalVariablesHook,
hvd.broadcast_parameters / broadcast_optimizer_state, resume-epoch broadcast
in examples/pytorch_imagenet_resnet50.py). Here serialization is orbax (the
JAX checkpoint library), and the same contract is packaged as two calls:

    hvd.checkpoint.save(path, {"params": params, "opt_state": opt_state,
                               "epoch": epoch})          # writes on rank 0
    state = hvd.checkpoint.restore(path)                 # every rank reads
    params = hvd.jax.broadcast_parameters(state["params"])   # in-SPMD, or
    # rely on identical files: restore() verifies a cross-rank digest.
"""

from __future__ import annotations

import os
from typing import Any, Optional

from .common import basics


def _ocp():
    import orbax.checkpoint as ocp

    return ocp


def save(path: str, state: Any, step: Optional[int] = None, force: bool = True) -> None:
    """Write a checkpoint from rank 0 only; other ranks return immediately
    (reference contract: 'save checkpoints only on worker 0 to prevent other
    workers from corrupting them', README.md:117-119). A marker barrier via
    the eager engine keeps ranks from racing ahead of an unfinished save."""
    import numpy as np

    if basics.rank() == 0:
        ocp = _ocp()
        ckptr = ocp.StandardCheckpointer()
        target = os.path.join(os.path.abspath(path), f"step_{step}") \
            if step is not None else os.path.abspath(path)
        ckptr.save(target, state, force=force)
        ckptr.wait_until_finished()
    if basics.size() > 1:
        # barrier: everyone waits until rank 0's save completed
        basics.engine().run("allreduce", np.zeros(1), f"ckpt.barrier.{path}.{step}")


def restore(path: str, template: Any = None, step: Optional[int] = None) -> Any:
    """Read a checkpoint on every rank (all ranks share the filesystem on a
    pod slice; if not, restore on rank 0 and use hvd.jax.broadcast_parameters
    inside the first step). ``template`` gives dtypes/shapes for orbax."""
    ocp = _ocp()
    ckptr = ocp.StandardCheckpointer()
    target = os.path.join(os.path.abspath(path), f"step_{step}") \
        if step is not None else os.path.abspath(path)
    state = ckptr.restore(target, template) if template is not None \
        else ckptr.restore(target)
    return state


def latest_step(path: str) -> Optional[int]:
    """Highest step_N subdirectory under ``path`` (resume-epoch discovery,
    reference examples/pytorch_imagenet_resnet50.py scans for existing
    checkpoint files the same way)."""
    try:
        steps = [int(d.split("_", 1)[1]) for d in os.listdir(path)
                 if d.startswith("step_") and d.split("_", 1)[1].isdigit()]
    except OSError:
        return None
    return max(steps) if steps else None


def broadcast_resume_state(state: Any, root_rank: int = 0) -> Any:
    """Host-side broadcast of restored state (epoch counters, small pytrees)
    through the eager engine — for values needed OUTSIDE jit (the in-jit
    path is hvd.jax.broadcast_parameters)."""
    import numpy as np

    if basics.size() == 1:
        return state
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(state)
    out = []
    for i, leaf in enumerate(leaves):
        arr = np.asarray(leaf)
        res = basics.engine().run("broadcast", arr, f"ckpt.resume.{i}",
                                  root_rank=root_rank)
        out.append(np.asarray(res).reshape(arr.shape).astype(arr.dtype)
                   if arr.shape else type(leaf)(res))
    return jax.tree_util.tree_unflatten(treedef, out)
