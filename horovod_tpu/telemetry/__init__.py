"""Pod-scale telemetry tree (ISSUE 17 tentpole).

Every telemetry path built in ISSUEs 2/6/15 — pod metrics snapshots,
trace-span collection, flight-ring sweeps, NTP clock probes, stall
reports — originally fanned in O(world) through the coordinator's single
socket loop. This package restructures all of them as a two-level tree,
the Dapper pattern (local collection daemons + aggregation before the
slow tier, PAPERS.md Observability):

- :mod:`tree`  — the plan: which rank leads each host (same election as
  the hier data plane: lowest rank on the host) and the collection
  interval knob.
- :mod:`agent` — :class:`~horovod_tpu.telemetry.agent.TelemetryAgent`,
  the per-host leader service (hosted by the runner HostAgent process):
  ranks push metrics-snapshot DELTAS to it, it answers their clock probes
  locally with composed offsets, batches their watchdog/anomaly events,
  and serves pull-based ``sweep`` endpoints for flight rings and trace
  spans. :class:`~horovod_tpu.telemetry.agent.RankTelemetryClient` is the
  rank side.
- :mod:`root`  — :class:`~horovod_tpu.telemetry.root.RootAggregator`,
  the coordinator side: ingests per-host partials (associative merge,
  metrics/aggregate.py), tracks per-host staleness (feeding the
  ``telemetry_lag`` anomaly), and exposes the pod view.

Root connections and control bytes per collection tick are O(hosts), not
O(world); the host-then-root merge is bitwise-identical to the flat merge
by construction (exact rational sums, rounded once at finalize).
"""

from __future__ import annotations

from .agent import RankTelemetryClient, TelemetryAgent  # noqa: F401
from .root import RootAggregator  # noqa: F401
from .tree import TreePlan, interval_s_from_env, plan_tree  # noqa: F401
