"""Per-host telemetry leader + the rank-side client (ISSUE 17 tentpole).

:class:`TelemetryAgent` is the host-local collection daemon — the Dapper
move: ranks talk to a process one loopback hop away, and only the MERGED
host view crosses the slow tier to the coordinator. It is a
:class:`~horovod_tpu.runner.network.BasicService` (HMAC-authenticated,
session-keyed), normally hosted by the runner HostAgent process under the
job-derived secret, so the ranks' existing ``HOROVOD_SECRET`` authenticates
them to it and nothing new crosses the wire in the clear.

What it does per hop:

- **rank → leader** (push): ranks push metrics snapshots as DELTAS
  (aggregate.snapshot_delta) every collection interval; a sequence gap —
  agent restart, dropped push — answers ``need_full`` and the rank resends
  the whole snapshot. Watchdog/anomaly events ride ``telemetry_events``
  and are batched.
- **leader → root** (push): every interval the agent merges its ranks'
  latest snapshots into ONE host partial (the associative merge) and
  pushes it — itself delta-compressed — to the driver's ``host_metrics``
  endpoint, piggybacking the batched events and per-rank ages. Root
  ingest per tick is O(hosts).
- **clock**: the agent answers rank ``clock_probe``s locally (BasicService
  built-in) and serves ``clock_info`` — its own cached NTP estimate
  against the root — so a rank composes rank→leader + leader→root
  (clock.compose_offsets) instead of probing the root directly.
- **sweeps** (pull): ``sweep`` returns the host's flight rings (decoded),
  flight dumps, and trace-span files, plus per-rank coverage (last push
  age, seq) — ``python -m horovod_tpu.tracing.bundle --leader`` streams a
  pod's telemetry host-by-host through these.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Optional

from ..metrics.aggregate import (
    apply_snapshot_delta,
    finalize_partial,
    lift_snapshot,
    merge_partials,
    snapshot_delta,
)
from ..metrics.registry import MetricsRegistry, registry
from ..runner.network import BasicClient, BasicService
from ..tracing.clock import compose_offsets, estimate_offset_ns
from .tree import interval_s_from_env

#: events kept while waiting for the next root push (leader) — bounded so
#: a storm of stall warnings can't grow the agent without limit.
EVENT_QUEUE_LIMIT = 2048


def _event_source(event: dict) -> str:
    kind = str(event.get("kind", ""))
    if kind == "stall":
        return "watchdog"
    if kind in ("anomaly",) or event.get("flight_event") == "anomaly":
        return "anomaly"
    return "other"


class TelemetryAgent(BasicService):
    """One host's telemetry leader. Protocol (request ``kind`` → response):

    - ``telemetry_hello`` ``{rank}`` → ``{ok, interval_s}`` — registers the
      rank as expected on this host and tells it the collection interval.
    - ``telemetry_push`` ``{rank, seq, full, body}`` → ``{ok, need_full}``
      — a full snapshot (``full``) or a delta against the last acked one.
    - ``telemetry_events`` ``{rank, events}`` → ``{ok}`` — batch of
      structured watchdog/anomaly events, forwarded on the next root push.
    - ``clock_info`` → ``{ok, synced, offset_ns, error_ns}`` — this
      agent's cached offset to the root clock (for composition).
    - ``host_metrics`` → ``{ok, host, partial, ages_s, expected}`` — the
      current host partial (pull; the push loop uses the same builder).
    - ``sweep`` ``{want: ["flight","spans"]}`` → rings/dumps/span files +
      per-rank coverage (the bundle's per-host collection endpoint).
    """

    def __init__(self, key: bytes, host_name: Optional[str] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 flight_dir: Optional[str] = None,
                 trace_dir: Optional[str] = None,
                 interval_s: Optional[float] = None,
                 expected_ranks=None,
                 reg: Optional[MetricsRegistry] = None) -> None:
        super().__init__(key, host=host, port=port)
        from ..runner.service import host_hash

        self.host_name = host_name or host_hash()
        self.flight_dir = flight_dir if flight_dir is not None \
            else os.environ.get("HOROVOD_FLIGHT_DIR", "")
        self.trace_dir = trace_dir if trace_dir is not None \
            else os.environ.get("HOROVOD_TRACE_DIR", "")
        self.interval_s = float(interval_s) if interval_s is not None \
            else interval_s_from_env()
        self.reg = reg or registry()
        self._state_lock = threading.Lock()
        self._ranks: dict[int, dict] = {}   # rank -> {snap, seq, t, pushes}
        self._expected: set[int] = set(int(r) for r in expected_ranks or ())
        self._events: deque = deque(maxlen=EVENT_QUEUE_LIMIT)
        # leader → root push state
        self._root_lock = threading.Lock()
        self._root_addresses = None
        self._root_key: Optional[bytes] = None
        self._root_client: Optional[BasicClient] = None
        self._root_offset: Optional[tuple] = None
        self._root_seq = 0
        self._root_acked: Optional[dict] = None
        self._push_stop = threading.Event()
        self._push_thread: Optional[threading.Thread] = None
        self._rank_push_c = self.reg.counter(
            "horovod_telemetry_pushes_total",
            help="telemetry-tree snapshot pushes received, by hop "
                 "(rank→leader on agents, leader→root at the root)",
            hop="rank")

    # -- protocol ------------------------------------------------------------

    def handle(self, req: Any, client_addr) -> Any:
        kind = req.get("kind")
        if kind == "telemetry_hello":
            with self._state_lock:
                self._expected.add(int(req["rank"]))
            return {"ok": True, "interval_s": self.interval_s,
                    "host": self.host_name}
        if kind == "telemetry_push":
            return self._handle_push(req)
        if kind == "telemetry_events":
            events = list(req.get("events") or [])
            with self._state_lock:
                for e in events:
                    self._events.append(dict(e, _rank=req.get("rank")))
            for e in events:
                self.reg.counter(
                    "horovod_telemetry_events_total",
                    help="watchdog/anomaly events batched through the "
                         "telemetry tree, by source",
                    source=_event_source(e)).inc()
            return {"ok": True}
        if kind == "clock_info":
            with self._root_lock:
                off = self._root_offset
            return {"ok": True, "synced": off is not None,
                    "offset_ns": int(off[0]) if off else 0,
                    "error_ns": int(off[1]) if off else 0}
        if kind == "host_metrics":
            partial, ages = self._partial_and_ages()
            return {"ok": True, "host": self.host_name, "partial": partial,
                    "ages_s": ages, "expected": self.expected_ranks(),
                    "interval_s": self.interval_s}
        if kind == "sweep":
            return self._handle_sweep(req)
        return {"ok": False, "error": f"unknown request {kind}"}

    def _handle_push(self, req: dict) -> dict:
        rank = int(req["rank"])
        seq = int(req.get("seq", 0))
        now = time.monotonic()
        with self._state_lock:
            self._expected.add(rank)
            st = self._ranks.get(rank)
            if req.get("full"):
                snap = req["body"]
            else:
                if st is None or seq != st["seq"] + 1:
                    # Resync: agent restarted, or a push was lost. The rank
                    # answers with a full snapshot; meanwhile the last good
                    # snapshot (if any) keeps feeding the host partial.
                    return {"ok": True, "need_full": True}
                snap = apply_snapshot_delta(st["snap"], req["body"])
            self._ranks[rank] = {
                "snap": snap, "seq": seq, "t": now,
                "pushes": (st["pushes"] + 1) if st else 1,
            }
        self._rank_push_c.inc()
        return {"ok": True, "need_full": False}

    def _handle_sweep(self, req: dict) -> dict:
        want = req.get("want") or ["flight", "spans"]
        resp: dict = {"ok": True, "host": self.host_name,
                      "coverage": self.coverage()}
        if "flight" in want:
            items: list = []
            errors: list = []
            if self.flight_dir and os.path.isdir(self.flight_dir):
                from ..tracing import flight as _flight

                for path in _flight.ring_files(self.flight_dir):
                    name = os.path.basename(path)
                    try:
                        items.append({"name": name + ".json", "kind": "ring",
                                      "doc": _flight.read_ring(path)})
                    except Exception as e:
                        # torn/truncated rings raise struct.error and
                        # friends — a bad ring must become a NAMED row in
                        # the bundle, never a crashed sweep
                        errors.append({"file": name, "error": str(e)[:200]})
                for path in _flight.dump_files(self.flight_dir):
                    name = os.path.basename(path)
                    try:
                        with open(path) as f:
                            items.append({"name": name, "kind": "dump",
                                          "doc": json.load(f)})
                    except Exception as e:
                        errors.append({"file": name, "error": str(e)[:200]})
            resp["flight"] = items
            resp["flight_errors"] = errors
        if "spans" in want:
            spans: list = []
            if self.trace_dir and os.path.isdir(self.trace_dir):
                from ..tracing.collector import span_files

                for path in span_files(self.trace_dir):
                    try:
                        with open(path) as f:
                            spans.append({"name": os.path.basename(path),
                                          "text": f.read()})
                    except OSError as e:
                        resp.setdefault("flight_errors", []).append(
                            {"file": os.path.basename(path),
                             "error": str(e)[:200]})
            resp["spans"] = spans
        return resp

    # -- host views ----------------------------------------------------------

    def _partial_and_ages(self) -> tuple:
        now = time.monotonic()
        with self._state_lock:
            items = sorted(self._ranks.items())
            ages = {str(r): round(now - st["t"], 3) for r, st in items}
        partial = merge_partials(
            [lift_snapshot(r, st["snap"]) for r, st in items])
        return partial, ages

    def host_partial(self) -> dict:
        """The associative merge of every local rank's latest snapshot."""
        return self._partial_and_ages()[0]

    def host_view(self) -> Optional[dict]:
        """Finalized host-merged snapshot for ``/metrics.json?host=1``
        (exposition.MetricsServer ``host_view=``); None before any push."""
        with self._state_lock:
            empty = not self._ranks
        if empty:
            return None
        return finalize_partial(self.host_partial())

    def expected_ranks(self) -> list:
        with self._state_lock:
            return sorted(self._expected | set(self._ranks))

    def coverage(self) -> dict:
        """Per-rank liveness as this leader sees it — what the bundle's
        MANIFEST per-host accounting is built from."""
        now = time.monotonic()
        with self._state_lock:
            ranks = {str(r): {"age_s": round(now - st["t"], 3),
                              "seq": st["seq"], "pushes": st["pushes"]}
                     for r, st in sorted(self._ranks.items())}
            expected = sorted(self._expected | set(self._ranks))
        return {"host": self.host_name, "expected": expected,
                "ranks": ranks, "interval_s": self.interval_s}

    def drain_events(self) -> list:
        with self._state_lock:
            out = list(self._events)
            self._events.clear()
        return out

    # -- leader → root push loop ---------------------------------------------

    def attach_root(self, addresses, key: Optional[bytes] = None,
                    probe_rounds: int = 8, start_loop: bool = True) -> None:
        """Connect to the root (DriverService), estimate this agent's clock
        offset against it (served back to ranks via ``clock_info``), and —
        unless ``start_loop`` is False — start pushing the host partial
        every collection interval."""
        with self._root_lock:
            self._root_addresses = list(addresses)
            self._root_key = key or self.key
        self._connect_root(probe_rounds)
        if start_loop and self._push_thread is None:
            self._push_thread = threading.Thread(
                target=self._push_loop, name="hvd_telemetry_push",
                daemon=True)
            self._push_thread.start()

    def _connect_root(self, probe_rounds: int = 8) -> None:
        with self._root_lock:
            addresses, key = self._root_addresses, self._root_key
        client = BasicClient(addresses, key, timeout=30.0,
                             connect_retry_s=10.0)
        offset = estimate_offset_ns(
            lambda: client.request({"kind": "clock_probe"})["t"],
            rounds=probe_rounds)
        with self._root_lock:
            self._root_client = client
            self._root_offset = offset
            self._root_acked = None   # fresh connection → resend full

    def _push_loop(self) -> None:
        while not self._push_stop.wait(self.interval_s):
            try:
                self.push_to_root_once()
            except Exception:   # telemetry must never take the host down
                with self._root_lock:
                    client, self._root_client = self._root_client, None
                if client is not None:
                    try:
                        client.close()
                    except Exception:
                        pass
                try:
                    self._connect_root()
                except Exception:
                    pass   # root still gone; retry next tick

    def push_to_root_once(self) -> dict:
        """One leader→root tick: host partial (delta-compressed against the
        last acked push), batched events, per-rank ages."""
        partial, ages = self._partial_and_ages()
        events = self.drain_events()
        with self._root_lock:
            client = self._root_client
            acked = self._root_acked
            seq = self._root_seq
        if client is None:
            raise ConnectionError("no root attached")
        full = acked is None
        body = partial if full else snapshot_delta(acked, partial)
        req = {"kind": "host_metrics", "host": self.host_name, "seq": seq,
               "full": full, "body": body, "events": events,
               "ages_s": ages, "expected": self.expected_ranks(),
               "interval_s": self.interval_s}
        try:
            resp = client.request(req)
            if resp.get("need_full") and not full:
                req.update(full=True, body=partial, events=[])
                resp = client.request(req)
        except Exception:
            # Re-queue the drained events so a root blip doesn't lose them.
            with self._state_lock:
                for e in events:
                    self._events.append(e)
            raise
        with self._root_lock:
            self._root_acked = partial
            self._root_seq = seq + 1
        return resp

    def stop(self) -> None:
        self._push_stop.set()
        if self._push_thread is not None:
            self._push_thread.join(timeout=5)
        with self._root_lock:
            client, self._root_client = self._root_client, None
        if client is not None:
            try:
                client.close()
            except Exception:
                pass
        super().stop()


class RankTelemetryClient:
    """The rank side of the rank→leader hop.

    Owns one authenticated connection to the host's TelemetryAgent and
    pushes this process's metrics snapshot as deltas (full on first push
    or whenever the agent asks ``need_full``). ``event_sink`` plugs into
    ``StallWatchdog(event_sink=...)`` / ``AnomalyDetector.subscribe`` so
    rank-local events batch through the leader instead of each rank
    talking to the root. ``composed_clock_offset`` is the tree's clock
    path: rank→leader probe (local, tight RTT) composed with the leader's
    cached leader→root estimate.
    """

    def __init__(self, addresses, key: bytes, rank: int,
                 snapshot_fn: Optional[Callable[[], dict]] = None) -> None:
        self.rank = int(rank)
        self._snapshot_fn = snapshot_fn
        self._lock = threading.Lock()
        self._seq = 0
        self._acked: Optional[dict] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.client = BasicClient(addresses, key, timeout=30.0,
                                  connect_retry_s=10.0)
        hello = self.client.request({"kind": "telemetry_hello",
                                     "rank": self.rank})
        self.interval_s = float(hello.get("interval_s",
                                          interval_s_from_env()))

    def _snapshot(self) -> dict:
        if self._snapshot_fn is not None:
            return self._snapshot_fn()
        from ..metrics import snapshot

        return snapshot()

    def push(self, snap: Optional[dict] = None) -> dict:
        """Push the current snapshot (delta-compressed); returns the wire
        request actually sent (tests and the bench read its size)."""
        snap = snap if snap is not None else self._snapshot()
        with self._lock:
            acked, seq = self._acked, self._seq
            full = acked is None
            body = snap if full else snapshot_delta(acked, snap)
            req = {"kind": "telemetry_push", "rank": self.rank, "seq": seq,
                   "full": full, "body": body}
            resp = self.client.request(req)
            if resp.get("need_full") and not full:
                req = {"kind": "telemetry_push", "rank": self.rank,
                       "seq": seq, "full": True, "body": snap}
                resp = self.client.request(req)
            if resp.get("ok"):
                self._acked = snap
                self._seq = seq + 1
        return req

    def push_events(self, events: list) -> None:
        self.client.request({"kind": "telemetry_events", "rank": self.rank,
                             "events": list(events)})

    def event_sink(self, event: dict) -> None:
        """Single-event convenience for watchdog/anomaly hooks; never
        raises (a telemetry blip must not kill the caller's thread)."""
        try:
            self.push_events([event])
        except Exception:
            pass

    def composed_clock_offset(self, rounds: int = 8) -> tuple:
        """(offset_ns, error_bound_ns) of the ROOT clock relative to this
        rank: rank→leader estimate composed with the leader's cached
        leader→root estimate. Falls back to the rank→leader estimate alone
        when the leader is not synced to a root (single-host runs: the
        leader IS the reference)."""
        local = estimate_offset_ns(
            lambda: self.client.request({"kind": "clock_probe"})["t"],
            rounds=rounds)
        info = self.client.request({"kind": "clock_info"})
        if not info.get("synced"):
            return local
        return compose_offsets(
            local, (int(info["offset_ns"]), int(info["error_ns"])))

    def start(self) -> "RankTelemetryClient":
        """Push every collection interval on a daemon thread."""
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="hvd_telemetry_rank", daemon=True)
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.push()
            except Exception:
                pass   # leader blip: keep the training loop alive, retry

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        try:
            self.client.close()
        except Exception:
            pass
