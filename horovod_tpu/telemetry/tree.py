"""The telemetry tree's shape: leaders, members, and the collection tick.

Leader election mirrors the hier data plane (parallel/hier and the
runner's barrel-shift rank assignment): ranks on one host are contiguous,
and the LOWEST rank on each host — local_rank 0 — leads it. Electing the
same rank both planes already treat as the host representative means the
telemetry agent rides the process that is already the host's cross-plane
endpoint, and a membership change moves both roles together.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Mapping, Sequence, Union

#: seconds between collection ticks at every hop (rank→leader push,
#: leader→root push, root staleness accounting). One knob on purpose:
#: the ``telemetry_lag`` anomaly judges "host snapshot older than
#: TELEMETRY_LAG_TICKS collection intervals", which only means something
#: when every hop agrees what an interval is.
DEFAULT_INTERVAL_S = 1.0


def interval_s_from_env() -> float:
    """The collection interval: ``HOROVOD_TELEMETRY_INTERVAL_S`` (seconds,
    default 1.0, floored at 50 ms so a typo can't busy-spin the agents)."""
    raw = os.environ.get("HOROVOD_TELEMETRY_INTERVAL_S", "")
    try:
        val = float(raw) if raw else DEFAULT_INTERVAL_S
    except ValueError:
        val = DEFAULT_INTERVAL_S
    return max(val, 0.05)


@dataclass(frozen=True)
class TreePlan:
    """Which rank leads each host. ``hosts`` is sorted (the same order the
    driver's rank assignment sorts by host hash)."""

    hosts: tuple
    ranks_of: dict      # host -> tuple of member ranks, ascending
    leader_of: dict     # host -> leader rank (min member rank)

    @property
    def num_hosts(self) -> int:
        return len(self.hosts)

    def host_of(self, rank: int) -> str:
        for host, ranks in self.ranks_of.items():
            if rank in ranks:
                return host
        raise KeyError(f"rank {rank} is not in the tree plan")

    def leader_for(self, rank: int) -> int:
        return self.leader_of[self.host_of(rank)]

    def is_leader(self, rank: int) -> bool:
        return rank in self.leader_of.values()


def plan_tree(host_of_rank: Union[Mapping[int, str], Sequence[str]]
              ) -> TreePlan:
    """Build the plan from rank→host (a dict, or a list indexed by rank —
    the shape ``DriverService._topology`` and the smokes already carry)."""
    if not isinstance(host_of_rank, Mapping):
        host_of_rank = dict(enumerate(host_of_rank))
    by_host: dict = {}
    for rank in sorted(host_of_rank):
        by_host.setdefault(str(host_of_rank[rank]), []).append(int(rank))
    hosts = tuple(sorted(by_host))
    ranks_of = {h: tuple(by_host[h]) for h in hosts}
    leader_of = {h: min(by_host[h]) for h in hosts}
    return TreePlan(hosts=hosts, ranks_of=ranks_of, leader_of=leader_of)
