"""Root of the telemetry tree — the coordinator-side aggregator.

:class:`RootAggregator` is transport-free bookkeeping: the runner's
DriverService routes its ``host_metrics`` requests here (one per host per
collection tick — O(hosts) connections and bytes at the root), and
``pod_metrics`` merges the stored host partials with any directly-pushed
rank snapshots through the same associative merge, so the pod view is
bitwise what the flat O(world) fan-in would have produced.

Staleness is first-class: every ingest refreshes per-host ages, published
as ``horovod_telemetry_snapshot_age_ticks{host=...}`` (in collection
intervals). The anomaly detector's ``telemetry_lag`` rule reads that gauge
and fires when any host's snapshot is older than TELEMETRY_LAG_TICKS
intervals — stale observability is an alarm, not something to silently
average over (Monarch's freshness framing, PAPERS.md Observability).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

from ..metrics.aggregate import apply_snapshot_delta
from ..metrics.registry import MetricsRegistry, registry
from .tree import interval_s_from_env

#: batched events retained at the root until drained (bounded).
EVENT_BUFFER_LIMIT = 4096


class RootAggregator:
    def __init__(self, interval_s: Optional[float] = None,
                 reg: Optional[MetricsRegistry] = None,
                 now=time.monotonic) -> None:
        self.interval_s = float(interval_s) if interval_s is not None \
            else interval_s_from_env()
        self.reg = reg or registry()
        self._now = now
        self._lock = threading.Lock()
        # host -> {partial, seq, t, expected, ages_s, pushes}
        self._hosts: dict[str, dict] = {}
        self._events: deque = deque(maxlen=EVENT_BUFFER_LIMIT)
        self._push_c = self.reg.counter(
            "horovod_telemetry_pushes_total",
            help="telemetry-tree snapshot pushes received, by hop "
                 "(rank→leader on agents, leader→root at the root)",
            hop="host")
        self._hosts_g = self.reg.gauge(
            "horovod_telemetry_hosts",
            help="hosts currently reporting through the telemetry tree")

    # -- ingest (DriverService `host_metrics` requests land here) ------------

    def ingest(self, req: dict, now: Optional[float] = None) -> dict:
        """One leader push: full host partial or a delta against the last
        acked one. A sequence gap (root restart, dropped push) answers
        ``need_full`` — the stored partial keeps serving until the resend."""
        now = now if now is not None else self._now()
        host = str(req.get("host", "?"))
        seq = int(req.get("seq", 0))
        with self._lock:
            st = self._hosts.get(host)
            if req.get("full"):
                partial = req["body"]
            else:
                if st is None or seq != st["seq"] + 1:
                    return {"ok": True, "need_full": True}
                partial = apply_snapshot_delta(st["partial"], req["body"])
            self._hosts[host] = {
                "partial": partial, "seq": seq, "t": now,
                "expected": list(req.get("expected") or []),
                "ages_s": dict(req.get("ages_s") or {}),
                # staleness is judged in the PUSHING leader's collection
                # intervals — the tick every hop of that host agreed on
                "interval_s": float(req.get("interval_s") or
                                    self.interval_s),
                "pushes": (st["pushes"] + 1) if st else 1,
            }
            for e in req.get("events") or []:
                self._events.append(dict(e, _host=host))
        self._push_c.inc()
        self.publish(now)
        return {"ok": True, "need_full": False}

    # -- views ---------------------------------------------------------------

    def hosts(self) -> list:
        with self._lock:
            return sorted(self._hosts)

    def partials(self) -> list:
        """Stored host partials in sorted host order — the order the
        driver's rank assignment sorts hosts, so the combine order matches
        the flat merge's rank order."""
        with self._lock:
            return [self._hosts[h]["partial"] for h in sorted(self._hosts)]

    def covered_ranks(self) -> set:
        """Ranks whose snapshots already live inside a host partial — the
        driver must not double-count a direct push from the same rank."""
        with self._lock:
            out: set = set()
            for st in self._hosts.values():
                out.update(int(r) for r in st["partial"].get("rank_ids", []))
            return out

    def ages_ticks(self, now: Optional[float] = None) -> dict:
        """Per-host snapshot age in collection intervals."""
        now = now if now is not None else self._now()
        with self._lock:
            return {h: (now - st["t"])
                    / st.get("interval_s", self.interval_s)
                    for h, st in self._hosts.items()}

    def staleness(self, now: Optional[float] = None) -> dict:
        """Coverage summary for callers that report on the pod (elastic
        driver events, debug tooling): per-host age + expected ranks."""
        now = now if now is not None else self._now()
        with self._lock:
            return {h: {"age_ticks": round(
                            (now - st["t"])
                            / st.get("interval_s", self.interval_s), 2),
                        "expected": list(st["expected"]),
                        "pushes": st["pushes"]}
                    for h, st in sorted(self._hosts.items())}

    def drain_events(self) -> list:
        with self._lock:
            out = list(self._events)
            self._events.clear()
        return out

    # -- publication (feeds the telemetry_lag anomaly rule) ------------------

    def publish(self, now: Optional[float] = None) -> None:
        """Refresh the root's own gauges: host count + per-host snapshot
        age in ticks. Call it right before reading the registry (ingest
        calls it too, but a SILENT host only goes stale through a reader's
        refresh — the dead host is exactly the one that stops pushing)."""
        ages = self.ages_ticks(now)
        self._hosts_g.set(len(ages))
        for host, age in ages.items():
            self.reg.gauge(
                "horovod_telemetry_snapshot_age_ticks",
                help="age of each host's latest telemetry push, in "
                     "collection intervals (telemetry_lag fires past "
                     "TELEMETRY_LAG_TICKS)",
                host=host).set(round(age, 3))

    # -- membership ----------------------------------------------------------

    def forget_host(self, host: str) -> None:
        """Drop a host's partial and its staleness gauge — an elastic
        membership change that removed the host must not leave a gauge
        aging toward a spurious ``telemetry_lag`` firing."""
        with self._lock:
            self._hosts.pop(host, None)
        try:
            self.reg.remove("horovod_telemetry_snapshot_age_ticks",
                            host=host)
        except Exception:
            pass
        self._hosts_g.set(len(self.hosts()))

    def keep_only(self, hosts) -> None:
        """Forget every host not in ``hosts`` (the new membership)."""
        keep = {str(h) for h in hosts}
        for h in self.hosts():
            if h not in keep:
                self.forget_host(h)
