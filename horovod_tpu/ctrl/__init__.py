"""Pod-scale control tree (ISSUE 18).

Rank-0's control plane — rendezvous registration, engine negotiation
ticks, knob-epoch acks, elastic polls, clock probes — is a star: every
rank holds a socket to the root, so root connections and control bytes
are O(world). This package fans that traffic through per-host leaders,
the same shape the telemetry tree (ISSUE 17) gave the metrics plane:

- :mod:`~horovod_tpu.ctrl.tree` — the host-grouping plan and the knobs
  (``HOROVOD_CTRL_TREE``, batching/poll intervals), with a LOUD flat
  fallback when no host grouping exists.
- :mod:`~horovod_tpu.ctrl.agent` — :class:`ControlAgent`, the per-host
  runner-plane leader: batches its ranks' register/wait/poll traffic
  into one upstream connection to the driver, passes everything else
  through verbatim, and serves checkpoint streaming to cold-starting
  joiners (ckpt_async/stream.py).
- :mod:`~horovod_tpu.ctrl.relay` — :class:`CoordRelay`, the per-host
  engine-plane leader: speaks the coordinator's raw HMAC wire protocol
  on both sides, batching exchange ticks and ring barriers so the
  rank-0 coordinator sees one connection per host.
"""

from .tree import (  # noqa: F401
    TreePlan,
    ctrl_batch_s,
    ctrl_poll_s,
    plan_tree,
    tree_enabled,
    use_tree,
)
