"""Per-host control leader for the runner plane (ISSUE 18 tentpole).

:class:`ControlAgent` is a transparent, aggregating proxy between a
host's ranks and the driver: ranks speak the EXACT driver protocol to
it (``register``/``rendezvous``, ``wait_assignment``, ``elastic_poll``,
``clock_probe``, anything else verbatim), and the agent folds that
traffic into one upstream connection:

- **register/rendezvous** arriving within one ``HOROVOD_CTRL_BATCH_S``
  window ride a single ``host_register`` request.
- **wait_assignment** waiters are grouped per target generation; ONE
  upstream ``host_wait_assignment`` long-poll resolves the whole
  host's waiters (latecomers trigger a follow-up for the remainder).
- **elastic_poll** is answered from a verdict cached for
  ``HOROVOD_CTRL_POLL_S``: the root sees one ``host_elastic_poll``
  per host per interval instead of one per rank per interval.
- **clock_probe** never leaves the host (BasicService built-in).
- **ckpt_manifest/ckpt_fetch** serve the latest committed checkpoint
  shards to streaming cold-starters (ckpt_async/stream.py).

Because every aggregated request routes through the driver's OWN
per-rank handlers (runner/service.py ``host_*`` kinds loop the flat
handlers), the tree preserves the flat protocol's semantics: removed
slots still answer ``{"ok": False, "removed": True}``, stale
generations still bounce, and a rank that skips the tree entirely
behaves identically.

Like the telemetry agent, it is normally hosted by the runner
HostAgent under the job-derived secret (``kind="ctrl"`` command), so
the ranks' existing ``HOROVOD_SECRET`` authenticates them to it.

``horovod_ctrl_bytes_total{dir=...}`` counts the tree's economics:
``up_out``/``up_in`` are measured upstream wire bytes, ``absorbed`` is
the flat-equivalent wire size of rank requests answered at this leader
without an upstream exchange — the savings the O(hosts) claim is made
of.
"""

from __future__ import annotations

import os
import pickle
import threading
import time
import zlib
from typing import Any, Optional

from ..metrics.registry import MetricsRegistry, registry
from ..runner.network import BasicClient, BasicService
from .tree import ctrl_batch_s, ctrl_poll_s

#: per-frame wire overhead of one request/response pair on the
#: authenticated channel (2 × (32 B MAC + 8 B length)) — used to price
#: locally-absorbed requests in flat-equivalent bytes.
FRAME_OVERHEAD = 2 * (32 + 8)


def _flat_bytes(req: Any, resp: Any = None) -> int:
    """Flat-equivalent wire size of a request (+ optional response) had
    it crossed to the root directly."""
    n = len(pickle.dumps(req, protocol=pickle.HIGHEST_PROTOCOL))
    if resp is not None:
        n += len(pickle.dumps(resp, protocol=pickle.HIGHEST_PROTOCOL))
    return n + FRAME_OVERHEAD


class ControlAgent(BasicService):
    """One host's control-plane leader (see module docstring)."""

    def __init__(self, key: bytes, host_name: Optional[str] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 ckpt_dir: Optional[str] = None,
                 batch_s: Optional[float] = None,
                 poll_s: Optional[float] = None,
                 reg: Optional[MetricsRegistry] = None) -> None:
        super().__init__(key, host=host, port=port)
        from ..runner.service import host_hash

        self.host_name = host_name or host_hash()
        self.ckpt_dir = ckpt_dir if ckpt_dir is not None \
            else os.environ.get("HOROVOD_CKPT_STREAM_DIR", "")
        self.batch_s = float(batch_s) if batch_s is not None else ctrl_batch_s()
        self.poll_s = float(poll_s) if poll_s is not None else ctrl_poll_s()
        self.reg = reg or registry()
        # upstream (leader → root). TWO connections per leader — still
        # O(hosts) at the root: the wait client carries only the blocking
        # grouped assignment polls, so a register batch or elastic poll is
        # never queued behind a wait that needs that very registration to
        # resolve (requests on one BasicClient serialize).
        self._root_lock = threading.Lock()
        self._root_client: Optional[BasicClient] = None
        self._wait_client: Optional[BasicClient] = None
        self._up_requests = 0
        # rank indices this leader has seen (register/hello/wait) — the
        # index set one cached elastic poll answers for.
        self._known_lock = threading.Lock()
        self._known_indices: set[int] = set()
        # register micro-batch (one in flight at a time; next opens fresh)
        self._reg_lock = threading.Lock()
        self._reg_batch: Optional[dict] = None
        # wait_assignment groups keyed by min_generation (None = static)
        self._wait_lock = threading.Lock()
        self._wait_cv = threading.Condition(self._wait_lock)
        self._wait_groups: dict = {}
        # elastic-poll verdict cache
        self._poll_lock = threading.Lock()
        self._poll_fetch_lock = threading.Lock()
        self._poll_cache: Optional[dict] = None
        # Engine-plane relay (ctrl/relay.py): same key — the job secret IS
        # the workers' HOROVOD_SECRET — so ranks authenticate to it with
        # the credentials they already hold. Lazy so pure runner-plane
        # deployments (and tests) pay nothing.
        self._relay_lock = threading.Lock()
        self._relay: Optional[Any] = None

    def relay_port(self) -> int:
        """Start (once) and return the engine coordinator relay's port."""
        with self._relay_lock:
            if self._relay is None:
                from .relay import CoordRelay

                self._relay = CoordRelay(self.key)
            return self._relay.port

    # -- upstream ------------------------------------------------------------

    def attach_root(self, addresses, key: Optional[bytes] = None) -> None:
        """Connect this leader to the driver service. Socket timeout must
        out-wait the driver's 120 s assignment window (TaskAgent uses the
        same margin)."""
        client = BasicClient(addresses, key or self.key, timeout=180.0,
                             connect_retry_s=30.0)
        wait_client = BasicClient(addresses, key or self.key, timeout=180.0,
                                  connect_retry_s=30.0)
        with self._root_lock:
            old = (self._root_client, self._wait_client)
            self._root_client, self._wait_client = client, wait_client
        for c in old:
            if c is not None:
                try:
                    c.close()
                except Exception:
                    pass

    def has_root(self) -> bool:
        """True once :meth:`attach_root` connected this leader upstream —
        the gate HostAgent._spawn uses before pointing workers here."""
        with self._root_lock:
            return self._root_client is not None

    def _bytes_c(self, direction: str):
        return self.reg.counter(
            "horovod_ctrl_bytes_total",
            help="control-tree wire accounting: measured leader-to-root "
                 "bytes (up_out/up_in), flat-equivalent bytes answered at "
                 "a host leader without an upstream exchange (absorbed), "
                 "and per-host response fields hoisted out of batched "
                 "coordinator replies (hoisted)",
            dir=direction)

    def _upstream(self, req: Any, wait: bool = False) -> Any:
        """One upstream exchange. ``wait=True`` routes over the dedicated
        blocking-wait connection (see __init__)."""
        with self._root_lock:
            client = self._wait_client if wait else self._root_client
        if client is None:
            return {"ok": False, "error": "control agent has no root "
                                          "attached"}
        resp, out_b, in_b = client.request_counted(req)
        self._bytes_c("up_out").inc(out_b)
        self._bytes_c("up_in").inc(in_b)
        with self._root_lock:
            self._up_requests += 1
        return resp

    def upstream_requests(self) -> int:
        with self._root_lock:
            return self._up_requests

    # -- protocol ------------------------------------------------------------

    def handle(self, req: Any, client_addr) -> Any:
        kind = req.get("kind")
        if kind == "ctrl_hello":
            if req.get("index") is not None:
                with self._known_lock:
                    self._known_indices.add(int(req["index"]))
            return {"ok": True, "host": self.host_name,
                    "poll_s": self.poll_s, "batch_s": self.batch_s}
        if kind in ("register", "rendezvous"):
            return self._register(req)
        if kind == "wait_assignment":
            return self._wait_assignment(req)
        if kind == "elastic_poll":
            return self._elastic_poll(req)
        if kind == "ctrl_stats":
            return {"ok": True, "host": self.host_name,
                    "stats": self.stats(),
                    "upstream_requests": self.upstream_requests()}
        if kind == "ckpt_manifest":
            from ..ckpt_async import stream

            return stream.serve_manifest(self.ckpt_dir)
        if kind == "ckpt_fetch":
            from ..ckpt_async import stream

            return stream.serve_chunk(self.ckpt_dir, req)
        # Everything else — results, metrics pushes, get_fn, telemetry —
        # passes through verbatim on the shared upstream connection, so a
        # worker pointed at the tree never needs a second address.
        return self._upstream(req)

    # -- register micro-batch ------------------------------------------------

    def _register(self, req: dict) -> Any:
        if req.get("index") is not None:
            with self._known_lock:
                self._known_indices.add(int(req["index"]))
        with self._reg_lock:
            batch = self._reg_batch
            leader = batch is None
            if leader:
                batch = self._reg_batch = {"entries": [],
                                           "done": threading.Event(),
                                           "result": None}
            batch["entries"].append(dict(req))
        if not leader:
            batch["done"].wait(timeout=self.batch_s + 120.0)
            resp = batch["result"]
            # This rank's request never crossed to the root itself.
            self._bytes_c("absorbed").inc(_flat_bytes(req, {"ok": True}))
            return dict(resp) if isinstance(resp, dict) \
                else {"ok": False, "error": "batched register failed"}
        time.sleep(self.batch_s)
        with self._reg_lock:
            self._reg_batch = None   # snapshot + close in one critical section
            entries = list(batch["entries"])
        resp = self._upstream(self._pack_register(entries))
        batch["result"] = resp if isinstance(resp, dict) else {"ok": False}
        batch["done"].set()
        return dict(batch["result"])

    def _pack_register(self, entries: list) -> dict:
        """One host's registrations are highly redundant (same host_hash,
        same address prefixes, same field names), so the batch ships
        zlib-compressed when that wins; the driver re-inflates
        (service.py host_register). The eliminated bytes land in
        ``horovod_ctrl_bytes_total{dir="hoisted"}``."""
        raw = pickle.dumps(entries, protocol=pickle.HIGHEST_PROTOCOL)
        z = zlib.compress(raw, 6)
        if len(z) >= len(raw):
            return {"kind": "host_register", "entries": entries}
        self._bytes_c("hoisted").inc(len(raw) - len(z))
        return {"kind": "host_register", "entries_z": z}

    # -- grouped assignment waits --------------------------------------------

    def _wait_assignment(self, req: dict) -> Any:
        index = int(req["index"])
        min_gen = req.get("min_generation")
        timeout = float(req.get("timeout", 120.0))
        deadline = time.monotonic() + timeout
        with self._known_lock:
            self._known_indices.add(index)
        with self._wait_cv:
            g = self._wait_groups.get(min_gen)
            if g is None or g.get("closed"):
                g = self._wait_groups[min_gen] = {
                    "indices": set(), "results": {}, "closed": False,
                    "deadline": deadline, "running": False,
                }
            g["indices"].add(index)
            g["deadline"] = max(g["deadline"], deadline)
            if not g["running"]:
                g["running"] = True
                threading.Thread(target=self._wait_leader,
                                 args=(min_gen, g),
                                 name="hvd_ctrl_wait", daemon=True).start()
            else:
                self._bytes_c("absorbed").inc(_flat_bytes(req))
            while index not in g["results"] \
                    and time.monotonic() < deadline and not g["closed"]:
                self._wait_cv.wait(0.2)
            res = g["results"].get(index)
        if res is None:
            return {"ok": False,
                    "error": "timed out waiting for assignment via the "
                             "control tree"}
        return res

    #: Upstream poll bound for grouped assignment waits. The waits ride a
    #: dedicated connection (so a straggler's register batch never queues
    #: behind a wait that needs that registration to resolve), and each
    #: poll is additionally bounded so a driver that dies mid-formation
    #: is noticed within seconds, not at the 120 s assignment window.
    WAIT_POLL_S = 2.0

    def _wait_leader(self, min_gen, g: dict) -> None:
        """One upstream poll resolves every local waiter; loops while
        unresolved indices remain (latecomers within the group's window)."""
        time.sleep(self.batch_s)   # let the host's other ranks join
        try:
            while True:
                with self._wait_cv:
                    pend = sorted(g["indices"] - set(g["results"]))
                    remaining = g["deadline"] - time.monotonic()
                if not pend or remaining <= 0:
                    return
                up: dict = {"kind": "host_wait_assignment", "indices": pend,
                            "timeout": min(remaining, self.WAIT_POLL_S),
                            "z": True}
                if min_gen is not None:
                    up["min_generation"] = min_gen
                resp = self._upstream(up, wait=True)
                got: dict = {}
                if isinstance(resp, dict):
                    if resp.get("assignments_z") is not None:
                        # compressed batch reply (service.py) — the
                        # per-rank assignments share topology fields and
                        # coordinator addresses, so the batch deflates
                        # well below flat per-rank responses.
                        raw = zlib.decompress(resp["assignments_z"])
                        got = pickle.loads(raw)
                        self._bytes_c("hoisted").inc(
                            len(raw) - len(resp["assignments_z"]))
                    else:
                        got = resp.get("assignments") or {}
                adopted = 0
                with self._wait_cv:
                    for i, a in got.items():
                        # Only terminal answers reach waiters: an
                        # assignment, or a definitive removal. A per-index
                        # poll timeout ("ok": False without "removed") just
                        # means the world hasn't formed within this short
                        # poll — retry, don't fail the rank.
                        if isinstance(a, dict) and (a.get("ok")
                                                    or a.get("removed")):
                            g["results"][int(i)] = a
                            adopted += 1
                    self._wait_cv.notify_all()
                if not adopted:
                    time.sleep(min(0.5, self.batch_s * 2))
        finally:
            with self._wait_cv:
                g["closed"] = True
                if self._wait_groups.get(min_gen) is g:
                    del self._wait_groups[min_gen]
                self._wait_cv.notify_all()

    # -- cached elastic polls ------------------------------------------------

    def _elastic_poll(self, req: dict) -> Any:
        index = int(req["index"])
        gen = req.get("generation", 0)
        with self._known_lock:
            self._known_indices.add(index)
            indices = sorted(self._known_indices)
        now = time.monotonic()
        with self._poll_lock:
            c = self._poll_cache
            fresh = (c is not None and c["generation"] == gen
                     and now - c["t"] < self.poll_s)
        if not fresh:
            with self._poll_fetch_lock:
                with self._poll_lock:   # another thread may have refreshed
                    c = self._poll_cache
                    fresh = (c is not None and c["generation"] == gen
                             and time.monotonic() - c["t"] < self.poll_s)
                if not fresh:
                    resp = self._upstream({"kind": "host_elastic_poll",
                                           "indices": indices,
                                           "generation": gen})
                    if not (isinstance(resp, dict) and resp.get("ok")):
                        # Root unreachable: report "no change" like the flat
                        # path's error handling (elastic/run.py) does.
                        return {"ok": False,
                                "error": "control-tree poll failed"}
                    c = {"t": time.monotonic(), "generation": gen,
                         "reset": bool(resp.get("reset_required")),
                         "removed": set(resp.get("removed") or ())}
                    with self._poll_lock:
                        self._poll_cache = c
        else:
            self._bytes_c("absorbed").inc(
                _flat_bytes(req, {"ok": True, "reset_required": False}))
        return {"ok": True,
                "reset_required": bool(c["reset"] or index in c["removed"])}

    def stop(self) -> None:
        with self._relay_lock:
            relay, self._relay = self._relay, None
        if relay is not None:
            try:
                relay.stop()
            except Exception:
                pass
        with self._root_lock:
            clients = (self._root_client, self._wait_client)
            self._root_client = self._wait_client = None
        for client in clients:
            if client is not None:
                try:
                    client.close()
                except Exception:
                    pass
        super().stop()
