"""Per-host relay for the engine coordinator's control plane (ISSUE 18).

The Python eager engine coordinates collectives through a rank-0 star:
every rank holds one control socket to the coordinator and ticks it with
exchange envelopes, so the root pays O(world) connections and O(world)
control bytes per step. ``CoordRelay`` collapses that to O(hosts): it
listens on loopback, every LOCAL rank connects to it instead of the
coordinator (``HOROVOD_CTRL_RELAY``), and it maintains exactly one primary
upstream connection per coordinator generation. Rank envelopes are
forwarded with three disciplines, chosen per message kind to preserve the
engine's protocol invariants exactly:

- ``exchange`` — opportunistically coalesced: envelopes that arrive within
  a short window (``HOROVOD_CTRL_TICK_WINDOW_S``) ride one upstream
  ``batch_exchange``; the coordinator ingests them all before its bounded
  wait, and response fields identical across the host (knob table, plane
  epochs) come back hoisted once and are re-inflated here. This is NOT a
  local barrier — an idle rank delays nobody; a lone envelope simply
  ships alone after the window.
- ``ring_hello`` / ``ring_confirm`` — true local barriers: the engine's
  establishment rounds are world barriers anyway (the coordinator answers
  after ALL ranks arrive), so waiting for the host's full complement
  (declared in ``relay_hello``) costs nothing and sends one
  ``batch_ring_*`` per host. The shared verdict fans back out locally,
  keeping the all-or-nothing activation property bit-identical.
- ``plane_fault`` / ``knob_change`` / ``clock_probe`` — forwarded
  one-for-one; these are rare (fault paths) or latency-calibrating (the
  probe brackets its own round trip, the extra hop only widens its error
  bound).

Liveness is preserved across the extra hop: the relay declares its ranks
upstream via ``relay_hello``, so an unclean RELAY drop fails the whole
host at the coordinator (the host is the failure domain), and an unclean
LOCAL drop is reported as ``peer_lost`` so the coordinator fails exactly
that rank — the same rung-3 semantics a flat connection gives. If the
upstream dies, every local connection is closed so ranks escalate into
the elastic reset path immediately.

Barriers share the primary upstream connection. A ring barrier can hold
it for up to 120 s at the coordinator, but the engine only runs barriers
while every local rank is parked INSIDE the same barrier — no exchange
traffic exists to block behind it, and the occasional clock probe just
waits (its socket timeout outlasts the barrier window).
"""

from __future__ import annotations

import os
import pickle
import socket
import threading
from typing import Any, Optional

from ..common.engine import _recv_msg, _send_msg
from ..common.protocol import COORD_WIRE_KINDS
from ..metrics import registry as _metrics_registry
from ..utils.logging import log

# The kinds this relay treats specially (coalesced, barriered, or
# consumed); everything else in the coordinator's dispatch alphabet is
# forwarded one-for-one. Guarded against COORD_WIRE_KINDS so a kind
# renamed or split in _Coordinator._serve fails HERE at import, not as a
# silent pass-through that defeats the batching.
_RELAY_SPECIAL_KINDS = ("exchange", "ring_hello", "ring_confirm",
                        "relay_hello", "bye")
if not set(_RELAY_SPECIAL_KINDS) <= set(COORD_WIRE_KINDS):
    raise AssertionError(
        f"ctrl relay special-cases {set(_RELAY_SPECIAL_KINDS) - set(COORD_WIRE_KINDS)} "
        "which the coordinator no longer dispatches — update ctrl/relay.py "
        "to match common/protocol.py COORD_WIRE_KINDS")


def _wire_size(obj: Any) -> int:
    """Bytes this object would have cost as its own wire frame (payload +
    length prefix + HMAC tag) — the accounting unit for ``absorbed``."""
    return len(pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)) + 40


def tick_window_s() -> float:
    """Coalescing window for exchange envelopes (seconds)."""
    try:
        v = float(os.environ.get("HOROVOD_CTRL_TICK_WINDOW_S", "0.005"))
    except ValueError:
        v = 0.005
    return max(0.0, v)


class CoordRelay:
    """Loopback control-plane relay for one job's local ranks."""

    def __init__(self, key: bytes, host: str = "127.0.0.1", port: int = 0,
                 window_s: Optional[float] = None) -> None:
        self.key = key
        self.window_s = tick_window_s() if window_s is None else window_s
        self._stop = threading.Event()
        # Upstream (coordinator) state, re-established per generation: the
        # elastic reset rebuilds the coordinator at a NEW address, and the
        # fresh local clients announce it in their relay_hello.
        self._up_lock = threading.Lock()      # serializes primary-socket RPCs
        self._up: Optional[socket.socket] = None
        self._coord: Optional[tuple[str, int]] = None
        self._declared: set[int] = set()      # ranks declared upstream
        # Local membership: rank -> its connection, plus each rank's claim
        # of the host's full complement (for the ring barriers).
        self._state = threading.Condition()
        self._conns: dict[int, socket.socket] = {}
        self._local: int = 1
        # Exchange coalescing batch (leader/follower, like a bakery queue):
        # {"items": [(rank, envelope)], "out": {rank: resp}, "done": Event,
        #  "closed": bool, "error": Optional[str]}
        self._batch: Optional[dict] = None
        # Ring barrier aggregation, one per kind in flight at a time.
        self._barrier: dict[str, dict] = {}
        reg = _metrics_registry()
        self._m_up_out = reg.counter(
            "horovod_ctrl_bytes_total",
            help="Control-plane bytes by direction (up_out/up_in at host "
                 "agents, absorbed = rank requests answered locally, "
                 "hoisted = response bytes deduplicated by batching).",
            dir="up_out")
        self._m_absorbed = reg.counter(
            "horovod_ctrl_bytes_total",
            help="Control-plane bytes by direction (up_out/up_in at host "
                 "agents, absorbed = rank requests answered locally, "
                 "hoisted = response bytes deduplicated by batching).",
            dir="absorbed")
        self._listener = socket.create_server((host, port))
        self.host, self.port = self._listener.getsockname()[:2]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="ctrl-relay-accept", daemon=True)
        self._accept_thread.start()

    # -- lifecycle

    def stop(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._up_lock:
            self._close_up(clean=True)
        with self._state:
            for conn in self._conns.values():
                try:
                    conn.close()
                except OSError:
                    pass
            self._conns.clear()
            self._state.notify_all()

    def _close_up(self, clean: bool) -> None:
        """Drop the primary upstream (caller holds _up_lock)."""
        if self._up is not None:
            try:
                if clean:
                    _send_msg(self._up, {"kind": "bye"}, self.key)
            except OSError:
                pass
            try:
                self._up.close()
            except OSError:
                pass
        self._up = None
        self._declared.clear()

    # -- local side

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _addr = self._listener.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             name="ctrl-relay-conn", daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        rank: Optional[int] = None
        clean = False
        try:
            while not self._stop.is_set():
                msg = _recv_msg(conn, self.key)
                kind = msg.get("kind")
                if kind == "relay_hello":
                    rank = int(msg["rank"])
                    with self._state:
                        old = self._conns.get(rank)
                        self._conns[rank] = conn
                        if msg.get("local"):
                            self._local = max(1, int(msg["local"]))
                        self._state.notify_all()
                    if old is not None and old is not conn:
                        # Stale connection from a previous generation of
                        # this rank: retire it quietly (no peer_lost — the
                        # rank is alive, right here).
                        try:
                            old.close()
                        except OSError:
                            pass
                    coord = msg.get("coord")
                    if coord:
                        self._ensure_up((str(coord[0]), int(coord[1])))
                    self._declare_ranks()
                    _send_msg(conn, {"ok": 1}, self.key)
                elif kind == "exchange":
                    _send_msg(conn, self._relay_exchange(msg), self.key)
                elif kind in ("ring_hello", "ring_confirm"):
                    _send_msg(conn, self._relay_barrier(kind, msg), self.key)
                elif kind == "bye":
                    clean = True
                    return
                else:
                    # plane_fault / knob_change / clock_probe and anything
                    # future: one-for-one forwarding preserves semantics.
                    _send_msg(conn, self._upstream(msg), self.key)
        except (ConnectionError, EOFError, OSError):
            return
        finally:
            try:
                conn.close()
            except OSError:
                pass
            if rank is not None:
                with self._state:
                    if self._conns.get(rank) is conn:
                        del self._conns[rank]
                        self._state.notify_all()
                    else:
                        rank = None  # superseded connection: not a loss
            if rank is not None and not clean and not self._stop.is_set():
                # Exactly the flat-mode rung-3 signal, one rank wide.
                try:
                    self._upstream({"kind": "peer_lost", "lost": rank})
                except (ConnectionError, EOFError, OSError):
                    pass

    # -- upstream side

    def _ensure_up(self, coord: tuple[str, int]) -> None:
        with self._up_lock:
            if self._coord == coord and self._up is not None:
                return
            # New coordinator generation: drop the old upstream and any
            # coalescing state that referenced it.
            self._close_up(clean=True)
            self._coord = coord
        with self._state:
            self._batch = None
            self._barrier.clear()
            self._state.notify_all()

    def _dial(self) -> socket.socket:
        """Connect the primary upstream (caller holds _up_lock)."""
        if self._coord is None:
            raise ConnectionError("relay has no coordinator address yet")
        sock = socket.create_connection(self._coord, timeout=60)
        sock.settimeout(180)
        return sock

    def _declare_ranks(self) -> None:
        """Tell the coordinator which ranks live behind this connection —
        the unclean-drop failure domain (engine _serve relay_for)."""
        with self._state:
            ranks = set(self._conns)
        with self._up_lock:
            if not ranks - self._declared and self._up is not None:
                return
            try:
                if self._up is None:
                    self._up = self._dial()
                    self._declared.clear()
                self._m_up_out.inc(_send_msg(
                    self._up, {"kind": "relay_hello",
                               "ranks": sorted(ranks)}, self.key))
                _recv_msg(self._up, self.key)
                self._declared = ranks
            except (ConnectionError, EOFError, OSError) as e:
                self._upstream_lost(e)
                raise

    def _upstream(self, msg: dict) -> Any:
        """One request/response on the primary upstream connection."""
        with self._up_lock:
            try:
                if self._up is None:
                    self._up = self._dial()
                    self._declared.clear()
                self._m_up_out.inc(_send_msg(self._up, msg, self.key))
                return _recv_msg(self._up, self.key)
            except (ConnectionError, EOFError, OSError) as e:
                self._upstream_lost(e)
                raise

    def _upstream_lost(self, err: Exception) -> None:
        """Primary upstream died (caller holds _up_lock): close every local
        connection so ranks fail fast into the elastic reset instead of
        hanging on a relay that can no longer deliver."""
        self._close_up(clean=False)
        log("warning", f"[ctrl] relay lost its coordinator ({err}); "
                       "failing local control connections")
        with self._state:
            conns = list(self._conns.values())
            self._conns.clear()
            self._batch = None
            self._barrier.clear()
            self._state.notify_all()
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass

    # -- exchange coalescing

    def _relay_exchange(self, msg: dict) -> dict:
        """Coalesce co-arriving exchange envelopes into one upstream
        batch_exchange; re-inflate hoisted response fields per rank."""
        item = {k: v for k, v in msg.items() if k != "kind"}
        rank = int(msg["rank"])
        with self._state:
            batch = self._batch
            if batch is None or batch["closed"]:
                batch = self._batch = {"items": [], "out": {}, "closed": False,
                                       "error": None,
                                       "done": threading.Event()}
                leader = True
            else:
                leader = False
            batch["items"].append((rank, item))
        if leader:
            if self.window_s > 0:
                self._stop.wait(self.window_s)
            with self._state:
                batch["closed"] = True
                if self._batch is batch:
                    self._batch = None
                items = list(batch["items"])
            try:
                resp = self._upstream({"kind": "batch_exchange",
                                       "items": [it for _r, it in items]})
                out_items = resp["items"]
                for field in ("knob", "plane"):
                    if field in resp:
                        for it in out_items:
                            it[field] = resp[field]
                for (r, _req), it in zip(items, out_items):
                    batch["out"][r] = it
                if len(items) > 1:
                    # Every envelope after the first rode the leader's
                    # upstream tick instead of its own root connection.
                    self._m_absorbed.inc(sum(
                        _wire_size(it) for _r, it in items[1:]))
            except (ConnectionError, EOFError, OSError) as e:
                batch["error"] = str(e)
            finally:
                batch["done"].set()
        else:
            batch["done"].wait(180.0)
        if batch["error"] is not None or rank not in batch["out"]:
            raise ConnectionError(
                batch["error"] or "relay batch lost this rank's response")
        return batch["out"][rank]

    # -- ring barriers

    def _relay_barrier(self, kind: str, msg: dict) -> dict:
        """Local-host barrier for ring_hello / ring_confirm: gather the
        host's full complement, one upstream batch, shared verdict out."""
        rank = int(msg["rank"])
        item = {k: v for k, v in msg.items() if k != "kind"}
        with self._state:
            bar = self._barrier.get(kind)
            if bar is None or bar["closed"]:
                bar = self._barrier[kind] = {
                    "items": {}, "shared": None, "closed": False,
                    "error": None, "done": threading.Event()}
            bar["items"][rank] = item
            leader = len(bar["items"]) == 1
            self._state.notify_all()
            if leader:
                # Wait for the host's declared complement; on timeout ship
                # what arrived — the coordinator's own 120 s world barrier
                # resolves stragglers (or fails establishment world-wide,
                # exactly as flat mode would).
                deadline = 115.0
                while (len(bar["items"]) < self._local
                       and not self._stop.is_set() and deadline > 0):
                    self._state.wait(0.2)
                    deadline -= 0.2
                bar["closed"] = True
                if self._barrier.get(kind) is bar:
                    del self._barrier[kind]
                items = [bar["items"][r] for r in sorted(bar["items"])]
        if leader:
            try:
                resp = self._upstream({"kind": "batch_" + kind,
                                       "items": items})
                bar["shared"] = resp["shared"]
                if len(items) > 1:
                    self._m_absorbed.inc(sum(
                        _wire_size(it) for it in items[1:]))
            except (ConnectionError, EOFError, OSError) as e:
                bar["error"] = str(e)
            finally:
                bar["done"].set()
        else:
            bar["done"].wait(150.0)
        if bar["error"] is not None or bar["shared"] is None:
            raise ConnectionError(
                bar["error"] or f"relay {kind} barrier did not resolve")
        return bar["shared"]
