"""Control-tree shape and knobs (ISSUE 18).

The grouping itself is the telemetry tree's plan verbatim
(telemetry/tree.py plan_tree): ranks on one host are contiguous, the
lowest rank on each host leads it, and re-using the SAME plan means a
membership change moves the telemetry leader, the control leader, and
the hier data plane's host representative together.

What is new here is the *decision*: the control tree only pays for
itself when there are multiple hosts, and silently routing a
single-host or 2-rank job through an extra hop would be pure overhead
— so :func:`use_tree` falls back to the flat star LOUDLY (one warning
naming the reason) whenever no host grouping exists.
"""

from __future__ import annotations

import os

from ..utils.logging import log
from ..telemetry.tree import TreePlan, plan_tree  # noqa: F401  (re-export)

#: smallest world the tree is worth a hop for: at world <= 2 every
#: grouping is degenerate (one rank per host or one host total).
MIN_TREE_WORLD = 3


def tree_enabled() -> bool:
    """``HOROVOD_CTRL_TREE`` (default 1): route control traffic through
    per-host leaders when a host grouping exists. 0 forces the flat
    rank-to-root star everywhere."""
    return os.environ.get("HOROVOD_CTRL_TREE", "1") not in ("0", "false")


def ctrl_poll_s() -> float:
    """``HOROVOD_CTRL_POLL_S`` (seconds, default 1.0): how long a host
    leader's cached elastic-poll verdict stays fresh — every local rank
    polling within the window is answered from cache, so the root sees
    one poll per host per interval. Floored at 50 ms."""
    raw = os.environ.get("HOROVOD_CTRL_POLL_S", "")
    try:
        val = float(raw) if raw else 1.0
    except ValueError:
        val = 1.0
    return max(val, 0.05)


def ctrl_batch_s() -> float:
    """``HOROVOD_CTRL_BATCH_S`` (seconds, default 0.05): the leader's
    aggregation window — registrations and wait-assignment arrivals
    from local ranks within one window ride a single upstream request.
    Floored at 1 ms so a typo can't busy-spin the agent."""
    raw = os.environ.get("HOROVOD_CTRL_BATCH_S", "")
    try:
        val = float(raw) if raw else 0.05
    except ValueError:
        val = 0.05
    return max(val, 0.001)


def use_tree(num_hosts: int, world: int) -> bool:
    """The one gate every tree entry point shares: True when the control
    tree should carry this job's traffic. Falls back to flat LOUDLY —
    the operator reading logs must be able to tell which plane shape a
    job ran with, because the O(hosts) scaling claim only holds on the
    tree path."""
    if not tree_enabled():
        log("warning", "[ctrl] HOROVOD_CTRL_TREE=0: control tree disabled, "
            f"using flat rank-to-root control plane ({world} root "
            "connections)")
        return False
    if num_hosts <= 1:
        log("warning", "[ctrl] single-host job: no host grouping to fan "
            "control traffic through — using flat control plane")
        return False
    if world < MIN_TREE_WORLD:
        log("warning", f"[ctrl] world {world} <= 2: host grouping is "
            "degenerate — using flat control plane")
        return False
    return True
