"""Roofline measurement from the XLA device profile.

Answers "is this step compute- or HBM-bound?" with measured numbers
instead of assertions (VERDICT r3 weak #1): run a step under
``jax.profiler.trace``, parse the trace's per-op ``bytes_accessed`` /
``model_flops`` / ``device_duration_ps`` fields, and aggregate achieved
bandwidth and FLOP rate per HLO category.

Caveats, stated once here and echoed in docs/benchmarks.md: XLA's
``bytes_accessed`` is the compiler's MODEL of memory traffic (operand +
output bytes per op), not a DRAM counter — ops whose operands sit in
VMEM/SMEM can "exceed" the HBM roof, and re-read operands are counted per
op. The per-category rates over multi-millisecond windows are still the
standard roofline evidence: a category sustaining ~90% of nominal HBM
bandwidth for most of the step IS bandwidth-bound.
"""

from __future__ import annotations

import collections
import glob
import gzip
import json
import os
import tempfile
from typing import Callable, Optional

# Nominal v5e numbers for the "% of roof" columns (public spec).
V5E_HBM_GBS = 819.0
V5E_BF16_TFLOPS = 197.0


def _load_latest_trace(logdir: str) -> list:
    paths = sorted(glob.glob(os.path.join(logdir, "**", "*.trace.json.gz"),
                             recursive=True))
    if not paths:
        raise FileNotFoundError(f"no trace.json.gz under {logdir}")
    with gzip.open(paths[-1]) as f:
        return json.load(f)["traceEvents"]


def profile_device_ops(run_step: Callable[[], None], steps: int = 5,
                       sync: Optional[Callable[[], None]] = None,
                       logdir: Optional[str] = None) -> dict:
    """Profile ``steps`` calls of ``run_step`` and aggregate device ops.

    The caller must have warmed the step (compile outside the trace).
    Returns a report dict; ``ok=False`` with a reason when the platform's
    trace carries no per-op cost fields (e.g. CPU)."""
    import jax

    fence = sync or (lambda: None)
    logdir = logdir or tempfile.mkdtemp(prefix="hvd_roofline_")
    with jax.profiler.trace(logdir):
        for _ in range(steps):
            run_step()
        fence()
    ev = _load_latest_trace(logdir)
    pids = {e["pid"]: e["args"].get("name", "")
            for e in ev if e.get("ph") == "M" and e.get("name") == "process_name"
            and "args" in e}

    cat = collections.defaultdict(lambda: [0.0, 0, 0])   # t_s, bytes, flops
    ops = collections.defaultdict(lambda: [0.0, 0, 0])
    tot_t = 0.0
    tot_b = tot_f = 0
    for e in ev:
        if e.get("ph") != "X":
            continue
        a = e.get("args") or {}
        if "device_duration_ps" not in a:
            continue
        pname = pids.get(e["pid"], "")
        if "TPU" not in pname:
            continue
        c = a.get("hlo_category")
        if c is None:
            continue  # envelopes (jit_..., per-step frames) — no cost fields
        t = int(a["device_duration_ps"]) / 1e12
        b = int(a.get("bytes_accessed", 0))
        f = int(a.get("model_flops", 0) or 0)
        for table, key in ((cat, c), (ops, a.get("tf_op", e["name"]))):
            table[key][0] += t
            table[key][1] += b
            table[key][2] += f
        tot_t += t
        tot_b += b
        tot_f += f
    if tot_t == 0:
        return {"ok": False,
                "reason": "no TPU device ops with cost fields in trace "
                          f"(tracks: {sorted(set(pids.values()))})"}

    def row(key, t, b, f):
        return {
            "name": key,
            "ms_per_step": round(t / steps * 1e3, 3),
            "gbs": round(b / t / 1e9, 1) if t else 0.0,
            "pct_hbm_roof": round(b / t / 1e9 / V5E_HBM_GBS * 100, 1) if t else 0.0,
            "tflops": round(f / t / 1e12, 2) if t else 0.0,
        }

    categories = [row(k, *v) for k, v in
                  sorted(cat.items(), key=lambda kv: -kv[1][0])]
    top_ops = [row(k, *v) for k, v in
               sorted(ops.items(), key=lambda kv: -kv[1][0])[:12]]
    return {
        "ok": True,
        "steps": steps,
        "device_ms_per_step": round(tot_t / steps * 1e3, 2),
        "model_bytes_gb_per_step": round(tot_b / steps / 1e9, 2),
        "achieved_gbs": round(tot_b / tot_t / 1e9, 1),
        "pct_hbm_roof": round(tot_b / tot_t / 1e9 / V5E_HBM_GBS * 100, 1),
        "model_tflop_per_step": round(tot_f / steps / 1e12, 3),
        "achieved_tflops": round(tot_f / tot_t / 1e12, 1),
        "categories": categories,
        "top_ops": top_ops,
        "logdir": logdir,
    }


def format_report(rep: dict) -> str:
    if not rep.get("ok"):
        return f"roofline: unavailable ({rep.get('reason')})"
    lines = [
        f"device busy {rep['device_ms_per_step']} ms/step | "
        f"XLA-model bytes {rep['model_bytes_gb_per_step']} GB/step | "
        f"achieved {rep['achieved_gbs']} GB/s "
        f"({rep['pct_hbm_roof']}% of v5e HBM) | "
        f"{rep['achieved_tflops']} TFLOP/s "
        f"({round(rep['achieved_tflops'] / V5E_BF16_TFLOPS * 100, 1)}% of bf16 peak)",
        f"{'category':<24}{'ms/step':>9}{'GB/s':>8}{'%roof':>7}{'TFLOP/s':>9}",
    ]
    for r in rep["categories"]:
        if r["ms_per_step"] < 0.01:
            continue
        lines.append(f"{r['name']:<24}{r['ms_per_step']:>9}{r['gbs']:>8}"
                     f"{r['pct_hbm_roof']:>7}{r['tflops']:>9}")
    return "\n".join(lines)
