"""Logging mirroring the reference's LOG(severity[, rank]) macros
(horovod/common/logging.h:37-55, logging.cc:76-90).

Levels trace..fatal selected by HOROVOD_LOG_LEVEL; timestamps suppressed by
HOROVOD_LOG_HIDE_TIME. Python-side counterpart of the native logger in
horovod_tpu/cc/logging.cc — both honour the same env vars.
"""

from __future__ import annotations

import logging as _pylog
import os
import sys
import time

TRACE = 5
_pylog.addLevelName(TRACE, "TRACE")

_LEVELS = {
    "trace": TRACE,
    "debug": _pylog.DEBUG,
    "info": _pylog.INFO,
    "warning": _pylog.WARNING,
    "error": _pylog.ERROR,
    "fatal": _pylog.CRITICAL,
}


class _HvdFormatter(_pylog.Formatter):
    def __init__(self, hide_time: bool):
        super().__init__()
        self.hide_time = hide_time

    def format(self, record: _pylog.LogRecord) -> str:
        rank = getattr(record, "hvd_rank", None)
        prefix = f"[{record.levelname[0]}"
        if not self.hide_time:
            t = time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(record.created))
            prefix += f" {t}.{int(record.msecs):03d}"
        if rank is not None:
            prefix += f" rank {rank}"
        prefix += "]"
        return f"{prefix} {record.getMessage()}"


_logger: _pylog.Logger | None = None


def get_logger() -> _pylog.Logger:
    global _logger
    if _logger is None:
        _logger = _pylog.getLogger("horovod_tpu")
        level = _LEVELS.get(os.environ.get("HOROVOD_LOG_LEVEL", "warning").lower(), _pylog.WARNING)
        _logger.setLevel(level)
        handler = _pylog.StreamHandler(sys.stderr)
        # `or ""`: unset means the config.py default (False) — the two-arg
        # get() form would register a second default for the knob
        hide_time = (os.environ.get("HOROVOD_LOG_HIDE_TIME") or "").lower() \
            not in ("", "0", "false")
        handler.setFormatter(_HvdFormatter(hide_time))
        _logger.addHandler(handler)
        _logger.propagate = False
    return _logger


def log(level: str, msg: str, rank: int | None = None) -> None:
    lv = _LEVELS.get(level.lower(), _pylog.INFO)
    get_logger().log(lv, msg, extra={"hvd_rank": rank})
