"""horovod_tpu.utils"""
