"""Horovod Timeline — Chrome-tracing (catapult) JSON of per-tensor collective
phases, written by rank 0 only (reference horovod/common/timeline.{cc,h};
docs/timeline.md).

Mechanism mirrors the reference: events go into a queue drained by a dedicated
writer thread (TimelineWriter::WriterLoop, timeline.cc:120-146); the main path
never blocks on file IO. Phases per tensor: NEGOTIATE_<OP> (instant events per
reporting rank), then <OP> with nested activity spans (WAIT_FOR_DATA,
MEMCPY_IN_FUSION_BUFFER, ..., operations.h:29-50). Optional cycle markers via
HOROVOD_TIMELINE_MARK_CYCLES (timeline.h:93 MarkCycleStart).

On-device time is XLA's domain: pair this host-side timeline with the JAX/TPU
profiler (jax.profiler.trace) for kernel-level spans.

Span-schema upgrade (ISSUE 6, docs/tracing.md): the emitters accept an
optional ``tid`` — the pod-wide trace ID minted at enqueue — and attach it
as ``args.trace_id`` on the Chrome events, so this per-rank timeline can be
joined against the merged pod trace (horovod_tpu/tracing) by ID. Fully
backward compatible: with ``tid=None`` (the default) the events are
byte-identical to the pre-tracing schema.
"""

from __future__ import annotations

import json
import queue
import threading
import time
from typing import Optional


class Timeline:
    def __init__(self, path: str, mark_cycles: bool = False) -> None:
        self.path = path
        self.mark_cycles_enabled = mark_cycles
        self._q: queue.Queue = queue.Queue(maxsize=1 << 20)  # capacity mirrors timeline.h:66-68
        self._stop = threading.Event()
        self._t0 = time.monotonic()
        self._tensor_pids: dict[str, int] = {}
        self._next_pid = 1
        self._lock = threading.Lock()
        # Backpressure policy: the hot path never blocks on file IO — a full
        # queue sheds the event and COUNTS the shed (docs/timeline.md), so a
        # gappy trace is diagnosable instead of silently incomplete.
        from ..metrics import registry as _metrics_registry

        self._dropped = _metrics_registry().counter(
            "horovod_timeline_dropped_total",
            help="timeline events dropped because the writer queue was "
                 "full or the writer failed")
        self._thread = threading.Thread(target=self._writer_loop, name="hvd_timeline", daemon=True)
        self._thread.start()

    @property
    def dropped(self) -> int:
        return int(self._dropped.value)

    # -- event emission (Timeline::NegotiateStart/Start/ActivityStart/End, timeline.h:83-93)

    def _ts_us(self) -> int:
        return int((time.monotonic() - self._t0) * 1e6)

    def _pid(self, name: str) -> int:
        with self._lock:
            if name not in self._tensor_pids:
                pid = self._next_pid
                self._next_pid += 1
                self._tensor_pids[name] = pid
                self._emit({"name": "process_name", "ph": "M", "pid": pid,
                            "args": {"name": name}})
            return self._tensor_pids[name]

    def _emit(self, ev: dict) -> None:
        try:
            self._q.put_nowait(ev)
        except queue.Full:  # drop rather than block the hot path
            self._dropped.inc()

    @staticmethod
    def _with_tid(ev: dict, tid) -> dict:
        if tid is not None:
            ev["args"] = {"trace_id": tid}
        return ev

    def negotiate_start(self, name: str, op: str, tid=None) -> None:
        pid = self._pid(name)
        self._emit(self._with_tid(
            {"name": f"NEGOTIATE_{op}", "ph": "B", "pid": pid, "tid": 0,
             "ts": self._ts_us()}, tid))

    def negotiate_rank_ready(self, name: str, rank: int) -> None:
        pid = self._pid(name)
        self._emit({"name": str(rank), "ph": "i", "pid": pid, "tid": 0,
                    "ts": self._ts_us(), "s": "p"})

    def negotiate_end(self, name: str) -> None:
        pid = self._pid(name)
        self._emit({"name": "", "ph": "E", "pid": pid, "tid": 0, "ts": self._ts_us()})

    def start(self, name: str, op: str, tid=None) -> None:
        self.negotiate_end(name)
        pid = self._pid(name)
        self._emit(self._with_tid(
            {"name": op, "ph": "B", "pid": pid, "tid": 0,
             "ts": self._ts_us()}, tid))

    def activity_start(self, name: str, activity: str) -> None:
        pid = self._pid(name)
        self._emit({"name": activity, "ph": "B", "pid": pid, "tid": 1, "ts": self._ts_us()})

    def activity_end(self, name: str) -> None:
        pid = self._pid(name)
        self._emit({"name": "", "ph": "E", "pid": pid, "tid": 1, "ts": self._ts_us()})

    def end(self, name: str) -> None:
        pid = self._pid(name)
        self._emit({"name": "", "ph": "E", "pid": pid, "tid": 0, "ts": self._ts_us()})

    def mark_cycle(self) -> None:
        if self.mark_cycles_enabled:
            self._emit({"name": "CYCLE_START", "ph": "i", "pid": 0, "tid": 0,
                        "ts": self._ts_us(), "s": "g"})

    # -- writer thread

    def _writer_loop(self) -> None:
        # An unwritable path (bad HOROVOD_TIMELINE, disk full) must not kill
        # the thread silently: the trace degrades to counted drops and the
        # engine keeps running — telemetry never takes the job down.
        try:
            f = open(self.path, "w")
        except OSError:
            while not (self._stop.is_set() and self._q.empty()):
                try:
                    self._q.get(timeout=0.1)
                    self._dropped.inc()
                except queue.Empty:
                    continue
            return
        with f:
            f.write("[\n")
            first = True
            while not (self._stop.is_set() and self._q.empty()):
                try:
                    ev = self._q.get(timeout=0.1)
                except queue.Empty:
                    continue
                try:
                    if not first:
                        f.write(",\n")
                    f.write(json.dumps(ev))
                    first = False
                    f.flush()
                except OSError:  # disk full mid-trace: shed and count
                    self._dropped.inc()
            f.write("\n]\n")

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)


import contextlib as _contextlib  # noqa: E402


@_contextlib.contextmanager
def trace(log_dir: str, mark_cycles: bool = False):
    """Both panes of "where did the step go" in ONE directory (VERDICT r2
    missing #5; reference analog: the timeline instruments its hot path end
    to end, timeline.h:83-93 — here the hot path is split between host
    engine and XLA device, so one artifact needs both recorders):

    - device pane: ``jax.profiler.trace(log_dir)`` captures the XLA profile
      of every jitted step run inside the context (per-op device time,
      collective latencies, HBM; open with tensorboard or Perfetto);
    - host pane: ``<log_dir>/host_timeline.json`` gets the eager engine's
      catapult timeline for the same interval. If the engine already writes
      one (HOROVOD_TIMELINE), that file keeps recording and is left alone;
      otherwise a timeline is attached for the scope (rank 0 writes, like
      the reference).

    Usage::

        with hvd.timeline.trace("/tmp/step_profile"):
            for _ in range(10):
                state = step(state, batch)
            jax.block_until_ready(state)
    """
    import os

    from ..common import basics

    os.makedirs(log_dir, exist_ok=True)
    host_path = os.path.join(log_dir, "host_timeline.json")
    owned = 0
    if basics.is_initialized():
        eng = basics.engine()
        if hasattr(eng, "timeline_start"):
            owned = eng.timeline_start(host_path, mark_cycles)
    import jax

    try:
        with jax.profiler.trace(log_dir):
            yield log_dir
    finally:
        if owned:
            basics.engine().timeline_stop()
