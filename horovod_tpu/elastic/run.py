"""``hvd.elastic.run`` — the worker-side reset loop.

Wrap the training function; it gains survive-and-resume semantics:

    @hvd.elastic.run
    def train(state):
        while state.step < TOTAL:
            ... collectives ...
            state.step += 1
            state.commit()

    hvd.runner.run_elastic(train, args=(state,), num_proc=4)

On a collective failure — a peer died (connection error to the
coordinator), the PR 2 stall watchdog escalated a hung collective
(``HOROVOD_STALL_SHUTDOWN_TIME``), or the driver signalled a membership
change (:class:`HostsUpdatedInterrupt` out of ``state.commit()``) — the
wrapper:

1. tears the communicator down (``hvd.shutdown()``);
2. rolls the state back to the last commit (skipped for the clean
   host-update interrupt, which is raised post-commit);
3. re-registers with the driver and blocks for the next generation's
   rendezvous (new rank/size/coordinator, exported into env);
4. re-initializes, adopts the survivors' committed state
   (``state.sync()``), and re-enters the training function.

A worker the driver dropped (its host blacklisted, or scaled away) gets
:class:`WorkerRemovedError` from the rendezvous and exits instead of
spinning. Everything else — a genuine bug in the training function —
propagates unchanged: elastic recovery is for infrastructure failures,
not for exceptions resets cannot fix.
"""

from __future__ import annotations

import functools
import os
import time
from typing import Any, Callable, Optional

from ..common.engine import HorovodInternalError
from ..metrics import registry as _registry
from ..utils.logging import log
from .state import ElasticState, HostsUpdatedInterrupt

# Failures a reset can heal. HorovodInternalError covers the watchdog's
# stall-shutdown escalation, coordinator connection loss, and shape
# mismatches surfaced as engine errors on a torn world.
RESETTABLE = (HorovodInternalError, HostsUpdatedInterrupt, ConnectionError)

_context: Optional["_WorkerContext"] = None


def _require_worker_removed():
    from ..runner.service import WorkerRemovedError

    return WorkerRemovedError


class _WorkerContext:
    """This worker's line to the elastic driver: rate-limited membership
    polls (state.commit) and the blocking re-rendezvous on reset."""

    def __init__(self, index: int, addresses, secret: bytes) -> None:
        self.index = index
        self.addresses = addresses
        self.secret = secret
        self._agent = None
        self._last_poll = 0.0
        self.poll_interval_s = float(
            os.environ.get("HOROVOD_ELASTIC_POLL_S", "") or 1.0)

    @classmethod
    def from_env(cls) -> Optional["_WorkerContext"]:
        if os.environ.get("HOROVOD_ELASTIC") != "1":
            return None
        from ..runner.service import worker_addresses

        addrs = worker_addresses()  # host ControlAgent or driver (ISSUE 18)
        secret = os.environ.get("HOROVOD_SECRET")
        index = os.environ.get("HOROVOD_TASK_INDEX")
        if not addrs or not secret or index is None:
            return None
        return cls(int(index), addrs, bytes.fromhex(secret))

    @property
    def generation(self) -> int:
        return int(os.environ.get("HOROVOD_ELASTIC_GENERATION", "0"))

    def _task_agent(self):
        if self._agent is None:
            from ..runner.service import TaskAgent

            self._agent = TaskAgent(self.index, self.addresses, self.secret)
        return self._agent

    def _drop_agent(self) -> None:
        if self._agent is not None:
            try:
                self._agent.client.close()
            except OSError:
                pass
            self._agent = None

    def poll_reset_required(self) -> bool:
        """Cheap driver poll, at most once per ``poll_interval_s``. Errors
        (driver briefly busy) read as 'no change' — a real membership
        change also surfaces as a collective failure soon enough."""
        now = time.monotonic()
        if now - self._last_poll < self.poll_interval_s:
            return False
        self._last_poll = now
        try:
            resp = self._task_agent().client.request({
                "kind": "elastic_poll", "index": self.index,
                "generation": self.generation})
            return bool(resp.get("reset_required"))
        except (ConnectionError, OSError):
            self._drop_agent()
            return False

    def rendezvous(self, timeout: float = 300.0) -> dict:
        """Blocking re-registration; exports the new assignment into env
        (rank/size/coordinator/generation). Raises WorkerRemovedError when
        the driver dropped this slot."""
        min_gen = self.generation + 1
        try:
            return self._task_agent().rendezvous(min_gen, timeout=timeout)
        except (ConnectionError, OSError):
            # stale connection from before the failure: reconnect once
            self._drop_agent()
            return self._task_agent().rendezvous(min_gen, timeout=timeout)


def poll_host_updates() -> bool:
    """Hook for ``ElasticState.commit``: True when the driver wants a reset
    (membership changed). False outside an elastic worker."""
    return _context.poll_reset_required() if _context is not None else False


def run(fn: Callable) -> Callable:
    """Decorator: make ``fn(state, *args, **kwargs)`` survive worker loss
    via reset/restore/re-rendezvous (module docstring). The first positional
    argument must be an :class:`ElasticState`."""

    @functools.wraps(fn)
    def wrapper(state: ElasticState, *args: Any, **kwargs: Any) -> Any:
        global _context
        from ..common import basics

        ctx = _WorkerContext.from_env()
        _context = ctx
        reg = _registry()
        resets = reg.counter("horovod_elastic_resets_total",
                             help="elastic resets survived by this worker")
        gen_gauge = reg.gauge("horovod_elastic_generation",
                              help="current elastic rendezvous generation")
        reset_hist = reg.histogram(
            "horovod_elastic_reset_seconds",
            help="failure-to-resumed wall time per elastic reset",
            buckets=(0.1, 0.5, 1, 2, 5, 10, 30, 60, 120, 300))
        max_resets = int(os.environ.get("HOROVOD_ELASTIC_MAX_RESETS", "")
                         or 100)
        if ctx is not None and os.environ.get("HOROVOD_JAX_DISTRIBUTED") == "1":
            log("warning",
                "elastic mode cannot re-form the JAX distributed runtime "
                "after a membership change; jitted cross-process collectives "
                "will not survive a reset (eager-engine collectives do)")
        WorkerRemovedError = _require_worker_removed()
        performed = 0
        try:
            while True:
                try:
                    if not basics.is_initialized():
                        basics.init()
                    gen_gauge.set(ctx.generation if ctx else 0)
                    # EVERY generation entry syncs — including this worker's
                    # first: a worker that just JOINED an in-flight job must
                    # participate in the survivors' committed-state broadcast,
                    # or the world deadlocks with survivors in sync() and the
                    # newcomer already in the training loop.
                    if ctx is not None and basics.size() > 1:
                        state.sync(root_rank=0)
                    return fn(state, *args, **kwargs)
                except RESETTABLE as exc:
                    if ctx is None:
                        # No elastic launcher behind us: nothing to
                        # rendezvous with — surface the failure.
                        raise
                    performed += 1
                    if performed > max_resets:
                        raise HorovodInternalError(
                            f"elastic worker exceeded "
                            f"HOROVOD_ELASTIC_MAX_RESETS={max_resets}"
                        ) from exc
                    t0 = time.monotonic()
                    rollback = not isinstance(exc, HostsUpdatedInterrupt)
                    log("warning",
                        f"elastic reset {performed}: "
                        f"{type(exc).__name__}: {exc}; "
                        f"{'rolling back to last commit' if rollback else 'state already committed'}"
                        " and re-rendezvousing")
                    # Response-cache flush FIRST, explicitly, on every rank:
                    # a bit bound under the old membership must never serve
                    # a negotiation in the new one. shutdown() also tears
                    # the engine (and with it both cache halves) down, but
                    # the order matters if teardown is interrupted — a
                    # flushed cache is safe even when the engine object
                    # briefly outlives this generation.
                    try:
                        if basics._state.engine is not None:
                            basics._state.engine.cache_flush()
                    except Exception:
                        pass
                    try:
                        basics.shutdown()
                    except Exception:
                        pass
                    if rollback:
                        state.restore()
                    try:
                        ctx.rendezvous()
                    except WorkerRemovedError:
                        log("info",
                            f"task index {ctx.index} removed from the "
                            "elastic job; exiting")
                        raise
                    # init + sync happen at the top of the next loop pass,
                    # so a newly-joined peer and a reset survivor take the
                    # exact same entry path.
                    resets.inc()
                    gen_gauge.set(ctx.generation)
                    reset_hist.observe(time.monotonic() - t0)
                    log("info",
                        f"elastic reset complete: generation "
                        f"{ctx.generation}, rank "
                        f"{os.environ.get('HOROVOD_RANK', '?')}/"
                        f"{os.environ.get('HOROVOD_SIZE', '?')}, resuming "
                        "from last commit")
        finally:
            _context = None

    return wrapper
