"""Elastic training — fault-tolerant re-scaling (ISSUE 3 tentpole).

The capability the paper's §5.7 honest accounting names as missing and
upstream Horovod later shipped as ``hvd.elastic``: a pod-scale job
survives worker death and host loss without restarting from scratch, and
absorbs new hosts mid-run.

    import horovod_tpu as hvd

    state = hvd.elastic.ElasticState(params=params, opt_state=opt_state,
                                     step=0)

    @hvd.elastic.run
    def train(state):
        while state.step < TOTAL_STEPS:
            state.params, state.opt_state = train_step(
                state.params, state.opt_state)
            state.step += 1
            state.commit()
        return state.step

    results = hvd.runner.run_elastic(train, args=(state,), num_proc=8)

Pieces (docs/elastic.md for the full model):

- :class:`ElasticState` (state.py) — commit/restore/sync training state;
  optionally checkpoint-backed through ``horovod_tpu.checkpoint``.
- :func:`run` (run.py) — the reset wrapper: catches collective failures
  (including the stall watchdog's shutdown escalation), tears down the
  communicator, re-rendezvouses, restores the last commit, re-enters.
- ``runner.run_elastic`` / :mod:`~horovod_tpu.elastic.driver` — the
  supervising launcher: rendezvous generations, respawn, blacklist,
  host discovery.
- :class:`HostDiscovery` / :class:`StaticDiscovery` /
  :class:`ScriptDiscovery`, :class:`Blacklist` (discovery.py).
- :mod:`~horovod_tpu.elastic.fault` — env-triggered fault injection for
  tests and the ci.sh elastic smoke.
"""

from __future__ import annotations

from . import fault  # noqa: F401
from .discovery import (  # noqa: F401
    Blacklist,
    HostDiscovery,
    ScriptDiscovery,
    StaticDiscovery,
    parse_discovery_output,
)
from .run import RESETTABLE, poll_host_updates, run  # noqa: F401
from .state import ElasticState, HostsUpdatedInterrupt  # noqa: F401


def __getattr__(name: str):
    # Lazy: WorkerRemovedError lives with the runner services; importing the
    # runner package here would pull the whole launcher into `import
    # horovod_tpu`.
    if name == "WorkerRemovedError":
        from ..runner.service import WorkerRemovedError

        return WorkerRemovedError
    if name == "run_elastic":
        from ..runner import run_elastic

        return run_elastic
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
