"""Env-triggered fault injection — the test/chaos hooks the elastic smoke
and the reset tests drive (ISSUE 3 tentpole item 4).

Everything here is opt-in via environment variables and free when unset;
none of it belongs in a production config:

- ``HOROVOD_FAULT_INJECT_STEP=N`` + ``HOROVOD_FAULT_INJECT_INDEX=i``:
  the worker at task index ``i`` kills itself when :func:`maybe_die` is
  called with ``step == N``. Training loops call ``maybe_die(step)`` once
  per step (``ElasticState.commit`` calls it with the state's ``step``/
  ``batch`` value when one exists, so elastic loops get the hook for
  free). A worker resumed from a commit PAST step N never re-triggers —
  which is exactly how the respawn-then-survive path is exercised — while
  a commit cadence that replays step N re-kills the worker and exercises
  the repeated-failure -> blacklist path.
- ``HOROVOD_FAULT_INJECT_SIGNAL`` (default ``KILL``): how to die — a
  signal name/number sent to self (``KILL`` models a hard crash: no
  result report, no clean TCP shutdown) or ``exit:<code>`` for
  ``os._exit``.
- ``HOROVOD_FAULT_AGENT_EXIT_AFTER_S=S``: a resident hvd-agent hard-exits
  ``S`` seconds after start (agent.py) — the host-loss scenario.

Network chaos (ISSUE 8, tools/chaos_smoke.py): frame-level fault injection
inside the authenticated Channel (runner/network.py), exercising the
transport-resilience ladder instead of killing processes:

- ``HOROVOD_FAULT_NET={delay,reset,corrupt,drop}``: what to inject on a
  matching outbound frame. ``delay`` sleeps ``HOROVOD_FAULT_NET_DELAY_MS``
  (default 1000) before sending — absorbed by the receive retry budget
  (rung 1); ``HOROVOD_FAULT_NET_DELAY_PER_MB`` (default 0) adds a
  bytes-proportional term (ms per MiB of payload) on top, modeling a
  bandwidth-collapsed link instead of a latency spike. ``reset`` abort-closes the socket (RST to the peer) — a hard
  link fault, absorbed by plane demotion (rung 2). ``corrupt`` flips a MAC
  byte so the receiver rejects the frame (``horovod_frames_rejected_total``)
  and fails the link — also rung 2. ``drop`` swallows the frame: the
  receiver sees the *next* frame early (size/HMAC mismatch — the
  broken-middlebox model) and fails the link.
- Target selectors: ``HOROVOD_FAULT_NET_SCOPE`` (comma list of channel
  scopes, default ``ring`` — the eager data-plane links; ``*`` = all),
  ``HOROVOD_FAULT_NET_RANK`` (only this HOROVOD_RANK injects; default
  any), ``HOROVOD_FAULT_NET_AFTER`` (skip the first N matching frames,
  default 0), ``HOROVOD_FAULT_NET_COUNT`` (stop after firing N times,
  default 1; -1 = unlimited), ``HOROVOD_FAULT_NET_RATE`` (per-frame firing
  probability once past AFTER, default 1 = deterministic).
"""

from __future__ import annotations

import os
import random
import signal
import threading


def _target_index() -> str:
    return os.environ.get("HOROVOD_FAULT_INJECT_INDEX", "")


def armed() -> bool:
    """True when this process is the fault target (cheap pre-check)."""
    step = os.environ.get("HOROVOD_FAULT_INJECT_STEP", "")
    if not step:
        return False
    target = _target_index()
    return target == "" or target == os.environ.get("HOROVOD_TASK_INDEX", "")


def maybe_die(step) -> None:
    """Kill this worker if the injected fault matches ``(step, index)``."""
    if not armed():
        return
    try:
        if int(step) != int(os.environ["HOROVOD_FAULT_INJECT_STEP"]):
            return
    except (TypeError, ValueError):
        return
    die()


def die() -> None:
    """Die the configured way, now. Logs first so the event is attributable
    in worker stderr."""
    spec = os.environ.get("HOROVOD_FAULT_INJECT_SIGNAL", "KILL")
    from ..utils.logging import log

    log("warning", f"fault injection firing ({spec}) at task index "
        f"{os.environ.get('HOROVOD_TASK_INDEX', '?')}")
    if spec.startswith("exit:"):
        os._exit(int(spec.split(":", 1)[1]))
    try:
        sig = int(spec)
    except ValueError:
        sig = getattr(signal, f"SIG{spec.upper()}", signal.SIGKILL)
    os.kill(os.getpid(), sig)


# -- network chaos (ISSUE 8) -------------------------------------------------

NET_ACTIONS = ("delay", "reset", "corrupt", "drop")

_net_lock = threading.Lock()
_net_fired = 0
_net_frames: dict = {}


def net_fault_armed() -> bool:
    """True when this process injects network faults (checked once per
    Channel construction — the hot path stays branch-free when unset)."""
    spec = os.environ.get("HOROVOD_FAULT_NET", "")
    if spec not in NET_ACTIONS:
        return False
    target = os.environ.get("HOROVOD_FAULT_NET_RANK", "")
    return target == "" or target == os.environ.get("HOROVOD_RANK", "")


def net_fault(scope: str) -> str | None:
    """Per-frame decision: return the action to inject on this outbound
    frame, or None. Counts frames per scope so AFTER/COUNT selectors are
    deterministic (the chaos smoke needs the fault to land mid-run, not at
    a random establishment frame)."""
    global _net_fired
    spec = os.environ.get("HOROVOD_FAULT_NET", "")
    if spec not in NET_ACTIONS:
        return None
    scopes = os.environ.get("HOROVOD_FAULT_NET_SCOPE", "ring")
    if scopes != "*" and scope not in scopes.split(","):
        return None
    target = os.environ.get("HOROVOD_FAULT_NET_RANK", "")
    if target and target != os.environ.get("HOROVOD_RANK", ""):
        return None
    with _net_lock:
        count = int(os.environ.get("HOROVOD_FAULT_NET_COUNT", "") or 1)
        if 0 <= count <= _net_fired:
            return None
        n = _net_frames.get(scope, 0) + 1
        _net_frames[scope] = n
        if n <= int(os.environ.get("HOROVOD_FAULT_NET_AFTER", "") or 0):
            return None
        rate = float(os.environ.get("HOROVOD_FAULT_NET_RATE", "") or 1.0)
        if rate < 1.0 and random.random() >= rate:
            return None
        _net_fired += 1
    from ..utils.logging import log

    log("warning",
        f"net fault injection firing: {spec} on {scope} frame {n} "
        f"(rank {os.environ.get('HOROVOD_RANK', '?')})")
    return spec


def net_fault_delay_s(nbytes: int = 0) -> float:
    """Injected per-frame delay. ``HOROVOD_FAULT_NET_DELAY_MS`` is a flat
    per-frame latency (default 1000). ``HOROVOD_FAULT_NET_DELAY_PER_MB``
    adds a bytes-proportional component (ms per MiB of frame payload,
    default 0) — that models a bandwidth-collapsed link rather than a
    latency spike, which is the fault class where shrinking the wire
    format (bf16/top-k) genuinely restores throughput. The controller
    chaos leg (tools/controller_smoke.py) uses it so the canary's
    commit-vs-rollback verdict reflects a real causal win, not luck."""
    flat = float(os.environ.get("HOROVOD_FAULT_NET_DELAY_MS", "") or 1000.0)
    per_mb = float(
        os.environ.get("HOROVOD_FAULT_NET_DELAY_PER_MB", "") or 0.0)
    return (flat + per_mb * (nbytes / float(1 << 20))) / 1000.0


def reset_net_fault_state() -> None:
    """Forget fired/frame counters (unit tests re-arm between cases)."""
    global _net_fired
    with _net_lock:
        _net_fired = 0
        _net_frames.clear()


def start_agent_fault_timer() -> None:
    """Arm HOROVOD_FAULT_AGENT_EXIT_AFTER_S on a resident agent: hard-exit
    after the delay, modeling sudden host loss (the driver must notice via
    the broken connection, not a goodbye)."""
    delay = os.environ.get("HOROVOD_FAULT_AGENT_EXIT_AFTER_S", "")
    if not delay:
        return

    def _boom() -> None:
        os._exit(1)

    t = threading.Timer(float(delay), _boom)
    t.daemon = True
    t.start()
