"""Env-triggered fault injection — the test/chaos hooks the elastic smoke
and the reset tests drive (ISSUE 3 tentpole item 4).

Everything here is opt-in via environment variables and free when unset;
none of it belongs in a production config:

- ``HOROVOD_FAULT_INJECT_STEP=N`` + ``HOROVOD_FAULT_INJECT_INDEX=i``:
  the worker at task index ``i`` kills itself when :func:`maybe_die` is
  called with ``step == N``. Training loops call ``maybe_die(step)`` once
  per step (``ElasticState.commit`` calls it with the state's ``step``/
  ``batch`` value when one exists, so elastic loops get the hook for
  free). A worker resumed from a commit PAST step N never re-triggers —
  which is exactly how the respawn-then-survive path is exercised — while
  a commit cadence that replays step N re-kills the worker and exercises
  the repeated-failure -> blacklist path.
- ``HOROVOD_FAULT_INJECT_SIGNAL`` (default ``KILL``): how to die — a
  signal name/number sent to self (``KILL`` models a hard crash: no
  result report, no clean TCP shutdown) or ``exit:<code>`` for
  ``os._exit``.
- ``HOROVOD_FAULT_AGENT_EXIT_AFTER_S=S``: a resident hvd-agent hard-exits
  ``S`` seconds after start (agent.py) — the host-loss scenario.
"""

from __future__ import annotations

import os
import signal
import threading


def _target_index() -> str:
    return os.environ.get("HOROVOD_FAULT_INJECT_INDEX", "")


def armed() -> bool:
    """True when this process is the fault target (cheap pre-check)."""
    step = os.environ.get("HOROVOD_FAULT_INJECT_STEP", "")
    if not step:
        return False
    target = _target_index()
    return target == "" or target == os.environ.get("HOROVOD_TASK_INDEX", "")


def maybe_die(step) -> None:
    """Kill this worker if the injected fault matches ``(step, index)``."""
    if not armed():
        return
    try:
        if int(step) != int(os.environ["HOROVOD_FAULT_INJECT_STEP"]):
            return
    except (TypeError, ValueError):
        return
    die()


def die() -> None:
    """Die the configured way, now. Logs first so the event is attributable
    in worker stderr."""
    spec = os.environ.get("HOROVOD_FAULT_INJECT_SIGNAL", "KILL")
    from ..utils.logging import log

    log("warning", f"fault injection firing ({spec}) at task index "
        f"{os.environ.get('HOROVOD_TASK_INDEX', '?')}")
    if spec.startswith("exit:"):
        os._exit(int(spec.split(":", 1)[1]))
    try:
        sig = int(spec)
    except ValueError:
        sig = getattr(signal, f"SIG{spec.upper()}", signal.SIGKILL)
    os.kill(os.getpid(), sig)


def start_agent_fault_timer() -> None:
    """Arm HOROVOD_FAULT_AGENT_EXIT_AFTER_S on a resident agent: hard-exit
    after the delay, modeling sudden host loss (the driver must notice via
    the broken connection, not a goodbye)."""
    delay = os.environ.get("HOROVOD_FAULT_AGENT_EXIT_AFTER_S", "")
    if not delay:
        return

    def _boom() -> None:
        os._exit(1)

    t = threading.Timer(float(delay), _boom)
    t.daemon = True
    t.start()
