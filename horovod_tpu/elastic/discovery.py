"""Host discovery + blacklist for elastic jobs.

Upstream Horovod's elastic mode learns the current host set from a
user-supplied ``--host-discovery-script`` (re-run periodically by the
driver) and blacklists hosts whose workers keep failing. Same contract
here, minus the CLI shell-out being the only option:

- :class:`HostDiscovery` — interface: ``probe()`` returns the *desired*
  ``[(host, slots), ...]`` right now. The elastic driver polls it every
  ``HOROVOD_ELASTIC_DISCOVERY_INTERVAL`` seconds and triggers a reset when
  the answer changes.
- :class:`StaticDiscovery` — a fixed list (the no-discovery default).
- :class:`ScriptDiscovery` — the ``--host-discovery-script`` analog: runs
  an executable that prints one ``host[:slots]`` per line.
- :class:`Blacklist` — failure bookkeeping per host key: after
  ``HOROVOD_ELASTIC_BLACKLIST_THRESHOLD`` (default 2) recorded failures a
  key is excluded from every future generation.
"""

from __future__ import annotations

import os
import subprocess
from typing import Iterable, Optional, Sequence


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


class HostDiscovery:
    """Interface: the driver polls :meth:`probe` for the desired slot set."""

    def probe(self) -> list:   # pragma: no cover - interface
        """Return the currently-desired ``[(host, slots), ...]``."""
        raise NotImplementedError


class StaticDiscovery(HostDiscovery):
    """Fixed host set — elastic in the *fault tolerance* sense only (dead
    slots are respawned/blacklisted; nothing is ever added)."""

    def __init__(self, hosts: Sequence) -> None:
        self._hosts = [(str(h), int(s)) for h, s in hosts]

    def probe(self) -> list:
        return list(self._hosts)


class ScriptDiscovery(HostDiscovery):
    """Run ``script`` (any executable) and parse one ``host[:slots]`` per
    line — the ``horovodrun --host-discovery-script`` analog. A failing or
    hanging script yields the LAST good answer (never an empty world: a
    flaky discovery script must not scale the job to zero)."""

    def __init__(self, script: str, timeout: float = 10.0) -> None:
        self.script = script
        self.timeout = timeout
        self._last: list = []

    def probe(self) -> list:
        try:
            out = subprocess.run(
                [self.script], capture_output=True, text=True,
                timeout=self.timeout, check=True).stdout
        except (OSError, subprocess.SubprocessError):
            return list(self._last)
        hosts = parse_discovery_output(out)
        if hosts:
            self._last = hosts
        return list(self._last)


def parse_discovery_output(text: str) -> list:
    """``host[:slots]`` lines -> ``[(host, slots), ...]`` (slots default 1;
    blank lines and ``#`` comments ignored)."""
    hosts = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        host, _, slots = line.partition(":")
        try:
            hosts.append((host.strip(), int(slots) if slots.strip() else 1))
        except ValueError:
            continue
    return hosts


class Blacklist:
    """Failure counts per host key; a key past ``threshold`` failures is
    excluded from membership until the job ends (upstream Horovod's
    blacklisted-host set; the cooldown refinement arrived later)."""

    def __init__(self, threshold: Optional[int] = None) -> None:
        self.threshold = threshold if threshold is not None else _env_int(
            "HOROVOD_ELASTIC_BLACKLIST_THRESHOLD", 2)
        self._failures: dict[str, int] = {}

    def record_failure(self, key: str) -> bool:
        """Count one failure; returns True when this pushed ``key`` over
        the threshold (i.e. it just became blacklisted)."""
        self._failures[key] = self._failures.get(key, 0) + 1
        return self._failures[key] == self.threshold

    def ban(self, key: str) -> bool:
        """Blacklist ``key`` immediately regardless of count (lost agent:
        the host is gone, not flaky). Returns True if newly blacklisted."""
        if self.is_blacklisted(key):
            return False
        self._failures[key] = max(self._failures.get(key, 0), self.threshold)
        return True

    def is_blacklisted(self, key: str) -> bool:
        return self._failures.get(key, 0) >= self.threshold

    def failures(self, key: str) -> int:
        return self._failures.get(key, 0)

    def blacklisted(self) -> list:
        return sorted(k for k, n in self._failures.items()
                      if n >= self.threshold)

    def filter(self, hosts: Iterable) -> list:
        """Drop blacklisted hosts from a ``[(host, slots), ...]`` list."""
        return [(h, s) for h, s in hosts if not self.is_blacklisted(h)]
