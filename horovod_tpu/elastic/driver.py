"""Elastic launcher orchestration — the driver-side half of ISSUE 3.

``launch_elastic`` (exposed as ``horovod_tpu.runner.run_elastic``) owns the
job across membership changes:

- a **slot pool** materializes workers: :class:`LocalSlotPool` spawns local
  processes (the ``run()`` local leg), :class:`AgentSlotPool` spawns through
  resident per-host ``hvd-agent`` daemons (the ``-H`` leg, extended with an
  incremental-spawn request so one job can grow).
- a supervision loop polls worker liveness, the
  :class:`~horovod_tpu.elastic.discovery.HostDiscovery` hook, and the
  :class:`~horovod_tpu.runner.service.ElasticDriverService` membership; a
  dead worker (non-zero exit, clean exit without a result, lost agent) or a
  discovery change starts a new generation: failed slots are respawned
  under FRESH task indices (so rank 0 — assigned oldest-member-first — is
  always a survivor carrying committed state) or their host blacklisted
  after repeated failures (:class:`~.discovery.Blacklist`).
- every membership event lands in the **elastic event log** (structured,
  JSONL at ``HOROVOD_ELASTIC_EVENT_LOG``; docs/elastic.md explains how to
  read it) and in the driver-process metrics registry.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, Callable, Optional

from ..metrics import registry as _registry
from ..utils.logging import log
from .discovery import Blacklist, HostDiscovery, StaticDiscovery

_POLL_S = 0.1


class ElasticEventLog:
    """Append-only membership event record. Always logged; mirrored as
    JSONL to ``HOROVOD_ELASTIC_EVENT_LOG`` when set (the artifact
    troubleshooting tells a reset-surprised user to read)."""

    def __init__(self, path: Optional[str] = None) -> None:
        self.path = path if path is not None \
            else os.environ.get("HOROVOD_ELASTIC_EVENT_LOG", "")
        self.events: list[dict] = []

    def emit(self, event: str, **detail: Any) -> None:
        rec = {"time_unix_s": time.time(), "event": event, **detail}
        self.events.append(rec)
        log("info", f"elastic: {event} "
            + " ".join(f"{k}={v}" for k, v in detail.items()))
        if self.path:
            try:
                with open(self.path, "a") as f:
                    f.write(json.dumps(rec) + "\n")
            except OSError:   # telemetry must never kill the job
                pass


class _Slot:
    __slots__ = ("key", "host", "index")

    def __init__(self, key: str, host: str, index: int) -> None:
        self.key = key      # stable slot identity ("host:ordinal")
        self.host = host    # host component (blacklist granularity, agents)
        self.index = index  # task index of the CURRENT incarnation


class LocalSlotPool:
    """Workers as local child processes (one pseudo-host per slot: a local
    slot stands in for a host, so blacklisting works per slot — the same
    convention the agent tests use for faked hosts)."""

    def __init__(self, driver, secret: bytes, env: Optional[dict],
                 python: Optional[str]) -> None:
        self._driver = driver
        self._secret = secret
        self._env = env
        self._python = python or sys.executable
        self._procs: dict[int, Any] = {}

    def blame_keys(self, slot: _Slot) -> list:
        return [slot.key]

    def spawn(self, slot: _Slot) -> None:
        from ..runner import _spawn_worker

        self._procs[slot.index] = _spawn_worker(
            slot.index, self._driver.addresses(), self._secret,
            [self._python, "-m", "horovod_tpu.runner.task_main"], self._env)

    def poll(self) -> dict:
        return {i: p.poll() for i, p in self._procs.items()}

    def kill(self, indices) -> None:
        from ..runner.proc_tree import terminate_trees

        terminate_trees([self._procs[i] for i in indices if i in self._procs])

    def close(self) -> None:
        self.kill(list(self._procs))


class AgentSlotPool:
    """Workers through resident hvd-agents (the remote leg). One persistent
    authenticated connection per host; slots spawn incrementally into a
    single job id (agent ``spawn`` with ``extend``), so membership can grow
    without re-deriving the job secret. An unreachable agent reads as every
    one of its slots dying at once (its workers self-terminate via the
    parent-death watchdog) and bans the host outright."""

    def __init__(self, driver, agent_secret: bytes, agent_port: Optional[int],
                 env: Optional[dict], python: Optional[str]) -> None:
        import secrets as _secrets

        self._driver = driver
        self._agent_secret = agent_secret
        self._agent_port = agent_port
        self._env = env
        self._python = python or sys.executable
        self.job_id = _secrets.token_hex(8)
        self._clients: dict[str, Any] = {}      # host -> BasicClient | None
        self._host_indices: dict[str, set] = {}  # host -> task indices
        self._last_codes: dict[int, Optional[int]] = {}
        # Control tree (ISSUE 18): set via enable_control() before spawns;
        # each host's leader starts lazily with its first slot, so hosts
        # joining mid-run (discovery) get one too.
        self._ctrl_root: Optional[list] = None
        self._ctrl_ckpt_dir = ""
        self._ctrl_started: set[str] = set()

    def enable_control(self, root_addrs, ckpt_dir: str = "") -> None:
        """Route every host's rank traffic through a ControlAgent leader
        (started on first spawn per host) instead of rank-to-root."""
        self._ctrl_root = [list(a) for a in root_addrs]
        self._ctrl_ckpt_dir = ckpt_dir

    def _start_control(self, host: str, client) -> None:
        if self._ctrl_root is None or host in self._ctrl_started:
            return
        try:
            resp = client.request({
                "kind": "ctrl", "cmd": "start", "job_id": self.job_id,
                "root": self._ctrl_root, "relay": True,
                "ckpt_dir": self._ctrl_ckpt_dir})
        except (ConnectionError, OSError) as e:
            resp = {"ok": False, "error": str(e)}
        if resp.get("ok"):
            self._ctrl_started.add(host)
        else:
            from ..utils.logging import log

            log("warning",
                f"[ctrl] control leader failed to start on {host}: "
                f"{resp.get('error')} — that host's workers use the flat "
                "control plane")

    def job_secret(self) -> bytes:
        from ..runner.network import derive_key

        return derive_key(self._agent_secret,
                          b"hvd-job:" + self.job_id.encode())

    def _client(self, host: str):
        from ..runner.agent import DEFAULT_AGENT_PORT
        from ..runner.network import BasicClient

        if host not in self._clients:
            name, _, port = host.partition("@")
            client = BasicClient(
                [(name, int(port) if port else
                  (self._agent_port or DEFAULT_AGENT_PORT))],
                self._agent_secret, timeout=30.0)
            pong = client.request({"kind": "ping"})
            if not pong.get("ok"):
                raise RuntimeError(f"agent on {host} rejected ping: {pong}")
            self._clients[host] = client
        if self._clients[host] is None:
            raise ConnectionError(f"agent on {host} is gone")
        return self._clients[host]

    def blame_keys(self, slot: _Slot) -> list:
        return [slot.key, slot.host]

    def spawn(self, slot: _Slot) -> None:
        from ..runner import _worker_env

        env = _worker_env(slot.index, self._driver.addresses(), None,
                          self._env)
        client = self._client(slot.host)
        self._start_control(slot.host, client)
        resp = client.request({
            "kind": "spawn", "job_id": self.job_id, "extend": True,
            "workers": [{"index": slot.index,
                         "argv": [self._python, "-m",
                                  "horovod_tpu.runner.task_main"],
                         "env": env}]})
        if not resp.get("ok"):
            raise RuntimeError(
                f"agent on {slot.host} failed to spawn: {resp.get('error')}")
        self._host_indices.setdefault(slot.host, set()).add(slot.index)
        self._last_codes[slot.index] = None

    def poll(self) -> dict:
        codes = dict(self._last_codes)
        for host, indices in self._host_indices.items():
            if self._clients.get(host) is None:
                continue
            try:
                resp = self._clients[host].request(
                    {"kind": "poll", "job_id": self.job_id})
            except (ConnectionError, OSError):
                # Lost agent = lost host: every slot on it reads as dead
                # with a sentinel code; the orchestrator bans the host.
                self._clients[host] = None
                for i in indices:
                    codes[i] = codes[i] if codes[i] is not None else -9
                continue
            if resp.get("ok"):
                for w in resp["workers"]:
                    if w["index"] in indices:
                        codes[w["index"]] = w["returncode"]
        self._last_codes = codes
        return codes

    def lost_hosts(self) -> list:
        return sorted(h for h, c in self._clients.items() if c is None)

    def kill(self, indices) -> None:
        # Agents key kills by job, not worker; individual removals happen
        # via the rendezvous protocol (the worker exits on removal). A
        # whole-job kill is only issued from close().
        pass

    def close(self) -> None:
        for host, client in self._clients.items():
            if client is None:
                continue
            try:
                client.request({"kind": "kill", "job_id": self.job_id})
            except (ConnectionError, OSError):
                pass
            try:
                client.close()
            except OSError:
                pass
        self._clients.clear()


def _desired_slot_keys(hosts: list, blacklist: Blacklist,
                       max_np: Optional[int]) -> list:
    """Expand ``[(host, slots)]`` into stable slot keys, dropping
    blacklisted hosts/slots and capping at ``max_np``."""
    keys = []
    for host, slots in hosts:
        if blacklist.is_blacklisted(host):
            continue
        for i in range(int(slots)):
            key = f"{host}:{i}"
            if not blacklist.is_blacklisted(key):
                keys.append(key)
    return keys[:max_np] if max_np else keys


def launch_elastic(fn: Callable, args: tuple = (), kwargs: Optional[dict] = None,
                   num_proc: Optional[int] = None, min_np: int = 1,
                   max_np: Optional[int] = None, env: Optional[dict] = None,
                   timeout: float = 600.0,
                   discovery: Optional[HostDiscovery] = None,
                   python: Optional[str] = None,
                   hosts=None, agent_port: Optional[int] = None,
                   agent_secret: Optional[bytes] = None) -> list:
    """Supervise an elastic job to completion; returns per-rank results of
    the final generation (see runner.run_elastic docstring)."""
    from ..runner.network import make_secret
    from ..runner.service import ElasticDriverService

    env = dict(env or {})
    env.setdefault("HOROVOD_ELASTIC", "1")
    # A dead peer must surface as a Python-visible failure on survivors:
    # the PR 2 stall watchdog is the detector for non-coordinator deaths.
    for var, default in (("HOROVOD_STALL_CHECK_TIME", "10"),
                         ("HOROVOD_STALL_SHUTDOWN_TIME", "30")):
        if var not in env and var not in os.environ:
            env[var] = default

    def knob(name: str, default: str) -> str:
        # Driver-side knobs honor the job's env= dict too: callers naturally
        # put every HOROVOD_ELASTIC_* setting there, and the blacklist /
        # discovery cadence / event log live in THIS process.
        return env.get(name) or os.environ.get(name) or default

    events = ElasticEventLog(path=knob("HOROVOD_ELASTIC_EVENT_LOG", ""))
    blacklist = Blacklist(
        threshold=int(knob("HOROVOD_ELASTIC_BLACKLIST_THRESHOLD", "2")))
    reg = _registry()
    added_c = reg.counter("horovod_elastic_workers_added_total",
                          help="workers added to the elastic job")
    removed_c = reg.counter("horovod_elastic_workers_removed_total",
                            help="workers removed from the elastic job")
    gen_gauge = reg.gauge("horovod_elastic_generation",
                          help="current elastic rendezvous generation")
    black_gauge = reg.gauge("horovod_elastic_blacklisted_hosts",
                            help="hosts/slots currently blacklisted")

    if hosts is not None:
        from ..runner.remote import parse_hosts

        if agent_secret is None:
            hex_secret = os.environ.get("HOROVOD_AGENT_SECRET")
            if not hex_secret:
                raise ValueError(
                    "elastic multi-host launch needs the agent secret: pass "
                    "agent_secret= or set HOROVOD_AGENT_SECRET (hex)")
            agent_secret = bytes.fromhex(hex_secret)
        specs = parse_hosts(hosts, agent_port)
        initial_hosts = [(f"{s.host}@{s.agent_port}", s.slots) for s in specs]
        driver = ElasticDriverService(b"\0" * 32, fn=fn, args=args,
                                      kwargs=kwargs)
        pool = AgentSlotPool(driver, agent_secret, agent_port, env, python)
        # Workers authenticate with the per-job derived secret (the agents
        # derive the same value and inject it into worker env; it never
        # crosses the wire) — re-key the driver service before any worker
        # can connect (spawns happen strictly later).
        driver.key = pool.job_secret()
        # Control tree (ISSUE 18): per-host leaders fold rendezvous and
        # elastic-poll traffic into one upstream connection per host, and
        # serve checkpoint streaming to cold-starting joiners.
        from ..ctrl.tree import use_tree

        world = sum(int(s.slots) for s in specs)
        if use_tree(len(specs), world):
            pool.enable_control(
                driver.addresses(),
                ckpt_dir=knob("HOROVOD_CKPT_STREAM_DIR", ""))
    else:
        num_proc = num_proc or os.cpu_count() or 1
        if num_proc < 1:
            raise ValueError(f"num_proc must be >= 1, got {num_proc}")
        initial_hosts = [("local", num_proc)]
        secret = make_secret()
        driver = ElasticDriverService(secret, fn=fn, args=args, kwargs=kwargs)
        pool = LocalSlotPool(driver, secret, env, python)

    if discovery is None:
        discovery = StaticDiscovery(initial_hosts)
    discovery_interval = float(
        knob("HOROVOD_ELASTIC_DISCOVERY_INTERVAL", "1.0"))

    slots: dict[str, _Slot] = {}
    done: dict[int, tuple] = {}   # index -> (rank, value) of ok results seen
    next_index = 0

    def spawn_key(key: str) -> None:
        nonlocal next_index
        slot = _Slot(key, key.rsplit(":", 1)[0], next_index)
        next_index += 1
        pool.spawn(slot)
        slots[key] = slot
        added_c.inc()
        events.emit("worker_spawned", slot=key, index=slot.index)

    def reform(reason: str) -> None:
        expected = {s.index for s in slots.values() if s.index not in done}
        if not expected:
            return  # every remaining slot already delivered a result
        if len(expected) < min_np:
            raise RuntimeError(
                f"elastic job fell below min_np={min_np} "
                f"({len(expected)} live slots; blacklisted: "
                f"{blacklist.blacklisted()})")
        driver.begin_reset(expected)
        events.emit("rendezvous_opened", reason=reason,
                    expected=sorted(expected))

    def harvest(m: dict) -> None:
        """Fold the membership snapshot's results into ``done`` (ok) or
        raise (a non-resettable failure in user code aborts the job)."""
        index_by_rank = {r: i for i, r in m["ranks"].items()}
        failures = {}
        for rank, payload in m["results"].items():
            if isinstance(payload, dict) and not payload.get("ok", True):
                failures[rank] = payload.get("error", "unknown")
            else:
                idx = index_by_rank.get(rank)
                if idx is not None:
                    done.setdefault(idx, (rank, payload))
        if failures:
            rank, tb = sorted(failures.items())[0]
            raise RuntimeError(
                f"task on rank {rank} failed"
                f" (and {len(failures) - 1} more):\n{tb}")

    try:
        for key in _desired_slot_keys(discovery.probe() or initial_hosts,
                                      blacklist, max_np):
            spawn_key(key)
        if not slots:
            raise RuntimeError("no slots to launch (empty discovery?)")
        reform("initial formation")
        deadline = time.monotonic() + timeout
        next_probe = time.monotonic() + discovery_interval
        last_gen = 0
        while True:
            # Order matters: a worker reports its result strictly before it
            # exits, so polling process exits FIRST and reading driver
            # results SECOND guarantees a finished worker's result is
            # visible before its exit is judged — a clean exit without a
            # result is then always a real failure (never a race).
            codes = pool.poll()
            m = driver.membership()
            if m["generation"] != last_gen:
                last_gen = m["generation"]
                gen_gauge.set(last_gen)
                events.emit("rendezvous_complete", generation=last_gen,
                            size=len(m["ranks"]))
            harvest(m)
            live_pending = {s.index for s in slots.values()
                            if s.index not in done}
            if not live_pending and slots:
                break  # every current member delivered a result
            # -- liveness ----------------------------------------------------
            dead: list[_Slot] = []
            for slot in list(slots.values()):
                rc = codes.get(slot.index)
                if rc is None or slot.index in done:
                    continue  # running, or finished cleanly after reporting
                dead.append(slot)
            for host in (pool.lost_hosts()
                         if hasattr(pool, "lost_hosts") else ()):
                if blacklist.ban(host):
                    events.emit("host_blacklisted", host=host,
                                reason="agent unreachable")
            for slot in dead:
                del slots[slot.key]
                removed_c.inc()
                events.emit("worker_failed", slot=slot.key, index=slot.index,
                            returncode=codes.get(slot.index))
                for key in pool.blame_keys(slot):
                    if blacklist.record_failure(key):
                        events.emit("host_blacklisted", host=key,
                                    reason=f"{blacklist.failures(key)} "
                                           "failures")
            black_gauge.set(len(blacklist.blacklisted()))
            # -- discovery ---------------------------------------------------
            if time.monotonic() >= next_probe:
                next_probe = time.monotonic() + discovery_interval
                probed = discovery.probe()
                if probed:
                    initial_hosts = probed
            desired = _desired_slot_keys(initial_hosts, blacklist, max_np)
            to_remove = [k for k in slots if k not in desired
                         and slots[k].index not in done]
            to_add = [k for k in desired if k not in slots]
            survivors_pending = [s for s in slots.values()
                                 if s.index not in done]
            if dead and not survivors_pending and not to_remove:
                # End-game: the failure hit while everyone else had already
                # finished; nobody is left to re-rendezvous with, and a
                # fresh replacement alone would restart from scratch.
                events.emit("job_finished_degraded",
                            missing=[s.key for s in dead])
                break
            if dead or to_remove or to_add:
                for key in to_remove:
                    slot = slots.pop(key)
                    pool.kill([slot.index])
                    removed_c.inc()
                    events.emit("worker_removed", slot=key, index=slot.index,
                                reason="scale-down or blacklist")
                for key in to_add:
                    spawn_key(key)
                reform("membership changed: "
                       f"{len(dead)} dead, {len(to_remove)} removed, "
                       f"{len(to_add)} added")
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"elastic job incomplete after {timeout}s "
                    f"(generation {m['generation']}, "
                    f"{len(done)}/{len(slots)} results)")
            time.sleep(_POLL_S)
        _emit_elastic_pod_metrics(driver, events, blacklist, last_gen)
        ordered = sorted(done.values(), key=lambda rv: rv[0])
        return [_unwrap(v) for _, v in ordered]
    finally:
        pool.close()
        driver.stop()


def _unwrap(payload: Any) -> Any:
    return payload["value"] if isinstance(payload, dict) and "value" in payload \
        else payload


def _emit_elastic_pod_metrics(driver, events: ElasticEventLog,
                              blacklist: Blacklist, generation: int) -> None:
    """Pod snapshot to HOROVOD_METRICS_SNAPSHOT (the run() contract) with
    the driver's elastic view attached under info.elastic. Never fatal."""
    path = os.environ.get("HOROVOD_METRICS_SNAPSHOT", "")
    try:
        pod = driver.pod_metrics()
        if pod is None:
            return
        pod["info"]["elastic"] = {
            "generation": generation,
            "blacklisted": blacklist.blacklisted(),
            "events": [e["event"] for e in events.events],
        }
        # Telemetry-tree coverage, when host leaders were pushing through
        # the tree: per-host snapshot age + expected ranks, so the final
        # snapshot records which hosts were still reporting at the end.
        tele = getattr(driver, "_telemetry", None)
        if tele is not None:
            pod["info"]["elastic"]["telemetry"] = tele.staleness()
        if path:
            with open(path, "w") as f:
                json.dump(pod, f, indent=2)
        key = "horovod_elastic_resets_total"
        log("debug",
            f"elastic pod metrics: generation {generation}, "
            f"{pod['counters'].get(key, 0):.0f} worker resets"
            + (f" -> {path}" if path else ""))
    except Exception as e:  # pragma: no cover - telemetry must not kill jobs
        log("warning", f"elastic pod metrics emission failed: {e}")
