"""Elastic training state — commit / restore / broadcast-on-reset.

The contract upstream Horovod ships as ``hvd.elastic.State`` (state commit
+ rollback + sync after re-rendezvous), framework-free here:

    state = hvd.elastic.ElasticState(params=params, opt_state=opt_state,
                                     epoch=0, step=0)
    ...
    state.params, state.opt_state = train_step(...)
    state.step += 1
    state.commit()            # in-memory snapshot (+ optional checkpoint)

- ``commit()`` deep-copies every value to host memory (jax arrays are
  materialized to numpy, so a committed snapshot cannot alias device
  buffers that a reset tears down). With ``checkpoint_dir`` set, every
  ``checkpoint_every``-th commit also writes a rank-0 checkpoint through
  ``horovod_tpu.checkpoint`` — the restart-from-disk story for full-job
  loss, on top of the in-memory story for worker loss.
- ``restore()`` rolls the live values back to the last commit (steps run
  since are discarded — exactly the semantics the reset path needs: an
  interrupted step may have updated a subset of ranks).
- ``sync()`` makes the world consistent after a re-rendezvous: rank 0 (by
  elastic rank assignment always a *survivor* holding the newest commit)
  broadcasts its committed snapshot; every rank — including workers that
  just joined and have no history — adopts it.

``commit()`` doubles as the elastic heartbeat: it fires the env-triggered
fault hooks (fault.py) and polls the driver for membership changes,
raising :class:`HostsUpdatedInterrupt` so the training loop re-enters
rendezvous at a step boundary instead of waiting for a failure.
"""

from __future__ import annotations

import os
from typing import Any, Optional

from ..metrics import registry as _registry


class HostsUpdatedInterrupt(Exception):
    """Membership changed (discovery added/removed hosts, or a reset is
    already forming): re-rendezvous at the next step boundary. State is
    already committed when this is raised — the reset path syncs without
    rolling back."""


def _copy_tree(tree: Any) -> Any:
    """Deep copy a pytree with every array leaf materialized to numpy on
    the host (a committed snapshot must survive engine/device teardown)."""
    import copy as _copy

    import jax
    import numpy as np

    def leaf(x):
        if hasattr(x, "__array__"):
            return np.array(x)
        return _copy.deepcopy(x)

    return jax.tree_util.tree_map(leaf, tree)


class ElasticState:
    """Named training values with commit/restore/sync semantics. Values are
    attributes (``state.params``), the names are the keys you passed to the
    constructor; assignment replaces the live value, never the commit."""

    def __init__(self, checkpoint_dir: Optional[str] = None,
                 checkpoint_every: int = 1, **values: Any) -> None:
        object.__setattr__(self, "_checkpoint_dir", checkpoint_dir)
        object.__setattr__(self, "_checkpoint_every", max(int(checkpoint_every), 1))
        object.__setattr__(self, "_values", dict(values))
        object.__setattr__(self, "_committed", None)
        object.__setattr__(self, "_commits", 0)
        # Async checkpoint writer (ISSUE 18), created on first checkpointing
        # commit when HOROVOD_CKPT_ASYNC is on. Rank-gated there, not here:
        # rank is unknown until the world initializes.
        object.__setattr__(self, "_async_writer", None)
        # The construction-time values are the first commit: restore() and
        # sync() are well-defined before the loop's first explicit commit.
        self.commit(checkpoint=False, check_host_updates=False)

    # -- attribute routing ---------------------------------------------------

    def __getattr__(self, name: str) -> Any:
        values = object.__getattribute__(self, "_values")
        if name in values:
            return values[name]
        raise AttributeError(f"ElasticState has no value {name!r}")

    def __setattr__(self, name: str, value: Any) -> None:
        if name.startswith("_"):
            object.__setattr__(self, name, value)
        else:
            self._values[name] = value

    def to_dict(self) -> dict:
        """The live values (not copies)."""
        return dict(self._values)

    def committed_dict(self) -> dict:
        """The last committed snapshot (not copies; treat as read-only)."""
        return dict(self._committed or {})

    # -- commit / restore ----------------------------------------------------

    def commit(self, checkpoint: Optional[bool] = None,
               check_host_updates: bool = True) -> None:
        """Snapshot the live values as the new rollback point. Also runs the
        fault-injection hook and (in an elastic worker) the membership poll
        — see module docstring."""
        object.__setattr__(self, "_committed", _copy_tree(self._values))
        object.__setattr__(self, "_commits", self._commits + 1)
        _registry().counter(
            "horovod_elastic_commits_total",
            help="ElasticState.commit() calls").inc()
        if checkpoint is None:
            checkpoint = (self._checkpoint_dir is not None
                          and self._commits % self._checkpoint_every == 0)
        if checkpoint and self._checkpoint_dir:
            from .. import checkpoint as ckpt
            from ..ckpt_async import async_enabled

            if async_enabled():
                # Off-step-path commit (ISSUE 18): rank 0 hands the writer
                # thread the snapshot BY REFERENCE — safe because commit()
                # binds a fresh _copy_tree every time, never mutating the
                # tree the writer holds. No completion barrier: the commit
                # pipeline keeps the on-disk state crash-consistent at
                # every instant, so non-zero ranks need not wait (they
                # never read the directory outside cold start).
                from ..common import basics

                if not basics.is_initialized() or basics.rank() == 0:
                    writer = self._async_writer
                    if writer is None or writer.path != self._checkpoint_dir:
                        from ..ckpt_async import AsyncCheckpointer

                        if writer is not None:
                            writer.close()
                        writer = AsyncCheckpointer(self._checkpoint_dir)
                        object.__setattr__(self, "_async_writer", writer)
                    writer.submit(self._committed)
            else:
                ckpt.save(self._checkpoint_dir, self._committed)
        from . import fault

        if fault.armed():
            step = self._values.get("step", self._values.get("batch"))
            if step is not None:
                fault.maybe_die(step)
        if check_host_updates:
            from .run import poll_host_updates

            if poll_host_updates():
                raise HostsUpdatedInterrupt(
                    "elastic membership changed; re-rendezvous requested")

    def checkpoint_wait(self, timeout: float = 120.0) -> bool:
        """Block until any in-flight background checkpoint commit lands
        (True), or ``timeout`` passes (False). No-op without a writer."""
        writer = self._async_writer
        return True if writer is None else writer.wait(timeout)

    def restore(self) -> None:
        """Roll the live values back to the last commit (uncommitted steps
        are discarded)."""
        if self._committed is None:  # pragma: no cover - commit() in __init__
            raise RuntimeError("nothing committed yet")
        object.__setattr__(self, "_values", _copy_tree(self._committed))
        _registry().counter(
            "horovod_elastic_restores_total",
            help="rollbacks to the last committed elastic state").inc()

    def load_checkpoint(self) -> bool:
        """Cold-start restore from ``checkpoint_dir`` (full-job restart, not
        the in-memory reset path). Returns False when no checkpoint exists.
        Single-rank read (``verify=False``): callers sync() afterwards, and
        the broadcast is the consistency guarantee."""
        from ..ckpt_async import writer as _async_writer

        # A cold start in the same process (full-restart tests, notebook
        # reuse) must see every commit already submitted to a background
        # writer — flush before looking at the filesystem.
        if self._checkpoint_dir:
            _async_writer.drain(self._checkpoint_dir)
        if not self._checkpoint_dir or not os.path.isdir(self._checkpoint_dir):
            return False
        from .. import checkpoint as ckpt

        state = ckpt.restore(self._checkpoint_dir, template=self._values,
                             verify=False)
        object.__setattr__(self, "_values", state)
        self.commit(checkpoint=False, check_host_updates=False)
        return True

    # -- reset-path consistency ---------------------------------------------

    def sync(self, root_rank: int = 0) -> None:
        """Adopt rank ``root_rank``'s committed snapshot everywhere (the
        post-rendezvous broadcast; new workers join with whatever state they
        constructed and leave with the survivors' commit)."""
        from ..common import basics

        if basics.is_initialized() and basics.size() > 1:
            from .. import broadcast_object

            gen = os.environ.get("HOROVOD_ELASTIC_GENERATION", "0")
            committed = broadcast_object(
                self._committed, root_rank=root_rank,
                name=f"elastic.sync.g{gen}")
            object.__setattr__(self, "_committed", committed)
        object.__setattr__(self, "_values", _copy_tree(self._committed))
