"""Adaptive compression policy — per tensor, per fabric tier (ISSUE 9).

``HOROVOD_COMPRESSION=adaptive`` hands the wire-format choice to this
controller instead of one global knob. The insight from PowerSGD (Vogels
et al., 2019) and the SCALING_r05 projection is that *which* compressor
wins is tensor- and bandwidth-dependent: the ICI/intra-host fabric is
rarely the bottleneck (full width is free there), while the DCN/cross-pod
hop is the scaling cliff — worth paying topk's select/merge cost for a
~100x byte cut on large gradients, and at least a 16-bit cast on the rest.

Two kinds of decision live here, with deliberately different safety rules:

- **Value-changing** decisions (which format quantizes/sparsifies the
  tensor at enqueue) are a *deterministic* function of (size, dtype,
  fabric topology, config). Every rank evaluates the same inputs, so the
  cross-rank wire-format agreement the coordinator validates
  ("Mismatched wire compression") holds by construction — no negotiation
  round is spent on policy.
- **Value-neutral** decisions (whether a topk hop frames its payload
  sparse or dense on a given tier) may react to *live metrics* freely:
  both framings carry identical f32 values (compression.py frame
  contract), so ranks can disagree without any correctness consequence.
  :meth:`CompressionPolicy.refresh` reads the per-tier wire-byte counters
  and the critical-path wire-seconds gauges (docs/tracing.md) and moves
  the sparse framing to wherever the wire time actually is.

The per-tier decision table (docs/compression.md has the full story):

    tier  | tensor                                   | format
    ------+------------------------------------------+-------
    any   | non-float, <=2-byte, < min_bytes          | none
    ici   | everything else                           | none  (full width)
    dcn   | float32 >= HOROVOD_TOPK_MIN_BYTES         | topk
    dcn   | other floats >= min_bytes                 | bf16
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .config import _env_int
from ..compression import (
    DEFAULT_TOPK_RATIO,
    topk_eligible,
    topk_ratio_from_env,
)

# Below this dense size the sparse frame's select/merge overhead outweighs
# the byte cut even on DCN; the 16-bit cast still pays.
DEFAULT_TOPK_MIN_BYTES = 1 << 16

# Canonical tier spellings: the eager planes tag links "local"/"cross",
# the compiled plane and the docs say "ici"/"dcn".
TIER_ALIASES = {"local": "ici", "ici": "ici", "cross": "dcn", "dcn": "dcn"}


class CompressionPolicy:
    """The HOROVOD_COMPRESSION=adaptive controller.

    ``decide`` is the per-(tensor, tier) table; ``resolve`` collapses it to
    the single value-changing format the eager engine applies at enqueue
    (the decision for the most aggressive fabric the topology actually
    crosses); ``sparse_tiers``/``refresh`` steer the value-neutral hop
    framing from live telemetry."""

    def __init__(self, config=None, topo=None) -> None:
        self.min_bytes = int(getattr(config, "compression_min_bytes", 4096)
                             or 4096)
        self.topk_ratio = float(getattr(config, "topk_ratio", 0.0)
                                or topk_ratio_from_env(DEFAULT_TOPK_RATIO))
        self.topk_min_bytes = max(
            self.min_bytes,
            _env_int("HOROVOD_TOPK_MIN_BYTES", DEFAULT_TOPK_MIN_BYTES))
        # Does this world cross a host boundary at all? Single-host worlds
        # have no DCN hop, so adaptive resolves to full width everywhere.
        self.has_dcn = bool(topo is None or getattr(topo, "cross_size", 1) > 1)
        # Where topk frames ship sparse (value-neutral; see module doc).
        # DCN by default — loopback links move dense f32 faster than they
        # select/merge — until refresh() sees the wire time move.
        self._sparse_tiers = {"cross"}
        self._diag: dict = {}

    # -- the deterministic table (value-changing: must agree across ranks)

    def decide(self, nbytes: int, dtype, tier: str) -> str:
        """Wire format for a tensor of ``nbytes``/``dtype`` on ``tier``."""
        dtype = np.dtype(dtype)
        if dtype.kind != "f" or dtype.itemsize <= 2 \
                or nbytes < self.min_bytes:
            return "none"
        if TIER_ALIASES.get(tier, "dcn") == "ici":
            return "none"
        if nbytes >= self.topk_min_bytes and topk_eligible(
                dtype, nbytes, self.topk_ratio, self.min_bytes):
            return "topk"
        return "bf16"

    def resolve(self, nbytes: int, dtype) -> str:
        """The single value-changing format the eager engine quantizes a
        tensor to: the decision for the slowest fabric its bytes will
        cross. (A topk tensor still frames DENSE on tiers whose decision
        is 'none' — that is the value-neutral half, see sparse_tiers.)"""
        return self.decide(nbytes, dtype, "dcn" if self.has_dcn else "ici")

    # -- live-metrics half (value-neutral)

    def sparse_tiers(self) -> frozenset:
        """Link tiers ('local'/'cross') where topk hops frame sparse."""
        return frozenset(self._sparse_tiers)

    def refresh(self, snapshot: dict) -> dict:
        """Re-read the live per-tier wire telemetry and steer the sparse
        framing. Input is a metrics-registry snapshot; reads
        ``horovod_wire_bytes_total{tier=...}`` counters and the
        ``horovod_critical_path_wire_seconds{tier=...}`` gauges the tracing
        analyzer exports. Returns (and stores) the diagnosis dict."""
        counters = snapshot.get("counters", {}) or {}
        gauges = snapshot.get("gauges", {}) or {}

        def tier(series: dict, name: str, t: str) -> float:
            return float(series.get(f'{name}{{tier="{t}"}}', 0) or 0)

        local_b = tier(counters, "horovod_wire_bytes_total", "local")
        cross_b = tier(counters, "horovod_wire_bytes_total", "cross")
        local_s = tier(gauges, "horovod_critical_path_wire_seconds", "local")
        cross_s = tier(gauges, "horovod_critical_path_wire_seconds", "cross")
        # Which fabric is the wire time on? Critical-path seconds when the
        # analyzer ran; byte share as the fallback signal.
        if local_s or cross_s:
            bottleneck = "dcn" if cross_s >= local_s else "ici"
        elif local_b or cross_b:
            bottleneck = "dcn" if cross_b >= local_b else "ici"
        else:
            bottleneck = "dcn" if self.has_dcn else "ici"
        tiers = {"cross"}
        if bottleneck == "ici" and (local_s > 0 or local_b > 0):
            # The local fabric is where the wire time is (shared-core CI
            # boxes, oversubscribed hosts): sparse-frame it too — value-
            # neutral, so ranks may flip this at different moments.
            tiers.add("local")
        self._sparse_tiers = tiers
        self._diag = {
            "bottleneck_tier": bottleneck,
            "wire_bytes": {"local": local_b, "cross": cross_b},
            "wire_seconds": {"local": local_s, "cross": cross_s},
            "sparse_tiers": sorted(tiers),
        }
        return dict(self._diag)

    # -- reporting (cache_stats / smoke assertions / docs)

    def report(self, nbytes: int = 1 << 22,
               dtype=np.float32) -> dict:
        """The policy table for a representative large gradient plus the
        live diagnosis — what ``cache_stats()['policy']`` and the sparse
        smoke read to prove the tiers resolve differently."""
        return {
            "ici": self.decide(nbytes, dtype, "ici"),
            "dcn": self.decide(nbytes, dtype, "dcn"),
            "resolved": self.resolve(nbytes, dtype),
            "topk_ratio": self.topk_ratio,
            "has_dcn": self.has_dcn,
            "sparse_tiers": sorted(self._sparse_tiers),
            "diag": dict(self._diag),
        }


#: The dense format the compiled plane substitutes when the adaptive
#: table answers 'topk' for a fused bucket. This substitution is BY
#: DESIGN, not a gap (ISSUE 16 closes the ROADMAP open question): XLA
#: collectives have static shapes, so a runtime-sparse frame is
#: structurally unservable there — the nearest value-reducing format on
#: the same tier is the bf16 cast, and `adaptive` promises "the policy's
#: best SERVABLE format per tier", not "identical bytes to eager".
#: `horovod_compiled_adaptive_fallback_total` keeps counting the
#: substituting traces purely for observability.
COMPILED_TOPK_SUBSTITUTE = "bf16"


def compiled_tier_format(nbytes: int, dtype, tier: str,
                         with_fallback: bool = False):
    """The compiled plane's per-bucket tier resolve (ISSUE 13 satellite):
    the SAME value-changing table the eager engines evaluate per tensor,
    applied to one fused bucket on one fabric tier, with the 'topk'
    answer substituted by :data:`COMPILED_TOPK_SUBSTITUTE` — see its note
    for why that substitution is the designed behaviour. Returns the
    servable format NAME ('none'/'bf16'), or ``(format, substituted)``
    when ``with_fallback`` so the caller can count substituting traces.
    Evaluated at trace time only."""
    fmt = CompressionPolicy().decide(int(nbytes), dtype, tier)
    substituted = fmt == "topk"
    if substituted:
        fmt = COMPILED_TOPK_SUBSTITUTE
    return (fmt, substituted) if with_fallback else fmt


def resolve_format(compression: Optional[str], policy,
                   nbytes: int, dtype) -> str:
    """One-stop eager-side resolution: an explicit HOROVOD_COMPRESSION name
    passes through; 'adaptive' consults the policy. Returns a concrete
    format name ('none'/'fp16'/'bf16'/'topk')."""
    from ..compression import normalize

    name = normalize(compression)
    if name != "adaptive":
        return name
    if policy is None:
        return "none"
    return policy.resolve(nbytes, dtype)
