"""Transport-resilience policy — one deadline/retry/backoff implementation
for every socket the runtime owns (ISSUE 8 tentpole, docs/troubleshooting.md
"my ring keeps demoting to star").

Before this module, each blocking socket op picked its own patience: the
eager ring links waited 600 s, the coordinator client 120 s, BasicClient
grew a private jittered connect loop, run_command a private poll backoff.
A flaky hop therefore either hung until the stall watchdog fired or failed
on the first hiccup — there was no rung between "wait forever" and
"HorovodInternalError → full elastic reset". This module is the bottom
rung of the graded escalation ladder:

- every socket op gets a per-attempt **deadline** (``HOROVOD_NETWORK_TIMEOUT``
  seconds, applied as the socket timeout by the callers) and a **retry
  budget** (``HOROVOD_NETWORK_RETRIES`` extra attempts). A receive that
  makes progress resets its budget — the deadline bounds *idle* time, not
  transfer time, so an MB-scale frame trickling over a congested link is
  not punished for being large.
- reconnect/poll loops share one **decorrelated-jitter** backoff
  (:class:`Backoff`, capped at ``HOROVOD_NETWORK_BACKOFF_MAX_MS``), so a
  whole pod retrying in lockstep cannot hammer a recovering peer at the
  same instants.
- every rung is observable: ``horovod_transport_retries_total`` (attempts
  absorbed in place), ``horovod_transport_timeouts_total`` (budgets
  exhausted — the next rung, plane demotion, starts here) and
  ``horovod_frames_rejected_total`` (authentication failures: corrupt HMAC
  or replayed sequence numbers, treated as link faults, not crashes).

Total patience per op is ``timeout_s * (1 + retries)`` — 120 s by default,
matching the old coordinator-client behaviour while cutting the ring's
600 s hang to the same bound.
"""

from __future__ import annotations

import os
import random
import socket
import threading
import time
from dataclasses import dataclass
from typing import Optional

from .config import _env_float, _env_int

# Defaults: 30 s idle deadline x (1 + 3) attempts = 120 s total patience,
# the pre-existing coordinator-client bound. The stall watchdog's 60 s
# warning fires inside that window, so a wedged link is *named* before it
# is given up on.
DEFAULT_TIMEOUT_S = 30.0
DEFAULT_RETRIES = 3
DEFAULT_BACKOFF_MAX_MS = 2000.0


@dataclass(frozen=True)
class Policy:
    """One transport policy: per-attempt deadline, retry budget, backoff cap."""

    timeout_s: float = DEFAULT_TIMEOUT_S
    retries: int = DEFAULT_RETRIES
    backoff_max_ms: float = DEFAULT_BACKOFF_MAX_MS

    @property
    def patience_s(self) -> float:
        """Worst-case wall time one op may stay idle before failing."""
        return self.timeout_s * (1 + self.retries)


def from_env() -> Policy:
    """Parse the HOROVOD_NETWORK_* knobs (README config table)."""
    return Policy(
        timeout_s=max(_env_float("HOROVOD_NETWORK_TIMEOUT",
                                 DEFAULT_TIMEOUT_S), 0.05),
        retries=max(_env_int("HOROVOD_NETWORK_RETRIES", DEFAULT_RETRIES), 0),
        backoff_max_ms=max(_env_float("HOROVOD_NETWORK_BACKOFF_MAX_MS",
                                      DEFAULT_BACKOFF_MAX_MS), 1.0),
    )


_lock = threading.Lock()
_default: Optional[Policy] = None


def default_policy(refresh: bool = False) -> Policy:
    """Process-wide policy, parsed from the env once (``refresh=True``
    re-reads — tests and elastic re-init use it)."""
    global _default
    with _lock:
        if _default is None or refresh:
            _default = from_env()
        return _default


# ------------------------------------------------------------------ metrics

_counters: dict = {}


def _counter(name: str, help_: str):
    c = _counters.get(name)
    if c is None:
        from ..metrics import registry

        c = _counters[name] = registry().counter(name, help=help_)
    return c


def retries_counter():
    return _counter(
        "horovod_transport_retries_total",
        "socket ops retried in place after an idle deadline "
        "(HOROVOD_NETWORK_TIMEOUT) — rung 1 of the escalation ladder")


def timeouts_counter():
    return _counter(
        "horovod_transport_timeouts_total",
        "socket ops that exhausted their retry budget "
        "(HOROVOD_NETWORK_RETRIES) and failed — what escalates to rung 2, "
        "plane demotion")


def frames_rejected_counter():
    return _counter(
        "horovod_frames_rejected_total",
        "authenticated frames rejected (HMAC mismatch: corruption, replay, "
        "or reordering) — treated as a link fault, never unpickled")


# ------------------------------------------------------------------ backoff

class Backoff:
    """Decorrelated-jitter backoff (the AWS architecture-blog variant):
    ``delay = min(cap, uniform(base, 3 * previous))``. One implementation
    for every reconnect/poll loop (BasicClient connect, run_command's
    remote poll, and anything new) so there is exactly one set of knobs."""

    def __init__(self, base_s: float = 0.05, cap_s: Optional[float] = None,
                 policy: Optional[Policy] = None, rng=random) -> None:
        p = policy or default_policy()
        self.base_s = max(base_s, 0.001)
        self.cap_s = cap_s if cap_s is not None else p.backoff_max_ms / 1000.0
        self._prev = self.base_s
        self._rng = rng

    def next(self) -> float:
        d = min(self.cap_s, self._rng.uniform(self.base_s, self._prev * 3))
        self._prev = max(d, self.base_s)
        return d

    def sleep(self) -> float:
        d = self.next()
        time.sleep(d)
        return d

    def reset(self) -> None:
        self._prev = self.base_s


# ------------------------------------------------------------- resilient IO

def recv_exact(sock: socket.socket, n: int,
               policy: Optional[Policy] = None) -> bytearray:
    """Receive exactly ``n`` bytes into a preallocated buffer (quadratic
    bytes-+= avoided), with the retry ladder applied *when the socket has a
    timeout set*: each idle period of the socket timeout costs one retry
    from the budget; any received byte resets the budget (the deadline
    bounds idle time, not frame size). A socket with no timeout keeps the
    historical block-forever behaviour — request servers waiting for the
    next command must idle indefinitely."""
    pol = policy or default_policy()
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    attempts = 0
    while got < n:
        try:
            r = sock.recv_into(view[got:], n - got)
        except (socket.timeout, TimeoutError) as e:
            attempts += 1
            if attempts > pol.retries:
                timeouts_counter().inc()
                raise TimeoutError(
                    f"recv idle past deadline: {got}/{n} bytes after "
                    f"{attempts} attempts of "
                    f"{sock.gettimeout() or pol.timeout_s:g}s each "
                    "(HOROVOD_NETWORK_TIMEOUT / HOROVOD_NETWORK_RETRIES)"
                ) from e
            retries_counter().inc()
            continue
        if not r:
            raise ConnectionError("peer closed")
        got += r
        attempts = 0
    return buf


def send_all(sock: socket.socket, data) -> None:
    """``sendall`` with the timeout classified and counted. A send stalled
    past the socket deadline leaves the stream in an undefined partial
    state, so it is not retried — it fails as a link fault (the demotion
    rung handles it)."""
    try:
        sock.sendall(data)
    except (socket.timeout, TimeoutError) as e:
        timeouts_counter().inc()
        raise TimeoutError(
            "send stalled past the socket deadline "
            "(HOROVOD_NETWORK_TIMEOUT); stream state unknown — failing the "
            "link") from e


def bind_with_retry(bind_fn, port: int, window: int = 1,
                    deadline_s: float = 0.0, sleep_s: float = 0.2):
    """EADDRINUSE-tolerant server bind — ONE implementation for every
    listener the runtime opens (ISSUE 20 satellite; previously the
    metrics exporter's port-window sweep and the coordinator's same-port
    retry were two private copies, and test launchers had neither).

    Tries ``bind_fn(port + offset)`` for each offset in ``window`` (a
    sliding sweep — an elastic respawn lands where the previous
    generation's exporter still holds ``port + local_rank``); when the
    whole window is busy, sleeps ``sleep_s`` and re-sweeps until
    ``deadline_s`` has elapsed (a re-rendezvous rebinds the SAME address
    moments after the old server closed — lingering accepted sockets can
    hold it for a beat despite SO_REUSEADDR). Any other OSError — and
    EADDRINUSE past the window and deadline — raises. Returns
    ``(bound_object, offset)`` so the caller can log a port slide."""
    import errno

    deadline = time.monotonic() + deadline_s
    window = max(window, 1)
    while True:
        last: Optional[OSError] = None
        for offset in range(window):
            try:
                return bind_fn(port + offset), offset
            except OSError as e:
                if e.errno != errno.EADDRINUSE:
                    raise
                last = e
        if time.monotonic() >= deadline:
            raise last
        time.sleep(sleep_s)


def _reset_for_tests() -> None:
    """Drop cached policy/counters (unit tests flip env vars)."""
    global _default
    with _lock:
        _default = None
        _counters.clear()
