"""Runtime configuration for horovod_tpu.

Mirrors the reference's env-var config surface (horovod/common/operations.h:56-66,
parsed once in BackgroundThreadLoop, operations.cc:1837-1909). All knobs are
environment variables read once at init(); the autotuner may override the
non-pinned ones at runtime, exactly like the reference's ParameterManager
(parameter_manager.cc:145-233).

TPU-first differences:
- fusion threshold applies to gradient-bucket concatenation before a single
  ``psum`` (the XLA collective replaces ncclAllReduce);
- cycle time drives the host-side negotiation engine used by the eager
  (torch / numpy) path only — inside ``jit`` ordering is static at trace time.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    if v is None or v == "":
        return default
    try:
        return int(v)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    if v is None or v == "":
        return default
    try:
        return float(v)
    except ValueError:
        return default


def _env_bool(name: str, default: bool = False) -> bool:
    v = os.environ.get(name)
    if v is None or v == "":  # unset and empty both mean "use the default"
        return default
    return v.lower() not in ("0", "false", "no")


# Default tensor fusion threshold: 64 MiB (reference operations.cc:1838).
DEFAULT_FUSION_THRESHOLD = 64 * 1024 * 1024
# Default gradient bucket count for the compiled allreduce path: 1 keeps the
# historical single-fused-buffer behaviour; >1 splits the gradient set into
# that many reverse-backward-order buckets so XLA can overlap early buckets'
# allreduce with the rest of the backward pass (fusion.build_plan).
DEFAULT_NUM_BUCKETS = 1
# Default cycle time: 5 ms (reference operations.cc:1844).
DEFAULT_CYCLE_TIME_MS = 5.0
# Buckets below this byte size skip wire compression on the compiled plane:
# the cast pair costs more than the bytes it saves on tiny buffers, and
# non-gradient scalars (loss, counters) keep full precision.
DEFAULT_COMPRESSION_MIN_BYTES = 4096


def _env_compression() -> str:
    """HOROVOD_COMPRESSION={none,fp16,bf16,topk,adaptive}: the wire format
    every data plane applies to gradient payloads (docs/compression.md).
    ``topk@<ratio>`` specs (the autotune spelling) are kept verbatim —
    the engine's parse_spec extracts the ratio. Unknown values warn and
    fall back to none — config parsing never takes the job down."""
    from ..compression import WIRE_DTYPES, parse_spec

    v = os.environ.get("HOROVOD_COMPRESSION", "none").lower() or "none"
    if v not in WIRE_DTYPES and parse_spec(v) == ("none", None):
        import sys

        print(f"[horovod_tpu/warning] unknown HOROVOD_COMPRESSION={v!r}; "
              f"expected one of {sorted(WIRE_DTYPES)} or 'topk@<ratio>'; "
              "using 'none'", file=sys.stderr)
        return "none"
    return v
# Stall-check warning period: 60 s (reference operations.cc:258 STALL_WARNING_TIME).
STALL_WARNING_TIME_S = 60.0
# Stall-shutdown escalation: 0 disables (reference STALL_SHUTDOWN_TIME is
# likewise opt-in); > 0 makes the watchdog fail collectives stalled past it.
STALL_SHUTDOWN_TIME_S = 0.0


def _env_stall_check_time(default: float = STALL_WARNING_TIME_S) -> float:
    """HOROVOD_STALL_CHECK_TIME (reference spelling) with the historical
    HOROVOD_STALL_WARNING_TIME accepted as a fallback alias."""
    v = os.environ.get("HOROVOD_STALL_CHECK_TIME")
    if v not in (None, ""):
        try:
            return float(v)
        except ValueError:
            pass
    return _env_float("HOROVOD_STALL_WARNING_TIME", default)

# XLA compile options that let the scheduler hide collective latency behind
# compute — the compiled-plane analog of the reference's background thread
# starting allreduces while the backward pass still runs. Appended to
# XLA_FLAGS (not jax.config: these are DebugOptions, which jax only reads
# from the env) by enable_latency_hiding_scheduler() BEFORE backend init.
LATENCY_HIDING_XLA_FLAGS = (
    "--xla_tpu_enable_latency_hiding_scheduler=true",
)


def enable_latency_hiding_scheduler(env=None) -> bool:
    """Append the latency-hiding scheduler flags to XLA_FLAGS (idempotent).

    Returns True when the flags are (now) present. Must run before the XLA
    backend initializes — jax reads XLA_FLAGS once at first device use — so
    callers (init(), bench.py) invoke it as early as possible. Gated behind
    HOROVOD_LATENCY_HIDING because the scheduler changes compile time and
    schedule shape; the A/B bench measures whether it pays on a platform.
    """
    if env is None:
        env = os.environ
    flags = env.get("XLA_FLAGS", "")
    added = False
    for f in LATENCY_HIDING_XLA_FLAGS:
        if f.split("=")[0] not in flags:
            flags = (flags + " " + f).strip()
            added = True
    if added:
        env["XLA_FLAGS"] = flags
    return True


def clamp_shm_bytes(v: int) -> int:
    """Mirror of the native clamp (shm_ring.h shm_ring_capacity): power of
    two in [64 KiB, 1 GiB], so config() reports the EFFECTIVE capacity."""
    v = max(1 << 16, min(int(v), 1 << 30))
    p = 1
    while p * 2 <= v:
        p *= 2
    return p


@dataclass
class Config:
    """Knobs parsed from the environment, one field per reference env var."""

    fusion_threshold: int = DEFAULT_FUSION_THRESHOLD      # HOROVOD_FUSION_THRESHOLD
    num_buckets: int = DEFAULT_NUM_BUCKETS                # HOROVOD_NUM_BUCKETS
    latency_hiding: bool = False                          # HOROVOD_LATENCY_HIDING
    cycle_time_ms: float = DEFAULT_CYCLE_TIME_MS          # HOROVOD_CYCLE_TIME
    timeline: str = ""                                    # HOROVOD_TIMELINE
    timeline_mark_cycles: bool = False                    # HOROVOD_TIMELINE_MARK_CYCLES
    autotune: bool = False                                # HOROVOD_AUTOTUNE
    autotune_log: str = ""                                # HOROVOD_AUTOTUNE_LOG
    stall_check_disable: bool = False                     # HOROVOD_STALL_CHECK_DISABLE
    # HOROVOD_STALL_CHECK_TIME (alias: HOROVOD_STALL_WARNING_TIME)
    stall_warning_s: float = STALL_WARNING_TIME_S
    stall_shutdown_s: float = STALL_SHUTDOWN_TIME_S       # HOROVOD_STALL_SHUTDOWN_TIME
    metrics_port: int = 0                                 # HOROVOD_METRICS_PORT (0 = off)
    hierarchical_allreduce: bool = False                  # HOROVOD_HIERARCHICAL_ALLREDUCE
    hierarchical_allgather: bool = False                  # HOROVOD_HIERARCHICAL_ALLGATHER
    # Shared-memory data plane for same-host ring links (cc/src/shm_ring.h;
    # the reference's NCCL-shm / MPI shared-window intra-host paths,
    # operations.cc:929-1034). The native binding exports these into the
    # env right before engine init, so Config(shm=...) works like every
    # other field whether or not the env var was set.
    # Env-aware defaults (field factories, unlike the static defaults
    # above): a directly-constructed Config(cycle_time_ms=...) — the test
    # idiom — must still honor HOROVOD_SHM=0 from the launcher env, because
    # the binding UNCONDITIONALLY exports these two back into the env.
    shm: bool = field(                                    # HOROVOD_SHM (0 disables)
        default_factory=lambda: _env_bool("HOROVOD_SHM", True))
    shm_bytes: int = field(                               # HOROVOD_SHM_BYTES
        default_factory=lambda: clamp_shm_bytes(
            _env_int("HOROVOD_SHM_BYTES", 16 << 20)))
    # Steady-state fast path (docs/eager-engine.md). Env-aware defaults for
    # the same reason as shm above: tests construct Config(...) directly and
    # the launcher env must still win.
    cache_capacity: int = field(                          # HOROVOD_CACHE_CAPACITY (0 disables)
        default_factory=lambda: max(
            0, _env_int("HOROVOD_CACHE_CAPACITY", 1024)))
    ring_data_plane: bool = field(                        # HOROVOD_RING_DATA_PLANE (0 disables)
        default_factory=lambda: _env_bool("HOROVOD_RING_DATA_PLANE", True))
    # On-the-wire gradient compression (ISSUE 5, docs/compression.md).
    # Env-aware defaults like shm/cache above: tests and bench workers
    # construct Config(...) directly and the launcher env must still win.
    compression: str = field(                             # HOROVOD_COMPRESSION
        default_factory=_env_compression)
    compression_error_feedback: bool = field(             # HOROVOD_COMPRESSION_ERROR_FEEDBACK
        default_factory=lambda: _env_bool(
            "HOROVOD_COMPRESSION_ERROR_FEEDBACK", False))
    compression_min_bytes: int = field(                   # HOROVOD_COMPRESSION_MIN_BYTES
        default_factory=lambda: max(0, _env_int(
            "HOROVOD_COMPRESSION_MIN_BYTES", DEFAULT_COMPRESSION_MIN_BYTES)))
    # Sparse top-k wire format (ISSUE 9, docs/compression.md): fraction of
    # entries a topk-compressed gradient keeps. Env-aware default like the
    # compression fields above. 0.0 means "unset" — resolution falls back
    # to HOROVOD_TOPK_RATIO / the 1% default at use time.
    topk_ratio: float = field(                            # HOROVOD_TOPK_RATIO
        default_factory=lambda: _env_float("HOROVOD_TOPK_RATIO", 0.0))
    # Fabric-aware compiled plane (ISSUE 7, docs/hierarchical.md): a wire
    # dtype and a bucket-size cap applied to the DCN (cross-host) tier of
    # the hierarchical ladder only. Empty dcn_compression inherits the
    # global HOROVOD_COMPRESSION; dcn_fusion_threshold 0 means no separate
    # DCN cap. Env-aware defaults for the same reason as the fields above.
    dcn_compression: str = field(                         # HOROVOD_DCN_COMPRESSION
        default_factory=lambda: os.environ.get(
            "HOROVOD_DCN_COMPRESSION", "").lower())
    dcn_fusion_threshold: int = field(                    # HOROVOD_DCN_FUSION_THRESHOLD
        default_factory=lambda: max(0, _env_int(
            "HOROVOD_DCN_FUSION_THRESHOLD", 0)))
    # Sharded data parallelism (ISSUE 14, docs/sharded.md). HOROVOD_MESH
    # names the 2-D ('batch','shard') mesh shape as "<batch>x<shard>"
    # (empty = pure DP, shard=1); HOROVOD_SHARD_PARAMS flips
    # DistributedOptimizer onto the ZeRO wire pattern (reduce-scatter
    # grads into the owning shard, bucketed allgather parameter refresh).
    # Env-aware defaults for the same reason as the fields above.
    mesh: str = field(                                    # HOROVOD_MESH
        default_factory=lambda: os.environ.get("HOROVOD_MESH", "").strip())
    shard_params: bool = field(                           # HOROVOD_SHARD_PARAMS
        default_factory=lambda: _env_bool("HOROVOD_SHARD_PARAMS", False))
    # Distributed tracing (ISSUE 6, docs/tracing.md): non-empty directory
    # enables per-rank span capture on every data plane. Env-aware default
    # like compression above: workers constructed with Config(...) directly
    # must still honor the launcher-exported HOROVOD_TRACE_DIR.
    trace_dir: str = field(                               # HOROVOD_TRACE_DIR
        default_factory=lambda: os.environ.get("HOROVOD_TRACE_DIR", ""))
    log_level: str = "warning"                            # HOROVOD_LOG_LEVEL
    log_hide_time: bool = False                           # HOROVOD_LOG_HIDE_TIME
    # Which env vars were explicitly pinned (autotuner must not override,
    # reference operations.cc:1840-1879 "fixed=true").
    pinned: set = field(default_factory=set)

    @classmethod
    def from_env(cls) -> "Config":
        cfg = cls(
            fusion_threshold=_env_int("HOROVOD_FUSION_THRESHOLD", DEFAULT_FUSION_THRESHOLD),
            num_buckets=max(1, _env_int("HOROVOD_NUM_BUCKETS", DEFAULT_NUM_BUCKETS)),
            latency_hiding=_env_bool("HOROVOD_LATENCY_HIDING"),
            cycle_time_ms=_env_float("HOROVOD_CYCLE_TIME", DEFAULT_CYCLE_TIME_MS),
            timeline=os.environ.get("HOROVOD_TIMELINE", ""),
            timeline_mark_cycles=_env_bool("HOROVOD_TIMELINE_MARK_CYCLES"),
            autotune=_env_bool("HOROVOD_AUTOTUNE"),
            autotune_log=os.environ.get("HOROVOD_AUTOTUNE_LOG", ""),
            stall_check_disable=_env_bool("HOROVOD_STALL_CHECK_DISABLE"),
            stall_warning_s=_env_stall_check_time(),
            stall_shutdown_s=_env_float("HOROVOD_STALL_SHUTDOWN_TIME",
                                        STALL_SHUTDOWN_TIME_S),
            metrics_port=_env_int("HOROVOD_METRICS_PORT", 0),
            hierarchical_allreduce=_env_bool("HOROVOD_HIERARCHICAL_ALLREDUCE"),
            hierarchical_allgather=_env_bool("HOROVOD_HIERARCHICAL_ALLGATHER"),
            # shm / shm_bytes: omitted — their default_factory already reads
            # the env, and duplicating the parse here would give two places
            # for the semantics to drift apart.
            log_level=os.environ.get("HOROVOD_LOG_LEVEL", "warning").lower(),
            log_hide_time=_env_bool("HOROVOD_LOG_HIDE_TIME"),
        )
        for var in (
            "HOROVOD_FUSION_THRESHOLD",
            "HOROVOD_NUM_BUCKETS",
            "HOROVOD_CYCLE_TIME",
            "HOROVOD_HIERARCHICAL_ALLREDUCE",
            "HOROVOD_HIERARCHICAL_ALLGATHER",
        ):
            if os.environ.get(var) not in (None, ""):
                cfg.pinned.add(var)
        return cfg
