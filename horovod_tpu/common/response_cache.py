"""Response cache for the eager engines — the steady-state fast path.

The reference's biggest eager-path latency win was the *response cache*
(horovod/common/response_cache.{cc,h}): after a tensor's first full
negotiation, every rank remembers the coordinator's response under a small
integer *bit*, and subsequent ticks exchange only per-rank bitvectors of
pending bits instead of full request lists.  The per-tick control frame
becomes a handful of bytes regardless of how many tensors the training
step re-submits.

This module holds the two Python-side halves used by
``horovod_tpu/common/engine.py`` (the C++ engine carries the same design
in ``cc/src/cache.h``):

- :class:`ResponseCache` — the *authority*, owned by the rank-0
  coordinator.  Assigns bits to validated signatures, bounds the table at
  ``HOROVOD_CACHE_CAPACITY`` entries with LRU eviction (never evicting a
  bit whose tensor is mid-negotiation), and records evictions so they can
  be broadcast to every rank.
- :class:`CacheMirror` — the per-rank mirror.  Pure follower: it only
  inserts what the coordinator announced and drops what the coordinator
  evicted, so it is bounded by the authority's capacity and can be flushed
  unilaterally at any time (the coordinator re-announces assignments with
  every result delivery, so a flushed rank self-heals).

A cache *key* is the full request signature ``(name, op, shape, dtype,
root, average)``: a shape or dtype change produces a different key, which
misses, falls back to a full request, and makes the authority evict the
stale bit for that name (shape-change invalidation).  World-size changes
and elastic resets rebuild the engine — and with it both cache halves —
so a stale response is never servable across memberships.
"""

from __future__ import annotations

import os
from collections import OrderedDict
from typing import Any, Optional

DEFAULT_CACHE_CAPACITY = 1024


def cache_capacity_from_env() -> int:
    """HOROVOD_CACHE_CAPACITY: max cached signatures (0 disables)."""
    v = os.environ.get("HOROVOD_CACHE_CAPACITY")
    if v in (None, ""):
        return DEFAULT_CACHE_CAPACITY
    try:
        return max(0, int(v))
    except ValueError:
        return DEFAULT_CACHE_CAPACITY


def request_key(req: dict) -> tuple:
    """Signature tuple for a request dict (engine wire shape). The trailing
    element is the wire FORMAT — a wire dtype name ('bfloat16'/'float16'),
    the sparse 'topk' tag (ISSUE 9), or '' for uncompressed — so a cache
    bit bound under one format invalidates when HOROVOD_COMPRESSION (or an
    adaptive-policy resolution) changes, exactly like a shape change —
    mirroring PyEngine._entry_key."""
    return (req["name"], req["op"], tuple(req["shape"]), req["dtype"],
            req.get("root", 0), bool(req.get("average", True)),
            str(req.get("wire") or ""))


class ResponseCache:
    """Coordinator-side bit table: signature -> bit, LRU-bounded.

    Single-threaded by contract (the coordinator mutates it under its own
    lock).  ``assign`` returns the new bit plus any bits evicted to make
    room; the caller is responsible for broadcasting both.
    """

    def __init__(self, capacity: Optional[int] = None) -> None:
        self.capacity = (cache_capacity_from_env()
                         if capacity is None else max(0, int(capacity)))
        # bit -> (key, meta); OrderedDict doubles as the LRU order
        # (oldest first).
        self._bits: "OrderedDict[int, tuple[tuple, Any]]" = OrderedDict()
        self._key_to_bit: dict[tuple, int] = {}
        self._name_to_bit: dict[str, int] = {}
        self._next_bit = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    # -- lookups

    def __len__(self) -> int:
        return len(self._bits)

    @property
    def enabled(self) -> bool:
        return self.capacity > 0

    def bit_for(self, key: tuple) -> Optional[int]:
        return self._key_to_bit.get(key)

    def lookup_bit(self, bit: int) -> Optional[tuple]:
        """(key, meta) for a live bit, refreshing its LRU position."""
        entry = self._bits.get(bit)
        if entry is None:
            return None
        self._bits.move_to_end(bit)
        return entry

    def bit_for_name(self, name: str) -> Optional[int]:
        return self._name_to_bit.get(name)

    # -- mutation

    def assign(self, key: tuple, meta: Any,
               in_use: Optional[set] = None) -> tuple[Optional[int], list]:
        """Bind ``key`` to a fresh bit; returns ``(bit, evicted)`` where
        ``evicted`` is a list of ``(bit, key, meta)`` triples (the caller
        broadcasts them and keeps tombstones until every rank has seen the
        eviction).

        Evicts first any stale bit held by the same tensor *name* under a
        different signature (shape/dtype change), then the LRU entry if at
        capacity.  Bits named in ``in_use`` (mid-negotiation) are never
        evicted; if nothing is evictable the assignment is skipped
        (``bit=None``) and the tensor simply stays on the full-request
        path.
        """
        if not self.enabled:
            return None, []
        evicted: list = []
        name = key[0]
        stale = self._name_to_bit.get(name)
        if stale is not None and self._bits[stale][0] != key:
            evicted.append(self._drop(stale))
            self.evictions += 1
        if key in self._key_to_bit:  # already assigned (idempotent)
            return self._key_to_bit[key], evicted
        while len(self._bits) >= self.capacity:
            victim = self._lru_victim(in_use or set())
            if victim is None:
                return None, evicted
            evicted.append(self._drop(victim))
            self.evictions += 1
        bit = self._next_bit
        self._next_bit += 1
        self._bits[bit] = (key, meta)
        self._key_to_bit[key] = bit
        self._name_to_bit[name] = bit
        return bit, evicted

    def evict_name(self, name: str) -> list:
        """Evict the bit bound to ``name``; returns [(bit, key, meta)]."""
        bit = self._name_to_bit.get(name)
        if bit is None:
            return []
        self.evictions += 1
        return [self._drop(bit)]

    def flush(self) -> list:
        """Drop everything; returns the evicted (bit, key, meta) triples
        (broadcast as evictions so every mirror follows)."""
        return [self._drop(bit) for bit in list(self._bits)]

    def _lru_victim(self, in_use: set) -> Optional[int]:
        for bit, (key, _meta) in self._bits.items():  # oldest first
            if key[0] not in in_use:
                return bit
        return None

    def _drop(self, bit: int) -> tuple:
        key, meta = self._bits.pop(bit)
        self._key_to_bit.pop(key, None)
        if self._name_to_bit.get(key[0]) == bit:
            self._name_to_bit.pop(key[0], None)
        return (bit, key, meta)

    def stats(self) -> dict:
        return {"size": len(self._bits), "capacity": self.capacity,
                "hits": self.hits, "misses": self.misses,
                "evictions": self.evictions}


class CacheMirror:
    """Rank-side follower table: key <-> bit, updated only from the
    coordinator's assign/evict announcements."""

    def __init__(self) -> None:
        self._key_to_bit: dict[tuple, int] = {}
        self._bit_to_key: dict[int, tuple] = {}
        self.hits = 0
        self.misses = 0

    def __len__(self) -> int:
        return len(self._key_to_bit)

    def lookup(self, key: tuple) -> Optional[int]:
        bit = self._key_to_bit.get(key)
        if bit is not None:
            self.hits += 1
        else:
            self.misses += 1
        return bit

    def peek(self, key: tuple) -> Optional[int]:
        """Lookup without touching the hit/miss stats (re-polls)."""
        return self._key_to_bit.get(key)

    def apply(self, assign, evict) -> None:
        """Apply one response's announcements (evictions first)."""
        for bit in evict or ():
            key = self._bit_to_key.pop(bit, None)
            if key is not None and self._key_to_bit.get(key) == bit:
                self._key_to_bit.pop(key, None)
        for bit, key in assign or ():
            key = tuple(key)
            key = ((key[0], key[1], tuple(key[2]), key[3], key[4],
                    bool(key[5])) + tuple(str(k) for k in key[6:]))
            old = self._key_to_bit.get(key)
            if old is not None:
                self._bit_to_key.pop(old, None)
            self._key_to_bit[key] = bit
            self._bit_to_key[bit] = key

    def flush(self) -> None:
        self._key_to_bit.clear()
        self._bit_to_key.clear()

    def stats(self) -> dict:
        return {"size": len(self._key_to_bit), "hits": self.hits,
                "misses": self.misses}
