"""Lifecycle state — the TPU analog of HorovodBasics + HorovodGlobalState.

The reference's HorovodBasics (horovod/common/__init__.py:51-154) is a ctypes
wrapper over the C ABI (horovod_init/_rank/_size/..., operations.h:76-106,
operations.cc:2413-2468). Here the same contract is split:

- topology & lifecycle live in this Python object (no MPI to spin up);
- the native background engine (horovod_tpu/cc) is attached lazily for the
  eager/host data plane and owns the coordinator tick, fusion planner,
  timeline and stall check, exactly like the reference's background thread
  (operations.cc:1695-2380);
- the compiled data plane needs no runtime state at all: mesh axes are the
  communicators.

``init()`` is idempotent (reference InitializeHorovodOnce test_and_set guard,
operations.cc:2384-2401); ``shutdown()`` allows re-init (operations.cc:2424-2432).
"""

from __future__ import annotations

import atexit
import threading
from typing import Optional, Sequence

from .config import Config
from .topology import Topology, detect, num_devices, num_local_devices
from ..utils.logging import log


class NotInitializedError(RuntimeError):
    def __init__(self) -> None:
        super().__init__(
            "Horovod has not been initialized; use hvd.init()."
        )


class _State:
    """Singleton global state (reference HorovodGlobalState, operations.cc:115)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.initialized = False
        self.topology: Optional[Topology] = None
        self.config: Optional[Config] = None
        self.engine = None          # native engine handle, attached lazily
        self.mesh = None            # default data-parallel mesh, created lazily
        self._atexit_registered = False


_state = _State()


def init(comm: Optional[Sequence[int]] = None) -> None:
    """Initialize. ``comm`` may be a list of ranks forming a subset world
    (reference horovod_init with ranks[], operations.cc:2415; mpi4py comms have
    no TPU analog and raise)."""
    with _state._lock:
        if _state.initialized:
            return
        topo = detect()
        if comm is not None:
            if not isinstance(comm, (list, tuple)):
                raise ValueError(
                    "comm must be a list of ranks on TPU (MPI communicators do not exist here)"
                )
            ranks = sorted(set(comm))
            if any(not (0 <= r < topo.size) for r in ranks):
                raise ValueError(
                    f"comm {ranks} contains ranks outside the launched world "
                    f"of size {topo.size}")
            if topo.rank not in ranks and topo.size > 1:
                raise ValueError(
                    f"rank {topo.rank} is not a member of comm {ranks}: this "
                    "process cannot participate in the sub-world's "
                    "collectives. Only member processes may call "
                    "init(comm=...); non-members should skip Horovod work "
                    "(or exit) — they must NOT fall back to init(), which "
                    "would target the same coordinator address.")
            if topo.size > 1 and len(ranks) != topo.size:
                if ranks[0] != 0:
                    # The sub-world's rank 0 binds HOROVOD_COORD_ADDR, which
                    # names ORIGINAL rank 0's host: on a multi-host job where
                    # the member at ranks[0] lives elsewhere, that bind fails
                    # (EADDRNOTAVAIL). Warn with the fix up front.
                    log("warning",
                        f"init(comm={ranks}): member rank {ranks[0]} will "
                        "bind the coordinator at HOROVOD_COORD_ADDR. If it "
                        "is not on the same host as the original rank 0, "
                        "re-export HOROVOD_COORD_ADDR on every member to an "
                        "address local to that member before init.")
                # Sub-world semantics (reference horovod_init with ranks[],
                # operations.cc:2415): rank/size are re-indexed within the
                # subset — the member at ranks[0] becomes rank 0 and binds
                # the coordinator address, so the control plane and ring are
                # exactly a world of len(ranks). Host coordinates are NOT
                # preserved: a member only knows its own host placement, not
                # the other members', so any local/cross guess would build
                # wrong topology (the round-3 bug: local_size=min(...) could
                # group ranks that share no host). The subset world uses the
                # consistent one-rank-per-host view — local_rank 0, hierarchy
                # simply not available — which every rank derives identically
                # from `ranks` alone. A ranks list naming the FULL world is
                # plain init (reference accepts this too) and keeps the real
                # host topology — the branch guard above.
                topo = Topology(
                    rank=ranks.index(topo.rank),
                    size=len(ranks),
                    local_rank=0,
                    local_size=1,
                    cross_rank=ranks.index(topo.rank),
                    cross_size=len(ranks),
                )
        _state.topology = topo
        _state.config = Config.from_env()
        _state.initialized = True
        if not _state._atexit_registered:
            atexit.register(shutdown)
            _state._atexit_registered = True
        log("debug", f"horovod_tpu initialized: {topo}", rank=topo.rank)


def shutdown() -> None:
    """Tear down (reference horovod_shutdown, operations.cc:2424-2432);
    re-init is allowed afterwards."""
    with _state._lock:
        if not _state.initialized:
            return
        if _state.engine is not None:
            try:
                _state.engine.shutdown()
            except Exception as e:  # pragma: no cover
                log("warning", f"engine shutdown failed: {e}")
            _state.engine = None
        _state.mesh = None
        _state.topology = None
        _state.config = None
        _state.initialized = False


def is_initialized() -> bool:
    return _state.initialized


def _topo() -> Topology:
    if not _state.initialized or _state.topology is None:
        raise NotInitializedError()
    return _state.topology


def rank() -> int:
    return _topo().rank


def size() -> int:
    return _topo().size


def local_rank() -> int:
    return _topo().local_rank


def local_size() -> int:
    return _topo().local_size


def cross_rank() -> int:
    return _topo().cross_rank


def cross_size() -> int:
    return _topo().cross_size


def is_homogeneous() -> bool:
    return _topo().is_homogeneous


def config() -> Config:
    if not _state.initialized or _state.config is None:
        raise NotInitializedError()
    return _state.config


def mpi_threads_supported() -> bool:
    """Parity shim for hvd.mpi_threads_supported() (operations.cc:2460-2467).
    There is no MPI on TPU; the host control plane is always thread-safe."""
    _topo()
    return True


def default_mesh():
    """Lazily-created 1-D 'hvd' mesh over all visible chips."""
    _topo()
    if _state.mesh is None:
        from ..parallel.mesh import data_parallel_mesh

        _state.mesh = data_parallel_mesh()
    return _state.mesh


def engine():
    """Lazily attach the native eager engine (host data plane)."""
    _topo()
    if _state.engine is None:
        from . import engine as engine_mod

        _state.engine = engine_mod.create(_topo(), config())
    return _state.engine
