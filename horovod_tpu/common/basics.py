"""Lifecycle state — the TPU analog of HorovodBasics + HorovodGlobalState.

The reference's HorovodBasics (horovod/common/__init__.py:51-154) is a ctypes
wrapper over the C ABI (horovod_init/_rank/_size/..., operations.h:76-106,
operations.cc:2413-2468). Here the same contract is split:

- topology & lifecycle live in this Python object (no MPI to spin up);
- the native background engine (horovod_tpu/cc) is attached lazily for the
  eager/host data plane and owns the coordinator tick, fusion planner,
  timeline and stall check, exactly like the reference's background thread
  (operations.cc:1695-2380);
- the compiled data plane needs no runtime state at all: mesh axes are the
  communicators.

``init()`` is idempotent (reference InitializeHorovodOnce test_and_set guard,
operations.cc:2384-2401); ``shutdown()`` allows re-init (operations.cc:2424-2432).
"""

from __future__ import annotations

import atexit
import os
import threading
from typing import Optional, Sequence

from .config import Config, _env_bool, enable_latency_hiding_scheduler
from .topology import Topology, detect, num_devices, num_local_devices
from ..utils.logging import log


class NotInitializedError(RuntimeError):
    def __init__(self) -> None:
        super().__init__(
            "Horovod has not been initialized; use hvd.init()."
        )


class _State:
    """Singleton global state (reference HorovodGlobalState, operations.cc:115)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.initialized = False
        self.topology: Optional[Topology] = None
        self.config: Optional[Config] = None
        self.engine = None          # native engine handle, attached lazily
        self.mesh = None            # default data-parallel mesh, created lazily
        self.metrics_server = None  # HTTP exposition (HOROVOD_METRICS_PORT)
        self._atexit_registered = False


_state = _State()


def _maybe_init_jax_distributed() -> None:
    """Join the JAX distributed runtime when launched for it.

    This is the compiled plane's world formation — the analog of the
    reference's MPI_COMM_WORLD + NCCL communicator setup
    (operations.cc:1728-1797), done once per process before any backend use.
    The launcher (horovod_tpu.runner) negotiates a coordination-service
    address on rank 0's host and exports it as HOROVOD_JAX_COORDINATOR;
    opting in (hvdrun --jax-distributed / run(jax_distributed=True) /
    HOROVOD_JAX_DISTRIBUTED=1) makes init() federate the processes so
    ``jax.devices()`` becomes the GLOBAL device list and jitted collectives
    span process boundaries (N hosts x M local chips, the pod execution
    shape). Off by default: a single-chip box can't share its chip between
    workers, and eager/torch-only jobs don't need a JAX backend at all.
    """
    if os.environ.get("HOROVOD_JAX_DISTRIBUTED") != "1":
        return
    coord = os.environ.get("HOROVOD_JAX_COORDINATOR")
    if not coord:
        raise RuntimeError(
            "HOROVOD_JAX_DISTRIBUTED=1 but no HOROVOD_JAX_COORDINATOR: "
            "launch through horovod_tpu.runner (hvdrun --jax-distributed), "
            "or export the coordinator address yourself.")
    if "HOROVOD_SIZE" not in os.environ or "HOROVOD_RANK" not in os.environ:
        raise RuntimeError(
            "HOROVOD_JAX_DISTRIBUTED=1 needs HOROVOD_RANK and HOROVOD_SIZE "
            "(process_id / num_processes for the JAX runtime); the launcher "
            "exports them — a hand-rolled launch must too.")
    import jax

    from ..compat import distributed_is_initialized

    if distributed_is_initialized():
        return  # re-init after shutdown(): the runtime outlives the hvd state
    try:  # diagnostics-only guard on a private API: skip if jax moved it
        from jax._src import xla_bridge

        backend_up = xla_bridge.backends_are_initialized()
    except Exception:  # pragma: no cover - jax internals changed
        backend_up = False
    if backend_up:  # pragma: no cover - misuse guard
        raise RuntimeError(
            "hvd.init() with HOROVOD_JAX_DISTRIBUTED=1 must run before any "
            "JAX computation: the backend is already initialized, so this "
            "process can no longer join the multi-process runtime.")
    # Cross-process collectives on the CPU backend (virtual-device testing,
    # SURVEY.md §4) ride gloo; a no-op for the TPU backend, which uses ICI/DCN.
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # pragma: no cover - older jaxlib without the option
        pass
    jax.distributed.initialize(
        coordinator_address=coord,
        num_processes=int(os.environ["HOROVOD_SIZE"]),
        process_id=int(os.environ["HOROVOD_RANK"]),
    )
    # Log from env, not jax.process_index()/device_count(): those would
    # force full backend initialization inside init() as a side effect of a
    # debug message.
    log("debug",
        f"joined JAX distributed runtime at {coord} as process "
        f"{os.environ['HOROVOD_RANK']}/{os.environ['HOROVOD_SIZE']}")


def init(comm: Optional[Sequence[int]] = None) -> None:
    """Initialize. ``comm`` may be a list of ranks forming a subset world
    (reference horovod_init with ranks[], operations.cc:2415; mpi4py comms have
    no TPU analog and raise)."""
    with _state._lock:
        if _state.initialized:
            return
        if _env_bool("HOROVOD_LATENCY_HIDING"):
            # Must happen before anything touches the XLA backend (detect()
            # below counts devices): jax snapshots XLA_FLAGS at first use.
            enable_latency_hiding_scheduler()
        _maybe_init_jax_distributed()
        topo = detect()
        if comm is not None:
            if not isinstance(comm, (list, tuple)):
                raise ValueError(
                    "comm must be a list of ranks on TPU (MPI communicators do not exist here)"
                )
            ranks = sorted(set(comm))
            if any(not (0 <= r < topo.size) for r in ranks):
                raise ValueError(
                    f"comm {ranks} contains ranks outside the launched world "
                    f"of size {topo.size}")
            if topo.rank not in ranks and topo.size > 1:
                raise ValueError(
                    f"rank {topo.rank} is not a member of comm {ranks}: this "
                    "process cannot participate in the sub-world's "
                    "collectives. Only member processes may call "
                    "init(comm=...); non-members should skip Horovod work "
                    "(or exit) — they must NOT fall back to init(), which "
                    "would target the same coordinator address.")
            if topo.size > 1 and len(ranks) != topo.size:
                if ranks[0] != 0:
                    # The sub-world's rank 0 binds HOROVOD_COORD_ADDR, which
                    # names ORIGINAL rank 0's host: on a multi-host job where
                    # the member at ranks[0] lives elsewhere, that bind fails
                    # (EADDRNOTAVAIL). Warn with the fix up front.
                    log("warning",
                        f"init(comm={ranks}): member rank {ranks[0]} will "
                        "bind the coordinator at HOROVOD_COORD_ADDR. If it "
                        "is not on the same host as the original rank 0, "
                        "re-export HOROVOD_COORD_ADDR on every member to an "
                        "address local to that member before init.")
                # Sub-world semantics (reference horovod_init with ranks[],
                # operations.cc:2415): rank/size are re-indexed within the
                # subset — the member at ranks[0] becomes rank 0 and binds
                # the coordinator address, so the control plane and ring are
                # exactly a world of len(ranks). Host coordinates are NOT
                # preserved: a member only knows its own host placement, not
                # the other members', so any local/cross guess would build
                # wrong topology (the round-3 bug: local_size=min(...) could
                # group ranks that share no host). The subset world uses the
                # consistent one-rank-per-host view — local_rank 0, hierarchy
                # simply not available — which every rank derives identically
                # from `ranks` alone. A ranks list naming the FULL world is
                # plain init (reference accepts this too) and keeps the real
                # host topology — the branch guard above.
                topo = Topology(
                    rank=ranks.index(topo.rank),
                    size=len(ranks),
                    local_rank=0,
                    local_size=1,
                    cross_rank=ranks.index(topo.rank),
                    cross_size=len(ranks),
                )
        _state.topology = topo
        _state.config = Config.from_env()
        _state.initialized = True
        _start_metrics(topo, _state.config)
        if not _state._atexit_registered:
            atexit.register(shutdown)
            _state._atexit_registered = True
        log("debug", f"horovod_tpu initialized: {topo}", rank=topo.rank)


def _start_metrics(topo: Topology, config: Config) -> None:
    """Always-on registry identity gauges; HTTP exposition only when
    HOROVOD_METRICS_PORT is set. Rank r on a host serves at
    port + local_rank so co-located workers never collide (docs/metrics.md);
    failure to bind is a warning, not an init failure — telemetry must
    never take the job down."""
    from ..metrics import registry, start_metrics_server

    reg = registry()
    reg.gauge("horovod_rank", help="this process's rank").set(topo.rank)
    reg.gauge("horovod_size", help="world size").set(topo.size)
    reg.gauge("horovod_local_rank").set(topo.local_rank)
    port = getattr(config, "metrics_port", 0)
    if port:
        try:
            _state.metrics_server = start_metrics_server(port + topo.local_rank)
            log("debug",
                f"metrics exposition at http://127.0.0.1:"
                f"{_state.metrics_server.port}/metrics", rank=topo.rank)
        except OSError as e:
            log("warning",
                f"HOROVOD_METRICS_PORT={port}: cannot bind metrics server "
                f"({e}); exposition disabled for this rank", rank=topo.rank)


def shutdown() -> None:
    """Tear down (reference horovod_shutdown, operations.cc:2424-2432);
    re-init is allowed afterwards."""
    with _state._lock:
        if not _state.initialized:
            return
        if _state.metrics_server is not None:
            try:
                _state.metrics_server.stop()
            except Exception:  # pragma: no cover
                pass
            _state.metrics_server = None
        if _state.engine is not None:
            try:
                _state.engine.shutdown()
            except Exception as e:  # pragma: no cover
                log("warning", f"engine shutdown failed: {e}")
            _state.engine = None
        # Close this process's trace recorder (the engines only flush: the
        # recorder outlives elastic engine rebuilds, but not the session).
        try:
            from ..tracing import close_recorder

            close_recorder()
        except Exception:  # pragma: no cover - tracing never blocks teardown
            pass
        _state.mesh = None
        _state.topology = None
        _state.config = None
        _state.initialized = False


def is_initialized() -> bool:
    return _state.initialized


def _topo() -> Topology:
    if not _state.initialized or _state.topology is None:
        raise NotInitializedError()
    return _state.topology


def rank() -> int:
    return _topo().rank


def size() -> int:
    return _topo().size


def local_rank() -> int:
    return _topo().local_rank


def local_size() -> int:
    return _topo().local_size


def cross_rank() -> int:
    return _topo().cross_rank


def cross_size() -> int:
    return _topo().cross_size


def is_homogeneous() -> bool:
    return _topo().is_homogeneous


def config() -> Config:
    if not _state.initialized or _state.config is None:
        raise NotInitializedError()
    return _state.config


def mpi_threads_supported() -> bool:
    """Parity shim for hvd.mpi_threads_supported() (operations.cc:2460-2467).
    There is no MPI on TPU; the host control plane is always thread-safe."""
    _topo()
    return True


def default_mesh():
    """Lazily-created 1-D 'hvd' mesh over all visible chips."""
    _topo()
    if _state.mesh is None:
        from ..parallel.mesh import data_parallel_mesh

        _state.mesh = data_parallel_mesh()
    return _state.mesh


def engine():
    """Lazily attach the native eager engine (host data plane)."""
    _topo()
    if _state.engine is None:
        from . import engine as engine_mod

        _state.engine = engine_mod.create(_topo(), config())
    return _state.engine
