"""The shared protocol core — ONE spec both eager engines interpret.

ROADMAP item 2's unification seed, cashed in (ISSUE 13): every protocol
fact the two engines must agree on lives here as data or a pure function —
op/dtype/status id spaces, the control-wire field orders, the response
cache signature, the canonical reduction order and accumulator semantics,
and the negotiation/cache/demote state machine.  The Python engine
(common/engine.py) and the ctypes bridge (cc/native_engine.py) consume
these tables directly; the C++ core cannot import them, so the
conformance analyzer (tools/analyze, docs/analysis.md) machine-extracts
the native side into ``docs/protocol_spec.json`` and THIS module is
generation-checked against that spec: :func:`verify_spec` names the first
divergent entry, and CI fails on any.

Three layers:

- **Id spaces and wire shapes** — ``OPS``/``DTYPES``/``STATUS_NAMES``/
  ``WIRE_FORMATS`` plus the serialized field orders of every control
  message.  These are the literal contract the analyzer's parity tables
  (tools/analyze/protocol.py) encode pairwise; here they are the single
  importable copy.
- **Canonical reduction semantics** — :func:`chunk_bounds`,
  :func:`fold_start`, :func:`reduce_plan`.  The rule that makes
  star == ring == hier == native bitwise for every wire format: chunk c
  folds contributions in ring order starting at rank (c+1) % world; the
  accumulator runs at the NATIVE ring width (f32 for f32 payloads — the
  width cc/src/ring.h adds at), 16-bit payloads round at every hop
  boundary (storage is 16-bit on both engines), and compressed folds
  round the finished partial once more before the average divide — the
  "storage round" the native ring performs by construction.
- **The state machine** — :class:`Machine`, a pure validator for
  negotiation/cache/wire/demote-redo transition traces.  The golden
  protocol-trace tests (tests/test_protocol.py) replay recorded tick
  sequences from BOTH engines through it; a divergence names the first
  mismatching transition instead of failing on a downstream hash.
"""

from __future__ import annotations

import json
import os
from typing import Iterable, Optional

import numpy as np

# --------------------------------------------------------------- id spaces

# Collective op ids — must match hvd_common.h OpType (the analyzer checks
# native_engine.OPS against the enum; verify_spec() checks us against the
# extracted spec, closing the triangle).
OPS = {"allreduce": 0, "allgather": 1, "broadcast": 2,
       "reducescatter": 3, "alltoall": 4}

# DataType id -> numpy dtype name (hvd_common.h DataType order).
DTYPES = ["uint8", "int8", "int32", "int64", "float16", "bfloat16",
          "float32", "float64", "bool"]

# Non-OK StatusType values surfaced through the ctypes bridge.
STATUS_NAMES = {1: "UnknownError", 2: "PreconditionError", 3: "Aborted",
                4: "InvalidArgument"}

# Request.wire_fmt values (wire.h): the sparse-wire tag. Dense formats ride
# the dtype/orig_dtype pair (native) or the `wire` dtype tag (python);
# `topk` changes the FRAME, not the dtype, so it needs its own wire field.
WIRE_FORMATS = {"none": 0, "topk": 1}

# ------------------------------------------------------- wire field orders

# Serialized field order of each native control message (wire.h write()
# bodies). verify_spec() pins these against the machine-extracted
# wire_order lists, so a C++ field added or reordered without touching
# this module fails CI with the exact field named.
REQUEST_WIRE_ORDER = ["rank", "op", "dtype", "orig_dtype", "wire_fmt",
                      "name", "root_rank", "average", "trace_seq", "shape"]
TICK_WIRE_ORDER = ["rank", "shutdown", "reqs", "cache_bits"]
RESPONSE_LIST_WIRE_ORDER = ["shutdown", "knob_version", "fusion_threshold",
                            "cycle_time_ms", "hier_allreduce",
                            "hier_allgather", "stall_warnings", "entries",
                            "cache_evict", "cache_assign"]

# Response-cache signature facets, both spellings. A bit bound under one
# engine's rules must invalidate under the other's: the two lists name the
# same facets through the dtype/orig_dtype <-> dtype/wire shift.
NATIVE_CACHE_KEY_FIELDS = ["name", "op", "dtype", "orig_dtype", "wire_fmt",
                           "average", "root_rank", "shape"]
PY_REQUEST_KEY_FIELDS = ["name", "op", "dtype", "root", "shape", "average",
                         "wire"]

# Python full-request dict keys (base + optional), the python half of the
# native Request struct.
PY_REQUEST_FIELDS = ["name", "op", "shape", "dtype", "root", "average"]
PY_REQUEST_OPTIONAL_FIELDS = ["wire", "trace", "ke"]

# The coordinator's control-socket dispatch alphabet, in the source order
# of _Coordinator._serve (ISSUE 18). The per-host relay (ctrl/relay.py)
# special-cases a subset of these and forwards the rest verbatim; it
# asserts its subset against this list at import, so a kind added or
# renamed in the coordinator cannot silently bypass the tree's batching.
# The analyzer machine-extracts the dispatch and fails on drift.
COORD_WIRE_KINDS = ["exchange", "batch_exchange", "ring_hello",
                    "ring_confirm", "batch_ring_hello",
                    "batch_ring_confirm", "relay_hello", "peer_lost",
                    "plane_fault", "knob_change", "clock_probe", "bye"]

SPEC_REL = os.path.join("docs", "protocol_spec.json")


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def load_spec(root: Optional[str] = None) -> dict:
    with open(os.path.join(root or repo_root(), SPEC_REL),
              encoding="utf-8") as f:
        return json.load(f)


def verify_spec(spec: Optional[dict] = None,
                root: Optional[str] = None) -> list[str]:
    """Check this module against the machine-extracted protocol spec.

    Returns a list of human-readable mismatch strings (empty = conformant),
    each naming the first divergent entry of its table — the test fails
    with the drift itself, not a downstream symptom."""
    if spec is None:
        spec = load_spec(root)
    native = spec.get("native", {})
    py = spec.get("python", {})
    out: list[str] = []

    def _pair(what: str, mine, theirs) -> None:
        if mine == theirs:
            return
        if isinstance(mine, list) and isinstance(theirs, list):
            for i, (a, b) in enumerate(zip(mine, theirs)):
                if a != b:
                    out.append(f"{what}[{i}]: protocol.py has {a!r}, "
                               f"spec has {b!r}")
                    return
            out.append(f"{what}: length {len(mine)} (protocol.py) != "
                       f"{len(theirs)} (spec)")
            return
        out.append(f"{what}: protocol.py has {mine!r}, spec has {theirs!r}")

    enums = native.get("enums", {})
    _pair("OpType", {k.lower(): v for k, v in
                     enums.get("OpType", {}).items()}, OPS)
    dt = enums.get("DataType", {})
    spec_dtypes = [None] * len(dt)
    for cname, val in dt.items():
        if 0 <= val < len(spec_dtypes):
            spec_dtypes[val] = cname
    _pair("DataType-count", len(DTYPES), len(dt))
    _pair("StatusNames",
          {int(k): v for k, v in py.get("status_names", {}).items()},
          STATUS_NAMES)
    msgs = native.get("messages", {})
    _pair("Request.wire_order",
          msgs.get("Request", {}).get("wire_order", []), REQUEST_WIRE_ORDER)
    _pair("TickRequest.wire_order",
          msgs.get("TickRequest", {}).get("wire_order", []), TICK_WIRE_ORDER)
    _pair("ResponseList.wire_order",
          msgs.get("ResponseList", {}).get("wire_order", []),
          RESPONSE_LIST_WIRE_ORDER)
    _pair("native cache_key", native.get("cache_key_fields", []),
          NATIVE_CACHE_KEY_FIELDS)
    _pair("python request_key", py.get("request_key_fields", []),
          PY_REQUEST_KEY_FIELDS)
    _pair("python request fields", py.get("request_fields", []),
          PY_REQUEST_FIELDS)
    _pair("python optional request fields",
          py.get("request_optional_fields", []), PY_REQUEST_OPTIONAL_FIELDS)
    _pair("python dtypes", py.get("dtypes", []), DTYPES)
    _pair("python ops", py.get("ops", {}), OPS)
    return out


# ------------------------------------------- canonical reduction semantics

def chunk_bounds(n: int, world: int) -> list[int]:
    """np.array_split boundaries of a flat n-element buffer into `world`
    ring chunks: the first n % world chunks carry one extra element.
    Identical to ring.h split_counts/offsets_of."""
    base, rem = divmod(int(n), int(world))
    bounds = [0]
    for i in range(world):
        bounds.append(bounds[-1] + base + (1 if i < rem else 0))
    return bounds


def fold_start(chunk: int, world: int) -> int:
    """The rank whose contribution seeds chunk ``chunk``'s fold: the ring
    reduce-scatter's natural start (chunk + 1) % world — after world-1
    hops the chunk lands fully reduced on rank ``chunk``."""
    return (chunk + 1) % world


def fold_order(chunk: int, world: int) -> list[int]:
    """Full fold order for one chunk: the add sequence every plane (star
    oracle, flat ring, hier stages, native ring.h) must reproduce."""
    s = fold_start(chunk, world)
    return [(s + k) % world for k in range(world)]


_16BIT_FLOATS = ("float16", "bfloat16")


def reduce_plan(dtype, wire_dtype=None) -> dict:
    """Canonical allreduce arithmetic for a payload ``dtype`` under an
    optional explicit wire format.

    Returns ``{"acc": np.dtype, "hop": np.dtype | "topk" | None,
    "storage_round": bool}``:

    - ``hop`` is the dtype every inter-rank hop carries (None = native
      width, no rounding); ``acc`` is the accumulator width each add runs
      at.  Uncompressed floats accumulate at NATIVE ring width — f32 adds
      for f32 payloads, exactly what cc/src/ring.h computes — and 16-bit
      payloads implicitly hop at their own width with per-hop rounding
      (storage on both engines is 16-bit between adds).
    - ``storage_round``: compressed folds round the finished reduce-scatter
      partial to the hop dtype BEFORE the average divide (the partial is
      stored at wire width on the native ring); the allgather rounds once
      more after it.  Identities for f32 hops.
    """
    dtype = np.dtype(dtype)
    if isinstance(wire_dtype, str) and wire_dtype == "topk":
        return {"acc": np.dtype(np.float32), "hop": "topk",
                "storage_round": False}
    if wire_dtype is not None:
        return {"acc": np.dtype(np.float32), "hop": np.dtype(wire_dtype),
                "storage_round": True}
    if dtype.name in _16BIT_FLOATS:
        # Implicit wire = self: the native engine stores and forwards the
        # 16-bit value after every add (ring.h add_chunk_bf16/f16).
        return {"acc": np.dtype(np.float32), "hop": dtype,
                "storage_round": True}
    return {"acc": dtype, "hop": None, "storage_round": False}


# ------------------------------------------------------- the state machine

class ProtocolViolation(AssertionError):
    """A transition trace broke the protocol; the message names the event
    index and the rule it violated."""

    def __init__(self, index: int, event: tuple, why: str) -> None:
        self.index = index
        self.event = event
        self.why = why
        super().__init__(f"event[{index}] {event!r}: {why}")


class Machine:
    """Pure validator of the eager engines' shared state machine.

    Events are ``(kind, *args)`` tuples, the vocabulary both engines'
    observable transitions map onto:

    - ``("tick_full", rank, key)``      — a full request for signature key
    - ``("tick_cached", rank, key)``    — a cache-bit negotiation
    - ``("assign", bit, key)``          — coordinator binds key -> bit
    - ``("evict", bit)``                — coordinator invalidates a bit
    - ``("flush", rank)``               — a rank drops its mirror
    - ``("execute", key)``              — the collective runs
    - ``("demote", rank)``              — rung 2: peer plane -> star
    - ``("redo", key)``                 — demotion replay of a collective
    - ``("repromote", rank)``           — cooldown rebuilt the peer plane

    Rules enforced (the cross-engine contract):

    - a cached tick requires the key bound AND the rank's mirror to have
      learned the binding after its last flush;
    - an assign may re-announce the same (bit, key) pair (mirror re-heal)
      but must evict before re-binding either half differently;
    - an execute requires every live rank to have contributed the key
      since its last execute;
    - a redo is only legal while demoted, and a demoted rank negotiates
      star-only until re-promotion.
    """

    def __init__(self, world: int) -> None:
        self.world = world
        self.bit_of: dict = {}        # key -> bit
        self.key_of: dict = {}        # bit -> key
        self.learned: dict = {r: set() for r in range(world)}  # rank mirrors
        self.contributed: dict = {}   # key -> set of ranks this round
        self.plane: dict = {r: "peer" for r in range(world)}

    def feed(self, i: int, ev: tuple) -> None:
        kind = ev[0]
        if kind == "tick_full":
            _, rank, key = ev
            self.contributed.setdefault(key, set()).add(rank)
        elif kind == "tick_cached":
            _, rank, key = ev
            if key not in self.bit_of:
                raise ProtocolViolation(
                    i, ev, "cached tick for a signature with no bound bit")
            if key not in self.learned[rank]:
                raise ProtocolViolation(
                    i, ev, "cached tick before this rank's mirror learned "
                           "the binding (flushed mirrors must re-learn "
                           "from a full request + re-announcement)")
            self.contributed.setdefault(key, set()).add(rank)
        elif kind == "assign":
            _, bit, key = ev
            if self.key_of.get(bit, key) != key:
                raise ProtocolViolation(
                    i, ev, f"bit {bit} already bound to "
                           f"{self.key_of[bit]!r} without an evict")
            if self.bit_of.get(key, bit) != bit:
                raise ProtocolViolation(
                    i, ev, f"key already bound to bit {self.bit_of[key]} "
                           "without an evict")
            self.bit_of[key] = bit
            self.key_of[bit] = key
            for r in range(self.world):
                self.learned[r].add(key)  # announcement reaches every rank
        elif kind == "evict":
            _, bit = ev
            key = self.key_of.pop(bit, None)
            if key is None:
                raise ProtocolViolation(i, ev, f"evict of unbound bit {bit}")
            self.bit_of.pop(key, None)
            for r in range(self.world):
                self.learned[r].discard(key)
        elif kind == "flush":
            _, rank = ev
            self.learned[rank] = set()
        elif kind == "execute":
            _, key = ev
            got = self.contributed.pop(key, set())
            if len(got) < self.world:
                raise ProtocolViolation(
                    i, ev, f"executed with contributions from {sorted(got)} "
                           f"only (world {self.world})")
        elif kind == "demote":
            self.plane[ev[1]] = "star"
        elif kind == "redo":
            _, key = ev
            if all(p == "peer" for p in self.plane.values()):
                raise ProtocolViolation(
                    i, ev, "redo replay outside a demotion epoch")
        elif kind == "repromote":
            _, rank = ev
            if self.plane[rank] != "star":
                raise ProtocolViolation(
                    i, ev, "re-promotion of a rank that never demoted")
            self.plane[rank] = "peer"
        else:
            raise ProtocolViolation(i, ev, f"unknown event kind {kind!r}")

    def replay(self, events: Iterable[tuple]) -> int:
        """Validate a whole trace; returns the number of events consumed.
        Raises :class:`ProtocolViolation` naming the first bad one."""
        n = 0
        for i, ev in enumerate(events):
            self.feed(i, ev)
            n += 1
        return n


def replay(events: Iterable[tuple], world: int) -> int:
    """Convenience: validate ``events`` on a fresh :class:`Machine`."""
    return Machine(world).replay(events)
