"""horovod_tpu.common"""
