"""Process / device topology resolution for TPU pod slices.

The reference resolves rank/size/local_rank from ``MPI_COMM_WORLD`` and a
node-local shared-memory split (operations.cc:1728-1797). On TPU there is no
MPI: topology comes from the pod-slice runtime (one process per host, N local
chips per process) or from the horovodrun-equivalent launcher, which exports
``HOROVOD_RANK`` / ``HOROVOD_SIZE`` / ``HOROVOD_LOCAL_RANK`` /
``HOROVOD_LOCAL_SIZE`` / ``HOROVOD_CROSS_RANK`` / ``HOROVOD_CROSS_SIZE``.

Resolution priority:
1. launcher-exported HOROVOD_* env vars (set by horovod_tpu.runner);
2. JAX distributed runtime (``jax.process_index()`` / ``jax.process_count()``)
   when it has been initialized with more than one process;
3. single-process world: rank 0, size 1.

The reference's homogeneity check (equal local_size on every node,
operations.cc:1774-1790) is mirrored in :func:`Topology.validate`.
"""

from __future__ import annotations

import os
from dataclasses import dataclass


@dataclass(frozen=True)
class Topology:
    """One rank's view of the job, mirroring HorovodGlobalState's rank fields
    (reference operations.cc:115-171)."""

    rank: int
    size: int
    local_rank: int
    local_size: int
    cross_rank: int   # which node this rank's host is (reference cross_comm split)
    cross_size: int   # number of nodes
    is_homogeneous: bool = True

    def validate(self) -> None:
        if not (0 <= self.rank < self.size):
            raise ValueError(f"rank {self.rank} out of range for size {self.size}")
        if not (0 <= self.local_rank < self.local_size):
            raise ValueError(
                f"local_rank {self.local_rank} out of range for local_size {self.local_size}"
            )
        if self.size % self.local_size != 0 and self.is_homogeneous:
            raise ValueError(
                "homogeneous topology requires size to be a multiple of local_size "
                f"(got size={self.size}, local_size={self.local_size})"
            )


def _from_env() -> Topology | None:
    if "HOROVOD_RANK" not in os.environ or "HOROVOD_SIZE" not in os.environ:
        return None
    rank = int(os.environ["HOROVOD_RANK"])
    size = int(os.environ["HOROVOD_SIZE"])
    local_rank = int(os.environ.get("HOROVOD_LOCAL_RANK", 0))
    local_size = int(os.environ.get("HOROVOD_LOCAL_SIZE", 1))
    cross_rank = int(os.environ.get("HOROVOD_CROSS_RANK", rank // max(local_size, 1)))
    cross_size = int(os.environ.get("HOROVOD_CROSS_SIZE", max(size // max(local_size, 1), 1)))
    return Topology(rank, size, local_rank, local_size, cross_rank, cross_size)


def _from_jax() -> Topology | None:
    try:
        import jax
    except ImportError:  # pragma: no cover
        return None
    try:
        count = jax.process_count()
    except Exception:  # jax.distributed not initialized / no backend
        return None
    if count <= 1:
        return None
    rank = jax.process_index()
    # One process per host on TPU pod slices: local_rank is 0, local_size 1,
    # and the process grid is the cross grid.
    return Topology(
        rank=rank,
        size=count,
        local_rank=0,
        local_size=1,
        cross_rank=rank,
        cross_size=count,
    )


def detect() -> Topology:
    """Resolve this process's topology (see module docstring for priority)."""
    topo = _from_env() or _from_jax() or Topology(0, 1, 0, 1, 0, 1)
    topo.validate()
    return topo


def num_local_devices() -> int:
    """Chips attached to this process (reference local_size is the per-node GPU
    count; on TPU a single process drives all local chips via SPMD)."""
    try:
        import jax

        return jax.local_device_count()
    except Exception:  # pragma: no cover
        return 1


def num_devices() -> int:
    """Total chips in the job."""
    try:
        import jax

        return jax.device_count()
    except Exception:  # pragma: no cover
        return 1
